"""Logical-axis sharding rules.

Replaces the reference's parallel-layer library (ColumnParallelLinear /
RowParallelLinear / ParallelEmbedding from `neuronx_distributed`, used throughout
`modules/attention/attention_base.py:210-218`, `modules/attention/gqa.py:375`) with the
idiomatic JAX mechanism: every parameter and activation is annotated with *logical* axis
names; a rule table maps logical axes to mesh axes; `NamedSharding`s are derived from the
rules and handed to `jax.jit` / `jax.lax.with_sharding_constraint`. XLA GSPMD then
inserts the same collectives the reference's parallel layers issue explicitly
(all-reduce after row-parallel matmul, all-gather for sequence parallel, ...).

Logical axes used by the model code:

- ``vocab``     : embedding/lm_head vocab dim (sharded on tp — ≈ vocab_parallel,
                  `models/config.py:142`)
- ``embed``     : model hidden dim (replicated for weights whose other dim is sharded)
- ``heads``     : attention query-head dim (column-parallel q/o, `attention_base.py:210`)
- ``kv_heads``  : attention kv-head dim (GQA; may be replicated when heads < tp,
                  ≈ `modules/attention/gqa.py:89-271`)
- ``mlp``       : MLP intermediate dim (column-parallel gate/up, row-parallel down)
- ``experts``   : MoE expert dim (expert parallel)
- ``batch``     : batch dim of activations and KV caches (dp)
- ``seq``       : sequence dim of activations (cp; sp when enabled)
- ``kv_seq``    : sequence dim of KV caches (cp for flash-decoding-style sharding)
- ``act_seq``   : sequence dim of the PREFILL residual stream between layers —
                  None (replicated) by default; ``sequence_parallel_enabled``
                  maps it to (cp, tp) so residuals/norms live sequence-sharded
                  and the per-layer all-reduces split into all-gather +
                  reduce-scatter halves (fused into the collective matmuls,
                  parallel/overlap.py; ≈ reference sequence-parallel norm)
- ``act_embed`` : hidden dim of the DECODE residual stream — None by default;
                  ``sequence_parallel_enabled`` maps it to tp (decode steps
                  have T≈1, so the residual shards over hidden instead of
                  seq — the decode analog of sequence parallelism)
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import AXIS_CP, AXIS_DP, AXIS_EP, AXIS_TP

MeshAxes = Union[None, str, Tuple[str, ...]]

# Default rule table: logical axis -> mesh axis (or tuple, or None = replicated).
# Attention heads and dense MLP shard over tp only: the ep axis shards *experts*
# (attention is replicated across ep ranks, matching the reference's TP-attention +
# EP-MoE process-group split, `modules/moe_v2.py:135`).
DEFAULT_RULES: Dict[str, MeshAxes] = {
    "vocab": AXIS_TP,
    "embed": None,
    "heads": AXIS_TP,
    "kv_heads": AXIS_TP,
    "mlp": (AXIS_CP, AXIS_TP),
    "experts": AXIS_EP,
    "expert_mlp": AXIS_TP,
    "batch": AXIS_DP,
    "seq": AXIS_CP,
    "kv_seq": None,
    "act_seq": None,
    "act_embed": None,
    "layers": None,
    # decode-attention layout knobs (≈ reference attention data parallelism,
    # `modules/attention/attention_process_groups.py:125-163` + the DP KV cache
    # manager): by default identical to the prefill layout; with
    # attention_dp_enabled the application remaps decode_batch -> (dp, tp) and
    # decode_heads/decode_kv_heads -> None, so decode attention runs batch-parallel
    # over ALL chips with replicated (GQA) kv heads — the GSPMD expression of the
    # reference's TP-group -> DP-groups split, with the all-to-alls at the region
    # boundaries inserted by the compiler instead of hand-built process groups.
    "decode_batch": AXIS_DP,
    "decode_heads": AXIS_TP,
    "decode_kv_heads": AXIS_TP,
    # decode-time MoE dispatch layout (≈ reference hybrid sharding: different
    # TP/EP degrees for CTE vs TKG, `models/config.py:1055-1061`, and the
    # AR_AG/RS_AG/AG_AR dispatch options, `:602,685-686`). By default identical
    # to the prefill MoE layout; `moe_hybrid_sharding` remaps these so the
    # decode graph's expert activations constrain to a different axis split —
    # GSPMD then derives the dispatch/combine collectives for each graph, the
    # TPU form of picking the dispatch CC algorithm per sub-model.
    "decode_experts": AXIS_EP,
    "decode_expert_mlp": AXIS_TP,
}


def logical_to_spec(logical: Sequence[Optional[str]],
                    rules: Optional[Dict[str, MeshAxes]] = None) -> P:
    """Map a tuple of logical axis names (None = replicated dim) to a PartitionSpec."""
    rules = rules or DEFAULT_RULES
    out = []
    for name in logical:
        if name is None:
            out.append(None)
        else:
            if name not in rules:
                raise KeyError(f"no sharding rule for logical axis {name!r}")
            out.append(rules[name])
    return P(*out)


def named_sharding(mesh: Mesh, logical: Sequence[Optional[str]],
                   rules: Optional[Dict[str, MeshAxes]] = None) -> NamedSharding:
    return NamedSharding(mesh, logical_to_spec(logical, rules))


def tree_shardings(mesh: Mesh, logical_tree: Any,
                   rules: Optional[Dict[str, MeshAxes]] = None) -> Any:
    """Map a pytree of logical-axis tuples to a pytree of NamedShardings."""
    return jax.tree.map(
        lambda logical: named_sharding(mesh, logical, rules),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple) and all(
            isinstance(e, str) or e is None for e in x),
    )


def constrain(x: jax.Array, logical: Sequence[Optional[str]],
              rules: Optional[Dict[str, MeshAxes]] = None,
              mesh: Optional[Mesh] = None) -> jax.Array:
    """`with_sharding_constraint` by logical axes.

    Pass ``mesh`` explicitly (model code threads it through) so the constraint works
    without an ambient mesh context; with mesh=None this is a no-op passthrough, which
    keeps single-device code paths mesh-free.
    """
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, logical_to_spec(logical, rules)))


def shard_put(x, mesh: Mesh, logical: Sequence[Optional[str]],
              rules: Optional[Dict[str, MeshAxes]] = None) -> jax.Array:
    """Device-put a host array with the sharding derived from logical axes."""
    return jax.device_put(x, named_sharding(mesh, logical, rules))
