"""Overlap-scheduled tensor-parallel collective matmuls.

Replaces the blocking GSPMD collective placement around the dense layer matmuls
with explicit shard_map **collective matmuls** — the TPU analog of the
reference's sequence-parallel Row/ColumnParallelLinear pairing
(`modules/attention/attention_base.py:210-218`, sequence-parallel norm in the
attention/MLP blocks) and of the decomposed collective-matmul schedules in
TPLA / "Overlap Communication with Dependent Computation" (PAPERS.md):

- **all-gather -> matmul** (column-parallel: qkv / gate-up). The activation
  enters *sharded* (sequence-sharded in prefill, hidden-sharded in decode) and
  each chip starts the matmul on the shard it already owns while
  `lax.ppermute` rotates the next shard in around the tp ring — the ICI
  transfer hides behind the MXU instead of serializing in front of it.
- **matmul -> reduce-scatter** (row-parallel: o-proj / down-proj). Each chip
  computes per-destination partial tiles and rotate-accumulates them around
  the ring, so the reduction traffic overlaps the remaining tiles' compute and
  the output lands already in the sharded residual layout.

Together with the sequence-parallel residual path (`models/base.py`
``act_seq`` / ``act_embed`` residual constraints) this converts the per-layer
all-reduces XLA would place after o-proj/down-proj into all-gather +
reduce-scatter *halves fused into the matmuls* — same bytes on the wire,
no blocking collective on the critical path.

Selection is trace-time: the layer takes this path when the mesh has tp > 1,
the residual rules are sharded (``sequence_parallel_enabled``), and the
operand shapes/weights are eligible; ``TPUINF_TP_OVERLAP=0`` opts out and
falls back to today's pure GSPMD constraint placement (read at TRACE time —
set before the first compile; a warm executable never re-reads it).
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .mesh import AXIS_CP, AXIS_EP, AXIS_TP
from .sharding import DEFAULT_RULES, logical_to_spec


def overlap_enabled() -> bool:
    """TPUINF_TP_OVERLAP=0 falls back to GSPMD constraint placement (trace-time)."""
    return os.environ.get("TPUINF_TP_OVERLAP", "1") != "0"


def _shard_map(local_fn, mesh, in_specs, out_specs):
    """shard_map with the replication check off, across jax versions (kept local
    to avoid a models.base import cycle — see models/base.shard_map_compat)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(local_fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(local_fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def _rule_is_tp(rules: Dict, name: str) -> bool:
    v = (rules or DEFAULT_RULES).get(name)
    if v == AXIS_TP:
        return True
    # (cp, tp)-style tuples are tp-equivalent when the other axes are size 1
    # (the caller checks cp == ep == 1 before asking)
    return isinstance(v, tuple) and AXIS_TP in v


def layer_phase(args, mesh, rules, *, decode: bool) -> Optional[str]:
    """Decide whether THIS trace's dense projections take the collective-matmul
    path. Returns ``"seq"`` (prefill: activations sequence-sharded over tp),
    ``"hidden"`` (decode: T is 1-ish so the residual shards over the hidden
    dim instead — the decode analog of sequence parallelism), or None for the
    GSPMD fallback.

    The ring rotates over the tp axis only, so cp/ep must be size 1 (cp > 1
    configs keep ring-attention prefill + GSPMD constraints); LoRA and
    activation-quant projections keep their fused qapply paths.
    """
    if mesh is None or not overlap_enabled():
        return None
    shape = dict(mesh.shape)
    if shape.get(AXIS_TP, 1) <= 1:
        return None
    if shape.get(AXIS_CP, 1) != 1 or shape.get(AXIS_EP, 1) != 1:
        return None
    if args.lora is not None or args.activation_quant:
        return None
    r = rules or DEFAULT_RULES
    if decode:
        if r.get("act_embed") != AXIS_TP:
            return None
        # attention-DP remaps decode head rules to None — the collective
        # matmuls produce head-sharded projections, so both must agree
        if r.get("decode_heads") != AXIS_TP or r.get("decode_kv_heads") != AXIS_TP:
            return None
        return "hidden"
    if not _rule_is_tp(r, "act_seq"):
        return None
    if r.get("heads") != AXIS_TP or r.get("kv_heads") != AXIS_TP:
        return None
    return "seq"


def _plain(w) -> bool:
    """Quantized weights ride dict payloads ({"q","s"} / {"q4","s"}) through
    qapply; the collective matmuls serve plain dense arrays only."""
    return not isinstance(w, dict)


def _perm(tp: int):
    return [(i, (i + 1) % tp) for i in range(tp)]


def column_projection(x, ws: Sequence, mesh, rules, phase: str,
                      out_logicals: Sequence[str]):
    """Fused column-parallel projection ``x @ [w_0 | w_1 | ...]`` with the
    all-gather half of the residual collective folded into the matmul.

    ``phase="seq"``: x (B, S, H) sequence-sharded (``act_seq``); each chip
    matmuls the seq shard it holds while ppermute rotates the next one in;
    outputs are full-sequence with their out dim tp-sharded.
    ``phase="hidden"``: x (B, T, H) hidden-sharded (``act_embed``); the ring
    rotates hidden shards and accumulates partial contractions against the
    matching weight row block.

    Returns a list of (B, S, O_i) outputs (out dims tp-sharded), or None when
    the operands are ineligible (quantized payloads, non-dividing shapes) —
    the caller falls back to qapply + GSPMD placement.
    """
    r = rules or DEFAULT_RULES
    tp = mesh.shape[AXIS_TP]
    if not all(_plain(w) for w in ws):
        return None
    b, s, h = x.shape
    if h % tp != 0 or any(w.shape[-1] % tp != 0 for w in ws):
        return None
    if phase == "seq" and s % tp != 0:
        return None
    sizes = [w.shape[-1] // tp for w in ws]
    x_logical = (("batch", None, "act_embed") if phase == "hidden"
                 else ("batch", "act_seq", None))
    in_specs = (logical_to_spec(x_logical, r),) + tuple(
        logical_to_spec((None, name), r) for name in out_logicals)
    out_specs = tuple(logical_to_spec(("batch", None, name), r)
                      for name in out_logicals)
    perm = _perm(tp)

    def _split(out):
        parts, o0 = [], 0
        for sz in sizes:
            parts.append(jax.lax.dynamic_slice_in_dim(out, o0, sz, axis=2))
            o0 += sz
        return tuple(parts)

    if phase == "seq":

        def _local(xs, *wl):
            w = jnp.concatenate(wl, axis=-1)            # (H, sum O_i / tp)
            rk = jax.lax.axis_index(AXIS_TP)
            s_loc = xs.shape[1]
            dt = jnp.result_type(xs.dtype, w.dtype)
            out = jnp.zeros((xs.shape[0], tp * s_loc, w.shape[-1]), dtype=dt)
            cur = xs
            for k in range(tp):
                # issue the ring transfer FIRST: the matmul below does not
                # depend on it, so the scheduler hides the ICI hop behind MXU
                nxt = (jax.lax.ppermute(cur, AXIS_TP, perm)
                       if k < tp - 1 else None)
                blk = jnp.matmul(cur, w).astype(dt)
                src = (rk - k) % tp                      # chunk held this step
                out = jax.lax.dynamic_update_slice(out, blk, (0, src * s_loc, 0))
                cur = nxt
            return _split(out)

    else:

        def _local(xs, *wl):
            w = jnp.concatenate(wl, axis=-1)            # (H, sum O_i / tp)
            rk = jax.lax.axis_index(AXIS_TP)
            h_loc = xs.shape[-1]
            dt = jnp.result_type(xs.dtype, w.dtype)
            acc = jnp.zeros(xs.shape[:-1] + (w.shape[-1],), dtype=jnp.float32)
            cur = xs
            for k in range(tp):
                nxt = (jax.lax.ppermute(cur, AXIS_TP, perm)
                       if k < tp - 1 else None)
                src = (rk - k) % tp
                w_rows = jax.lax.dynamic_slice_in_dim(w, src * h_loc, h_loc,
                                                      axis=0)
                acc = acc + jnp.matmul(cur, w_rows,
                                       preferred_element_type=jnp.float32)
                cur = nxt
            return _split(acc.astype(dt))

    fn = _shard_map(_local, mesh, in_specs, out_specs)
    return list(fn(x, *ws))


def row_projection(x, w, mesh, rules, phase: str, in_logical: str):
    """Row-parallel projection ``x @ w`` with the reduce-scatter half of the
    residual collective folded in: x (B, S, I) has its contraction dim
    tp-sharded (``in_logical``: "heads" for o-proj, "mlp" for down-proj) and
    the partial sums rotate-accumulate around the tp ring, landing directly in
    the sharded residual layout (seq-sharded in prefill, hidden-sharded in
    decode). Per-destination partial tiles are computed lazily inside the
    ring so each tile's matmul overlaps the previous tile's ppermute.

    Returns the (B, S, H) output (residual-sharded), or None when ineligible.
    """
    r = rules or DEFAULT_RULES
    tp = mesh.shape[AXIS_TP]
    if not _plain(w):
        return None
    b, s, i = x.shape
    h = w.shape[-1]
    if i % tp != 0:
        return None
    if phase == "seq" and s % tp != 0:
        return None
    if phase == "hidden" and h % tp != 0:
        return None
    in_specs = (logical_to_spec(("batch", None, in_logical), r),
                logical_to_spec((in_logical, None), r))
    out_logical = (("batch", None, "act_embed") if phase == "hidden"
                   else ("batch", "act_seq", None))
    out_spec = logical_to_spec(out_logical, r)
    perm = _perm(tp)

    def _local(xs, wl):
        rk = jax.lax.axis_index(AXIS_TP)
        dt = jnp.result_type(xs.dtype, wl.dtype)
        if phase == "seq":
            s_loc = xs.shape[1] // tp

            def part(c):
                xc = jax.lax.dynamic_slice_in_dim(xs, c * s_loc, s_loc, axis=1)
                return jnp.matmul(xc, wl, preferred_element_type=jnp.float32)
        else:
            h_loc = wl.shape[-1] // tp

            def part(c):
                wc = jax.lax.dynamic_slice_in_dim(wl, c * h_loc, h_loc, axis=1)
                return jnp.matmul(xs, wc, preferred_element_type=jnp.float32)

        acc = part((rk - 1) % tp)
        for k in range(1, tp):
            acc = jax.lax.ppermute(acc, AXIS_TP, perm)
            acc = acc + part((rk - k - 1) % tp)
        # after tp-1 hops the accumulator at rank r holds destination tile r,
        # having collected every rank's partial along the ring
        return acc.astype(dt)

    fn = _shard_map(_local, mesh, in_specs, out_spec)
    return fn(x, w)


# ---------------------------------------------------------------------------
# Expert-parallel MoE dispatch/combine ring
# ---------------------------------------------------------------------------


def ep_overlap_enabled() -> bool:
    """TPUINF_EP_OVERLAP=0 keeps the MoE combine on GSPMD constraint placement
    (the blocking EP all-reduce after the gate-weighted combine). Read at
    TRACE time, like TPUINF_TP_OVERLAP."""
    return os.environ.get("TPUINF_EP_OVERLAP", "1") != "0"


def moe_ep_phase(mesh, rules, e_ax: str, m_ax: str) -> bool:
    """Decide whether THIS trace's MoE decode takes the explicit expert-ring
    dispatch/combine path (``expert_ring_moe``) instead of the GSPMD-placed
    combine all-reduce.

    The ring rotates over the ep axis only, so it requires ep > 1, cp == 1,
    the expert axis mapped to exactly ``ep`` (hybrid remaps that move experts
    onto tp keep GSPMD placement), and the expert-mlp axis unsharded or
    tp-sharded (the per-tile partial then finishes with one tp psum).
    """
    if mesh is None or not ep_overlap_enabled():
        return False
    shape = dict(mesh.shape)
    if shape.get(AXIS_EP, 1) <= 1:
        return False
    if shape.get(AXIS_CP, 1) != 1:
        return False
    r = rules or DEFAULT_RULES
    if r.get(e_ax) != AXIS_EP:
        return False
    if r.get(m_ax) not in (None, AXIS_TP):
        return False
    return True


def expert_ring_moe(x, gates, weights: Dict[str, jnp.ndarray],
                    waxes: Dict[str, tuple], mesh, rules, e_ax: str,
                    m_ax: str, expert_fn, tp_once: tuple = ()):
    """Overlap-scheduled expert-parallel dispatch/combine.

    Replaces the GSPMD combine all-reduce of the dense all-experts MoE with an
    explicit rotate-accumulate over the ``ep`` mesh axis (the row_projection
    template): tokens are split into ep destination tiles; each chip computes
    its local experts' contribution to one tile while ``lax.ppermute`` rotates
    the partial accumulator around the ep ring, so the combine traffic hides
    behind the next tile's expert matmuls. After ep-1 hops chip r holds token
    tile r fully combined across every chip's experts; a tp psum finishes the
    column-sharded expert mlp dim and a tiled all-gather restores the
    replicated (N, H) layout the residual expects.

    x: (N, H) tokens (``batch`` dp-sharded, replicated over ep/tp); gates:
    (N, E) f32 router gates; ``weights``: plain (unquantized) expert leaves
    keyed by name with logical axes in ``waxes`` (resolved through ``rules``
    so hybrid decode remaps shard them exactly as GSPMD would);
    ``expert_fn(x_tile, gates_tile, local_weights) -> (n, H) f32`` computes
    one shard's local-experts contribution (ops/moe._local_expert_combine —
    which reuses the grouped Pallas kernel when eligible).

    ``tp_once`` names ADDITIVE leaves that are replicated over tp (no tp axis
    in their resolved sharding — e.g. the (E, H) down-projection bias): when
    the expert-mlp dim is tp-sharded, every tp shard's expert_fn adds its
    (identical) copy and the finishing tp psum would count the term tp times,
    so these leaves are zeroed on every tp rank but 0 before expert_fn sees
    them (an exact 0/1 mask — the psum then contributes the term once, same
    as the GSPMD reference).

    Returns the replicated (N, H) combine in x.dtype, or None when shapes
    don't divide the ring (caller keeps GSPMD placement). Bit-exactness with
    the fallback is pinned by tests/test_moe_serving.py.
    """
    r = rules or DEFAULT_RULES
    shape = dict(mesh.shape)
    ep = shape.get(AXIS_EP, 1)
    tp = shape.get(AXIS_TP, 1)
    if ep <= 1:
        return None
    if any(isinstance(w, dict) for w in weights.values()):
        return None
    n, _ = x.shape
    e = gates.shape[1]
    # local token count after the dp shard must split into ep destination tiles
    batch_axes = r.get("batch")
    if batch_axes is None:
        batch_axes = ()
    elif not isinstance(batch_axes, tuple):
        batch_axes = (batch_axes,)
    dp = 1
    for a in batch_axes:
        dp *= shape.get(a, 1)
    if n % dp or (n // dp) % ep or e % ep:
        return None
    tp_partial = tp > 1 and r.get(m_ax) == AXIS_TP

    names = list(weights)
    in_specs = (logical_to_spec(("batch", None), r),
                logical_to_spec(("batch", e_ax), r)) + tuple(
                    logical_to_spec(waxes[k], r) for k in names)
    out_spec = logical_to_spec(("batch", None), r)
    perm = _perm(ep)

    def _local(xl, gl, *wl_flat):
        wl = dict(zip(names, wl_flat))
        if tp_partial and tp_once:
            # tp-replicated additive leaves must survive the tp psum once,
            # not once per shard: keep rank 0's copy, zero the rest
            keep = (jax.lax.axis_index(AXIS_TP) == 0)
            for nm in tp_once:
                wl[nm] = wl[nm] * keep.astype(wl[nm].dtype)
        rk = jax.lax.axis_index(AXIS_EP)
        n_loc = xl.shape[0] // ep

        def part(c):
            xc = jax.lax.dynamic_slice_in_dim(xl, c * n_loc, n_loc, axis=0)
            gc = jax.lax.dynamic_slice_in_dim(gl, c * n_loc, n_loc, axis=0)
            return expert_fn(xc, gc, wl)

        acc = part((rk - 1) % ep)
        for k in range(1, ep):
            acc = jax.lax.ppermute(acc, AXIS_EP, perm)
            acc = acc + part((rk - k - 1) % ep)
        # after ep-1 hops the accumulator at rank r holds token tile r,
        # combined across every rank's local experts along the ring
        if tp_partial:
            acc = jax.lax.psum(acc, AXIS_TP)
        acc = acc.astype(xl.dtype)
        return jax.lax.all_gather(acc, AXIS_EP, axis=0, tiled=True)

    fn = _shard_map(_local, mesh, in_specs, out_spec)
    return fn(x, gates.astype(jnp.float32), *(weights[k] for k in names))


def moe_tp_grouped_enabled() -> bool:
    """TPUINF_MOE_TP_GROUPED=0 keeps pure-TP MoE decode on the dense GSPMD
    einsums (the pre-ISSUE-17 behaviour). Read at TRACE time, like
    TPUINF_EP_OVERLAP."""
    return os.environ.get("TPUINF_MOE_TP_GROUPED", "1") != "0"


def moe_tp_phase(mesh, rules, e_ax: str, m_ax: str) -> bool:
    """Decide whether THIS trace's MoE decode takes the pure-TP grouped
    shard_map path (``expert_tp_moe``) instead of the GSPMD dense einsums.

    The wrapper is the EP ring's finishing step without the ring: every chip
    holds ALL experts but only a tp column slice of the expert mlp dim, so a
    per-shard grouped combine plus one tp psum reproduces the GSPMD
    all-reduce. It requires ep == 1 (ep > 1 belongs to ``moe_ep_phase``),
    tp > 1, cp == 1, the expert-mlp axis mapped to exactly ``tp``, and the
    experts axis unsharded on any live mesh axis (sharded experts at ep == 1
    would leave each chip with a partial expert set and no ring to combine
    them).
    """
    if mesh is None or not moe_tp_grouped_enabled():
        return False
    shape = dict(mesh.shape)
    if shape.get(AXIS_EP, 1) != 1:
        return False
    if shape.get(AXIS_TP, 1) <= 1:
        return False
    if shape.get(AXIS_CP, 1) != 1:
        return False
    r = rules or DEFAULT_RULES
    if r.get(m_ax) != AXIS_TP:
        return False
    ea = r.get(e_ax)
    if ea is not None and shape.get(ea, 1) != 1:
        return False
    return True


def expert_tp_moe(x, gates, weights: Dict[str, jnp.ndarray],
                  waxes: Dict[str, tuple], mesh, rules, e_ax: str,
                  m_ax: str, expert_fn, tp_once: tuple = ()):
    """Pure-TP grouped MoE combine: the ring's finishing step without the ring.

    At ep == 1 with the expert mlp dim tp-sharded, every chip holds all
    experts' column slices, so the routed combine is one per-shard all-experts
    pass over the LOCAL slices followed by a single tp psum — exactly the sum
    GSPMD places after the dense einsums, but computed through ``expert_fn``
    (ops/moe._local_expert_combine, which reuses the grouped Pallas kernel
    when eligible). A trace-level pallas_call cannot consume GSPMD-sharded
    leaves, so this shard_map wrapper is what lets TPUINF_MOE_GROUPED reach
    multi-chip pure-TP serving at all.

    Arguments mirror ``expert_ring_moe``: x (N, H) tokens (``batch``
    dp-sharded, replicated over tp), gates (N, E) f32 router gates, plain
    expert leaves in ``weights`` with logical axes in ``waxes``. ``tp_once``
    names additive leaves replicated over tp (the (E, H) down bias): each
    shard's expert_fn would add its identical copy and the psum would count it
    tp times, so every rank but 0 sees an exact zero (same 0/1 mask as the
    ring).

    Returns the replicated (N, H) combine in x.dtype, or None when the leaves
    are quantized (GSPMD keeps the dequant placement). Exactness against the
    dense fallback is pinned by tests/test_moe_serving.py.
    """
    r = rules or DEFAULT_RULES
    shape = dict(mesh.shape)
    tp = shape.get(AXIS_TP, 1)
    if tp <= 1:
        return None
    if any(isinstance(w, dict) for w in weights.values()):
        return None

    names = list(weights)
    in_specs = (logical_to_spec(("batch", None), r),
                logical_to_spec(("batch", None), r)) + tuple(
                    logical_to_spec(waxes[k], r) for k in names)
    out_spec = logical_to_spec(("batch", None), r)

    def _local(xl, gl, *wl_flat):
        wl = dict(zip(names, wl_flat))
        if tp_once:
            # tp-replicated additive leaves must survive the tp psum once,
            # not once per shard: keep rank 0's copy, zero the rest
            keep = (jax.lax.axis_index(AXIS_TP) == 0)
            for nm in tp_once:
                wl[nm] = wl[nm] * keep.astype(wl[nm].dtype)
        acc = expert_fn(xl, gl, wl)
        acc = jax.lax.psum(acc, AXIS_TP)
        return acc.astype(xl.dtype)

    fn = _shard_map(_local, mesh, in_specs, out_spec)
    return fn(x, gates.astype(jnp.float32), *(weights[k] for k in names))


def estimated_ep_bytes_per_step(num_moe_layers: int, hidden: int, ep: int,
                                tokens: int, dtype_bytes: int = 2) -> int:
    """Analytic per-decode-step expert dispatch/combine ICI bytes of the ring
    path (shape-derived, never needs a compile — the bench's
    ``ep_all_to_all_bytes_per_step`` gauge).

    Per MoE layer the ring rotates ep-1 f32 partial tiles of (tokens/ep, H)
    and the tiled all-gather moves (ep-1)/ep of the combined activation back
    out in the model dtype.
    """
    if ep <= 1:
        return 0
    tile = tokens / ep * hidden
    ring = (ep - 1) * tile * 4
    gather = (ep - 1) * tile * dtype_bytes
    return int(num_moe_layers * (ring + gather))


# ---------------------------------------------------------------------------
# ICI traffic accounting
# ---------------------------------------------------------------------------

# optimized-HLO collective ops counted as inter-chip traffic (fusion suffixes
# like all-reduce-start / all-gather-done collapse onto their base name)
_COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                   "collective-permute", "all-to-all")
_DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "bf16": 2,
                "f16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8,
                "f64": 8, "f8e4m3fn": 1, "f8e5m2": 1}
_COLLECTIVE_RE = re.compile(
    r"=\s+(?:\()?(\w+)\[([\d,]*)\][^=]*?\b"
    r"(all-reduce|all-gather|reduce-scatter|collective-permute|all-to-all)"
    r"(?:-start)?\(")


def collective_stats(hlo_text: str) -> Dict[str, object]:
    """Count collectives (and their output bytes) in an optimized-HLO dump.

    The multichip analog of the HBM bytes-accessed canaries
    (tests/test_perf_regression.py): ``counts`` pins the collective schedule
    of a compiled step (a refactor that reintroduces a stray all-gather shows
    up immediately) and ``bytes`` approximates the per-dispatch ICI traffic
    as the summed output shapes of every collective op. ``-done`` halves of
    async pairs carry no shape of their own and are not double counted.
    """
    counts: Dict[str, int] = {}
    total = 0
    for m in _COLLECTIVE_RE.finditer(hlo_text):
        dtype, dims, op = m.group(1), m.group(2), m.group(3)
        counts[op] = counts.get(op, 0) + 1
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES.get(dtype, 4)
    return {"counts": counts, "count_total": sum(counts.values()),
            "bytes": total}


def compiled_collective_stats(compiled) -> Dict[str, object]:
    """collective_stats over a jax Compiled object's optimized HLO."""
    return collective_stats(compiled.as_text())


def estimated_ici_bytes_per_step(args, tp: int, batch: int, t: int = 1,
                                 dtype_bytes: int = 2) -> int:
    """Analytic per-decode-step ICI bytes at tp > 1 (the telemetry gauge's
    model, shape-derived so it never needs a compile).

    Per layer the residual crosses the ring twice (attention + MLP), each
    crossing one all-gather plus one reduce-scatter (or the all-reduce XLA
    fuses them into — same bytes either way, which is why there is no
    seq-parallel/overlap knob here): ``2 * 2 * (tp-1)/tp * B*T*H``. The
    epilogue adds one hidden-dim gather ahead of the vocab-sharded lm_head
    and the (negligible, k-width) sampling window merge.
    """
    if tp <= 1:
        return 0
    ring = (tp - 1) / tp
    act = batch * t * args.hidden_size * dtype_bytes
    per_layer = 2 * 2 * act * ring
    return int(args.num_layers * per_layer + act * ring)
