"""Device mesh construction and axis conventions.

Replaces the reference's `neuronx_distributed.parallel_state` world/group management
(`models/model_base.py:161-166`, `modules/attention/attention_process_groups.py`) with a
single `jax.sharding.Mesh` carrying named axes. Collectives are never issued against
explicit process groups: shardings over these axes let XLA GSPMD place
all-reduce/all-gather/reduce-scatter on ICI/DCN.

Axis conventions — all four axes are always present (size 1 when unused) so sharding
specs are stable across configurations; ``world = dp * cp * tp * ep``:

- ``dp``: data parallel over batch (≈ attention DP groups,
  `attention_process_groups.py:125-163`).
- ``cp``: context parallel over sequence (≈ CP groups, `attention_process_groups.py:47-123`).
- ``tp``: tensor parallel over heads / hidden / vocab (≈ tp_degree SPMD trace).
- ``ep``: expert parallel over MoE experts (≈ `modules/moe_v2.py:135`).

Unlike the reference (where cp divides tp and world = tp*pp*ep,
`models/config.py:370-383`), axes here are orthogonal: dense layers shard their model
dimension over the *combined* model axes ``(cp, tp, ep)`` (see sharding.MODEL_AXES), so a
pure-TP config and a TP×CP config use the same parameter specs. Attention shards heads
over ``tp``(+``ep``) and sequence over ``cp``; MoE shards experts over ``ep``.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_DP = "dp"
AXIS_CP = "cp"
AXIS_TP = "tp"
AXIS_EP = "ep"
MESH_AXES = (AXIS_DP, AXIS_CP, AXIS_TP, AXIS_EP)

# Combined "model" axes: dense weight shards span all of these (size-1 axes are no-ops).
MODEL_AXES = (AXIS_CP, AXIS_TP, AXIS_EP)


def build_mesh(
    tp_degree: int = 1,
    dp_degree: int = 1,
    cp_degree: int = 1,
    ep_degree: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (dp, cp, tp, ep) mesh; requires dp*cp*tp*ep devices.

    Device order: ep fastest, then tp, then cp, then dp — so tp neighbours are adjacent
    in the device list (on real hardware, adjacent along ICI), keeping the
    latency-critical per-layer all-reduces on the tightest links.
    """
    if devices is None:
        devices = jax.devices()
    devices = np.asarray(devices)
    n_needed = dp_degree * cp_degree * tp_degree * ep_degree
    if devices.size < n_needed:
        raise ValueError(
            f"need {n_needed} devices for dp={dp_degree} cp={cp_degree} "
            f"tp={tp_degree} ep={ep_degree}, have {devices.size}"
        )
    grid = devices[:n_needed].reshape(dp_degree, cp_degree, tp_degree, ep_degree)
    return Mesh(grid, MESH_AXES)


def mesh_from_config(tpu_config, devices=None) -> Mesh:
    return build_mesh(
        tp_degree=tpu_config.tp_degree,
        dp_degree=tpu_config.dp_degree,
        cp_degree=tpu_config.cp_degree,
        ep_degree=tpu_config.ep_degree,
        devices=devices,
    )


def single_device_mesh(device: Optional[jax.Device] = None) -> Mesh:
    dev = device if device is not None else jax.devices()[0]
    return Mesh(np.asarray([dev]).reshape(1, 1, 1, 1), MESH_AXES)


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis]


def model_parallel_size(mesh: Mesh) -> int:
    """Total model-parallel width (cp*tp*ep) — the divisor for hidden-dim sharding."""
    return mesh.shape[AXIS_CP] * mesh.shape[AXIS_TP] * mesh.shape[AXIS_EP]
