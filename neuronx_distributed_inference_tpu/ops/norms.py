"""Normalization ops.

≈ reference `modules/custom_calls.py` (CustomRMSNorm XLA custom op :15-45, NKI rmsnorm
kernel :61-87). On TPU a plain jnp RMSNorm fuses into neighbouring ops under XLA, so no
custom kernel is needed for the norm alone; fused norm+matmul Pallas kernels live in
ops/ when profiling justifies them.
"""

from __future__ import annotations

import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-6,
             zero_centered: bool = False) -> jnp.ndarray:
    """RMSNorm computed in float32, cast back to x.dtype.

    ``zero_centered`` supports Gemma-style (1 + weight) scaling.
    """
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    normed = x32 * jnp.reciprocal(jnp.sqrt(var + eps))
    w = weight.astype(jnp.float32)
    if zero_centered:
        w = 1.0 + w
    return (normed * w).astype(dtype)


def layer_norm(x: jnp.ndarray, weight: jnp.ndarray, bias: jnp.ndarray,
               eps: float = 1e-5) -> jnp.ndarray:
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    normed = (x32 - mean) * jnp.reciprocal(jnp.sqrt(var + eps))
    return (normed * weight.astype(jnp.float32) + bias.astype(jnp.float32)).astype(dtype)
