"""On-device sampling: greedy / top-k / top-p / temperature with per-request params.

≈ reference `modules/generation/sampling.py` (`Sampler.forward` :437-468, `_top_k_masked`
:303, `prepare/validate_sampling_params` :99-209). Design notes:

- ``sampling_params`` is the reference's (B, 3) tensor [top_k, top_p, temperature]; each
  request can use different values ("dynamic" sampling).
- Like the reference, a *global* top-k prefilter (default 256, `global_topk`) bounds the
  sort/cumsum cost to a constant width regardless of vocab size. Under a vocab-sharded
  lm_head, `lax.top_k` over the sharded axis lets GSPMD do a per-shard top-k + gather
  (the analog of the reference's staged `nxd_topk` collective, `sampling.py:303-328`).
- Multinomial draws use Gumbel noise over the masked log-probs (TPU-friendly: no cumsum
  search); deterministic mode threads a fixed key.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..config import OnDeviceSamplingConfig

NEG_INF = -1e30


def prepare_sampling_params(batch_size: int, top_k=1, top_p=1.0, temperature=1.0):
    """Host-side helper: broadcast scalars/lists to a (B, 3) float32 array
    (≈ `sampling.py:99-150`)."""
    import numpy as np

    def _col(v):
        arr = np.asarray(v, dtype=np.float32).reshape(-1)
        if arr.size == 1:
            arr = np.full((batch_size,), arr[0], dtype=np.float32)
        if arr.shape != (batch_size,):
            raise ValueError(f"sampling param shape {arr.shape} != ({batch_size},)")
        return arr

    return np.stack([_col(top_k), _col(top_p), _col(temperature)], axis=1)


def _masked_window(
    logits: jnp.ndarray,                  # (..., V) fp32
    sampling_params: jnp.ndarray,         # (..., 3) broadcastable to logits[:-1]
    config: OnDeviceSamplingConfig,
):
    """Shared top-k/top-p/temperature masking over the global-topk window.

    Returns ``(masked (..., K), top_idx (..., K))``: temperature-scaled logits in
    descending order with rejected entries at NEG_INF, plus their vocab indices.
    """
    logits = logits.astype(jnp.float32)
    vocab = logits.shape[-1]
    k_width = min(config.global_topk, vocab)
    top_vals, top_idx = jax.lax.top_k(logits, k_width)   # (..., K) desc order

    top_k = sampling_params[..., 0:1]                    # (..., 1) float
    top_p = sampling_params[..., 1:2]
    temperature = jnp.maximum(sampling_params[..., 2:3], 1e-6)

    ranks = jnp.arange(k_width, dtype=jnp.float32)
    # top_k <= 0 means "all" (within the global prefilter window)
    k_eff = jnp.where(top_k <= 0, float(k_width), top_k)
    topk_mask = ranks < k_eff                            # (..., K)

    scaled = top_vals / temperature
    scaled = jnp.where(topk_mask, scaled, NEG_INF)

    # top-p (nucleus): keep the smallest prefix whose prob mass >= top_p; the first
    # token always survives (cumsum - p_i < top_p for i=0).
    probs = jax.nn.softmax(scaled, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    topp_mask = (cum - probs) < top_p
    masked = jnp.where(topp_mask, scaled, NEG_INF)
    return masked, top_idx


def sample(
    logits: jnp.ndarray,                  # (B, V) any float dtype
    sampling_params: jnp.ndarray,         # (B, 3) [top_k, top_p, temperature]
    key: Optional[jax.Array],
    config: OnDeviceSamplingConfig,
) -> jnp.ndarray:
    """Return sampled token ids (B,) int32, entirely on device."""
    logits = logits.astype(jnp.float32)
    batch = logits.shape[0]

    if not config.do_sample and not config.dynamic:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    masked, top_idx = _masked_window(logits, sampling_params, config)

    greedy_choice = jnp.zeros((batch,), dtype=jnp.int32)  # index 0 = argmax in sorted order
    if key is None:
        choice = greedy_choice
    else:
        gumbel = jax.random.gumbel(key, masked.shape, dtype=jnp.float32)
        sampled_choice = jnp.argmax(masked + gumbel, axis=-1).astype(jnp.int32)
        # greedy requests (top_k == 1) stay exact argmax regardless of noise
        choice = jnp.where(sampling_params[:, 0] == 1, greedy_choice, sampled_choice)

    return jnp.take_along_axis(top_idx, choice[:, None], axis=-1)[:, 0].astype(jnp.int32)


def window_probs(
    logits: jnp.ndarray,                  # (..., V)
    sampling_params: jnp.ndarray,         # (..., 3)
    config: OnDeviceSamplingConfig,
):
    """Post-mask probabilities over the global-topk window: ``(probs (..., K),
    idx (..., K))``. Used by speculative acceptance, which needs the *distribution* a
    token was (or would be) sampled from, not just a draw."""
    masked, top_idx = _masked_window(logits, sampling_params, config)
    return jax.nn.softmax(masked, axis=-1), top_idx


def scatter_to_vocab(probs: jnp.ndarray, idx: jnp.ndarray, vocab: int) -> jnp.ndarray:
    """Scatter window probabilities (..., K) at vocab indices (..., K) into a dense
    (..., V) distribution (zeros elsewhere)."""
    out = jnp.zeros(probs.shape[:-1] + (vocab,), dtype=probs.dtype)
    flat_out = out.reshape(-1, out.shape[-1])
    flat_idx = idx.reshape(-1, idx.shape[-1])
    flat_probs = probs.reshape(-1, probs.shape[-1])
    rows = jnp.arange(flat_out.shape[0])[:, None]
    flat_out = flat_out.at[rows, flat_idx].set(flat_probs)
    return flat_out.reshape(out.shape)


def greedy(logits: jnp.ndarray) -> jnp.ndarray:
    return jnp.argmax(logits.astype(jnp.float32), axis=-1).astype(jnp.int32)
