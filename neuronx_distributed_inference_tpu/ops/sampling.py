"""On-device sampling: greedy / top-k / top-p / temperature with per-request params.

≈ reference `modules/generation/sampling.py` (`Sampler.forward` :437-468, `_top_k_masked`
:303, `prepare/validate_sampling_params` :99-209). Design notes:

- ``sampling_params`` is the reference's (B, 3) tensor [top_k, top_p, temperature]; each
  request can use different values ("dynamic" sampling).
- Like the reference, a *global* top-k prefilter (default 256, `global_topk`) bounds the
  sort/cumsum cost to a constant width regardless of vocab size. Under a vocab-sharded
  lm_head, `lax.top_k` over the sharded axis lets GSPMD do a per-shard top-k + gather
  (the analog of the reference's staged `nxd_topk` collective, `sampling.py:303-328`).
- Multinomial draws use Gumbel noise over the masked log-probs (TPU-friendly: no cumsum
  search); deterministic mode threads a fixed key.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..config import OnDeviceSamplingConfig

NEG_INF = -1e30


def _sharded_vocab_axis(logits_shape, mesh, rules) -> Optional[str]:
    """The mesh axis to run per-shard top-k over, or None for the dense path.

    Sharded sampling is on by default whenever the lm_head is vocab-sharded
    over a real axis (the ``vocab`` rule, tp by default) and the shapes
    divide; ``TPUINF_SHARDED_SAMPLING=0`` opts out (trace-time)."""
    if mesh is None:
        return None
    if os.environ.get("TPUINF_SHARDED_SAMPLING", "1") == "0":
        return None
    from ..parallel.sharding import DEFAULT_RULES

    r = rules or DEFAULT_RULES
    ax = r.get("vocab")
    if not isinstance(ax, str) or mesh.shape.get(ax, 1) <= 1:
        return None
    if logits_shape[-1] % mesh.shape[ax] != 0:
        return None
    batch_rule = r.get("batch")
    b_axes = ((batch_rule,) if isinstance(batch_rule, str)
              else tuple(batch_rule or ()))
    b_div = 1
    for a in b_axes:
        b_div *= mesh.shape.get(a, 1)
    if logits_shape[0] % b_div != 0:
        return None
    return ax


def vocab_topk_window(logits: jnp.ndarray, k_width: int, mesh, rules,
                      axis: str) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """``lax.top_k(logits, k_width)`` computed WITHOUT materializing the full
    (..., V) logits on one shard: each shard top-ks its local vocab slice,
    the (tiny) per-shard candidate windows all-gather across the axis, and a
    final top-k merges them. ≈ the reference's staged ``nxd_topk`` collective
    (`modules/generation/sampling.py:303-328`).

    Exactness: candidates concatenate in ascending-vocab-chunk order and each
    shard's window is value-desc/index-asc, so the merge's tie-breaking (lower
    position wins) reproduces dense ``lax.top_k`` bit-for-bit — including the
    order among equal logits."""
    from ..parallel.sharding import logical_to_spec

    nd = logits.ndim
    logical = ("batch",) + (None,) * (nd - 2) + ("vocab",)
    spec = logical_to_spec(logical, rules)
    out_spec = logical_to_spec(("batch",) + (None,) * (nd - 1), rules)

    def _local(lg):
        v_loc = lg.shape[-1]
        kw = min(k_width, v_loc)
        vals, idx = jax.lax.top_k(lg, kw)
        gidx = idx + jax.lax.axis_index(axis) * v_loc
        allv = jax.lax.all_gather(vals, axis, axis=nd - 1, tiled=True)
        alli = jax.lax.all_gather(gidx, axis, axis=nd - 1, tiled=True)
        mvals, mpos = jax.lax.top_k(allv, k_width)
        return mvals, jnp.take_along_axis(alli, mpos, axis=-1)

    from ..models.base import shard_map_compat

    fn = shard_map_compat(_local, mesh=mesh, in_specs=(spec,),
                          out_specs=(out_spec, out_spec))
    return fn(logits)


def prepare_sampling_params(batch_size: int, top_k=1, top_p=1.0, temperature=1.0):
    """Host-side helper: broadcast scalars/lists to a (B, 3) float32 array
    (≈ `sampling.py:99-150`)."""
    import numpy as np

    def _col(v):
        arr = np.asarray(v, dtype=np.float32).reshape(-1)
        if arr.size == 1:
            arr = np.full((batch_size,), arr[0], dtype=np.float32)
        if arr.shape != (batch_size,):
            raise ValueError(f"sampling param shape {arr.shape} != ({batch_size},)")
        return arr

    return np.stack([_col(top_k), _col(top_p), _col(temperature)], axis=1)


def _masked_window(
    logits: jnp.ndarray,                  # (..., V) fp32
    sampling_params: jnp.ndarray,         # (..., 3) broadcastable to logits[:-1]
    config: OnDeviceSamplingConfig,
    mesh=None,
    rules=None,
):
    """Shared top-k/top-p/temperature masking over the global-topk window.

    Returns ``(masked (..., K), top_idx (..., K))``: temperature-scaled logits in
    descending order with rejected entries at NEG_INF, plus their vocab indices.
    With a mesh whose ``vocab`` rule is sharded, the window comes from the
    per-shard top-k merge (vocab_topk_window) — no full (..., V) logits ever
    land on one chip.
    """
    logits = logits.astype(jnp.float32)
    vocab = logits.shape[-1]
    k_width = min(config.global_topk, vocab)
    axis = _sharded_vocab_axis(logits.shape, mesh, rules)
    if axis is not None:
        top_vals, top_idx = vocab_topk_window(logits, k_width, mesh, rules,
                                              axis)
    else:
        top_vals, top_idx = jax.lax.top_k(logits, k_width)  # (..., K) desc

    top_k = sampling_params[..., 0:1]                    # (..., 1) float
    top_p = sampling_params[..., 1:2]
    temperature = jnp.maximum(sampling_params[..., 2:3], 1e-6)

    ranks = jnp.arange(k_width, dtype=jnp.float32)
    # top_k <= 0 means "all" (within the global prefilter window)
    k_eff = jnp.where(top_k <= 0, float(k_width), top_k)
    topk_mask = ranks < k_eff                            # (..., K)

    scaled = top_vals / temperature
    scaled = jnp.where(topk_mask, scaled, NEG_INF)

    # top-p (nucleus): keep the smallest prefix whose prob mass >= top_p; the first
    # token always survives (cumsum - p_i < top_p for i=0).
    probs = jax.nn.softmax(scaled, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    topp_mask = (cum - probs) < top_p
    masked = jnp.where(topp_mask, scaled, NEG_INF)
    return masked, top_idx


def sample(
    logits: jnp.ndarray,                  # (B, V) any float dtype
    sampling_params: jnp.ndarray,         # (B, 3) [top_k, top_p, temperature]
    key: Optional[jax.Array],
    config: OnDeviceSamplingConfig,
    mesh=None,
    rules=None,
) -> jnp.ndarray:
    """Return sampled token ids (B,) int32, entirely on device.

    ``mesh``/``rules`` opt into tp-sharded sampling: the candidate window is
    merged from per-shard top-ks (the full (B, V) logits stay vocab-sharded);
    the gumbel draw and masking then run on the tiny (B, K) window, identical
    to the dense path."""
    logits = logits.astype(jnp.float32)
    batch = logits.shape[0]

    if not config.do_sample and not config.dynamic:
        return greedy(logits, mesh=mesh, rules=rules)

    masked, top_idx = _masked_window(logits, sampling_params, config,
                                     mesh=mesh, rules=rules)

    greedy_choice = jnp.zeros((batch,), dtype=jnp.int32)  # index 0 = argmax in sorted order
    if key is None:
        choice = greedy_choice
    else:
        gumbel = jax.random.gumbel(key, masked.shape, dtype=jnp.float32)
        sampled_choice = jnp.argmax(masked + gumbel, axis=-1).astype(jnp.int32)
        # greedy requests (top_k == 1) stay exact argmax regardless of noise
        choice = jnp.where(sampling_params[:, 0] == 1, greedy_choice, sampled_choice)

    return jnp.take_along_axis(top_idx, choice[:, None], axis=-1)[:, 0].astype(jnp.int32)


def window_probs(
    logits: jnp.ndarray,                  # (..., V)
    sampling_params: jnp.ndarray,         # (..., 3)
    config: OnDeviceSamplingConfig,
    mesh=None,
    rules=None,
):
    """Post-mask probabilities over the global-topk window: ``(probs (..., K),
    idx (..., K))``. Used by speculative acceptance, which needs the *distribution* a
    token was (or would be) sampled from, not just a draw."""
    masked, top_idx = _masked_window(logits, sampling_params, config,
                                     mesh=mesh, rules=rules)
    return jax.nn.softmax(masked, axis=-1), top_idx


def scatter_to_vocab(probs: jnp.ndarray, idx: jnp.ndarray, vocab: int) -> jnp.ndarray:
    """Scatter window probabilities (..., K) at vocab indices (..., K) into a dense
    (..., V) distribution (zeros elsewhere)."""
    out = jnp.zeros(probs.shape[:-1] + (vocab,), dtype=probs.dtype)
    flat_out = out.reshape(-1, out.shape[-1])
    flat_idx = idx.reshape(-1, idx.shape[-1])
    flat_probs = probs.reshape(-1, probs.shape[-1])
    rows = jnp.arange(flat_out.shape[0])[:, None]
    flat_out = flat_out.at[rows, flat_idx].set(flat_probs)
    return flat_out.reshape(out.shape)


def greedy(logits: jnp.ndarray, mesh=None, rules=None) -> jnp.ndarray:
    """Argmax token ids; under a vocab-sharded mesh the argmax merges
    per-shard (value, index) candidates instead of gathering (B, V) logits
    (same lowest-index tie-breaking as dense argmax)."""
    logits = logits.astype(jnp.float32)
    axis = _sharded_vocab_axis(logits.shape, mesh, rules)
    if axis is not None:
        _, idx = vocab_topk_window(logits, 1, mesh, rules, axis)
        return idx[..., 0].astype(jnp.int32)
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)
