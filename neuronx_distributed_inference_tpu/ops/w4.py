"""int4 weight-only quantization: packed storage + Pallas streaming matmul.

Decode is HBM-bandwidth-bound and the int8 weight stream already runs at ~90%
of roofline (ROUND5_NOTES §12), so the only way to shrink the decode step
further is fewer weight bytes: int4 halves them. The reference stops at
int8/fp8 weights (NxD quantize configs, `models/model_wrapper.py:11-21`) and
MXFP4 for gpt-oss ingest — this is a capability beyond reference parity.

Measured on v5e (scripts/probe_w4_kernel_bf16.py, 4096x14336 @ bs=64):
- XLA cannot ride the nibble unpack into the dot's operand read (ratio 0.95 of
  int8 — the whole bandwidth win burned on VPU materialization), and the native
  `jnp.int4` dtype is UNIMPLEMENTED on this backend, so the unpack must live in
  a Pallas kernel.
- The Pallas W4A8 kernel (int8 MXU dots) streams a layer in ~46 us of real
  work vs ~80 us for the int8 XLA dot (36 us DMA floor): a ~1.7x win on the
  weight-streaming portion of the decode step.

Layout: **half-split packing, biased lo nibble**. A logical weight W
(..., in, out) packs rows i and i+in/2 into one byte:

    packed[..., i, o] = (W[..., i + in/2, o] << 4) | ((W[..., i, o] + 8) & 0xF)

The lo nibble is stored BIASED (+8, so 0..15 unsigned) while the hi nibble is
two's complement: ``p & 15`` recovers ``lo + 8`` with a constant bias the
epilogue removes via ``-8 * rowsum(x_lo)``, and ``p & 0xF0`` IS ``16 * hi`` as
a signed byte (the hi dot's int32 accumulator shifts right 4, exact) — so the
in-kernel unpack is two int8 AND ops into one contiguous (in, bo) VMEM scratch
(two plain sublane-range stores, no interleave shuffle), with no i32
widen/narrow relayouts and no shifts: Mosaic legalizes neither int8 vector
shifts nor int8 subtraction, and the widen/narrow relayouts of an i32-domain
unpack dominated the kernel (measured, see ROUND5_NOTES §14). An earlier
even/odd two-dot design split x into strided halves; the on-chip profile
showed XLA materializing those slices through transposed relayout fusions at
~26 us each per wd layer call. Half-split keeps x whole. Unaligned-hin shapes
fall back to the i32 unpack (same trick as paged_decode._vmem_cast). Under a
sharded mesh the q4 leaf takes the XLA dequant path (w4_apply), where GSPMD
keeps any packing correct.

The stacked (L, in/2, out) payload is NEVER sliced by the layer scan — it
reaches the kernel whole (closure through `_scan_layers`, see models/base) and
the layer index arrives via scalar prefetch, so the per-layer "slice" is just
a BlockSpec index-map coordinate (an XLA slice of a packed operand feeding a
pallas_call would materialize a per-layer copy and destroy the win).

Activations: per-token dynamic int8 quantization happens OUTSIDE the kernel
(XLA fuses it into the preceding norm); the kernel runs int8 x int8 on the MXU
(394 TOPS — the bf16-dot variant measured MXU-bound at B=64) and applies both
scales (per-token sx, per-channel s) in the f32 epilogue before the bf16 cast.
For wide inputs (prefill), the grid adds an m dimension; the unpacked weight
tile is cached in VMEM scratch at mi==0 and reused across the m sweep.
"""

from __future__ import annotations

import functools
import os
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# packed-layout version, recorded in weight artifacts: v2 = half-split with
# BIASED lo nibble (v1, an interim unbiased even/odd layout, decodes silently
# wrong under v2 unpack — loaders must refuse mismatched artifacts)
W4_PACK_VERSION = 2

# out-tile width cap: r5b sweep on the single-dot kernel — 1024 beats 512 at
# both bs=64 (12.92 vs 13.48 ms/step) and bs=128 (17.06 vs 17.36); the VMEM
# model below still shrinks per-shape (wd lands at 256 either way).
# TPUINF_W4_BO overrides for on-chip sweeps (read at TRACE time: set it before
# the first compile; a warm executable never re-reads it).
_BO = 1024


def _bo_cap() -> int:
    try:
        cap = int(os.environ.get("TPUINF_W4_BO", _BO))
    except ValueError:
        cap = _BO
    return cap if cap >= 128 else _BO
# m-tile height for wide (prefill) inputs
_BM = 512


def _plan_tiles(m: int, hin: int, out: int, *, xbytes: int, wsbytes: int,
                tag: str = "") -> tuple:
    """Pick (bm, bo) so one grid cell fits the default 16 MB scoped-vmem
    budget — raising the budget via compiler_params backfired (XLA then placed
    the whole output in scoped vmem and blew the 128 MB chip total).

    The estimator models Mosaic pipelining streamed blocks with up to THREE
    live buffers (measured: a 2-buffer model overflowed by exactly one buffer
    generation); the (2*hin, bo) scratch is single-buffered. Out-tile
    candidates are lane-aligned (128-multiple) DIVISORS of out, widest first,
    capped by _BO/TPUINF_W4_BO — walking divisors (not halving) keeps every
    candidate aligned: halving 896 would visit 448, which Mosaic rejects.
    Odd out dims (no aligned divisor) run whole-out."""
    bm = min(m, _BM)

    def _est(bm_, bo_):
        return (3 * (2 * bm_ * hin * xbytes + hin * bo_ + 2 * bm_ * bo_
                     + bm_ * 128 * 4)
                + 2 * hin * bo_ * wsbytes)

    cap = _bo_cap()
    bo_cands = [d for d in range(min(out, cap), 127, -128) if out % d == 0]
    if not bo_cands:
        bo_cands = [out]
    boi = 0
    bo = bo_cands[boi]
    can_tile_m = m > _BM                 # decode keeps its single whole-m tile
    while _est(bm, bo) > 15 * 2 ** 20:
        # prefer shrinking bm (when m-tiling): a wide out tile keeps the MXU
        # fed (a 128-wide out tile makes every cell a single-tile-wide dot)
        if can_tile_m and bm > 64 and (bm > bo or boi == len(bo_cands) - 1):
            bm //= 2
        elif boi < len(bo_cands) - 1:
            boi += 1
            bo = bo_cands[boi]
        elif can_tile_m and bm > 64:
            bm //= 2
        else:
            break
    if os.environ.get("W4_DEBUG"):
        print(f"[w4] m={m} hin={hin} out={out} {tag} bm={bm} bo={bo} "  # debug-ok: env-gated
              f"est={_est(bm, bo)/2**20:.2f}MB", flush=True)
    return bm, bo


def _slice_stacked_w4(q4, s, li):
    """One layer's {"q4","s"} leaf from the stacked payload (the shared
    slicing convention for the GSPMD dequant fallbacks in w4_apply/qeinsum)."""
    return {"q4": jax.lax.dynamic_index_in_dim(q4, li, 0, keepdims=False),
            "s": jax.lax.dynamic_index_in_dim(s, li, 0, keepdims=False)}


def is_w4(w) -> bool:
    return isinstance(w, dict) and "q4" in w and "s" in w


def pack_int4(w) -> Dict[str, Any]:
    """Symmetric per-output-channel int4 quantization, half-split packed.

    ``w`` (..., in, out) float -> {"q4": int8 (..., in/2, out) packed,
    "s": f32 (..., 1, out)}. Host-side numpy (see quantize_tensor): a model
    larger than one device's HBM never materializes unsharded on device.

    The scale reduction is FIXED over the contraction dim (axis -2): every
    consumer (the Pallas kernel epilogue, dequant_w4, the GSPMD dequant dot)
    applies ``s`` per OUTPUT channel after the contraction sum — a scale that
    varied along the contraction axis could not be factored out of the dot.
    (An earlier ``scale_axis`` parameter was accepted and silently ignored;
    it is gone rather than half-honored.)
    """
    import numpy as np

    w32 = np.asarray(jax.device_get(w) if isinstance(w, jax.Array) else w,
                     dtype=np.float32)
    if w32.shape[-2] % 2:
        raise ValueError(f"int4 packing needs an even contraction dim, got "
                         f"{w32.shape}")
    absmax = np.max(np.abs(w32), axis=-2, keepdims=True)
    scale = np.maximum(absmax / 7.0, 1e-12)
    q = np.clip(np.round(w32 / scale), -7, 7).astype(np.int8)
    h = q.shape[-2] // 2
    lo = q[..., :h, :]
    hi = q[..., h:, :]
    packed = ((hi << 4) | ((lo + 8) & 0xF)).astype(np.int8)
    return {"q4": packed, "s": scale.astype(np.float32)}


def unpack_int4(packed) -> "np.ndarray":
    """Host-side inverse of the packing (returns int values, no scales)."""
    import numpy as np

    p = np.asarray(packed).astype(np.int8)
    lo = (p & 0xF) - 8                # lo nibble is stored biased by +8
    hi = p >> 4                       # numpy int8 >> is arithmetic
    return np.concatenate([lo, hi], axis=-2)


def dequant_w4(qw: Dict[str, Any], dtype=jnp.float32) -> jnp.ndarray:
    """Dequantize a {"q4","s"} leaf back to the logical (..., in, out) weight
    (host/differentiable-free reference path; used by CPU fallbacks + tests)."""
    p = qw["q4"].astype(jnp.int32)
    lo = (p & 0xF) - 8                # lo nibble is stored biased by +8
    hi = jax.lax.shift_right_arithmetic(p, 4)
    w = jnp.concatenate([lo, hi], axis=-2).astype(jnp.float32)
    return (w * qw["s"]).astype(dtype)


def _unpack_into(w_s, p, hin: int, int8_acts: bool, fast_unpack: bool):
    """Unpack one packed (hin, bo) tile into the (2*hin, bo) dot-ready scratch.

    fast path: AND-only unpack, pure int8 vector ops (no i32 widen/narrow
    relayouts — those dominated the kernel, see module docstring): rows
    [0, hin) hold the UNSIGNED lo nibbles (bias corrected in the epilogue via
    -8*rowsum(x_lo)); rows [hin, 2hin) hold p & 0xF0, which in two's
    complement IS 16*hi — the hi dot's int32 accumulator shifts right 4
    (exact)."""
    if fast_unpack:
        w_s[:hin] = p & jnp.int8(15)
        w_s[hin:] = p & jnp.int8(-16)
    else:
        p32 = p.astype(jnp.int32)
        tgt = jnp.int8 if int8_acts else jnp.bfloat16
        w_s[:hin] = ((p32 & 15) - 8).astype(tgt)
        w_s[hin:] = jax.lax.shift_right_arithmetic(p32, 4).astype(tgt)


def _w4_cell(x, w_s, hin: int, int8_acts: bool, fast_unpack: bool):
    """The shared dot body: (bm, 2hin) x against the unpacked scratch -> f32
    accumulator (per-channel/per-token scales applied by the caller)."""
    if fast_unpack:
        dims = (((1,), (0,)), ((), ()))
        acc_l = jax.lax.dot_general(x[:, :hin], w_s[:hin], dims,
                                    preferred_element_type=jnp.int32)
        acc_h = jax.lax.dot_general(x[:, hin:], w_s[hin:], dims,
                                    preferred_element_type=jnp.int32)
        rs = jnp.sum(x[:, :hin].astype(jnp.int32), axis=1, keepdims=True)
        return (acc_l - 8 * rs
                + jax.lax.shift_right_arithmetic(acc_h, 4)).astype(jnp.float32)
    pref = jnp.int32 if int8_acts else jnp.float32
    return jax.lax.dot_general(x, w_s[...], (((1,), (0,)), ((), ())),
                               preferred_element_type=pref).astype(jnp.float32)


def _w4_kernel(lidx_ref, x_ref, sx_ref, p_ref, s_ref, o_ref, w_s, *,
               int8_acts: bool, hin: int, fast_unpack: bool):
    mi = pl.program_id(1)

    @pl.when(mi == 0)
    def _unpack():
        _unpack_into(w_s, p_ref[0], hin, int8_acts, fast_unpack)

    acc = _w4_cell(x_ref[...], w_s, hin, int8_acts, fast_unpack) * s_ref[0, 0]
    if int8_acts:
        acc = acc * sx_ref[:, 0:1]
    o_ref[...] = acc.astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def w4_matmul_stacked(
    x: jnp.ndarray,              # (M, in) bf16/f32 activations
    packed: jnp.ndarray,         # (L, in/2, out) int8 — FULL stacked payload
    scales: jnp.ndarray,         # (L, 1, out) f32
    layer_idx: jnp.ndarray,      # () int32
    interpret: bool = False,
) -> jnp.ndarray:
    """One layer's ``x @ W`` from the stacked int4-packed weight.

    Decode (M <= _BM): W4A8 — x quantizes per-token to int8 outside the kernel
    and the dots run int8 x int8 on the MXU. Wider inputs (prefill) keep bf16
    activations (no act-quant error where compute, not bandwidth, binds) and
    sweep m tiles with the unpacked weight cached in VMEM scratch.
    Returns (M, out) bf16.
    """
    l, hin, out = packed.shape
    m, in_dim = x.shape
    if in_dim != 2 * hin:
        raise ValueError(f"x in-dim {in_dim} != 2*{hin}")

    # wide (prefill) inputs also take the A8 path when the fast AND-unpack is
    # available: int8 MXU doubles the bf16 rate (compute binds at prefill) and
    # the reference's own prefill act-quants (rmsnorm_quant, fp8 there);
    # per-token int8 act quant error is ~0.4% relative. The bf16 sweep remains
    # for unaligned hin. TPUINF_W4_PREFILL_BF16 opts out — read at TRACE time
    # (like TPUINF_STACKED_ATTEND_MIN_BUCKET): set it before the first compile;
    # a warm executable never re-reads it.
    int8_acts = (m <= _BM
                 or (hin % 128 == 0
                     and not os.environ.get("TPUINF_W4_PREFILL_BF16")))
    if int8_acts:
        xf = x.astype(jnp.float32)
        sx = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1, keepdims=True),
                         1e-8) / 127.0
        xq = jnp.clip(jnp.round(xf / sx), -127, 127).astype(jnp.int8)
        sxp = jnp.broadcast_to(sx.astype(jnp.float32), (m, 128))
    else:
        xq = x.astype(jnp.bfloat16)
        sxp = jnp.zeros((8, 128), jnp.float32)     # unused
    bm = min(m, _BM)

    bm, bo = _plan_tiles(m, hin, out, xbytes=xq.dtype.itemsize,
                         wsbytes=1 if int8_acts else 2,
                         tag=f"int8_acts={int8_acts}")
    if m % bm:
        pad = bm - m % bm
        xq = jnp.pad(xq, ((0, pad), (0, 0)))
        if int8_acts:
            sxp = jnp.pad(sxp, ((0, pad), (0, 0)))
    mp = xq.shape[0]
    nm = mp // bm
    nt = out // bo

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nt, nm),                 # m fastest: weight tile reused across m
        in_specs=[
            pl.BlockSpec((bm, 2 * hin), lambda ti, mi, lidx: (mi, 0)),
            pl.BlockSpec((bm, 128) if int8_acts else (8, 128),
                         lambda ti, mi, lidx: (mi, 0) if int8_acts else (0, 0)),
            pl.BlockSpec((1, hin, bo), lambda ti, mi, lidx: (lidx[0], 0, ti)),
            pl.BlockSpec((1, 1, bo), lambda ti, mi, lidx: (lidx[0], 0, ti)),
        ],
        out_specs=pl.BlockSpec((bm, bo), lambda ti, mi, lidx: (mi, ti)),
        scratch_shapes=[
            pltpu.VMEM((2 * hin, bo), jnp.int8 if int8_acts else jnp.bfloat16),
        ],
    )
    # the AND-only unpack needs int8 operands and lane-aligned x halves
    fast_unpack = int8_acts and hin % 128 == 0
    kernel = functools.partial(_w4_kernel, int8_acts=int8_acts, hin=hin,
                               fast_unpack=fast_unpack)
    y = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((mp, out), jnp.bfloat16),
        interpret=interpret,
    )(layer_idx.reshape(1).astype(jnp.int32), xq, sxp, packed, scales)
    return y[:m] if mp != m else y


def w4_apply(x: jnp.ndarray, w: Dict[str, Any],
             interpret: Optional[bool] = None) -> jnp.ndarray:
    """qapply-equivalent for a w4 leaf: handles arbitrary leading dims and both
    stacked ({"q4": (L, in/2, out), "layer": li}) and flat ({"q4": (in/2, out)})
    layouts.

    ``w["use_kernel"]`` (a static bool attached by the layer scan) selects the
    Pallas kernel (single-device meshes — the bench/serving configuration) or
    the XLA dequant path (multi-device meshes, where a pallas_call has no GSPMD
    partitioning rule: the dequantized per-layer slice is a plain dot GSPMD can
    shard; correct everywhere, fast only where it doesn't matter).
    Returns x.dtype."""
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    q4, s = w["q4"], w["s"]
    use_kernel = w.get("use_kernel", True)
    if q4.ndim == 2:
        if not use_kernel:
            return (x @ dequant_w4({"q4": q4, "s": s}, x.dtype)).astype(x.dtype)
        q4 = q4[None]
        s = s.reshape(1, 1, -1)
        li = jnp.int32(0)
    else:
        if q4.ndim != 3:
            raise ValueError(f"w4_apply takes (in/2, out) or (L, in/2, out) "
                             f"payloads, got {q4.shape} — 4-D stacked expert "
                             f"weights route through qeinsum's MoE patterns")
        li = w.get("layer")
        if li is None:
            raise ValueError("stacked w4 leaf reached w4_apply without a layer "
                             "index — int4 weights must flow through the layer "
                             "scan's closure path (see _scan_layers)")
        s = s.reshape(q4.shape[0], 1, -1)
        if not use_kernel:
            return (x @ dequant_w4(_slice_stacked_w4(q4, s, li), x.dtype)
                    ).astype(x.dtype)
    lead = x.shape[:-1]
    m = 1
    for d in lead:
        m *= d
    x2 = x.reshape(m, x.shape[-1])
    y = w4_matmul_stacked(x2, q4, s.astype(jnp.float32), li,
                          interpret=interpret)
    return y.reshape(*lead, y.shape[-1]).astype(x.dtype)


def repack_int8_to_int4(qw: Dict[str, Any]) -> Dict[str, Any]:
    """Re-quantize an int8 {"q","s"} leaf to the int4 {"q4","s"} layout without
    materializing the float weight: q4 = round(q * 7/127), s4 = s * 127/7.
    Used to int4-convert pre-quantized int8 checkpoints (and the synthetic
    bench trees, which are born int8)."""
    import numpy as np

    q = np.asarray(qw["q"])
    if q.dtype != np.int8:
        raise ValueError(f"repack_int8_to_int4 needs an int8 payload, got {q.dtype}")
    q4 = np.clip(np.round(q.astype(np.float32) * (7.0 / 127.0)), -7, 7
                 ).astype(np.int8)
    h = q4.shape[-2] // 2
    lo = q4[..., :h, :]
    hi = q4[..., h:, :]
    packed = ((hi << 4) | ((lo + 8) & 0xF)).astype(np.int8)
    return {"q4": packed, "s": np.asarray(qw["s"]) * np.float32(127.0 / 7.0)}


def _w4_moe_kernel(lidx_ref, x_ref, sx_ref, p_ref, s_ref, o_ref, w_s, *,
                   int8_acts: bool, hin: int, fast_unpack: bool,
                   per_expert_x: bool):
    mi = pl.program_id(2)

    @pl.when(mi == 0)
    def _unpack():
        _unpack_into(w_s, p_ref[0, 0], hin, int8_acts, fast_unpack)

    x = x_ref[0] if per_expert_x else x_ref[...]
    acc = _w4_cell(x, w_s, hin, int8_acts, fast_unpack) * s_ref[0, 0, 0]
    if int8_acts:
        sx = sx_ref[0] if per_expert_x else sx_ref[...]
        acc = acc * sx[:, 0:1]
    o_ref[0] = acc.astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("per_expert_x", "interpret"))
def w4_moe_matmul_stacked(
    x: jnp.ndarray,              # (N, in) shared or (E, N, in) per-expert
    packed: jnp.ndarray,         # (L, E, in/2, out) int8 — FULL stacked payload
    scales: jnp.ndarray,         # (L, E, 1, out) f32
    layer_idx: jnp.ndarray,      # () int32
    per_expert_x: bool = False,
    interpret: bool = False,
) -> jnp.ndarray:
    """Dense all-experts MoE matmul from the stacked int4-packed expert weights
    (the ``nh,ehi->eni`` / ``eni,eih->enh`` qeinsum patterns, ops/moe.py).
    Same design as w4_matmul_stacked with an expert grid dimension; every
    (expert, out-tile) unpacks once and is swept over the m tiles.
    Returns (E, N, out) bf16."""
    l, e, hin, out = packed.shape
    n = x.shape[-2]
    if x.shape[-1] != 2 * hin:
        raise ValueError(f"x in-dim {x.shape[-1]} != 2*{hin}")

    # same activation-dtype rule as the dense path (incl. the
    # TPUINF_W4_PREFILL_BF16 opt-out) — see w4_matmul_stacked
    int8_acts = (n <= _BM
                 or (hin % 128 == 0
                     and not os.environ.get("TPUINF_W4_PREFILL_BF16")))
    if int8_acts:
        xf = x.astype(jnp.float32)
        sx = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1, keepdims=True),
                         1e-8) / 127.0
        xq = jnp.clip(jnp.round(xf / sx), -127, 127).astype(jnp.int8)
        sxp = jnp.broadcast_to(sx.astype(jnp.float32), x.shape[:-1] + (128,))
    else:
        xq = x.astype(jnp.bfloat16)
        sxp = jnp.zeros(x.shape[:-2] + (8, 128), jnp.float32)   # unused

    bm, bo = _plan_tiles(n, hin, out, xbytes=xq.dtype.itemsize,
                         wsbytes=1 if int8_acts else 2,
                         tag=f"moe int8_acts={int8_acts}")
    if n % bm:
        pad = bm - n % bm
        width = [(0, 0)] * (x.ndim - 2) + [(0, pad), (0, 0)]
        xq = jnp.pad(xq, width)
        sxp = jnp.pad(sxp, width)
    np_ = xq.shape[-2]
    nm = np_ // bm
    nt = out // bo
    fast_unpack = int8_acts and hin % 128 == 0
    sbm = bm if int8_acts else 8

    if per_expert_x:
        x_spec = pl.BlockSpec((1, bm, 2 * hin),
                              lambda ei, ti, mi, lidx: (ei, mi, 0))
        sx_spec = pl.BlockSpec(
            (1, sbm, 128),
            (lambda ei, ti, mi, lidx: (ei, mi, 0)) if int8_acts
            else (lambda ei, ti, mi, lidx: (ei, 0, 0)))
    else:
        x_spec = pl.BlockSpec((bm, 2 * hin), lambda ei, ti, mi, lidx: (mi, 0))
        sx_spec = pl.BlockSpec(
            (sbm, 128),
            (lambda ei, ti, mi, lidx: (mi, 0)) if int8_acts
            else (lambda ei, ti, mi, lidx: (0, 0)))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(e, nt, nm),
        in_specs=[
            x_spec,
            sx_spec,
            pl.BlockSpec((1, 1, hin, bo),
                         lambda ei, ti, mi, lidx: (lidx[0], ei, 0, ti)),
            pl.BlockSpec((1, 1, 1, bo),
                         lambda ei, ti, mi, lidx: (lidx[0], ei, 0, ti)),
        ],
        out_specs=pl.BlockSpec((1, bm, bo),
                               lambda ei, ti, mi, lidx: (ei, mi, ti)),
        scratch_shapes=[
            pltpu.VMEM((2 * hin, bo), jnp.int8 if int8_acts else jnp.bfloat16),
        ],
    )
    kernel = functools.partial(_w4_moe_kernel, int8_acts=int8_acts, hin=hin,
                               fast_unpack=fast_unpack,
                               per_expert_x=per_expert_x)
    y = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((e, np_, out), jnp.bfloat16),
        interpret=interpret,
    )(layer_idx.reshape(1).astype(jnp.int32), xq, sxp, packed,
      scales.reshape(l, e, 1, out).astype(jnp.float32))
    return y[:, :n] if np_ != n else y
