"""Ring attention: context-parallel prefill over the mesh's ``cp`` axis.

The reference scales long-context prefill with context parallelism — attention computed
in reduced TP groups where each rank owns a sequence shard and the flash kernel gets a
``cp_offset`` so it computes only its causal trapezoid
(`modules/attention/attention_base.py:647-734`, process groups
`attention_process_groups.py:47-123`). SURVEY §5 notes the idiomatic TPU form is ring
attention, and that is what this is:

- q/k/v are sharded along the sequence dim over ``cp``; each rank computes attention of
  its query block against every KV block, with KV blocks **rotating around the ring**
  via `lax.ppermute` (ICI neighbor exchange, bandwidth-optimal, overlappable with the
  block compute by XLA).
- Blocks combine with the online-softmax recurrence (running max ``m``, normalizer
  ``l``, accumulator ``acc``) — the cross-device generalization of the flash-attention
  update, so no rank ever materializes a full S×S score matrix or the full KV.
- Causality is positional: each block carries its global kv positions; fully-masked
  (future) blocks contribute zero. A load-balanced (strided/zigzag) layout
  (≈ the reference's strided CP kernel variant, `models/model_base.py:890-898`) is a
  later optimization — correctness here is layout-independent because masks follow the
  carried position arrays, not rank indices.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from ..parallel.mesh import AXIS_CP
from ..parallel.sharding import logical_to_spec
from .attention import repeat_kv

NEG_BIG = -1e30


def _ring_local(q, k, v, q_pos, kv_pos, *, cp_size: int, scale: float, n_rep: int,
                window: Optional[int]):
    """Per-shard body (runs under shard_map). q (B, Hq, Sq, D); k/v (B, Hkv, Skv, D);
    q_pos (B, Sq); kv_pos (B, Skv). Returns (B, Hq, Sq, D)."""
    b, hq, sq, d = q.shape
    q32 = q.astype(jnp.float32)
    acc = jnp.zeros((b, hq, sq, d), dtype=jnp.float32)
    m = jnp.full((b, hq, sq), NEG_BIG, dtype=jnp.float32)
    l = jnp.zeros((b, hq, sq), dtype=jnp.float32)

    k_blk, v_blk, kvp = k, v, kv_pos
    perm = [(i, (i + 1) % cp_size) for i in range(cp_size)]
    for step in range(cp_size):
        kr = repeat_kv(k_blk, n_rep).astype(jnp.float32)
        vr = repeat_kv(v_blk, n_rep).astype(jnp.float32)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q32, kr) * scale
        mask = kvp[:, None, None, :] <= q_pos[:, None, :, None]
        if window is not None:
            mask = jnp.logical_and(
                mask, kvp[:, None, None, :] > q_pos[:, None, :, None] - window)
        scores = jnp.where(mask, scores, NEG_BIG)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        # mask-multiply guards the all-masked case (exp(NEG_BIG - NEG_BIG) = 1)
        p = jnp.exp(scores - m_new[..., None]) * mask
        alpha = jnp.exp(m - m_new)
        l = l * alpha + p.sum(axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, vr)
        m = m_new
        if step < cp_size - 1:
            k_blk = jax.lax.ppermute(k_blk, AXIS_CP, perm)
            v_blk = jax.lax.ppermute(v_blk, AXIS_CP, perm)
            kvp = jax.lax.ppermute(kvp, AXIS_CP, perm)

    out = acc / jnp.maximum(l, 1e-20)[..., None]
    return out.astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,            # (B, n_q, S, D), S sharded over cp
    k: jnp.ndarray,            # (B, n_kv, S, D)
    v: jnp.ndarray,
    q_pos: jnp.ndarray,        # (B, S) global positions of the query tokens
    kv_pos: jnp.ndarray,       # (B, S) global positions of the kv tokens
    mesh,
    rules=None,
    scale: Optional[float] = None,
    window: Optional[int] = None,
) -> jnp.ndarray:
    """Causal (optionally sliding-window) GQA ring attention over the cp mesh axis."""
    cp_size = mesh.shape[AXIS_CP]
    n_rep = q.shape[1] // k.shape[1]
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if q.shape[2] % cp_size != 0:
        raise ValueError(f"seq {q.shape[2]} not divisible by cp={cp_size}")

    # shard_map needs exact divisibility; a batch that doesn't divide the dp axis
    # (e.g. batch-1 continuous-batching inserts) is replicated across dp instead —
    # redundant compute on the idle dp shards, never wrong
    batch_spec = logical_to_spec(("batch",), rules)[0]
    if batch_spec is not None:
        axes = (batch_spec,) if isinstance(batch_spec, str) else tuple(batch_spec)
        dp_size = 1
        for ax in axes:
            dp_size *= mesh.shape[ax]
        if q.shape[0] % dp_size != 0:
            rules = dict(rules) if rules else {}
            from ..parallel.sharding import DEFAULT_RULES

            rules = {**DEFAULT_RULES, **rules, "batch": None}
    q_spec = logical_to_spec(("batch", "heads", "seq", None), rules)
    kv_spec = logical_to_spec(("batch", "kv_heads", "seq", None), rules)
    pos_spec = logical_to_spec(("batch", "seq"), rules)
    from ..models.base import shard_map_compat

    fn = shard_map_compat(
        partial(_ring_local, cp_size=cp_size, scale=scale, n_rep=n_rep,
                window=window),
        mesh=mesh,
        in_specs=(q_spec, kv_spec, kv_spec, pos_spec, pos_spec),
        out_specs=q_spec,
    )
    return fn(q, k, v, q_pos, kv_pos)
