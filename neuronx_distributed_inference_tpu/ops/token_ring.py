"""On-device emitted-token ring buffer for the device-resident serving
megastep (the ``lax.while_loop`` inner loop, ISSUE-10 / ROADMAP open item 2).

≈ reference async output queue (`modules/async_execution.py:190-306`): the
reference's 2-deep async decode parks each step's output tensor host-side and
syncs one step late; here the per-inner-step tokens never leave the device —
the while_loop body pushes one ``(B,)`` token row per executed inner step into
a fixed ``(capacity, B)`` ring that rides the loop carry, and the host drains
the whole ring ONCE per megastep (the megastep's single sync), replaying its
commit rules over ``ring[:n_executed]``. TPU redesign notes:

- The ring is (capacity, B) rather than (B, capacity) so each push is one
  contiguous ``dynamic_update_index_in_dim`` row write (no strided scatter).
- Capacity is a trace-time static (the jitted megastep's ring shape); the
  executed-iteration count ``n`` is DYNAMIC — one executable serves every
  early-exit length, and the ring-full condition is one of the megastep's
  in-graph host-service exits (the host commits, i.e. "services", the ring
  and the next dispatch starts the cursor back at 0 — the wrap).
- Rows frozen in-graph (eos/budget stops) still push their pinned token,
  exactly like the scan-chunk path's ``toks`` output: the host replay
  discards post-stop tokens, so the two paths stay bit-identical.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax import lax

__all__ = ["init_ring", "push", "drain"]


def init_ring(capacity: int, batch: int) -> jnp.ndarray:
    """Fresh zeroed (capacity, B) int32 ring (trace-time: capacity static)."""
    return jnp.zeros((capacity, batch), jnp.int32)


def push(ring: jnp.ndarray, i, toks: jnp.ndarray) -> jnp.ndarray:
    """Write one inner step's per-row tokens at ring row ``i`` (traced int32
    cursor) — one contiguous row update inside the while_loop body."""
    return lax.dynamic_update_index_in_dim(ring, toks, i, axis=0)


def drain(ring_host, n: int) -> np.ndarray:
    """Host-side view of the committed prefix of a synced ring:
    (capacity, B) -> (B, n) in the (slots, steps) layout the runner's
    ``_commit`` replay consumes."""
    return np.asarray(ring_host)[:n].T
