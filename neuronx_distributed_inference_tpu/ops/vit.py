"""Shared ViT encoder for vision towers (CLIP / SigLIP / Janus / AIMv2 shapes).

One scanned pre-norm transformer parameterized by the few axes the tower
families actually differ on — norm type (LayerNorm vs RMSNorm), MLP kind
(plain GELU-variant vs silu-gated), activation, CLS token, embedding pre-norms,
optional per-head q/k LayerNorm — so llava (CLIP), gemma3-vision (SigLIP),
janus, and ovis2 (AIMv2) share a single implementation. Each family keeps its
own head/projector on the returned hidden states.

The patch conv runs as an unfold + matmul (stride == kernel == patch_size), so
``patch_w`` is the HF conv weight (H_vis, C, p, p) reshaped to (C*p*p, H_vis).

≈ reference: each contrib VLM re-implements its tower in torch
(`contrib/models/{llava-v1.5-7b,gemma3-vision,...}/src`); here the XLA scan
serves them all.
"""

from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp

from .norms import layer_norm, rms_norm

__all__ = ["ViTSpec", "vit_encode"]


@dataclass(frozen=True)
class ViTSpec:
    patch_size: int
    num_heads: int
    eps: float
    norm: str = "layer"          # "layer" (biased LayerNorm) | "rms"
    act: str = "gelu_tanh"       # "gelu_tanh" | "gelu" | "quick_gelu"
    mlp: str = "plain"           # "plain" (fc1 -> act -> fc2) | "gated_silu"
    attn_bias: bool = True       # biases on q/k/v/o projections
    patch_bias: bool = True      # bias on the patch conv
    cls_token: bool = False      # CLIP prepends a learned CLS row
    pre_ln: bool = False         # CLIP pre_layrnorm after embeddings
    embed_rms: bool = False      # AIMv2 RMSNorm on patch embeds before pos
    post_ln: bool = True         # final post-norm over the last hidden state
    qk_norm: bool = False        # per-head LayerNorm on q/k (janus option)


def _act(spec: ViTSpec, x):
    if spec.act == "quick_gelu":
        return x * jax.nn.sigmoid(1.702 * x)
    if spec.act == "gelu":
        return jax.nn.gelu(x, approximate=False)
    return jax.nn.gelu(x, approximate=True)          # tanh approximation


def _norm(spec: ViTSpec, x, w, b):
    if spec.norm == "rms":
        return rms_norm(x, w, spec.eps)
    return layer_norm(x, w, b, eps=spec.eps)


def vit_encode(vp: Dict[str, Any], pixel_values: jnp.ndarray,
               spec: ViTSpec) -> jnp.ndarray:
    """(N, C, H, W) -> (N, T(+1 if cls), H_vis) tower hidden states."""
    n, c, hh, ww = pixel_values.shape
    p = spec.patch_size
    gh, gw = hh // p, ww // p
    x = pixel_values.reshape(n, c, gh, p, gw, p)
    x = x.transpose(0, 2, 4, 1, 3, 5).reshape(n, gh * gw, -1)
    h = x @ vp["patch_w"]
    if spec.patch_bias:
        h = h + vp["patch_b"]
    if spec.embed_rms:
        h = rms_norm(h, vp["embed_norm"], spec.eps)
    if spec.cls_token:
        cls = jnp.broadcast_to(vp["cls"][None, None, :], (n, 1, h.shape[-1]))
        h = jnp.concatenate([cls, h], axis=1)
    h = h + vp["pos_embed"][None]
    if spec.pre_ln:
        h = _norm(spec, h, vp["ln_pre"], vp.get("ln_pre_b"))

    d = h.shape[-1] // spec.num_heads

    def layer(hh, lp):
        x = _norm(spec, hh, lp["ln1"], lp.get("ln1_b"))
        b, s, _ = x.shape

        def proj(wk, bk):
            y = x @ lp[wk]
            if spec.attn_bias:
                y = y + lp[bk]
            return y.reshape(b, s, spec.num_heads, d)

        q, k = proj("wq", "bq"), proj("wk", "bk")
        if spec.qk_norm:
            q = layer_norm(q, lp["q_norm"], lp["q_norm_b"], eps=spec.eps)
            k = layer_norm(k, lp["k_norm"], lp["k_norm_b"], eps=spec.eps)
        q = q.transpose(0, 2, 1, 3)
        k = k.transpose(0, 2, 1, 3)
        v = proj("wv", "bv").transpose(0, 2, 1, 3)
        from .attention import attend
        a = attend(q, k, v)                          # full bidirectional
        a = a.transpose(0, 2, 1, 3).reshape(b, s, -1)
        a = a @ lp["wo"]
        if spec.attn_bias:
            a = a + lp["bo"]
        hh = hh + a
        x = _norm(spec, hh, lp["ln2"], lp.get("ln2_b"))
        if spec.mlp == "gated_silu":
            m = (jax.nn.silu(x @ lp["wg"]) * (x @ lp["wu"])) @ lp["wd"]
        else:
            m = _act(spec, x @ lp["w1"] + lp["b1"]) @ lp["w2"] + lp["b2"]
        return hh + m, None

    h, _ = jax.lax.scan(layer, h, vp["layers"])
    if spec.post_ln:
        h = _norm(spec, h, vp["ln_post"], vp.get("ln_post_b"))
    return h
