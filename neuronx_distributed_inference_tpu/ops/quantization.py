"""Weight-only quantization (int8 / fp8) and fp8 KV-cache support.

≈ reference quantization plumbing: NxD `quantize` configs imported at
`models/model_wrapper.py:11-21`, quantized checkpoint generation
(`models/application_base.py:744-797`), quantized MLP kernels
(`models/llama/modeling_llama.py:626`), fp8 KV quantization (direct cast or static
scales, `modules/kvcache/kv_cache_manager.py` fp8 paths). TPU redesign:

- A quantized weight is a tiny pytree ``{"q": int8|fp8 (..., in, out), "s": f32
  (..., 1, out)}`` with **per-output-channel symmetric scales** over the contraction
  dim. Matmuls run as ``(x @ q.astype(x.dtype)) * s``: XLA fuses the dequant cast into
  the matmul's operand read, so the weight lives in HBM at 1 byte/element — decode is
  HBM-bandwidth-bound, so weight bytes are the decode speedup, exactly why the
  reference quantizes.
- KV fp8 is "direct cast" mode: the cache tensor dtype is float8_e4m3; writes cast in,
  reads cast back to the compute dtype before attention.

`quantize_params` walks a model param tree and converts the named projection weights;
everything else (norms, router, embeddings, biases, rope tables) stays high precision,
matching the reference's modules_to_not_convert behavior.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import jax
import jax.numpy as jnp

# params converted by default: every large projection matmul. All are stored in
# (..., in, out) layout so per-output-channel scales reduce over axis -2.
DEFAULT_QUANTIZED_PARAMS = (
    "wq", "wk", "wv", "wo", "wg", "wu", "wd",
    "shared_wg", "shared_wu", "shared_wd", "lm_head",
)

# params packed to int4 under weight_dtype="int4" (the rest of the quantized
# names stay int8). wk/wv are EXCLUDED: at their sizes the per-call fixed cost
# of the w4 Pallas matmul exceeds the halved DMA (see ops/w4.py); lm_head is
# excluded for accuracy (logits feed sampling directly) — both stay int8.
W4_DEFAULT_PARAMS = ("wq", "wo", "wg", "wu", "wd",
                     "shared_wg", "shared_wu", "shared_wd")

# stacked attention projections stored TRANSPOSED ((..., out, in) as "qT"):
# XLA chooses a transposed physical layout for these under the decode layer
# scan and then materializes an s8[1, in, out] copy of every per-layer slice
# (~0.75 ms/step at 32 layers, ROUND3_NOTES §3 / ROUND4_NOTES §9); storing
# them logically transposed makes the natural row-major layout THE layout the
# dots want, so the scan slice fuses straight into the matmul (the MLP stacks
# already behave this way untransposed).
TRANSPOSED_ATTENTION_PARAMS = ("wq", "wk", "wv", "wo")

_QMAX = {"int8": 127.0, "float8_e4m3": 448.0}

WEIGHT_DTYPES = ("int8", "float8_e4m3", "int4")


def is_quantized(w) -> bool:
    return (isinstance(w, dict) and ("q" in w or "qT" in w or "q4" in w)
            and "s" in w)


def quantize_tensor(w, weight_dtype: str = "int8") -> Dict[str, Any]:
    """Symmetric per-output-channel quantization, computed **on host in numpy** so a
    model larger than one device's HBM never materializes unsharded on a device
    (sharded device_put happens after conversion).

    ``w`` is (..., in, out); the scale reduces over the contraction dim (axis -2) so
    each output channel (and each stacked layer / expert) gets its own scale.
    """
    import ml_dtypes
    import numpy as np

    if weight_dtype == "int4":
        from .w4 import pack_int4

        return pack_int4(w)
    if weight_dtype not in _QMAX:
        raise ValueError(f"weight_dtype must be one of {sorted(WEIGHT_DTYPES)}")
    w32 = np.asarray(jax.device_get(w) if isinstance(w, jax.Array) else w,
                     dtype=np.float32)
    absmax = np.max(np.abs(w32), axis=-2, keepdims=True)
    scale = np.maximum(absmax / _QMAX[weight_dtype], 1e-12)
    if weight_dtype == "int8":
        q = np.clip(np.round(w32 / scale), -127, 127).astype(np.int8)
    else:
        q = (w32 / scale).astype(ml_dtypes.float8_e4m3fn)
    return {"q": q, "s": scale.astype(np.float32)}


def dequantize_tensor(qw: Dict[str, jnp.ndarray], dtype=jnp.float32) -> jnp.ndarray:
    """Dequantize back to the logical (..., in, out) orientation."""
    if "q4" in qw:
        from .w4 import dequant_w4

        return dequant_w4(qw, dtype)
    if "qT" in qw:
        w = jnp.swapaxes(qw["qT"].astype(jnp.float32), -1, -2)
        return (w * qw["s"]).astype(dtype)
    return (qw["q"].astype(jnp.float32) * qw["s"]).astype(dtype)


def transpose_attention_stacks(
    params: Dict[str, Any],
    names: Sequence[str] = TRANSPOSED_ATTENTION_PARAMS,
    group_keys: Optional[Sequence[str]] = None,
) -> Dict[str, Any]:
    """Convert the named quantized weights to the transposed {"qT","s"} form
    (see TRANSPOSED_ATTENTION_PARAMS). Already-transposed leaves pass through,
    so artifact reloads are idempotent. Host-side: the contiguous copy here IS
    the physical layout device_put uploads."""
    import numpy as np

    nameset = set(names)
    # shares quantize_params' group scoping so the two walks can never diverge
    groups = set(DEFAULT_QUANTIZED_GROUPS if group_keys is None else group_keys)

    def conv(w):
        if not (is_quantized(w) and "q" in w):
            return w
        return {"qT": np.ascontiguousarray(np.swapaxes(np.asarray(w["q"]),
                                                       -1, -2)),
                "s": w["s"]}

    def walk(node, in_group):
        if not isinstance(node, dict) or is_quantized(node):
            return node
        return {k: (conv(v) if in_group and k in nameset and is_quantized(v)
                    else walk(v, k in groups) if isinstance(v, dict) else v)
                for k, v in node.items()}

    return walk(params, True)


def qapply(x: jnp.ndarray, w, act_quant: bool = False) -> jnp.ndarray:
    """``x @ w`` for a dense or quantized weight (the model's single matmul hook).

    ``act_quant`` additionally quantizes the ACTIVATIONS dynamically (per-token
    symmetric int8) so the matmul runs int8 x int8 on the MXU — the TPU-native
    analog of the reference's `rmsnorm_quant` fp8 activation quantization
    (`models/config.py:511-515`): v5e has no fp8 matmul units, but its int8 MXU
    path doubles bf16 throughput, which is where compute-bound prefill gains.
    XLA fuses the quantize into the preceding norm/elementwise ops."""
    if not is_quantized(w):
        return x @ w
    if "q4" in w:
        # int4-packed: Pallas streaming matmul (single-device) or the XLA
        # dequant path (sharded meshes / CPU model tests) — see ops/w4.py
        from .w4 import w4_apply

        return w4_apply(x, w)
    if "qT" in w:
        # transposed storage (..., out, in): contract both operands' LAST axes
        wq = w["qT"]
        dims = (((x.ndim - 1,), (wq.ndim - 1,)), ((), ()))
        if act_quant and wq.dtype == jnp.int8:
            sx = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1,
                         keepdims=True) / 127.0
            sx = jnp.maximum(sx, 1e-8)
            xq = jnp.clip(jnp.round(x.astype(jnp.float32) / sx),
                          -127, 127).astype(jnp.int8)
            y = jax.lax.dot_general(xq, wq, dims,
                                    preferred_element_type=jnp.int32)
            return (y.astype(jnp.float32) * sx
                    * w["s"].reshape(-1)).astype(x.dtype)
        y = jax.lax.dot_general(x, wq.astype(x.dtype), dims)
        return y * w["s"].reshape(-1).astype(y.dtype)
    if act_quant and w["q"].dtype == jnp.int8:
        sx = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True) / 127.0
        sx = jnp.maximum(sx, 1e-8)
        xq = jnp.clip(jnp.round(x.astype(jnp.float32) / sx),
                      -127, 127).astype(jnp.int8)
        y = jax.lax.dot_general(
            xq, w["q"], (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        return (y.astype(jnp.float32) * sx
                * w["s"].reshape(-1)).astype(x.dtype)
    y = x @ w["q"].astype(x.dtype)
    return y * w["s"].reshape(-1).astype(y.dtype)


def qeinsum(spec: str, x: jnp.ndarray, w) -> jnp.ndarray:
    """``einsum(spec, x, w)`` for a dense or quantized weight.

    Supports the MoE patterns whose output ends with the weight's last (out) axis —
    the per-channel scale then broadcasts onto the result's trailing dim.
    """
    if not is_quantized(w):
        return jnp.einsum(spec, x, w)
    if "q4" in w:
        # MoE expert weights (dense all-experts patterns): route to the w4 MoE
        # kernel on single-device meshes, GSPMD dequant otherwise (see w4_apply)
        from .w4 import _slice_stacked_w4, dequant_w4, w4_moe_matmul_stacked

        if spec not in ("nh,ehi->eni", "enh,ehi->eni", "eni,eih->enh"):
            raise ValueError(f"int4 qeinsum supports the dense all-experts MoE "
                             f"patterns only, got {spec!r}")
        q4, sc = w["q4"], w["s"]
        li = w.get("layer")
        if q4.ndim == 3:               # non-stacked (E, in/2, out)
            q4 = q4[None]
            sc = sc[None] if sc.ndim == 3 else sc
            li = jnp.int32(0)
        elif li is None:
            raise ValueError("stacked MoE w4 leaf reached qeinsum without a "
                             "layer index — int4 expert weights must flow "
                             "through the layer scan (see _scan_layers)")
        if not w.get("use_kernel", True):
            wl = _slice_stacked_w4(
                q4, sc.reshape(q4.shape[0], q4.shape[1], 1, -1), li)
            return jnp.einsum(spec, x, dequant_w4(wl, x.dtype))
        interpret = jax.default_backend() == "cpu"
        y = w4_moe_matmul_stacked(x, q4,
                                  sc.reshape(q4.shape[0], q4.shape[1], 1, -1),
                                  li, per_expert_x=spec.startswith("e"),
                                  interpret=interpret)
        return y.astype(x.dtype)
    if "qT" in w:
        # transposed storage (..., out, in): swap the SPEC's last two weight
        # axes so the flag is layout-transparent for any family routing an
        # attention projection through qeinsum rather than qapply
        ins, out = spec.split("->")
        xs, ws = ins.split(",")
        ws = ws[:-2] + ws[-1] + ws[-2]
        y = jnp.einsum(f"{xs},{ws}->{out}", x, w["qT"].astype(x.dtype))
        return y * w["s"].astype(y.dtype)
    y = jnp.einsum(spec, x, w["q"].astype(x.dtype))
    out_scale = w["s"]                     # (..., 1, out); experts lead
    # result layout for "nh,ehi->eni" / "eni,eih->enh": (E, N, out) — scale is
    # (E, 1, out) which broadcasts directly
    return y * out_scale.astype(y.dtype)


# dict keys `quantize_params` descends into. Recursion is scoped so a future
# family nesting a same-named weight under an unrelated group (consumed with a
# plain matmul) is never silently converted; such a family extends this via the
# `group_keys` argument (or `quantized_param_names` for leaf names).
DEFAULT_QUANTIZED_GROUPS = ("layers", "dense", "moe")


def quantize_params(params: Dict[str, Any], weight_dtype: str = "int8",
                    names: Sequence[str] = DEFAULT_QUANTIZED_PARAMS,
                    group_keys: Sequence[str] = DEFAULT_QUANTIZED_GROUPS,
                    int4_names: Optional[Sequence[str]] = None,
                    ) -> Dict[str, Any]:
    """Convert the named weights of a model param tree: at the top level and inside
    the known group containers (``group_keys``, recursively) — covers the base
    layout (top level + ``layers``) as well as custom layouts (DeepSeek-MLA /
    Llama4 ``dense``/``moe`` groups) without touching unrelated subtrees.

    ``weight_dtype="int4"`` packs ``int4_names`` (default W4_DEFAULT_PARAMS)
    to {"q4","s"} and the REMAINING names to int8 — the small projections
    aren't worth a w4 kernel call (see W4_DEFAULT_PARAMS note).

    Leaves that are ALREADY in the quantized {"q","s"} layout pass through
    untouched, so pre-quantized (or partially pre-quantized) checkpoints load
    correctly — with ONE exception: under ``weight_dtype="int4"`` a
    pre-quantized int8 leaf whose name is in ``int4_names`` is REPACKED to the
    {"q4","s"} layout (ops/w4.repack_int8_to_int4, no float intermediate), so
    an int8 checkpoint loaded with an int4 config actually serves int4 instead
    of silently staying on the int8 path. fp8 pre-quantized payloads cannot be
    repacked losslessly and pass through with a warning."""
    import logging

    nameset = set(names)
    groups = set(group_keys)
    if weight_dtype == "int4":
        w4set = nameset & set(W4_DEFAULT_PARAMS if int4_names is None
                              else int4_names)
    else:
        w4set = set()

    def conv(k, v):
        return quantize_tensor(v, "int4" if k in w4set else
                               ("int8" if w4set or weight_dtype == "int4"
                                else weight_dtype))

    def reconv(k, v):
        """Already-quantized leaf named for int4: repack int8 payloads."""
        import numpy as np

        from .w4 import repack_int8_to_int4

        if "q4" in v:
            return v                        # already the target layout
        payload = v.get("q", v.get("qT"))
        if np.asarray(payload).dtype != np.int8:
            logging.getLogger("tpu-inference").warning(
                "weight_dtype='int4': pre-quantized %s leaf %r cannot be "
                "repacked to int4 (only int8 payloads can); serving it as-is",
                np.asarray(payload).dtype, k)
            return v
        if "qT" in v:
            # transposed int8 storage (..., out, in): restore the logical
            # orientation first — the q4 layout packs the contraction dim
            return repack_int8_to_int4(
                {"q": np.ascontiguousarray(
                    np.swapaxes(np.asarray(v["qT"]), -1, -2)), "s": v["s"]})
        return repack_int8_to_int4(v)

    def walk(node, in_group):
        if is_quantized(node):
            return node
        if isinstance(node, dict):
            return {k: (conv(k, v)
                        if in_group and k in nameset and not is_quantized(v)
                        and not isinstance(v, dict)
                        else reconv(k, v)
                        if in_group and k in w4set and is_quantized(v)
                        else walk(v, k in groups)
                        if isinstance(v, dict) else v)
                    for k, v in node.items()}
        return node

    # top level counts as a group (base layout keeps lm_head there)
    return walk(params, True)


# OCP MXFP4 (e2m1) code points: 4-bit index -> value. Sign bit high, then 2-bit
# exponent, 1-bit mantissa (≈ reference gpt_oss MXFP4 layout transform,
# `models/gpt_oss/` 767 LoC; here a host-side numpy dequant at ingest).
_MXFP4_VALUES = (0.0, 0.5, 1.0, 1.5, 2.0, 3.0, 4.0, 6.0,
                 -0.0, -0.5, -1.0, -1.5, -2.0, -3.0, -4.0, -6.0)


def dequant_mxfp4(blocks, scales):
    """Dequantize an OCP MXFP4 tensor on host.

    ``blocks``: uint8 (..., G, B/2) — each byte packs two fp4 values, low nibble
    first; ``scales``: uint8 (..., G) — shared e8m0 exponent per 32-value block
    (value = 2^(scale-127)). Returns float32 (..., G*B).
    """
    import numpy as np

    blocks = np.asarray(blocks, dtype=np.uint8)
    scales = np.asarray(scales, dtype=np.uint8)
    lut = np.asarray(_MXFP4_VALUES, dtype=np.float32)
    lo = lut[blocks & 0x0F]
    hi = lut[blocks >> 4]
    vals = np.stack([lo, hi], axis=-1).reshape(blocks.shape[:-1] + (-1,))
    exp = np.ldexp(np.float32(1.0), scales.astype(np.int32) - 127)
    return (vals * exp[..., None]).reshape(blocks.shape[:-2] + (-1,))


def quantized_logical_axes(logical: Dict[str, Any], names: Sequence[str],
                           group_keys: Sequence[str] = DEFAULT_QUANTIZED_GROUPS,
                           transposed_names: Sequence[str] = (),
                           int4_names: Sequence[str] = (),
                           ) -> Dict[str, Any]:
    """Transform a logical-axes tree to match a quantized param tree (scoped to the
    same group containers as quantize_params): each quantized leaf's axes apply to
    ``q``; the scale keeps the output axis, contraction replaced by None.
    ``transposed_names`` get the {"qT","s"} form: the payload's last two axes
    swap, the scale keeps the ORIGINAL output axis. ``int4_names`` get the
    {"q4","s"} form: the packed payload keeps the SAME axis names. NOTE the
    shipped packing is HALF-SPLIT (ops/w4.py: byte row i pairs logical rows i
    and i + in/2, lo nibble stored biased), so a packed row is NOT a
    self-contained pair of adjacent logical rows — sharding the packed
    contraction axis would split each byte's two logical rows across shards.
    That is safe ONLY because sharded meshes never run the Pallas kernel:
    w4_apply routes multi-device meshes through the GSPMD dequant path
    (`use_kernel=False`), where the dequantized (in, out) weight is a plain
    dot GSPMD repartitions correctly regardless of the byte layout. A future
    shard_map w4 kernel must shard the OUTPUT axis (or unpack before
    resharding), never the packed contraction axis."""
    nameset = set(names)
    tset = set(transposed_names)
    w4set = set(int4_names)
    groups = set(group_keys)

    def _q_axes(axes, transposed, w4):
        s_axes = tuple(list(axes[:-2]) + [None, axes[-1]])
        if w4:
            return {"q4": tuple(axes), "s": s_axes}
        if transposed:
            qt = tuple(list(axes[:-2]) + [axes[-1], axes[-2]])
            return {"qT": qt, "s": s_axes}
        return {"q": tuple(axes), "s": s_axes}

    def walk(node, in_group):
        if isinstance(node, dict):
            return {k: (_q_axes(v, k in tset and k not in w4set, k in w4set)
                        if in_group and k in nameset and not isinstance(v, dict)
                        else walk(v, k in groups) if isinstance(v, dict) else v)
                    for k, v in node.items()}
        return node

    return walk(logical, True)
