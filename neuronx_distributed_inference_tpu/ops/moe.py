"""Mixture-of-Experts block: top-k router + expert MLPs with expert-parallel sharding.

≈ reference `modules/moe_v2.py` (`initialize_moe_module` :23-135: NxD `RouterTopK` +
`ExpertMLPsV2`) and the decode-time all-experts kernel
(`_pre_prod_kernels.moe_token_gen`, used via `experimental/functional/moe/tokengen_moe`).

TPU design: experts are a leading dim on stacked weights (E, H, I); the block computes
**all experts densely** and combines with the sparse router gates:

- decode (few tokens): dense all-experts is the fast path on the MXU — exactly the shape
  of the reference's `moe_token_gen_all_experts_kernel`; gathering per-expert token
  subsets would serialize on dynamic shapes XLA can't tile.
- prefill: dense all-experts costs E/top_k extra MLP FLOPs but keeps every matmul large,
  static, and EP-shardable. A capacity-based dispatch/combine einsum (token dropping,
  lower FLOPs) can be added behind MoEArgs later without touching callers.

Expert parallelism: the ``experts`` logical axis shards E over the mesh's ``ep`` axis
(`parallel/sharding.py` DEFAULT_RULES); the final gate-weighted combine contracts over
E, so GSPMD inserts the EP all-reduce exactly where the reference places its MoE
dispatch collectives (`ep_dispatch_cc_option`, `models/config.py:602`).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from ..parallel.sharding import constrain
from .quantization import qapply, qeinsum


@dataclass(frozen=True)
class MoEArgs:
    """Static MoE architecture description (hashable, nested in ModelArchArgs)."""

    num_experts: int
    experts_per_tok: int
    norm_topk_prob: bool = True          # renormalize top-k gates to sum to 1
    # DBRX-style p-norm renormalization of the top-k gates (HF
    # moe_normalize_expert_weights); overrides norm_topk_prob when set. p=1 over the
    # positive softmax weights equals sum renormalization.
    norm_topk_p: Optional[float] = None
    # qwen-style shared expert running densely alongside the routed experts, with a
    # sigmoid gate projected from the hidden state (0 = disabled)
    shared_expert_intermediate_size: int = 0
    # routing order: "softmax_topk" (Mixtral/Qwen: softmax over all experts, then
    # top-k), "topk_softmax" (gpt-oss: top-k of raw logits, softmax over the k),
    # "sigmoid_group" (DeepSeek-V3: sigmoid scores + e_score_correction_bias for
    # *selection only*, group-limited top-k, gates from the raw sigmoid scores), or
    # "topk_sigmoid" (Llama4: top-k of logits, sigmoid of the selected values)
    router_mode: str = "softmax_topk"
    # Llama4 scales the expert *input* by the gate (x·g into the expert MLP) instead
    # of weighting the expert output
    scale_expert_input: bool = False
    # DeepSeek group-limited routing: experts partitioned into n_group groups; the
    # topk_group best groups (by sum of each group's top-2 biased scores) stay eligible
    n_group: int = 1
    topk_group: int = 1
    score_correction_bias: bool = False  # learned selection bias (router_cb param)
    routed_scaling_factor: float = 1.0   # final gate multiplier (DeepSeek)
    # qwen shared expert is sigmoid-gated from the hidden state; DeepSeek's shared
    # experts are an ungated parallel MLP
    shared_expert_gated: bool = True
    # PhiMoE sparsemixer routing jitter band (router_mode="sparsemixer"): each
    # pick's weight is the softmax over experts within 2*jitter of the pick
    router_jitter: float = 0.01
    router_bias: bool = False            # router logits get a learned bias (gpt-oss)
    expert_bias: bool = False            # expert MLPs have biases (gpt-oss)
    # gpt-oss clamped glu: gate/up clipped at ±limit, act = gate·σ(α·gate), out =
    # (up+1)·act — replaces the standard activation(gate)·up when set
    swiglu_limit: Optional[float] = None
    swiglu_alpha: float = 1.702


def route(router_w: jnp.ndarray, x: jnp.ndarray, moe: MoEArgs,
          router_b: Optional[jnp.ndarray] = None,
          router_cb: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Top-k routing gates.

    x: (N, H) tokens; router_w: (H, E). Returns dense gates (N, E) float32 with
    exactly top-k nonzeros per row. ``softmax_topk`` matches HF Mixtral/Qwen3-MoE
    (softmax over all experts, top-k, optional renorm); ``topk_softmax`` matches HF
    gpt-oss (top-k of logits, softmax over the selected k); ``sigmoid_group`` matches
    HF DeepSeek-V3 (`DeepseekV3TopkRouter`: sigmoid scores, group-limited selection
    with the correction bias ``router_cb`` applied to selection only, gates taken from
    the *unbiased* scores, scaled by ``routed_scaling_factor``).
    """
    logits = x.astype(jnp.float32) @ router_w.astype(jnp.float32)   # (N, E)
    if router_b is not None:
        logits = logits + router_b.astype(jnp.float32)
    if moe.router_mode == "sigmoid_group":
        n, e = logits.shape
        scores = jax.nn.sigmoid(logits)                             # (N, E)
        choice = scores
        if router_cb is not None:
            choice = choice + router_cb.astype(jnp.float32)
        group_sz = e // moe.n_group
        grouped = choice.reshape(n, moe.n_group, group_sz)
        group_scores = jnp.sum(jax.lax.top_k(grouped, 2)[0], axis=-1)   # (N, G)
        _, gidx = jax.lax.top_k(group_scores, moe.topk_group)
        gmask = jnp.sum(jax.nn.one_hot(gidx, moe.n_group, dtype=jnp.float32),
                        axis=1)                                      # (N, G)
        emask = jnp.repeat(gmask, group_sz, axis=-1)                 # (N, E)
        masked_choice = jnp.where(emask > 0, choice, 0.0)
        _, top_idx = jax.lax.top_k(masked_choice, moe.experts_per_tok)
        top_vals = jnp.take_along_axis(scores, top_idx, axis=-1)     # unbiased scores
        if moe.norm_topk_prob:
            top_vals = top_vals / (jnp.sum(top_vals, axis=-1, keepdims=True) + 1e-20)
        top_vals = top_vals * moe.routed_scaling_factor
    elif moe.router_mode == "sparsemixer":
        # PhiMoE sparsemixer, inference path (HF `modeling_phimoe.sparsemixer`,
        # training=False): two sequential argmax picks; each pick's weight is the
        # softmax over the experts inside the 2*jitter threshold band, and the
        # second pick runs on the scores with the first expert masked out. The
        # two weights are NOT renormalized against each other.
        if moe.experts_per_tok != 2:
            raise ValueError("sparsemixer routing requires experts_per_tok == 2")
        jitter = 2.0 * moe.router_jitter

        def _pick(cur):
            m = jnp.max(cur, axis=-1, keepdims=True)
            factor = jnp.maximum(jnp.abs(logits), m)    # |original| clamped at max
            band_mask = ((m - cur) / factor) > jitter
            gated = jnp.where(band_mask, -jnp.inf, cur)
            sel = jnp.argmax(cur, axis=-1)
            w = jnp.take_along_axis(jax.nn.softmax(gated, axis=-1),
                                    sel[:, None], axis=1)[:, 0]
            return sel, w

        sel1, w1 = _pick(logits)
        masked = jnp.where(jax.nn.one_hot(sel1, moe.num_experts, dtype=bool),
                           -jnp.inf, logits)
        # HF quirk: the second threshold band compares the masked max against the
        # ORIGINAL scores, then applies the mask to the masked scores
        m2 = jnp.max(masked, axis=-1, keepdims=True)
        factor2 = jnp.maximum(jnp.abs(logits), m2)
        band2 = ((m2 - logits) / factor2) > jitter
        gated2 = jnp.where(band2, -jnp.inf, masked)
        sel2 = jnp.argmax(masked, axis=-1)
        w2 = jnp.take_along_axis(jax.nn.softmax(gated2, axis=-1),
                                 sel2[:, None], axis=1)[:, 0]
        top_idx = jnp.stack([sel1, sel2], axis=-1)
        top_vals = jnp.stack([w1, w2], axis=-1)
    elif moe.router_mode == "topk_sigmoid":
        top_vals, top_idx = jax.lax.top_k(logits, moe.experts_per_tok)
        top_vals = jax.nn.sigmoid(top_vals)
    elif moe.router_mode == "topk_softmax":
        top_vals, top_idx = jax.lax.top_k(logits, moe.experts_per_tok)
        top_vals = jax.nn.softmax(top_vals, axis=-1)
    elif moe.router_mode == "softmax_topk":
        probs = jax.nn.softmax(logits, axis=-1)
        top_vals, top_idx = jax.lax.top_k(probs, moe.experts_per_tok)   # (N, k)
        if moe.norm_topk_p is not None:
            scale = jnp.sum(jnp.abs(top_vals) ** moe.norm_topk_p,
                            axis=-1, keepdims=True) ** (1.0 / moe.norm_topk_p)
            top_vals = top_vals / scale
        elif moe.norm_topk_prob:
            top_vals = top_vals / jnp.sum(top_vals, axis=-1, keepdims=True)
    else:
        raise ValueError(f"unknown router_mode {moe.router_mode!r}")
    onehot = jax.nn.one_hot(top_idx, moe.num_experts, dtype=jnp.float32)  # (N, k, E)
    return jnp.einsum("nk,nke->ne", top_vals, onehot)


def moe_block(lp, args, hn: jnp.ndarray, mesh, rules,
              activation, decode: bool = False) -> jnp.ndarray:
    """(B, S, H) -> (B, S, H) through the MoE FFN.

    ``lp`` carries this layer's stacked expert weights: ``router`` (H, E), ``wg``/``wu``
    (E, H, I), ``wd`` (E, I, H), plus optional shared-expert weights.
    """
    moe: MoEArgs = args.moe
    # decode graphs constrain expert activations to the decode_* MoE axes, which
    # hybrid sharding may remap (identical to prefill by default)
    e_ax = "decode_experts" if decode else "experts"
    m_ax = "decode_expert_mlp" if decode else "expert_mlp"
    if moe.scale_expert_input and moe.expert_bias:
        # unselected experts see zero input but nonzero bias; the unweighted sum
        # would add bias-derived garbage from every expert
        raise ValueError("scale_expert_input requires bias-free expert MLPs")
    b, s, h = hn.shape
    x = hn.reshape(b * s, h)
    gates = route(lp["router"], x, moe, lp.get("router_b"),
                  lp.get("router_cb"))                              # (N, E) fp32

    # dense all-experts MLP: (E, N, I) intermediates, EP-sharded on E, TP on I
    if moe.scale_expert_input:
        # Llama4: expert input pre-scaled by its gate (unselected experts see zeros,
        # which the bias-free glu maps back to zero); combine is then an unweighted sum
        xe = gates.astype(x.dtype).T[:, :, None] * x[None, :, :]    # (E, N, H)
        xe = constrain(xe, (e_ax, "batch", None), rules, mesh=mesh)
        gate_proj = qeinsum("enh,ehi->eni", xe, lp["wg"])
        up_proj = qeinsum("enh,ehi->eni", xe, lp["wu"])
    else:
        gate_proj = qeinsum("nh,ehi->eni", x, lp["wg"])
        up_proj = qeinsum("nh,ehi->eni", x, lp["wu"])
    if moe.expert_bias:
        gate_proj = gate_proj + lp["bg"][:, None, :]
        up_proj = up_proj + lp["bu"][:, None, :]
    if moe.swiglu_limit is not None:
        # gpt-oss clamped glu (`GptOssExperts.forward`): clamp, gate·σ(α·gate), (up+1)·
        lim = jnp.asarray(moe.swiglu_limit, gate_proj.dtype)
        gate_proj = jnp.minimum(gate_proj, lim)
        up_proj = jnp.clip(up_proj, -lim, lim)
        glu = gate_proj * jax.nn.sigmoid(moe.swiglu_alpha * gate_proj)
        inter = (up_proj + 1.0) * glu
    else:
        inter = activation(gate_proj) * up_proj
    inter = constrain(inter, (e_ax, None, m_ax), rules, mesh=mesh)
    per_expert = qeinsum("eni,eih->enh", inter, lp["wd"])           # (E, N, H)
    if moe.expert_bias:
        per_expert = per_expert + lp["bd"][:, None, :]
    if moe.scale_expert_input:
        out = jnp.sum(per_expert, axis=0)                           # sum over E: EP psum
    else:
        out = jnp.einsum("enh,ne->nh", per_expert,
                         gates.astype(per_expert.dtype))            # sum over E: EP psum
    out = constrain(out, ("batch", None), rules, mesh=mesh)

    if moe.shared_expert_intermediate_size:
        shared_inter = (activation(qapply(x, lp["shared_wg"]))
                        * qapply(x, lp["shared_wu"]))
        shared = qapply(shared_inter, lp["shared_wd"])
        if moe.shared_expert_gated:
            shared_gate = jax.nn.sigmoid(
                (x.astype(jnp.float32)
                 @ lp["shared_gate"].astype(jnp.float32)))           # (N, 1)
            shared = shared * shared_gate.astype(shared.dtype)
        out = out + shared

    return out.reshape(b, s, h).astype(hn.dtype)
