"""Mixture-of-Experts block: top-k router + expert MLPs with expert-parallel sharding.

≈ reference `modules/moe_v2.py` (`initialize_moe_module` :23-135: NxD `RouterTopK` +
`ExpertMLPsV2`) and the decode-time all-experts kernel
(`_pre_prod_kernels.moe_token_gen`, used via `experimental/functional/moe/tokengen_moe`).

TPU design: experts are a leading dim on stacked weights (E, H, I); the block computes
**all experts densely** and combines with the sparse router gates:

- decode (few tokens): dense all-experts is the fast path on the MXU — exactly the shape
  of the reference's `moe_token_gen_all_experts_kernel`; gathering per-expert token
  subsets would serialize on dynamic shapes XLA can't tile.
- prefill: dense all-experts costs E/top_k extra MLP FLOPs but keeps every matmul large,
  static, and EP-shardable. A capacity-based dispatch/combine einsum (token dropping,
  lower FLOPs) can be added behind MoEArgs later without touching callers.

Expert parallelism: the ``experts`` logical axis shards E over the mesh's ``ep`` axis
(`parallel/sharding.py` DEFAULT_RULES); the final gate-weighted combine contracts over
E, so GSPMD inserts the EP all-reduce exactly where the reference places its MoE
dispatch collectives (`ep_dispatch_cc_option`, `models/config.py:602`).

Decode fast paths (both trace-time selected, dense einsum kept as the reference
and fallback):

- **Grouped expert matmul** (`grouped_expert_matmul`): one Pallas kernel over the
  stacked (E, H, I) weights with a per-expert/per-I-tile grid and a gate-weighted
  f32 accumulator — the TPU analog of the reference's
  `moe_token_gen_all_experts_kernel`. Serves bf16 and the int8/fp8 (`{"q","s"}`)
  and int4 half-split (`{"q4","s"}`, ops/w4.py layout) quantized leaves with
  in-kernel dequant. ``TPUINF_MOE_GROUPED=0`` opts out (trace time).
- **EP ring dispatch/combine** (`parallel/overlap.expert_ring_moe`): on ep > 1
  meshes the GSPMD combine all-reduce is replaced by an explicit rotate-
  accumulate over the ep axis whose ppermutes hide behind the local expert
  matmuls (the PR 5 row_projection template), with the grouped kernel serving
  each shard's local experts. ``TPUINF_EP_OVERLAP=0`` falls back to GSPMD.
- **Pure-TP grouped combine** (`parallel/overlap.expert_tp_moe`): on ep == 1,
  tp > 1 meshes the shard_map wrapper runs the grouped kernel over each chip's
  tp column slice of the expert mlp dim and finishes with one tp psum —
  exactly the ring's finishing step without the ring, closing the gap where a
  trace-level pallas_call could not consume GSPMD-sharded leaves.
  ``TPUINF_MOE_TP_GROUPED=0`` falls back to GSPMD.
"""

from __future__ import annotations

import contextlib
import functools
import os
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..parallel.overlap import (expert_ring_moe, expert_tp_moe, moe_ep_phase,
                                moe_tp_phase)
from ..parallel.sharding import constrain
from .quantization import qapply, qeinsum


@dataclass(frozen=True)
class MoEArgs:
    """Static MoE architecture description (hashable, nested in ModelArchArgs)."""

    num_experts: int
    experts_per_tok: int
    norm_topk_prob: bool = True          # renormalize top-k gates to sum to 1
    # DBRX-style p-norm renormalization of the top-k gates (HF
    # moe_normalize_expert_weights); overrides norm_topk_prob when set. p=1 over the
    # positive softmax weights equals sum renormalization.
    norm_topk_p: Optional[float] = None
    # qwen-style shared expert running densely alongside the routed experts, with a
    # sigmoid gate projected from the hidden state (0 = disabled)
    shared_expert_intermediate_size: int = 0
    # routing order: "softmax_topk" (Mixtral/Qwen: softmax over all experts, then
    # top-k), "topk_softmax" (gpt-oss: top-k of raw logits, softmax over the k),
    # "sigmoid_group" (DeepSeek-V3: sigmoid scores + e_score_correction_bias for
    # *selection only*, group-limited top-k, gates from the raw sigmoid scores), or
    # "topk_sigmoid" (Llama4: top-k of logits, sigmoid of the selected values)
    router_mode: str = "softmax_topk"
    # Llama4 scales the expert *input* by the gate (x·g into the expert MLP) instead
    # of weighting the expert output
    scale_expert_input: bool = False
    # DeepSeek group-limited routing: experts partitioned into n_group groups; the
    # topk_group best groups (by sum of each group's top-2 biased scores) stay eligible
    n_group: int = 1
    topk_group: int = 1
    score_correction_bias: bool = False  # learned selection bias (router_cb param)
    routed_scaling_factor: float = 1.0   # final gate multiplier (DeepSeek)
    # qwen shared expert is sigmoid-gated from the hidden state; DeepSeek's shared
    # experts are an ungated parallel MLP
    shared_expert_gated: bool = True
    # PhiMoE sparsemixer routing jitter band (router_mode="sparsemixer"): each
    # pick's weight is the softmax over experts within 2*jitter of the pick
    router_jitter: float = 0.01
    router_bias: bool = False            # router logits get a learned bias (gpt-oss)
    expert_bias: bool = False            # expert MLPs have biases (gpt-oss)
    # gpt-oss clamped glu: gate/up clipped at ±limit, act = gate·σ(α·gate), out =
    # (up+1)·act — replaces the standard activation(gate)·up when set
    swiglu_limit: Optional[float] = None
    swiglu_alpha: float = 1.702

    def __post_init__(self):
        # fail at config build time, not as an opaque top_k/reshape trace error
        if self.num_experts < 1:
            raise ValueError(f"num_experts must be >= 1, got {self.num_experts}")
        if not 1 <= self.experts_per_tok <= self.num_experts:
            raise ValueError(
                f"experts_per_tok={self.experts_per_tok} must be in [1, "
                f"num_experts={self.num_experts}]: the router cannot select "
                f"more experts than exist")
        if self.n_group > 1 and self.num_experts % self.n_group:
            raise ValueError(
                f"num_experts={self.num_experts} must divide evenly into "
                f"n_group={self.n_group} routing groups")
        if self.topk_group > self.n_group:
            raise ValueError(
                f"topk_group={self.topk_group} cannot exceed "
                f"n_group={self.n_group}")


def route(router_w: jnp.ndarray, x: jnp.ndarray, moe: MoEArgs,
          router_b: Optional[jnp.ndarray] = None,
          router_cb: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Top-k routing gates.

    x: (N, H) tokens; router_w: (H, E). Returns dense gates (N, E) float32 with
    exactly top-k nonzeros per row. ``softmax_topk`` matches HF Mixtral/Qwen3-MoE
    (softmax over all experts, top-k, optional renorm); ``topk_softmax`` matches HF
    gpt-oss (top-k of logits, softmax over the selected k); ``sigmoid_group`` matches
    HF DeepSeek-V3 (`DeepseekV3TopkRouter`: sigmoid scores, group-limited selection
    with the correction bias ``router_cb`` applied to selection only, gates taken from
    the *unbiased* scores, scaled by ``routed_scaling_factor``).
    """
    logits = x.astype(jnp.float32) @ router_w.astype(jnp.float32)   # (N, E)
    if router_b is not None:
        logits = logits + router_b.astype(jnp.float32)
    if moe.router_mode == "sigmoid_group":
        n, e = logits.shape
        scores = jax.nn.sigmoid(logits)                             # (N, E)
        choice = scores
        if router_cb is not None:
            choice = choice + router_cb.astype(jnp.float32)
        group_sz = e // moe.n_group
        grouped = choice.reshape(n, moe.n_group, group_sz)
        group_scores = jnp.sum(jax.lax.top_k(grouped, 2)[0], axis=-1)   # (N, G)
        _, gidx = jax.lax.top_k(group_scores, moe.topk_group)
        gmask = jnp.sum(jax.nn.one_hot(gidx, moe.n_group, dtype=jnp.float32),
                        axis=1)                                      # (N, G)
        emask = jnp.repeat(gmask, group_sz, axis=-1)                 # (N, E)
        masked_choice = jnp.where(emask > 0, choice, 0.0)
        _, top_idx = jax.lax.top_k(masked_choice, moe.experts_per_tok)
        top_vals = jnp.take_along_axis(scores, top_idx, axis=-1)     # unbiased scores
        if moe.norm_topk_prob:
            top_vals = top_vals / (jnp.sum(top_vals, axis=-1, keepdims=True) + 1e-20)
        top_vals = top_vals * moe.routed_scaling_factor
    elif moe.router_mode == "sparsemixer":
        # PhiMoE sparsemixer, inference path (HF `modeling_phimoe.sparsemixer`,
        # training=False): two sequential argmax picks; each pick's weight is the
        # softmax over the experts inside the 2*jitter threshold band, and the
        # second pick runs on the scores with the first expert masked out. The
        # two weights are NOT renormalized against each other.
        if moe.experts_per_tok != 2:
            raise ValueError("sparsemixer routing requires experts_per_tok == 2")
        jitter = 2.0 * moe.router_jitter

        def _pick(cur):
            m = jnp.max(cur, axis=-1, keepdims=True)
            factor = jnp.maximum(jnp.abs(logits), m)    # |original| clamped at max
            band_mask = ((m - cur) / factor) > jitter
            gated = jnp.where(band_mask, -jnp.inf, cur)
            sel = jnp.argmax(cur, axis=-1)
            w = jnp.take_along_axis(jax.nn.softmax(gated, axis=-1),
                                    sel[:, None], axis=1)[:, 0]
            return sel, w

        sel1, w1 = _pick(logits)
        masked = jnp.where(jax.nn.one_hot(sel1, moe.num_experts, dtype=bool),
                           -jnp.inf, logits)
        # HF quirk: the second threshold band compares the masked max against the
        # ORIGINAL scores, then applies the mask to the masked scores
        m2 = jnp.max(masked, axis=-1, keepdims=True)
        factor2 = jnp.maximum(jnp.abs(logits), m2)
        band2 = ((m2 - logits) / factor2) > jitter
        gated2 = jnp.where(band2, -jnp.inf, masked)
        sel2 = jnp.argmax(masked, axis=-1)
        w2 = jnp.take_along_axis(jax.nn.softmax(gated2, axis=-1),
                                 sel2[:, None], axis=1)[:, 0]
        top_idx = jnp.stack([sel1, sel2], axis=-1)
        top_vals = jnp.stack([w1, w2], axis=-1)
    elif moe.router_mode == "topk_sigmoid":
        top_vals, top_idx = jax.lax.top_k(logits, moe.experts_per_tok)
        top_vals = jax.nn.sigmoid(top_vals)
    elif moe.router_mode == "topk_softmax":
        top_vals, top_idx = jax.lax.top_k(logits, moe.experts_per_tok)
        top_vals = jax.nn.softmax(top_vals, axis=-1)
    elif moe.router_mode == "softmax_topk":
        probs = jax.nn.softmax(logits, axis=-1)
        top_vals, top_idx = jax.lax.top_k(probs, moe.experts_per_tok)   # (N, k)
        if moe.norm_topk_p is not None:
            scale = jnp.sum(jnp.abs(top_vals) ** moe.norm_topk_p,
                            axis=-1, keepdims=True) ** (1.0 / moe.norm_topk_p)
            top_vals = top_vals / scale
        elif moe.norm_topk_prob:
            top_vals = top_vals / jnp.sum(top_vals, axis=-1, keepdims=True)
    else:
        raise ValueError(f"unknown router_mode {moe.router_mode!r}")
    onehot = jax.nn.one_hot(top_idx, moe.num_experts, dtype=jnp.float32)  # (N, k, E)
    return jnp.einsum("nk,nke->ne", top_vals, onehot)


# ---------------------------------------------------------------------------
# Decode fast path: fused grouped expert matmul (Pallas)
# ---------------------------------------------------------------------------

# trace-time counters per routed-MoE implementation actually lowered into a
# graph since the last reset — bench.py's honesty gate (a "dense_decode" tick
# during the measured MoE leg means the fast path silently declined)
_TRACE_STATS = {"grouped": 0, "ep_ring": 0, "tp_grouped": 0,
                "dense_decode": 0}


def grouped_trace_stats() -> dict:
    """Snapshot of which MoE decode implementations have been TRACED (not run)."""
    return dict(_TRACE_STATS)


def reset_grouped_trace_stats() -> None:
    for k in _TRACE_STATS:
        _TRACE_STATS[k] = 0


@contextlib.contextmanager
def trace_stats_scope():
    """Isolate the trace counters around one measured region.

    Yields a dict that on exit holds the counter DELTAS ticked inside the
    ``with`` body — the bench honesty gate reads this instead of a global
    reset/read pair, so pre-existing counter state can't leak in and a region
    that traced NO MoE graph at all (e.g. a warm executable silently reused)
    reports all-zero deltas, which the gate refuses loudly rather than
    mistaking stale global counts for fast-path evidence."""
    before = dict(_TRACE_STATS)
    delta = dict.fromkeys(_TRACE_STATS, 0)
    try:
        yield delta
    finally:
        for k in _TRACE_STATS:
            delta[k] = _TRACE_STATS[k] - before[k]


def grouped_moe_enabled() -> bool:
    """TPUINF_MOE_GROUPED=0 keeps decode on the dense all-experts einsums
    (read at TRACE time, like TPUINF_TP_OVERLAP)."""
    return os.environ.get("TPUINF_MOE_GROUPED", "1") != "0"


def _glu(gate_proj, up_proj, moe: MoEArgs, activation):
    """The expert glu nonlinearity, shared by the dense reference path, the
    grouped kernel, and the EP-ring local compute so all three are the same
    math (gpt-oss clamped variant included)."""
    if moe.swiglu_limit is not None:
        # gpt-oss clamped glu (`GptOssExperts.forward`): clamp, gate·σ(α·gate), (up+1)·
        lim = jnp.asarray(moe.swiglu_limit, gate_proj.dtype)
        gate_proj = jnp.minimum(gate_proj, lim)
        up_proj = jnp.clip(up_proj, -lim, lim)
        glu = gate_proj * jax.nn.sigmoid(moe.swiglu_alpha * gate_proj)
        return (up_proj + 1.0) * glu
    return activation(gate_proj) * up_proj


def _grouped_mode(w):
    """Classify one expert-weight leaf for the grouped kernel.

    Returns ``(mode, payload4d, scale4d, layer_idx)`` with the payload
    normalized to a stacked ``(L_or_1, E, in[, /2], out)`` array, or None when
    the leaf cannot be served in-kernel (transposed int8 storage, GSPMD-dequant
    int4 on sharded meshes, stacked int4 outside the layer scan).
    """
    if not isinstance(w, dict):
        if getattr(w, "ndim", 0) != 3:
            return None
        return ("plain", w[None], None, None)
    if "qT" in w:
        return None
    if "q4" in w:
        # half-split packed int4 (ops/w4.py): byte row i pairs logical rows i
        # and i + in/2 — dequants contiguously in VMEM, but the *contraction*
        # dim of a packed operand cannot be block-tiled (the two logical rows
        # of a byte land in different tiles); the builder forces a full-I down
        # projection block for this mode.
        if not w.get("use_kernel", True):
            return None
        q4, li = w["q4"], w.get("layer")
        if q4.ndim == 3:
            q4, li = q4[None], None
        elif li is None:
            return None
        sc = jnp.asarray(w["s"], jnp.float32).reshape(
            q4.shape[0], q4.shape[1], 1, -1)
        return ("q4", q4, sc, li)
    if "q" in w:
        q = w["q"]
        if q.ndim != 3:
            return None
        sc = jnp.asarray(w["s"], jnp.float32).reshape(1, q.shape[0], 1, -1)
        return ("q", q[None], sc, None)
    return None


def _grouped_kernel(li_ref, *refs, modes, has_bias, moe, activation):
    """One (expert, I-tile) cell of the fused decode MoE: gate/up matmul on the
    tile, glu, down matmul back to (N, H), gate-weighted accumulate into the
    f32 scratch; the last cell flushes the accumulator to the output."""
    del li_ref  # consumed by the BlockSpec index maps only
    x_ref, g_ref = refs[0], refs[1]
    pos = 2
    projs = []
    for m in modes:
        if m == "plain":
            projs.append((m, refs[pos], None))
            pos += 1
        else:
            projs.append((m, refs[pos], refs[pos + 1]))
            pos += 2
    if has_bias:
        bg_ref, bu_ref, bd_ref = refs[pos:pos + 3]
        pos += 3
    o_ref, acc_ref = refs[-2], refs[-1]
    ei, ti = pl.program_id(0), pl.program_id(1)
    ne, nt = pl.num_programs(0), pl.num_programs(1)

    @pl.when(jnp.logical_and(ei == 0, ti == 0))
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def dot(xop, m, w_ref, s_ref):
        if m == "q4":
            p = w_ref[0, 0].astype(jnp.int32)
            lo = (p & 15) - 8                               # biased low nibble
            hi = jax.lax.shift_right_arithmetic(p, 4)       # sign-extending
            w = jnp.concatenate([lo, hi], axis=0).astype(jnp.float32)
        else:
            w = w_ref[0, 0].astype(jnp.float32)
        y = jax.lax.dot(xop.astype(jnp.float32), w,
                        preferred_element_type=jnp.float32)
        if s_ref is not None:
            y = y * s_ref[0, 0, 0]                          # per-out-channel
        return y

    gp = dot(x_ref[...], *projs[0])
    up = dot(x_ref[...], *projs[1])
    if has_bias:
        gp = gp + bg_ref[0].astype(jnp.float32)
        up = up + bu_ref[0].astype(jnp.float32)
    inter = _glu(gp, up, moe, activation)
    part = dot(inter.astype(x_ref.dtype), *projs[2])        # (N, H) partial
    g = g_ref[0].astype(jnp.float32)                        # (N,) this expert
    if has_bias:
        # the down bias contributes once per expert, not once per I-tile
        @pl.when(ti == 0)
        def _bd():
            acc_ref[...] += g[:, None] * bd_ref[0].astype(jnp.float32)

    acc_ref[...] += part * g[:, None]

    @pl.when(jnp.logical_and(ei == ne - 1, ti == nt - 1))
    def _emit():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


_VMEM_BUDGET = 12 * 2 ** 20     # leave headroom under the ~16MB/core arena


def grouped_expert_matmul(x, gates_t, wg, wu, wd, *, moe: MoEArgs, activation,
                          biases=None, out_dtype=None, interpret=None):
    """Fused all-experts decode MoE: one Pallas kernel over the stacked expert
    weights with gate-weighted f32 accumulation — the TPU analog of the
    reference's ``moe_token_gen_all_experts_kernel``.

    x: (N, H) tokens; gates_t: (E, N) f32 router gates (transposed so each
    expert grid cell streams a contiguous (1, N) block); wg/wu (E, H, I) and
    wd (E, I, H) leaves — plain arrays, int8/fp8 ``{"q","s"}``, or int4
    half-split ``{"q4","s"}`` payloads (dequantized in VMEM). ``biases`` is
    the optional (bg, bu, bd) tuple. Returns (N, H) in ``out_dtype`` (default
    x.dtype), or **None** when the operands are ineligible — the caller falls
    back to the dense einsum reference.

    The (E, H, I)-stacked weight walk with a per-group offset grid is also the
    shape of a batched multi-adapter LoRA matmul (adapters as the group dim) —
    ROADMAP item 5 grows from this kernel.
    """
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    cls = [_grouped_mode(w) for w in (wg, wu, wd)]
    if any(c is None for c in cls):
        return None
    modes = tuple(c[0] for c in cls)
    payloads = [c[1] for c in cls]
    scales = [c[2] for c in cls]
    li = next((c[3] for c in cls if c[3] is not None), None)

    n, h = x.shape
    e = payloads[0].shape[1]

    def indim(k):
        return payloads[k].shape[2] * (2 if modes[k] == "q4" else 1)

    inter_i = payloads[0].shape[3]
    if gates_t.shape != (e, n):
        return None
    if indim(0) != h or indim(1) != h or payloads[1].shape[3] != inter_i:
        return None
    if indim(2) != inter_i or payloads[2].shape[3] != h:
        return None
    if biases is not None and any(isinstance(b, dict) for b in biases):
        return None

    # I-tile width: the q4 down projection cannot tile its packed contraction
    # dim (see _grouped_mode), so it pins bi = I; otherwise prefer MXU-friendly
    # 128-multiples that fit the VMEM budget with double-buffered weight blocks
    esz = [p.dtype.itemsize for p in payloads]

    def vmem_bytes(bi):
        wgt = 2 * bi * (payloads[0].shape[2] * esz[0] + payloads[1].shape[2]
                        * esz[1])
        wdn = 2 * h * (payloads[2].shape[2] if modes[2] == "q4" else bi) * esz[2]
        act = n * h * (x.dtype.itemsize + 4 + 4)        # x + f32 acc + unpack slack
        return wgt + wdn + act + n * bi * 8             # gp/up f32 tiles

    if modes[2] == "q4":
        candidates = [inter_i]
    else:
        candidates = [c for c in (512, 256, 128) if inter_i % c == 0] + [inter_i]
    bi = next((c for c in candidates if vmem_bytes(c) <= _VMEM_BUDGET), None)
    if bi is None:
        return None
    if not interpret and (h % 128 or bi % 128):
        return None                     # compiled path wants lane-aligned tiles
    nt = inter_i // bi

    # pad N to the f32 sublane tile; padded rows carry zero gates so they only
    # produce zero rows that are sliced off below
    np_ = -(-n // 8) * 8
    xp = jnp.pad(x, ((0, np_ - n), (0, 0))) if np_ != n else x
    gtp = (jnp.pad(gates_t, ((0, 0), (0, np_ - n))) if np_ != n
           else gates_t).astype(jnp.float32)

    specs = [pl.BlockSpec((np_, h), lambda ei, ti, lidx: (0, 0)),
             pl.BlockSpec((1, np_), lambda ei, ti, lidx: (ei, 0))]
    inputs = [xp, gtp]
    for k, (m, p, s) in enumerate(zip(modes, payloads, scales)):
        stacked = p.shape[0] > 1
        if k < 2:
            blk = (1, 1, p.shape[2], bi)
            imap = (lambda ei, ti, lidx: (lidx[0], ei, 0, ti)) if stacked \
                else (lambda ei, ti, lidx: (0, ei, 0, ti))
        else:
            rows = p.shape[2] if m == "q4" else bi
            blk = (1, 1, rows, h)
            if m == "q4":
                imap = (lambda ei, ti, lidx: (lidx[0], ei, 0, 0)) if stacked \
                    else (lambda ei, ti, lidx: (0, ei, 0, 0))
            else:
                imap = (lambda ei, ti, lidx: (lidx[0], ei, ti, 0)) if stacked \
                    else (lambda ei, ti, lidx: (0, ei, ti, 0))
        specs.append(pl.BlockSpec(blk, imap))
        inputs.append(p)
        if s is not None:
            if k < 2:
                sblk = (1, 1, 1, bi)
                smap = (lambda ei, ti, lidx: (lidx[0], ei, 0, ti)) if stacked \
                    else (lambda ei, ti, lidx: (0, ei, 0, ti))
            else:
                sblk = (1, 1, 1, h)
                smap = (lambda ei, ti, lidx: (lidx[0], ei, 0, 0)) if stacked \
                    else (lambda ei, ti, lidx: (0, ei, 0, 0))
            specs.append(pl.BlockSpec(sblk, smap))
            inputs.append(s)
    has_bias = biases is not None
    if has_bias:
        bg, bu, bd = biases
        specs += [pl.BlockSpec((1, bi), lambda ei, ti, lidx: (ei, ti)),
                  pl.BlockSpec((1, bi), lambda ei, ti, lidx: (ei, ti)),
                  pl.BlockSpec((1, h), lambda ei, ti, lidx: (ei, 0))]
        inputs += [bg, bu, bd]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(e, nt),
        in_specs=specs,
        out_specs=pl.BlockSpec((np_, h), lambda ei, ti, lidx: (0, 0)),
        scratch_shapes=[pltpu.VMEM((np_, h), jnp.float32)],
    )
    kernel = functools.partial(_grouped_kernel, modes=modes, has_bias=has_bias,
                               moe=moe, activation=activation)
    li_arr = (li if li is not None else jnp.int32(0))
    y = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((np_, h), out_dtype or x.dtype),
        interpret=interpret,
    )(jnp.asarray(li_arr, jnp.int32).reshape(1), *inputs)
    return y[:n] if np_ != n else y


def moe_decode_grouped(x, gates, lp, moe: MoEArgs, activation,
                       out_dtype=None, interpret=None):
    """Grouped-kernel decode fast path from a layer's param dict: returns
    (N, H) or None when the leaves are ineligible (caller keeps the dense
    reference einsums)."""
    if moe.scale_expert_input:
        return None
    biases = (lp["bg"], lp["bu"], lp["bd"]) if moe.expert_bias else None
    return grouped_expert_matmul(
        x, gates.T, lp["wg"], lp["wu"], lp["wd"], moe=moe,
        activation=activation, biases=biases, out_dtype=out_dtype,
        interpret=interpret)


def _local_expert_combine(xc, gc, wl, *, moe: MoEArgs, activation):
    """Per-shard all-local-experts MLP + gate combine for one destination token
    tile of the EP ring: xc (n, H) tokens, gc (n, E_local) f32 gates, wl this
    shard's plain weight slices. Returns an (n, H) f32 partial — summed over
    the ring's experts by the caller (and over tp by its psum when the expert
    mlp dim is column-sharded)."""
    if grouped_moe_enabled():
        biases = ((wl["bg"], wl["bu"], wl["bd"]) if moe.expert_bias else None)
        y = grouped_expert_matmul(xc, gc.T, wl["wg"], wl["wu"], wl["wd"],
                                  moe=moe, activation=activation,
                                  biases=biases, out_dtype=jnp.float32)
        if y is not None:
            return y
    gp = jnp.einsum("nh,ehi->eni", xc, wl["wg"])
    up = jnp.einsum("nh,ehi->eni", xc, wl["wu"])
    if moe.expert_bias:
        gp = gp + wl["bg"][:, None, :]
        up = up + wl["bu"][:, None, :]
    inter = _glu(gp, up, moe, activation)
    pe = jnp.einsum("eni,eih->enh", inter, wl["wd"])
    if moe.expert_bias:
        pe = pe + wl["bd"][:, None, :]
    return jnp.einsum("enh,ne->nh", pe, gc).astype(jnp.float32)


def _ring_moe(x, gates, lp, moe: MoEArgs, activation, mesh, rules, e_ax, m_ax):
    """Overlap-scheduled EP dispatch/combine (parallel/overlap.expert_ring_moe)
    for the routed experts; None when the phase/leaves are ineligible."""
    names = ["wg", "wu", "wd"]
    waxes = {"wg": (e_ax, None, m_ax), "wu": (e_ax, None, m_ax),
             "wd": (e_ax, m_ax, None)}
    if moe.expert_bias:
        names += ["bg", "bu", "bd"]
        waxes.update(bg=(e_ax, m_ax), bu=(e_ax, m_ax), bd=(e_ax, None))
    weights = {k: lp[k] for k in names}
    if any(isinstance(w, dict) for w in weights.values()):
        return None                     # quantized leaves keep GSPMD dequant
    expert_fn = functools.partial(_local_expert_combine, moe=moe,
                                  activation=activation)
    # bd is tp-replicated (waxes (e_ax, None)) but added inside every tp
    # shard's expert_fn; tp_once keeps it to one shard so the finishing tp
    # psum counts the gate-weighted bias once, like the GSPMD reference
    return expert_ring_moe(x, gates, weights, waxes, mesh, rules,
                           e_ax, m_ax, expert_fn,
                           tp_once=("bd",) if moe.expert_bias else ())


def _tp_grouped_moe(x, gates, lp, moe: MoEArgs, activation, mesh, rules,
                    e_ax, m_ax):
    """Pure-TP grouped combine (parallel/overlap.expert_tp_moe) for the routed
    experts at ep == 1; None when the phase/leaves are ineligible."""
    names = ["wg", "wu", "wd"]
    waxes = {"wg": (e_ax, None, m_ax), "wu": (e_ax, None, m_ax),
             "wd": (e_ax, m_ax, None)}
    if moe.expert_bias:
        names += ["bg", "bu", "bd"]
        waxes.update(bg=(e_ax, m_ax), bu=(e_ax, m_ax), bd=(e_ax, None))
    weights = {k: lp[k] for k in names}
    if any(isinstance(w, dict) for w in weights.values()):
        return None                     # quantized leaves keep GSPMD dequant
    expert_fn = functools.partial(_local_expert_combine, moe=moe,
                                  activation=activation)
    # bd is tp-replicated (waxes (e_ax, None)) but added inside every tp
    # shard's expert_fn; tp_once keeps it to one shard so the finishing tp
    # psum counts the gate-weighted bias once, like the GSPMD reference
    return expert_tp_moe(x, gates, weights, waxes, mesh, rules,
                         e_ax, m_ax, expert_fn,
                         tp_once=("bd",) if moe.expert_bias else ())


def dense_all_experts(x, gates, lp, moe: MoEArgs, activation, mesh=None,
                      rules=None, e_ax="experts", m_ax="expert_mlp"):
    """The dense all-experts routed-MoE reference: (E, N, I) intermediates,
    EP-sharded on E, TP on I, GSPMD-placed combine. Exactness oracle for the
    grouped kernel / EP ring and the non-TPU / quantized-GSPMD fallback."""
    if moe.scale_expert_input:
        # Llama4: expert input pre-scaled by its gate (unselected experts see
        # zeros, which the bias-free glu maps back to zero); combine is then an
        # unweighted sum
        xe = gates.astype(x.dtype).T[:, :, None] * x[None, :, :]    # (E, N, H)
        xe = constrain(xe, (e_ax, "batch", None), rules, mesh=mesh)
        gate_proj = qeinsum("enh,ehi->eni", xe, lp["wg"])
        up_proj = qeinsum("enh,ehi->eni", xe, lp["wu"])
    else:
        gate_proj = qeinsum("nh,ehi->eni", x, lp["wg"])
        up_proj = qeinsum("nh,ehi->eni", x, lp["wu"])
    if moe.expert_bias:
        gate_proj = gate_proj + lp["bg"][:, None, :]
        up_proj = up_proj + lp["bu"][:, None, :]
    inter = _glu(gate_proj, up_proj, moe, activation)
    inter = constrain(inter, (e_ax, None, m_ax), rules, mesh=mesh)
    per_expert = qeinsum("eni,eih->enh", inter, lp["wd"])           # (E, N, H)
    if moe.expert_bias:
        per_expert = per_expert + lp["bd"][:, None, :]
    if moe.scale_expert_input:
        return jnp.sum(per_expert, axis=0)                          # sum over E: EP psum
    return jnp.einsum("enh,ne->nh", per_expert,
                      gates.astype(per_expert.dtype))               # sum over E: EP psum


def moe_block(lp, args, hn: jnp.ndarray, mesh, rules,
              activation, decode: bool = False) -> jnp.ndarray:
    """(B, S, H) -> (B, S, H) through the MoE FFN.

    ``lp`` carries this layer's stacked expert weights: ``router`` (H, E), ``wg``/``wu``
    (E, H, I), ``wd`` (E, I, H), plus optional shared-expert weights.

    Fast-path selection (decode only): on a multi-device mesh the fused routes
    are the EP ring at ep > 1 (``moe_ep_phase`` -> ``_ring_moe``) and the
    pure-TP grouped wrapper at ep == 1, tp > 1 (``moe_tp_phase`` ->
    ``_tp_grouped_moe`` — the ring's finishing tp psum + tp_once bias
    handling without the ring, since a trace-level pallas_call cannot consume
    GSPMD-sharded leaves and needs the shard_map to see per-chip slices).
    Both run the grouped kernel per-shard when TPUINF_MOE_GROUPED allows and
    the local slices are eligible, exact einsums otherwise. When neither
    phase engages — quantized expert leaves, hybrid remaps off the expected
    axes, cp > 1 — decode keeps the dense all-experts einsums with GSPMD
    placement. Single-device decode takes the grouped kernel directly.
    """
    moe: MoEArgs = args.moe
    # decode graphs constrain expert activations to the decode_* MoE axes, which
    # hybrid sharding may remap (identical to prefill by default)
    e_ax = "decode_experts" if decode else "experts"
    m_ax = "decode_expert_mlp" if decode else "expert_mlp"
    if moe.scale_expert_input and moe.expert_bias:
        # unselected experts see zero input but nonzero bias; the unweighted sum
        # would add bias-derived garbage from every expert
        raise ValueError("scale_expert_input requires bias-free expert MLPs")
    b, s, h = hn.shape
    x = hn.reshape(b * s, h)
    gates = route(lp["router"], x, moe, lp.get("router_b"),
                  lp.get("router_cb"))                              # (N, E) fp32

    routed = None
    if decode and not moe.scale_expert_input:
        if mesh is not None and mesh.size > 1:
            if moe_ep_phase(mesh, rules, e_ax, m_ax):
                routed = _ring_moe(x, gates, lp, moe, activation, mesh, rules,
                                   e_ax, m_ax)
                if routed is not None:
                    _TRACE_STATS["ep_ring"] += 1
            elif moe_tp_phase(mesh, rules, e_ax, m_ax):
                routed = _tp_grouped_moe(x, gates, lp, moe, activation, mesh,
                                         rules, e_ax, m_ax)
                if routed is not None:
                    _TRACE_STATS["tp_grouped"] += 1
        elif grouped_moe_enabled():
            routed = moe_decode_grouped(x, gates, lp, moe, activation)
            if routed is not None:
                _TRACE_STATS["grouped"] += 1

    if routed is None:
        if decode:
            _TRACE_STATS["dense_decode"] += 1
        routed = dense_all_experts(x, gates, lp, moe, activation, mesh=mesh,
                                   rules=rules, e_ax=e_ax, m_ax=m_ax)
    out = constrain(routed.astype(x.dtype), ("batch", None), rules, mesh=mesh)

    if moe.shared_expert_intermediate_size:
        shared_inter = (activation(qapply(x, lp["shared_wg"]))
                        * qapply(x, lp["shared_wu"]))
        shared = qapply(shared_inter, lp["shared_wd"])
        if moe.shared_expert_gated:
            shared_gate = jax.nn.sigmoid(
                (x.astype(jnp.float32)
                 @ lp["shared_gate"].astype(jnp.float32)))           # (N, 1)
            shared = shared * shared_gate.astype(shared.dtype)
        out = out + shared

    return out.reshape(b, s, h).astype(hn.dtype)
