"""Pallas flash-attention prefill kernel (causal, GQA-native, CP-offset-aware).

≈ reference NKI prefill kernels: `attention_isa_kernel`
(`modules/attention/attention_base.py:51-53,122`), the newer
`attention_nki_kernel_adapter` with native GQA + `cp_offset`/`global_cp_deg` args for
context parallelism (`attention_base.py:88-121,684-713`), and the sliding-window
`flash_fwd` (`modules/sliding_window/attention.py`). One kernel covers all three on TPU:

- online-softmax flash attention over (block_q, block_k) tiles; fp32 accumulation,
  bf16 MXU matmuls;
- GQA without repeating KV: the kv head is selected in the BlockSpec index map
  (``h // n_rep``), so KV tiles are fetched once per kv head;
- ``q_offset`` shifts absolute query positions — the context-parallel rank offset
  (reference `cp_offset`) and the chunked-prefill resume offset use the same mechanism;
- optional ``sliding_window`` adds the in-window constraint (SWA prefill kernel);
- causal tiles strictly above the diagonal are predicated off (`@pl.when`), skipping
  their compute like the reference kernels' trapezoid scheduling;
- arch extras the reference's new CTE kernel carries (`attention_base.py:88-121`):
  ``logits_soft_cap`` (gemma tanh cap), per-head learned ``sinks`` (gpt-oss — a
  virtual softmax-denominator logit, folded in at finalize), and per-head ALiBi
  ``bias_slopes`` (bloom/mpt — bias computed in-kernel from the position iotas, never
  materialized as a (S, S) tensor).

Grid: (batch, q_heads, q_blocks, kv_blocks); the innermost kv dimension iterates
sequentially on-core, carrying running (max, sum, acc) in VMEM scratch.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, *refs, scale: float, q_offset: int,
                  block_q: int, block_k: int, num_kv_blocks: int, causal: bool,
                  window: Optional[int], kv_len: int,
                  soft_cap: Optional[float], has_sinks: bool, has_slopes: bool):
    # trailing refs: [sinks?], [slopes?], o_ref, m_scratch, l_scratch, acc_scratch
    idx = 0
    sinks_ref = slopes_ref = None
    if has_sinks:
        sinks_ref, idx = refs[idx], idx + 1
    if has_slopes:
        slopes_ref, idx = refs[idx], idx + 1
    o_ref, m_scratch, l_scratch, acc_scratch = refs[idx : idx + 4]

    qi = pl.program_id(2)
    ki = pl.program_id(3)

    q_start = qi * block_q + q_offset        # absolute position of query row 0
    k_start = ki * block_k                   # absolute position of kv col 0

    @pl.when(ki == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    # causal: the whole tile is masked iff its first kv position exceeds the last
    # query position; predicate the body off to skip the compute entirely
    run = k_start < kv_len                   # skip tiles entirely in kv padding
    if causal:
        run = jnp.logical_and(run, k_start <= q_start + block_q - 1)
    if window is not None:
        run = jnp.logical_and(run, k_start + block_k - 1 > q_start - window)

    @pl.when(run)
    def _body():
        q = q_ref[0, 0]                      # (block_q, D)
        k = k_ref[0, 0]                      # (block_k, D)
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (block_q, block_k)

        q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        kv_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        if slopes_ref is not None:
            # ALiBi: bias = -slope_h * (q_pos - kv_pos), computed from the iotas
            s = s - slopes_ref[0, 0] * (q_pos - kv_pos).astype(jnp.float32)
        if soft_cap is not None:
            s = soft_cap * jnp.tanh(s / soft_cap)
        mask = kv_pos < kv_len               # hide zero-padded kv columns
        if causal:
            mask = jnp.logical_and(mask, kv_pos <= q_pos)
        if window is not None:
            mask = jnp.logical_and(mask, kv_pos > q_pos - window)
        s = jnp.where(mask, s, NEG_INF)

        # m/l scratches are (block_q, 128) with all lanes equal (TPU lane-width tiles)
        m_prev = m_scratch[:, 0:1]           # (block_q, 1)
        l_prev = l_scratch[:, 0:1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # rows with no valid kv yet keep m = -inf; guard the exp against -inf - -inf
        alpha = jnp.exp(jnp.minimum(m_prev - m_new, 0.0))
        p = jnp.exp(s - m_new)               # (block_q, block_k) fp32
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)

        acc = acc_scratch[:] * alpha
        acc = acc + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scratch[:] = jnp.broadcast_to(m_new, m_scratch.shape)
        l_scratch[:] = jnp.broadcast_to(l_new, l_scratch.shape)
        acc_scratch[:] = acc

    @pl.when(ki == num_kv_blocks - 1)
    def _finalize():
        m = m_scratch[:, 0:1]
        l = l_scratch[:, 0:1]
        acc = acc_scratch[:]
        if sinks_ref is not None:
            # learned sink: one virtual logit per head in the softmax denominator
            # only (no V contribution) — fold it in with one extra online-softmax
            # rescale step
            sink = sinks_ref[0, 0]
            m_new = jnp.maximum(m, sink)
            alpha = jnp.exp(jnp.minimum(m - m_new, 0.0))
            l = alpha * l + jnp.exp(sink - m_new)
            acc = acc * alpha
        l_safe = jnp.where(l == 0.0, 1.0, l)   # fully-masked rows -> zeros, not NaN
        o_ref[0, 0] = (acc / l_safe).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "scale", "q_offset", "window", "soft_cap",
                     "block_q", "block_k", "interpret"))
def flash_attention(
    q: jnp.ndarray,              # (B, Hq, Sq, D)
    k: jnp.ndarray,              # (B, Hkv, Skv, D)
    v: jnp.ndarray,              # (B, Hkv, Skv, D)
    causal: bool = True,
    scale: Optional[float] = None,
    q_offset: int = 0,
    window: Optional[int] = None,
    soft_cap: Optional[float] = None,
    sinks: Optional[jnp.ndarray] = None,        # (Hq,) learned sink logits
    alibi_slopes: Optional[jnp.ndarray] = None,  # (Hq,) ALiBi slopes
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool = False,
) -> jnp.ndarray:
    """Tiled causal flash attention; returns (B, Hq, Sq, D) in q.dtype.

    Inputs need not be multiples of the block sizes — they are padded here and the
    output sliced back (bucket ladders make the common shapes already aligned).
    """
    b, hq, sq, d = q.shape
    _, hkv, skv, _ = k.shape
    if hq % hkv != 0:
        raise ValueError(f"q heads {hq} not divisible by kv heads {hkv}")
    n_rep = hq // hkv
    if scale is None:
        scale = d ** -0.5

    block_q = min(block_q, _round_up(sq, 8))
    block_k = min(block_k, _round_up(skv, 8))
    sq_p = _round_up(sq, block_q)
    skv_p = _round_up(skv, block_k)
    if sq_p != sq:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, sq_p - sq), (0, 0)))
    if skv_p != skv:
        # padded kv columns are masked in-kernel via kv_len
        k = jnp.pad(k, ((0, 0), (0, 0), (0, skv_p - skv), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, skv_p - skv), (0, 0)))

    num_q_blocks = sq_p // block_q
    num_kv_blocks = skv_p // block_k

    kernel = functools.partial(
        _flash_kernel, scale=scale, q_offset=q_offset, block_q=block_q,
        block_k=block_k, num_kv_blocks=num_kv_blocks, causal=causal, window=window,
        kv_len=skv, soft_cap=soft_cap, has_sinks=sinks is not None,
        has_slopes=alibi_slopes is not None)

    def _head_scalar_spec():
        # per-head scalar broadcast over the lane dim: (Hq, 128), one row per cell
        return pl.BlockSpec((1, 128), lambda bi, hi, qi, ki: (hi, 0))

    in_specs = [
        pl.BlockSpec((1, 1, block_q, d),
                     lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        pl.BlockSpec((1, 1, block_k, d),
                     lambda bi, hi, qi, ki, n_rep=n_rep: (bi, hi // n_rep, ki, 0)),
        pl.BlockSpec((1, 1, block_k, d),
                     lambda bi, hi, qi, ki, n_rep=n_rep: (bi, hi // n_rep, ki, 0)),
    ]
    operands = [q, k, v]
    for extra in (sinks, alibi_slopes):
        if extra is not None:
            in_specs.append(_head_scalar_spec())
            operands.append(jnp.broadcast_to(
                extra.astype(jnp.float32)[:, None], (hq, 128)))

    out = pl.pallas_call(
        kernel,
        grid=(b, hq, num_q_blocks, num_kv_blocks),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, hq, sq_p, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(*operands)

    if sq_p != sq:
        out = out[:, :, :sq, :]
    return out


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m
