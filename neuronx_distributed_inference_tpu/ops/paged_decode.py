"""Pallas ragged paged decode: block-table-indexed, length-aware KV attention + write.

≈ reference paged decode: `BlockKVCacheManager` gather/scatter
(`modules/kvcache/block_kv_cache_manager.py:268-374`) + the TKG attention kernels
(`modules/attention/attention_base.py:1483-1677`) + the batched KV-write kernel
(`modules/kvcache/utils.py:20-38`). The reference's continuous-batching decode gathers
the full block-table width; SURVEY §7 flags ragged paged attention as "the performance
cliff". These kernels are the TPU answer:

- The paged cache is layer-stacked ``(L, NB, H_kv, BS, D)`` (see modules/block_kvcache)
  and rides the model's layer scan as a **carry** — the layer index arrives via scalar
  prefetch, so the scan never slices or re-stacks the (potentially huge) block pool.
- **Attention** streams each row's blocks *through its block table*: the BlockSpec
  index map reads the scalar-prefetched table, so the DMA engine fetches exactly the
  physical blocks of that row — and per-row positions predicate off whole block groups
  beyond the row's live length, so HBM traffic tracks each row's true length, not the
  table width. Trailing out-of-range fetches are clamped to the last live block, which
  Mosaic elides (same block index as the previous grid step -> no DMA).
- **Write** is a tile-aligned read-modify-write per fresh token (Mosaic DMA slices on
  the sublane dim must be whole packed tiles), with dropped-slot (-1) padding writes
  predicated off — replacing the reference's garbage-position padding writes.

Decode is HBM-bandwidth-bound: the win over the gather path is strictly fewer cache
bytes read per step (table-width -> live-length).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _vmem_cast(x: jnp.ndarray, dtype) -> jnp.ndarray:
    """Fast in-kernel cast of fp8 cache tiles to the compute dtype.

    Mosaic lowers `astype` from fp8 through a scalarized emulation that costs
    ~10 ms/step at bs=64 (measured: the paged attend dropped 16.1 -> 6.5
    ms/step when the cache was bf16 instead of f8e4m3). fp8 -> bf16 is pure
    bit surgery — widen to i32, rebase the exponent, reassemble — which runs
    at VPU integer rate. Denormals flush to zero (KV scales keep serving
    values normal; the saturating write precludes NaN/Inf payloads)."""
    if x.dtype == dtype:
        return x
    name = jnp.dtype(x.dtype).name
    if name not in ("float8_e4m3fn", "float8_e5m2") or dtype != jnp.bfloat16:
        return x.astype(dtype)
    u = jax.lax.bitcast_convert_type(x, jnp.uint8).astype(jnp.int32)
    if name == "float8_e4m3fn":                      # s eeee mmm, bias 7
        s, e, m = (u >> 7) & 1, (u >> 3) & 0xF, u & 0x7
        bits = (s << 15) | ((e + 120) << 7) | (m << 4)
    else:                                            # s eeeee mm, bias 15
        s, e, m = (u >> 7) & 1, (u >> 2) & 0x1F, u & 0x3
        bits = (s << 15) | ((e + 112) << 7) | (m << 5)
    bits = jnp.where(e == 0, 0, bits).astype(jnp.uint16)
    return jax.lax.bitcast_convert_type(bits, jnp.bfloat16)


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _pack(dtype) -> int:
    """Sublane packing: DMA slices on the second-minor dim must cover whole
    (8 * 4/itemsize)-row tiles."""
    return 8 * max(1, 4 // jnp.dtype(dtype).itemsize)


# --- paged KV write -------------------------------------------------------------------


def _paged_write_kernel(slots_ref, lidx_ref, new_k_ref, new_v_ref, _k_in, _v_in,
                        k_out, v_out, sk, sv, sems, *, t: int, pack: int, bs: int):
    """Per-row scatter of the step's t fresh tokens, tile-aligned RMW.

    t == 1 (plain decode): one RMW window per row. t in {2..8} (the
    speculative multi-query commit): the common case — consecutive live slots
    inside ONE aligned pack window (pack >= 32 for int8/fp8 caches, so a K<=8
    chain straddles a window boundary at most once every pack positions) —
    collapses to a SINGLE read-modify-write per row: 4 DMA waits instead of
    4*t. Rows that straddle a window/block boundary, carry dropped (-1) slots,
    or aren't consecutive fall back to the per-token loop. Dropped slots stay
    predicated off in both paths (the conditional commit: a dead CB slot or a
    masked speculative row writes nothing)."""
    b = pl.program_id(0)
    l = lidx_ref[0]

    def _rmw(blk, w0, edit):
        """One aligned-window RMW: read both tiles, apply ``edit``, write back."""
        dst_k = k_out.at[l, blk, :, pl.ds(w0, pack), :]
        dst_v = v_out.at[l, blk, :, pl.ds(w0, pack), :]
        pltpu.make_async_copy(dst_k, sk, sems.at[0]).start()
        pltpu.make_async_copy(dst_v, sv, sems.at[1]).start()
        pltpu.make_async_copy(dst_k, sk, sems.at[0]).wait()
        pltpu.make_async_copy(dst_v, sv, sems.at[1]).wait()
        edit()
        pltpu.make_async_copy(sk, dst_k, sems.at[0]).start()
        pltpu.make_async_copy(sv, dst_v, sems.at[1]).start()
        pltpu.make_async_copy(sk, dst_k, sems.at[0]).wait()
        pltpu.make_async_copy(sv, dst_v, sems.at[1]).wait()

    def _per_token():
        for tok in range(t):                   # t is tiny (1 or speculation width)
            slot = slots_ref[b * t + tok]

            @pl.when(slot >= 0)
            def _write(slot=slot, tok=tok):
                blk = slot // bs
                off = slot % bs
                w0 = (off // pack) * pack      # aligned window inside the block

                def edit(off=off, w0=w0, tok=tok):
                    iota = jax.lax.broadcasted_iota(jnp.int32, sk.shape, 1)
                    hit = iota == off - w0
                    sk[:] = jnp.where(hit, new_k_ref[0, :, tok : tok + 1, :],
                                      sk[:])
                    sv[:] = jnp.where(hit, new_v_ref[0, :, tok : tok + 1, :],
                                      sv[:])

                _rmw(blk, w0, edit)

    if t == 1:
        _per_token()
        return

    slot0 = slots_ref[b * t]
    contig = slot0 >= 0
    for tok in range(1, t):
        contig = jnp.logical_and(contig, slots_ref[b * t + tok] == slot0 + tok)
    off0 = slot0 % bs
    # same aligned window => same block (bs % pack == 0, enforced by the caller)
    one_window = jnp.logical_and(contig, off0 // pack == (off0 + t - 1) // pack)

    @pl.when(one_window)
    def _fused():
        blk = slot0 // bs
        w0 = (off0 // pack) * pack

        def edit():
            iota = jax.lax.broadcasted_iota(jnp.int32, sk.shape, 1)
            rel = iota - (off0 - w0)           # window row -> fresh-token index
            for tok in range(t):
                hit = rel == tok
                sk[:] = jnp.where(hit, new_k_ref[0, :, tok : tok + 1, :], sk[:])
                sv[:] = jnp.where(hit, new_v_ref[0, :, tok : tok + 1, :], sv[:])

        _rmw(blk, w0, edit)

    @pl.when(jnp.logical_not(one_window))
    def _straddle():
        _per_token()


@functools.partial(jax.jit, static_argnames=("interpret",))
def write_paged_stacked_kv(
    k_cache: jnp.ndarray,        # (L, NB, Hkv, BS, D) — donated/aliased in place
    v_cache: jnp.ndarray,
    new_k: jnp.ndarray,          # (B, Hkv, T, D), already in cache dtype
    new_v: jnp.ndarray,
    slot_mapping: jnp.ndarray,   # (B, T) int32 flat slots (block*BS + off); -1 = drop
    layer_idx: jnp.ndarray,      # () int32 layer to write
    interpret: bool = False,
):
    """Scatter the step's K and V rows into the stacked paged cache in one kernel.

    ≈ `write_kv_cache_at_batch_kernel` (`modules/kvcache/utils.py:20-38`) over the
    paged layout: tile-aligned RMW windows, -1 slots dropped. T > 1 (the
    speculative multi-query commit) collapses a row's consecutive
    same-window slots into ONE RMW — see _paged_write_kernel."""
    b, h, t, d = new_k.shape
    bs = k_cache.shape[3]
    pack = _pack(k_cache.dtype)
    if bs % pack != 0:
        raise ValueError(f"pa_block_size {bs} must be a multiple of {pack} for "
                         f"{k_cache.dtype} caches")
    kernel = functools.partial(_paged_write_kernel, t=t, pack=pack, bs=bs)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, h, t, d), lambda bi, *_: (bi, 0, 0, 0)),
            pl.BlockSpec((1, h, t, d), lambda bi, *_: (bi, 0, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[pl.BlockSpec(memory_space=pl.ANY),
                   pl.BlockSpec(memory_space=pl.ANY)],
        scratch_shapes=[
            pltpu.VMEM((h, pack, d), k_cache.dtype),
            pltpu.VMEM((h, pack, d), v_cache.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct(k_cache.shape, k_cache.dtype),
                   jax.ShapeDtypeStruct(v_cache.shape, v_cache.dtype)],
        input_output_aliases={4: 0, 5: 1},   # caches (after 2 prefetch + 2 new)
        interpret=interpret,
    )(slot_mapping.reshape(-1).astype(jnp.int32),
      layer_idx.reshape(1).astype(jnp.int32), new_k, new_v, k_cache, v_cache)


# --- paged decode attention -----------------------------------------------------------


def _paged_attend_kernel_v3(pos_ref, lidx_ref, bt_ref, q_ref, *refs,
                            o_ref=None, m_scratch=None, l_scratch=None,
                            acc_scratch=None, scale: float, bs: int, kb: int,
                            bb: int, num_cells: int, t: int, qr: int,
                            nq: int, hkv: int, window: Optional[int],
                            soft_cap: Optional[float], has_sinks: bool,
                            has_slopes: bool):
    """v3 cell body: FLAT q packing + per-block-group dots, no concat.

    v2 padded each head's q rows to 8 sublanes and concatenated the cell's kb
    blocks into one (hkv*width, D) operand — measured on-chip the cell is
    VPU-epilogue-bound (fp8 was SLOWER than bf16 despite half the DMA), and
    the score matrix was 2x over-padded on rows plus a VMEM concat copy per
    row-unit. v3 packs q as (hkv*n_rep*t, D) rows with NO per-head padding
    (the head index is recovered as row // qr in the mask iota) and runs one
    (nq, hkv*bs) dot + flash update PER BLOCK GROUP straight off each fetched
    block ref: half the score elements, half the MXU flops, zero concat.
    Cross-head score tiles are masked; the masked-zero p rows make the single
    packed p @ V dot exact (same trick as v2)."""
    kv_refs = refs[: 2 * kb * bb]
    idx = 2 * kb * bb
    sinks_ref = slopes_ref = None
    if has_sinks:
        sinks_ref, idx = refs[idx], idx + 1
    if has_slopes:
        slopes_ref, idx = refs[idx], idx + 1

    bi = pl.program_id(0)
    ci = pl.program_id(1)

    @pl.when(ci == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    width = kb * bs
    k_start = ci * width
    d = q_ref.shape[-1]
    cols = hkv * bs

    row_iota = jax.lax.broadcasted_iota(jnp.int32, (nq, cols), 0)
    col_iota = jax.lax.broadcasted_iota(jnp.int32, (nq, cols), 1)
    same_head = (row_iota // qr) == (col_iota // bs)
    tok_idx = (row_iota % qr) % t
    col_off = col_iota % bs

    for j in range(bb):                        # static unroll over batch rows
        pos = pos_ref[bi * bb + j]
        run = k_start <= pos + t - 1           # cell fully beyond the row -> skip
        if window is not None:
            run = jnp.logical_and(run, k_start + width - 1 > pos - window)
        r0 = j * nq

        @pl.when(run)
        def _body(j=j, pos=pos, r0=r0):
            q = q_ref[j]                                   # (nq, d)
            q_pos = pos + tok_idx
            for g in range(kb):
                k = _vmem_cast(kv_refs[2 * (j * kb + g)][0, 0].reshape(cols, d),
                               q.dtype)
                v = _vmem_cast(
                    kv_refs[2 * (j * kb + g) + 1][0, 0].reshape(cols, d),
                    q.dtype)
                kv_pos = k_start + g * bs + col_off
                mask = jnp.logical_and(same_head, kv_pos <= q_pos)
                if window is not None:
                    mask = jnp.logical_and(mask, kv_pos > q_pos - window)

                s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                        preferred_element_type=jnp.float32
                                        ) * scale
                if slopes_ref is not None:
                    s = s - slopes_ref[:, 0:1] * (q_pos - kv_pos).astype(
                        jnp.float32)
                if soft_cap is not None:
                    s = soft_cap * jnp.tanh(s / soft_cap)
                s = jnp.where(mask, s, NEG_INF)

                m_prev = m_scratch[r0 : r0 + nq, 0:1]
                l_prev = l_scratch[r0 : r0 + nq, 0:1]
                m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
                alpha = jnp.exp(jnp.minimum(m_prev - m_new, 0.0))
                p = jnp.exp(s - m_new)
                p = jnp.where(mask, p, 0.0)
                l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
                acc = acc_scratch[r0 : r0 + nq] * alpha + jax.lax.dot_general(
                    p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                m_scratch[r0 : r0 + nq] = jnp.broadcast_to(m_new, (nq, 128))
                l_scratch[r0 : r0 + nq] = jnp.broadcast_to(l_new, (nq, 128))
                acc_scratch[r0 : r0 + nq] = acc

    @pl.when(ci == num_cells - 1)
    def _finalize():
        for j in range(bb):
            r0 = j * nq
            m = m_scratch[r0 : r0 + nq, 0:1]
            l = l_scratch[r0 : r0 + nq, 0:1]
            acc = acc_scratch[r0 : r0 + nq]
            if sinks_ref is not None:
                sink = sinks_ref[:, 0:1]
                m_new = jnp.maximum(m, sink)
                alpha = jnp.exp(jnp.minimum(m - m_new, 0.0))
                l = alpha * l + jnp.exp(sink - m_new)
                acc = acc * alpha
            l_safe = jnp.where(l == 0.0, 1.0, l)
            o_ref[j] = (acc / l_safe).reshape(o_ref.shape[1:]).astype(
                o_ref.dtype)


def _paged_attend_kernel(pos_ref, lidx_ref, bt_ref, q_ref, *refs, o_ref=None,
                         m_scratch=None, l_scratch=None, acc_scratch=None,
                         scale: float, bs: int, kb: int, bb: int,
                         num_cells: int, t: int,
                         rows: int, hkv: int, window: Optional[int],
                         soft_cap: Optional[float], has_sinks: bool,
                         has_slopes: bool):
    """Block-diagonal head packing over ``bb`` batch rows per grid cell.

    Per row: every kv head's q rows stack into ONE (hkv*rows, D) operand and
    the cell's kv blocks into ONE (hkv*width, D) operand, so each row costs
    2 large MXU dots + a single vectorized flash update instead of hkv*kb tiny
    per-head ops (v1 was VPU-serialization-bound: 15.7 ms/step at bs=64).
    Cross-head (off-diagonal) score tiles are masked to -inf — wasted MXU
    flops that the 8x-wider op amortizes, not bandwidth. Batching ``bb`` rows
    per cell amortizes the per-cell grid fixed cost (v2 at bb=1 measured
    ~12 us/cell with only ~3 us of real work)."""
    kv_refs = refs[: 2 * kb * bb]
    idx = 2 * kb * bb
    sinks_ref = slopes_ref = None
    if has_sinks:
        sinks_ref, idx = refs[idx], idx + 1
    if has_slopes:
        slopes_ref, idx = refs[idx], idx + 1

    bi = pl.program_id(0)
    ci = pl.program_id(1)

    @pl.when(ci == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    width = kb * bs                            # kv positions fetched per row
    k_start = ci * width
    nrows = hkv * rows
    d = q_ref.shape[-1]

    for j in range(bb):                        # static unroll over batch rows
        pos = pos_ref[bi * bb + j]
        run = k_start <= pos + t - 1           # cell fully beyond the row -> skip
        if window is not None:
            run = jnp.logical_and(run, k_start + width - 1 > pos - window)
        r0 = j * nrows

        @pl.when(run)
        def _body(j=j, pos=pos, r0=r0):
            q = q_ref[j].reshape(nrows, d)
            k = jnp.concatenate(
                [kv_refs[2 * (j * kb + g)][0, 0] for g in range(kb)], axis=1)
            v = jnp.concatenate(
                [kv_refs[2 * (j * kb + g) + 1][0, 0] for g in range(kb)], axis=1)
            int8_kv = k.dtype == jnp.int8
            k = k.reshape(hkv * width, d)
            v = v.reshape(hkv * width, d)
            if int8_kv:
                # int8 KV (static scales): feed the MXU int8 x int8 directly —
                # no cast of the streamed operands. q rows quantize per-row
                # (tiny), scores rescale by sx; p quantizes to [0, 127] for the
                # PV dot (the cache payload is already K/sigma resp. V/sigma,
                # the per-head sigma fold happens outside the kernel).
                qf = q.astype(jnp.float32)
                sx = jnp.max(jnp.abs(qf), axis=1, keepdims=True) / 127.0
                sx = jnp.maximum(sx, 1e-8)
                q = jnp.clip(jnp.round(qf / sx), -127, 127).astype(jnp.int8)
            else:
                k = _vmem_cast(k, q.dtype)
                v = _vmem_cast(v, q.dtype)

            row_iota = jax.lax.broadcasted_iota(jnp.int32, (nrows, hkv * width), 0)
            col_iota = jax.lax.broadcasted_iota(jnp.int32, (nrows, hkv * width), 1)
            # row r = head * rows + i, token index i % t; K stacking is
            # (hkv, width) row-major, so column c belongs to kv head c // width
            # at in-cell offset c % width
            q_pos = pos + (row_iota % rows) % t
            kv_pos = k_start + col_iota % width
            same_head = (row_iota // rows) == (col_iota // width)
            mask = jnp.logical_and(same_head, kv_pos <= q_pos)
            if window is not None:
                mask = jnp.logical_and(mask, kv_pos > q_pos - window)

            if int8_kv:
                s = jax.lax.dot_general(
                    q, k, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.int32
                ).astype(jnp.float32) * (sx * scale)
            else:
                s = jax.lax.dot_general(
                    q, k, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32) * scale
            if slopes_ref is not None:
                s = s - slopes_ref[:, 0:1] * (q_pos - kv_pos).astype(jnp.float32)
            if soft_cap is not None:
                s = soft_cap * jnp.tanh(s / soft_cap)
            s = jnp.where(mask, s, NEG_INF)

            m_prev = m_scratch[r0 : r0 + nrows, 0:1]
            l_prev = l_scratch[r0 : r0 + nrows, 0:1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
            alpha = jnp.exp(jnp.minimum(m_prev - m_new, 0.0))
            p = jnp.exp(s - m_new)
            p = jnp.where(mask, p, 0.0)
            l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
            if int8_kv:
                pi = jnp.round(p * 127.0).astype(jnp.int8)
                pv = jax.lax.dot_general(
                    pi, v, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.int32
                ).astype(jnp.float32) * (1.0 / 127.0)
            else:
                pv = jax.lax.dot_general(
                    p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
            acc = acc_scratch[r0 : r0 + nrows] * alpha + pv
            m_scratch[r0 : r0 + nrows] = jnp.broadcast_to(m_new, (nrows, 128))
            l_scratch[r0 : r0 + nrows] = jnp.broadcast_to(l_new, (nrows, 128))
            acc_scratch[r0 : r0 + nrows] = acc

    @pl.when(ci == num_cells - 1)
    def _finalize():
        for j in range(bb):
            r0 = j * nrows
            m = m_scratch[r0 : r0 + nrows, 0:1]
            l = l_scratch[r0 : r0 + nrows, 0:1]
            acc = acc_scratch[r0 : r0 + nrows]
            if sinks_ref is not None:
                sink = sinks_ref[:, 0:1]
                m_new = jnp.maximum(m, sink)
                alpha = jnp.exp(jnp.minimum(m - m_new, 0.0))
                l = alpha * l + jnp.exp(sink - m_new)
                acc = acc * alpha
            l_safe = jnp.where(l == 0.0, 1.0, l)
            o_ref[j] = (acc / l_safe).reshape(o_ref.shape[1:]).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "window", "soft_cap", "blocks_per_cell",
                     "rows_per_cell", "interpret", "variant"))
def paged_decode_attention_stacked(
    q: jnp.ndarray,              # (B, Hq, T, D), T small (1 or speculation width)
    k_cache: jnp.ndarray,        # (L, NB, Hkv, BS, D) — full stacked paged cache
    v_cache: jnp.ndarray,
    positions: jnp.ndarray,      # (B,) int32 write position of q[:, :, 0]
    layer_idx: jnp.ndarray,      # () int32 layer to attend over
    block_table: jnp.ndarray,    # (B, MB) int32 physical block ids (logical order)
    scale: Optional[float] = None,
    window: Optional[int] = None,
    soft_cap: Optional[float] = None,
    sinks: Optional[jnp.ndarray] = None,         # (Hq,) learned sink logits
    alibi_slopes: Optional[jnp.ndarray] = None,  # (Hq,) ALiBi slopes
    blocks_per_cell: Optional[int] = None,
    rows_per_cell: Optional[int] = None,
    interpret: bool = False,
    variant: int = 2,
) -> jnp.ndarray:
    """Ragged paged decode attention over one layer of the stacked paged cache.

    Streams each row's physical blocks through its block-table row (BlockSpec index
    maps over the scalar-prefetched table); block groups beyond a row's position are
    clamped to the row's last live block (DMA elided) and predicated off. The fresh
    step's K/V must already be written (write_paged_stacked_kv).

    T = 1 is plain chain decode. T in {2..8} is the MULTI-QUERY (ragged
    verify) shape — the q_len>1 ragged-paged-attention case: the K
    speculative positions of every row attend in ONE pass over the row's
    live blocks (each block group is streamed once for all T queries) with
    an intra-chunk causal mask (q_pos = pos + tok index, kv_pos <= q_pos),
    instead of T single-token attends or a table-width gather that would
    stream the cache T times.
    ``variant``: 2 = head-padded concat cells (the measured default), 3 = flat-q
    per-block-group cells (measured neutral-bf16 / worse-fp8 on v5e at bs=64 —
    kept for other geometries; see _paged_attend_kernel_v3).
    Returns (B, Hq, T, D) in q.dtype."""
    b, hq, t, d = q.shape
    _, nb, hkv, bs, _ = k_cache.shape
    mb = block_table.shape[1]
    if hq % hkv != 0:
        raise ValueError(f"q heads {hq} not divisible by kv heads {hkv}")
    n_rep = hq // hkv
    if scale is None:
        scale = d ** -0.5

    qr = n_rep * t
    if variant == 3:
        nq = _round_up(hkv * qr, 8)
        qg = q.reshape(b, hkv, qr, d).reshape(b, hkv * qr, d)
        if nq != hkv * qr:
            qg = jnp.pad(qg, ((0, 0), (0, nq - hkv * qr), (0, 0)))
        rows = None
    else:
        qg = q.reshape(b, hkv, n_rep, t, d).reshape(b, hkv, n_rep * t, d)
        rows = max(8, _round_up(n_rep * t, 8))
        if rows != n_rep * t:
            qg = jnp.pad(qg, ((0, 0), (0, 0), (0, rows - n_rep * t), (0, 0)))

    # cell geometry (r5 on-chip sweep at bs=64/BS=128/Hkv=8/D=128): batch 4
    # rows per cell to amortize grid fixed cost, and size the per-cell KV
    # footprint to ~2 MB so Mosaic's automatic double-buffering fits in VMEM
    # and block fetches PIPELINE against compute — larger cells (the old
    # 512-position heuristic) serialized DMA with the body (bf16 335 -> 291 us
    # per layer; fp8 405 -> 399, cast-bound).
    kv_itemsize = jnp.dtype(k_cache.dtype).itemsize
    # int8 prefers bigger cells (r5 sweep: 182 us at 4 MB/cell vs 210 at
    # 2 MB — the int8 body is cheap enough that fetch batching wins);
    # bf16/fp8 pipeline best at ~2 MB/cell
    budget = (4 if jnp.dtype(k_cache.dtype) == jnp.int8 else 2) * 2 ** 20
    if rows_per_cell is not None:
        if b % rows_per_cell != 0:
            raise ValueError(f"rows_per_cell {rows_per_cell} must divide {b}")
        bb = rows_per_cell
    else:
        # bound bb so even a kb=1 cell fits the budget (large pa_block_size /
        # many kv heads would otherwise blow VMEM with double-buffering)
        one_block = 2 * hkv * bs * d * kv_itemsize
        bb = 1
        for cand in (4, 2):
            if b % cand == 0 and cand * one_block <= max(budget, one_block):
                bb = cand
                break
    if blocks_per_cell:
        kb = min(mb, blocks_per_cell)
    else:
        per_block = 2 * bb * hkv * bs * d * kv_itemsize
        kb = min(mb, max(1, budget // per_block))
    while mb % kb != 0:
        kb -= 1
    num_cells = mb // kb

    def _kv_index_map(j, g):
        def index_map(bi, ci, pos, lidx, bt):
            row = bi * bb + j
            gg = ci * kb + g
            # clamp out-of-range fetches to the nearest live block — beyond-live
            # groups to the last live block (this step's fresh tokens reach
            # pos + t - 1) and, under a sliding window, below-window groups to the
            # first in-window block: the repeated (layer, block) tuple matches the
            # neighbouring grid step, so Mosaic elides the DMA and HBM traffic
            # tracks the live (windowed) length, not the table width
            last_live = (pos[row] + t - 1) // bs
            gg = jnp.minimum(gg, last_live)
            if window is not None:
                first_live = jnp.maximum(pos[row] - (window - 1), 0) // bs
                gg = jnp.maximum(gg, jnp.minimum(first_live, last_live))
            return (lidx[0], bt[row, gg], 0, 0, 0)

        return index_map

    kv_specs = []
    for j in range(bb):
        for g in range(kb):
            kv_specs.append(pl.BlockSpec((1, 1, hkv, bs, d), _kv_index_map(j, g)))
            kv_specs.append(pl.BlockSpec((1, 1, hkv, bs, d), _kv_index_map(j, g)))

    if variant == 3:
        kernel = functools.partial(
            _paged_attend_kernel_v3, scale=scale, bs=bs, kb=kb, bb=bb,
            num_cells=num_cells, t=t, qr=qr, nq=nq, hkv=hkv, window=window,
            soft_cap=soft_cap, has_sinks=sinks is not None,
            has_slopes=alibi_slopes is not None)
        q_spec = pl.BlockSpec((bb, nq, d), lambda bi, ci, *_: (bi, 0, 0))
        out_shape = jax.ShapeDtypeStruct((b, nq, d), q.dtype)
        n_scr_rows = bb * nq
        extra_rows = nq
    else:
        kernel = functools.partial(
            _paged_attend_kernel, scale=scale, bs=bs, kb=kb, bb=bb,
            num_cells=num_cells,
            t=t, rows=rows, hkv=hkv, window=window, soft_cap=soft_cap,
            has_sinks=sinks is not None, has_slopes=alibi_slopes is not None)
        q_spec = pl.BlockSpec((bb, hkv, rows, d), lambda bi, ci, *_: (bi, 0, 0, 0))
        out_shape = jax.ShapeDtypeStruct((b, hkv, rows, d), q.dtype)
        n_scr_rows = bb * hkv * rows
        extra_rows = hkv * rows

    extra_specs, extra_ops = [], []
    for extra in (sinks, alibi_slopes):
        if extra is not None:
            from .flash_decode import _group_head_scalars

            extra_specs.append(
                pl.BlockSpec((extra_rows, 128), lambda bi, ci, *_: (0, 0)))
            grouped = _group_head_scalars(extra, hkv, n_rep, t,
                                          qr if variant == 3 else rows)
            if variant == 3 and nq != hkv * qr:
                grouped = jnp.pad(grouped, ((0, nq - hkv * qr), (0, 0)))
            extra_ops.append(grouped)
    n_extra = len(extra_ops)

    def _kernel(pos_ref, lidx_ref, bt_ref, q_ref, *rest):
        ins = rest[: 2 * kb * bb + n_extra]
        o_ref, m_s, l_s, acc_s = rest[2 * kb * bb + n_extra :]
        kernel(pos_ref, lidx_ref, bt_ref, q_ref, *ins, o_ref=o_ref,
               m_scratch=m_s, l_scratch=l_s, acc_scratch=acc_s)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b // bb, num_cells),
        in_specs=[q_spec] + kv_specs + extra_specs,
        out_specs=pl.BlockSpec(q_spec.block_shape, q_spec.index_map),
        scratch_shapes=[
            pltpu.VMEM((n_scr_rows, 128), jnp.float32),
            pltpu.VMEM((n_scr_rows, 128), jnp.float32),
            pltpu.VMEM((n_scr_rows, d), jnp.float32),
        ],
    )
    # the per-layer cache view (4D) keeps the kv BlockSpecs rank-4; layer selection
    # happens in the index map's first coordinate against the 5D array — pass the 5D
    # cache and fold the layer into the block index map instead of slicing (the whole
    # point is never materializing a layer slice)
    out = pl.pallas_call(
        _kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(positions.astype(jnp.int32), layer_idx.reshape(1).astype(jnp.int32),
      block_table.astype(jnp.int32), qg,
      *([k_cache, v_cache] * (kb * bb)), *extra_ops)

    if variant == 3:
        out = out[:, : hkv * qr, :].reshape(b, hkv, n_rep, t, d)
    else:
        out = out[:, :, : n_rep * t, :].reshape(b, hkv, n_rep, t, d)
    return out.reshape(b, hq, t, d)
