"""Pallas ragged paged decode: block-table-indexed, length-aware KV attention + write.

≈ reference paged decode: `BlockKVCacheManager` gather/scatter
(`modules/kvcache/block_kv_cache_manager.py:268-374`) + the TKG attention kernels
(`modules/attention/attention_base.py:1483-1677`) + the batched KV-write kernel
(`modules/kvcache/utils.py:20-38`). The reference's continuous-batching decode gathers
the full block-table width; SURVEY §7 flags ragged paged attention as "the performance
cliff". These kernels are the TPU answer:

- The paged cache is layer-stacked ``(L, NB, H_kv, BS, D)`` (see modules/block_kvcache)
  and rides the model's layer scan as a **carry** — the layer index arrives via scalar
  prefetch, so the scan never slices or re-stacks the (potentially huge) block pool.
- **Attention** streams each row's blocks *through its block table*: the BlockSpec
  index map reads the scalar-prefetched table, so the DMA engine fetches exactly the
  physical blocks of that row — and per-row positions predicate off whole block groups
  beyond the row's live length, so HBM traffic tracks each row's true length, not the
  table width. Trailing out-of-range fetches are clamped to the last live block, which
  Mosaic elides (same block index as the previous grid step -> no DMA).
- **Write** is a tile-aligned read-modify-write per fresh token (Mosaic DMA slices on
  the sublane dim must be whole packed tiles), with dropped-slot (-1) padding writes
  predicated off — replacing the reference's garbage-position padding writes.
- **Fused append+attend** (`fused_paged_decode_stacked`, the q_len<=8 decode hot
  path): ONE pallas call per layer commits the fresh tokens through the same RMW
  windows AND attends — fresh K/V from VMEM operands (no read-after-write of the
  appended block), committed blocks through a manual ``prefetch_depth``-deep
  `make_async_copy` pipeline whose loop bound is each row's LIVE block count.
  Halves the per-step dispatch count vs separate write-then-attend.

Decode is HBM-bandwidth-bound: the win over the gather path is strictly fewer cache
bytes read per step (table-width -> live-length), and — fused — fewer kernel
boundaries between them.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
LOG2E = 1.4426950408889634


def _vmem_cast(x: jnp.ndarray, dtype) -> jnp.ndarray:
    """Fast in-kernel cast of fp8 cache tiles to the compute dtype.

    Mosaic lowers `astype` from fp8 through a scalarized emulation that costs
    ~10 ms/step at bs=64 (measured: the paged attend dropped 16.1 -> 6.5
    ms/step when the cache was bf16 instead of f8e4m3). fp8 -> bf16 is pure
    bit surgery — widen to i32, rebase the exponent, reassemble — which runs
    at VPU integer rate. Denormals flush to zero (KV scales keep serving
    values normal; the saturating write precludes NaN/Inf payloads)."""
    if x.dtype == dtype:
        return x
    name = jnp.dtype(x.dtype).name
    if name not in ("float8_e4m3fn", "float8_e5m2") or dtype != jnp.bfloat16:
        return x.astype(dtype)
    u = jax.lax.bitcast_convert_type(x, jnp.uint8).astype(jnp.int32)
    if name == "float8_e4m3fn":                      # s eeee mmm, bias 7
        s, e, m = (u >> 7) & 1, (u >> 3) & 0xF, u & 0x7
        bits = (s << 15) | ((e + 120) << 7) | (m << 4)
    else:                                            # s eeeee mm, bias 15
        s, e, m = (u >> 7) & 1, (u >> 2) & 0x1F, u & 0x3
        bits = (s << 15) | ((e + 112) << 7) | (m << 5)
    bits = jnp.where(e == 0, 0, bits).astype(jnp.uint16)
    return jax.lax.bitcast_convert_type(bits, jnp.bfloat16)


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _pack(dtype) -> int:
    """Sublane packing: DMA slices on the second-minor dim must cover whole
    (8 * 4/itemsize)-row tiles."""
    return 8 * max(1, 4 // jnp.dtype(dtype).itemsize)


# --- AMLA exponent-add rescaling + length-parallel split selection --------------------
#
# AMLA ("MUL by ADD in FlashAttention Rescaling", PAPERS.md): the online-softmax
# running max is kept on the BASE-2 INTEGER grid (m = ceil(max(s * log2 e))), so
# every rescale factor alpha = 2^(m_prev - m_new) is an exact power of two and the
# `acc * alpha` / `l * alpha` VPU multiplies become an integer ADD into the f32
# exponent field — the same bit-surgery family as `_vmem_cast` above. p = 2^(s2 - m)
# stays <= 1 (m overshoots the true max by < 1 bit), so the int8 p-quantization
# grid and every overflow argument of the multiply path carry over unchanged.


def _amla_default() -> bool:
    """Trace-time opt-out: TPUINF_AMLA=0 restores the multiply rescale."""
    return os.environ.get("TPUINF_AMLA", "1") != "0"


def _exp2_rescale(x: jnp.ndarray, delta: jnp.ndarray) -> jnp.ndarray:
    """``x * 2**delta`` for f32 ``x`` and integer-valued ``delta <= 0`` via an
    ADD into the exponent field: widen to i32, add ``delta`` to bits 23..30,
    reassemble. Zeros stay zero (e == 0 is kept out of the add) and a rebased
    exponent that underflows flushes to zero — exactly the denormal policy of
    `_vmem_cast`. ``delta`` must already be clamped to > -255 by the caller."""
    bits = jax.lax.bitcast_convert_type(x, jnp.int32)
    d = delta.astype(jnp.int32)
    e = (bits >> 23) & 0xFF
    keep = jnp.logical_and(e > 0, e + d > 0)
    out = jnp.where(keep, bits + (d << 23), 0)
    return jax.lax.bitcast_convert_type(out, jnp.float32)


def _flash_accumulate(s, mask, m_prev, l_prev, acc_prev, pv_dot, amla: bool):
    """One online-softmax accumulate over score tile ``s`` (rows, C).

    ``pv_dot(p)`` closes over the V operand (and the int8 p-quantization where
    the cache is int8) and returns the f32 PV partial. Returns (m, l, acc).

    amla=False is the classic multiply rescale (`alpha = e^(m_prev - m_new)`).
    amla=True works in base 2 with the running max on the integer grid: the
    l/acc rescale is `_exp2_rescale` (exponent-field ADD, exact), and only the
    probabilities pay a transcendental (`exp2`). The integer grid costs < 1 bit
    of headroom on p — outputs agree with the multiply path to ulp-scale."""
    if amla:
        s2 = s * LOG2E
        m_new = jnp.maximum(
            m_prev, jnp.ceil(jnp.max(s2, axis=1, keepdims=True)))
        # m_prev starts at NEG_INF: clamp before the i32 cast (the add target
        # is an 8-bit exponent; anything <= -254 flushes to zero anyway)
        delta = jnp.maximum(m_prev - m_new, -254.0)
        p = jnp.exp2(s2 - m_new)
        p = jnp.where(mask, p, 0.0)
        l_new = _exp2_rescale(l_prev, delta) + jnp.sum(p, axis=1, keepdims=True)
        acc = _exp2_rescale(acc_prev, delta) + pv_dot(p)
    else:
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(jnp.minimum(m_prev - m_new, 0.0))
        p = jnp.exp(s - m_new)
        p = jnp.where(mask, p, 0.0)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc = acc_prev * alpha + pv_dot(p)
    return m_new, l_new, acc


def _fold_sinks(m, l, acc, sink, amla: bool):
    """Finalize-time sink fold under the same rescale discipline as the body.

    Shapes broadcast: in-kernel m/l are (rows, 1) against acc (rows, d); the
    jnp-level split merge passes (B, R) against (B, R) with acc handled by the
    caller. Returns (m, l, acc) with the sink folded into l (and acc rescaled
    onto the new max)."""
    if amla:
        s2 = sink * LOG2E
        m_new = jnp.maximum(m, jnp.ceil(s2))
        delta = jnp.maximum(m - m_new, -254.0)
        l_new = _exp2_rescale(l, delta) + jnp.exp2(s2 - m_new)
        acc_new = _exp2_rescale(acc, delta)
    else:
        m_new = jnp.maximum(m, sink)
        alpha = jnp.exp(jnp.minimum(m - m_new, 0.0))
        l_new = alpha * l + jnp.exp(sink - m_new)
        acc_new = acc * alpha
    return m_new, l_new, acc_new


# length-parallel (flash-decode) split: trace-time witness + auto heuristic.
_LENPAR_STATS = {"traces": 0, "split_traces": 0, "auto_engaged": 0,
                 "last_splits": 1}


def lenpar_stats() -> dict:
    """Trace-time length-split witness (bench honesty: `lenpar_invalid`)."""
    return dict(_LENPAR_STATS)


def reset_lenpar_stats() -> None:
    for k in _LENPAR_STATS:
        _LENPAR_STATS[k] = 1 if k == "last_splits" else 0


def _auto_kv_splits(b: int, hkv: int, mb: int, t: int) -> int:
    """Trace-time split auto-select: the long-context bs=1 regime.

    One grid row per (batch row x kv head group) is all the parallelism the
    unsplit attend exposes — at bs=1 a single core serializes the whole KV
    walk. Split the KV length when the row/head product is tiny (<= 4 score
    row-units), the step is plain chain decode (t == 1), and the table is long
    enough that every split still owns >= 8 block groups. TPUINF_LENPAR=0 is
    the trace-time opt-out."""
    if os.environ.get("TPUINF_LENPAR", "1") == "0":
        return 1
    if t != 1 or b * hkv > 4:
        return 1
    s = 1
    while s < 8 and mb // (s * 2) >= 8:
        s *= 2
    return s


def _lenpar_merge(o32, m, l, sink_col, amla: bool, out_dtype):
    """Cross-split LSE merge: ``o32`` (S, B, R, D) f32 raw accumulators,
    ``m``/``l`` (S, B, R) running max / denominator per split (no sink fold,
    no division — the split kernels emit raw flash state).

    A split that saw no live KV leaves (m, l) = (NEG_INF, 0) and drops out of
    the weighted sum with weight exactly 0. When <= 1 split is live the merge
    SELECTS that split's state bit-for-bit (no arithmetic on it) and runs the
    identical finalize the unsplit kernel would — so a row whose live blocks
    sit inside one split is bit-equal to the unsplit kernel. Rows straddling
    splits pay one extra LSE combine (ulp-scale, fp-add order differs from the
    serial walk — see docs/ROUND24_NOTES.md)."""
    S = o32.shape[0]
    live = l > 0.0                                        # (S, B, R)
    nlive = jnp.sum(live.astype(jnp.int32), axis=0)       # (B, R)

    # exact path: bit-preserving select of the single live split
    m1, l1, o1 = m[0], l[0], o32[0]
    taken = live[0]
    for si in range(1, S):
        fresh = jnp.logical_and(live[si], jnp.logical_not(taken))
        m1 = jnp.where(fresh, m[si], m1)
        l1 = jnp.where(fresh, l[si], l1)
        o1 = jnp.where(fresh[..., None], o32[si], o1)
        taken = jnp.logical_or(taken, live[si])
    m1 = jnp.where(taken, m1, NEG_INF)
    if sink_col is not None:
        if amla:
            s2 = sink_col * LOG2E
            m_f = jnp.maximum(m1, jnp.ceil(s2))
            delta = jnp.maximum(m1 - m_f, -254.0)
            l1 = _exp2_rescale(l1, delta) + jnp.exp2(s2 - m_f)
            o1 = _exp2_rescale(o1, delta[..., None])
        else:
            m_f = jnp.maximum(m1, sink_col)
            alpha = jnp.exp(jnp.minimum(m1 - m_f, 0.0))
            l1 = alpha * l1 + jnp.exp(sink_col - m_f)
            o1 = o1 * alpha[..., None]
    exact = o1 / jnp.where(l1 == 0.0, 1.0, l1)[..., None]

    # generic path: weighted LSE combine across live splits
    expfn = jnp.exp2 if amla else jnp.exp
    M = jnp.max(m, axis=0)                                # (B, R)
    sink_s = None
    if sink_col is not None:
        sink_s = sink_col * LOG2E if amla else sink_col
        M = jnp.maximum(M, jnp.ceil(sink_s) if amla else sink_s)
    w = expfn(m - M[None])                                # dead split -> 0
    den = jnp.sum(w * l, axis=0)
    num = jnp.sum(w[..., None] * o32, axis=0)
    if sink_col is not None:
        den = den + expfn(sink_s - M)
    merged = num / jnp.where(den == 0.0, 1.0, den)[..., None]

    return jnp.where((nlive <= 1)[..., None], exact, merged).astype(out_dtype)


# --- paged KV write -------------------------------------------------------------------


def _window_rmw(k_out, v_out, sk, sv, sems, l, blk, w0, pack, edit):
    """One aligned-window RMW against the stacked pool: read both K/V tiles
    into scratch, apply ``edit`` to the scratch, write back. THE write
    primitive every commit path shares (per-token, one-window fused, chunk)."""
    dst_k = k_out.at[l, blk, :, pl.ds(w0, pack), :]
    dst_v = v_out.at[l, blk, :, pl.ds(w0, pack), :]
    pltpu.make_async_copy(dst_k, sk, sems.at[0]).start()
    pltpu.make_async_copy(dst_v, sv, sems.at[1]).start()
    pltpu.make_async_copy(dst_k, sk, sems.at[0]).wait()
    pltpu.make_async_copy(dst_v, sv, sems.at[1]).wait()
    edit()
    pltpu.make_async_copy(sk, dst_k, sems.at[0]).start()
    pltpu.make_async_copy(sv, dst_v, sems.at[1]).start()
    pltpu.make_async_copy(sk, dst_k, sems.at[0]).wait()
    pltpu.make_async_copy(sv, dst_v, sems.at[1]).wait()


def _append_tokens_rmw(slots_ref, new_k_ref, new_v_ref, k_out, v_out, sk, sv,
                       sems, l, b, *, t: int, pack: int, bs: int):
    """Shared t<=8 fresh-token commit: tile-aligned RMW windows, -1 slots dropped.

    The write body of `_paged_write_kernel` (plain decode t=1 and the
    speculative multi-query commit t in 2..8), factored out so the FUSED
    append+attend kernel (`fused_paged_decode_stacked`) commits through the
    exact same windows. The common case — consecutive live slots inside ONE
    aligned pack window — collapses to a single read-modify-write per row
    (4 DMA waits, not 4*t); straddling / dropped / non-consecutive slots fall
    back to the per-token loop."""

    def _rmw(blk, w0, edit):
        _window_rmw(k_out, v_out, sk, sv, sems, l, blk, w0, pack, edit)

    def _per_token():
        for tok in range(t):                   # t is tiny (1 or speculation width)
            slot = slots_ref[b * t + tok]

            @pl.when(slot >= 0)
            def _write(slot=slot, tok=tok):
                blk = slot // bs
                off = slot % bs
                w0 = (off // pack) * pack      # aligned window inside the block

                def edit(off=off, w0=w0, tok=tok):
                    iota = jax.lax.broadcasted_iota(jnp.int32, sk.shape, 1)
                    hit = iota == off - w0
                    sk[:] = jnp.where(hit, new_k_ref[0, :, tok : tok + 1, :],
                                      sk[:])
                    sv[:] = jnp.where(hit, new_v_ref[0, :, tok : tok + 1, :],
                                      sv[:])

                _rmw(blk, w0, edit)

    if t == 1:
        _per_token()
        return

    slot0 = slots_ref[b * t]
    contig = slot0 >= 0
    for tok in range(1, t):
        contig = jnp.logical_and(contig, slots_ref[b * t + tok] == slot0 + tok)
    off0 = slot0 % bs
    # same aligned window => same block (bs % pack == 0, enforced by the caller)
    one_window = jnp.logical_and(contig, off0 // pack == (off0 + t - 1) // pack)

    @pl.when(one_window)
    def _fused():
        blk = slot0 // bs
        w0 = (off0 // pack) * pack

        def edit():
            iota = jax.lax.broadcasted_iota(jnp.int32, sk.shape, 1)
            rel = iota - (off0 - w0)           # window row -> fresh-token index
            for tok in range(t):
                hit = rel == tok
                sk[:] = jnp.where(hit, new_k_ref[0, :, tok : tok + 1, :], sk[:])
                sv[:] = jnp.where(hit, new_v_ref[0, :, tok : tok + 1, :], sv[:])

        _rmw(blk, w0, edit)

    @pl.when(jnp.logical_not(one_window))
    def _straddle():
        _per_token()


def _paged_write_kernel(slots_ref, lidx_ref, live_ref, new_k_ref, new_v_ref,
                        _k_in, _v_in, k_out, v_out, sk, sv, sems, *, t: int,
                        pack: int, bs: int):
    """Per-row scatter of the step's t fresh tokens, tile-aligned RMW.

    t == 1 (plain decode): one RMW window per row. t in {2..8} (the
    speculative multi-query commit): the common case — consecutive live slots
    inside ONE aligned pack window (pack >= 32 for int8/fp8 caches, so a K<=8
    chain straddles a window boundary at most once every pack positions) —
    collapses to a SINGLE read-modify-write per row: 4 DMA waits instead of
    4*t. Rows that straddle a window/block boundary, carry dropped (-1) slots,
    or aren't consecutive fall back to the per-token loop. Dropped slots stay
    predicated off in both paths (the conditional commit: a dead CB slot or a
    masked speculative row writes nothing).

    t > 8 (the CHUNK-length commit of mixed prefill+decode serving steps):
    each row's live slots must be the position-consecutive prefix of the row
    (suffix -1 padding only — the shape make_slot_mapping emits for a
    contiguous token run with a tail valid mask; live counts arrive scalar-
    prefetched in ``live_ref``). The row's run is walked per aligned pack
    window: ONE read-modify-write commits up to ``pack`` tokens (4 DMA waits
    per window instead of per token), and window boundaries coincide with
    position boundaries (bs % pack == 0), so block crossings just change the
    window's destination block."""
    b = pl.program_id(0)
    l = lidx_ref[0]

    if t <= 8:
        _append_tokens_rmw(slots_ref, new_k_ref, new_v_ref, k_out, v_out,
                           sk, sv, sems, l, b, t=t, pack=pack, bs=bs)
        return

    # chunk-length commit (t > 8): consecutive positions, suffix drops only.
    # Walk the run window by window — group boundaries are the positions where
    # slot % pack rolls to 0 (consecutive positions advance off by 1 and
    # bs % pack == 0, so this holds across block crossings too).
    n = live_ref[b]

    @pl.when(n > 0)
    def _chunk():
        base = b * t
        a0 = slots_ref[base] % pack        # first token's offset in its window
        for g in range((t + pack - 1) // pack + 1):
            t0 = jnp.maximum(g * pack - a0, 0)
            t1 = jnp.minimum((g + 1) * pack - a0, n)
            cnt = t1 - t0

            @pl.when(cnt > 0)
            def _one(t0=t0, cnt=cnt):
                s0 = slots_ref[base + t0]
                blk = s0 // bs
                off = s0 % bs
                w0 = (off // pack) * pack

                def edit(off=off, w0=w0, t0=t0, cnt=cnt):
                    iota = jax.lax.broadcasted_iota(jnp.int32, sk.shape, 1)
                    rel = iota - (off - w0)        # window row -> token offset
                    for j in range(pack):          # blends only; one RMW total
                        src = jnp.minimum(t0 + j, t - 1)
                        hit = jnp.logical_and(rel == j, j < cnt)
                        sk[:] = jnp.where(
                            hit, new_k_ref[0, :, pl.ds(src, 1), :], sk[:])
                        sv[:] = jnp.where(
                            hit, new_v_ref[0, :, pl.ds(src, 1), :], sv[:])

                _window_rmw(k_out, v_out, sk, sv, sems, l, blk, w0, pack,
                            edit)


@functools.partial(jax.jit, static_argnames=("interpret",))
def write_paged_stacked_kv(
    k_cache: jnp.ndarray,        # (L, NB, Hkv, BS, D) — donated/aliased in place
    v_cache: jnp.ndarray,
    new_k: jnp.ndarray,          # (B, Hkv, T, D), already in cache dtype
    new_v: jnp.ndarray,
    slot_mapping: jnp.ndarray,   # (B, T) int32 flat slots (block*BS + off); -1 = drop
    layer_idx: jnp.ndarray,      # () int32 layer to write
    interpret: bool = False,
):
    """Scatter the step's K and V rows into the stacked paged cache in one kernel.

    ≈ `write_kv_cache_at_batch_kernel` (`modules/kvcache/utils.py:20-38`) over the
    paged layout: tile-aligned RMW windows, -1 slots dropped. T in {2..8} (the
    speculative multi-query commit) collapses a row's consecutive same-window
    slots into ONE RMW; T > 8 (the chunk-length commit of mixed serving steps)
    walks the row's consecutive run one RMW per aligned pack window — each
    row's live slots must then be a position-consecutive prefix (suffix -1
    padding only; ENFORCED: a non-conforming suffix is dropped like -1 slots,
    never written to the wrong place). See _paged_write_kernel."""
    b, h, t, d = new_k.shape
    bs = k_cache.shape[3]
    pack = _pack(k_cache.dtype)
    if bs % pack != 0:
        raise ValueError(f"pa_block_size {bs} must be a multiple of {pack} for "
                         f"{k_cache.dtype} caches")
    slots = slot_mapping.reshape(b, -1).astype(jnp.int32)
    # per-row live-token counts for the chunk path (t > 8): the length of the
    # longest POSITION-CONSECUTIVE prefix — slot +1 within a block, or a jump
    # to some block's first slot right after a block's last (bs % pack == 0
    # makes those exactly the pack-window boundaries the kernel walks).
    # Clamping here ENFORCES the chunk contract in-graph: a malformed mapping
    # (interior -1, non-consecutive jump) has its non-conforming suffix
    # DROPPED — the defined -1 semantics — instead of corrupting other slots.
    # Tiny and cheap to compute unconditionally, and keeping the operand list
    # fixed keeps one kernel signature across all T
    if t > 1:
        prev, nxt = slots[:, :-1], slots[:, 1:]
        ok = jnp.logical_or(
            nxt == prev + 1,
            jnp.logical_and(nxt % bs == 0,
                            jnp.logical_and(nxt >= 0, prev % bs == bs - 1)))
        run = jnp.concatenate(
            [slots[:, :1] >= 0, jnp.logical_and(ok, slots[:, 1:] >= 0)],
            axis=1)
        live = jnp.sum(jnp.cumprod(run.astype(jnp.int32), axis=1), axis=1)
    else:
        live = jnp.sum((slots >= 0).astype(jnp.int32), axis=1)
    kernel = functools.partial(_paged_write_kernel, t=t, pack=pack, bs=bs)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, h, t, d), lambda bi, *_: (bi, 0, 0, 0)),
            pl.BlockSpec((1, h, t, d), lambda bi, *_: (bi, 0, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[pl.BlockSpec(memory_space=pl.ANY),
                   pl.BlockSpec(memory_space=pl.ANY)],
        scratch_shapes=[
            pltpu.VMEM((h, pack, d), k_cache.dtype),
            pltpu.VMEM((h, pack, d), v_cache.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct(k_cache.shape, k_cache.dtype),
                   jax.ShapeDtypeStruct(v_cache.shape, v_cache.dtype)],
        input_output_aliases={5: 0, 6: 1},   # caches (after 3 prefetch + 2 new)
        interpret=interpret,
    )(slots.reshape(-1), layer_idx.reshape(1).astype(jnp.int32), live,
      new_k, new_v, k_cache, v_cache)


# --- paged decode attention -----------------------------------------------------------


def _paged_attend_kernel_v3(pos_ref, lidx_ref, bt_ref, q_ref, *refs,
                            o_ref=None, m_scratch=None, l_scratch=None,
                            acc_scratch=None, scale: float, bs: int, kb: int,
                            bb: int, num_cells: int, t: int, qr: int,
                            nq: int, hkv: int, window: Optional[int],
                            soft_cap: Optional[float], has_sinks: bool,
                            has_slopes: bool, amla: bool):
    """v3 cell body: FLAT q packing + per-block-group dots, no concat.

    v2 padded each head's q rows to 8 sublanes and concatenated the cell's kb
    blocks into one (hkv*width, D) operand — measured on-chip the cell is
    VPU-epilogue-bound (fp8 was SLOWER than bf16 despite half the DMA), and
    the score matrix was 2x over-padded on rows plus a VMEM concat copy per
    row-unit. v3 packs q as (hkv*n_rep*t, D) rows with NO per-head padding
    (the head index is recovered as row // qr in the mask iota) and runs one
    (nq, hkv*bs) dot + flash update PER BLOCK GROUP straight off each fetched
    block ref: half the score elements, half the MXU flops, zero concat.
    Cross-head score tiles are masked; the masked-zero p rows make the single
    packed p @ V dot exact (same trick as v2)."""
    kv_refs = refs[: 2 * kb * bb]
    idx = 2 * kb * bb
    sinks_ref = slopes_ref = None
    if has_sinks:
        sinks_ref, idx = refs[idx], idx + 1
    if has_slopes:
        slopes_ref, idx = refs[idx], idx + 1

    bi = pl.program_id(0)
    ci = pl.program_id(1)

    @pl.when(ci == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    width = kb * bs
    k_start = ci * width
    d = q_ref.shape[-1]
    cols = hkv * bs

    row_iota = jax.lax.broadcasted_iota(jnp.int32, (nq, cols), 0)
    col_iota = jax.lax.broadcasted_iota(jnp.int32, (nq, cols), 1)
    same_head = (row_iota // qr) == (col_iota // bs)
    tok_idx = (row_iota % qr) % t
    col_off = col_iota % bs

    for j in range(bb):                        # static unroll over batch rows
        pos = pos_ref[bi * bb + j]
        run = k_start <= pos + t - 1           # cell fully beyond the row -> skip
        if window is not None:
            run = jnp.logical_and(run, k_start + width - 1 > pos - window)
        r0 = j * nq

        @pl.when(run)
        def _body(j=j, pos=pos, r0=r0):
            q = q_ref[j]                                   # (nq, d)
            q_pos = pos + tok_idx
            for g in range(kb):
                k = _vmem_cast(kv_refs[2 * (j * kb + g)][0, 0].reshape(cols, d),
                               q.dtype)
                v = _vmem_cast(
                    kv_refs[2 * (j * kb + g) + 1][0, 0].reshape(cols, d),
                    q.dtype)
                kv_pos = k_start + g * bs + col_off
                mask = jnp.logical_and(same_head, kv_pos <= q_pos)
                if window is not None:
                    mask = jnp.logical_and(mask, kv_pos > q_pos - window)

                s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                        preferred_element_type=jnp.float32
                                        ) * scale
                if slopes_ref is not None:
                    s = s - slopes_ref[:, 0:1] * (q_pos - kv_pos).astype(
                        jnp.float32)
                if soft_cap is not None:
                    s = soft_cap * jnp.tanh(s / soft_cap)
                s = jnp.where(mask, s, NEG_INF)

                pv_dot = lambda p, v=v: jax.lax.dot_general(
                    p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                m_new, l_new, acc = _flash_accumulate(
                    s, mask, m_scratch[r0 : r0 + nq, 0:1],
                    l_scratch[r0 : r0 + nq, 0:1], acc_scratch[r0 : r0 + nq],
                    pv_dot, amla)
                m_scratch[r0 : r0 + nq] = jnp.broadcast_to(m_new, (nq, 128))
                l_scratch[r0 : r0 + nq] = jnp.broadcast_to(l_new, (nq, 128))
                acc_scratch[r0 : r0 + nq] = acc

    @pl.when(ci == num_cells - 1)
    def _finalize():
        for j in range(bb):
            r0 = j * nq
            m = m_scratch[r0 : r0 + nq, 0:1]
            l = l_scratch[r0 : r0 + nq, 0:1]
            acc = acc_scratch[r0 : r0 + nq]
            if sinks_ref is not None:
                _, l, acc = _fold_sinks(m, l, acc, sinks_ref[:, 0:1], amla)
            l_safe = jnp.where(l == 0.0, 1.0, l)
            o_ref[j] = (acc / l_safe).reshape(o_ref.shape[1:]).astype(
                o_ref.dtype)


def _paged_attend_kernel(pos_ref, lidx_ref, bt_ref, q_ref, *refs, o_ref=None,
                         m_out=None, l_out=None,
                         m_scratch=None, l_scratch=None, acc_scratch=None,
                         scale: float, bs: int, kb: int, bb: int,
                         num_cells: int, t: int,
                         rows: int, hkv: int, window: Optional[int],
                         soft_cap: Optional[float], has_sinks: bool,
                         has_slopes: bool, amla: bool, splits: int = 1,
                         cps: int = 0):
    """Block-diagonal head packing over ``bb`` batch rows per grid cell.

    Per row: every kv head's q rows stack into ONE (hkv*rows, D) operand and
    the cell's kv blocks into ONE (hkv*width, D) operand, so each row costs
    2 large MXU dots + a single vectorized flash update instead of hkv*kb tiny
    per-head ops (v1 was VPU-serialization-bound: 15.7 ms/step at bs=64).
    Cross-head (off-diagonal) score tiles are masked to -inf — wasted MXU
    flops that the 8x-wider op amortizes, not bandwidth. Batching ``bb`` rows
    per cell amortizes the per-cell grid fixed cost (v2 at bb=1 measured
    ~12 us/cell with only ~3 us of real work).

    ``splits > 1`` is the LENGTH-PARALLEL variant: the grid grows a leading
    KV-split dimension, each split walks its ``cps`` cells of the table with
    its own flash state, and finalize emits the RAW (acc, m, l) per split
    (``m_out``/``l_out``) for the outside cross-split LSE merge — no sink
    fold, no division in-kernel."""
    kv_refs = refs[: 2 * kb * bb]
    idx = 2 * kb * bb
    sinks_ref = slopes_ref = None
    if has_sinks:
        sinks_ref, idx = refs[idx], idx + 1
    if has_slopes:
        slopes_ref, idx = refs[idx], idx + 1

    if splits == 1:
        bi = pl.program_id(0)
        ci = pl.program_id(1)
        cell = ci
        last_cell = num_cells - 1
    else:
        si = pl.program_id(0)
        bi = pl.program_id(1)
        ci = pl.program_id(2)
        cell = si * cps + ci
        last_cell = cps - 1

    @pl.when(ci == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    width = kb * bs                            # kv positions fetched per row
    k_start = cell * width
    nrows = hkv * rows
    d = q_ref.shape[-1]

    for j in range(bb):                        # static unroll over batch rows
        pos = pos_ref[bi * bb + j]
        run = k_start <= pos + t - 1           # cell fully beyond the row -> skip
        if window is not None:
            run = jnp.logical_and(run, k_start + width - 1 > pos - window)
        r0 = j * nrows

        @pl.when(run)
        def _body(j=j, pos=pos, r0=r0):
            q = q_ref[j].reshape(nrows, d)
            k = jnp.concatenate(
                [kv_refs[2 * (j * kb + g)][0, 0] for g in range(kb)], axis=1)
            v = jnp.concatenate(
                [kv_refs[2 * (j * kb + g) + 1][0, 0] for g in range(kb)], axis=1)
            int8_kv = k.dtype == jnp.int8
            k = k.reshape(hkv * width, d)
            v = v.reshape(hkv * width, d)
            if int8_kv:
                # int8 KV (static scales): feed the MXU int8 x int8 directly —
                # no cast of the streamed operands. q rows quantize per-row
                # (tiny), scores rescale by sx; p quantizes to [0, 127] for the
                # PV dot (the cache payload is already K/sigma resp. V/sigma,
                # the per-head sigma fold happens outside the kernel).
                qf = q.astype(jnp.float32)
                sx = jnp.max(jnp.abs(qf), axis=1, keepdims=True) / 127.0
                sx = jnp.maximum(sx, 1e-8)
                q = jnp.clip(jnp.round(qf / sx), -127, 127).astype(jnp.int8)
            else:
                k = _vmem_cast(k, q.dtype)
                v = _vmem_cast(v, q.dtype)

            row_iota = jax.lax.broadcasted_iota(jnp.int32, (nrows, hkv * width), 0)
            col_iota = jax.lax.broadcasted_iota(jnp.int32, (nrows, hkv * width), 1)
            # row r = head * rows + i, token index i % t; K stacking is
            # (hkv, width) row-major, so column c belongs to kv head c // width
            # at in-cell offset c % width
            q_pos = pos + (row_iota % rows) % t
            kv_pos = k_start + col_iota % width
            same_head = (row_iota // rows) == (col_iota // width)
            mask = jnp.logical_and(same_head, kv_pos <= q_pos)
            if window is not None:
                mask = jnp.logical_and(mask, kv_pos > q_pos - window)

            if int8_kv:
                s = jax.lax.dot_general(
                    q, k, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.int32
                ).astype(jnp.float32) * (sx * scale)
            else:
                s = jax.lax.dot_general(
                    q, k, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32) * scale
            if slopes_ref is not None:
                s = s - slopes_ref[:, 0:1] * (q_pos - kv_pos).astype(jnp.float32)
            if soft_cap is not None:
                s = soft_cap * jnp.tanh(s / soft_cap)
            s = jnp.where(mask, s, NEG_INF)

            if int8_kv:
                def pv_dot(p, v=v):
                    pi = jnp.round(p * 127.0).astype(jnp.int8)
                    return jax.lax.dot_general(
                        pi, v, (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.int32
                    ).astype(jnp.float32) * (1.0 / 127.0)
            else:
                pv_dot = lambda p, v=v: jax.lax.dot_general(
                    p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
            m_new, l_new, acc = _flash_accumulate(
                s, mask, m_scratch[r0 : r0 + nrows, 0:1],
                l_scratch[r0 : r0 + nrows, 0:1], acc_scratch[r0 : r0 + nrows],
                pv_dot, amla)
            m_scratch[r0 : r0 + nrows] = jnp.broadcast_to(m_new, (nrows, 128))
            l_scratch[r0 : r0 + nrows] = jnp.broadcast_to(l_new, (nrows, 128))
            acc_scratch[r0 : r0 + nrows] = acc

    @pl.when(ci == last_cell)
    def _finalize():
        for j in range(bb):
            r0 = j * nrows
            m = m_scratch[r0 : r0 + nrows, 0:1]
            l = l_scratch[r0 : r0 + nrows, 0:1]
            acc = acc_scratch[r0 : r0 + nrows]
            if splits > 1:
                # raw per-split flash state; the sink fold and the division
                # happen in the outside cross-split merge
                o_ref[0, j] = acc.reshape(o_ref.shape[2:])
                m_out[0, j] = m_scratch[r0 : r0 + nrows]
                l_out[0, j] = l_scratch[r0 : r0 + nrows]
            else:
                if sinks_ref is not None:
                    _, l, acc = _fold_sinks(m, l, acc, sinks_ref[:, 0:1], amla)
                l_safe = jnp.where(l == 0.0, 1.0, l)
                o_ref[j] = (acc / l_safe).reshape(o_ref.shape[1:]).astype(
                    o_ref.dtype)


def paged_decode_attention_stacked(
    q: jnp.ndarray,              # (B, Hq, T, D), T small (1 or speculation width)
    k_cache: jnp.ndarray,        # (L, NB, Hkv, BS, D) — full stacked paged cache
    v_cache: jnp.ndarray,
    positions: jnp.ndarray,      # (B,) int32 write position of q[:, :, 0]
    layer_idx: jnp.ndarray,      # () int32 layer to attend over
    block_table: jnp.ndarray,    # (B, MB) int32 physical block ids (logical order)
    scale: Optional[float] = None,
    window: Optional[int] = None,
    soft_cap: Optional[float] = None,
    sinks: Optional[jnp.ndarray] = None,         # (Hq,) learned sink logits
    alibi_slopes: Optional[jnp.ndarray] = None,  # (Hq,) ALiBi slopes
    blocks_per_cell: Optional[int] = None,
    rows_per_cell: Optional[int] = None,
    interpret: bool = False,
    variant: int = 2,
    amla: Optional[bool] = None,
    kv_splits: Optional[int] = None,
) -> jnp.ndarray:
    """Ragged paged decode attention (plain wrapper, see the jitted impl below).

    Resolves the trace-time knobs and dispatches to the jitted impl:
    ``amla=None`` reads TPUINF_AMLA (default ON — exponent-add rescaling),
    ``kv_splits=None`` auto-selects the length-parallel split count for the
    long-context small-batch regime (TPUINF_LENPAR=0 opts out). Runs at trace
    time under an enclosing jit, so env toggles between runner builds retrace."""
    b, hq, t, d = q.shape
    hkv = k_cache.shape[2]
    mb = block_table.shape[1]
    amla_r = _amla_default() if amla is None else bool(amla)
    ks = kv_splits if kv_splits is not None else _auto_kv_splits(b, hkv, mb, t)
    if ks > 1 and variant == 3:
        if kv_splits is not None:
            raise ValueError("kv_splits > 1 requires variant=2")
        ks = 1
    _LENPAR_STATS["traces"] += 1
    if ks > 1:
        _LENPAR_STATS["split_traces"] += 1
        _LENPAR_STATS["last_splits"] = ks
        if kv_splits is None:
            _LENPAR_STATS["auto_engaged"] += 1
    return _paged_decode_attention_impl(
        q, k_cache, v_cache, positions, layer_idx, block_table, scale=scale,
        window=window, soft_cap=soft_cap, sinks=sinks,
        alibi_slopes=alibi_slopes, blocks_per_cell=blocks_per_cell,
        rows_per_cell=rows_per_cell, interpret=interpret, variant=variant,
        amla=amla_r, kv_splits=ks)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "window", "soft_cap", "blocks_per_cell",
                     "rows_per_cell", "interpret", "variant", "amla",
                     "kv_splits"))
def _paged_decode_attention_impl(
    q: jnp.ndarray,              # (B, Hq, T, D), T small (1 or speculation width)
    k_cache: jnp.ndarray,        # (L, NB, Hkv, BS, D) — full stacked paged cache
    v_cache: jnp.ndarray,
    positions: jnp.ndarray,      # (B,) int32 write position of q[:, :, 0]
    layer_idx: jnp.ndarray,      # () int32 layer to attend over
    block_table: jnp.ndarray,    # (B, MB) int32 physical block ids (logical order)
    scale: Optional[float] = None,
    window: Optional[int] = None,
    soft_cap: Optional[float] = None,
    sinks: Optional[jnp.ndarray] = None,         # (Hq,) learned sink logits
    alibi_slopes: Optional[jnp.ndarray] = None,  # (Hq,) ALiBi slopes
    blocks_per_cell: Optional[int] = None,
    rows_per_cell: Optional[int] = None,
    interpret: bool = False,
    variant: int = 2,
    amla: bool = True,
    kv_splits: int = 1,
) -> jnp.ndarray:
    """Ragged paged decode attention over one layer of the stacked paged cache.

    Streams each row's physical blocks through its block-table row (BlockSpec index
    maps over the scalar-prefetched table); block groups beyond a row's position are
    clamped to the row's last live block (DMA elided) and predicated off. The fresh
    step's K/V must already be written (write_paged_stacked_kv).

    T = 1 is plain chain decode. T in {2..8} is the MULTI-QUERY (ragged
    verify) shape — the q_len>1 ragged-paged-attention case: the K
    speculative positions of every row attend in ONE pass over the row's
    live blocks (each block group is streamed once for all T queries) with
    an intra-chunk causal mask (q_pos = pos + tok index, kv_pos <= q_pos),
    instead of T single-token attends or a table-width gather that would
    stream the cache T times.
    ``variant``: 2 = head-padded concat cells (the measured default), 3 = flat-q
    per-block-group cells (measured neutral-bf16 / worse-fp8 on v5e at bs=64 —
    kept for other geometries; see _paged_attend_kernel_v3).
    Returns (B, Hq, T, D) in q.dtype."""
    b, hq, t, d = q.shape
    _, nb, hkv, bs, _ = k_cache.shape
    mb = block_table.shape[1]
    if hq % hkv != 0:
        raise ValueError(f"q heads {hq} not divisible by kv heads {hkv}")
    n_rep = hq // hkv
    if scale is None:
        scale = d ** -0.5

    qr = n_rep * t
    if variant == 3:
        nq = _round_up(hkv * qr, 8)
        qg = q.reshape(b, hkv, qr, d).reshape(b, hkv * qr, d)
        if nq != hkv * qr:
            qg = jnp.pad(qg, ((0, 0), (0, nq - hkv * qr), (0, 0)))
        rows = None
    else:
        qg = q.reshape(b, hkv, n_rep, t, d).reshape(b, hkv, n_rep * t, d)
        rows = max(8, _round_up(n_rep * t, 8))
        if rows != n_rep * t:
            qg = jnp.pad(qg, ((0, 0), (0, 0), (0, rows - n_rep * t), (0, 0)))

    # cell geometry (r5 on-chip sweep at bs=64/BS=128/Hkv=8/D=128): batch 4
    # rows per cell to amortize grid fixed cost, and size the per-cell KV
    # footprint to ~2 MB so Mosaic's automatic double-buffering fits in VMEM
    # and block fetches PIPELINE against compute — larger cells (the old
    # 512-position heuristic) serialized DMA with the body (bf16 335 -> 291 us
    # per layer; fp8 405 -> 399, cast-bound).
    kv_itemsize = jnp.dtype(k_cache.dtype).itemsize
    # int8 prefers bigger cells (r5 sweep: 182 us at 4 MB/cell vs 210 at
    # 2 MB — the int8 body is cheap enough that fetch batching wins);
    # bf16/fp8 pipeline best at ~2 MB/cell
    budget = (4 if jnp.dtype(k_cache.dtype) == jnp.int8 else 2) * 2 ** 20
    if rows_per_cell is not None:
        if b % rows_per_cell != 0:
            raise ValueError(f"rows_per_cell {rows_per_cell} must divide {b}")
        bb = rows_per_cell
    else:
        # bound bb so even a kb=1 cell fits the budget (large pa_block_size /
        # many kv heads would otherwise blow VMEM with double-buffering)
        one_block = 2 * hkv * bs * d * kv_itemsize
        bb = 1
        for cand in (4, 2):
            if b % cand == 0 and cand * one_block <= max(budget, one_block):
                bb = cand
                break
    if blocks_per_cell:
        kb = min(mb, blocks_per_cell)
    else:
        per_block = 2 * bb * hkv * bs * d * kv_itemsize
        kb = min(mb, max(1, budget // per_block))
    while mb % kb != 0:
        kb -= 1
    num_cells = mb // kb

    # length-parallel split: shrink until it divides the cell count (and never
    # split the v3 packing — the split kernel is the v2 concat-cell body)
    splits = 1 if variant == 3 else max(1, min(kv_splits, num_cells))
    while num_cells % splits:
        splits -= 1
    cps = num_cells // splits

    def _kv_index_map(j, g):
        def index_map(*a):
            if splits == 1:
                (bi, ci), (pos, lidx, bt) = a[:2], a[2:]
                cell = ci
            else:
                (si, bi, ci), (pos, lidx, bt) = a[:3], a[3:]
                cell = si * cps + ci
            row = bi * bb + j
            gg = cell * kb + g
            # clamp out-of-range fetches to the nearest live block — beyond-live
            # groups to the last live block (this step's fresh tokens reach
            # pos + t - 1) and, under a sliding window, below-window groups to the
            # first in-window block: the repeated (layer, block) tuple matches the
            # neighbouring grid step, so Mosaic elides the DMA and HBM traffic
            # tracks the live (windowed) length, not the table width
            last_live = (pos[row] + t - 1) // bs
            gg = jnp.minimum(gg, last_live)
            if window is not None:
                first_live = jnp.maximum(pos[row] - (window - 1), 0) // bs
                gg = jnp.maximum(gg, jnp.minimum(first_live, last_live))
            return (lidx[0], bt[row, gg], 0, 0, 0)

        return index_map

    kv_specs = []
    for j in range(bb):
        for g in range(kb):
            kv_specs.append(pl.BlockSpec((1, 1, hkv, bs, d), _kv_index_map(j, g)))
            kv_specs.append(pl.BlockSpec((1, 1, hkv, bs, d), _kv_index_map(j, g)))

    if variant == 3:
        kernel = functools.partial(
            _paged_attend_kernel_v3, scale=scale, bs=bs, kb=kb, bb=bb,
            num_cells=num_cells, t=t, qr=qr, nq=nq, hkv=hkv, window=window,
            soft_cap=soft_cap, has_sinks=sinks is not None,
            has_slopes=alibi_slopes is not None, amla=amla)
        q_spec = pl.BlockSpec((bb, nq, d), lambda bi, ci, *_: (bi, 0, 0))
        out_shape = jax.ShapeDtypeStruct((b, nq, d), q.dtype)
        n_scr_rows = bb * nq
        extra_rows = nq
    else:
        kernel = functools.partial(
            _paged_attend_kernel, scale=scale, bs=bs, kb=kb, bb=bb,
            num_cells=num_cells,
            t=t, rows=rows, hkv=hkv, window=window, soft_cap=soft_cap,
            has_sinks=sinks is not None, has_slopes=alibi_slopes is not None,
            amla=amla, splits=splits, cps=cps)
        if splits == 1:
            q_spec = pl.BlockSpec((bb, hkv, rows, d),
                                  lambda bi, ci, *_: (bi, 0, 0, 0))
        else:
            q_spec = pl.BlockSpec((bb, hkv, rows, d),
                                  lambda si, bi, ci, *_: (bi, 0, 0, 0))
        out_shape = jax.ShapeDtypeStruct((b, hkv, rows, d), q.dtype)
        n_scr_rows = bb * hkv * rows
        extra_rows = hkv * rows

    extra_specs, extra_ops = [], []
    for extra in (sinks, alibi_slopes):
        if extra is not None:
            from .flash_decode import _group_head_scalars

            extra_specs.append(
                pl.BlockSpec((extra_rows, 128), lambda bi, ci, *_: (0, 0)))
            grouped = _group_head_scalars(extra, hkv, n_rep, t,
                                          qr if variant == 3 else rows)
            if variant == 3 and nq != hkv * qr:
                grouped = jnp.pad(grouped, ((0, nq - hkv * qr), (0, 0)))
            extra_ops.append(grouped)
    n_extra = len(extra_ops)

    def _kernel(pos_ref, lidx_ref, bt_ref, q_ref, *rest):
        ins = rest[: 2 * kb * bb + n_extra]
        outs = rest[2 * kb * bb + n_extra :]
        if splits == 1:
            o_ref, m_s, l_s, acc_s = outs
            kernel(pos_ref, lidx_ref, bt_ref, q_ref, *ins, o_ref=o_ref,
                   m_scratch=m_s, l_scratch=l_s, acc_scratch=acc_s)
        else:
            o_ref, m_o, l_o, m_s, l_s, acc_s = outs
            kernel(pos_ref, lidx_ref, bt_ref, q_ref, *ins, o_ref=o_ref,
                   m_out=m_o, l_out=l_o, m_scratch=m_s, l_scratch=l_s,
                   acc_scratch=acc_s)

    scratch_shapes = [
        pltpu.VMEM((n_scr_rows, 128), jnp.float32),
        pltpu.VMEM((n_scr_rows, 128), jnp.float32),
        pltpu.VMEM((n_scr_rows, d), jnp.float32),
    ]
    nrows = extra_rows
    if splits == 1:
        grid = (b // bb, num_cells)
        out_specs = pl.BlockSpec(q_spec.block_shape, q_spec.index_map)
        out_shapes = out_shape
    else:
        grid = (splits, b // bb, cps)
        out_specs = [
            pl.BlockSpec((1, bb, hkv, rows, d),
                         lambda si, bi, ci, *_: (si, bi, 0, 0, 0)),
            pl.BlockSpec((1, bb, nrows, 128),
                         lambda si, bi, ci, *_: (si, bi, 0, 0)),
            pl.BlockSpec((1, bb, nrows, 128),
                         lambda si, bi, ci, *_: (si, bi, 0, 0)),
        ]
        out_shapes = [
            jax.ShapeDtypeStruct((splits, b, hkv, rows, d), jnp.float32),
            jax.ShapeDtypeStruct((splits, b, nrows, 128), jnp.float32),
            jax.ShapeDtypeStruct((splits, b, nrows, 128), jnp.float32),
        ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=grid,
        in_specs=[q_spec] + kv_specs + extra_specs,
        out_specs=out_specs,
        scratch_shapes=scratch_shapes,
    )
    # the per-layer cache view (4D) keeps the kv BlockSpecs rank-4; layer selection
    # happens in the index map's first coordinate against the 5D array — pass the 5D
    # cache and fold the layer into the block index map instead of slicing (the whole
    # point is never materializing a layer slice)
    out = pl.pallas_call(
        _kernel,
        grid_spec=grid_spec,
        out_shape=out_shapes,
        interpret=interpret,
    )(positions.astype(jnp.int32), layer_idx.reshape(1).astype(jnp.int32),
      block_table.astype(jnp.int32), qg,
      *([k_cache, v_cache] * (kb * bb)), *extra_ops)

    if splits > 1:
        o32, m_o, l_o = out
        sink_col = extra_ops[0][:, 0] if sinks is not None else None
        out = _lenpar_merge(o32.reshape(splits, b, nrows, d), m_o[..., 0],
                            l_o[..., 0], sink_col, amla, q.dtype)
        out = out.reshape(b, hkv, rows, d)

    if variant == 3:
        out = out[:, : hkv * qr, :].reshape(b, hkv, n_rep, t, d)
    else:
        out = out[:, :, : n_rep * t, :].reshape(b, hkv, n_rep, t, d)
    return out.reshape(b, hq, t, d)


# --- fused KV-append + attend (single-dispatch decode hot path) -----------------------


def _fused_append_attend_kernel(pos_ref, lidx_ref, slots_ref, bt_ref, q_ref,
                                new_k_ref, new_v_ref, *refs, scale: float,
                                bs: int, t: int, qr: int, nq: int, hkv: int,
                                pack: int, pdepth: int,
                                window: Optional[int],
                                soft_cap: Optional[float], has_sinks: bool,
                                has_slopes: bool, amla: bool, splits: int = 1,
                                bps: int = 0):
    """Fused decode body: commit the step's fresh K/V AND attend, one grid row
    per batch row.

    Layout of ``refs``: [sinks?, slopes?, k_in, v_in, o_ref, k_out, v_out,
    ks, vs, wk, wv, m_s, l_s, acc_s, ssem, wsem].

    Three phases per row:
      1. WRITE — the row's t fresh tokens commit through the same tile-aligned
         RMW windows as `_paged_write_kernel` (shared `_append_tokens_rmw`).
         The common one-window case overlaps: the window READ is issued first,
         the blend happens while iotas/scratch init run, and the write-BACK is
         left in flight across the whole attend (waited at row end) — safe
         because the attend never reads fresh lanes from HBM (phase 3 attends
         them from the VMEM operands) and committed lanes are written back
         byte-identical.
      2. STREAM — committed context attends over the row's LIVE blocks only:
         a ``pdepth``-deep manual DMA pipeline (make_async_copy per block,
         wait slot i, compute, refill slot i) walks blocks
         [window_start_block, ceil(pos/bs)). Dead table cells are never
         fetched (the loop bound is the live length, not the table width),
         and block fetches overlap the QK/AV compute explicitly instead of
         relying on the BlockSpec pipeliner's fixed double-buffering.
      3. FRESH — the t fresh tokens attend from the operands with the
         intra-chunk causal mask (kv token j visible to q token i iff j <= i,
         and only if its slot is live), eliminating the separate-kernel
         read-after-write of the just-written block.

    q rows pack FLAT (hkv * n_rep * t, D) with no per-head padding (v3
    packing): row r is kv-head ``r // qr``, token ``(r % qr) % t``.

    ``splits > 1`` is the LENGTH-PARALLEL variant: grid (splits, B), split s
    streams committed blocks [max(blk_lo, s*bps), min(blk_hi, (s+1)*bps)) with
    its own flash state; ONLY split 0 runs the append (phases 1a/1b and the
    straddle fallback) and the fresh-token attend (phase 3) — the TPU grid is
    sequential, so every split-0 write-back drains before later splits stream.
    Finalize emits RAW (acc, m, l) per split for the outside LSE merge."""
    idx = 0
    sinks_ref = slopes_ref = None
    if has_sinks:
        sinks_ref, idx = refs[idx], idx + 1
    if has_slopes:
        slopes_ref, idx = refs[idx], idx + 1
    _k_in, _v_in, o_ref = refs[idx : idx + 3]
    idx += 3
    if splits > 1:
        m_out, l_out = refs[idx : idx + 2]
        idx += 2
    else:
        m_out = l_out = None
    k_out, v_out = refs[idx : idx + 2]
    (ks, vs, wk, wv, m_s, l_s, acc_s, ssem, wsem) = refs[idx + 2 :]

    if splits == 1:
        si = None
        bi = pl.program_id(0)
        on_split0 = None
    else:
        si = pl.program_id(0)
        bi = pl.program_id(1)
        on_split0 = si == 0
    l = lidx_ref[0]
    pos = pos_ref[bi]
    d = q_ref.shape[-1]
    cols = hkv * bs

    # ---- phase 1a: classify the write and issue the window READ early -------
    slot0 = slots_ref[bi * t]
    if t == 1:
        one_window = slot0 >= 0
        fallback = jnp.zeros((), jnp.bool_)    # dead slot writes nothing
    else:
        contig = slot0 >= 0
        for tok in range(1, t):
            contig = jnp.logical_and(contig,
                                     slots_ref[bi * t + tok] == slot0 + tok)
        off0_ = slot0 % bs
        one_window = jnp.logical_and(
            contig, off0_ // pack == (off0_ + t - 1) // pack)
        fallback = jnp.logical_not(one_window)
    blk_w = jnp.maximum(slot0, 0) // bs
    w0 = (jnp.maximum(slot0, 0) % bs // pack) * pack
    dst_k = k_out.at[l, blk_w, :, pl.ds(w0, pack), :]
    dst_v = v_out.at[l, blk_w, :, pl.ds(w0, pack), :]
    if splits > 1:                             # only split 0 owns the append
        one_window = jnp.logical_and(one_window, on_split0)
        fallback = jnp.logical_and(fallback, on_split0)

    @pl.when(one_window)
    def _start_window_read():
        pltpu.make_async_copy(dst_k, wk, wsem.at[0]).start()
        pltpu.make_async_copy(dst_v, wv, wsem.at[1]).start()

    # ---- flash state init + iotas (overlaps the RMW read latency) -----------
    m_s[:] = jnp.full_like(m_s, NEG_INF)
    l_s[:] = jnp.zeros_like(l_s)
    acc_s[:] = jnp.zeros_like(acc_s)

    q = q_ref[0]                                           # (nq, d)
    int8_kv = jnp.dtype(k_out.dtype) == jnp.int8
    if int8_kv:
        # int8 KV (static scales): MXU int8 x int8 — same discipline as the
        # separate attend kernel; per-row q quantization happens once
        qf = q.astype(jnp.float32)
        sx = jnp.max(jnp.abs(qf), axis=1, keepdims=True) / 127.0
        sx = jnp.maximum(sx, 1e-8)
        qq = jnp.clip(jnp.round(qf / sx), -127, 127).astype(jnp.int8)
    else:
        qq = sx = None

    row_iota = jax.lax.broadcasted_iota(jnp.int32, (nq, cols), 0)
    col_iota = jax.lax.broadcasted_iota(jnp.int32, (nq, cols), 1)
    same_head = (row_iota // qr) == (col_iota // bs)
    tok_idx = (row_iota % qr) % t
    q_pos = pos + tok_idx                                  # (nq, cols)
    col_off = col_iota % bs

    def _flash_update(kmat, vmat, mask, s_extra_pos=None):
        """One flash step over (nq, C) score columns; kmat/vmat are (C, d) in
        the cache dtype. ``s_extra_pos`` = (q_pos - kv_pos) for ALiBi."""
        if int8_kv:
            s = jax.lax.dot_general(
                qq, kmat, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.int32
            ).astype(jnp.float32) * (sx * scale)
        else:
            s = jax.lax.dot_general(
                q, _vmem_cast(kmat, q.dtype), (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
        if slopes_ref is not None:
            s = s - slopes_ref[:, 0:1] * s_extra_pos.astype(jnp.float32)
        if soft_cap is not None:
            s = soft_cap * jnp.tanh(s / soft_cap)
        s = jnp.where(mask, s, NEG_INF)
        if int8_kv:
            def pv_dot(p, vmat=vmat):
                pi = jnp.round(p * 127.0).astype(jnp.int8)
                return jax.lax.dot_general(
                    pi, vmat, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.int32
                ).astype(jnp.float32) * (1.0 / 127.0)
        else:
            pv_dot = lambda p, vmat=vmat: jax.lax.dot_general(
                p.astype(q.dtype), _vmem_cast(vmat, q.dtype),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        m_new, l_new, acc = _flash_accumulate(
            s, mask, m_s[:, 0:1], l_s[:, 0:1], acc_s[:], pv_dot, amla)
        acc_s[:] = acc
        m_s[:] = jnp.broadcast_to(m_new, (nq, 128))
        l_s[:] = jnp.broadcast_to(l_new, (nq, 128))

    # ---- phase 1b: blend the fresh tokens, leave the write-back in flight ---
    @pl.when(one_window)
    def _blend_and_write_back():
        pltpu.make_async_copy(dst_k, wk, wsem.at[0]).wait()
        pltpu.make_async_copy(dst_v, wv, wsem.at[1]).wait()
        iota = jax.lax.broadcasted_iota(jnp.int32, wk.shape, 1)
        rel = iota - (jnp.maximum(slot0, 0) % bs - w0)
        for tok in range(t):
            hit = rel == tok
            wk[:] = jnp.where(hit, new_k_ref[0, :, tok : tok + 1, :], wk[:])
            wv[:] = jnp.where(hit, new_v_ref[0, :, tok : tok + 1, :], wv[:])
        pltpu.make_async_copy(wk, dst_k, wsem.at[0]).start()
        pltpu.make_async_copy(wv, dst_v, wsem.at[1]).start()

    if t > 1:
        @pl.when(fallback)
        def _straddle_write():
            # straddling / dropped / non-consecutive slots: the shared
            # synchronous per-token RMW loop (rare — at most once every
            # ``pack`` positions per row)
            _append_tokens_rmw(slots_ref, new_k_ref, new_v_ref, k_out, v_out,
                               wk, wv, wsem, l, bi, t=t, pack=pack, bs=bs)

    # ---- phase 2: stream the committed blocks (live length only) ------------
    blk_hi = (pos + bs - 1) // bs              # ceil(pos / bs): kv_pos < pos
    if window is not None:
        blk_lo = jnp.maximum(pos - (window - 1), 0) // bs
        blk_lo = jnp.minimum(blk_lo, blk_hi)
    else:
        blk_lo = jnp.zeros((), jnp.int32)
    if splits > 1:                             # this split's slice of the walk
        blk_lo = jnp.maximum(blk_lo, si * bps)
        blk_hi = jnp.minimum(blk_hi, (si + 1) * bps)

    def _stream_dma(i, slot):
        pb = bt_ref[bi, i]
        return (pltpu.make_async_copy(k_out.at[l, pb], ks.at[slot],
                                      ssem.at[0, slot]),
                pltpu.make_async_copy(v_out.at[l, pb], vs.at[slot],
                                      ssem.at[1, slot]))

    for j in range(pdepth):                    # warm-up: fill the pipeline
        @pl.when(blk_lo + j < blk_hi)
        def _warm(j=j):
            i = blk_lo + j
            dk, dv = _stream_dma(i, i % pdepth)
            dk.start()
            dv.start()

    def _stream_body(i, _):
        slot = jax.lax.rem(i, pdepth)
        dk, dv = _stream_dma(i, slot)
        dk.wait()
        dv.wait()
        kmat = ks[slot].reshape(cols, d)
        vmat = vs[slot].reshape(cols, d)
        kv_pos = i * bs + col_off
        mask = jnp.logical_and(same_head, kv_pos < pos)
        if window is not None:
            mask = jnp.logical_and(mask, kv_pos > q_pos - window)
        _flash_update(kmat, vmat, mask,
                      s_extra_pos=(q_pos - kv_pos) if has_slopes else None)

        @pl.when(i + pdepth < blk_hi)
        def _refill():
            nk, nv = _stream_dma(i + pdepth, slot)
            nk.start()
            nv.start()

        return 0

    jax.lax.fori_loop(blk_lo, blk_hi, _stream_body, 0)

    # ---- phase 3: the fresh tokens attend from the operands (split 0 only) --
    def _fresh_attend():
        cols_f = hkv * t
        kf = new_k_ref[0].reshape(cols_f, d)
        vf = new_v_ref[0].reshape(cols_f, d)
        row_f = jax.lax.broadcasted_iota(jnp.int32, (nq, cols_f), 0)
        col_f = jax.lax.broadcasted_iota(jnp.int32, (nq, cols_f), 1)
        tok_f = col_f % t
        mask_f = jnp.logical_and((row_f // qr) == (col_f // t),
                                 tok_f <= (row_f % qr) % t)
        live_f = jnp.zeros((nq, cols_f), jnp.bool_)
        for j in range(t):
            live_f = jnp.logical_or(
                live_f, jnp.logical_and(tok_f == j, slots_ref[bi * t + j] >= 0))
        mask_f = jnp.logical_and(mask_f, live_f)
        q_pos_f = pos + (row_f % qr) % t
        kv_pos_f = pos + tok_f
        if window is not None:
            mask_f = jnp.logical_and(mask_f, kv_pos_f > q_pos_f - window)
        _flash_update(kf, vf, mask_f,
                      s_extra_pos=(q_pos_f - kv_pos_f) if has_slopes else None)

    if splits == 1:
        _fresh_attend()
    else:
        pl.when(on_split0)(_fresh_attend)

    # ---- finalize -----------------------------------------------------------
    if splits > 1:
        # raw per-split flash state for the outside cross-split merge
        o_ref[0, 0] = acc_s[:]
        m_out[0, 0] = m_s[:]
        l_out[0, 0] = l_s[:]
    else:
        m = m_s[:, 0:1]
        lsum = l_s[:, 0:1]
        acc = acc_s[:]
        if sinks_ref is not None:
            _, lsum, acc = _fold_sinks(m, lsum, acc, sinks_ref[:, 0:1], amla)
        l_safe = jnp.where(lsum == 0.0, 1.0, lsum)
        o_ref[0] = (acc / l_safe).astype(o_ref.dtype)

    @pl.when(one_window)
    def _drain_write_back():
        pltpu.make_async_copy(wk, dst_k, wsem.at[0]).wait()
        pltpu.make_async_copy(wv, dst_v, wsem.at[1]).wait()


# Process-wide prefetch-depth override (serving/knobs.py `prefetch_depth`).
# Resolved in the NON-jitted wrapper below so the value rides the jit cache
# as a static argname: setting it mints a new executable on the next trace;
# dispatches already traced keep their depth (schedule-only, never a stream
# change). None = the per-dtype VMEM-budget auto policy in the impl.
_PREFETCH_DEPTH_OVERRIDE: Optional[int] = None


def set_prefetch_depth(depth: Optional[int]) -> None:
    """Set (or with ``None`` clear) the process-wide prefetch-depth override
    for `fused_paged_decode_stacked` callers that do not pass one
    explicitly. Takes effect on the next (re)trace of a calling step."""
    global _PREFETCH_DEPTH_OVERRIDE
    _PREFETCH_DEPTH_OVERRIDE = None if not depth else int(depth)


def get_prefetch_depth() -> Optional[int]:
    return _PREFETCH_DEPTH_OVERRIDE


def fused_paged_decode_stacked(
    q: jnp.ndarray,              # (B, Hq, T, D), T <= 8 (1 or speculation width)
    new_k: jnp.ndarray,          # (B, Hkv, T, D), already in cache dtype
    new_v: jnp.ndarray,
    k_cache: jnp.ndarray,        # (L, NB, Hkv, BS, D) — donated/aliased in place
    v_cache: jnp.ndarray,
    positions: jnp.ndarray,      # (B,) int32 write position of q[:, :, 0]
    slot_mapping: jnp.ndarray,   # (B, T) int32 flat slots (block*BS + off); -1 = drop
    layer_idx: jnp.ndarray,      # () int32 layer to serve
    block_table: jnp.ndarray,    # (B, MB) int32 physical block ids (logical order)
    scale: Optional[float] = None,
    window: Optional[int] = None,
    soft_cap: Optional[float] = None,
    sinks: Optional[jnp.ndarray] = None,         # (Hq,) learned sink logits
    alibi_slopes: Optional[jnp.ndarray] = None,  # (Hq,) ALiBi slopes
    prefetch_depth: Optional[int] = None,
    interpret: bool = False,
    amla: Optional[bool] = None,
    kv_splits: Optional[int] = None,
):
    """Fused KV-append + attend (plain wrapper, see the jitted impl below).

    Resolves the trace-time knobs (TPUINF_AMLA / TPUINF_LENPAR, see
    `paged_decode_attention_stacked`) and dispatches to the jitted impl."""
    b, hq, t, d = q.shape
    hkv = k_cache.shape[2]
    mb = block_table.shape[1]
    if prefetch_depth is None:
        prefetch_depth = _PREFETCH_DEPTH_OVERRIDE
    amla_r = _amla_default() if amla is None else bool(amla)
    ks = kv_splits if kv_splits is not None else _auto_kv_splits(b, hkv, mb, t)
    _LENPAR_STATS["traces"] += 1
    if ks > 1:
        _LENPAR_STATS["split_traces"] += 1
        _LENPAR_STATS["last_splits"] = ks
        if kv_splits is None:
            _LENPAR_STATS["auto_engaged"] += 1
    return _fused_paged_decode_impl(
        q, new_k, new_v, k_cache, v_cache, positions, slot_mapping, layer_idx,
        block_table, scale=scale, window=window, soft_cap=soft_cap,
        sinks=sinks, alibi_slopes=alibi_slopes, prefetch_depth=prefetch_depth,
        interpret=interpret, amla=amla_r, kv_splits=ks)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "window", "soft_cap", "prefetch_depth",
                     "interpret", "amla", "kv_splits"))
def _fused_paged_decode_impl(
    q: jnp.ndarray,              # (B, Hq, T, D), T <= 8 (1 or speculation width)
    new_k: jnp.ndarray,          # (B, Hkv, T, D), already in cache dtype
    new_v: jnp.ndarray,
    k_cache: jnp.ndarray,        # (L, NB, Hkv, BS, D) — donated/aliased in place
    v_cache: jnp.ndarray,
    positions: jnp.ndarray,      # (B,) int32 write position of q[:, :, 0]
    slot_mapping: jnp.ndarray,   # (B, T) int32 flat slots (block*BS + off); -1 = drop
    layer_idx: jnp.ndarray,      # () int32 layer to serve
    block_table: jnp.ndarray,    # (B, MB) int32 physical block ids (logical order)
    scale: Optional[float] = None,
    window: Optional[int] = None,
    soft_cap: Optional[float] = None,
    sinks: Optional[jnp.ndarray] = None,         # (Hq,) learned sink logits
    alibi_slopes: Optional[jnp.ndarray] = None,  # (Hq,) ALiBi slopes
    prefetch_depth: Optional[int] = None,
    interpret: bool = False,
    amla: bool = True,
    kv_splits: int = 1,
):
    """FUSED KV-append + ragged paged attend: one pallas call serves the layer.

    ≈ the reference TKG hot path collapsed to a single kernel: what
    `write_paged_stacked_kv` + `paged_decode_attention_stacked` did in TWO
    dispatches per layer — with the attend RE-READING the block the write had
    just committed — happens in one. Exact same math: the fresh tokens are
    written through the identical RMW windows AND attended from the VMEM
    operands (never read back from HBM), so per step the cache is streamed
    ONCE at each row's live length. Committed blocks stream through a
    ``prefetch_depth``-deep manual DMA pipeline (explicit double/multi-
    buffering against the QK/AV compute) instead of the BlockSpec pipeliner.

    CONTRACT: rows whose slots are dropped (-1) do not write, and their fresh
    tokens are masked OUT of the attend — a dead serving slot's output row is
    unspecified-but-finite (the separate-kernel path attends whatever stale
    bytes sit at those cache positions instead; live rows are bit-exact
    between the two paths, dead rows are discarded by the host either way).

    Returns (attn (B, Hq, T, D) in q.dtype, k_cache, v_cache)."""
    b, hq, t, d = q.shape
    if t > 8:
        raise ValueError(f"fused append+attend serves decode rows (T <= 8), "
                         f"got T={t}")
    _, nb, hkv, bs, _ = k_cache.shape
    mb = block_table.shape[1]
    if hq % hkv != 0:
        raise ValueError(f"q heads {hq} not divisible by kv heads {hkv}")
    pack = _pack(k_cache.dtype)
    if bs % pack != 0:
        raise ValueError(f"pa_block_size {bs} must be a multiple of {pack} for "
                         f"{k_cache.dtype} caches")
    n_rep = hq // hkv
    if scale is None:
        scale = d ** -0.5
    qr = n_rep * t
    nq = _round_up(hkv * qr, 8)
    qg = q.reshape(b, hkv * qr, d)
    if nq != hkv * qr:
        qg = jnp.pad(qg, ((0, 0), (0, nq - hkv * qr), (0, 0)))

    kv_itemsize = jnp.dtype(k_cache.dtype).itemsize
    if prefetch_depth is not None:
        pdepth = prefetch_depth
    else:
        # pipeline depth: keep ~the separate kernel's per-cell VMEM budget in
        # flight (int8 4 MB / bf16+fp8 2 MB — the r5 sweep's pipelining
        # sweet spots), power of two for the cheap slot modulo
        budget = (4 if jnp.dtype(k_cache.dtype) == jnp.int8 else 2) * 2 ** 20
        per_block = 2 * hkv * bs * d * kv_itemsize
        pdepth = 2
        while pdepth * 2 <= max(2, budget // per_block) and pdepth < 8:
            pdepth *= 2

    extra_specs, extra_ops = [], []
    for extra in (sinks, alibi_slopes):
        if extra is not None:
            from .flash_decode import _group_head_scalars

            grouped = _group_head_scalars(extra, hkv, n_rep, t, qr)
            if nq != hkv * qr:
                grouped = jnp.pad(grouped, ((0, nq - hkv * qr), (0, 0)))
            extra_specs.append(
                pl.BlockSpec((nq, 128), lambda bi, *_: (0, 0)))
            extra_ops.append(grouped)
    n_extra = len(extra_ops)

    splits = max(1, min(kv_splits, mb))
    bps = -(-mb // splits)                     # static blocks per split

    kernel = functools.partial(
        _fused_append_attend_kernel, scale=scale, bs=bs, t=t, qr=qr, nq=nq,
        hkv=hkv, pack=pack, pdepth=pdepth, window=window, soft_cap=soft_cap,
        has_sinks=sinks is not None, has_slopes=alibi_slopes is not None,
        amla=amla, splits=splits, bps=bps)

    if splits == 1:
        grid = (b,)
        qim = lambda bi, *_: (bi, 0, 0)
        kvim = lambda bi, *_: (bi, 0, 0, 0)
        out_specs = [
            pl.BlockSpec((1, nq, d), lambda bi, *_: (bi, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ]
        out_shapes = [jax.ShapeDtypeStruct((b, nq, d), q.dtype),
                      jax.ShapeDtypeStruct(k_cache.shape, k_cache.dtype),
                      jax.ShapeDtypeStruct(v_cache.shape, v_cache.dtype)]
        aliases = {7 + n_extra: 1, 8 + n_extra: 2}
    else:
        grid = (splits, b)
        qim = lambda si, bi, *_: (bi, 0, 0)
        kvim = lambda si, bi, *_: (bi, 0, 0, 0)
        out_specs = [
            pl.BlockSpec((1, 1, nq, d), lambda si, bi, *_: (si, bi, 0, 0)),
            pl.BlockSpec((1, 1, nq, 128), lambda si, bi, *_: (si, bi, 0, 0)),
            pl.BlockSpec((1, 1, nq, 128), lambda si, bi, *_: (si, bi, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ]
        out_shapes = [jax.ShapeDtypeStruct((splits, b, nq, d), jnp.float32),
                      jax.ShapeDtypeStruct((splits, b, nq, 128), jnp.float32),
                      jax.ShapeDtypeStruct((splits, b, nq, 128), jnp.float32),
                      jax.ShapeDtypeStruct(k_cache.shape, k_cache.dtype),
                      jax.ShapeDtypeStruct(v_cache.shape, v_cache.dtype)]
        aliases = {7 + n_extra: 3, 8 + n_extra: 4}

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, nq, d), qim),
            pl.BlockSpec((1, hkv, t, d), kvim),
            pl.BlockSpec((1, hkv, t, d), kvim),
        ] + extra_specs + [
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((pdepth, hkv, bs, d), k_cache.dtype),
            pltpu.VMEM((pdepth, hkv, bs, d), v_cache.dtype),
            pltpu.VMEM((hkv, pack, d), k_cache.dtype),
            pltpu.VMEM((hkv, pack, d), v_cache.dtype),
            pltpu.VMEM((nq, 128), jnp.float32),
            pltpu.VMEM((nq, 128), jnp.float32),
            pltpu.VMEM((nq, d), jnp.float32),
            pltpu.SemaphoreType.DMA((2, pdepth)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    outs = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shapes,
        # caches alias in place (after 4 prefetch + q/new_k/new_v + extras)
        input_output_aliases=aliases,
        interpret=interpret,
    )(positions.astype(jnp.int32), layer_idx.reshape(1).astype(jnp.int32),
      slot_mapping.reshape(-1).astype(jnp.int32), block_table.astype(jnp.int32),
      qg, new_k, new_v, *extra_ops, k_cache, v_cache)

    if splits == 1:
        out, kc, vc = outs
    else:
        o32, m_o, l_o, kc, vc = outs
        sink_col = extra_ops[0][:, 0] if sinks is not None else None
        out = _lenpar_merge(o32, m_o[..., 0], l_o[..., 0], sink_col, amla,
                            q.dtype)

    out = out[:, : hkv * qr, :].reshape(b, hkv, n_rep, t, d)
    return out.reshape(b, hq, t, d), kc, vc


# --- mixed-step ragged paged attention ------------------------------------------------


def _paged_mixed_attend_kernel(pos_ref, qlen_ref, lidx_ref, bt_ref, q_ref,
                               *refs, o_ref=None, m_scratch=None,
                               l_scratch=None, acc_scratch=None, scale: float,
                               bs: int, kb: int, num_cells: int, qt: int,
                               hq: int, n_rep: int, hkv: int, tr: int,
                               window: Optional[int],
                               soft_cap: Optional[float], has_sinks: bool,
                               has_slopes: bool, amla: bool):
    """Mixed-step cell body: per-row VARIABLE q_len over token-major q tiles.

    Grid is (row, q_tile, kv_cell). q rows pack token-major — row r of a tile
    is q head ``r % hq`` of token ``tile0 + r // hq`` — so a q tile is ``qt``
    whole tokens and tiling never splits a head group. Decode rows (q_len 1)
    run only tile 0 and only the cells at or below their position; prefill-
    chunk rows (q_len up to the chunk bucket) run the causal triangle: tile
    qi skips every cell beyond ``pos + min(q_len, (qi+1)*qt) - 1``, and the
    clamped kv index map turns the skipped fetches into elided DMAs — HBM
    traffic tracks each row's LIVE length exactly as in the q_len=1 kernel.
    Rows/tokens at or beyond q_len are masked (l stays 0 -> output rows 0)."""
    kv_refs = refs[: 2 * kb]
    idx = 2 * kb
    sinks_ref = slopes_ref = None
    if has_sinks:
        sinks_ref, idx = refs[idx], idx + 1
    if has_slopes:
        slopes_ref, idx = refs[idx], idx + 1

    bi = pl.program_id(0)
    qi = pl.program_id(1)
    ci = pl.program_id(2)

    @pl.when(ci == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    width = kb * bs
    k_start = ci * width
    d = q_ref.shape[-1]
    cols = hkv * bs

    pos = pos_ref[bi]
    qlen = qlen_ref[bi]
    tile0 = qi * qt                       # first token of this q tile
    tile_max_q = pos + jnp.minimum(qlen, tile0 + qt) - 1
    run = jnp.logical_and(tile0 < qlen, k_start <= tile_max_q)
    if window is not None:
        run = jnp.logical_and(run, k_start + width - 1 > pos + tile0 - window)

    row_iota = jax.lax.broadcasted_iota(jnp.int32, (tr, cols), 0)
    col_iota = jax.lax.broadcasted_iota(jnp.int32, (tr, cols), 1)
    tok = tile0 + row_iota // hq          # global in-chunk token index
    same_head = ((row_iota % hq) // n_rep) == (col_iota // bs)
    col_off = col_iota % bs

    @pl.when(run)
    def _body():
        q = q_ref[0]                                   # (tr, d)
        q_pos = pos + tok
        live = tok < qlen
        int8_kv = jnp.dtype(kv_refs[0].dtype) == jnp.int8
        if int8_kv:
            # int8 KV (static scales): MXU int8 x int8, per-row q quantization
            # — same discipline as the q_len<=8 kernel
            qf = q.astype(jnp.float32)
            sx = jnp.max(jnp.abs(qf), axis=1, keepdims=True) / 127.0
            sx = jnp.maximum(sx, 1e-8)
            qq = jnp.clip(jnp.round(qf / sx), -127, 127).astype(jnp.int8)
        for g in range(kb):
            k = kv_refs[2 * g][0, 0].reshape(cols, d)
            v = kv_refs[2 * g + 1][0, 0].reshape(cols, d)
            kv_pos = k_start + g * bs + col_off
            mask = jnp.logical_and(jnp.logical_and(same_head, live),
                                   kv_pos <= q_pos)
            if window is not None:
                mask = jnp.logical_and(mask, kv_pos > q_pos - window)

            if int8_kv:
                s = jax.lax.dot_general(
                    qq, k, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.int32
                ).astype(jnp.float32) * (sx * scale)
            else:
                k = _vmem_cast(k, q.dtype)
                s = jax.lax.dot_general(
                    q, k, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32) * scale
            if slopes_ref is not None:
                s = s - slopes_ref[:, 0:1] * (q_pos - kv_pos).astype(
                    jnp.float32)
            if soft_cap is not None:
                s = soft_cap * jnp.tanh(s / soft_cap)
            s = jnp.where(mask, s, NEG_INF)

            if int8_kv:
                def pv_dot(p, v=v):
                    pi = jnp.round(p * 127.0).astype(jnp.int8)
                    return jax.lax.dot_general(
                        pi, v, (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.int32
                    ).astype(jnp.float32) * (1.0 / 127.0)
            else:
                v = _vmem_cast(v, q.dtype)
                pv_dot = lambda p, v=v: jax.lax.dot_general(
                    p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
            m_new, l_new, acc = _flash_accumulate(
                s, mask, m_scratch[:, 0:1], l_scratch[:, 0:1], acc_scratch[:],
                pv_dot, amla)
            acc_scratch[:] = acc
            m_scratch[:] = jnp.broadcast_to(m_new, (tr, 128))
            l_scratch[:] = jnp.broadcast_to(l_new, (tr, 128))

    @pl.when(ci == num_cells - 1)
    def _finalize():
        m = m_scratch[:, 0:1]
        l = l_scratch[:, 0:1]
        acc = acc_scratch[:]
        if sinks_ref is not None:
            _, l, acc = _fold_sinks(m, l, acc, sinks_ref[:, 0:1], amla)
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc / l_safe).astype(o_ref.dtype)


def paged_mixed_attention_stacked(
    q: jnp.ndarray,              # (B, Hq, T, D), T = chunk bucket (e.g. 64..256)
    k_cache: jnp.ndarray,        # (L, NB, Hkv, BS, D) — full stacked paged cache
    v_cache: jnp.ndarray,
    positions: jnp.ndarray,      # (B,) int32 position of q[:, :, 0]
    q_lens: jnp.ndarray,         # (B,) int32 live queries per row (1..T)
    layer_idx: jnp.ndarray,      # () int32 layer to attend over
    block_table: jnp.ndarray,    # (B, MB) int32 physical block ids (logical order)
    scale: Optional[float] = None,
    window: Optional[int] = None,
    soft_cap: Optional[float] = None,
    sinks: Optional[jnp.ndarray] = None,         # (Hq,) learned sink logits
    alibi_slopes: Optional[jnp.ndarray] = None,  # (Hq,) ALiBi slopes
    blocks_per_cell: Optional[int] = None,
    q_tile: Optional[int] = None,
    interpret: bool = False,
    amla: Optional[bool] = None,
) -> jnp.ndarray:
    """Mixed-step attention (plain wrapper): resolves TPUINF_AMLA at trace
    time and dispatches to the jitted impl. The mixed kernel is never
    length-split (chunk rows already expose q-tile grid parallelism)."""
    amla_r = _amla_default() if amla is None else bool(amla)
    return _paged_mixed_attention_impl(
        q, k_cache, v_cache, positions, q_lens, layer_idx, block_table,
        scale=scale, window=window, soft_cap=soft_cap, sinks=sinks,
        alibi_slopes=alibi_slopes, blocks_per_cell=blocks_per_cell,
        q_tile=q_tile, interpret=interpret, amla=amla_r)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "window", "soft_cap", "blocks_per_cell",
                     "q_tile", "interpret", "amla"))
def _paged_mixed_attention_impl(
    q: jnp.ndarray,              # (B, Hq, T, D), T = chunk bucket (e.g. 64..256)
    k_cache: jnp.ndarray,        # (L, NB, Hkv, BS, D) — full stacked paged cache
    v_cache: jnp.ndarray,
    positions: jnp.ndarray,      # (B,) int32 position of q[:, :, 0]
    q_lens: jnp.ndarray,         # (B,) int32 live queries per row (1..T)
    layer_idx: jnp.ndarray,      # () int32 layer to attend over
    block_table: jnp.ndarray,    # (B, MB) int32 physical block ids (logical order)
    scale: Optional[float] = None,
    window: Optional[int] = None,
    soft_cap: Optional[float] = None,
    sinks: Optional[jnp.ndarray] = None,         # (Hq,) learned sink logits
    alibi_slopes: Optional[jnp.ndarray] = None,  # (Hq,) ALiBi slopes
    blocks_per_cell: Optional[int] = None,
    q_tile: Optional[int] = None,
    interpret: bool = False,
    amla: bool = True,
) -> jnp.ndarray:
    """MIXED-STEP ragged paged attention: per-row variable q_len in one kernel.

    The mixed prefill+decode serving shape (≈ "Ragged Paged Attention", PAPERS.md):
    decode rows carry q_len 1, prefill-chunk rows carry q_len up to the chunk
    bucket T, all in one dispatch. Per row, the q_lens[b] live queries attend
    causally over the row's blocks — q token i at position positions[b] + i sees
    kv positions <= its own (the in-chunk causal triangle plus all committed
    context); the chunk's fresh K/V must already be written
    (write_paged_stacked_kv). Tokens at or beyond q_lens[b] are padding: masked
    in-kernel, output rows zero, and their KV writes must carry slot -1.

    Generalizes paged_decode_attention_stacked's uniform multi-query attend
    (q_len 2..8, the speculative verify) to chunk-length ragged rows with
    q-tiling: token-major q tiles of ``qt`` tokens bound the score tile to
    (qt*Hq, Hkv*BS) VMEM whatever T is, and per-(row, tile) cell skipping keeps
    HBM traffic on each row's causal live length — a decode row costs exactly
    the q_len=1 kernel's traffic, never the table width.
    Returns (B, Hq, T, D) in q.dtype."""
    b, hq, t, d = q.shape
    _, nb, hkv, bs, _ = k_cache.shape
    mb = block_table.shape[1]
    if hq % hkv != 0:
        raise ValueError(f"q heads {hq} not divisible by kv heads {hkv}")
    n_rep = hq // hkv
    if scale is None:
        scale = d ** -0.5

    # q tile: whole tokens, (qt * hq) rows, sublane-aligned. ~128 rows per tile
    # keeps the (tr, hkv*bs) score tile ~0.5 MB fp32 at serving geometry.
    if q_tile is not None:
        qt = q_tile
    else:
        qt = max(1, 128 // hq)
    while (qt * hq) % 8 != 0:
        qt += 1
    tr = qt * hq
    nqt = -(-t // qt)
    t_pad = nqt * qt

    # token-major packing: row r of a tile = q head r % hq of token r // hq
    qg = q.transpose(0, 2, 1, 3).reshape(b, t * hq, d)
    if t_pad != t:
        qg = jnp.pad(qg, ((0, 0), (0, (t_pad - t) * hq), (0, 0)))

    kv_itemsize = jnp.dtype(k_cache.dtype).itemsize
    budget = (4 if jnp.dtype(k_cache.dtype) == jnp.int8 else 2) * 2 ** 20
    if blocks_per_cell:
        kb = min(mb, blocks_per_cell)
    else:
        per_block = 2 * hkv * bs * d * kv_itemsize
        kb = min(mb, max(1, budget // per_block))
    while mb % kb != 0:
        kb -= 1
    num_cells = mb // kb

    def _kv_index_map(g):
        def index_map(bi, qi, ci, pos, qlen, lidx, bt):
            gg = ci * kb + g
            # clamp to the TILE's live end: cells beyond it repeat the previous
            # grid step's (layer, block) tuple, so Mosaic elides the DMA
            live_end = (pos[bi]
                        + jnp.maximum(jnp.minimum(qlen[bi], (qi + 1) * qt), 1)
                        - 1)
            last_live = live_end // bs
            gg = jnp.minimum(gg, last_live)
            if window is not None:
                first_live = jnp.maximum(
                    pos[bi] + qi * qt - (window - 1), 0) // bs
                gg = jnp.maximum(gg, jnp.minimum(first_live, last_live))
            return (lidx[0], bt[bi, gg], 0, 0, 0)

        return index_map

    kv_specs = []
    for g in range(kb):
        kv_specs.append(pl.BlockSpec((1, 1, hkv, bs, d), _kv_index_map(g)))
        kv_specs.append(pl.BlockSpec((1, 1, hkv, bs, d), _kv_index_map(g)))

    extra_specs, extra_ops = [], []
    for extra in (sinks, alibi_slopes):
        if extra is not None:
            # per-row scalar of q head r % hq: the (hq,) pattern tiled over the
            # tile's qt tokens — identical for every tile
            grouped = jnp.tile(extra.astype(jnp.float32), qt)
            grouped = jnp.broadcast_to(grouped[:, None], (tr, 128))
            extra_specs.append(
                pl.BlockSpec((tr, 128), lambda bi, qi, ci, *_: (0, 0)))
            extra_ops.append(grouped)
    n_extra = len(extra_ops)

    kernel = functools.partial(
        _paged_mixed_attend_kernel, scale=scale, bs=bs, kb=kb,
        num_cells=num_cells, qt=qt, hq=hq, n_rep=n_rep, hkv=hkv, tr=tr,
        window=window, soft_cap=soft_cap, has_sinks=sinks is not None,
        has_slopes=alibi_slopes is not None, amla=amla)

    def _kernel(pos_ref, qlen_ref, lidx_ref, bt_ref, q_ref, *rest):
        ins = rest[: 2 * kb + n_extra]
        o_ref, m_s, l_s, acc_s = rest[2 * kb + n_extra:]
        kernel(pos_ref, qlen_ref, lidx_ref, bt_ref, q_ref, *ins, o_ref=o_ref,
               m_scratch=m_s, l_scratch=l_s, acc_scratch=acc_s)

    q_spec = pl.BlockSpec((1, tr, d), lambda bi, qi, ci, *_: (bi, qi, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(b, nqt, num_cells),
        in_specs=[q_spec] + kv_specs + extra_specs,
        out_specs=pl.BlockSpec(q_spec.block_shape, q_spec.index_map),
        scratch_shapes=[
            pltpu.VMEM((tr, 128), jnp.float32),
            pltpu.VMEM((tr, 128), jnp.float32),
            pltpu.VMEM((tr, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        _kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, t_pad * hq, d), q.dtype),
        interpret=interpret,
    )(positions.astype(jnp.int32), q_lens.astype(jnp.int32),
      layer_idx.reshape(1).astype(jnp.int32), block_table.astype(jnp.int32),
      qg, *([k_cache, v_cache] * kb), *extra_ops)

    out = out[:, : t * hq, :].reshape(b, t, hq, d)
    return out.transpose(0, 2, 1, 3)
