"""Pallas decode-attention kernel: per-row length-aware attention over the KV cache.

≈ reference decode (TKG) attention kernels: `attention_tkg_fwd_isa_kernel` /
`attention_token_gen_kernel` (`modules/attention/attention_base.py:129-144,1483-1677`).
Those kernels' job is to make the decode step read only the *live* part of the cache;
this kernel does the TPU equivalent:

- Grid (batch, kv_heads, kv_blocks); the GQA group's query rows (n_rep * T, padded to
  the sublane width) ride one tile, so KV is streamed once per kv head — never
  materialized repeated (`repeat_kv`-free, like the reference's native-GQA kernels).
- Per-row positions arrive via scalar prefetch (SMEM); KV tiles entirely beyond a
  row's current position are **predicated off**, so HBM traffic tracks each row's true
  length, not the bucket width — the kernel-level refinement of bucketing, and the
  reason decode stays HBM-optimal under continuous batching where row lengths diverge.
- Online-softmax accumulation in VMEM scratch across the sequential kv_blocks dim;
  optional sliding window.

Decode is HBM-bandwidth-bound: the win over the jnp path is strictly fewer cache bytes
read per step.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_K = 256
from ..analysis.contracts import DispatchContract
from ..analysis.registry import register_external
from .paged_decode import _vmem_cast

NEG_INF = -1e30


def _decode_kernel(pos_ref, q_ref, k_ref, v_ref, o_ref, m_scratch, l_scratch,
                   acc_scratch, *, scale: float, block_k: int, num_kv_blocks: int,
                   t: int, rows: int, window: Optional[int]):
    bi = pl.program_id(0)
    ki = pl.program_id(2)
    k_start = ki * block_k

    @pl.when(ki == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    pos = pos_ref[bi]                       # this row's write position (first token)
    max_q_pos = pos + t - 1
    run = k_start <= max_q_pos              # tile fully beyond the row -> skip
    if window is not None:
        run = jnp.logical_and(run, k_start + block_k - 1 > pos - window)

    @pl.when(run)
    def _body():
        q = q_ref[0, 0]                     # (rows, D); rows = pad(n_rep * T)
        k = k_ref[0, 0]                     # (block_k, D)
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (rows, block_k)

        # row r of the tile is (kv-group rep, token) pair; its query position is
        # pos + (r % t) — reps of the same token share a position
        # (padded rows r >= n_rep*t compute garbage that the caller slices off)
        row_idx = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        q_pos = pos + row_idx % t
        kv_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = kv_pos <= q_pos
        if window is not None:
            mask = jnp.logical_and(mask, kv_pos > q_pos - window)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scratch[:, 0:1]
        l_prev = l_scratch[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(jnp.minimum(m_prev - m_new, 0.0))
        p = jnp.exp(s - m_new)
        p = jnp.where(mask, p, 0.0)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc = acc_scratch[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scratch[:] = jnp.broadcast_to(m_new, m_scratch.shape)
        l_scratch[:] = jnp.broadcast_to(l_new, l_scratch.shape)
        acc_scratch[:] = acc

    @pl.when(ki == num_kv_blocks - 1)
    def _finalize():
        l = l_scratch[:, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scratch[:] / l_safe).astype(o_ref.dtype)


def _flash_decode_attention(
    q: jnp.ndarray,              # (B, Hq, T, D), T small (1 or speculation width)
    k: jnp.ndarray,              # (B, Hkv, S_bucket, D) cache slice
    v: jnp.ndarray,
    positions: jnp.ndarray,      # (B,) int32 write position of q[:, :, 0]
    scale: Optional[float] = None,
    window: Optional[int] = None,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool = False,
) -> jnp.ndarray:
    """Length-aware decode attention; returns (B, Hq, T, D) in q.dtype."""
    b, hq, t, d = q.shape
    _, hkv, skv, _ = k.shape
    if hq % hkv != 0:
        raise ValueError(f"q heads {hq} not divisible by kv heads {hkv}")
    n_rep = hq // hkv
    if scale is None:
        scale = d ** -0.5

    # group GQA reps with their kv head: (B, Hkv, n_rep*T, D), rows padded to 8
    qg = q.reshape(b, hkv, n_rep, t, d).reshape(b, hkv, n_rep * t, d)
    rows = max(8, _round_up(n_rep * t, 8))
    if rows != n_rep * t:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, rows - n_rep * t), (0, 0)))

    block_k = min(block_k, _round_up(skv, 128))
    skv_p = _round_up(skv, block_k)
    if skv_p != skv:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, skv_p - skv), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, skv_p - skv), (0, 0)))
    num_kv_blocks = skv_p // block_k

    kernel = functools.partial(
        _decode_kernel, scale=scale, block_k=block_k, num_kv_blocks=num_kv_blocks,
        t=t, rows=rows, window=window)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, hkv, num_kv_blocks),
        in_specs=[
            pl.BlockSpec((1, 1, rows, d), lambda bi, hi, ki, *_: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ki, *_: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ki, *_: (bi, hi, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, rows, d), lambda bi, hi, ki, *_: (bi, hi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((rows, 128), jnp.float32),
            pltpu.VMEM((rows, 128), jnp.float32),
            pltpu.VMEM((rows, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, rows, d), q.dtype),
        interpret=interpret,
    )(positions.astype(jnp.int32), qg, k, v)

    out = out[:, :, : n_rep * t, :].reshape(b, hkv, n_rep, t, d)
    return out.reshape(b, hq, t, d)


# ISSUE-19 satellite: these standalone entry points were the only attention
# dispatches outside analysis/ coverage — register them as EXTERNAL audited
# dispatches (donation exactly as before: the write kernels alias at the
# pallas level via input_output_aliases, deliberately WITHOUT jit donation,
# so their contracts declare no cache operand).
_FLASH_DECODE_STATICS = ("scale", "window", "block_k", "interpret")
flash_decode_attention = register_external(
    jax.jit(_flash_decode_attention, static_argnames=_FLASH_DECODE_STATICS),
    _flash_decode_attention,
    DispatchContract(kind="flash.decode", waivers={
        "hbm_bytes": "toy-scale accounting: XLA charges the padded GQA row "
                     "tile per grid cell (~9x a 48-wide toy slice's inputs, "
                     "amortized away at real cache widths); the stacked twin "
                     "flash.decode.stacked carries the unwaived budget"}),
    static_argnames=_FLASH_DECODE_STATICS)


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


# --- stacked-cache decode path (the serving hot path) ---------------------------------
#
# The jnp decode path pays three cache-movement taxes per layer-step that profiling
# shows dominate the decode step (≈ 65% of wall time at 8B/bs=64):
#   1. the vmapped dynamic_update_slice KV write lowers to a SERIAL while loop over
#      the batch dim;
#   2. lax.scan materializes each layer's (B, H, S, D) cache slice (xs copy);
#   3. scan re-stacks the updated slice into the (L, ...) output (ys copy).
# These kernels operate directly on the STACKED (L, B, H, S, D) cache — the layer
# index arrives via scalar prefetch, so the cache rides the scan as a carry and is
# never sliced or re-stacked — and the write is one strided DMA per row instead of a
# serial loop. ≈ the reference's in-kernel KV write + TKG attention kernels
# (`modules/attention/attention_base.py:1679-1994`, `modules/kvcache/utils.py:20-38`).


def _kv_write_kernel(pos_ref, lidx_ref, new_ref, _cache_in, cache_out, scratch, sem,
                     *, t: int, pack: int, win: int, s_max: int):
    """Tile-aligned read-modify-write: Mosaic DMA slices on the sublane dim must be
    whole (8 x packing)-row tiles (32 rows for 1-byte dtypes, 16 for bf16), so the T
    new rows are inserted into an aligned ``win``-wide window staged through VMEM."""
    b = pl.program_id(0)
    pos = pos_ref[b]
    # clamp keeps the window inside the cache (still covers [pos, pos+t) because
    # pos + t <= s_max); the trailing multiply keeps the offset provably
    # pack-aligned for Mosaic's divisibility check
    w0 = jnp.minimum(pos // pack, (s_max - win) // pack) * pack
    dst = cache_out.at[lidx_ref[0], b, :, pl.ds(w0, win), :]
    dma_in = pltpu.make_async_copy(dst, scratch, sem)
    dma_in.start()
    dma_in.wait()
    off = pos - w0
    iota = jax.lax.broadcasted_iota(jnp.int32, scratch.shape, 1)  # window row ids
    vals = scratch[:]
    for j in range(t):                          # t is tiny (1 or speculation width)
        vals = jnp.where(iota == off + j, new_ref[0, :, j : j + 1, :], vals)
    scratch[:] = vals
    dma_out = pltpu.make_async_copy(scratch, dst, sem)
    dma_out.start()
    dma_out.wait()


def _write_decode_stacked(
    cache: jnp.ndarray,          # (L, B, Hkv, S, D) — donated/aliased in place
    new_kv: jnp.ndarray,         # (B, Hkv, T, D), already in cache dtype
    positions: jnp.ndarray,      # (B,) int32 write position per row
    layer_idx: jnp.ndarray,      # () int32 layer to write
    interpret: bool = False,
) -> jnp.ndarray:
    """Scatter the step's K or V rows into the stacked cache, one batch row per grid
    cell (the reference's batched-KV-write kernel analog, `kvcache/utils.py:20-38`)."""
    b, h, t, d = new_kv.shape
    s_max = cache.shape[3]
    pack = 8 * max(1, 4 // jnp.dtype(cache.dtype).itemsize)
    win = _round_up(t + pack - 1, pack)
    if s_max % pack != 0 or s_max < win:
        raise ValueError(f"cache seq dim {s_max} must be a multiple of {pack} "
                         f"and at least {win}")
    kernel = functools.partial(_kv_write_kernel, t=t, pack=pack, win=win,
                               s_max=s_max)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1,) + new_kv.shape[1:], lambda bi, *_: (bi, 0, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((h, win, d), cache.dtype),
            pltpu.SemaphoreType.DMA,
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(cache.shape, cache.dtype),
        input_output_aliases={3: 0},    # cache in (after 2 prefetch + new) -> out
        interpret=interpret,
    )(positions.astype(jnp.int32), layer_idx.reshape(1).astype(jnp.int32),
      new_kv, cache)


write_decode_stacked = register_external(
    # lint: ok(jit-no-donate): aliased IN the pallas kernel (input_output_aliases); jit donation is the enclosing caller's call
    jax.jit(_write_decode_stacked, static_argnames=("interpret",)),
    _write_decode_stacked,
    DispatchContract(kind="flash.write.stacked"),
    static_argnames=("interpret",))


def _kv_write_kv_kernel(pos_ref, lidx_ref, new_k_ref, new_v_ref, _k_in, _v_in,
                        k_out, v_out, sk, sv, sems, *, t: int, pack: int, win: int,
                        s_max: int, bb: int):
    """Combined K+V write, ``bb`` batch rows per cell, all DMAs overlapped."""
    bi = pl.program_id(0)
    l = lidx_ref[0]
    w0s = []
    for j in range(bb):
        pos = pos_ref[bi * bb + j]
        w0 = jnp.minimum(pos // pack, (s_max - win) // pack) * pack
        w0s.append(w0)
        pltpu.make_async_copy(k_out.at[l, bi * bb + j, :, pl.ds(w0, win), :],
                              sk.at[j], sems.at[j, 0]).start()
        pltpu.make_async_copy(v_out.at[l, bi * bb + j, :, pl.ds(w0, win), :],
                              sv.at[j], sems.at[j, 1]).start()
    for j in range(bb):
        pltpu.make_async_copy(k_out.at[l, bi * bb + j, :, pl.ds(w0s[j], win), :],
                              sk.at[j], sems.at[j, 0]).wait()
        pltpu.make_async_copy(v_out.at[l, bi * bb + j, :, pl.ds(w0s[j], win), :],
                              sv.at[j], sems.at[j, 1]).wait()
    iota = jax.lax.broadcasted_iota(
        jnp.int32, (sk.shape[1], win, sk.shape[3]), 1)
    for j in range(bb):
        off = pos_ref[bi * bb + j] - w0s[j]          # scalar (Mosaic-friendly)
        vk, vv = sk[j], sv[j]
        for tok in range(t):
            hit = iota == off + tok
            vk = jnp.where(hit, new_k_ref[j, :, tok : tok + 1, :], vk)
            vv = jnp.where(hit, new_v_ref[j, :, tok : tok + 1, :], vv)
        sk[j] = vk
        sv[j] = vv
    for j in range(bb):
        pltpu.make_async_copy(sk.at[j],
                              k_out.at[l, bi * bb + j, :, pl.ds(w0s[j], win), :],
                              sems.at[j, 0]).start()
        pltpu.make_async_copy(sv.at[j],
                              v_out.at[l, bi * bb + j, :, pl.ds(w0s[j], win), :],
                              sems.at[j, 1]).start()
    for j in range(bb):
        pltpu.make_async_copy(sk.at[j],
                              k_out.at[l, bi * bb + j, :, pl.ds(w0s[j], win), :],
                              sems.at[j, 0]).wait()
        pltpu.make_async_copy(sv.at[j],
                              v_out.at[l, bi * bb + j, :, pl.ds(w0s[j], win), :],
                              sems.at[j, 1]).wait()


def _batch_block(b: int) -> int:
    for bb in (8, 4, 2):
        if b % bb == 0:
            return bb
    return 1


def _write_decode_stacked_kv(
    k_cache: jnp.ndarray,        # (L, B, Hkv, S, D) — donated/aliased in place
    v_cache: jnp.ndarray,
    new_k: jnp.ndarray,          # (B, Hkv, T, D), already in cache dtype
    new_v: jnp.ndarray,
    positions: jnp.ndarray,      # (B,) int32 write position per row
    layer_idx: jnp.ndarray,      # () int32 layer to write
    interpret: bool = False,
):
    """Scatter the step's K and V rows into both stacked caches in ONE kernel
    (the reference's batched-KV-write kernel analog, `kvcache/utils.py:20-38`):
    tile-aligned read-modify-write windows, DMAs for ``bb`` rows in flight at once."""
    b, h, t, d = new_k.shape
    s_max = k_cache.shape[3]
    pack = 8 * max(1, 4 // jnp.dtype(k_cache.dtype).itemsize)
    win = _round_up(t + pack - 1, pack)
    if s_max % pack != 0 or s_max < win:
        raise ValueError(f"cache seq dim {s_max} must be a multiple of {pack} "
                         f"and at least {win}")
    bb = _batch_block(b)
    kernel = functools.partial(_kv_write_kv_kernel, t=t, pack=pack, win=win,
                               s_max=s_max, bb=bb)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b // bb,),
        in_specs=[
            pl.BlockSpec((bb, h, t, d), lambda bi, *_: (bi, 0, 0, 0)),
            pl.BlockSpec((bb, h, t, d), lambda bi, *_: (bi, 0, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[pl.BlockSpec(memory_space=pl.ANY),
                   pl.BlockSpec(memory_space=pl.ANY)],
        scratch_shapes=[
            pltpu.VMEM((bb, h, win, d), k_cache.dtype),
            pltpu.VMEM((bb, h, win, d), v_cache.dtype),
            pltpu.SemaphoreType.DMA((bb, 2)),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct(k_cache.shape, k_cache.dtype),
                   jax.ShapeDtypeStruct(v_cache.shape, v_cache.dtype)],
        input_output_aliases={4: 0, 5: 1},   # caches (after 2 prefetch + 2 new)
        interpret=interpret,
    )(positions.astype(jnp.int32), layer_idx.reshape(1).astype(jnp.int32),
      new_k, new_v, k_cache, v_cache)


write_decode_stacked_kv = register_external(
    # lint: ok(jit-no-donate): aliased IN the pallas kernel (input_output_aliases); jit donation is the enclosing caller's call
    jax.jit(_write_decode_stacked_kv, static_argnames=("interpret",)),
    _write_decode_stacked_kv,
    DispatchContract(kind="flash.write.stacked_kv"),
    static_argnames=("interpret",))


def _stacked_decode_kernel(pos_ref, lidx_ref, q_ref, k_ref, v_ref, *refs,
                           scale: float, block_k: int,
                           num_kv_blocks: int, t: int, rows: int, bb: int,
                           hkv: int, window: Optional[int],
                           soft_cap: Optional[float], has_sinks: bool,
                           has_slopes: bool):
    # trailing refs: [sinks?], [slopes?], o_ref, m_scratch, l_scratch, acc_scratch
    idx = 0
    sinks_ref = slopes_ref = None
    if has_sinks:
        sinks_ref, idx = refs[idx], idx + 1
    if has_slopes:
        slopes_ref, idx = refs[idx], idx + 1
    o_ref, m_scratch, l_scratch, acc_scratch = refs[idx : idx + 4]

    bi = pl.program_id(0)
    ki = pl.program_id(1)
    k_start = ki * block_k

    @pl.when(ki == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    import functools as _ft

    pos = [pos_ref[bi * bb + j] for j in range(bb)]                # bb scalars
    pos_max = _ft.reduce(jnp.maximum, pos)
    run = k_start <= pos_max + t - 1
    if window is not None:
        pos_min = _ft.reduce(jnp.minimum, pos)
        run = jnp.logical_and(run, k_start + block_k - 1 > pos_min - window)

    @pl.when(run)
    def _body():
        # static (bb x hkv) loop keeps every op 2D (Mosaic's comfort zone: its
        # reshape/layout inference rejects multi-dim collapses); the loop unrolls
        # into straight-line vector code inside ONE big grid cell, so the per-cell
        # fixed cost amortizes over all heads and bb batch rows
        kv_iota = jax.lax.broadcasted_iota(jnp.int32, (rows, block_k), 1) + k_start
        row_iota = jax.lax.broadcasted_iota(jnp.int32, (rows, block_k), 0)
        for j in range(bb):
            q_pos = pos[j] + row_iota % t
            mask = kv_iota <= q_pos
            if window is not None:
                mask = jnp.logical_and(mask, kv_iota > q_pos - window)
            for h in range(hkv):
                q = q_ref[j, h]                          # (rows, D)
                int8_kv = k_ref.dtype == jnp.int8
                if int8_kv:
                    # int8 KV (static scales): int8 x int8 on the MXU, no cast
                    # of the streamed K/V (see paged_decode for the scheme)
                    k = k_ref[0, j, h]
                    v = v_ref[0, j, h]
                    qf = q.astype(jnp.float32)
                    sx = jnp.maximum(
                        jnp.max(jnp.abs(qf), axis=1, keepdims=True) / 127.0,
                        1e-8)
                    q = jnp.clip(jnp.round(qf / sx), -127, 127).astype(jnp.int8)
                    s = jax.lax.dot_general(
                        q, k, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.int32
                    ).astype(jnp.float32) * (sx * scale)
                else:
                    k = _vmem_cast(k_ref[0, j, h], q.dtype)  # (block_k, D)
                    v = _vmem_cast(v_ref[0, j, h], q.dtype)
                    s = jax.lax.dot_general(
                        q, k, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32) * scale
                if slopes_ref is not None:
                    # ALiBi: per-row slope (rows grouped by q head, batch-invariant)
                    s = s - slopes_ref[h * rows : (h + 1) * rows, 0:1] * (
                        q_pos - kv_iota).astype(jnp.float32)
                if soft_cap is not None:
                    s = soft_cap * jnp.tanh(s / soft_cap)
                s = jnp.where(mask, s, NEG_INF)
                r0 = (j * hkv + h) * rows
                m_prev = m_scratch[r0 : r0 + rows, 0:1]
                l_prev = l_scratch[r0 : r0 + rows, 0:1]
                m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
                alpha = jnp.exp(jnp.minimum(m_prev - m_new, 0.0))
                p = jnp.exp(s - m_new)
                p = jnp.where(mask, p, 0.0)
                l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
                if int8_kv:
                    pi = jnp.round(p * 127.0).astype(jnp.int8)
                    pv_d = jax.lax.dot_general(
                        pi, v, (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.int32
                    ).astype(jnp.float32) * (1.0 / 127.0)
                else:
                    pv_d = jax.lax.dot_general(
                        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)
                acc = acc_scratch[r0 : r0 + rows] * alpha + pv_d
                m_scratch[r0 : r0 + rows] = jnp.broadcast_to(m_new, (rows, 128))
                l_scratch[r0 : r0 + rows] = jnp.broadcast_to(l_new, (rows, 128))
                acc_scratch[r0 : r0 + rows] = acc

    @pl.when(ki == num_kv_blocks - 1)
    def _finalize():
        for j in range(bb):
            for h in range(hkv):
                r0 = (j * hkv + h) * rows
                m = m_scratch[r0 : r0 + rows, 0:1]
                l = l_scratch[r0 : r0 + rows, 0:1]
                acc = acc_scratch[r0 : r0 + rows]
                if sinks_ref is not None:
                    # learned sink: virtual denominator-only logit per q head
                    sink = sinks_ref[h * rows : (h + 1) * rows, 0:1]
                    m_new = jnp.maximum(m, sink)
                    alpha = jnp.exp(jnp.minimum(m - m_new, 0.0))
                    l = alpha * l + jnp.exp(sink - m_new)
                    acc = acc * alpha
                l_safe = jnp.where(l == 0.0, 1.0, l)
                o_ref[j, h] = (acc / l_safe).astype(o_ref.dtype)


def _group_head_scalars(x: jnp.ndarray, hkv: int, n_rep: int, t: int, rows: int
                        ) -> jnp.ndarray:
    """(Hq,) per-q-head scalars -> (Hkv*rows, 128): row r of kv head h holds the
    scalar of q head ``h*n_rep + r//t`` (the kernels' GQA row grouping)."""
    grouped = jnp.repeat(x.astype(jnp.float32).reshape(hkv, n_rep), t, axis=1)
    grouped = jnp.pad(grouped, ((0, 0), (0, rows - n_rep * t)))
    return jnp.broadcast_to(grouped.reshape(hkv * rows, 1), (hkv * rows, 128))


def _flash_decode_attention_stacked(
    q: jnp.ndarray,              # (B, Hq, T, D)
    k_cache: jnp.ndarray,        # (L, B, Hkv, S_max, D) — full stacked cache
    v_cache: jnp.ndarray,
    positions: jnp.ndarray,      # (B,) int32 write position of q[:, :, 0]
    layer_idx: jnp.ndarray,      # () int32 layer to attend over
    bucket: int,                 # static attention width (<= S_max)
    scale: Optional[float] = None,
    window: Optional[int] = None,
    soft_cap: Optional[float] = None,
    sinks: Optional[jnp.ndarray] = None,         # (Hq,) learned sink logits
    alibi_slopes: Optional[jnp.ndarray] = None,  # (Hq,) ALiBi slopes
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool = False,
) -> jnp.ndarray:
    """Length-aware decode attention over one layer of the stacked cache.

    Reads only KV tiles at or below each row's position (and the static ``bucket``
    bound); the fresh step's K/V must already be written (write_decode_stacked).
    Supports the arch extras of the reference TKG kernels: soft-cap, learned sinks,
    ALiBi (computed in-kernel). Returns (B, Hq, T, D) in q.dtype."""
    b, hq, t, d = q.shape
    _, _, hkv, s_max, _ = k_cache.shape
    if hq % hkv != 0:
        raise ValueError(f"q heads {hq} not divisible by kv heads {hkv}")
    n_rep = hq // hkv
    if scale is None:
        scale = d ** -0.5

    qg = q.reshape(b, hkv, n_rep, t, d).reshape(b, hkv, n_rep * t, d)
    rows = max(8, _round_up(n_rep * t, 8))
    if rows != n_rep * t:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, rows - n_rep * t), (0, 0)))

    bucket = min(bucket, s_max)
    # blocks must stay inside the cache's S_max extent: out-of-bounds tiles would
    # stream garbage whose 0-weighted NaNs still poison the PV contraction
    if s_max % 128 == 0:
        block_k = min(block_k, _round_up(bucket, 128))
        while s_max % block_k != 0:
            block_k //= 2
    else:
        block_k = s_max              # tiny/test configs: one block, no tiling
    num_kv_blocks = -(-bucket // block_k)
    bb = _batch_block(b)

    kernel = functools.partial(
        _stacked_decode_kernel, scale=scale, block_k=block_k,
        num_kv_blocks=num_kv_blocks, t=t, rows=rows, bb=bb, hkv=hkv,
        window=window, soft_cap=soft_cap, has_sinks=sinks is not None,
        has_slopes=alibi_slopes is not None)

    # coarse grid: bb batch rows x ALL kv heads per cell — per-cell work must
    # dominate the fixed per-cell cost or the kernel is overhead-bound
    in_specs = [
        pl.BlockSpec((bb, hkv, rows, d), lambda bi, ki, *_: (bi, 0, 0, 0)),
        pl.BlockSpec((1, bb, hkv, block_k, d),
                     lambda bi, ki, pos, lidx: (lidx[0], bi, 0, ki, 0)),
        pl.BlockSpec((1, bb, hkv, block_k, d),
                     lambda bi, ki, pos, lidx: (lidx[0], bi, 0, ki, 0)),
    ]
    operands = [qg, k_cache, v_cache]
    for extra in (sinks, alibi_slopes):
        if extra is not None:
            in_specs.append(
                pl.BlockSpec((hkv * rows, 128), lambda bi, ki, *_: (0, 0)))
            operands.append(_group_head_scalars(extra, hkv, n_rep, t, rows))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b // bb, num_kv_blocks),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bb, hkv, rows, d), lambda bi, ki, *_: (bi, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((bb * hkv * rows, 128), jnp.float32),
            pltpu.VMEM((bb * hkv * rows, 128), jnp.float32),
            pltpu.VMEM((bb * hkv * rows, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, rows, d), q.dtype),
        interpret=interpret,
    )(positions.astype(jnp.int32), layer_idx.reshape(1).astype(jnp.int32),
      *operands)

    out = out[:, :, : n_rep * t, :].reshape(b, hkv, n_rep, t, d)
    return out.reshape(b, hq, t, d)


_FLASH_STACKED_STATICS = ("bucket", "scale", "window", "soft_cap", "block_k",
                          "interpret")
flash_decode_attention_stacked = register_external(
    # lint: ok(jit-no-donate): read-only attend over the stacked caches — the write twins own the aliasing
    jax.jit(_flash_decode_attention_stacked,
            static_argnames=_FLASH_STACKED_STATICS),
    _flash_decode_attention_stacked,
    DispatchContract(kind="flash.decode.stacked"),
    static_argnames=_FLASH_STACKED_STATICS)
