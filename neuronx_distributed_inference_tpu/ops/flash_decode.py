"""Pallas decode-attention kernel: per-row length-aware attention over the KV cache.

≈ reference decode (TKG) attention kernels: `attention_tkg_fwd_isa_kernel` /
`attention_token_gen_kernel` (`modules/attention/attention_base.py:129-144,1483-1677`).
Those kernels' job is to make the decode step read only the *live* part of the cache;
this kernel does the TPU equivalent:

- Grid (batch, kv_heads, kv_blocks); the GQA group's query rows (n_rep * T, padded to
  the sublane width) ride one tile, so KV is streamed once per kv head — never
  materialized repeated (`repeat_kv`-free, like the reference's native-GQA kernels).
- Per-row positions arrive via scalar prefetch (SMEM); KV tiles entirely beyond a
  row's current position are **predicated off**, so HBM traffic tracks each row's true
  length, not the bucket width — the kernel-level refinement of bucketing, and the
  reason decode stays HBM-optimal under continuous batching where row lengths diverge.
- Online-softmax accumulation in VMEM scratch across the sequential kv_blocks dim;
  optional sliding window.

Decode is HBM-bandwidth-bound: the win over the jnp path is strictly fewer cache bytes
read per step.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_K = 256
NEG_INF = -1e30


def _decode_kernel(pos_ref, q_ref, k_ref, v_ref, o_ref, m_scratch, l_scratch,
                   acc_scratch, *, scale: float, block_k: int, num_kv_blocks: int,
                   t: int, rows: int, window: Optional[int]):
    bi = pl.program_id(0)
    ki = pl.program_id(2)
    k_start = ki * block_k

    @pl.when(ki == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    pos = pos_ref[bi]                       # this row's write position (first token)
    max_q_pos = pos + t - 1
    run = k_start <= max_q_pos              # tile fully beyond the row -> skip
    if window is not None:
        run = jnp.logical_and(run, k_start + block_k - 1 > pos - window)

    @pl.when(run)
    def _body():
        q = q_ref[0, 0]                     # (rows, D); rows = pad(n_rep * T)
        k = k_ref[0, 0]                     # (block_k, D)
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (rows, block_k)

        # row r of the tile is (kv-group rep, token) pair; its query position is
        # pos + (r % t) — reps of the same token share a position
        # (padded rows r >= n_rep*t compute garbage that the caller slices off)
        row_idx = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        q_pos = pos + row_idx % t
        kv_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = kv_pos <= q_pos
        if window is not None:
            mask = jnp.logical_and(mask, kv_pos > q_pos - window)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scratch[:, 0:1]
        l_prev = l_scratch[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(jnp.minimum(m_prev - m_new, 0.0))
        p = jnp.exp(s - m_new)
        p = jnp.where(mask, p, 0.0)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc = acc_scratch[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scratch[:] = jnp.broadcast_to(m_new, m_scratch.shape)
        l_scratch[:] = jnp.broadcast_to(l_new, l_scratch.shape)
        acc_scratch[:] = acc

    @pl.when(ki == num_kv_blocks - 1)
    def _finalize():
        l = l_scratch[:, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scratch[:] / l_safe).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "window", "block_k", "interpret"))
def flash_decode_attention(
    q: jnp.ndarray,              # (B, Hq, T, D), T small (1 or speculation width)
    k: jnp.ndarray,              # (B, Hkv, S_bucket, D) cache slice
    v: jnp.ndarray,
    positions: jnp.ndarray,      # (B,) int32 write position of q[:, :, 0]
    scale: Optional[float] = None,
    window: Optional[int] = None,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool = False,
) -> jnp.ndarray:
    """Length-aware decode attention; returns (B, Hq, T, D) in q.dtype."""
    b, hq, t, d = q.shape
    _, hkv, skv, _ = k.shape
    if hq % hkv != 0:
        raise ValueError(f"q heads {hq} not divisible by kv heads {hkv}")
    n_rep = hq // hkv
    if scale is None:
        scale = d ** -0.5

    # group GQA reps with their kv head: (B, Hkv, n_rep*T, D), rows padded to 8
    qg = q.reshape(b, hkv, n_rep, t, d).reshape(b, hkv, n_rep * t, d)
    rows = max(8, _round_up(n_rep * t, 8))
    if rows != n_rep * t:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, rows - n_rep * t), (0, 0)))

    block_k = min(block_k, _round_up(skv, 128))
    skv_p = _round_up(skv, block_k)
    if skv_p != skv:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, skv_p - skv), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, skv_p - skv), (0, 0)))
    num_kv_blocks = skv_p // block_k

    kernel = functools.partial(
        _decode_kernel, scale=scale, block_k=block_k, num_kv_blocks=num_kv_blocks,
        t=t, rows=rows, window=window)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, hkv, num_kv_blocks),
        in_specs=[
            pl.BlockSpec((1, 1, rows, d), lambda bi, hi, ki, *_: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ki, *_: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ki, *_: (bi, hi, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, rows, d), lambda bi, hi, ki, *_: (bi, hi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((rows, 128), jnp.float32),
            pltpu.VMEM((rows, 128), jnp.float32),
            pltpu.VMEM((rows, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, rows, d), q.dtype),
        interpret=interpret,
    )(positions.astype(jnp.int32), qg, k, v)

    out = out[:, :, : n_rep * t, :].reshape(b, hkv, n_rep, t, d)
    return out.reshape(b, hq, t, d)


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m
