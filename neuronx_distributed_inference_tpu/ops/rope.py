"""Rotary position embeddings.

≈ reference RoPE classes in `modules/attention/utils.py:200-` (default RotaryEmbedding
and Llama3 scaled variant used by `models/llama/modeling_llama.py`). Functional: the
inverse-frequency vector is precomputed host-side (numpy) and carried in the param
pytree; cos/sin are computed inside the jitted graph from position ids, so one compiled
graph serves every position without a (seq_len, dim) table in HBM.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np


def default_inv_freq(head_dim: int, rope_theta: float = 10000.0) -> np.ndarray:
    return 1.0 / (rope_theta ** (np.arange(0, head_dim, 2, dtype=np.float64)
                                 / head_dim)).astype(np.float32)


def llama3_scaled_inv_freq(
    head_dim: int,
    rope_theta: float,
    factor: float = 8.0,
    low_freq_factor: float = 1.0,
    high_freq_factor: float = 4.0,
    original_max_position_embeddings: int = 8192,
) -> np.ndarray:
    """Llama-3.1 frequency-dependent NTK scaling (matches HF `rope_type: llama3`)."""
    inv_freq = default_inv_freq(head_dim, rope_theta).astype(np.float64)
    low_freq_wavelen = original_max_position_embeddings / low_freq_factor
    high_freq_wavelen = original_max_position_embeddings / high_freq_factor
    wavelen = 2 * math.pi / inv_freq
    scaled = np.where(wavelen > low_freq_wavelen, inv_freq / factor, inv_freq)
    smooth = (original_max_position_embeddings / wavelen - low_freq_factor) / (
        high_freq_factor - low_freq_factor)
    smoothed = (1 - smooth) / factor * inv_freq + smooth * inv_freq
    is_medium = (wavelen <= low_freq_wavelen) & (wavelen >= high_freq_wavelen)
    return np.where(is_medium, smoothed, scaled).astype(np.float32)


def yarn_inv_freq(
    head_dim: int,
    rope_theta: float,
    factor: float,
    original_max_position_embeddings: int,
    beta_fast: float = 32.0,
    beta_slow: float = 1.0,
    truncate: bool = True,
) -> np.ndarray:
    """YaRN NTK-by-parts frequency interpolation (matches HF `rope_type: yarn`;
    used by gpt-oss and deepseek). Low frequencies are interpolated by ``factor``,
    high frequencies extrapolated, with a linear ramp between the correction dims."""
    dim = head_dim

    def correction_dim(num_rotations: float) -> float:
        return (dim * math.log(original_max_position_embeddings
                               / (num_rotations * 2 * math.pi))) / (2 * math.log(rope_theta))

    low = correction_dim(beta_fast)
    high = correction_dim(beta_slow)
    if truncate:
        low, high = math.floor(low), math.ceil(high)
    low, high = max(low, 0), min(high, dim - 1)
    if low == high:
        high += 0.001
    pos_freqs = rope_theta ** (np.arange(0, dim, 2, dtype=np.float64) / dim)
    extrapolation = 1.0 / pos_freqs
    interpolation = 1.0 / (factor * pos_freqs)
    ramp = np.clip((np.arange(dim // 2, dtype=np.float64) - low) / (high - low), 0, 1)
    extrapolation_factor = 1 - ramp
    return (interpolation * (1 - extrapolation_factor)
            + extrapolation * extrapolation_factor).astype(np.float32)


def yarn_mscale(scale: float, mscale: float = 1.0) -> float:
    """YaRN attention magnitude scaling: 0.1·mscale·ln(s) + 1."""
    if scale <= 1.0:
        return 1.0
    return 0.1 * mscale * math.log(scale) + 1.0


def attention_scaling_from_hf_config(rope_scaling) -> float:
    """The cos/sin magnitude factor HF applies for this rope type (yarn only)."""
    if rope_scaling is None:
        return 1.0
    rtype = rope_scaling.get("rope_type", rope_scaling.get("type", "default"))
    if rtype != "yarn":
        return 1.0
    attention_factor = rope_scaling.get("attention_factor")
    if attention_factor is not None:
        return float(attention_factor)
    factor = rope_scaling.get("factor", 1.0)
    mscale = rope_scaling.get("mscale")
    mscale_all_dim = rope_scaling.get("mscale_all_dim")
    if mscale and mscale_all_dim:
        return float(yarn_mscale(factor, mscale) / yarn_mscale(factor, mscale_all_dim))
    return float(yarn_mscale(factor))


def inv_freq_from_hf_config(head_dim: int, rope_theta: float, rope_scaling) -> np.ndarray:
    """Build inv_freq from HF config fields (``rope_scaling`` dict or None)."""
    if rope_scaling is None:
        return default_inv_freq(head_dim, rope_theta)
    rtype = rope_scaling.get("rope_type", rope_scaling.get("type", "default"))
    if rtype == "default":
        return default_inv_freq(head_dim, rope_theta)
    if rtype == "llama3":
        return llama3_scaled_inv_freq(
            head_dim,
            rope_theta,
            factor=rope_scaling.get("factor", 8.0),
            low_freq_factor=rope_scaling.get("low_freq_factor", 1.0),
            high_freq_factor=rope_scaling.get("high_freq_factor", 4.0),
            original_max_position_embeddings=rope_scaling.get(
                "original_max_position_embeddings", 8192),
        )
    if rtype == "linear":
        return default_inv_freq(head_dim, rope_theta) / rope_scaling.get("factor", 1.0)
    if rtype == "yarn":
        return yarn_inv_freq(
            head_dim,
            rope_theta,
            factor=rope_scaling.get("factor", 1.0),
            original_max_position_embeddings=rope_scaling.get(
                "original_max_position_embeddings", 4096),
            beta_fast=rope_scaling.get("beta_fast", 32.0),
            beta_slow=rope_scaling.get("beta_slow", 1.0),
            truncate=rope_scaling.get("truncate", True),
        )
    raise NotImplementedError(f"rope_type {rtype!r} not supported yet")


def compute_cos_sin(inv_freq: jnp.ndarray, position_ids: jnp.ndarray,
                    attention_scaling: float = 1.0):
    """cos/sin of shape (..., seq, head_dim) from positions (..., seq).

    Matches HF layout: freqs duplicated along the last dim (concat, not interleave).
    """
    freqs = position_ids[..., None].astype(jnp.float32) * inv_freq  # (..., S, D/2)
    emb = jnp.concatenate([freqs, freqs], axis=-1)
    return (jnp.cos(emb) * attention_scaling, jnp.sin(emb) * attention_scaling)


def deinterleave(x: jnp.ndarray) -> jnp.ndarray:
    """[x0, x1, x2, ...] -> [x0, x2, ..., x1, x3, ...] on the last dim.

    DeepSeek/Llama4 checkpoints store rope dims as interleaved complex pairs; after
    this shared permutation of q AND k the standard rotate-half application yields
    identical attention scores (scores are invariant to a permutation applied to both
    operands of the q.k contraction)."""
    return jnp.concatenate([x[..., 0::2], x[..., 1::2]], axis=-1)


def rotate_half(x: jnp.ndarray) -> jnp.ndarray:
    half = x.shape[-1] // 2
    return jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)


def apply_rotary(q: jnp.ndarray, k: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray):
    """Apply RoPE to q/k of shape (B, heads, S, D); cos/sin (B, S, D).

    Computed in float32 and cast back to the input dtype, like the reference's
    rotary application under `attention_base.py`.
    """
    cos = cos[:, None, :, :]
    sin = sin[:, None, :, :]
    out_dtype = q.dtype
    q32, k32 = q.astype(jnp.float32), k.astype(jnp.float32)
    q_rot = q32 * cos + rotate_half(q32) * sin
    k_rot = k32 * cos + rotate_half(k32) * sin
    return q_rot.astype(out_dtype), k_rot.astype(out_dtype)


def mrope_cos_sin(inv_freq: jnp.ndarray, positions3: jnp.ndarray,
                  sections, attention_scaling: float = 1.0):
    """Multimodal (3D) rotary tables (HF `apply_multimodal_rotary_pos_emb`).

    positions3 (3, B, S): temporal/height/width positions per token. ``sections``
    partitions the head_dim *half*: channel c of the full head dim takes its rotation
    from position stream i where c falls in the i-th section (pattern repeated for the
    second half). Text tokens carry equal positions in all three streams, collapsing
    to standard 1D rope. Returns (cos, sin) of shape (B, S, head_dim)."""
    freqs = positions3[..., None].astype(jnp.float32) * inv_freq   # (3, B, S, D/2)
    emb = jnp.concatenate([freqs, freqs], axis=-1)                 # (3, B, S, D)
    sec_idx = np.concatenate([np.full((s,), i % 3, dtype=np.int32)
                              for i, s in enumerate(tuple(sections) * 2)])
    onehot = jax.nn.one_hot(jnp.asarray(sec_idx), 3, dtype=jnp.float32)  # (D, 3)
    cos = jnp.einsum("sbtd,ds->btd", jnp.cos(emb), onehot)
    sin = jnp.einsum("sbtd,ds->btd", jnp.sin(emb), onehot)
    return cos * attention_scaling, sin * attention_scaling


def mrope_cos_sin_interleaved(inv_freq: jnp.ndarray, positions3: jnp.ndarray,
                              sections, attention_scaling: float = 1.0):
    """Qwen3-VL interleaved M-RoPE (HF `apply_interleaved_mrope`): frequency
    channel c of the half-dim takes stream H when c % 3 == 1 and c < 3*sec[1],
    stream W when c % 3 == 2 and c < 3*sec[2], else temporal — [THWTHW...TT]
    instead of the chunked [TTT..HHH..WWW]. Returns (cos, sin) (B, S, head_dim)."""
    half = inv_freq.shape[0]
    sec = tuple(sections)
    stream = np.zeros((half,), dtype=np.int32)
    for dim, offset in ((1, 1), (2, 2)):
        idx = np.arange(offset, sec[dim] * 3, 3)
        stream[idx] = dim
    sec_idx = np.concatenate([stream, stream])           # full head dim
    freqs = positions3[..., None].astype(jnp.float32) * inv_freq   # (3, B, S, D/2)
    emb = jnp.concatenate([freqs, freqs], axis=-1)                 # (3, B, S, D)
    onehot = jax.nn.one_hot(jnp.asarray(sec_idx), 3, dtype=jnp.float32)  # (D, 3)
    cos = jnp.einsum("sbtd,ds->btd", jnp.cos(emb), onehot)
    sin = jnp.einsum("sbtd,ds->btd", jnp.sin(emb), onehot)
    return cos * attention_scaling, sin * attention_scaling
