"""Attention reference ops (jnp; XLA-fused).

≈ reference `modules/attention/attention_base.py` native paths: GQA scaled-dot-product
with fp32 softmax, causal/padded masks, and the decode-time attention over a bucketed KV
cache (the reference's prior/active softmax decomposition, `utils.py:252
manual_softmax`, collapses on TPU to one masked softmax over the cache slice — XLA fuses
it; a Pallas decode kernel replaces this on the hot path when profiling warrants).

Shapes follow the JAX convention (B, heads, S, D). Pallas flash-attention kernels for
the prefill hot path live in `ops/flash_attention.py`.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

NEG_INF = -30000.0  # finite mask value, like the reference's -30k to avoid NaN rows


def repeat_kv(x: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    """(B, n_kv, S, D) -> (B, n_kv * n_rep, S, D), GQA head replication."""
    if n_rep == 1:
        return x
    b, n_kv, s, d = x.shape
    x = jnp.broadcast_to(x[:, :, None], (b, n_kv, n_rep, s, d))
    return x.reshape(b, n_kv * n_rep, s, d)


def causal_mask(q_len: int, kv_len: int, q_offset=0) -> jnp.ndarray:
    """Boolean (q_len, kv_len) mask; True = attend. ``q_offset`` is the absolute
    position of query row 0 (scalar or traced), for decode/chunked prefill."""
    q_pos = jnp.arange(q_len)[:, None] + q_offset
    kv_pos = jnp.arange(kv_len)[None, :]
    return kv_pos <= q_pos


def sliding_window_mask(q_len: int, kv_len: int, window: int, q_offset=0) -> jnp.ndarray:
    """Causal AND within-window mask (≈ SWA masks, `models/model_base.py:287-363`)."""
    q_pos = jnp.arange(q_len)[:, None] + q_offset
    kv_pos = jnp.arange(kv_len)[None, :]
    return (kv_pos <= q_pos) & (kv_pos > q_pos - window)


def attend(
    q: jnp.ndarray,            # (B, n_q, S_q, D)
    k: jnp.ndarray,            # (B, n_kv, S_kv, D)
    v: jnp.ndarray,            # (B, n_kv, S_kv, D)
    mask: Optional[jnp.ndarray] = None,   # broadcastable to (B, n_q, S_q, S_kv); True=keep
    scale: Optional[float] = None,
    logits_soft_cap: Optional[float] = None,
    sinks: Optional[jnp.ndarray] = None,  # (n_q,) learned attention sinks (gpt-oss style)
    bias: Optional[jnp.ndarray] = None,   # additive (B|1, n_q, S_q, S_kv) (ALiBi)
) -> jnp.ndarray:
    """Masked GQA attention, softmax in fp32. Returns (B, n_q, S_q, D) in q.dtype.

    Grouped-query form: q is reshaped to (B, n_kv, rep, S_q, D) and contracted against
    the UNEXPANDED k/v — a `repeat_kv` materialization would stream rep x the KV bytes
    through HBM every decode step (the decode hot path is KV-bandwidth-bound, which is
    why the reference hand-fuses its TKG kernels, `attention_base.py:1679-1994`).
    """
    b, n_q, s_q, d = q.shape
    n_kv = k.shape[1]
    if n_q % n_kv != 0:
        raise ValueError(f"n_q {n_q} not divisible by n_kv {n_kv}")
    rep = n_q // n_kv
    if scale is None:
        scale = d ** -0.5

    qg = q.reshape(b, n_kv, rep, s_q, d)
    scores = jnp.einsum("bkrqd,bktd->bkrqt", qg, k,
                        preferred_element_type=jnp.float32) * scale
    if bias is not None:
        scores = scores + bias.reshape(
            bias.shape[0], n_kv, rep, *bias.shape[2:]).astype(jnp.float32)
    if logits_soft_cap is not None:
        scores = logits_soft_cap * jnp.tanh(scores / logits_soft_cap)
    if mask is not None:
        # masks arrive (B, heads|1, S_q, S_kv); lift to the grouped layout
        if mask.ndim == 4 and mask.shape[1] == 1:
            gmask = mask[:, :, None]
        elif mask.ndim == 4:
            gmask = mask.reshape(b, n_kv, rep, *mask.shape[2:])
        else:
            gmask = mask
        scores = jnp.where(gmask, scores, NEG_INF)

    if sinks is not None:
        # learned sink logit per head participates in the softmax denominator only
        sink = jnp.broadcast_to(
            sinks.astype(jnp.float32).reshape(n_kv, rep)[None, :, :, None, None],
            scores.shape[:4] + (1,))
        scores = jnp.concatenate([scores, sink], axis=-1)
        probs = jnp.exp(scores - jnp.max(scores, axis=-1, keepdims=True))
        probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
        probs = probs[..., :-1]
    else:
        probs = jnp.exp(scores - jnp.max(scores, axis=-1, keepdims=True))
        probs = probs / jnp.sum(probs, axis=-1, keepdims=True)

    out = jnp.einsum("bkrqt,bktd->bkrqd", probs.astype(q.dtype), v)
    return out.reshape(b, n_q, s_q, d)
