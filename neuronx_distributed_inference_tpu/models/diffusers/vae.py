"""VAE decoder (diffusers AutoencoderKL decoder, as used by Flux).

≈ reference `models/diffusers/flux/` vae (216 LoC). Decode-only: latents -> RGB.
Structure: conv_in -> mid (resnet, spatial attention, resnet) -> up blocks (resnets +
nearest-neighbor upsample convs) -> GroupNorm/silu/conv_out. Weight conversion targets
the diffusers naming (`convert_vae_decoder_state_dict`)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Params = Dict[str, Any]


@dataclass(frozen=True)
class VaeDecoderArgs:
    latent_channels: int = 16
    base_channels: int = 128
    channel_mults: Tuple[int, ...] = (1, 2, 4, 4)   # up blocks run reversed
    layers_per_block: int = 3                        # decoder resnets per up block
    out_channels: int = 3
    norm_groups: int = 32
    scaling_factor: float = 0.3611
    shift_factor: float = 0.1159


def _group_norm(x: jnp.ndarray, w, b, groups: int, eps: float = 1e-6):
    """x (B, C, H, W) channelwise GroupNorm (computed f32, cast back to x.dtype)."""
    in_dtype = x.dtype
    bsz, c, h, wd = x.shape
    xg = x.reshape(bsz, groups, c // groups, h, wd).astype(jnp.float32)
    mean = xg.mean(axis=(2, 3, 4), keepdims=True)
    var = xg.var(axis=(2, 3, 4), keepdims=True)
    xg = (xg - mean) * jax.lax.rsqrt(var + eps)
    y = xg.reshape(bsz, c, h, wd)
    return (y * w[None, :, None, None] + b[None, :, None, None]).astype(in_dtype)


def _conv(x: jnp.ndarray, w, b, stride: int = 1, padding: int = 1):
    dn = ("NCHW", "OIHW", "NCHW")
    y = jax.lax.conv_general_dilated(
        x, w, (stride, stride), [(padding, padding)] * 2, dimension_numbers=dn)
    return y + b[None, :, None, None]


def _resnet(p: Params, prefix: str, x, groups: int):
    h = _group_norm(x, p[prefix + "n1_w"], p[prefix + "n1_b"], groups)
    h = _conv(jax.nn.silu(h), p[prefix + "c1_w"], p[prefix + "c1_b"])
    h = _group_norm(h, p[prefix + "n2_w"], p[prefix + "n2_b"], groups)
    h = _conv(jax.nn.silu(h), p[prefix + "c2_w"], p[prefix + "c2_b"])
    if prefix + "sc_w" in p:
        x = _conv(x, p[prefix + "sc_w"], p[prefix + "sc_b"], padding=0)
    return x + h


def _attn(p: Params, x, groups: int):
    bsz, c, hh, ww = x.shape
    h = _group_norm(x, p["attn_n_w"], p["attn_n_b"], groups)
    flat = h.reshape(bsz, c, hh * ww).transpose(0, 2, 1)    # (B, HW, C)
    q = flat @ p["attn_q_w"] + p["attn_q_b"]
    k = flat @ p["attn_k_w"] + p["attn_k_b"]
    v = flat @ p["attn_v_w"] + p["attn_v_b"]
    scores = (q @ k.transpose(0, 2, 1)).astype(jnp.float32) * (c ** -0.5)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = (probs @ v) @ p["attn_o_w"] + p["attn_o_b"]
    return x + out.transpose(0, 2, 1).reshape(bsz, c, hh, ww)


def vae_decode(params: Params, latents: jnp.ndarray, args: VaeDecoderArgs
               ) -> jnp.ndarray:
    """(B, latent_channels, h, w) -> (B, 3, h*8, w*8) in [-1, 1]."""
    g = args.norm_groups
    z = latents / args.scaling_factor + args.shift_factor
    x = _conv(z, params["conv_in_w"], params["conv_in_b"])
    x = _resnet(params, "mid_r1_", x, g)
    x = _attn(params, x, g)
    x = _resnet(params, "mid_r2_", x, g)
    n_up = len(args.channel_mults)
    for u in range(n_up):
        for r in range(args.layers_per_block):
            x = _resnet(params, f"up{u}_r{r}_", x, g)
        if u < n_up - 1:
            b, c, hh, ww = x.shape
            x = jax.image.resize(x, (b, c, hh * 2, ww * 2), method="nearest")
            x = _conv(x, params[f"up{u}_up_w"], params[f"up{u}_up_b"])
    x = _group_norm(x, params["out_n_w"], params["out_n_b"], g)
    return _conv(jax.nn.silu(x), params["conv_out_w"], params["conv_out_b"])


def convert_vae_decoder_state_dict(sd, args: VaeDecoderArgs) -> Params:
    """diffusers AutoencoderKL ``decoder.*`` keys -> flat param dict."""
    out: Params = {}

    def put(dst, src):
        out[dst + "_w"] = np.asarray(sd[f"decoder.{src}.weight"])
        out[dst + "_b"] = np.asarray(sd[f"decoder.{src}.bias"])

    def resnet(dst, src):
        put(dst + "n1", src + ".norm1")
        put(dst + "c1", src + ".conv1")
        put(dst + "n2", src + ".norm2")
        put(dst + "c2", src + ".conv2")
        if f"decoder.{src}.conv_shortcut.weight" in sd:
            put(dst + "sc", src + ".conv_shortcut")

    put("conv_in", "conv_in")
    resnet("mid_r1_", "mid_block.resnets.0")
    resnet("mid_r2_", "mid_block.resnets.1")
    out["attn_n_w"] = np.asarray(sd["decoder.mid_block.attentions.0.group_norm.weight"])
    out["attn_n_b"] = np.asarray(sd["decoder.mid_block.attentions.0.group_norm.bias"])
    for ours, theirs in (("q", "to_q"), ("k", "to_k"), ("v", "to_v"),
                         ("o", "to_out.0")):
        w = np.asarray(sd[f"decoder.mid_block.attentions.0.{theirs}.weight"])
        out[f"attn_{ours}_w"] = np.ascontiguousarray(w.reshape(w.shape[0], -1).T)
        out[f"attn_{ours}_b"] = np.asarray(
            sd[f"decoder.mid_block.attentions.0.{theirs}.bias"])
    for u in range(len(args.channel_mults)):
        for r in range(args.layers_per_block):
            resnet(f"up{u}_r{r}_", f"up_blocks.{u}.resnets.{r}")
        if f"decoder.up_blocks.{u}.upsamplers.0.conv.weight" in sd:
            put(f"up{u}_up", f"up_blocks.{u}.upsamplers.0.conv")
    put("out_n", "conv_norm_out")
    put("conv_out", "conv_out")
    return out


def init_vae_decoder_params(args: VaeDecoderArgs, key, dtype=np.float32) -> Params:
    """Random decoder params in the converted layout (tests)."""
    dtype = np.dtype(jnp.dtype(dtype).name) if hasattr(jnp, "dtype") else dtype
    rng = np.random.default_rng(int(jax.random.randint(key, (), 0, 2 ** 31 - 1)))
    mults = list(reversed(args.channel_mults))
    top = args.base_channels * mults[0]
    p: Params = {}

    def conv(name, cin, cout, k=3):
        p[name + "_w"] = (rng.standard_normal((cout, cin, k, k)) * 0.02
                          ).astype(np.float32)
        p[name + "_b"] = np.zeros((cout,), np.float32)

    def norm(name, c):
        p[name + "_w"] = np.ones((c,), np.float32)
        p[name + "_b"] = np.zeros((c,), np.float32)

    def resnet(prefix, cin, cout):
        norm(prefix + "n1", cin)
        conv(prefix + "c1", cin, cout)
        norm(prefix + "n2", cout)
        conv(prefix + "c2", cout, cout)
        if cin != cout:
            conv(prefix + "sc", cin, cout, k=1)

    conv("conv_in", args.latent_channels, top)
    resnet("mid_r1_", top, top)
    resnet("mid_r2_", top, top)
    norm("attn_n", top)
    for n in ("q", "k", "v", "o"):
        p[f"attn_{n}_w"] = (rng.standard_normal((top, top)) * 0.02).astype(np.float32)
        p[f"attn_{n}_b"] = np.zeros((top,), np.float32)
    cin = top
    for u, m in enumerate(mults):
        cout = args.base_channels * m
        for r in range(args.layers_per_block):
            resnet(f"up{u}_r{r}_", cin if r == 0 else cout, cout)
        cin = cout
        if u < len(mults) - 1:
            conv(f"up{u}_up", cout, cout)
    norm("out_n", cin)
    conv("conv_out", cin, args.out_channels)
    return {k: np.asarray(v).astype(dtype) for k, v in p.items()}
