"""Text encoders for the diffusion pipeline: T5 encoder + CLIP text model.

≈ reference `models/diffusers/flux/` t5 (903 LoC) and clip (601 LoC) ports. Functional
JAX implementations parity-tested against the transformers CPU models
(tests/test_diffusion.py); both are pure encoders (single forward, no KV cache), so
they compile to one jitted call each.
"""

from __future__ import annotations

import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ...ops.norms import layer_norm, rms_norm

Params = Dict[str, Any]


# --- T5 encoder -----------------------------------------------------------------------


def t5_relative_buckets(q_len: int, k_len: int, num_buckets: int = 32,
                        max_distance: int = 128) -> np.ndarray:
    """Bidirectional relative-position bucket ids (HF `_relative_position_bucket`)."""
    ctx = np.arange(q_len)[:, None]
    mem = np.arange(k_len)[None, :]
    rel = mem - ctx
    nb = num_buckets // 2
    out = (rel > 0).astype(np.int64) * nb
    rel = np.abs(rel)
    max_exact = nb // 2
    is_small = rel < max_exact
    large = max_exact + (np.log(np.maximum(rel, 1) / max_exact)
                         / np.log(max_distance / max_exact)
                         * (nb - max_exact)).astype(np.int64)
    large = np.minimum(large, nb - 1)
    return out + np.where(is_small, rel, large)


def t5_encode(params: Params, input_ids: jnp.ndarray, attention_mask: jnp.ndarray,
              *, num_heads: int, num_buckets: int = 32, max_distance: int = 128,
              eps: float = 1e-6) -> jnp.ndarray:
    """(B, S) ids -> (B, S, H) encoder states (HF T5EncoderModel)."""
    b, s = input_ids.shape
    h = jnp.take(params["embed"], input_ids, axis=0)
    buckets = t5_relative_buckets(s, s, num_buckets, max_distance)
    # (S, S) buckets -> (heads, S, S) learned bias, shared across layers
    bias = jnp.take(params["rel_bias"], jnp.asarray(buckets), axis=0)  # (S, S, heads)
    bias = bias.transpose(2, 0, 1)[None]                               # (1, h, S, S)
    neg = jnp.finfo(jnp.float32).min
    bias = bias + jnp.where(attention_mask[:, None, None, :] > 0, 0.0, neg)

    def block(hid, lp):
        hn = rms_norm(hid, lp["ln1"], eps)
        q = (hn @ lp["wq"]).reshape(b, s, num_heads, -1).transpose(0, 2, 1, 3)
        k = (hn @ lp["wk"]).reshape(b, s, num_heads, -1).transpose(0, 2, 1, 3)
        v = (hn @ lp["wv"]).reshape(b, s, num_heads, -1).transpose(0, 2, 1, 3)
        # T5 uses NO 1/sqrt(d) scaling (folded into init)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) + bias
        probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
        attn = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
        attn = attn.transpose(0, 2, 1, 3).reshape(b, s, -1)
        hid = hid + attn @ lp["wo"]
        hn = rms_norm(hid, lp["ln2"], eps)
        gelu = jax.nn.gelu(hn @ lp["wi0"], approximate=True)
        hid = hid + (gelu * (hn @ lp["wi1"])) @ lp["wo2"]
        return hid, None

    h, _ = jax.lax.scan(block, h, params["layers"])
    return rms_norm(h, params["final_ln"], eps)


def convert_t5_state_dict(sd, num_layers: int) -> Params:
    def linear_t(name):
        return np.ascontiguousarray(sd[name].T)

    layers = {k: [] for k in ("ln1", "wq", "wk", "wv", "wo", "ln2",
                              "wi0", "wi1", "wo2")}
    for i in range(num_layers):
        p = f"encoder.block.{i}."
        layers["ln1"].append(sd[p + "layer.0.layer_norm.weight"])
        layers["wq"].append(linear_t(p + "layer.0.SelfAttention.q.weight"))
        layers["wk"].append(linear_t(p + "layer.0.SelfAttention.k.weight"))
        layers["wv"].append(linear_t(p + "layer.0.SelfAttention.v.weight"))
        layers["wo"].append(linear_t(p + "layer.0.SelfAttention.o.weight"))
        layers["ln2"].append(sd[p + "layer.1.layer_norm.weight"])
        layers["wi0"].append(linear_t(p + "layer.1.DenseReluDense.wi_0.weight"))
        layers["wi1"].append(linear_t(p + "layer.1.DenseReluDense.wi_1.weight"))
        layers["wo2"].append(linear_t(p + "layer.1.DenseReluDense.wo.weight"))
    return {
        "embed": sd["shared.weight"],
        "rel_bias": sd["encoder.block.0.layer.0.SelfAttention."
                       "relative_attention_bias.weight"],   # (buckets, heads)
        "layers": {k: np.stack(v) for k, v in layers.items()},
        "final_ln": sd["encoder.final_layer_norm.weight"],
    }


# --- CLIP text model ------------------------------------------------------------------


def clip_text_encode(params: Params, input_ids: jnp.ndarray, *, num_heads: int,
                     eos_token_id: int, eps: float = 1e-5,
                     act: str = "quick_gelu"):
    """(B, S) -> (last_hidden (B, S, H), pooled (B, H)) (HF CLIPTextModel).

    Pooled output = final-LN hidden at each row's eos token (argmax-of-eos like HF)."""
    b, s = input_ids.shape
    h = jnp.take(params["embed"], input_ids, axis=0)
    h = h + params["pos_embed"][:s]
    causal = np.triu(np.full((s, s), np.finfo(np.float32).min), k=1)
    causal = jnp.asarray(causal)[None, None]
    act_fn = (lambda x: x * jax.nn.sigmoid(1.702 * x)) if act == "quick_gelu" \
        else functools.partial(jax.nn.gelu, approximate=False)

    def block(hid, lp):
        hn = layer_norm(hid, lp["ln1_w"], lp["ln1_b"], eps=eps)
        q = (hn @ lp["wq"] + lp["bq"]).reshape(b, s, num_heads, -1).transpose(0, 2, 1, 3)
        k = (hn @ lp["wk"] + lp["bk"]).reshape(b, s, num_heads, -1).transpose(0, 2, 1, 3)
        v = (hn @ lp["wv"] + lp["bv"]).reshape(b, s, num_heads, -1).transpose(0, 2, 1, 3)
        d = q.shape[-1]
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32)
        scores = scores * (d ** -0.5) + causal
        probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
        attn = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
        attn = attn.transpose(0, 2, 1, 3).reshape(b, s, -1)
        hid = hid + (attn @ lp["wo"] + lp["bo"])
        hn = layer_norm(hid, lp["ln2_w"], lp["ln2_b"], eps=eps)
        hid = hid + (act_fn(hn @ lp["fc1"] + lp["b1"]) @ lp["fc2"] + lp["b2"])
        return hid, None

    h, _ = jax.lax.scan(block, h, params["layers"])
    h = layer_norm(h, params["final_w"], params["final_b"], eps=eps)
    if eos_token_id == 2:
        # HF keeps the pre-#24773 legacy behavior for configs with eos_token_id == 2
        # (OpenAI CLIP): pooled position = argmax of the RAW token ids
        eos_pos = jnp.argmax(input_ids, axis=-1)
    else:
        eos_pos = jnp.argmax((input_ids == eos_token_id).astype(jnp.int32), axis=-1)
    pooled = jnp.take_along_axis(h, eos_pos[:, None, None], axis=1)[:, 0]
    return h, pooled


def convert_clip_state_dict(sd, num_layers: int) -> Params:
    def linear_t(name):
        return np.ascontiguousarray(sd[name].T)

    pre = "text_model."
    layers = {k: [] for k in ("ln1_w", "ln1_b", "wq", "bq", "wk", "bk", "wv", "bv",
                              "wo", "bo", "ln2_w", "ln2_b", "fc1", "b1", "fc2", "b2")}
    for i in range(num_layers):
        p = f"{pre}encoder.layers.{i}."
        layers["ln1_w"].append(sd[p + "layer_norm1.weight"])
        layers["ln1_b"].append(sd[p + "layer_norm1.bias"])
        for t, name in (("q", "q_proj"), ("k", "k_proj"), ("v", "v_proj"),
                        ("o", "out_proj")):
            layers[f"w{t}"].append(linear_t(p + f"self_attn.{name}.weight"))
            layers[f"b{t}"].append(sd[p + f"self_attn.{name}.bias"])
        layers["ln2_w"].append(sd[p + "layer_norm2.weight"])
        layers["ln2_b"].append(sd[p + "layer_norm2.bias"])
        layers["fc1"].append(linear_t(p + "mlp.fc1.weight"))
        layers["b1"].append(sd[p + "mlp.fc1.bias"])
        layers["fc2"].append(linear_t(p + "mlp.fc2.weight"))
        layers["b2"].append(sd[p + "mlp.fc2.bias"])
    return {
        "embed": sd[pre + "embeddings.token_embedding.weight"],
        "pos_embed": sd[pre + "embeddings.position_embedding.weight"],
        "layers": {k: np.stack(v) for k, v in layers.items()},
        "final_w": sd[pre + "final_layer_norm.weight"],
        "final_b": sd[pre + "final_layer_norm.bias"],
    }
