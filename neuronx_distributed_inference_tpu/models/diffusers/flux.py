"""Flux.1 (rectified-flow MMDiT) pipeline: transformer, scheduler, VAE decoder.

≈ reference `models/diffusers/flux/` (1407 LoC transformer + application). Follows the
published Flux architecture (double-stream + single-stream MMDiT with AdaLN-Zero
modulation, 3-axis rope, qk RMS norm; flow-matching Euler scheduler; AutoencoderKL
decoder). Weight conversion targets the diffusers checkpoint naming
(``convert_flux_state_dict``); the environment ships no `diffusers`, so numerical
parity against the reference pipeline runs wherever diffusers is importable, while
in-repo tests cover shapes, determinism, scheduler math, and the end-to-end pipeline
on random weights (tests/test_diffusion.py).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...ops.norms import layer_norm, rms_norm

Params = Dict[str, Any]


@dataclass(frozen=True)
class FluxArchArgs:
    hidden_size: int = 3072          # num_attention_heads * attention_head_dim
    num_heads: int = 24
    num_double_layers: int = 19
    num_single_layers: int = 38
    in_channels: int = 64            # packed 2x2 latent patches (16 ch * 4)
    joint_dim: int = 4096            # T5 hidden size
    pooled_dim: int = 768            # CLIP pooled size
    axes_dims: Tuple[int, ...] = (16, 56, 56)   # rope axes (id, y, x)
    guidance_embeds: bool = True
    mlp_ratio: float = 4.0

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


# --- embeddings / rope ----------------------------------------------------------------


def timestep_embedding(t: jnp.ndarray, dim: int, max_period: float = 10000.0):
    """Sinusoidal (diffusers Timesteps, flip_sin_to_cos=True): (B,) -> (B, dim)."""
    half = dim // 2
    freqs = jnp.exp(-np.log(max_period) * jnp.arange(half) / half)
    ang = t.astype(jnp.float32)[:, None] * freqs[None]
    return jnp.concatenate([jnp.cos(ang), jnp.sin(ang)], axis=-1)


def _mlp_embed(p: Params, prefix: str, x: jnp.ndarray) -> jnp.ndarray:
    h = jax.nn.silu(x @ p[prefix + "w1"] + p[prefix + "b1"])
    return h @ p[prefix + "w2"] + p[prefix + "b2"]


def flux_rope(ids: jnp.ndarray, axes_dims: Tuple[int, ...], theta: float = 10000.0):
    """3-axis rotary tables from position ids (S, n_axes) -> cos/sin (S, head_dim/2)
    in the interleaved-pair convention (Flux applies rope on (d/2, 2) pairs)."""
    outs_cos, outs_sin = [], []
    for a, dim in enumerate(axes_dims):
        pos = ids[:, a].astype(jnp.float32)
        freqs = 1.0 / (theta ** (jnp.arange(0, dim, 2) / dim))
        ang = pos[:, None] * freqs[None]
        outs_cos.append(jnp.cos(ang))
        outs_sin.append(jnp.sin(ang))
    return jnp.concatenate(outs_cos, -1), jnp.concatenate(outs_sin, -1)


def _apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray) -> jnp.ndarray:
    """x (B, h, S, D) with interleaved complex pairs; cos/sin (S, D/2)."""
    xr = x.reshape(*x.shape[:-1], -1, 2)
    x0, x1 = xr[..., 0], xr[..., 1]
    c = cos[None, None]
    s = sin[None, None]
    out = jnp.stack([x0 * c - x1 * s, x0 * s + x1 * c], axis=-1)
    return out.reshape(x.shape).astype(x.dtype)


# --- blocks ---------------------------------------------------------------------------


def _qk_norm(q, k, lp, eps=1e-6):
    q = rms_norm(q, lp["q_norm"], eps)
    k = rms_norm(k, lp["k_norm"], eps)
    return q, k


def _attention(q, k, v, cos, sin):
    q = _apply_rope(q, cos, sin)
    k = _apply_rope(k, cos, sin)
    d = q.shape[-1]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * (d ** -0.5)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def double_block(lp: Params, args: FluxArchArgs, img, txt, temb, cos, sin):
    """Double-stream MMDiT block (joint attention over [txt; img])."""
    b = img.shape[0]
    nh, d = args.num_heads, args.head_dim
    mod_img = jax.nn.silu(temb) @ lp["img_mod_w"] + lp["img_mod_b"]   # (B, 6H)
    mod_txt = jax.nn.silu(temb) @ lp["txt_mod_w"] + lp["txt_mod_b"]
    im = jnp.split(mod_img[:, None], 6, axis=-1)   # each (B, 1, H)
    tm = jnp.split(mod_txt[:, None], 6, axis=-1)

    def heads(x, w, bias):
        y = x @ w + bias
        return y.reshape(b, -1, 3, nh, d).transpose(2, 0, 3, 1, 4)   # (3, B, h, S, D)

    img_n = layer_norm(img, jnp.ones(img.shape[-1]), jnp.zeros(img.shape[-1]))
    img_n = img_n * (1 + im[1]) + im[0]
    txt_n = layer_norm(txt, jnp.ones(txt.shape[-1]), jnp.zeros(txt.shape[-1]))
    txt_n = txt_n * (1 + tm[1]) + tm[0]

    qi, ki, vi = heads(img_n, lp["img_qkv_w"], lp["img_qkv_b"])
    qt, kt, vt = heads(txt_n, lp["txt_qkv_w"], lp["txt_qkv_b"])
    qi, ki = _qk_norm(qi, ki, {"q_norm": lp["img_q_norm"], "k_norm": lp["img_k_norm"]})
    qt, kt = _qk_norm(qt, kt, {"q_norm": lp["txt_q_norm"], "k_norm": lp["txt_k_norm"]})
    q = jnp.concatenate([qt, qi], axis=2)          # txt first (Flux convention)
    k = jnp.concatenate([kt, ki], axis=2)
    v = jnp.concatenate([vt, vi], axis=2)
    attn = _attention(q, k, v, cos, sin)
    attn = attn.transpose(0, 2, 1, 3).reshape(b, -1, nh * d)
    t_len = txt.shape[1]
    txt_attn, img_attn = attn[:, :t_len], attn[:, t_len:]

    img = img + im[2] * (img_attn @ lp["img_out_w"] + lp["img_out_b"])
    txt = txt + tm[2] * (txt_attn @ lp["txt_out_w"] + lp["txt_out_b"])

    img_n2 = layer_norm(img, jnp.ones(img.shape[-1]), jnp.zeros(img.shape[-1]))
    img_n2 = img_n2 * (1 + im[4]) + im[3]
    img = img + im[5] * (jax.nn.gelu(img_n2 @ lp["img_mlp1_w"] + lp["img_mlp1_b"],
                                     approximate=True)
                         @ lp["img_mlp2_w"] + lp["img_mlp2_b"])
    txt_n2 = layer_norm(txt, jnp.ones(txt.shape[-1]), jnp.zeros(txt.shape[-1]))
    txt_n2 = txt_n2 * (1 + tm[4]) + tm[3]
    txt = txt + tm[5] * (jax.nn.gelu(txt_n2 @ lp["txt_mlp1_w"] + lp["txt_mlp1_b"],
                                     approximate=True)
                         @ lp["txt_mlp2_w"] + lp["txt_mlp2_b"])
    return img, txt


def single_block(lp: Params, args: FluxArchArgs, x, temb, cos, sin):
    """Single-stream block: parallel attention + MLP with shared AdaLN-Zero."""
    b, s, hdim = x.shape
    nh, d = args.num_heads, args.head_dim
    mod = jax.nn.silu(temb) @ lp["mod_w"] + lp["mod_b"]      # (B, 3H)
    shift, scale, gate = jnp.split(mod[:, None], 3, axis=-1)
    xn = layer_norm(x, jnp.ones(hdim), jnp.zeros(hdim)) * (1 + scale) + shift
    qkv = xn @ lp["qkv_w"] + lp["qkv_b"]
    q, k, v = (qkv.reshape(b, s, 3, nh, d).transpose(2, 0, 3, 1, 4))
    q, k = _qk_norm(q, k, lp)
    attn = _attention(q, k, v, cos, sin).transpose(0, 2, 1, 3).reshape(b, s, hdim)
    mlp = jax.nn.gelu(xn @ lp["mlp_w"] + lp["mlp_b"], approximate=True)
    out = jnp.concatenate([attn, mlp], axis=-1) @ lp["out_w"] + lp["out_b"]
    return x + gate * out


def flux_forward(params: Params, args: FluxArchArgs, latents, txt, pooled,
                 timestep, img_ids, txt_ids, guidance=None):
    """One denoising step of the MMDiT.

    latents (B, S_img, in_channels) packed 2x2 patches; txt (B, S_txt, joint_dim);
    pooled (B, pooled_dim); timestep (B,) in [0, 1]; ids (S, 3)."""
    img = latents @ params["x_embed_w"] + params["x_embed_b"]
    txt_h = txt @ params["ctx_embed_w"] + params["ctx_embed_b"]

    temb = _mlp_embed(params, "time_", timestep_embedding(timestep * 1000.0, 256))
    temb = temb + _mlp_embed(params, "text_", pooled)
    if args.guidance_embeds:
        g = guidance if guidance is not None else jnp.ones_like(timestep)
        temb = temb + _mlp_embed(params, "guide_",
                                 timestep_embedding(g * 1000.0, 256))

    ids = jnp.concatenate([txt_ids, img_ids], axis=0)
    cos, sin = flux_rope(ids, args.axes_dims)

    def dbl(carry, lp):
        img, txt_h = carry
        img, txt_h = double_block(lp, args, img, txt_h, temb, cos, sin)
        return (img, txt_h), None

    (img, txt_h), _ = jax.lax.scan(dbl, (img, txt_h), params["double"])

    x = jnp.concatenate([txt_h, img], axis=1)

    def sgl(carry, lp):
        return single_block(lp, args, carry, temb, cos, sin), None

    x, _ = jax.lax.scan(sgl, x, params["single"])
    img = x[:, txt_h.shape[1]:]

    # diffusers AdaLayerNormContinuous chunk order is (scale, shift)
    mod = jax.nn.silu(temb) @ params["final_mod_w"] + params["final_mod_b"]
    scale, shift = jnp.split(mod[:, None], 2, axis=-1)
    img = layer_norm(img, jnp.ones(img.shape[-1]), jnp.zeros(img.shape[-1]))
    img = img * (1 + scale) + shift
    return img @ params["proj_out_w"] + params["proj_out_b"]


# --- flow-matching Euler scheduler ----------------------------------------------------


def flux_time_shift(mu: float, sigma: np.ndarray) -> np.ndarray:
    """Dynamic shifting: exp(mu) / (exp(mu) + (1/sigma - 1))."""
    return np.exp(mu) / (np.exp(mu) + (1 / np.maximum(sigma, 1e-9) - 1))


def flux_mu(seq_len: int, base_len: int = 256, max_len: int = 4096,
            base_shift: float = 0.5, max_shift: float = 1.15) -> float:
    m = (max_shift - base_shift) / (max_len - base_len)
    return seq_len * m + (base_shift - base_len * m)


def scheduler_sigmas(num_steps: int, image_seq_len: Optional[int] = None,
                     shift: float = 3.0) -> np.ndarray:
    """Sigma schedule (1 -> 0), with Flux dynamic shifting when image_seq_len given."""
    sigmas = np.linspace(1.0, 1.0 / num_steps, num_steps)
    if image_seq_len is not None:
        sigmas = flux_time_shift(flux_mu(image_seq_len), sigmas)
    else:
        sigmas = shift * sigmas / (1 + (shift - 1) * sigmas)
    return np.concatenate([sigmas, [0.0]]).astype(np.float32)


def euler_step(latents, model_out, sigma: float, sigma_next: float):
    """Rectified-flow Euler: x_{t+1} = x_t + (sigma_next - sigma) * v."""
    return latents + (sigma_next - sigma) * model_out


# --- latent pack / unpack + pipeline --------------------------------------------------


def pack_latents(lat: jnp.ndarray) -> jnp.ndarray:
    """(B, C, H, W) -> (B, H/2*W/2, C*4) 2x2 patch packing."""
    b, c, h, w = lat.shape
    x = lat.reshape(b, c, h // 2, 2, w // 2, 2)
    return x.transpose(0, 2, 4, 1, 3, 5).reshape(b, (h // 2) * (w // 2), c * 4)


def unpack_latents(x: jnp.ndarray, h: int, w: int) -> jnp.ndarray:
    b, _, c4 = x.shape
    c = c4 // 4
    x = x.reshape(b, h // 2, w // 2, c, 2, 2)
    return x.transpose(0, 3, 1, 4, 2, 5).reshape(b, c, h, w)


def image_ids(h: int, w: int) -> np.ndarray:
    """(h/2*w/2, 3) rope ids (0, row, col) for the packed latent grid."""
    hh, ww = h // 2, w // 2
    ids = np.zeros((hh, ww, 3), dtype=np.int32)
    ids[..., 1] = np.arange(hh)[:, None]
    ids[..., 2] = np.arange(ww)[None, :]
    return ids.reshape(-1, 3)


class FluxPipeline:
    """Text-to-image sampling loop (≈ reference FluxApplication,
    `models/diffusers/flux/application.py`): CLIP pooled + T5 sequence conditioning,
    rectified-flow Euler over the MMDiT, VAE decode."""

    def __init__(self, args: FluxArchArgs, params: Params,
                 t5_encode_fn=None, clip_encode_fn=None, vae_decode_fn=None):
        self.args = args
        self.params = params
        self.t5_encode = t5_encode_fn
        self.clip_encode = clip_encode_fn
        self.vae_decode = vae_decode_fn
        self._step = jax.jit(functools.partial(flux_forward, args=args))

    def __call__(self, txt_embeds, pooled, *, height: int = 64, width: int = 64,
                 num_steps: int = 4, guidance_scale: float = 3.5, seed: int = 0):
        b = txt_embeds.shape[0]
        c = self.args.in_channels // 4
        lat = jax.random.normal(jax.random.PRNGKey(seed),
                                (b, c, height, width), dtype=jnp.float32)
        x = pack_latents(lat)
        img_ids = jnp.asarray(image_ids(height, width))
        txt_ids = jnp.zeros((txt_embeds.shape[1], 3), dtype=jnp.int32)
        sigmas = scheduler_sigmas(num_steps, image_seq_len=x.shape[1])
        guidance = jnp.full((b,), guidance_scale, dtype=jnp.float32)
        for i in range(num_steps):
            t = jnp.full((b,), sigmas[i], dtype=jnp.float32)
            v = self._step(self.params, latents=x, txt=txt_embeds, pooled=pooled,
                           timestep=t, img_ids=img_ids, txt_ids=txt_ids,
                           guidance=guidance)
            x = euler_step(x, v, float(sigmas[i]), float(sigmas[i + 1]))
        lat = unpack_latents(x, height, width)
        if self.vae_decode is not None:
            return self.vae_decode(lat)
        return lat


# --- diffusers checkpoint conversion --------------------------------------------------


def convert_flux_state_dict(sd: Dict[str, np.ndarray], args: FluxArchArgs) -> Params:
    """diffusers `FluxTransformer2DModel` state dict -> the stacked param pytree.

    (The environment ships no `diffusers`, so this path is exercised wherever real
    Flux checkpoints are available; layouts follow the published diffusers naming.)"""

    def lt(name):
        return np.ascontiguousarray(sd[name].T)

    def qkv(prefix, names=("to_q", "to_k", "to_v")):
        return (np.concatenate([lt(f"{prefix}.{n}.weight") for n in names], axis=1),
                np.concatenate([sd[f"{prefix}.{n}.bias"] for n in names], axis=0))

    dbl = []
    for i in range(args.num_double_layers):
        p = f"transformer_blocks.{i}"
        iw, ib = qkv(f"{p}.attn")
        tw, tb = qkv(f"{p}.attn", ("add_q_proj", "add_k_proj", "add_v_proj"))
        dbl.append({
            "img_mod_w": lt(f"{p}.norm1.linear.weight"),
            "img_mod_b": sd[f"{p}.norm1.linear.bias"],
            "txt_mod_w": lt(f"{p}.norm1_context.linear.weight"),
            "txt_mod_b": sd[f"{p}.norm1_context.linear.bias"],
            "img_qkv_w": iw, "img_qkv_b": ib,
            "txt_qkv_w": tw, "txt_qkv_b": tb,
            "img_q_norm": sd[f"{p}.attn.norm_q.weight"],
            "img_k_norm": sd[f"{p}.attn.norm_k.weight"],
            "txt_q_norm": sd[f"{p}.attn.norm_added_q.weight"],
            "txt_k_norm": sd[f"{p}.attn.norm_added_k.weight"],
            "img_out_w": lt(f"{p}.attn.to_out.0.weight"),
            "img_out_b": sd[f"{p}.attn.to_out.0.bias"],
            "txt_out_w": lt(f"{p}.attn.to_add_out.weight"),
            "txt_out_b": sd[f"{p}.attn.to_add_out.bias"],
            "img_mlp1_w": lt(f"{p}.ff.net.0.proj.weight"),
            "img_mlp1_b": sd[f"{p}.ff.net.0.proj.bias"],
            "img_mlp2_w": lt(f"{p}.ff.net.2.weight"),
            "img_mlp2_b": sd[f"{p}.ff.net.2.bias"],
            "txt_mlp1_w": lt(f"{p}.ff_context.net.0.proj.weight"),
            "txt_mlp1_b": sd[f"{p}.ff_context.net.0.proj.bias"],
            "txt_mlp2_w": lt(f"{p}.ff_context.net.2.weight"),
            "txt_mlp2_b": sd[f"{p}.ff_context.net.2.bias"],
        })
    sgl = []
    for i in range(args.num_single_layers):
        p = f"single_transformer_blocks.{i}"
        w, b = qkv(f"{p}.attn")
        sgl.append({
            "mod_w": lt(f"{p}.norm.linear.weight"),
            "mod_b": sd[f"{p}.norm.linear.bias"],
            "qkv_w": w, "qkv_b": b,
            "q_norm": sd[f"{p}.attn.norm_q.weight"],
            "k_norm": sd[f"{p}.attn.norm_k.weight"],
            "mlp_w": lt(f"{p}.proj_mlp.weight"),
            "mlp_b": sd[f"{p}.proj_mlp.bias"],
            "out_w": lt(f"{p}.proj_out.weight"),
            "out_b": sd[f"{p}.proj_out.bias"],
        })

    def stack(dicts):
        return {k: np.stack([d[k] for d in dicts]) for k in dicts[0]}

    t = "time_text_embed."
    params = {
        "x_embed_w": lt("x_embedder.weight"), "x_embed_b": sd["x_embedder.bias"],
        "ctx_embed_w": lt("context_embedder.weight"),
        "ctx_embed_b": sd["context_embedder.bias"],
        "time_w1": lt(t + "timestep_embedder.linear_1.weight"),
        "time_b1": sd[t + "timestep_embedder.linear_1.bias"],
        "time_w2": lt(t + "timestep_embedder.linear_2.weight"),
        "time_b2": sd[t + "timestep_embedder.linear_2.bias"],
        "text_w1": lt(t + "text_embedder.linear_1.weight"),
        "text_b1": sd[t + "text_embedder.linear_1.bias"],
        "text_w2": lt(t + "text_embedder.linear_2.weight"),
        "text_b2": sd[t + "text_embedder.linear_2.bias"],
        "double": stack(dbl), "single": stack(sgl),
        "final_mod_w": lt("norm_out.linear.weight"),
        "final_mod_b": sd["norm_out.linear.bias"],
        "proj_out_w": lt("proj_out.weight"), "proj_out_b": sd["proj_out.bias"],
    }
    if args.guidance_embeds:
        params.update({
            "guide_w1": lt(t + "guidance_embedder.linear_1.weight"),
            "guide_b1": sd[t + "guidance_embedder.linear_1.bias"],
            "guide_w2": lt(t + "guidance_embedder.linear_2.weight"),
            "guide_b2": sd[t + "guidance_embedder.linear_2.bias"],
        })
    return params


# --- random init (tests / synthetic benchmarks) ---------------------------------------


def init_flux_params(args: FluxArchArgs, key, dtype=jnp.float32) -> Params:
    ks = iter(jax.random.split(key, 16))
    H = args.hidden_size
    mlp = int(H * args.mlp_ratio)

    def w(shape, scale=0.02):
        return (jax.random.normal(next(ks), shape) * scale).astype(dtype)

    def stacked(n, shapes):
        k2 = jax.random.split(next(ks), len(shapes))
        return {name: (jax.random.normal(kk, (n,) + shape) * 0.02).astype(dtype)
                if "norm" not in name and name[-1] != "b"
                else (jnp.ones((n,) + shape, dtype) if "norm" in name
                      else jnp.zeros((n,) + shape, dtype))
                for (name, shape), kk in zip(shapes.items(), k2)}

    dbl = stacked(args.num_double_layers, {
        "img_mod_w": (H, 6 * H), "img_mod_b": (6 * H,),
        "txt_mod_w": (H, 6 * H), "txt_mod_b": (6 * H,),
        "img_qkv_w": (H, 3 * H), "img_qkv_b": (3 * H,),
        "txt_qkv_w": (H, 3 * H), "txt_qkv_b": (3 * H,),
        "img_q_norm": (args.head_dim,), "img_k_norm": (args.head_dim,),
        "txt_q_norm": (args.head_dim,), "txt_k_norm": (args.head_dim,),
        "img_out_w": (H, H), "img_out_b": (H,),
        "txt_out_w": (H, H), "txt_out_b": (H,),
        "img_mlp1_w": (H, mlp), "img_mlp1_b": (mlp,),
        "img_mlp2_w": (mlp, H), "img_mlp2_b": (H,),
        "txt_mlp1_w": (H, mlp), "txt_mlp1_b": (mlp,),
        "txt_mlp2_w": (mlp, H), "txt_mlp2_b": (H,),
    })
    sgl = stacked(args.num_single_layers, {
        "mod_w": (H, 3 * H), "mod_b": (3 * H,),
        "qkv_w": (H, 3 * H), "qkv_b": (3 * H,),
        "q_norm": (args.head_dim,), "k_norm": (args.head_dim,),
        "mlp_w": (H, mlp), "mlp_b": (mlp,),
        "out_w": (H + mlp, H), "out_b": (H,),
    })
    params = {
        "x_embed_w": w((args.in_channels, H)), "x_embed_b": jnp.zeros((H,), dtype),
        "ctx_embed_w": w((args.joint_dim, H)), "ctx_embed_b": jnp.zeros((H,), dtype),
        "time_w1": w((256, H)), "time_b1": jnp.zeros((H,), dtype),
        "time_w2": w((H, H)), "time_b2": jnp.zeros((H,), dtype),
        "text_w1": w((args.pooled_dim, H)), "text_b1": jnp.zeros((H,), dtype),
        "text_w2": w((H, H)), "text_b2": jnp.zeros((H,), dtype),
        "guide_w1": w((256, H)), "guide_b1": jnp.zeros((H,), dtype),
        "guide_w2": w((H, H)), "guide_b2": jnp.zeros((H,), dtype),
        "double": dbl, "single": sgl,
        "final_mod_w": w((H, 2 * H)), "final_mod_b": jnp.zeros((2 * H,), dtype),
        "proj_out_w": w((H, args.in_channels)),
        "proj_out_b": jnp.zeros((args.in_channels,), dtype),
    }
    return params
