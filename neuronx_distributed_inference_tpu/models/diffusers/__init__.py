from .flux import (FluxArchArgs, FluxPipeline, convert_flux_state_dict,
                   flux_forward, init_flux_params, scheduler_sigmas)
from .text_encoders import (clip_text_encode, convert_clip_state_dict,
                            convert_t5_state_dict, t5_encode)

__all__ = ["FluxArchArgs", "FluxPipeline", "convert_flux_state_dict",
           "flux_forward", "init_flux_params",
           "scheduler_sigmas", "t5_encode", "clip_text_encode",
           "convert_t5_state_dict", "convert_clip_state_dict"]

from .vae import (VaeDecoderArgs, convert_vae_decoder_state_dict,
                  init_vae_decoder_params, vae_decode)

__all__ += ["VaeDecoderArgs", "vae_decode", "convert_vae_decoder_state_dict",
            "init_vae_decoder_params"]
