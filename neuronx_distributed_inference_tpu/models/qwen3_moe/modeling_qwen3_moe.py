"""Qwen3-MoE model family.

≈ reference `models/qwen3_moe/modeling_qwen3_moe.py` (543 LoC: NeuronQwen3MoeForCausalLM).
Qwen3 attention (qk-norm) + top-k MoE FFN with configurable gate renormalization
(``norm_topk_prob``). All layers must be sparse (``mlp_only_layers`` empty,
``decoder_sparse_step`` 1) — mixed dense/sparse stacks would break the uniform layer
scan and are rejected at config time.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from ...modules import gqa
from ...ops.moe import MoEArgs
from ..base import ModelArchArgs
from ..llama.modeling_llama import LlamaForCausalLM, LlamaInferenceConfig


class Qwen3MoeInferenceConfig(LlamaInferenceConfig):
    REQUIRED_ATTRIBUTES = LlamaInferenceConfig.REQUIRED_ATTRIBUTES + (
        "num_experts", "num_experts_per_tok", "moe_intermediate_size")

    def add_derived_config(self) -> None:
        super().add_derived_config()
        for attr, default in (("norm_topk_prob", True), ("mlp_only_layers", []),
                              ("decoder_sparse_step", 1)):
            if not hasattr(self, attr):
                setattr(self, attr, default)

    def validate(self) -> None:
        super().validate()
        if self.mlp_only_layers or self.decoder_sparse_step != 1:
            raise ValueError(
                "mixed dense/sparse layer stacks are not supported (all layers must "
                "be MoE): mlp_only_layers must be empty and decoder_sparse_step == 1")


class Qwen3MoeForCausalLM(LlamaForCausalLM):
    """≈ NeuronQwen3MoeForCausalLM."""

    @classmethod
    def get_config_cls(cls):
        return Qwen3MoeInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config: Qwen3MoeInferenceConfig) -> ModelArchArgs:
        tp = config.tpu_config.tp_degree
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=gqa.effective_kv_heads(tp, config.num_key_value_heads),
            head_dim=config.head_dim,
            intermediate_size=config.moe_intermediate_size,
            rms_norm_eps=config.rms_norm_eps,
            activation=config.hidden_act,
            qk_norm=True,
            tie_word_embeddings=config.tie_word_embeddings,
            moe=MoEArgs(
                num_experts=config.num_experts,
                experts_per_tok=config.num_experts_per_tok,
                norm_topk_prob=config.norm_topk_prob,
            ),
        )

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config: Qwen3MoeInferenceConfig) -> Dict:
        args = cls.arch_args_from_config(config)
        L, E = config.num_hidden_layers, config.num_experts
        n_kv = config.num_key_value_heads
        d = config.head_dim
        factor = args.num_kv_heads // n_kv

        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return state_dict[name]

        def linear_t(name):
            return np.ascontiguousarray(get(name).T)

        layers = {k: [] for k in ("ln1", "wq", "wk", "wv", "wo", "q_norm", "k_norm",
                                  "ln2", "router", "wg", "wu", "wd")}
        for i in range(L):
            p = f"model.layers.{i}."
            layers["ln1"].append(get(p + "input_layernorm.weight"))
            layers["wq"].append(linear_t(p + "self_attn.q_proj.weight"))
            layers["wk"].append(gqa.replicate_kv_weight(
                linear_t(p + "self_attn.k_proj.weight"), n_kv, d, factor))
            layers["wv"].append(gqa.replicate_kv_weight(
                linear_t(p + "self_attn.v_proj.weight"), n_kv, d, factor))
            layers["wo"].append(linear_t(p + "self_attn.o_proj.weight"))
            layers["q_norm"].append(get(p + "self_attn.q_norm.weight"))
            layers["k_norm"].append(get(p + "self_attn.k_norm.weight"))
            layers["ln2"].append(get(p + "post_attention_layernorm.weight"))
            layers["router"].append(linear_t(p + "mlp.gate.weight"))
            layers["wg"].append(np.stack(
                [linear_t(p + f"mlp.experts.{e}.gate_proj.weight") for e in range(E)]))
            layers["wu"].append(np.stack(
                [linear_t(p + f"mlp.experts.{e}.up_proj.weight") for e in range(E)]))
            layers["wd"].append(np.stack(
                [linear_t(p + f"mlp.experts.{e}.down_proj.weight") for e in range(E)]))

        params = {
            "embed": get("model.embed_tokens.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("model.norm.weight"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
        if not args.tie_word_embeddings:
            params["lm_head"] = np.ascontiguousarray(get("lm_head.weight").T)
        return params
