from .modeling_qwen3_moe import (  # noqa: F401
    Qwen3MoeForCausalLM, Qwen3MoeInferenceConfig)
