"""DBRX (MoE) model family.

≈ reference `models/dbrx/modeling_dbrx.py` (308 LoC: NeuronDbrxForCausalLM; fused Wqkv
+ clip_qkv `:140-162`, 16-expert top-4 MoE ffn `:165-233`, state-dict conversion
`:51-112`). DBRX specifics vs Llama:

- bias-free **LayerNorm** (not RMSNorm) on every norm site (HF `DbrxNormAttentionNorm`),
- fused ``Wqkv`` projection with ``clip_qkv`` clamping,
- router = softmax over all experts then top-k with p-norm renormalization
  (HF ``moe_normalize_expert_weights``, typically 1),
- expert weights stored stacked as (E*I, H) blobs (w1/v1 transposed, w2 already (I, H)).
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from ...modules import gqa
from ...ops.moe import MoEArgs
from ..base import ModelArchArgs
from ..llama.modeling_llama import LlamaForCausalLM
from ...config import InferenceConfig


class DbrxInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("d_model", "n_heads", "n_layers", "vocab_size",
                           "attn_config", "ffn_config")

    def add_derived_config(self) -> None:
        # flatten the nested HF attn/ffn sub-configs into the attrs the base uses
        attn = self.attn_config if isinstance(self.attn_config, dict) else \
            self.attn_config.to_dict()
        ffn = self.ffn_config if isinstance(self.ffn_config, dict) else \
            self.ffn_config.to_dict()
        self.hidden_size = self.d_model
        self.num_attention_heads = self.n_heads
        self.num_hidden_layers = self.n_layers
        self.num_key_value_heads = attn["kv_n_heads"]
        self.head_dim = self.d_model // self.n_heads
        self.rope_theta = attn.get("rope_theta", 10000.0)
        self.clip_qkv = attn.get("clip_qkv")
        self.intermediate_size = ffn["ffn_hidden_size"]
        self.moe_num_experts = ffn["moe_num_experts"]
        self.moe_top_k = ffn["moe_top_k"]
        self.moe_normalize_expert_weights = ffn.get("moe_normalize_expert_weights", 1)
        act = ffn.get("ffn_act_fn") or {}
        self.hidden_act = act.get("name", "silu")
        self.tie_word_embeddings = getattr(self, "tie_word_embeddings", False)
        self.rope_scaling = None


class DbrxForCausalLM(LlamaForCausalLM):
    """≈ NeuronDbrxForCausalLM (`models/dbrx/modeling_dbrx.py:280`)."""

    @classmethod
    def get_config_cls(cls):
        return DbrxInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config: DbrxInferenceConfig) -> ModelArchArgs:
        tp = config.tpu_config.tp_degree
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=gqa.effective_kv_heads(tp, config.num_key_value_heads),
            head_dim=config.head_dim,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=1e-5,               # HF nn.LayerNorm default eps
            norm_type="layer",
            clip_qkv=config.clip_qkv,
            activation=config.hidden_act,
            tie_word_embeddings=config.tie_word_embeddings,
            moe=MoEArgs(
                num_experts=config.moe_num_experts,
                experts_per_tok=config.moe_top_k,
                norm_topk_p=(float(config.moe_normalize_expert_weights)
                             if config.moe_normalize_expert_weights is not None
                             else None),
                norm_topk_prob=False,
            ),
        )

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config: DbrxInferenceConfig) -> Dict:
        args = cls.arch_args_from_config(config)
        L, E, I = (config.num_hidden_layers, config.moe_num_experts,
                   config.intermediate_size)
        H = config.hidden_size
        n_kv, d = config.num_key_value_heads, config.head_dim
        factor = args.num_kv_heads // n_kv
        q_size = config.num_attention_heads * d

        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return state_dict[name]

        layers = {k: [] for k in ("ln1", "wq", "wk", "wv", "wo", "ln2",
                                  "router", "wg", "wu", "wd")}
        for i in range(L):
            p = f"transformer.blocks.{i}."
            layers["ln1"].append(get(p + "norm_attn_norm.norm_1.weight"))
            # fused Wqkv rows: [q (H); k (kv); v (kv)] (HF DbrxAttention.Wqkv)
            wqkv = get(p + "norm_attn_norm.attn.Wqkv.weight")
            wq, wk, wv = (wqkv[:q_size], wqkv[q_size:q_size + n_kv * d],
                          wqkv[q_size + n_kv * d:])
            layers["wq"].append(np.ascontiguousarray(wq.T))
            layers["wk"].append(gqa.replicate_kv_weight(
                np.ascontiguousarray(wk.T), n_kv, d, factor))
            layers["wv"].append(gqa.replicate_kv_weight(
                np.ascontiguousarray(wv.T), n_kv, d, factor))
            layers["wo"].append(np.ascontiguousarray(
                get(p + "norm_attn_norm.attn.out_proj.weight").T))
            layers["ln2"].append(get(p + "norm_attn_norm.norm_2.weight"))
            layers["router"].append(np.ascontiguousarray(
                get(p + "ffn.router.layer.weight").T))
            # w1/v1: (E*I, H) -> (E, H, I); w2: (E*I, H) -> (E, I, H) (already in->out)
            layers["wg"].append(
                get(p + "ffn.experts.mlp.w1").reshape(E, I, H).transpose(0, 2, 1))
            layers["wu"].append(
                get(p + "ffn.experts.mlp.v1").reshape(E, I, H).transpose(0, 2, 1))
            layers["wd"].append(get(p + "ffn.experts.mlp.w2").reshape(E, I, H))

        params = {
            "embed": get("transformer.wte.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("transformer.norm_f.weight"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
        if not args.tie_word_embeddings:
            params["lm_head"] = np.ascontiguousarray(get("lm_head.weight").T)
        return params
