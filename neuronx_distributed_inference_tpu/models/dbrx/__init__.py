from .modeling_dbrx import DbrxForCausalLM, DbrxInferenceConfig

__all__ = ["DbrxForCausalLM", "DbrxInferenceConfig"]
