"""Mixtral (sparse MoE) model family.

≈ reference `models/mixtral/modeling_mixtral.py` (330 LoC: NeuronMixtralForCausalLM,
built on NxD MoE modules via `modules/moe_v2.py`). Llama attention + an 8-expert top-2
MoE FFN per layer (see ops/moe.py for the TPU MoE design and EP sharding).
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from ...modules import gqa
from ...ops.moe import MoEArgs
from ..base import ModelArchArgs
from ..llama.modeling_llama import LlamaForCausalLM, LlamaInferenceConfig


class MixtralInferenceConfig(LlamaInferenceConfig):
    REQUIRED_ATTRIBUTES = LlamaInferenceConfig.REQUIRED_ATTRIBUTES + (
        "num_local_experts", "num_experts_per_tok")

    def add_derived_config(self) -> None:
        super().add_derived_config()
        if not hasattr(self, "sliding_window"):
            self.sliding_window = None


class MixtralForCausalLM(LlamaForCausalLM):
    """≈ NeuronMixtralForCausalLM."""

    @classmethod
    def get_config_cls(cls):
        return MixtralInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config: MixtralInferenceConfig) -> ModelArchArgs:
        tp = config.tpu_config.tp_degree
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=gqa.effective_kv_heads(tp, config.num_key_value_heads),
            head_dim=config.head_dim,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.rms_norm_eps,
            activation=config.hidden_act,
            sliding_window=config.sliding_window,
            tie_word_embeddings=config.tie_word_embeddings,
            moe=MoEArgs(
                num_experts=config.num_local_experts,
                experts_per_tok=config.num_experts_per_tok,
                norm_topk_prob=True,    # HF Mixtral renormalizes top-k weights
            ),
        )

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config: MixtralInferenceConfig) -> Dict:
        args = cls.arch_args_from_config(config)
        L, E = config.num_hidden_layers, config.num_local_experts
        n_kv = config.num_key_value_heads
        d = config.head_dim
        factor = args.num_kv_heads // n_kv

        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return state_dict[name]

        def linear_t(name):
            return np.ascontiguousarray(get(name).T)

        layers = {k: [] for k in ("ln1", "wq", "wk", "wv", "wo", "ln2",
                                  "router", "wg", "wu", "wd")}
        for i in range(L):
            p = f"model.layers.{i}."
            layers["ln1"].append(get(p + "input_layernorm.weight"))
            layers["wq"].append(linear_t(p + "self_attn.q_proj.weight"))
            layers["wk"].append(gqa.replicate_kv_weight(
                linear_t(p + "self_attn.k_proj.weight"), n_kv, d, factor))
            layers["wv"].append(gqa.replicate_kv_weight(
                linear_t(p + "self_attn.v_proj.weight"), n_kv, d, factor))
            layers["wo"].append(linear_t(p + "self_attn.o_proj.weight"))
            layers["ln2"].append(get(p + "post_attention_layernorm.weight"))
            m = p + "block_sparse_moe."
            layers["router"].append(linear_t(m + "gate.weight"))
            # experts: w1 = gate, w3 = up, w2 = down (HF Mixtral naming)
            layers["wg"].append(np.stack(
                [linear_t(m + f"experts.{e}.w1.weight") for e in range(E)]))
            layers["wu"].append(np.stack(
                [linear_t(m + f"experts.{e}.w3.weight") for e in range(E)]))
            layers["wd"].append(np.stack(
                [linear_t(m + f"experts.{e}.w2.weight") for e in range(E)]))

        params = {
            "embed": get("model.embed_tokens.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("model.norm.weight"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
        if not args.tie_word_embeddings:
            params["lm_head"] = np.ascontiguousarray(get("lm_head.weight").T)
        return params
