from .modeling_mixtral import MixtralForCausalLM, MixtralInferenceConfig  # noqa: F401
