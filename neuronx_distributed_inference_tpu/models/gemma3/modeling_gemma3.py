"""Gemma 3 (text) model family.

≈ reference `models/gemma3/modeling_gemma3.py` (361 LoC: NeuronGemma3ForCausalLM).
Architecture deltas vs Llama, all expressed through ModelArchArgs so the shared
functional core (`models/base.py`) runs them inside one `lax.scan`:

- alternating local (sliding-window, RoPE theta 10k) / global (full-attention, RoPE
  theta 1M with linear scaling) layers — ``layer_pattern`` + ``local_rope_theta``;
- sandwich norms: post-attention and post-feedforward RMSNorms applied to the branch
  output before the residual add;
- zero-centered RMSNorm weights ((1 + w) scaling) everywhere, incl. per-head q/k norm;
- embeddings scaled by sqrt(hidden_size); attention scale from query_pre_attn_scalar.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

from ...modules import gqa
from ...ops import rope as rope_ops
from ..base import ModelArchArgs
from ..llama.modeling_llama import LlamaForCausalLM, LlamaInferenceConfig


class Gemma3InferenceConfig(LlamaInferenceConfig):
    def add_derived_config(self) -> None:
        if not hasattr(self, "head_dim") or self.head_dim is None:
            self.head_dim = 256
        for attr, default in (
                ("rope_theta", 1_000_000.0),
                ("rope_local_base_freq", 10_000.0),
                ("query_pre_attn_scalar", 256.0),
                ("sliding_window", 4096),
                ("sliding_window_pattern", 6),
                ("layer_types", None),
                ("hidden_act", "gelu_pytorch_tanh"),
                ("hidden_activation", None),
                ("rms_norm_eps", 1e-6),
                ("rope_scaling", None),
                ("tie_word_embeddings", True),
                ("attention_bias", False),
        ):
            if not hasattr(self, attr):
                setattr(self, attr, default)
        if self.hidden_activation:
            self.hidden_act = self.hidden_activation

    def layer_pattern(self) -> Tuple[str, ...]:
        """Per-layer attention kind; prefers the explicit ``layer_types`` list (newer HF
        configs), else derives from ``sliding_window_pattern`` (every Nth layer full)."""
        if self.layer_types is not None:
            return tuple("sliding" if t == "sliding_attention" else "full"
                         for t in self.layer_types)
        n = self.sliding_window_pattern
        return tuple("full" if (i + 1) % n == 0 else "sliding"
                     for i in range(self.num_hidden_layers))


class Gemma3ForCausalLM(LlamaForCausalLM):
    """≈ NeuronGemma3ForCausalLM."""

    @classmethod
    def get_config_cls(cls):
        return Gemma3InferenceConfig

    @classmethod
    def arch_args_from_config(cls, config: Gemma3InferenceConfig) -> ModelArchArgs:
        tp = config.tpu_config.tp_degree
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=gqa.effective_kv_heads(tp, config.num_key_value_heads),
            head_dim=config.head_dim,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.rms_norm_eps,
            activation=config.hidden_act,
            qk_norm=True,
            sandwich_norms=True,
            zero_centered_norms=True,
            sliding_window=config.sliding_window,
            layer_pattern=config.layer_pattern(),
            local_rope_theta=config.rope_local_base_freq,
            attention_scale=float(config.query_pre_attn_scalar) ** -0.5,
            embedding_multiplier=float(config.hidden_size) ** 0.5,
            tie_word_embeddings=config.tie_word_embeddings,
        )

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config: Gemma3InferenceConfig) -> Dict:
        """Adds gemma's extra per-layer norms on top of the Llama mapping: ln1/ln2 are
        the *pre* norms (input / pre_feedforward), ln1_post/ln2_post the branch-output
        norms (post_attention / post_feedforward)."""
        args = cls.arch_args_from_config(config)
        L = config.num_hidden_layers
        n_kv = config.num_key_value_heads
        d = config.head_dim
        factor = args.num_kv_heads // n_kv

        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return state_dict[name]

        def linear_t(name):
            return np.ascontiguousarray(get(name).T)

        layers = {k: [] for k in ("ln1", "ln1_post", "ln2", "ln2_post", "wq", "wk",
                                  "wv", "wo", "wg", "wu", "wd", "q_norm", "k_norm")}
        for i in range(L):
            p = f"model.layers.{i}."
            layers["ln1"].append(get(p + "input_layernorm.weight"))
            layers["ln1_post"].append(get(p + "post_attention_layernorm.weight"))
            layers["ln2"].append(get(p + "pre_feedforward_layernorm.weight"))
            layers["ln2_post"].append(get(p + "post_feedforward_layernorm.weight"))
            layers["wq"].append(linear_t(p + "self_attn.q_proj.weight"))
            layers["wk"].append(gqa.replicate_kv_weight(
                linear_t(p + "self_attn.k_proj.weight"), n_kv, d, factor))
            layers["wv"].append(gqa.replicate_kv_weight(
                linear_t(p + "self_attn.v_proj.weight"), n_kv, d, factor))
            layers["wo"].append(linear_t(p + "self_attn.o_proj.weight"))
            layers["q_norm"].append(get(p + "self_attn.q_norm.weight"))
            layers["k_norm"].append(get(p + "self_attn.k_norm.weight"))
            layers["wg"].append(linear_t(p + "mlp.gate_proj.weight"))
            layers["wu"].append(linear_t(p + "mlp.up_proj.weight"))
            layers["wd"].append(linear_t(p + "mlp.down_proj.weight"))

        params = {
            "embed": get("model.embed_tokens.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("model.norm.weight"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
            "rope_inv_freq_local": rope_ops.default_inv_freq(
                config.head_dim, config.rope_local_base_freq),
        }
        if not args.tie_word_embeddings:
            params["lm_head"] = np.ascontiguousarray(get("lm_head.weight").T)
        return params
