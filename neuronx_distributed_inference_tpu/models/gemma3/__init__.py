from .modeling_gemma3 import Gemma3ForCausalLM, Gemma3InferenceConfig  # noqa: F401
