from .modeling_whisper import (WhisperForConditionalGeneration,
                               WhisperInferenceConfig)

__all__ = ["WhisperForConditionalGeneration", "WhisperInferenceConfig"]
