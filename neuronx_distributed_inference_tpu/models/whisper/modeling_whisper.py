"""Whisper (speech-to-text encoder-decoder) family.

≈ reference `models/whisper/modeling_whisper.py` (719 LoC: NeuronAudioEncoder :304,
NeuronTextDecoder :345, separate Encoder/Decoder ModelWrapper instances :432-455). TPU
redesign mirrors that split:

- **Audio encoder**: its own jitted function — two 1D convs (k=3; the second stride-2)
  with GELU, additive sinusoidal positions (stored, like HF, as a weight), pre-LN
  attention blocks (biased projections except k), final LayerNorm.
- **Text decoder**: learned positional embeddings, per-layer self-attention over a
  bucketed KV cache plus cross-attention over the encoder states; the cross K/V are
  computed ONCE from the encoder output and carried in the cache pytree — the same
  static-KV pattern as models/mllama (reference: NeuronCrossAttention precomputes
  `modeling_whisper.py:164-215`).
- Every decoder layer is (self-attn, cross-attn, mlp), uniform, so one `lax.scan`
  covers the stack.
- Greedy decode runs as an on-device `lax.scan` chunk like the causal-LM app.
- Sharding: attention heads and MLP widths carry tp logical axes (batch on dp);
  weights/caches are device_put with the resulting NamedShardings and GSPMD inserts
  the collectives — same recipe as the causal-LM families."""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...config import InferenceConfig, TpuConfig
from ...ops.attention import attend
from ...ops.norms import layer_norm

Params = Dict[str, Any]


class WhisperInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("d_model", "encoder_layers", "decoder_layers",
                           "encoder_attention_heads", "decoder_attention_heads",
                           "num_mel_bins", "vocab_size", "max_target_positions",
                           "max_source_positions")

    def add_derived_config(self) -> None:
        for attr, default in (("activation_function", "gelu"),
                              ("decoder_start_token_id", 50257),
                              ("eos_token_id", 50256)):
            if not hasattr(self, attr):
                setattr(self, attr, default)
        if self.tpu_config.seq_len > self.max_target_positions:
            # positions past the learned pos-embed table would silently clamp
            # (jnp.take clips indices) and corrupt long transcriptions
            raise ValueError(
                f"tpu_config.seq_len {self.tpu_config.seq_len} exceeds whisper "
                f"max_target_positions {self.max_target_positions}")


def _attention_block(p: Params, prefix: str, x, heads, mask=None):
    """Whisper self-attention MHA: q/v/out have biases, k does not."""
    b, s, hdim = x.shape
    d = hdim // heads
    q = (x @ p[prefix + "wq"] + p[prefix + "bq"]).reshape(b, s, heads, d)
    k = (x @ p[prefix + "wk"]).reshape(b, s, heads, d)
    v = (x @ p[prefix + "wv"] + p[prefix + "bv"]).reshape(b, s, heads, d)
    q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
    out = attend(q, k, v, mask=mask, scale=d ** -0.5)
    out = out.transpose(0, 2, 1, 3).reshape(b, s, hdim)
    return out @ p[prefix + "wo"] + p[prefix + "bo"]


def encode(params: Params, input_features: jnp.ndarray, *, heads: int,
           eps: float = 1e-5) -> jnp.ndarray:
    """(B, n_mels, T) log-mel features -> (B, T//2, H) encoder states."""
    dn = ("NCH", "OIH", "NCH")
    x = jax.lax.conv_general_dilated(input_features, params["conv1_w"], (1,),
                                     [(1, 1)], dimension_numbers=dn)
    x = jax.nn.gelu(x + params["conv1_b"][None, :, None], approximate=False)
    x = jax.lax.conv_general_dilated(x, params["conv2_w"], (2,),
                                     [(1, 1)], dimension_numbers=dn)
    x = jax.nn.gelu(x + params["conv2_b"][None, :, None], approximate=False)
    h = x.transpose(0, 2, 1)                              # (B, T', H)
    h = h + params["pos_embed"][: h.shape[1]]

    def body(hid, lp):
        hn = layer_norm(hid, lp["ln1_w"], lp["ln1_b"], eps=eps)
        hid = hid + _attention_block(lp, "attn_", hn, heads)
        hn = layer_norm(hid, lp["ln2_w"], lp["ln2_b"], eps=eps)
        hid = hid + (jax.nn.gelu(hn @ lp["fc1"] + lp["b1"], approximate=False)
                     @ lp["fc2"] + lp["b2"])
        return hid, None

    h, _ = jax.lax.scan(body, h, params["layers"])
    return layer_norm(h, params["ln_post_w"], params["ln_post_b"], eps=eps)


def compute_cross_kv(dec_params: Params, enc_states: jnp.ndarray, heads: int):
    """Precompute per-decoder-layer cross K/V from the encoder output
    (≈ NeuronCrossAttention precompute, `modeling_whisper.py:164-215`)."""
    b, t, hdim = enc_states.shape
    d = hdim // heads

    def one(lp):
        k = (enc_states @ lp["xattn_wk"]).reshape(b, t, heads, d).transpose(0, 2, 1, 3)
        v = (enc_states @ lp["xattn_wv"] + lp["xattn_bv"]).reshape(
            b, t, heads, d).transpose(0, 2, 1, 3)
        return k, v

    return jax.vmap(one)(dec_params["layers"])


def decoder_forward(params: Params, input_ids, position_ids, cache,
                    decode_bucket: Optional[int], *, heads: int, eps: float = 1e-5):
    """Decoder step over (B, T) tokens at absolute positions (B,)+arange.

    cache: {"k","v" (L,B,h,S,D) self KV; "xk","xv" (L,B,h,T_enc,D) static cross KV}.
    prefill mode: decode_bucket None -> attend over the fresh T tokens only."""
    from ...modules import kvcache

    b, t = input_ids.shape
    pos_grid = position_ids[:, None] + jnp.arange(t)[None, :]
    h = jnp.take(params["embed"], input_ids, axis=0)
    h = h + jnp.take(params["pos_embed"], pos_grid, axis=0)
    d = h.shape[-1] // heads

    if decode_bucket is None:
        mask = pos_grid[:, None, :, None] >= pos_grid[:, None, None, :]
    else:
        kv_pos = jnp.arange(decode_bucket)[None, None, None, :]
        mask = kv_pos <= pos_grid[:, None, :, None]

    def body(carry_h, xs):
        lp, kc, vc, xk, xv = xs
        hn = layer_norm(carry_h, lp["ln1_w"], lp["ln1_b"], eps=eps)
        q = (hn @ lp["attn_wq"] + lp["attn_bq"]).reshape(b, t, heads, d)
        k = (hn @ lp["attn_wk"]).reshape(b, t, heads, d)
        v = (hn @ lp["attn_wv"] + lp["attn_bv"]).reshape(b, t, heads, d)
        q, k, v = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
        if decode_bucket is None:
            kc = kvcache.write_prefill(kc, k)
            vc = kvcache.write_prefill(vc, v)
            k_att, v_att = k, v
        else:
            kc = kvcache.write_decode(kc, k, position_ids)
            vc = kvcache.write_decode(vc, v, position_ids)
            k_att = kvcache.read_bucket(kc, decode_bucket)
            v_att = kvcache.read_bucket(vc, decode_bucket)
        attn = attend(q, k_att, v_att, mask=mask, scale=d ** -0.5)
        attn = attn.transpose(0, 2, 1, 3).reshape(b, t, -1)
        carry_h = carry_h + (attn @ lp["attn_wo"] + lp["attn_bo"])

        hn = layer_norm(carry_h, lp["xln_w"], lp["xln_b"], eps=eps)
        q = (hn @ lp["xattn_wq"] + lp["xattn_bq"]).reshape(b, t, heads, d)
        q = q.transpose(0, 2, 1, 3)
        xout = attend(q, xk, xv, scale=d ** -0.5)
        xout = xout.transpose(0, 2, 1, 3).reshape(b, t, -1)
        carry_h = carry_h + (xout @ lp["xattn_wo"] + lp["xattn_bo"])

        hn = layer_norm(carry_h, lp["ln2_w"], lp["ln2_b"], eps=eps)
        carry_h = carry_h + (jax.nn.gelu(hn @ lp["fc1"] + lp["b1"], approximate=False)
                             @ lp["fc2"] + lp["b2"])
        return carry_h, (kc, vc)

    xs = (params["layers"], cache["k"], cache["v"], cache["xk"], cache["xv"])
    h, (k_new, v_new) = jax.lax.scan(body, h, xs)
    h = layer_norm(h, params["ln_post_w"], params["ln_post_b"], eps=eps)
    logits = (h @ params["embed"].T).astype(jnp.float32)
    cache = dict(cache, k=k_new, v=v_new)
    return logits, cache


class WhisperForConditionalGeneration:
    """Encoder-decoder application (≈ reference Whisper Encoder/Decoder instances,
    `modeling_whisper.py:432-491`)."""

    def __init__(self, model_path: Optional[str], config: WhisperInferenceConfig):
        from ...parallel import mesh as mesh_lib

        self.model_path = model_path
        self.config = config
        self.tpu_config: TpuConfig = config.tpu_config
        self.mesh = mesh_lib.mesh_from_config(self.tpu_config)
        self.enc_params = None
        self.dec_params = None
        enc_heads = config.encoder_attention_heads
        dec_heads = config.decoder_attention_heads
        # heads/mlp axes shard over tp (and cp for mlp): validate divisibility at
        # construction instead of failing with an opaque NamedSharding error at
        # device_put (e.g. whisper-large: 20 decoder heads vs tp=8)
        tp = self.mesh.shape.get("tp", 1)
        mlp_deg = tp * self.mesh.shape.get("cp", 1)
        for name, n in (("encoder_attention_heads", enc_heads),
                        ("decoder_attention_heads", dec_heads)):
            if n % tp != 0:
                divisors = [d for d in range(1, n + 1) if n % d == 0]
                raise ValueError(
                    f"Whisper {name}={n} is not divisible by tp_degree={tp}; "
                    f"choose a tp_degree that divides the head count "
                    f"(valid: {divisors})")
        for name, n in (("encoder_ffn_dim", getattr(config, "encoder_ffn_dim", 0)),
                        ("decoder_ffn_dim", getattr(config, "decoder_ffn_dim", 0))):
            if n and n % mlp_deg != 0:
                raise ValueError(
                    f"Whisper {name}={n} is not divisible by tp*cp={mlp_deg}")
        self._encode = jax.jit(functools.partial(encode, heads=enc_heads))
        self._cross_kv = jax.jit(functools.partial(compute_cross_kv, heads=dec_heads))

        def _prefill(dec_params, input_ids, position_ids, cache):
            return decoder_forward(dec_params, input_ids, position_ids, cache,
                                   None, heads=dec_heads)

        def _decode_chunk(dec_params, tok0, position_ids, cache, decode_bucket,
                          num_steps):
            def body(carry, _):
                tok, pos, cache = carry
                logits, cache = decoder_forward(dec_params, tok[:, None], pos, cache,
                                                decode_bucket, heads=dec_heads)
                nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
                return (nxt, pos + 1, cache), nxt

            (_, _, cache), toks = jax.lax.scan(body, (tok0, position_ids, cache),
                                               None, length=num_steps)
            return toks.T, cache

        self._prefill = jax.jit(_prefill, donate_argnums=(3,))
        self._decode_chunk = jax.jit(_decode_chunk, donate_argnums=(3,),
                                     static_argnames=("decode_bucket", "num_steps"))

    @classmethod
    def get_config_cls(cls):
        return WhisperInferenceConfig

    # --- weights ----------------------------------------------------------------------
    def load(self, model_path: Optional[str] = None) -> None:
        from ...utils import checkpoint as ckpt_lib

        path = model_path or self.model_path
        state_dict = ckpt_lib.load_state_dict(path)
        self.load_from_state_dict(state_dict)

    @staticmethod
    def _attn_axes(prefix):
        return {
            prefix + "wq": ("layers", "embed", "heads"),
            prefix + "bq": ("layers", "heads"),
            prefix + "wk": ("layers", "embed", "heads"),
            prefix + "wv": ("layers", "embed", "heads"),
            prefix + "bv": ("layers", "heads"),
            prefix + "wo": ("layers", "heads", "embed"),
            prefix + "bo": ("layers", None),
        }

    @classmethod
    def _layer_axes(cls, cross: bool):
        axes = {
            "ln1_w": ("layers", None), "ln1_b": ("layers", None),
            "ln2_w": ("layers", None), "ln2_b": ("layers", None),
            "fc1": ("layers", "embed", "mlp"), "b1": ("layers", "mlp"),
            "fc2": ("layers", "mlp", "embed"), "b2": ("layers", None),
        }
        axes.update(cls._attn_axes("attn_"))
        if cross:
            axes.update(cls._attn_axes("xattn_"))
            axes.update({"xln_w": ("layers", None), "xln_b": ("layers", None)})
        return axes

    def _shard(self, params, layer_axes):
        """device_put with tp/dp NamedShardings from the logical axes (replicated for
        leaves without an entry)."""
        from ...parallel.sharding import named_sharding

        dtype = self.tpu_config.jax_dtype

        def _put(x, axes):
            arr = np.asarray(x)
            if arr.dtype.kind == "f":
                arr = arr.astype(dtype)
            logical = axes if axes is not None else (None,) * arr.ndim
            return jax.device_put(arr, named_sharding(self.mesh, logical))

        out = {k: _put(v, None) for k, v in params.items() if k != "layers"}
        out["layers"] = {k: _put(v, layer_axes.get(k))
                         for k, v in params["layers"].items()}
        return out

    def load_from_state_dict(self, state_dict) -> None:
        enc, dec = self.convert_hf_state_dict(state_dict, self.config)
        self.enc_params = self._shard(enc, self._layer_axes(cross=False))
        self.dec_params = self._shard(dec, self._layer_axes(cross=True))

    @classmethod
    def from_pretrained(cls, model_path: str, tpu_config: TpuConfig):
        from ...config import load_pretrained_config

        config = WhisperInferenceConfig(
            tpu_config, load_config=load_pretrained_config(model_path))
        app = cls(model_path, config)
        app.load()
        return app

    @staticmethod
    def convert_hf_state_dict(state_dict, config):
        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return state_dict[name]

        def linear_t(name):
            return np.ascontiguousarray(get(name).T)

        def attn(prefix, out):
            out_prefix = "attn_" if ".self_attn." in prefix else "xattn_"
            res = {
                out_prefix + "wq": linear_t(prefix + "q_proj.weight"),
                out_prefix + "bq": get(prefix + "q_proj.bias"),
                out_prefix + "wk": linear_t(prefix + "k_proj.weight"),
                out_prefix + "wv": linear_t(prefix + "v_proj.weight"),
                out_prefix + "bv": get(prefix + "v_proj.bias"),
                out_prefix + "wo": linear_t(prefix + "out_proj.weight"),
                out_prefix + "bo": get(prefix + "out_proj.bias"),
            }
            out.update(res)

        def stack(dicts):
            return {k: np.stack([x[k] for x in dicts]) for k in dicts[0]}

        enc_layers = []
        for i in range(config.encoder_layers):
            p = f"model.encoder.layers.{i}."
            lp = {
                "ln1_w": get(p + "self_attn_layer_norm.weight"),
                "ln1_b": get(p + "self_attn_layer_norm.bias"),
                "ln2_w": get(p + "final_layer_norm.weight"),
                "ln2_b": get(p + "final_layer_norm.bias"),
                "fc1": linear_t(p + "fc1.weight"), "b1": get(p + "fc1.bias"),
                "fc2": linear_t(p + "fc2.weight"), "b2": get(p + "fc2.bias"),
            }
            attn(p + "self_attn.", lp)
            enc_layers.append(lp)
        enc = {
            "conv1_w": get("model.encoder.conv1.weight"),
            "conv1_b": get("model.encoder.conv1.bias"),
            "conv2_w": get("model.encoder.conv2.weight"),
            "conv2_b": get("model.encoder.conv2.bias"),
            "pos_embed": get("model.encoder.embed_positions.weight"),
            "layers": stack(enc_layers),
            "ln_post_w": get("model.encoder.layer_norm.weight"),
            "ln_post_b": get("model.encoder.layer_norm.bias"),
        }

        dec_layers = []
        for i in range(config.decoder_layers):
            p = f"model.decoder.layers.{i}."
            lp = {
                "ln1_w": get(p + "self_attn_layer_norm.weight"),
                "ln1_b": get(p + "self_attn_layer_norm.bias"),
                "xln_w": get(p + "encoder_attn_layer_norm.weight"),
                "xln_b": get(p + "encoder_attn_layer_norm.bias"),
                "ln2_w": get(p + "final_layer_norm.weight"),
                "ln2_b": get(p + "final_layer_norm.bias"),
                "fc1": linear_t(p + "fc1.weight"), "b1": get(p + "fc1.bias"),
                "fc2": linear_t(p + "fc2.weight"), "b2": get(p + "fc2.bias"),
            }
            attn(p + "self_attn.", lp)
            attn(p + "encoder_attn.", lp)
            dec_layers.append(lp)
        dec = {
            "embed": get("model.decoder.embed_tokens.weight"),
            "pos_embed": get("model.decoder.embed_positions.weight"),
            "layers": stack(dec_layers),
            "ln_post_w": get("model.decoder.layer_norm.weight"),
            "ln_post_b": get("model.decoder.layer_norm.bias"),
        }
        return enc, dec

    # --- inference --------------------------------------------------------------------
    def encode_audio(self, input_features: np.ndarray) -> jnp.ndarray:
        return self._encode(self.enc_params, np.asarray(input_features,
                                                        dtype=np.float32))

    def _init_cache(self, b: int, t_enc: int):
        from ...parallel.sharding import named_sharding

        c = self.config
        heads = c.decoder_attention_heads
        d = c.d_model // heads
        L = c.decoder_layers
        S = self.tpu_config.seq_len
        dtype = self.tpu_config.jax_dtype
        sharding = named_sharding(self.mesh,
                                  ("layers", "batch", "heads", None, None))
        return {
            "k": jax.device_put(jnp.zeros((L, b, heads, S, d), dtype=dtype), sharding),
            "v": jax.device_put(jnp.zeros((L, b, heads, S, d), dtype=dtype), sharding),
            "xk": jax.device_put(jnp.zeros((L, b, heads, t_enc, d), dtype=dtype),
                                 sharding),
            "xv": jax.device_put(jnp.zeros((L, b, heads, t_enc, d), dtype=dtype),
                                 sharding),
        }

    def generate(self, input_features: np.ndarray,
                 decoder_input_ids: Optional[np.ndarray] = None,
                 max_new_tokens: int = 64,
                 eos_token_id: Optional[int] = None) -> np.ndarray:
        """Greedy transcription: returns (B, prompt + generated) token ids."""
        if self.enc_params is None:
            raise RuntimeError("load weights before generate")
        feats = np.asarray(input_features, dtype=np.float32)
        b = feats.shape[0]
        if decoder_input_ids is None:
            decoder_input_ids = np.full((b, 1), self.config.decoder_start_token_id,
                                        dtype=np.int32)
        ids = np.asarray(decoder_input_ids, dtype=np.int32)
        enc_states = self.encode_audio(feats)
        xk, xv = self._cross_kv(self.dec_params, enc_states)
        cache = self._init_cache(b, enc_states.shape[1])
        cache["xk"], cache["xv"] = xk, xv

        pos0 = np.zeros((b,), dtype=np.int32)
        logits, cache = self._prefill(self.dec_params, ids, pos0, cache)
        tok = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)

        out = [ids, np.asarray(tok)[:, None]]
        n_done, pos = 1, ids.shape[1]
        chunk = max(1, self.tpu_config.decode_chunk_size)
        eos = (eos_token_id if eos_token_id is not None
               else self.config.eos_token_id)
        eos_done = np.zeros((b,), dtype=bool)
        while n_done < max_new_tokens:
            # chunk writes occupy cache slots [pos, pos+steps) -> steps <= S - pos
            steps = min(chunk, max_new_tokens - n_done,
                        self.tpu_config.seq_len - pos)
            if steps <= 0:
                break
            positions = np.full((b,), pos, dtype=np.int32)
            bucket = min(self.tpu_config.seq_len,
                         1 << (pos + steps + 1 - 1).bit_length())
            toks, cache = self._decode_chunk(self.dec_params, tok, positions, cache,
                                             decode_bucket=bucket, num_steps=steps)
            toks_np = np.asarray(toks)
            out.append(toks_np)
            tok = toks[:, -1]
            pos += steps
            n_done += steps
            if eos is not None:
                eos_done |= (toks_np == eos).any(axis=1)
                if eos_done.all():
                    break
        return np.concatenate(out, axis=1)
