from .modeling_mllama import (MllamaForConditionalGeneration,
                              MllamaInferenceConfig)

__all__ = ["MllamaForConditionalGeneration", "MllamaInferenceConfig"]
