"""MLlama (Llama-3.2 Vision) family: cross-attention multimodal.

≈ reference `models/mllama/` (1340 + 623 LoC: cross-attention text model +
`MultimodalKVCacheManager`). Architecture (matches HF mllama):

- **Vision tower**: tiled ViT — patch conv, pre/post tile aspect-ratio embeddings
  (gated), class token, gated positional embedding, LayerNorm encoder layers, a gated
  global transformer, and output = concat(final, selected intermediate layer states).
- **Text model**: llama self-attention layers interleaved with *cross-attention*
  layers (`cross_attention_layers` indices): q from text (per-head RMSNorm), k/v from
  the projected vision states (computed ONCE at prefill), tanh-gated residuals, and a
  full-text-row mask that zeroes the ffn contribution for tokens with no visible image.
- **Multimodal KV**: the cross-attention K/V are static per request; they live in the
  cache pytree (``xk``/``xv``) next to the self-attention cache, which is exactly the
  reference's MultimodalKVCacheManager (`modules/kvcache/`) — and it lets the
  unmodified decode loop thread them through donation. The decode-time cross-attention
  mask (last prompt token's row, ≈ HF generate semantics) rides along as ``xmask_dec``/
  ``xfull_dec``.
- Text-only requests degrade gracefully: zero vision KV + all-masked rows make every
  cross layer an exact identity (attn out of zero V is zero; the full-row mask zeroes
  the ffn), mirroring HF's skip-cross-layers path without a second graph.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...config import InferenceConfig
from ...modules import gqa, kvcache
from ...ops import rope as rope_ops
from ...ops.norms import layer_norm, rms_norm
from ...parallel.sharding import constrain, named_sharding
from ..base import (ModelArchArgs, Params, _ACTIVATIONS, _decoder_layer, _embed,
                    _lm_head, _norm, attend, causal_mask)
from ...runtime.application import TpuModelForCausalLM

NEG_INF = jnp.finfo(jnp.float32).min


@dataclass(frozen=True)
class MllamaArchArgs(ModelArchArgs):
    cross_attention_layers: Tuple[int, ...] = ()
    vision_tokens: int = 0        # static T_vis = max_media * tiles * (patches + 1)


# --- text side ------------------------------------------------------------------------


def _cross_layer(lp: Params, args: MllamaArchArgs, h, xk, xv, xmask, xfull,
                 mesh, rules):
    """Cross-attention decoder layer (HF MllamaCrossAttentionDecoderLayer).

    xk/xv: (B, H_kv, T_vis, D) static vision KV. xmask: (B, S, T_vis) bool allowed.
    xfull: (B, S, 1) float 0/1 — rows with no visible image get 0 (their ffn output is
    zeroed; their attention mask flattens to uniform over the zero KV -> exact zero).
    """
    resid = h
    hn = rms_norm(h, lp["ln1"], args.rms_norm_eps)
    b, s, _ = hn.shape
    q = (hn @ lp["wq"]).reshape(b, s, args.num_heads, args.head_dim).transpose(0, 2, 1, 3)
    q = rms_norm(q, lp["q_norm"], args.rms_norm_eps)
    # attend() reproduces the HF dead-row trick: an all-masked row softmaxes uniform
    # over the zero vision V -> exact zero attention output
    attn = attend(q, xk.astype(q.dtype), xv.astype(q.dtype), mask=xmask[:, None],
                  scale=args.head_dim ** -0.5)
    attn = attn.transpose(0, 2, 1, 3).reshape(b, s, args.q_size)
    attn_out = attn @ lp["wo"]
    attn_out = constrain(attn_out, ("batch", None, None), rules, mesh=mesh)
    h = resid + jnp.tanh(lp["gate_attn"]) * attn_out

    resid = h
    hn = rms_norm(h, lp["ln2"], args.rms_norm_eps)
    act = _ACTIVATIONS[args.activation]
    ffn = (act(hn @ lp["wg"]) * (hn @ lp["wu"])) @ lp["wd"]
    # full-text-row mask zeroes the ffn for image-less tokens (cast keeps bf16 runs
    # from being silently promoted to f32 by the mask multiply)
    ffn = ffn * xfull.astype(ffn.dtype)
    ffn = constrain(ffn, ("batch", None, None), rules, mesh=mesh)
    h = resid + jnp.tanh(lp["gate_mlp"]) * ffn
    return h


def _compute_cross_kv(xlayers: Params, args: MllamaArchArgs,
                      cross_states: jnp.ndarray):
    """(B, T_vis, H) projected vision states -> per-cross-layer static K/V stacks
    (L_cross, B, H_kv, T_vis, D), with per-head k RMSNorm (HF MllamaTextCrossAttention)."""
    b, t, _ = cross_states.shape

    def one(lp):
        k = (cross_states @ lp["wk"]).reshape(b, t, args.num_kv_heads, args.head_dim)
        k = k.transpose(0, 2, 1, 3)
        k = rms_norm(k, lp["k_norm"], args.rms_norm_eps)
        v = (cross_states @ lp["wv"]).reshape(b, t, args.num_kv_heads, args.head_dim)
        v = v.transpose(0, 2, 1, 3)
        return k, v

    return jax.vmap(one)(xlayers)


def _segment_runs(flags: Tuple[bool, ...]) -> List[Tuple[bool, int, int, int]]:
    runs = []
    counts = {True: 0, False: 0}
    i = 0
    while i < len(flags):
        j = i
        while j < len(flags) and flags[j] == flags[i]:
            j += 1
        runs.append((flags[i], i, j - i, counts[flags[i]]))
        counts[flags[i]] += j - i
        i = j
    return runs


def _run_text_layers(params: Params, args: MllamaArchArgs, h, cos, sin, mask, cache,
                     xmask, xfull, positions, decode_bucket, mesh, rules):
    """Interleave self-attention scans with cross-attention layers.

    Self layers scan in contiguous runs (unrolled at cross boundaries — the reference
    traces fully unrolled, see models/llama4 note)."""
    is_cross = tuple(i in args.cross_attention_layers
                     for i in range(args.num_layers))
    k_all, v_all = cache["k"], cache["v"]          # (L_self, ...) self-attn cache only
    xk_all, xv_all = cache["xk"], cache["xv"]      # (L_cross, B, H_kv, T_vis, D)
    new_k = [None] * sum(1 for f in is_cross if not f)
    new_v = [None] * sum(1 for f in is_cross if not f)

    for cross, g0, n, l0 in _segment_runs(is_cross):
        if cross:
            for idx in range(n):
                lp = jax.tree.map(lambda x: x[l0 + idx], params["xlayers"])
                h = _cross_layer(lp, args, h, xk_all[l0 + idx], xv_all[l0 + idx],
                                 xmask, xfull, mesh, rules)
        else:
            stack = jax.tree.map(lambda x: x[l0:l0 + n], params["layers"])
            xs = (stack, k_all[l0:l0 + n], v_all[l0:l0 + n])

            def body(carry_h, layer_xs):
                lp, kc, vc = layer_xs
                nh, kc, vc = _decoder_layer(lp, args, carry_h, cos, sin, mask, kc, vc,
                                            positions, decode_bucket, mesh, rules)
                return nh, (kc, vc)

            h, (ks, vs) = jax.lax.scan(body, h, xs)
            for idx in range(n):
                new_k[l0 + idx] = ks[idx:idx + 1]
                new_v[l0 + idx] = vs[idx:idx + 1]
    new_cache = dict(cache)
    new_cache["k"] = jnp.concatenate(new_k, axis=0)
    new_cache["v"] = jnp.concatenate(new_v, axis=0)
    return h, new_cache


def prefill_forward(params: Params, args: MllamaArchArgs, input_ids, position_ids,
                    last_token_idx, cache, cross_states, xmask, xfull,
                    xmask_dec, xfull_dec, mesh=None, rules=None):
    """Context encoding with vision cross-attention.

    cross_states (B, T_vis, H): projected vision features (zeros for text-only).
    xmask/xfull: per-prompt-token cross-attention visibility.
    xmask_dec/xfull_dec: the visibility row decode steps will use; stored in the cache.
    """
    h = _embed(params, args, input_ids, mesh, rules)
    cos, sin = rope_ops.compute_cos_sin(params["rope_inv_freq"], position_ids,
                                        args.rope_attention_scaling)
    s = input_ids.shape[1]
    mask = (position_ids[:, None, :, None] >= position_ids[:, None, None, :])
    mask = jnp.logical_and(mask, causal_mask(s, s)[None, None])

    xk, xv = _compute_cross_kv(params["xlayers"], args, cross_states)
    cache = dict(cache)
    cache["xk"], cache["xv"] = xk, xv
    cache["xmask_dec"], cache["xfull_dec"] = xmask_dec, xfull_dec

    h, cache = _run_text_layers(params, args, h, cos, sin, mask, cache,
                                xmask, xfull, positions=None, decode_bucket=None,
                                mesh=mesh, rules=rules)
    h = _norm(h, params["final_norm"], args)
    h_last = jnp.take_along_axis(h, last_token_idx[:, None, None], axis=1)[:, 0]
    logits = _lm_head(params, args, h_last, mesh, rules)
    return logits, cache


def decode_forward(params: Params, args: MllamaArchArgs, input_ids, position_ids,
                   cache, decode_bucket, mesh=None, rules=None, block_table=None,
                   slot_mapping=None, adapter_ids=None, tree=None,
                   return_hidden=False):
    """Token generation; vision KV and the decode cross mask come from the cache."""
    b, t = input_ids.shape
    h = _embed(params, args, input_ids, mesh, rules)
    pos_grid = position_ids[:, None] + jnp.arange(t)[None, :]
    cos, sin = rope_ops.compute_cos_sin(params["rope_inv_freq"], pos_grid,
                                        args.rope_attention_scaling)
    kv_pos = jnp.arange(decode_bucket)[None, None, None, :]
    q_pos = pos_grid[:, None, :, None]
    mask = kv_pos <= q_pos
    xmask = jnp.broadcast_to(cache["xmask_dec"][:, None, :],
                             (b, t, args.vision_tokens))
    xfull = jnp.broadcast_to(cache["xfull_dec"][:, None, :], (b, t, 1))
    h, cache = _run_text_layers(params, args, h, cos, sin, mask, cache,
                                xmask, xfull, positions=position_ids,
                                decode_bucket=decode_bucket, mesh=mesh, rules=rules)
    h = _norm(h, params["final_norm"], args)
    logits = _lm_head(params, args, h, mesh, rules)
    if return_hidden:
        return logits, cache, h
    return logits, cache


# --- vision side ----------------------------------------------------------------------


def vision_encode(vp: Dict[str, Any], pixel_values, aspect_ratio_ids,
                  aspect_ratio_mask, *, patch_size: int, num_heads: int,
                  intermediate_indices: Tuple[int, ...], norm_eps: float = 1e-5,
                  act=jax.nn.gelu):
    """HF MllamaVisionModel.forward, functional.

    pixel_values (B, M, T, C, H, W); aspect_ratio_ids (B, M); aspect_ratio_mask
    (B, M, T). Returns (B, M*T*P, hidden*(1+len(intermediate))) UNPROJECTED vision
    states (the multimodal projector runs in the text-side prefill wrapper so its
    output feeds the cross KV directly)."""
    b, m, ntiles, c, hh, ww = pixel_values.shape
    p = patch_size
    gh, gw = hh // p, ww // p
    n_patch = gh * gw
    hidden = vp["patch_w"].shape[-1]

    x = pixel_values.reshape(b * m * ntiles, c, gh, p, gw, p).transpose(0, 2, 4, 1, 3, 5)
    x = x.reshape(b * m * ntiles, n_patch, c * p * p)
    h = x @ vp["patch_w"]                                    # (BMT, P, hidden)

    ar_ids = aspect_ratio_ids.reshape(b * m)
    # pre-tile embedding (gated)
    pre = jnp.take(vp["pre_tile_embed"], ar_ids, axis=0).reshape(
        b * m, ntiles, 1, hidden)
    h = h.reshape(b * m, ntiles, n_patch, hidden) + jnp.tanh(vp["pre_tile_gate"]) * pre
    # class token
    h = h.reshape(b * m * ntiles, n_patch, hidden)
    cls = jnp.broadcast_to(vp["class_embed"], (b * m * ntiles, 1, hidden))
    h = jnp.concatenate([cls, h], axis=1)
    n_patch += 1
    # gated positional embedding
    h = h.reshape(b * m, ntiles, n_patch, hidden)
    gate = jnp.tanh(vp["pos_gate"])
    h = h + (1 - gate) * vp["pos_embed"][None, None]
    tile_pos = jnp.take(vp["tile_pos_embed"], ar_ids, axis=0).reshape(
        b * m, ntiles, n_patch, hidden)
    h = h + gate * tile_pos
    h = layer_norm(h, vp["ln_pre_w"], vp["ln_pre_b"], eps=norm_eps)

    # pad patches to a multiple of 8 (HF) and build the tile attention mask
    pad = (8 - (n_patch % 8)) % 8
    if pad:
        h = jnp.pad(h, ((0, 0), (0, 0), (0, pad), (0, 0)))
    pt = n_patch + pad
    tile_ok = aspect_ratio_mask.reshape(b * m, ntiles, 1).astype(jnp.float32)
    tok_ok = jnp.broadcast_to(tile_ok, (b * m, ntiles, pt)).reshape(b * m, -1)
    if pad:
        tok_ok = tok_ok.reshape(b * m, ntiles, pt).at[:, :, -pad:].set(0.0)
        tok_ok = tok_ok.reshape(b * m, -1)
    # HF: mask = (1-ok) @ (1-ok)^T * -inf  -> allowed iff BOTH tokens are live
    dead = 1.0 - tok_ok
    additive = (dead[:, :, None] @ dead[:, None, :]) * NEG_INF   # (BM, T, T)
    additive = additive[:, None]                                  # (BM, 1, T, T)

    d = hidden // num_heads
    seq = ntiles * pt

    def encoder_layer(hid, lp, gated):
        hn = layer_norm(hid, lp["ln1_w"], lp["ln1_b"], eps=norm_eps)
        q = (hn @ lp["wq"]).reshape(b * m, seq, num_heads, d).transpose(0, 2, 1, 3)
        k = (hn @ lp["wk"]).reshape(b * m, seq, num_heads, d).transpose(0, 2, 1, 3)
        v = (hn @ lp["wv"]).reshape(b * m, seq, num_heads, d).transpose(0, 2, 1, 3)
        scores = jnp.einsum("nhqd,nhkd->nhqk", q, k,
                            preferred_element_type=jnp.float32) * (d ** -0.5) + additive
        probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
        attn = jnp.einsum("nhqk,nhkd->nhqd", probs, v)
        attn = attn.transpose(0, 2, 1, 3).reshape(b * m, seq, hidden)
        attn = attn @ lp["wo"]
        if gated:
            attn = jnp.tanh(lp["gate_attn"]) * attn
        hid = hid + attn
        hn = layer_norm(hid, lp["ln2_w"], lp["ln2_b"], eps=norm_eps)
        ffn = act(hn @ lp["fc1"] + lp["b1"]) @ lp["fc2"] + lp["b2"]
        if gated:
            ffn = jnp.tanh(lp["gate_ffn"]) * ffn
        return hid + ffn

    h = h.reshape(b * m, seq, hidden)

    # capture only the selected layers' INPUTS (HF hidden_states[i]): scan in
    # segments split at the intermediate indices instead of materializing every
    # layer's activations as scan ys
    def local_body(hid, lp):
        return encoder_layer(hid, lp, gated=False), None

    captured = {}
    start = 0
    n_local = jax.tree.leaves(vp["layers"])[0].shape[0]
    for i in sorted(set(intermediate_indices)):
        if i > start:
            seg = jax.tree.map(lambda x: x[start:i], vp["layers"])
            h, _ = jax.lax.scan(local_body, h, seg)
        captured[i] = h
        start = i
    if start < n_local:
        seg = jax.tree.map(lambda x: x[start:], vp["layers"])
        h, _ = jax.lax.scan(local_body, h, seg)
    intermediates = jnp.stack([captured[i] for i in intermediate_indices],
                              axis=-1)                       # (BM, seq, hidden, K)

    h = layer_norm(h, vp["ln_post_w"], vp["ln_post_b"], eps=norm_eps)
    post = jnp.take(vp["post_tile_embed"], ar_ids, axis=0).reshape(
        b * m, ntiles, 1, hidden)
    h = h.reshape(b * m, ntiles, pt, hidden) + jnp.tanh(vp["post_tile_gate"]) * post
    h = h.reshape(b * m, seq, hidden)

    def global_body(hid, lp):
        return encoder_layer(hid, lp, gated=True), None

    h, _ = jax.lax.scan(global_body, h, vp["global_layers"])

    # un-pad and concat intermediates (HF: final first, then intermediates)
    h = h.reshape(b * m, ntiles, pt, hidden)[:, :, :n_patch]
    inter = intermediates.reshape(b * m, ntiles, pt, hidden * len(intermediate_indices))
    inter = inter[:, :, :n_patch]
    out = jnp.concatenate([h, inter], axis=-1)
    return out.reshape(b, m * ntiles * n_patch, -1)


# --- config / application -------------------------------------------------------------


class MllamaInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = ("vision_config", "text_config")

    def add_derived_config(self) -> None:
        tc = self.text_config
        if not isinstance(tc, dict):
            tc = tc.to_dict()
        for k, v in tc.items():
            if not k.startswith("_"):
                setattr(self, k, v)
        if not isinstance(self.vision_config, dict):
            self.vision_config = self.vision_config.to_dict()
        if not hasattr(self, "head_dim") or self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_attention_heads
        for attr, default in (("rms_norm_eps", 1e-5), ("rope_theta", 500000.0),
                              ("rope_scaling", None), ("tie_word_embeddings", False),
                              ("hidden_act", "silu"),
                              ("max_num_media", 1)):
            if not hasattr(self, attr):
                setattr(self, attr, default)

    @property
    def vision_tokens_per_tile(self) -> int:
        vc = self.vision_config
        return (vc["image_size"] // vc["patch_size"]) ** 2 + 1

    @property
    def total_vision_tokens(self) -> int:
        return (self.max_num_media * self.vision_config["max_num_tiles"]
                * self.vision_tokens_per_tile)


class MllamaForConditionalGeneration(TpuModelForCausalLM):
    """≈ NeuronMllamaForConditionalGeneration (`models/mllama/`)."""

    def __init__(self, model_path, config, mesh=None):
        self._require_base_layout(config.tpu_config, "MLlama")
        super().__init__(model_path, config, mesh=mesh)
        self.vision_params = None
        vc = config.vision_config
        import functools

        self._encode_fn = functools.partial(
            vision_encode,
            patch_size=vc["patch_size"],
            num_heads=vc["attention_heads"],
            intermediate_indices=tuple(vc["intermediate_layers_indices"]),
            norm_eps=vc.get("norm_eps", 1e-5),
            act=_ACTIVATIONS.get(vc.get("hidden_act", "gelu"), jax.nn.gelu),
        )
        self._xprefill_step = self._build_xprefill()

    @classmethod
    def get_config_cls(cls):
        return MllamaInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> MllamaArchArgs:
        tp = config.tpu_config.tp_degree
        return MllamaArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=gqa.effective_kv_heads(tp, config.num_key_value_heads),
            head_dim=config.head_dim,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.rms_norm_eps,
            activation=config.hidden_act,
            rope_attention_scaling=rope_ops.attention_scaling_from_hf_config(
                config.rope_scaling),
            tie_word_embeddings=config.tie_word_embeddings,
            cross_attention_layers=tuple(config.cross_attention_layers),
            vision_tokens=config.total_vision_tokens,
        )

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        return rope_ops.inv_freq_from_hf_config(
            config.head_dim, config.rope_theta, config.rope_scaling)

    def _use_flash_attention(self) -> bool:
        if self.tpu_config.attention_kernel_enabled is True:
            raise ValueError("the Pallas flash kernel does not support mllama yet")
        return False

    def _use_ring_attention(self) -> bool:
        if self.mesh.shape["cp"] > 1:
            raise ValueError("context parallelism is not supported for mllama yet")
        return False

    def decode_fn(self):
        return decode_forward

    # the plain-text prefill graph still runs through prefill_forward with zero
    # vision inputs — built by _build_steps via this hook
    def prefill_fn(self):
        a = self.arch_args

        def _text_only(params, args, input_ids, position_ids, last_token_idx, cache,
                       mesh=None, rules=None, **_):
            b, s = input_ids.shape
            t_vis = a.vision_tokens
            h_dim = a.hidden_size
            zeros_cs = jnp.zeros((b, t_vis, h_dim), dtype=self.tpu_config.jax_dtype)
            xmask = jnp.zeros((b, s, t_vis), dtype=bool)
            xfull = jnp.zeros((b, s, 1), dtype=jnp.float32)
            xmask_dec = jnp.zeros((b, t_vis), dtype=bool)
            xfull_dec = jnp.zeros((b, 1), dtype=jnp.float32)
            return prefill_forward(params, args, input_ids, position_ids,
                                   last_token_idx, cache, zeros_cs, xmask, xfull,
                                   xmask_dec, xfull_dec, mesh=mesh, rules=rules)

        return _text_only

    def _build_xprefill(self):
        args = self.arch_args
        mesh, rules = self.mesh, self.sharding_rules
        odsc = self.sampling_config
        from ...ops import sampling as sampling_ops

        precision = ("highest" if self.tpu_config.dtype == "float32" else "default")

        def _prefill_mm(params, vision_params, input_ids, position_ids,
                        last_token_idx, cache, sampling_params, key,
                        pixel_values, aspect_ratio_ids, aspect_ratio_mask,
                        xmask, xfull, xmask_dec, xfull_dec):
            with jax.default_matmul_precision(precision):
                vis = self._encode_fn(
                    vision_params, pixel_values, aspect_ratio_ids, aspect_ratio_mask)
                cross = vis @ vision_params["proj_w"] + vision_params["proj_b"]
                logits, cache = prefill_forward(
                    params, args, input_ids, position_ids, last_token_idx, cache,
                    cross.astype(self.tpu_config.jax_dtype), xmask, xfull,
                    xmask_dec, xfull_dec, mesh=mesh, rules=rules)
                tokens = sampling_ops.sample(logits, sampling_params, key, odsc)
            return tokens, logits, cache

        return jax.jit(_prefill_mm, donate_argnums=(5,))

    def warmup(self) -> None:
        """Also compile the vision+cross-attention prefill graph per CTE bucket."""
        super().warmup()
        if self.vision_params is None:
            return
        from ...ops import sampling as sampling_ops

        a: MllamaArchArgs = self.arch_args
        vc = self.config.vision_config
        b = self.tpu_config.max_batch_size
        m, t = self.config.max_num_media, vc["max_num_tiles"]
        side, chans = vc["image_size"], vc.get("num_channels", 3)
        sp = sampling_ops.prepare_sampling_params(b)
        key = jax.random.PRNGKey(0)
        pixels = np.zeros((b, m, t, chans, side, side), dtype=np.float32)
        ar_ids = np.ones((b, m), dtype=np.int32)
        ar_mask = np.ones((b, m, t), dtype=np.int32)
        for bucket in self.cte_buckets:
            self.reset_cache()
            ids = np.zeros((b, bucket), dtype=np.int32)
            pos = np.broadcast_to(np.arange(bucket, dtype=np.int32),
                                  (b, bucket)).copy()
            last = np.zeros((b,), dtype=np.int32)
            xmask = np.zeros((b, bucket, a.vision_tokens), dtype=bool)
            xfull = np.zeros((b, bucket, 1), dtype=np.float32)
            xmask_dec = np.zeros((b, a.vision_tokens), dtype=bool)
            xfull_dec = np.zeros((b, 1), dtype=np.float32)
            tokens, _, self.kv_cache = self._xprefill_step(
                self.params, self.vision_params, ids, pos, last, self.kv_cache, sp,
                key, pixels, ar_ids, ar_mask, xmask, xfull, xmask_dec, xfull_dec)
            tokens.block_until_ready()
        self.reset_cache()

    # --- cache with static vision KV --------------------------------------------------
    def reset_cache(self) -> None:
        a: MllamaArchArgs = self.arch_args
        n_self = a.num_layers - len(a.cross_attention_layers)
        spec = kvcache.KVCacheSpec(
            num_layers=n_self, batch_size=self.tpu_config.max_batch_size,
            num_kv_heads=a.num_kv_heads, max_seq_len=self.tpu_config.seq_len,
            head_dim=a.head_dim, dtype=self.tpu_config.kv_cache_jax_dtype)
        sharding = named_sharding(self.mesh, kvcache.CACHE_LOGICAL,
                                  self.sharding_rules)
        cache = {k: jax.device_put(v, sharding)
                 for k, v in kvcache.init_cache(spec).items()}
        b = self.tpu_config.max_batch_size
        n_cross = len(a.cross_attention_layers)
        xshape = (n_cross, b, a.num_kv_heads, a.vision_tokens, a.head_dim)
        xsharding = named_sharding(self.mesh,
                                   ("layers", "batch", "kv_heads", None, None))
        dtype = self.tpu_config.jax_dtype
        cache["xk"] = jax.device_put(jnp.zeros(xshape, dtype=dtype), xsharding)
        cache["xv"] = jax.device_put(jnp.zeros(xshape, dtype=dtype), xsharding)
        cache["xmask_dec"] = jnp.zeros((b, a.vision_tokens), dtype=bool)
        cache["xfull_dec"] = jnp.zeros((b, 1), dtype=jnp.float32)
        self.kv_cache = cache

    # --- weights ----------------------------------------------------------------------
    def logical_axes(self) -> Dict:
        a: MllamaArchArgs = self.arch_args
        self_axes = {
            "ln1": ("layers", None), "ln2": ("layers", None),
            "wq": ("layers", "embed", "heads"),
            "wk": ("layers", "embed", "kv_heads"),
            "wv": ("layers", "embed", "kv_heads"),
            "wo": ("layers", "heads", "embed"),
            "wg": ("layers", "embed", "mlp"),
            "wu": ("layers", "embed", "mlp"),
            "wd": ("layers", "mlp", "embed"),
        }
        x_axes = dict(self_axes)
        x_axes.update({"q_norm": ("layers", None), "k_norm": ("layers", None),
                       "gate_attn": ("layers",), "gate_mlp": ("layers",)})
        out = {
            "embed": ("vocab", "embed"),
            "layers": self_axes,
            "xlayers": x_axes,
            "final_norm": (None,),
            "rope_inv_freq": (None,),
        }
        if not a.tie_word_embeddings:
            out["lm_head"] = ("embed", "vocab")
        return out

    def init_random_params(self, key) -> Dict:
        a: MllamaArchArgs = self.arch_args
        dtype = self.tpu_config.jax_dtype
        H = a.hidden_size
        n_cross = len(a.cross_attention_layers)
        n_self = a.num_layers - n_cross
        ks = iter(jax.random.split(key, 48))

        def w(shape, scale=0.02):
            return (jax.random.normal(next(ks), shape, dtype=jnp.float32)
                    * scale).astype(dtype)

        def stack(L, cross):
            p = {
                "ln1": jnp.ones((L, H), dtype=dtype),
                "ln2": jnp.ones((L, H), dtype=dtype),
                "wq": w((L, H, a.q_size)),
                "wk": w((L, H, a.kv_size)),
                "wv": w((L, H, a.kv_size)),
                "wo": w((L, a.q_size, H)),
                "wg": w((L, H, a.intermediate_size)),
                "wu": w((L, H, a.intermediate_size)),
                "wd": w((L, a.intermediate_size, H)),
            }
            if cross:
                p.update({"q_norm": jnp.ones((L, a.head_dim), dtype=dtype),
                          "k_norm": jnp.ones((L, a.head_dim), dtype=dtype),
                          "gate_attn": jnp.zeros((L,), dtype=dtype),
                          "gate_mlp": jnp.zeros((L,), dtype=dtype)})
            return p

        params = {
            # HF mllama reserves 8 extra embed rows past vocab_size (image token etc.)
            "embed": w((a.vocab_size + 8, H)),
            "layers": stack(n_self, cross=False),
            "xlayers": stack(n_cross, cross=True),
            "final_norm": jnp.ones((H,), dtype=dtype),
            "rope_inv_freq": jnp.asarray(self.inv_freq_from_config(self.config),
                                         dtype=jnp.float32),
        }
        if not a.tie_word_embeddings:
            params["lm_head"] = w((H, a.vocab_size))
        return params

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray], config) -> Dict:
        state_dict = _normalize_mllama_keys(state_dict)
        args = cls.arch_args_from_config(config)
        L = config.num_hidden_layers
        cross = set(args.cross_attention_layers)
        n_kv, d = config.num_key_value_heads, config.head_dim
        factor = args.num_kv_heads // n_kv

        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return state_dict[name]

        def linear_t(name):
            return np.ascontiguousarray(get(name).T)

        self_layers, x_layers = [], []
        for i in range(L):
            p = f"model.language_model.layers.{i}."
            if i in cross:
                x_layers.append({
                    "ln1": get(p + "input_layernorm.weight"),
                    "ln2": get(p + "post_attention_layernorm.weight"),
                    "wq": linear_t(p + "cross_attn.q_proj.weight"),
                    "wk": gqa.replicate_kv_weight(
                        linear_t(p + "cross_attn.k_proj.weight"), n_kv, d, factor),
                    "wv": gqa.replicate_kv_weight(
                        linear_t(p + "cross_attn.v_proj.weight"), n_kv, d, factor),
                    "wo": linear_t(p + "cross_attn.o_proj.weight"),
                    "q_norm": get(p + "cross_attn.q_norm.weight"),
                    "k_norm": get(p + "cross_attn.k_norm.weight"),
                    "gate_attn": get(p + "cross_attn_attn_gate").reshape(()),
                    "gate_mlp": get(p + "cross_attn_mlp_gate").reshape(()),
                    "wg": linear_t(p + "mlp.gate_proj.weight"),
                    "wu": linear_t(p + "mlp.up_proj.weight"),
                    "wd": linear_t(p + "mlp.down_proj.weight"),
                })
            else:
                self_layers.append({
                    "ln1": get(p + "input_layernorm.weight"),
                    "ln2": get(p + "post_attention_layernorm.weight"),
                    "wq": linear_t(p + "self_attn.q_proj.weight"),
                    "wk": gqa.replicate_kv_weight(
                        linear_t(p + "self_attn.k_proj.weight"), n_kv, d, factor),
                    "wv": gqa.replicate_kv_weight(
                        linear_t(p + "self_attn.v_proj.weight"), n_kv, d, factor),
                    "wo": linear_t(p + "self_attn.o_proj.weight"),
                    "wg": linear_t(p + "mlp.gate_proj.weight"),
                    "wu": linear_t(p + "mlp.up_proj.weight"),
                    "wd": linear_t(p + "mlp.down_proj.weight"),
                })

        def stack(dicts):
            return {k: np.stack([x[k] for x in dicts]) for k in dicts[0]}

        params = {
            "embed": get("model.language_model.embed_tokens.weight"),
            "layers": stack(self_layers),
            "xlayers": stack(x_layers),
            "final_norm": get("model.language_model.norm.weight"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
        if not args.tie_word_embeddings:
            params["lm_head"] = np.ascontiguousarray(get("lm_head.weight").T)
        return params

    def _post_load_state_dict(self, state_dict) -> None:
        self.load_vision_from_state_dict(state_dict)

    def load_vision_from_state_dict(self, state_dict) -> None:
        host = self.convert_hf_vision_state_dict(state_dict, self.config)
        dtype = self.tpu_config.jax_dtype

        def _put(x):
            arr = np.asarray(x)
            if arr.dtype.kind == "f" or arr.dtype.name == "bfloat16":
                arr = arr.astype(dtype)
            return jax.device_put(arr)

        self.vision_params = jax.tree.map(_put, host)

    @classmethod
    def convert_hf_vision_state_dict(cls, state_dict: Dict[str, np.ndarray],
                                     config) -> Dict:
        state_dict = _normalize_mllama_keys(state_dict)
        vc = config.vision_config
        hidden = vc["hidden_size"]

        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return state_dict[name]

        def linear_t(name):
            return np.ascontiguousarray(get(name).T)

        def encoder_stack(prefix, n, gated):
            keys = ["ln1_w", "ln1_b", "wq", "wk", "wv", "wo",
                    "ln2_w", "ln2_b", "fc1", "b1", "fc2", "b2"]
            if gated:
                keys += ["gate_attn", "gate_ffn"]
            layers = {k: [] for k in keys}
            for i in range(n):
                p = f"{prefix}.layers.{i}."
                layers["ln1_w"].append(get(p + "input_layernorm.weight"))
                layers["ln1_b"].append(get(p + "input_layernorm.bias"))
                layers["wq"].append(linear_t(p + "self_attn.q_proj.weight"))
                layers["wk"].append(linear_t(p + "self_attn.k_proj.weight"))
                layers["wv"].append(linear_t(p + "self_attn.v_proj.weight"))
                layers["wo"].append(linear_t(p + "self_attn.o_proj.weight"))
                layers["ln2_w"].append(get(p + "post_attention_layernorm.weight"))
                layers["ln2_b"].append(get(p + "post_attention_layernorm.bias"))
                layers["fc1"].append(linear_t(p + "mlp.fc1.weight"))
                layers["b1"].append(get(p + "mlp.fc1.bias"))
                layers["fc2"].append(linear_t(p + "mlp.fc2.weight"))
                layers["b2"].append(get(p + "mlp.fc2.bias"))
                if gated:
                    layers["gate_attn"].append(get(p + "gate_attn").reshape(()))
                    layers["gate_ffn"].append(get(p + "gate_ffn").reshape(()))
            return {k: np.stack(v) for k, v in layers.items()}

        v = "model.vision_model."
        conv = get(v + "patch_embedding.weight")             # (hidden, C, p, p)
        return {
            "patch_w": np.ascontiguousarray(conv.reshape(hidden, -1).T),
            "class_embed": get(v + "class_embedding"),
            "pos_gate": get(v + "gated_positional_embedding.gate").reshape(()),
            "pos_embed": get(v + "gated_positional_embedding.embedding"),
            "tile_pos_embed": get(v + "gated_positional_embedding.tile_embedding.weight"),
            "pre_tile_embed": get(v + "pre_tile_positional_embedding.embedding.weight"),
            "pre_tile_gate": get(v + "pre_tile_positional_embedding.gate").reshape(()),
            "post_tile_embed": get(v + "post_tile_positional_embedding.embedding.weight"),
            "post_tile_gate": get(v + "post_tile_positional_embedding.gate").reshape(()),
            "ln_pre_w": get(v + "layernorm_pre.weight"),
            "ln_pre_b": get(v + "layernorm_pre.bias"),
            "ln_post_w": get(v + "layernorm_post.weight"),
            "ln_post_b": get(v + "layernorm_post.bias"),
            "layers": encoder_stack(v + "transformer", vc["num_hidden_layers"],
                                    gated=False),
            "global_layers": encoder_stack(v + "global_transformer",
                                           vc["num_global_layers"], gated=True),
            "proj_w": linear_t("model.multi_modal_projector.weight"),
            "proj_b": get("model.multi_modal_projector.bias"),
        }

    # --- generation -------------------------------------------------------------------
    def generate(self, input_ids, pixel_values=None, aspect_ratio_ids=None,
                 aspect_ratio_mask=None, cross_attention_mask=None, **kwargs):
        """HF-processor-compatible multimodal generate.

        pixel_values (B, M, T, C, H, W), aspect_ratio_ids (B, M), aspect_ratio_mask
        (B, M, T), cross_attention_mask (B, S, M, T)."""
        if pixel_values is None:
            return super().generate(input_ids, **kwargs)
        if cross_attention_mask is None or aspect_ratio_ids is None \
                or aspect_ratio_mask is None:
            raise ValueError("multimodal generate requires aspect_ratio_ids, "
                             "aspect_ratio_mask and cross_attention_mask (the HF "
                             "mllama processor produces all three)")
        pixel_values = np.asarray(pixel_values, dtype=np.float32)
        cam = np.asarray(cross_attention_mask, dtype=np.int32)
        vc = self.config.vision_config
        m_max, t_max = self.config.max_num_media, vc["max_num_tiles"]
        if pixel_values.shape[1] != m_max or pixel_values.shape[2] != t_max:
            raise ValueError(
                f"pixel_values media/tile dims {pixel_values.shape[1:3]} must match "
                f"the compiled (max_num_media={m_max}, max_num_tiles={t_max}); pad "
                f"images and aspect_ratio_mask to the static shape")
        if cam.shape[2] != m_max or cam.shape[3] != t_max:
            raise ValueError(
                f"cross_attention_mask media/tile dims {cam.shape[2:]} must match "
                f"(max_num_media={m_max}, max_num_tiles={t_max})")
        attention_mask = kwargs.get("attention_mask")
        if attention_mask is not None:
            # pad_prefill_inputs compacts each row's real tokens to the left; the
            # cross-attention mask rows must follow their tokens
            am = np.asarray(attention_mask).astype(bool)
            compacted = np.zeros_like(cam)
            for i in range(cam.shape[0]):
                real = cam[i][am[i]]
                compacted[i, :real.shape[0]] = real
            cam = compacted
        mm = {
            "pixel_values": pixel_values,
            "aspect_ratio_ids": np.asarray(aspect_ratio_ids, dtype=np.int32),
            "aspect_ratio_mask": np.asarray(aspect_ratio_mask, dtype=np.int32),
            "cross_attention_mask": cam,
        }
        return super().generate(input_ids, _mm_embeds=mm, **kwargs)

    def _run_prefill(self, padded, sampling_params, key, adapter_ids, mm=None):
        if mm is None:
            return super()._run_prefill(padded, sampling_params, key, adapter_ids)
        a: MllamaArchArgs = self.arch_args
        b, s = padded.input_ids.shape
        per_tile = self.config.vision_tokens_per_tile
        cam = mm["cross_attention_mask"]                 # (B_in, S_in, M, T)
        allowed = np.repeat(cam.reshape(cam.shape[0], cam.shape[1], -1),
                            per_tile, axis=2).astype(bool)  # (B_in, S_in, T_vis)
        xmask = np.zeros((b, s, a.vision_tokens), dtype=bool)
        s_in = min(allowed.shape[1], s)
        xmask[:allowed.shape[0], :s_in] = allowed[:, :s_in]
        xfull = xmask.any(axis=-1, keepdims=True).astype(np.float32)
        # decode visibility = each row's LAST real prompt token's row (HF generate)
        last = np.asarray(padded.last_token_idx)
        xmask_dec = xmask[np.arange(b), np.minimum(last, s - 1)]
        xfull_dec = xmask_dec.any(axis=-1, keepdims=True).astype(np.float32)

        def _pad_batch(x):
            if x.shape[0] == b:
                return x
            out = np.zeros((b,) + x.shape[1:], dtype=x.dtype)
            out[:x.shape[0]] = x
            return out

        return self._xprefill_step(
            self.params, self.vision_params, padded.input_ids, padded.position_ids,
            padded.last_token_idx, self.kv_cache, sampling_params, key,
            _pad_batch(mm["pixel_values"]), _pad_batch(mm["aspect_ratio_ids"]),
            _pad_batch(mm["aspect_ratio_mask"]), xmask, xfull, xmask_dec, xfull_dec)


def _normalize_mllama_keys(state_dict: Dict[str, Any]) -> Dict[str, Any]:
    """On-disk legacy layout (``language_model.model.*``, bare ``vision_model.*``) ->
    in-memory layout (``model.language_model.*`` etc.)."""
    out = {}
    for k, v in state_dict.items():
        if k.startswith("language_model.model."):
            k = "model.language_model." + k[len("language_model.model."):]
        elif k == "language_model.lm_head.weight":
            k = "lm_head.weight"
        elif k.startswith("vision_model.") or k.startswith("multi_modal_projector."):
            k = "model." + k
        out[k] = v
    return out
