"""DeepSeek-V3-family models (V3 / R1; V3-style sigmoid-group routing): Multi-head Latent Attention (MLA) + DeepSeek MoE.

≈ reference `models/deepseek/modeling_deepseek.py` (`DeepseekV3Attention` :79-325:
latent KV cache, weight-matrix absorption, yarn rope) and
`models/deepseek/rope_util.py`. TPU redesign:

- **Latent KV cache.** One cache tensor per layer of shape (B, 1, S, R + C) holding
  ``[k_pe (rope dim R) | compressed_kv (kv_lora_rank C)]`` — the MQA-like latent the
  reference caches (`modeling_deepseek.py:322` ``past_key_value = (k_pe, compressed_kv)``).
  For V3 geometry (R=64, C=512) this is ~9x smaller than the materialized per-head
  cache and is *replicated* across tp ranks (heads are sharded; the latent is shared),
  the standard MLA TP layout.
- **Absorbed matmuls.** ``q_nope`` is pre-multiplied by the K half of ``kv_b_proj`` and
  the attention output by the V half (`modeling_deepseek.py:255-259,291-317`), so
  attention runs entirely in the C-dim latent space; the per-head K/V are never
  materialized. HF's unabsorbed reference implementation is numerically identical.
- **Two-segment layer scan.** DeepSeek stacks ``first_k_dense_replace`` dense-MLP
  layers then MoE layers; each segment is a `lax.scan` over its stacked params
  (uniform shapes within a segment keep compile time O(1) in depth like models/base).
- MoE routing (sigmoid scores + group-limited top-k + e_score_correction_bias +
  ungated shared experts) lives in ops/moe.py (``router_mode="sigmoid_group"``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...config import InferenceConfig
from ...modules import block_kvcache, kvcache
from ...ops import rope as rope_ops
from ...ops.moe import MoEArgs, moe_block
from ...ops.norms import rms_norm
from ...ops.quantization import qapply, qeinsum
from ...parallel.sharding import constrain, named_sharding
from ..base import (ModelArchArgs, Params, _ACTIVATIONS, _embed, _lm_head, _mlp,
                    _norm)
from ...runtime.application import TpuModelForCausalLM


@dataclass(frozen=True)
class DeepseekArchArgs(ModelArchArgs):
    """MLA + DeepSeek-MoE architecture extension of ModelArchArgs.

    ``intermediate_size`` is the routed-expert width (moe_intermediate_size);
    ``dense_intermediate_size`` the width of the first-k dense layers' MLP."""

    q_lora_rank: Optional[int] = None     # None -> full q projection (V2-Lite)
    kv_lora_rank: int = 512
    qk_rope_head_dim: int = 64
    qk_nope_head_dim: int = 128
    v_head_dim: int = 128
    rope_interleave: bool = True
    first_k_dense_replace: int = 0
    dense_intermediate_size: int = 0

    @property
    def qk_head_dim(self) -> int:
        return self.qk_nope_head_dim + self.qk_rope_head_dim

    @property
    def latent_dim(self) -> int:
        return self.qk_rope_head_dim + self.kv_lora_rank


# --- functional MLA layers ------------------------------------------------------------


_deinterleave = rope_ops.deinterleave


def _mla_attention(lp: Params, args: DeepseekArchArgs, hn: jnp.ndarray,
                   cos: jnp.ndarray, sin: jnp.ndarray, mask: jnp.ndarray,
                   latent_cache: jnp.ndarray,
                   positions: Optional[jnp.ndarray], decode_bucket: Optional[int],
                   mesh, rules, paged=None, cache_batch_start=0):
    """MLA attention over the latent cache.

    hn: (B, S, H) normed hidden states. latent_cache: dense (B, 1, S_max, R+C), or
    paged (num_blocks, 1, block_size, R+C) when ``paged=(block_table, slot_mapping)``.
    Returns (attn_out (B, S, heads*v_dim), updated latent_cache)."""
    b, s, _ = hn.shape
    R, C = args.qk_rope_head_dim, args.kv_lora_rank
    nope = args.qk_nope_head_dim

    if args.q_lora_rank is None:
        q = qapply(hn, lp["wq"])
    else:
        q_a = rms_norm(qapply(hn, lp["q_a"]), lp["q_a_norm"], args.rms_norm_eps)
        q = qapply(q_a, lp["q_b"])
    q = q.reshape(b, s, args.num_heads, args.qk_head_dim).transpose(0, 2, 1, 3)
    q = constrain(q, ("batch", "heads", None, None), rules, mesh=mesh)
    q_nope, q_pe = q[..., :nope], q[..., nope:]

    ckv = qapply(hn, lp["kv_a"])                            # (B, S, C + R)
    c, k_pe = ckv[..., :C], ckv[..., C:]
    c = rms_norm(c, lp["kv_a_norm"], args.rms_norm_eps)     # (B, S, C)
    k_pe = k_pe[:, None, :, :]                              # (B, 1, S, R)

    if args.rope_interleave:
        q_pe = _deinterleave(q_pe)
        k_pe = _deinterleave(k_pe)
    q_pe, k_pe = rope_ops.apply_rotary(q_pe, k_pe, cos, sin)

    # absorb the K half of kv_b into q_nope: (B, h, S, nope) x (h, nope, C)
    q_c = qeinsum("bhsn,hnc->bhsc", q_nope, lp["k_absorb"])

    latent_new = jnp.concatenate(
        [k_pe, c[:, None, :, :]], axis=-1)                  # (B, 1, S, R+C)
    if paged is not None:
        block_table, slot_mapping = paged
        latent_cache = block_kvcache.write_slots(latent_cache, latent_new,
                                                 slot_mapping)
        if positions is None:
            latent_att = latent_new
        else:
            latent_att = block_kvcache.read_seq(latent_cache, block_table)
    elif positions is None:
        latent_cache = kvcache.write_prefill(latent_cache, latent_new,
                                             batch_start=cache_batch_start)
        latent_att = latent_new
    else:
        latent_cache = kvcache.write_decode(latent_cache, latent_new, positions)
        latent_att = kvcache.read_bucket(latent_cache, decode_bucket)
    k_pe_att = latent_att[:, 0, :, :R].astype(q_pe.dtype)   # (B, T, R)
    c_att = latent_att[:, 0, :, R:].astype(q_pe.dtype)      # (B, T, C)

    scale = (args.attention_scale if args.attention_scale is not None
             else args.qk_head_dim ** -0.5)
    scores = (jnp.einsum("bhsr,btr->bhst", q_pe, k_pe_att,
                         preferred_element_type=jnp.float32)
              + jnp.einsum("bhsc,btc->bhst", q_c, c_att,
                           preferred_element_type=jnp.float32)) * scale
    scores = jnp.where(mask, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1).astype(q_pe.dtype)

    x = jnp.einsum("bhst,btc->bhsc", probs, c_att)          # (B, h, S, C)
    attn = qeinsum("bhsc,hcv->bhsv", x, lp["v_absorb"])     # (B, h, S, v_dim)
    attn = constrain(attn, ("batch", "heads", None, None), rules, mesh=mesh)
    attn = attn.transpose(0, 2, 1, 3).reshape(b, s, args.num_heads * args.v_head_dim)
    return attn, latent_cache


def _deepseek_layer(lp: Params, args: DeepseekArchArgs, h, cos, sin, mask,
                    latent_cache, positions, decode_bucket, mesh, rules,
                    is_moe: bool, paged=None, cache_batch_start=0):
    resid = h
    hn = _norm(h, lp["ln1"], args)
    attn, latent_cache = _mla_attention(lp, args, hn, cos, sin, mask, latent_cache,
                                        positions, decode_bucket, mesh, rules,
                                        paged=paged,
                                        cache_batch_start=cache_batch_start)
    attn_out = qapply(attn, lp["wo"])
    attn_out = constrain(attn_out, ("batch", None, None), rules, mesh=mesh)
    h = resid + attn_out

    resid = h
    hn = _norm(h, lp["ln2"], args)
    if is_moe:
        ffn = moe_block(lp, args, hn, mesh, rules, _ACTIVATIONS[args.activation])
    else:
        ffn = _mlp(lp, args, hn, mesh, rules)
    h = resid + constrain(ffn, ("batch", None, None), rules, mesh=mesh)
    return h, latent_cache


def _run_segments(params: Params, args: DeepseekArchArgs, h, cos, sin, mask, cache,
                  positions, decode_bucket, mesh, rules, paged=None,
                  cache_batch_start=0):
    """Scan the dense segment then the MoE segment, carrying hidden + latent cache."""
    latents = cache["latent"]                       # (L, B, 1, S, R+C) | paged blocks
    kd = args.first_k_dense_replace
    new_latents = []

    def _scan(stack, latent_stack, is_moe):
        def body(carry_h, xs):
            lp, lat = xs
            new_h, lat = _deepseek_layer(lp, args, carry_h, cos, sin, mask, lat,
                                         positions, decode_bucket, mesh, rules,
                                         is_moe=is_moe, paged=paged,
                                         cache_batch_start=cache_batch_start)
            return new_h, lat

        return jax.lax.scan(body, h, (stack, latent_stack))

    if kd > 0:
        h, lat_dense = _scan(params["dense"], latents[:kd], is_moe=False)
        new_latents.append(lat_dense)
    if kd < args.num_layers:
        h, lat_moe = _scan(params["moe"], latents[kd:], is_moe=True)
        new_latents.append(lat_moe)
    return h, {"latent": jnp.concatenate(new_latents, axis=0)}


def prefill_forward(params: Params, args: DeepseekArchArgs, input_ids, position_ids,
                    last_token_idx, cache, mesh=None, rules=None, use_flash=False,
                    slot_mapping=None, cache_batch_start=0, adapter_ids=None,
                    use_ring=False, return_hidden=False):
    """Context encoding over the latent cache (signature-compatible with
    models/base.prefill_forward; flash/ring/LoRA are not supported for MLA yet).
    ``slot_mapping`` switches to the paged latent cache; ``cache_batch_start`` lands
    the dense write at a continuous-batching slot row."""
    h = _embed(params, args, input_ids, mesh, rules)
    cos, sin = rope_ops.compute_cos_sin(params["rope_inv_freq"], position_ids,
                                        args.rope_attention_scaling)
    from ..base import causal_mask as _cm  # reuse base mask helpers

    mask = (position_ids[:, None, :, None] >= position_ids[:, None, None, :])
    mask = jnp.logical_and(mask, _cm(input_ids.shape[1], input_ids.shape[1])[None, None])
    paged = None
    if slot_mapping is not None:
        paged = (jnp.zeros((input_ids.shape[0], 1), dtype=jnp.int32), slot_mapping)
    h, cache = _run_segments(params, args, h, cos, sin, mask, cache,
                             positions=None, decode_bucket=None, mesh=mesh,
                             rules=rules, paged=paged,
                             cache_batch_start=cache_batch_start)
    h = _norm(h, params["final_norm"], args)
    h_last = jnp.take_along_axis(h, last_token_idx[:, None, None], axis=1)[:, 0]
    logits = _lm_head(params, args, h_last, mesh, rules)
    if return_hidden:
        return logits, cache, h
    return logits, cache


def decode_forward(params: Params, args: DeepseekArchArgs, input_ids, position_ids,
                   cache, decode_bucket, mesh=None, rules=None, block_table=None,
                   slot_mapping=None, adapter_ids=None, tree=None,
                   return_hidden=False):
    """Token generation over the latent cache (dense bucketed or paged mode)."""
    paged = None
    if block_table is not None:
        paged = (block_table, slot_mapping)
        block_size = cache["latent"].shape[3]
        decode_bucket = block_table.shape[1] * block_size
    b, t = input_ids.shape
    h = _embed(params, args, input_ids, mesh, rules)
    pos_grid = position_ids[:, None] + jnp.arange(t)[None, :]
    cos, sin = rope_ops.compute_cos_sin(params["rope_inv_freq"], pos_grid,
                                        args.rope_attention_scaling)
    kv_pos = jnp.arange(decode_bucket)[None, None, None, :]
    q_pos = pos_grid[:, None, :, None]
    mask = kv_pos <= q_pos
    h, cache = _run_segments(params, args, h, cos, sin, mask, cache,
                             positions=position_ids, decode_bucket=decode_bucket,
                             mesh=mesh, rules=rules, paged=paged)
    h = _norm(h, params["final_norm"], args)
    logits = _lm_head(params, args, h, mesh, rules)
    if return_hidden:
        return logits, cache, h
    return logits, cache


# --- config / application -------------------------------------------------------------


class DeepseekInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = (
        "hidden_size", "num_attention_heads", "num_hidden_layers", "vocab_size",
        "kv_lora_rank", "qk_rope_head_dim", "qk_nope_head_dim", "v_head_dim",
    )

    def add_derived_config(self) -> None:
        # present-but-None attrs also get the default (for q_lora_rank/rope_scaling/
        # n_routed_experts/moe_intermediate_size the default IS None, i.e. meaningful)
        for attr, default in (
                ("rms_norm_eps", 1e-6), ("rope_theta", 10000.0),
                ("rope_scaling", None), ("rope_interleave", True),
                ("tie_word_embeddings", False), ("hidden_act", "silu"),
                ("q_lora_rank", None), ("first_k_dense_replace", 0),
                ("n_routed_experts", None), ("num_experts_per_tok", 8),
                ("n_group", 1), ("topk_group", 1), ("n_shared_experts", 0),
                ("routed_scaling_factor", 1.0), ("norm_topk_prob", True),
                ("moe_intermediate_size", None), ("intermediate_size", None)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)


class DeepseekForCausalLM(TpuModelForCausalLM):
    """≈ the reference DeepSeek application built on `DeepseekV3Attention`.

    Quantization (int8/fp8 weight-only over the MLA projections incl. the absorbed
    kv_b halves, ≈ reference quant flows `models/model_wrapper.py:11-21`), continuous
    batching, and paged attention run on the latent-cache layout; LoRA and fused
    speculation remain unsupported for MLA."""

    def __init__(self, model_path, config, mesh=None):
        self._require_base_layout(config.tpu_config, "MLA (DeepSeek)",
                                  allow=("quantization_config",
                                         "is_continuous_batching",
                                         "paged_attention_enabled"))
        super().__init__(model_path, config, mesh=mesh)

    def quantized_param_names(self):
        from ...ops.quantization import DEFAULT_QUANTIZED_PARAMS

        return DEFAULT_QUANTIZED_PARAMS + (
            "q_a", "q_b", "kv_a", "k_absorb", "v_absorb")

    @classmethod
    def get_config_cls(cls):
        return DeepseekInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> DeepseekArchArgs:
        rope_scaling = config.rope_scaling
        scale = (config.qk_nope_head_dim + config.qk_rope_head_dim) ** -0.5
        if rope_scaling is not None and rope_scaling.get("mscale_all_dim"):
            m = rope_ops.yarn_mscale(rope_scaling["factor"],
                                     rope_scaling["mscale_all_dim"])
            scale = scale * m * m
        moe = None
        if config.n_routed_experts:
            moe = MoEArgs(
                num_experts=config.n_routed_experts,
                experts_per_tok=config.num_experts_per_tok,
                norm_topk_prob=config.norm_topk_prob,
                router_mode="sigmoid_group",
                n_group=config.n_group,
                topk_group=config.topk_group,
                score_correction_bias=True,
                routed_scaling_factor=config.routed_scaling_factor,
                shared_expert_intermediate_size=(
                    (config.n_shared_experts or 0)
                    * (config.moe_intermediate_size or 0)),
                shared_expert_gated=False,
            )
        return DeepseekArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=1,                       # latent cache is MQA-like
            head_dim=config.v_head_dim,
            intermediate_size=(config.moe_intermediate_size
                               or config.intermediate_size),
            dense_intermediate_size=config.intermediate_size,
            rms_norm_eps=config.rms_norm_eps,
            activation=config.hidden_act,
            attention_scale=scale,
            rope_attention_scaling=rope_ops.attention_scaling_from_hf_config(
                rope_scaling),
            tie_word_embeddings=config.tie_word_embeddings,
            q_lora_rank=config.q_lora_rank,
            kv_lora_rank=config.kv_lora_rank,
            qk_rope_head_dim=config.qk_rope_head_dim,
            qk_nope_head_dim=config.qk_nope_head_dim,
            v_head_dim=config.v_head_dim,
            rope_interleave=config.rope_interleave,
            first_k_dense_replace=(config.first_k_dense_replace
                                   if config.n_routed_experts else
                                   config.num_hidden_layers),
            moe=moe,
        )

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        return rope_ops.inv_freq_from_hf_config(
            config.qk_rope_head_dim, config.rope_theta, config.rope_scaling)

    # MLA has no flash/ring path yet; the jnp attention is the supported strategy
    def _use_flash_attention(self) -> bool:
        if self.tpu_config.attention_kernel_enabled is True:
            raise ValueError("the Pallas flash kernel does not support MLA yet")
        return False

    def _use_ring_attention(self) -> bool:
        if self.mesh.shape["cp"] > 1:
            raise ValueError("context parallelism is not supported for MLA yet")
        return False

    # --- custom param layout ----------------------------------------------------------
    def prefill_fn(self):
        return prefill_forward

    def decode_fn(self):
        return decode_forward

    def _attn_axes(self) -> Dict[str, Tuple]:
        a = self.arch_args
        axes = {
            "ln1": ("layers", None),
            "ln2": ("layers", None),
            "kv_a": ("layers", "embed", None),
            "kv_a_norm": ("layers", None),
            "k_absorb": ("layers", "heads", None, None),
            "v_absorb": ("layers", "heads", None, None),
            "wo": ("layers", "heads", "embed"),
        }
        if a.q_lora_rank is None:
            axes["wq"] = ("layers", "embed", "heads")
        else:
            axes.update({"q_a": ("layers", "embed", None),
                         "q_a_norm": ("layers", None),
                         "q_b": ("layers", None, "heads")})
        return axes

    def logical_axes(self) -> Dict:
        a: DeepseekArchArgs = self.arch_args
        out: Dict[str, Any] = {
            "embed": ("vocab", "embed"),
            "final_norm": (None,),
            "rope_inv_freq": (None,),
        }
        if not a.tie_word_embeddings:
            out["lm_head"] = ("embed", "vocab")
        if a.first_k_dense_replace > 0:
            dense = dict(self._attn_axes())
            dense.update({"wg": ("layers", "embed", "mlp"),
                          "wu": ("layers", "embed", "mlp"),
                          "wd": ("layers", "mlp", "embed")})
            out["dense"] = dense
        if a.first_k_dense_replace < a.num_layers:
            moe_axes = dict(self._attn_axes())
            moe_axes.update({
                "router": ("layers", "embed", None),
                "router_cb": ("layers", None),
                "wg": ("layers", "experts", "embed", "expert_mlp"),
                "wu": ("layers", "experts", "embed", "expert_mlp"),
                "wd": ("layers", "experts", "expert_mlp", "embed"),
                "shared_wg": ("layers", "embed", "mlp"),
                "shared_wu": ("layers", "embed", "mlp"),
                "shared_wd": ("layers", "mlp", "embed"),
            })
            out["moe"] = moe_axes
        return out

    def init_random_params(self, key) -> Dict:
        a: DeepseekArchArgs = self.arch_args
        dtype = self.tpu_config.jax_dtype
        H, nh = a.hidden_size, a.num_heads
        ks = iter(jax.random.split(key, 40))

        def w(shape, scale=0.02):
            return (jax.random.normal(next(ks), shape, dtype=jnp.float32)
                    * scale).astype(dtype)

        def attn_stack(L):
            C, R = a.kv_lora_rank, a.qk_rope_head_dim
            p = {
                "ln1": jnp.ones((L, H), dtype=dtype),
                "ln2": jnp.ones((L, H), dtype=dtype),
                "kv_a": w((L, H, C + R)),
                "kv_a_norm": jnp.ones((L, C), dtype=dtype),
                "k_absorb": w((L, nh, a.qk_nope_head_dim, C)),
                "v_absorb": w((L, nh, C, a.v_head_dim)),
                "wo": w((L, nh * a.v_head_dim, H)),
            }
            if a.q_lora_rank is None:
                p["wq"] = w((L, H, nh * a.qk_head_dim))
            else:
                p.update({"q_a": w((L, H, a.q_lora_rank)),
                          "q_a_norm": jnp.ones((L, a.q_lora_rank), dtype=dtype),
                          "q_b": w((L, a.q_lora_rank, nh * a.qk_head_dim))})
            return p

        params: Dict[str, Any] = {
            "embed": w((a.vocab_size, H)),
            "final_norm": jnp.ones((H,), dtype=dtype),
            "rope_inv_freq": jnp.asarray(self.inv_freq_from_config(self.config),
                                         dtype=jnp.float32),
        }
        if not a.tie_word_embeddings:
            params["lm_head"] = w((H, a.vocab_size))
        kd = a.first_k_dense_replace
        if kd > 0:
            dense = attn_stack(kd)
            I = a.dense_intermediate_size
            dense.update({"wg": w((kd, H, I)), "wu": w((kd, H, I)),
                          "wd": w((kd, I, H))})
            params["dense"] = dense
        L_moe = a.num_layers - kd
        if L_moe > 0:
            moe_p = attn_stack(L_moe)
            E, I = a.moe.num_experts, a.intermediate_size
            Ish = a.moe.shared_expert_intermediate_size
            moe_p.update({
                "router": w((L_moe, H, E)),
                "router_cb": jnp.zeros((L_moe, E), dtype=dtype),
                "wg": w((L_moe, E, H, I)),
                "wu": w((L_moe, E, H, I)),
                "wd": w((L_moe, E, I, H)),
                "shared_wg": w((L_moe, H, Ish)),
                "shared_wu": w((L_moe, H, Ish)),
                "shared_wd": w((L_moe, Ish, H)),
            })
            params["moe"] = moe_p
        return params

    # --- latent cache -----------------------------------------------------------------
    def make_paged_cache(self, num_blocks: int, block_size: int):
        """Paged latent cache: (L, num_blocks, 1, block_size, R+C), replicated over
        tp like the dense latent."""
        a: DeepseekArchArgs = self.arch_args
        shape = (a.num_layers, num_blocks, 1, block_size, a.latent_dim)
        sharding = named_sharding(self.mesh, ("layers", None, None, None, None))
        return {"latent": jax.device_put(
            jnp.zeros(shape, dtype=self.tpu_config.kv_cache_jax_dtype), sharding)}

    def reset_cache(self) -> None:
        a: DeepseekArchArgs = self.arch_args
        shape = (a.num_layers, self.tpu_config.max_batch_size, 1,
                 self.tpu_config.seq_len, a.latent_dim)
        # latent is replicated over tp (heads are sharded, the latent is shared);
        # batch rides dp
        sharding = named_sharding(self.mesh,
                                  ("layers", "batch", None, None, None))
        self.kv_cache = {"latent": jax.device_put(
            jnp.zeros(shape, dtype=self.tpu_config.kv_cache_jax_dtype), sharding)}

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        args = cls.arch_args_from_config(config)
        L, nh = config.num_hidden_layers, config.num_attention_heads
        nope, v_dim, C = (config.qk_nope_head_dim, config.v_head_dim,
                          config.kv_lora_rank)
        kd = args.first_k_dense_replace

        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return state_dict[name]

        def linear_t(name):
            return np.ascontiguousarray(get(name).T)

        def attn_params(i):
            p = f"model.layers.{i}.self_attn."
            wkv_b = get(p + "kv_b_proj.weight").reshape(nh, nope + v_dim, C)
            out = {
                "ln1": get(f"model.layers.{i}.input_layernorm.weight"),
                "ln2": get(f"model.layers.{i}.post_attention_layernorm.weight"),
                "kv_a": linear_t(p + "kv_a_proj_with_mqa.weight"),
                "kv_a_norm": get(p + "kv_a_layernorm.weight"),
                "k_absorb": wkv_b[:, :nope, :],
                # stored (heads, C, v) so the contraction dim sits at axis -2
                # ((in, out) layout, required by per-channel weight quantization)
                "v_absorb": np.ascontiguousarray(
                    wkv_b[:, nope:, :].transpose(0, 2, 1)),
                "wo": linear_t(p + "o_proj.weight"),
            }
            if args.q_lora_rank is None:
                out["wq"] = linear_t(p + "q_proj.weight")
            else:
                out.update({"q_a": linear_t(p + "q_a_proj.weight"),
                            "q_a_norm": get(p + "q_a_layernorm.weight"),
                            "q_b": linear_t(p + "q_b_proj.weight")})
            return out

        def stack(dicts):
            return {k: np.stack([d[k] for d in dicts]) for k in dicts[0]}

        params: Dict[str, Any] = {
            "embed": get("model.embed_tokens.weight"),
            "final_norm": get("model.norm.weight"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
        if not args.tie_word_embeddings:
            params["lm_head"] = linear_t("lm_head.weight")

        if kd > 0:
            dense = []
            for i in range(kd):
                d = attn_params(i)
                m = f"model.layers.{i}.mlp."
                d.update({"wg": linear_t(m + "gate_proj.weight"),
                          "wu": linear_t(m + "up_proj.weight"),
                          "wd": linear_t(m + "down_proj.weight")})
                dense.append(d)
            params["dense"] = stack(dense)
        if kd < L:
            moe_layers = []
            E = config.n_routed_experts
            for i in range(kd, L):
                d = attn_params(i)
                m = f"model.layers.{i}.mlp."
                d.update({
                    "router": linear_t(m + "gate.weight"),
                    "router_cb": get(m + "gate.e_score_correction_bias"),
                    "wg": np.stack([linear_t(m + f"experts.{e}.gate_proj.weight")
                                    for e in range(E)]),
                    "wu": np.stack([linear_t(m + f"experts.{e}.up_proj.weight")
                                    for e in range(E)]),
                    "wd": np.stack([linear_t(m + f"experts.{e}.down_proj.weight")
                                    for e in range(E)]),
                })
                if args.moe.shared_expert_intermediate_size:
                    d.update({
                        "shared_wg": linear_t(m + "shared_experts.gate_proj.weight"),
                        "shared_wu": linear_t(m + "shared_experts.up_proj.weight"),
                        "shared_wd": linear_t(m + "shared_experts.down_proj.weight"),
                    })
                moe_layers.append(d)
            params["moe"] = stack(moe_layers)
        return params
