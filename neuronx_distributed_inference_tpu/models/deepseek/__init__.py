from .modeling_deepseek import (DeepseekArchArgs, DeepseekForCausalLM,
                                DeepseekInferenceConfig)

__all__ = ["DeepseekArchArgs", "DeepseekForCausalLM", "DeepseekInferenceConfig"]
