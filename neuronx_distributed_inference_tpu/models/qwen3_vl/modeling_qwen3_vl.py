"""Qwen3-VL (vision-language) family.

≈ reference `models/qwen3_vl/` (vision tower + deepstack + interleaved M-RoPE text).
TPU redesign over the image-to-text base:

- **Vision tower** (one pure jitted fn): 3D-conv patch embedding as a flat linear,
  bilinearly-interpolated learned position embeddings (indices/weights precomputed
  host-side, 4 gathers on device), 2D rotary over (row, col) patch coordinates,
  pre-LN biased blocks with per-frame full attention (segment mask), spatial-merge
  MLP head.
- **DeepStack** (`deepstack_visual_indexes`): intermediate block outputs pass through
  their own post-shuffle mergers and ADD into the first K text layers' outputs at
  image-token positions (`models/base.prefill_forward(deepstack=...)`,
  ≈ reference deepstack integration, `models/model_base.py:1235-1247`).
- **Text**: qwen3 stack (qk-norm) with *interleaved* M-RoPE
  (`ops/rope.mrope_cos_sin_interleaved`) — channels cycle [T,H,W,T,H,W,...] instead
  of qwen2-vl's chunked sections; decode collapses to 1D rope + per-row delta via
  the shared ``rope_delta`` cache mechanism.

Images only (videos need timestamp-separated grids; the images-only guard lives in
qwen2_5_vl.get_rope_index_images, reused here).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...ops import rope as rope_ops
from ...ops.norms import layer_norm
from ...runtime.image_to_text import (ImageToTextInferenceConfig,
                                      TpuModelForImageToText)
from ..qwen2_5_vl.modeling_qwen2_5_vl import get_rope_index_images, segment_mask
from ..qwen3.modeling_qwen3 import Qwen3ForCausalLM, Qwen3InferenceConfig


# --- host-side geometry ---------------------------------------------------------------


def merge_order_coords(grid_thw: np.ndarray, merge_size: int) -> np.ndarray:
    """(seq, 2) per-patch (row, col) coordinates in the processor's merge-window
    patch order (HF `rot_pos_emb`)."""
    out = []
    for t, h, w in np.asarray(grid_thw):
        mh, mw = h // merge_size, w // merge_size
        br = np.arange(mh)[:, None, None, None] * merge_size
        bc = np.arange(mw)[None, :, None, None] * merge_size
        ir = np.arange(merge_size)[None, None, :, None]
        ic = np.arange(merge_size)[None, None, None, :]
        rows = np.broadcast_to(br + ir, (mh, mw, merge_size, merge_size)).reshape(-1)
        cols = np.broadcast_to(bc + ic, (mh, mw, merge_size, merge_size)).reshape(-1)
        coords = np.stack([rows, cols], axis=-1)
        out.append(np.tile(coords, (int(t), 1)))
    return np.concatenate(out, axis=0)


def vision_rope_tables(grid_thw: np.ndarray, head_dim: int, merge_size: int,
                       theta: float = 10000.0) -> Tuple[np.ndarray, np.ndarray]:
    """(seq, head_dim) cos/sin for the vision blocks' 2D rotary."""
    coords = merge_order_coords(grid_thw, merge_size)          # (seq, 2)
    dim = head_dim // 2
    inv = 1.0 / (theta ** (np.arange(0, dim, 2, dtype=np.float64) / dim))
    freqs = coords[..., None].astype(np.float64) * inv[None, None, :]
    rpe = freqs.reshape(coords.shape[0], -1)                   # (seq, dim)
    emb = np.concatenate([rpe, rpe], axis=-1)                  # (seq, head_dim)
    return np.cos(emb).astype(np.float32), np.sin(emb).astype(np.float32)


def pos_embed_interp(grid_thw: np.ndarray, num_grid_per_side: int, merge_size: int
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """Bilinear interpolation plan for the learned position grid
    (HF `fast_pos_embed_interpolate`): returns (idx (4, seq), weights (4, seq)) in
    the merge-window patch order."""
    idx_all = [[] for _ in range(4)]
    w_all = [[] for _ in range(4)]
    n = num_grid_per_side
    for t, h, w in np.asarray(grid_thw):
        h_idx = np.linspace(0, n - 1, int(h))
        w_idx = np.linspace(0, n - 1, int(w))
        hf = h_idx.astype(np.int32)
        wf = w_idx.astype(np.int32)
        hc = np.clip(hf + 1, None, n - 1)
        wc = np.clip(wf + 1, None, n - 1)
        dh = h_idx - hf
        dw = w_idx - wf
        idx = [
            (hf[:, None] * n + wf[None, :]),
            (hf[:, None] * n + wc[None, :]),
            (hc[:, None] * n + wf[None, :]),
            (hc[:, None] * n + wc[None, :]),
        ]
        wts = [
            ((1 - dh)[:, None] * (1 - dw)[None, :]),
            ((1 - dh)[:, None] * dw[None, :]),
            (dh[:, None] * (1 - dw)[None, :]),
            (dh[:, None] * dw[None, :]),
        ]
        # permute row-major (h, w) -> merge-window order, tile over t frames
        mh, mw = int(h) // merge_size, int(w) // merge_size
        perm = (np.arange(int(h) * int(w))
                .reshape(mh, merge_size, mw, merge_size)
                .transpose(0, 2, 1, 3).reshape(-1))
        for i in range(4):
            flat_i = idx[i].reshape(-1)[perm]
            flat_w = wts[i].reshape(-1)[perm]
            idx_all[i].extend(np.tile(flat_i, int(t)).tolist())
            w_all[i].extend(np.tile(flat_w, int(t)).tolist())
    return (np.asarray(idx_all, dtype=np.int32),
            np.asarray(w_all, dtype=np.float32))


# --- vision encoder (jitted) ----------------------------------------------------------


def vision_encode(vp: Dict[str, Any], patches: jnp.ndarray, cos: jnp.ndarray,
                  sin: jnp.ndarray, seg_mask: jnp.ndarray, pos_idx: jnp.ndarray,
                  pos_w: jnp.ndarray, *, num_heads: int,
                  deepstack_indexes: Tuple[int, ...], merge_unit: int,
                  eps: float = 1e-6):
    """(seq, C*tps*p*p) merge-window-ordered patches ->
    (main (seq//unit, out_H), deepstack (K, seq//unit, out_H))."""
    h = patches @ vp["patch_w"] + vp["patch_b"]
    pos = sum(pos_w[i][:, None] * jnp.take(vp["pos_table"], pos_idx[i], axis=0)
              for i in range(4))
    h = h + pos.astype(h.dtype)
    seq, hidden = h.shape
    d = hidden // num_heads

    def rot(x):
        half = x.shape[-1] // 2
        rot_half = jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)
        return x * cos[:, None, :] + rot_half * sin[:, None, :]

    caps = tuple(jnp.zeros_like(h) for _ in deepstack_indexes)

    def block(carry, xs):
        hid, caps = carry
        lp, li = xs
        hn = layer_norm(hid, lp["ln1_w"], lp["ln1_b"], eps=eps)
        qkv = hn @ lp["wqkv"] + lp["bqkv"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = rot(q.reshape(seq, num_heads, d)).astype(hn.dtype)
        k = rot(k.reshape(seq, num_heads, d)).astype(hn.dtype)
        v = v.reshape(seq, num_heads, d)
        s = jnp.einsum("qhd,khd->hqk", q, k,
                       preferred_element_type=jnp.float32) * (d ** -0.5)
        s = jnp.where(seg_mask[None], s, jnp.finfo(jnp.float32).min)
        p = jax.nn.softmax(s, axis=-1).astype(hn.dtype)
        attn = jnp.einsum("hqk,khd->qhd", p, v).reshape(seq, hidden)
        hid = hid + (attn @ lp["wo"] + lp["bo"])
        hn = layer_norm(hid, lp["ln2_w"], lp["ln2_b"], eps=eps)
        hid = hid + (jax.nn.gelu(hn @ lp["fc1"] + lp["b1"], approximate=True)
                     @ lp["fc2"] + lp["b2"])
        caps = tuple(jnp.where(li == idx, hid, buf)
                     for idx, buf in zip(deepstack_indexes, caps))
        return (hid, caps), None

    depth = vp["blocks"]["wqkv"].shape[0]
    (h, caps), _ = jax.lax.scan(block, (h, caps),
                                (vp["blocks"], jnp.arange(depth)))

    # main merger: pre-shuffle LayerNorm, then merge-window concat + MLP
    def merger(x, mp, post_shuffle):
        if post_shuffle:
            x = x.reshape(seq // merge_unit, merge_unit * hidden)
            x = layer_norm(x, mp["ln_w"], mp["ln_b"], eps=eps)
        else:
            x = layer_norm(x, mp["ln_w"], mp["ln_b"], eps=eps)
            x = x.reshape(seq // merge_unit, merge_unit * hidden)
        x = jax.nn.gelu(x @ mp["fc1"] + mp["b1"], approximate=False)
        return x @ mp["fc2"] + mp["b2"]

    main = merger(h, vp["merger"], post_shuffle=False)
    ds = [merger(c, jax.tree.map(lambda t, _j=j: t[_j], vp["ds_mergers"]),
                 post_shuffle=True)
          for j, c in enumerate(caps)]
    return main, jnp.stack(ds) if ds else jnp.zeros((0,) + main.shape)


# --- config / application -------------------------------------------------------------


class Qwen3VLInferenceConfig(ImageToTextInferenceConfig, Qwen3InferenceConfig):
    REQUIRED_ATTRIBUTES = ("vision_config", "image_token_id")

    def add_derived_config(self) -> None:
        ImageToTextInferenceConfig.add_derived_config(self)
        Qwen3InferenceConfig.add_derived_config(self)
        for attr, default in (("vision_start_token_id", 151652),):
            if not hasattr(self, attr):
                setattr(self, attr, default)
        rs = getattr(self, "rope_scaling", None)
        sec = (rs or {}).get("mrope_section")
        if not sec:
            third = (self.head_dim // 2) // 3
            sec = [self.head_dim // 2 - 2 * third, third, third]
        self.mrope_section = sec


class Qwen3VLForConditionalGeneration(TpuModelForImageToText, Qwen3ForCausalLM):
    """≈ reference Qwen3VL conditional generation (deepstack vision + M-RoPE text)."""

    @classmethod
    def get_config_cls(cls):
        return Qwen3VLInferenceConfig

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        return rope_ops.default_inv_freq(config.head_dim,
                                         getattr(config, "rope_theta", 5e6))

    @property
    def image_token_index(self) -> int:
        return self.config.image_token_id

    def __init__(self, model_path, config, mesh=None):
        super().__init__(model_path, config, mesh=mesh)
        vc = config.vision_config
        self._vision_geo = {
            "patch_size": vc["patch_size"],
            "merge_size": vc["spatial_merge_size"],
            "num_heads": vc["num_heads"],
            "head_dim": vc["hidden_size"] // vc["num_heads"],
            "grid_side": int(vc["num_position_embeddings"] ** 0.5),
            "deepstack": tuple(vc["deepstack_visual_indexes"]),
        }
        m = vc["spatial_merge_size"]
        self._vision_jit = jax.jit(functools.partial(
            vision_encode, num_heads=vc["num_heads"],
            deepstack_indexes=self._vision_geo["deepstack"],
            merge_unit=m * m))

    def vision_encode_fn(self):
        # unused (variable image grids drive a dedicated jit); satisfy the hook
        return lambda vp, px: px

    # --- weights ----------------------------------------------------------------------
    @classmethod
    def convert_hf_state_dict(cls, state_dict, config):
        text_sd = {}
        for k, v in state_dict.items():
            if k.startswith("model.language_model."):
                text_sd["model." + k[len("model.language_model."):]] = v
            elif k.startswith("language_model.model."):
                text_sd["model." + k[len("language_model.model."):]] = v
            elif k == "language_model.lm_head.weight":
                text_sd["lm_head.weight"] = v
            elif k.startswith(("model.visual.", "visual.")):
                continue
            elif k.startswith("model.") or k == "lm_head.weight":
                text_sd[k] = v
        return super().convert_hf_state_dict(text_sd, config)

    @classmethod
    def convert_hf_vision_state_dict(cls, state_dict, config):
        vc = config.vision_config
        hidden = vc["hidden_size"]

        def norm_key(k):
            if k.startswith("model.visual."):
                return "visual." + k[len("model.visual."):]
            return k

        sd = {norm_key(k): v for k, v in state_dict.items()}

        def get(name):
            if name not in sd:
                raise KeyError(f"missing weight {name}")
            return np.asarray(sd[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        blocks = {k: [] for k in ("ln1_w", "ln1_b", "wqkv", "bqkv", "wo", "bo",
                                  "ln2_w", "ln2_b", "fc1", "b1", "fc2", "b2")}
        for i in range(vc["depth"]):
            p = f"visual.blocks.{i}."
            blocks["ln1_w"].append(get(p + "norm1.weight"))
            blocks["ln1_b"].append(get(p + "norm1.bias"))
            blocks["wqkv"].append(lin_t(p + "attn.qkv.weight"))
            blocks["bqkv"].append(get(p + "attn.qkv.bias"))
            blocks["wo"].append(lin_t(p + "attn.proj.weight"))
            blocks["bo"].append(get(p + "attn.proj.bias"))
            blocks["ln2_w"].append(get(p + "norm2.weight"))
            blocks["ln2_b"].append(get(p + "norm2.bias"))
            blocks["fc1"].append(lin_t(p + "mlp.linear_fc1.weight"))
            blocks["b1"].append(get(p + "mlp.linear_fc1.bias"))
            blocks["fc2"].append(lin_t(p + "mlp.linear_fc2.weight"))
            blocks["b2"].append(get(p + "mlp.linear_fc2.bias"))

        def merger_params(prefix):
            return {
                "ln_w": get(prefix + "norm.weight"),
                "ln_b": get(prefix + "norm.bias"),
                "fc1": lin_t(prefix + "linear_fc1.weight"),
                "b1": get(prefix + "linear_fc1.bias"),
                "fc2": lin_t(prefix + "linear_fc2.weight"),
                "b2": get(prefix + "linear_fc2.bias"),
            }

        ds = [merger_params(f"visual.deepstack_merger_list.{j}.")
              for j in range(len(vc["deepstack_visual_indexes"]))]
        conv = get("visual.patch_embed.proj.weight")   # (hidden, C, tps, p, p)
        return {
            "patch_w": np.ascontiguousarray(conv.reshape(hidden, -1).T),
            "patch_b": get("visual.patch_embed.proj.bias"),
            "pos_table": get("visual.pos_embed.weight"),
            "blocks": {k: np.stack(v) for k, v in blocks.items()},
            "merger": merger_params("visual.merger."),
            "ds_mergers": {k: np.stack([d[k] for d in ds]) for k in ds[0]}
            if ds else {},
        }

    # --- vision -----------------------------------------------------------------------
    def encode_vision(self, pixel_values: np.ndarray, image_grid_thw: np.ndarray):
        """Returns (features (n_llm_tokens, H_text), deepstack (K, n_llm_tokens, H))."""
        g = self._vision_geo
        grid = np.asarray(image_grid_thw)
        seq = int(np.prod(grid, axis=1).sum())
        cos, sin = vision_rope_tables(grid, g["head_dim"], g["merge_size"])
        pos_idx, pos_w = pos_embed_interp(grid, g["grid_side"], g["merge_size"])
        frame_lens = np.repeat(grid[:, 1] * grid[:, 2], grid[:, 0])
        cu = np.concatenate([[0], np.cumsum(frame_lens)]).astype(np.int64)
        seg = segment_mask(cu, seq)
        px = np.asarray(pixel_values, dtype=np.float32)
        main, ds = self._vision_jit(self.vision_params, px, cos, sin, seg,
                                    pos_idx, pos_w)
        return np.asarray(main), np.asarray(ds)

    # --- mm prefill with interleaved M-RoPE + deepstack -------------------------------
    def _build_mm_prefill(self):
        args, mesh, rules = self.arch_args, self.mesh, self.sharding_rules
        odsc = self.sampling_config
        prefill_core = self.prefill_fn()
        sections = tuple(self.config.mrope_section)
        from ...ops import sampling as sampling_ops

        precision, use_ring, use_flash = self._mm_strategy()

        def _prefill_mm(params, input_ids, position_ids, last_token_idx, cache,
                        sampling_params, key, mm_mask, mm_override, positions3,
                        deepstack, adapter_ids=None):
            with jax.default_matmul_precision(precision):
                cos, sin = rope_ops.mrope_cos_sin_interleaved(
                    params["rope_inv_freq"], positions3, sections,
                    args.rope_attention_scaling)
                logits, cache = prefill_core(
                    params, args, input_ids, position_ids, last_token_idx, cache,
                    mesh=mesh, rules=rules, adapter_ids=adapter_ids,
                    use_flash=use_flash, use_ring=use_ring,
                    merge_embeds=(mm_mask, mm_override),
                    rope_override=(cos, sin), deepstack=deepstack)
                tokens = sampling_ops.sample(logits, sampling_params, key, odsc)
            return tokens, logits, cache

        return jax.jit(_prefill_mm, donate_argnums=(4,))

    def reset_cache(self) -> None:
        super().reset_cache()
        b = self.tpu_config.max_batch_size
        self.kv_cache["rope_delta"] = jnp.zeros((b,), dtype=jnp.int32)

    def warmup(self) -> None:
        from ...runtime.application import TpuModelForCausalLM

        TpuModelForCausalLM.warmup(self)

    # --- generation -------------------------------------------------------------------
    def generate(self, input_ids, pixel_values=None, image_grid_thw=None, **kwargs):
        if pixel_values is None:
            return Qwen3ForCausalLM.generate(self, input_ids, **kwargs)
        feats, ds = self.encode_vision(pixel_values, image_grid_thw)
        mm = {"features": feats, "deepstack": ds,
              "grid_thw": np.asarray(image_grid_thw)}
        return Qwen3ForCausalLM.generate(self, input_ids, _mm_embeds=mm, **kwargs)

    def _run_prefill(self, padded, sampling_params, key, adapter_ids, mm=None):
        if mm is None:
            return super(TpuModelForImageToText, self)._run_prefill(
                padded, sampling_params, key, adapter_ids)
        mask, override = self._scatter_features(padded, mm["features"])
        ids = np.asarray(padded.input_ids)
        valid = np.arange(ids.shape[1])[None, :] <= np.asarray(
            padded.last_token_idx)[:, None]
        positions3, deltas = get_rope_index_images(
            ids, valid.astype(np.int64), mm["grid_thw"],
            self.config.vision_config["spatial_merge_size"],
            self.image_token_index, self.config.vision_start_token_id)
        self.kv_cache["rope_delta"] = jnp.asarray(deltas, dtype=jnp.int32)
        # deepstack features scattered at image positions per early layer
        k_layers = mm["deepstack"].shape[0]
        h = self.arch_args.hidden_size
        ds = np.zeros((k_layers,) + ids.shape + (h,), dtype=np.float32)
        flat_mask = ids == self.image_token_index
        for j in range(k_layers):
            ds[j][flat_mask] = mm["deepstack"][j]
        return self._mm_prefill_step(
            self.params, padded.input_ids, padded.position_ids,
            padded.last_token_idx, self.kv_cache, sampling_params, key,
            mask, override, positions3, ds, adapter_ids)
