from .modeling_qwen3_vl import (Qwen3VLForConditionalGeneration,
                                Qwen3VLInferenceConfig)

__all__ = ["Qwen3VLForConditionalGeneration", "Qwen3VLInferenceConfig"]
