"""Mistral model family (the text side of Pixtral; llama-compatible + sliding window).

≈ reference contrib mistral port; checkpoint layout is identical to llama
(`models/llama/modeling_llama.py` conversion applies unchanged)."""

from __future__ import annotations

import dataclasses

from ..base import ModelArchArgs
from ..llama.modeling_llama import LlamaForCausalLM, LlamaInferenceConfig


class MistralInferenceConfig(LlamaInferenceConfig):
    def add_derived_config(self) -> None:
        super().add_derived_config()
        if not hasattr(self, "sliding_window"):
            self.sliding_window = None


class MistralForCausalLM(LlamaForCausalLM):
    @classmethod
    def get_config_cls(cls):
        return MistralInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> ModelArchArgs:
        args = super().arch_args_from_config(config)
        return dataclasses.replace(args, sliding_window=config.sliding_window)
