from .modeling_mistral import MistralForCausalLM, MistralInferenceConfig

__all__ = ["MistralForCausalLM", "MistralInferenceConfig"]
