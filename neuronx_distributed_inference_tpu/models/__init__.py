"""Model hub registry (≈ reference `models/` + per-arch Neuron*ForCausalLM classes)."""

from typing import Dict, Type

_REGISTRY: Dict[str, str] = {
    # hf model_type -> "module:class"
    "llama": "neuronx_distributed_inference_tpu.models.llama.modeling_llama:LlamaForCausalLM",
    "qwen2": "neuronx_distributed_inference_tpu.models.qwen2.modeling_qwen2:Qwen2ForCausalLM",
    "qwen3": "neuronx_distributed_inference_tpu.models.qwen3.modeling_qwen3:Qwen3ForCausalLM",
    "gemma3": "neuronx_distributed_inference_tpu.models.gemma3.modeling_gemma3:Gemma3ForCausalLM",
    "gemma3_text": "neuronx_distributed_inference_tpu.models.gemma3.modeling_gemma3:Gemma3ForCausalLM",
    "mixtral": "neuronx_distributed_inference_tpu.models.mixtral.modeling_mixtral:MixtralForCausalLM",
    "qwen3_moe": "neuronx_distributed_inference_tpu.models.qwen3_moe.modeling_qwen3_moe:Qwen3MoeForCausalLM",
    "gpt_oss": "neuronx_distributed_inference_tpu.models.gpt_oss.modeling_gpt_oss:GptOssForCausalLM",
    "dbrx": "neuronx_distributed_inference_tpu.models.dbrx.modeling_dbrx:DbrxForCausalLM",
    "deepseek_v3": "neuronx_distributed_inference_tpu.models.deepseek.modeling_deepseek:DeepseekForCausalLM",
    # outer multimodal config (text_config + vision_config) -> vision+text app;
    # bare text config -> text-only app
    "llama4": "neuronx_distributed_inference_tpu.models.llama4.modeling_llama4_vision:Llama4ForConditionalGeneration",
    "llama4_text": "neuronx_distributed_inference_tpu.models.llama4.modeling_llama4:Llama4ForCausalLM",
    "mistral": "neuronx_distributed_inference_tpu.models.mistral.modeling_mistral:MistralForCausalLM",
    "llava": "neuronx_distributed_inference_tpu.models.pixtral.modeling_pixtral:PixtralForConditionalGeneration",
    "pixtral": "neuronx_distributed_inference_tpu.models.pixtral.modeling_pixtral:PixtralForConditionalGeneration",
    "mllama": "neuronx_distributed_inference_tpu.models.mllama.modeling_mllama:MllamaForConditionalGeneration",
    "qwen2_5_vl": "neuronx_distributed_inference_tpu.models.qwen2_5_vl.modeling_qwen2_5_vl:Qwen2_5_VLForConditionalGeneration",
    "qwen3_vl": "neuronx_distributed_inference_tpu.models.qwen3_vl.modeling_qwen3_vl:Qwen3VLForConditionalGeneration",
    # NOTE: whisper (models/whisper) is an encoder-decoder application with its own
    # generate(input_features, ...) interface; it deliberately does NOT register here
    # because this registry feeds the causal-LM CLI/adapters.
}


def get_model_cls(model_type: str) -> Type:
    if model_type not in _REGISTRY:
        raise KeyError(f"unsupported model_type {model_type!r}; "
                       f"have {sorted(_REGISTRY)}")
    mod_path, _, cls_name = _REGISTRY[model_type].partition(":")
    import importlib

    return getattr(importlib.import_module(mod_path), cls_name)


def register_model(model_type: str, path: str) -> None:
    _REGISTRY[model_type] = path
