from .modeling_gpt_oss import GptOssForCausalLM, GptOssInferenceConfig  # noqa: F401
