"""GPT-OSS model family (OpenAI open-weight MoE).

≈ reference `models/gpt_oss/modeling_gpt_oss.py` (1217 LoC) + its MXFP4 layout
transform (767 LoC). Architecture deltas vs Llama, expressed through ModelArchArgs so
the shared functional core (`models/base.py`) runs them in one `lax.scan`:

- learned per-head **attention sinks**: an extra logit per head joins the softmax
  denominator only (`ops/attention.attend` sinks path);
- **alternating sliding/full attention layers** from HF ``layer_types`` (same RoPE for
  both kinds — ``layer_pattern`` without a local theta);
- biases on q/k/v/o projections, the router, and the expert MLPs;
- MoE with **top-k-then-softmax routing** and the clamped-swiglu expert activation
  (gate/up clipped at ±limit, act = gate·σ(1.702·gate), out = (up+1)·act);
- YaRN RoPE with the attention magnitude factor applied to both layer kinds.

Checkpoint ingest accepts both bf16 (``gate_up_proj``) and MXFP4 checkpoints
(``gate_up_proj_blocks``/``_scales``, dequantized on host via
`ops/quantization.dequant_mxfp4`); HF stores gate/up interleaved along the last dim.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from ...modules import gqa
from ...ops.moe import MoEArgs
from ...ops import rope as rope_ops
from ...ops.quantization import dequant_mxfp4
from ..base import ModelArchArgs
from ..llama.modeling_llama import LlamaForCausalLM, LlamaInferenceConfig


class GptOssInferenceConfig(LlamaInferenceConfig):
    REQUIRED_ATTRIBUTES = LlamaInferenceConfig.REQUIRED_ATTRIBUTES + (
        "num_local_experts", "num_experts_per_tok")

    def add_derived_config(self) -> None:
        super().add_derived_config()
        for attr, default in (
                ("sliding_window", 128),
                ("layer_types", None),
                ("attention_bias", True),
                ("swiglu_limit", 7.0),
        ):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)

    def layer_pattern(self):
        if self.layer_types is not None:
            return tuple("sliding" if t == "sliding_attention" else "full"
                         for t in self.layer_types)
        # HF default: even layers sliding, odd layers full
        return tuple("sliding" if i % 2 == 0 else "full"
                     for i in range(self.num_hidden_layers))


class GptOssForCausalLM(LlamaForCausalLM):
    """≈ NeuronGptOssForCausalLM."""

    @classmethod
    def get_config_cls(cls):
        return GptOssInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config: GptOssInferenceConfig) -> ModelArchArgs:
        tp = config.tpu_config.tp_degree
        attention_scaling = rope_ops.attention_scaling_from_hf_config(
            config.rope_scaling)
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=gqa.effective_kv_heads(tp, config.num_key_value_heads),
            head_dim=config.head_dim,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.rms_norm_eps,
            attention_bias=config.attention_bias,
            o_bias=config.attention_bias,
            attn_sinks=True,
            sliding_window=config.sliding_window,
            layer_pattern=config.layer_pattern(),
            rope_attention_scaling=attention_scaling,
            local_rope_attention_scaling=attention_scaling,
            tie_word_embeddings=config.tie_word_embeddings,
            moe=MoEArgs(
                num_experts=config.num_local_experts,
                experts_per_tok=config.num_experts_per_tok,
                router_mode="topk_softmax",
                router_bias=True,
                expert_bias=True,
                swiglu_limit=config.swiglu_limit,
            ),
        )

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config: GptOssInferenceConfig) -> Dict:
        args = cls.arch_args_from_config(config)
        L = config.num_hidden_layers
        n_kv = config.num_key_value_heads
        d = config.head_dim
        factor = args.num_kv_heads // n_kv

        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def linear_t(name):
            return np.ascontiguousarray(get(name).T)

        def expert_weight(prefix):
            """(E, in, out) expert tensor from bf16 or MXFP4-packed checkpoint keys.

            MXFP4 stores (E, out, in/32, 16) blocks — dequant yields (E, out, in),
            transposed here to the (E, in, out) matmul layout."""
            if prefix in state_dict:
                return get(prefix).astype(np.float32)
            blocks, scales = get(prefix + "_blocks"), get(prefix + "_scales")
            deq = dequant_mxfp4(blocks, scales)        # (E, out, in)
            return np.ascontiguousarray(deq.transpose(0, 2, 1))

        layers = {k: [] for k in ("ln1", "wq", "wk", "wv", "wo", "ln2",
                                  "bq", "bk", "bv", "bo", "sinks",
                                  "router", "router_b",
                                  "wg", "wu", "wd", "bg", "bu", "bd")}
        for i in range(L):
            p = f"model.layers.{i}."
            layers["ln1"].append(get(p + "input_layernorm.weight"))
            layers["wq"].append(linear_t(p + "self_attn.q_proj.weight"))
            layers["wk"].append(gqa.replicate_kv_weight(
                linear_t(p + "self_attn.k_proj.weight"), n_kv, d, factor))
            layers["wv"].append(gqa.replicate_kv_weight(
                linear_t(p + "self_attn.v_proj.weight"), n_kv, d, factor))
            layers["wo"].append(linear_t(p + "self_attn.o_proj.weight"))
            layers["bq"].append(get(p + "self_attn.q_proj.bias"))
            layers["bk"].append(gqa.replicate_kv_bias(
                get(p + "self_attn.k_proj.bias"), n_kv, d, factor))
            layers["bv"].append(gqa.replicate_kv_bias(
                get(p + "self_attn.v_proj.bias"), n_kv, d, factor))
            layers["bo"].append(get(p + "self_attn.o_proj.bias"))
            layers["sinks"].append(get(p + "self_attn.sinks"))
            layers["ln2"].append(get(p + "post_attention_layernorm.weight"))
            m = p + "mlp."
            layers["router"].append(linear_t(m + "router.weight"))
            layers["router_b"].append(get(m + "router.bias"))
            gate_up = expert_weight(m + "experts.gate_up_proj")        # (E, H, 2I)
            layers["wg"].append(np.ascontiguousarray(gate_up[..., 0::2]))
            layers["wu"].append(np.ascontiguousarray(gate_up[..., 1::2]))
            gub = get(m + "experts.gate_up_proj_bias")                 # (E, 2I)
            layers["bg"].append(np.ascontiguousarray(gub[..., 0::2]))
            layers["bu"].append(np.ascontiguousarray(gub[..., 1::2]))
            layers["wd"].append(expert_weight(m + "experts.down_proj"))
            layers["bd"].append(get(m + "experts.down_proj_bias"))

        params = {
            "embed": get("model.embed_tokens.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("model.norm.weight"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
        if not args.tie_word_embeddings:
            params["lm_head"] = np.ascontiguousarray(get("lm_head.weight").T)
        return params
