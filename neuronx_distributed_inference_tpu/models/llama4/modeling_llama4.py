"""Llama-4 text model family (Scout / Maverick).

≈ reference `models/llama4/modeling_llama4_text.py` (770 LoC: chunked attention,
interleaved NoPE layers, input-scaled top-1 MoE + shared expert). Llama4 specifics:

- **Interleaved RoPE/NoPE layers** (`no_rope_layers`): rope layers use *chunked*
  attention (block-diagonal causal within `attention_chunk_size`, ≈ reference chunked
  masks `models/model_base.py:229-243`); NoPE layers attend globally with no rotary and
  optional temperature tuning (q scaled by log1p(floor((pos+1)/floor_scale))·attn_scale
  + 1).
- **QK L2 norm** (weightless RMS) on rope layers when `use_qk_norm`.
- **Interleaved rotary**: checkpoints store rope dims as complex pairs; q/k are
  deinterleaved host-of-graph then rotated with the standard rotate-half (attention
  scores are invariant to the shared permutation — same trick as DeepSeek).
- **MoE**: router = top-k of logits then sigmoid; the expert *input* is scaled by the
  gate (ops/moe.py `scale_expert_input`); an ungated shared expert always runs; every
  `interleave_moe_layer_step`-th layer is MoE, others dense with
  `intermediate_size_mlp`.
- Layers scan in contiguous dense/MoE runs (per-run `lax.scan` over stacked params,
  with per-layer use-rope booleans scanned alongside — same pattern as gemma3's
  layer_pattern in models/base).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...config import InferenceConfig
from ...modules import block_kvcache, gqa, kvcache
from ...ops import rope as rope_ops
from ...ops.moe import MoEArgs, moe_block
from ...ops.quantization import qapply
from ...parallel.sharding import constrain
from ..base import (ModelArchArgs, Params, _ACTIVATIONS, _embed, _lm_head, _mlp,
                    _norm, _project_qkv, causal_mask)
from ...runtime.application import TpuModelForCausalLM


@dataclass(frozen=True)
class Llama4ArchArgs(ModelArchArgs):
    """Llama4 extension: per-layer rope/moe interleaving + chunked attention."""

    use_rope_layers: Tuple[bool, ...] = ()    # True = rope + chunked attention
    moe_layer_flags: Tuple[bool, ...] = ()    # True = MoE FFN on that layer
    attention_chunk_size: Optional[int] = None
    attn_temperature_tuning: bool = False
    floor_scale: float = 8192.0
    attn_scale: float = 0.1
    use_qk_norm: bool = False                 # L2 (weightless) qk norm on rope layers
    dense_intermediate_size: int = 0          # intermediate_size_mlp


_deinterleave = rope_ops.deinterleave


def _l2_norm(x: jnp.ndarray, eps: float = 1e-6) -> jnp.ndarray:
    x32 = x.astype(jnp.float32)
    normed = x32 * jax.lax.rsqrt(jnp.mean(jnp.square(x32), axis=-1,
                                          keepdims=True) + eps)
    return normed.astype(x.dtype)


def _llama4_layer(lp: Params, args: Llama4ArchArgs, h, rope_ctx, k_cache, v_cache,
                  positions, decode_bucket, mesh, rules, is_moe: bool,
                  use_rope: jnp.ndarray, paged=None, cache_batch_start=0):
    """One decoder layer; ``use_rope`` is a scanned boolean selecting rope+chunked vs
    nope+global behaviour (cos/sin/masks for both kinds precomputed in rope_ctx)."""
    cos, sin, mask_chunked, mask_global, temp_scales = rope_ctx
    resid = h
    hn = _norm(h, lp["ln1"], args)
    q, k, v = _project_qkv(lp, args, hn)
    # interleaved rotary: deinterleave q/k then standard rotate-half (see docstring);
    # nope layers take identity cos/sin
    cos_i = jnp.where(use_rope, cos, jnp.ones_like(cos))
    sin_i = jnp.where(use_rope, sin, jnp.zeros_like(sin))
    q_r, k_r = rope_ops.apply_rotary(_deinterleave(q), _deinterleave(k), cos_i, sin_i)
    if args.use_qk_norm:
        q_r = jnp.where(use_rope, _l2_norm(q_r), q_r)
        k_r = jnp.where(use_rope, _l2_norm(k_r), k_r)
    if args.attn_temperature_tuning:
        # NoPE-layer temperature tuning (HF Llama4TextAttention.forward)
        q_r = jnp.where(use_rope, q_r, q_r * temp_scales)
    q, k = q_r, k_r

    if paged is not None:
        block_table, slot_mapping = paged
        k_cache = block_kvcache.write_slots(k_cache, k, slot_mapping)
        v_cache = block_kvcache.write_slots(v_cache, v, slot_mapping)
        if positions is None:
            k_att, v_att = k, v
        else:
            k_att = block_kvcache.read_seq(k_cache, block_table)
            v_att = block_kvcache.read_seq(v_cache, block_table)
    elif positions is None:
        k_cache = kvcache.write_prefill(k_cache, k, batch_start=cache_batch_start)
        v_cache = kvcache.write_prefill(v_cache, v, batch_start=cache_batch_start)
        k_att, v_att = k, v
    else:
        k_cache = kvcache.write_decode(k_cache, k, positions)
        v_cache = kvcache.write_decode(v_cache, v, positions)
        k_att = kvcache.read_bucket(k_cache, decode_bucket)
        v_att = kvcache.read_bucket(v_cache, decode_bucket)

    mask = jnp.where(use_rope, mask_chunked, mask_global)
    from ..base import attend

    attn = attend(q, k_att.astype(q.dtype), v_att.astype(q.dtype), mask=mask,
                  scale=args.attention_scale)
    attn = attn.transpose(0, 2, 1, 3).reshape(h.shape[0], h.shape[1], args.q_size)
    attn_out = qapply(attn, lp["wo"])
    attn_out = constrain(attn_out, ("batch", None, None), rules, mesh=mesh)
    h = resid + attn_out

    resid = h
    hn = _norm(h, lp["ln2"], args)
    if is_moe:
        ffn = moe_block(lp, args, hn, mesh, rules, _ACTIVATIONS[args.activation])
    else:
        ffn = _mlp(lp, args, hn, mesh, rules)
    h = resid + constrain(ffn, ("batch", None, None), rules, mesh=mesh)
    return h, k_cache, v_cache


def _segment_runs(flags: Tuple[bool, ...]) -> List[Tuple[bool, int, int, int]]:
    """Contiguous runs of equal flag: [(flag, global_start, length, kind_local_start)]."""
    runs = []
    counts = {True: 0, False: 0}
    i = 0
    while i < len(flags):
        j = i
        while j < len(flags) and flags[j] == flags[i]:
            j += 1
        runs.append((flags[i], i, j - i, counts[flags[i]]))
        counts[flags[i]] += j - i
        i = j
    return runs


def _run_layers(params: Params, args: Llama4ArchArgs, h, rope_ctx, cache,
                positions, decode_bucket, mesh, rules, paged=None,
                cache_batch_start=0):
    """Scan contiguous dense/MoE runs.

    All-MoE configs (Scout) get one scan; alternating configs (Maverick) degenerate to
    length-1 runs, i.e. a fully unrolled trace — matching the reference, which traces
    every model fully unrolled (`models/model_base.py:1376-1432`), so compile time is
    bounded by its baseline; a padded-uniform single-scan layout can come later if
    Maverick compile time warrants it."""
    use_rope = jnp.asarray(args.use_rope_layers)
    k_all, v_all = cache["k"], cache["v"]
    new_k = [None] * len(args.moe_layer_flags)
    new_v = [None] * len(args.moe_layer_flags)

    for is_moe, g0, n, l0 in _segment_runs(args.moe_layer_flags):
        stack = jax.tree.map(lambda x: x[l0:l0 + n],
                             params["moe" if is_moe else "dense"])
        xs = (stack, k_all[g0:g0 + n], v_all[g0:g0 + n], use_rope[g0:g0 + n])

        def body(carry_h, layer_xs, _is_moe=is_moe):
            lp, kc, vc, ur = layer_xs
            nh, kc, vc = _llama4_layer(lp, args, carry_h, rope_ctx, kc, vc,
                                       positions, decode_bucket, mesh, rules,
                                       is_moe=_is_moe, use_rope=ur, paged=paged,
                                       cache_batch_start=cache_batch_start)
            return nh, (kc, vc)

        h, (ks, vs) = jax.lax.scan(body, h, xs)
        for idx in range(n):
            new_k[g0 + idx] = ks[idx:idx + 1]
            new_v[g0 + idx] = vs[idx:idx + 1]
    return h, {"k": jnp.concatenate(new_k, axis=0),
               "v": jnp.concatenate(new_v, axis=0)}


def _chunk_mask(q_pos: jnp.ndarray, kv_pos: jnp.ndarray, base: jnp.ndarray,
                chunk: Optional[int]) -> jnp.ndarray:
    """Restrict a causal mask to block-diagonal chunks (≈ reference block-diagonal
    chunked-prefill masks, `models/model_base.py:229-243`)."""
    if chunk is None:
        return base
    return jnp.logical_and(base, q_pos // chunk == kv_pos // chunk)


def _temp_scales(args: Llama4ArchArgs, pos: jnp.ndarray) -> jnp.ndarray:
    """(..., S) positions -> (..., 1, S, 1) q scale factors for NoPE layers."""
    s = jnp.log1p(jnp.floor((pos.astype(jnp.float32) + 1.0) / args.floor_scale))
    return (s * args.attn_scale + 1.0)[:, None, :, None]


def prefill_forward(params: Params, args: Llama4ArchArgs, input_ids, position_ids,
                    last_token_idx, cache, mesh=None, rules=None, use_flash=False,
                    slot_mapping=None, cache_batch_start=0, adapter_ids=None,
                    use_ring=False, return_hidden=False, merge_embeds=None):
    h = _embed(params, args, input_ids, mesh, rules)
    if merge_embeds is not None:
        # image features override token embeddings at image-token positions
        mm_mask, mm_override = merge_embeds
        h = jnp.where(mm_mask, mm_override.astype(h.dtype), h)
    cos, sin = rope_ops.compute_cos_sin(params["rope_inv_freq"], position_ids,
                                        args.rope_attention_scaling)
    s = input_ids.shape[1]
    base = (position_ids[:, None, :, None] >= position_ids[:, None, None, :])
    base = jnp.logical_and(base, causal_mask(s, s)[None, None])
    q_pos = position_ids[:, None, :, None]
    kv_pos = position_ids[:, None, None, :]
    rope_ctx = (cos, sin, _chunk_mask(q_pos, kv_pos, base, args.attention_chunk_size),
                base, _temp_scales(args, position_ids))
    paged = None
    if slot_mapping is not None:
        paged = (jnp.zeros((input_ids.shape[0], 1), dtype=jnp.int32), slot_mapping)
    h, cache = _run_layers(params, args, h, rope_ctx, cache, positions=None,
                           decode_bucket=None, mesh=mesh, rules=rules, paged=paged,
                           cache_batch_start=cache_batch_start)
    h = _norm(h, params["final_norm"], args)
    h_last = jnp.take_along_axis(h, last_token_idx[:, None, None], axis=1)[:, 0]
    logits = _lm_head(params, args, h_last, mesh, rules)
    if return_hidden:
        return logits, cache, h
    return logits, cache


def decode_forward(params: Params, args: Llama4ArchArgs, input_ids, position_ids,
                   cache, decode_bucket, mesh=None, rules=None, block_table=None,
                   slot_mapping=None, adapter_ids=None, tree=None,
                   return_hidden=False):
    paged = None
    if block_table is not None:
        paged = (block_table, slot_mapping)
        block_size = cache["k"].shape[3]
        decode_bucket = block_table.shape[1] * block_size
    b, t = input_ids.shape
    h = _embed(params, args, input_ids, mesh, rules)
    pos_grid = position_ids[:, None] + jnp.arange(t)[None, :]
    cos, sin = rope_ops.compute_cos_sin(params["rope_inv_freq"], pos_grid,
                                        args.rope_attention_scaling)
    kv_pos = jnp.arange(decode_bucket)[None, None, None, :]
    q_pos = pos_grid[:, None, :, None]
    base = kv_pos <= q_pos
    rope_ctx = (cos, sin, _chunk_mask(q_pos, kv_pos, base, args.attention_chunk_size),
                base, _temp_scales(args, pos_grid))
    h, cache = _run_layers(params, args, h, rope_ctx, cache, positions=position_ids,
                           decode_bucket=decode_bucket, mesh=mesh, rules=rules,
                           paged=paged)
    h = _norm(h, params["final_norm"], args)
    logits = _lm_head(params, args, h, mesh, rules)
    if return_hidden:
        return logits, cache, h
    return logits, cache


# --- config / application -------------------------------------------------------------


class Llama4InferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = (
        "hidden_size", "num_attention_heads", "num_hidden_layers",
        "num_key_value_heads", "vocab_size", "intermediate_size",
    )

    def add_derived_config(self) -> None:
        # accept either a full Llama4Config (text_config nested) or a bare text config
        if hasattr(self, "text_config"):
            tc = self.text_config
            if not isinstance(tc, dict):
                tc = tc.to_dict()
            for k, v in tc.items():
                if not k.startswith("_"):
                    setattr(self, k, v)
        n_layers = self.num_hidden_layers
        for attr, default in (
                ("rms_norm_eps", 1e-5), ("rope_theta", 500000.0),
                ("rope_scaling", None), ("tie_word_embeddings", False),
                ("attention_bias", False), ("hidden_act", "silu"),
                ("head_dim", self.hidden_size // self.num_attention_heads),
                ("attention_chunk_size", 8192),
                ("attn_temperature_tuning", True),
                ("floor_scale", 8192.0), ("attn_scale", 0.1),
                ("use_qk_norm", True),
                ("num_local_experts", None), ("num_experts_per_tok", 1),
                ("interleave_moe_layer_step", 1),
                ("intermediate_size_mlp", None), ("moe_layers", None),
                ("no_rope_layers", None)):
            if not hasattr(self, attr) or getattr(self, attr) is None:
                setattr(self, attr, default)
        if not self.no_rope_layers:
            # HF default (also substituted for falsy [] like HF): every 4th is NoPE
            self.no_rope_layers = [int((i + 1) % 4 != 0) for i in range(n_layers)]
        if self.moe_layers is None and self.num_local_experts:
            step = self.interleave_moe_layer_step
            self.moe_layers = list(range(step - 1, n_layers, step))
        if self.intermediate_size_mlp is None:
            self.intermediate_size_mlp = self.intermediate_size


class Llama4ForCausalLM(TpuModelForCausalLM):
    """≈ NeuronLlama4ForCausalLM (text path).

    Quantization (int8/fp8 weight-only, ≈ reference quant flows
    `models/model_wrapper.py:11-21`), continuous batching, and paged attention run on
    the interleaved dense/MoE layout; LoRA and fused speculation remain unsupported."""

    def __init__(self, model_path, config, mesh=None):
        self._require_base_layout(config.tpu_config, "Llama4",
                                  allow=("quantization_config",
                                         "is_continuous_batching",
                                         "paged_attention_enabled"))
        super().__init__(model_path, config, mesh=mesh)

    @classmethod
    def get_config_cls(cls):
        return Llama4InferenceConfig

    @classmethod
    def arch_args_from_config(cls, config) -> Llama4ArchArgs:
        tp = config.tpu_config.tp_degree
        n_layers = config.num_hidden_layers
        moe_layers = set(config.moe_layers or [])
        moe = None
        if config.num_local_experts:
            moe = MoEArgs(
                num_experts=config.num_local_experts,
                experts_per_tok=config.num_experts_per_tok,
                router_mode="topk_sigmoid",
                scale_expert_input=True,
                norm_topk_prob=False,
                shared_expert_intermediate_size=config.intermediate_size,
                shared_expert_gated=False,
            )
        return Llama4ArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=n_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=gqa.effective_kv_heads(tp, config.num_key_value_heads),
            head_dim=config.head_dim,
            intermediate_size=config.intermediate_size,
            dense_intermediate_size=config.intermediate_size_mlp,
            rms_norm_eps=config.rms_norm_eps,
            activation=config.hidden_act,
            attention_bias=config.attention_bias,
            rope_attention_scaling=rope_ops.attention_scaling_from_hf_config(
                config.rope_scaling),
            tie_word_embeddings=config.tie_word_embeddings,
            use_rope_layers=tuple(bool(x) for x in config.no_rope_layers),
            moe_layer_flags=tuple(i in moe_layers for i in range(n_layers)),
            attention_chunk_size=config.attention_chunk_size,
            attn_temperature_tuning=bool(config.attn_temperature_tuning),
            floor_scale=float(config.floor_scale),
            attn_scale=float(config.attn_scale),
            use_qk_norm=bool(config.use_qk_norm),
            moe=moe,
        )

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        return rope_ops.inv_freq_from_hf_config(
            config.head_dim, config.rope_theta, config.rope_scaling)

    def _use_flash_attention(self) -> bool:
        if self.tpu_config.attention_kernel_enabled is True:
            raise ValueError("the Pallas flash kernel does not support llama4's "
                             "per-layer chunked/NoPE attention yet")
        return False

    def _use_ring_attention(self) -> bool:
        if self.mesh.shape["cp"] > 1:
            raise ValueError("context parallelism is not supported for llama4 yet")
        return False

    def prefill_fn(self):
        return prefill_forward

    def decode_fn(self):
        return decode_forward

    # --- param layout -----------------------------------------------------------------
    def _attn_axes(self) -> Dict[str, Tuple]:
        return {
            "ln1": ("layers", None),
            "ln2": ("layers", None),
            "wq": ("layers", "embed", "heads"),
            "wk": ("layers", "embed", "kv_heads"),
            "wv": ("layers", "embed", "kv_heads"),
            "wo": ("layers", "heads", "embed"),
        }

    def logical_axes(self) -> Dict:
        a: Llama4ArchArgs = self.arch_args
        out: Dict[str, Any] = {
            "embed": ("vocab", "embed"),
            "final_norm": (None,),
            "rope_inv_freq": (None,),
        }
        if not a.tie_word_embeddings:
            out["lm_head"] = ("embed", "vocab")
        if not all(a.moe_layer_flags):
            dense = dict(self._attn_axes())
            dense.update({"wg": ("layers", "embed", "mlp"),
                          "wu": ("layers", "embed", "mlp"),
                          "wd": ("layers", "mlp", "embed")})
            out["dense"] = dense
        if any(a.moe_layer_flags):
            moe_axes = dict(self._attn_axes())
            moe_axes.update({
                "router": ("layers", "embed", None),
                "wg": ("layers", "experts", "embed", "expert_mlp"),
                "wu": ("layers", "experts", "embed", "expert_mlp"),
                "wd": ("layers", "experts", "expert_mlp", "embed"),
                "shared_wg": ("layers", "embed", "mlp"),
                "shared_wu": ("layers", "embed", "mlp"),
                "shared_wd": ("layers", "mlp", "embed"),
            })
            out["moe"] = moe_axes
        return out

    def init_random_params(self, key) -> Dict:
        a: Llama4ArchArgs = self.arch_args
        dtype = self.tpu_config.jax_dtype
        H, nh = a.hidden_size, a.num_heads
        ks = iter(jax.random.split(key, 40))

        def w(shape, scale=0.02):
            return (jax.random.normal(next(ks), shape, dtype=jnp.float32)
                    * scale).astype(dtype)

        def attn_stack(L):
            return {
                "ln1": jnp.ones((L, H), dtype=dtype),
                "ln2": jnp.ones((L, H), dtype=dtype),
                "wq": w((L, H, a.q_size)),
                "wk": w((L, H, a.kv_size)),
                "wv": w((L, H, a.kv_size)),
                "wo": w((L, a.q_size, H)),
            }

        params: Dict[str, Any] = {
            "embed": w((a.vocab_size, H)),
            "final_norm": jnp.ones((H,), dtype=dtype),
            "rope_inv_freq": jnp.asarray(self.inv_freq_from_config(self.config),
                                         dtype=jnp.float32),
        }
        if not a.tie_word_embeddings:
            params["lm_head"] = w((H, a.vocab_size))
        n_dense = sum(1 for f in a.moe_layer_flags if not f)
        n_moe = len(a.moe_layer_flags) - n_dense
        if n_dense:
            dense = attn_stack(n_dense)
            I = a.dense_intermediate_size
            dense.update({"wg": w((n_dense, H, I)), "wu": w((n_dense, H, I)),
                          "wd": w((n_dense, I, H))})
            params["dense"] = dense
        if n_moe:
            moe_p = attn_stack(n_moe)
            E, I = a.moe.num_experts, a.intermediate_size
            moe_p.update({
                "router": w((n_moe, H, E)),
                "wg": w((n_moe, E, H, I)),
                "wu": w((n_moe, E, H, I)),
                "wd": w((n_moe, E, I, H)),
                "shared_wg": w((n_moe, H, I)),
                "shared_wu": w((n_moe, H, I)),
                "shared_wd": w((n_moe, I, H)),
            })
            params["moe"] = moe_p
        return params

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config) -> Dict:
        args = cls.arch_args_from_config(config)
        L = config.num_hidden_layers
        n_kv, d = config.num_key_value_heads, config.head_dim
        factor = args.num_kv_heads // n_kv
        I = config.intermediate_size

        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return state_dict[name]

        def linear_t(name):
            return np.ascontiguousarray(get(name).T)

        def attn_params(i):
            p = f"model.layers.{i}."
            return {
                "ln1": get(p + "input_layernorm.weight"),
                "ln2": get(p + "post_attention_layernorm.weight"),
                "wq": linear_t(p + "self_attn.q_proj.weight"),
                "wk": gqa.replicate_kv_weight(
                    linear_t(p + "self_attn.k_proj.weight"), n_kv, d, factor),
                "wv": gqa.replicate_kv_weight(
                    linear_t(p + "self_attn.v_proj.weight"), n_kv, d, factor),
                "wo": linear_t(p + "self_attn.o_proj.weight"),
            }

        def stack(dicts):
            return {k: np.stack([x[k] for x in dicts]) for k in dicts[0]}

        dense_layers, moe_layers = [], []
        for i in range(L):
            entry = attn_params(i)
            f = f"model.layers.{i}.feed_forward."
            if args.moe_layer_flags[i]:
                gu = get(f + "experts.gate_up_proj")        # (E, H, 2I), (in, out)
                entry.update({
                    "router": linear_t(f + "router.weight"),
                    "wg": gu[..., :I],
                    "wu": gu[..., I:],
                    "wd": get(f + "experts.down_proj"),     # (E, I, H)
                    "shared_wg": linear_t(f + "shared_expert.gate_proj.weight"),
                    "shared_wu": linear_t(f + "shared_expert.up_proj.weight"),
                    "shared_wd": linear_t(f + "shared_expert.down_proj.weight"),
                })
                moe_layers.append(entry)
            else:
                entry.update({
                    "wg": linear_t(f + "gate_proj.weight"),
                    "wu": linear_t(f + "up_proj.weight"),
                    "wd": linear_t(f + "down_proj.weight"),
                })
                dense_layers.append(entry)

        params: Dict[str, Any] = {
            "embed": get("model.embed_tokens.weight"),
            "final_norm": get("model.norm.weight"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
        if not args.tie_word_embeddings:
            params["lm_head"] = linear_t("lm_head.weight")
        if dense_layers:
            params["dense"] = stack(dense_layers)
        if moe_layers:
            params["moe"] = stack(moe_layers)
        return params
