"""Llama4 vision tower + conditional-generation application.

≈ reference `models/llama4/modeling_llama4_vision.py` (~1468 LoC:
NeuronLlama4VisionModel — unfold-conv patch embedding, 2D rotary attention,
pixel-shuffle adapter) redesigned as one pure jitted function over the
image-to-text base (runtime/image_to_text.py):

- Patch embedding = reshape/transpose unfold + linear (torch Unfold's (c, kh, kw)
  row ordering preserved by the transpose), CLS token appended at the END.
- 2D rotary: per-patch (x, y) angle tables precomputed host-side (cos/sin over
  head_dim/2 pairs), applied as an interleaved-pair rotation — the real form of the
  reference/HF complex multiply.
- Encoder layers: biased q/k/v/o + exact-gelu biased MLP, pre-LN.
- Adapter: pixel-shuffle (ratio r packs 1/r^2 patches into channels) + 2-layer
  gelu MLP, then the multimodal projector to the text hidden size.

Text side: Llama4ForCausalLM (interleaved NoPE/chunked-attention MoE stack,
modeling_llama4.py); image features merge at image-token positions via the shared
embed-override prefill.
"""

from __future__ import annotations

import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ...ops.norms import layer_norm
from ...runtime.image_to_text import (ImageToTextInferenceConfig,
                                      TpuModelForImageToText)
from .modeling_llama4 import Llama4ForCausalLM, Llama4InferenceConfig


def vision_rope_tables(image_size: int, patch_size: int, hidden: int, heads: int,
                       theta: float) -> np.ndarray:
    """(P, d/2) angle table for the 2D rotary (HF Llama4VisionRotaryEmbedding):
    x/y coordinate frequencies interleaved, zeroed for the CLS token."""
    idx = image_size // patch_size
    img_idx = np.arange(idx * idx, dtype=np.int32).reshape(-1, 1)
    img_idx = np.concatenate([img_idx, img_idx[:1]], axis=0)
    img_idx[-1, -1] = -2                      # CLS marker
    fx = img_idx % idx
    fy = img_idx // idx
    freq_dim = hidden // heads // 2
    rope_freq = 1.0 / (theta ** (np.arange(0, freq_dim, 2)[: freq_dim // 2]
                                 .astype(np.float64) / freq_dim))
    freqs_x = np.repeat((fx + 1)[..., None] * rope_freq[None, None, :], 2, axis=-1)
    freqs_y = np.repeat((fy + 1)[..., None] * rope_freq[None, None, :], 2, axis=-1)
    freqs = np.concatenate([freqs_x, freqs_y], axis=-1)[..., ::2]
    freqs = np.where(img_idx.reshape(-1, 1, 1) < 0, 0.0, freqs)
    return freqs[:, 0, :].astype(np.float32)  # (P, d/2)


def _rope_2d(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray) -> jnp.ndarray:
    """Interleaved-pair rotation: x (N, P, heads, D), cos/sin (P, D/2)."""
    x0 = x[..., 0::2]
    x1 = x[..., 1::2]
    c = cos[None, :, None, :]
    s = sin[None, :, None, :]
    o0 = x0 * c - x1 * s
    o1 = x0 * s + x1 * c
    return jnp.stack([o0, o1], axis=-1).reshape(x.shape)


def _pixel_shuffle(x: jnp.ndarray, ratio: float) -> jnp.ndarray:
    """(N, P, C) -> (N, P*r^2, C/r^2) (HF pixel_shuffle, r = ratio < 1)."""
    n, p, c = x.shape
    side = int(np.sqrt(p))
    x = x.reshape(n, side, side, c)
    x = x.reshape(n, side, int(side * ratio), int(c / ratio))
    x = x.transpose(0, 2, 1, 3)
    x = x.reshape(n, int(side * ratio), int(side * ratio), int(c / ratio ** 2))
    x = x.transpose(0, 2, 1, 3)
    return x.reshape(n, -1, x.shape[-1])


def llama4_vision_encode(vp: Dict[str, Any], pixel_values: jnp.ndarray, *,
                         patch_size: int, heads: int, shuffle_ratio: float,
                         eps: float = 1e-5) -> jnp.ndarray:
    """(N, C, H, W) pixel tiles -> (N, T_img, H_text) projected image features."""
    n, c, hh, ww = pixel_values.shape
    gh, gw = hh // patch_size, ww // patch_size
    x = pixel_values.reshape(n, c, gh, patch_size, gw, patch_size)
    x = x.transpose(0, 2, 4, 1, 3, 5).reshape(n, gh * gw, c * patch_size * patch_size)
    x = x.astype(vp["patch_w"].dtype) @ vp["patch_w"]

    cls = jnp.broadcast_to(vp["class_embed"][None, None, :], (n, 1, x.shape[-1]))
    x = jnp.concatenate([x, cls], axis=1)
    x = x + vp["pos_embed"]
    x = layer_norm(x, vp["pre_w"], vp["pre_b"], eps=eps)

    d = x.shape[-1] // heads
    cos, sin = vp["rope_cos"], vp["rope_sin"]

    def body(hid, lp):
        hn = layer_norm(hid, lp["ln1_w"], lp["ln1_b"], eps=eps)
        p = hn.shape[1]
        q = (hn @ lp["wq"] + lp["bq"]).reshape(n, p, heads, d)
        k = (hn @ lp["wk"] + lp["bk"]).reshape(n, p, heads, d)
        v = (hn @ lp["wv"] + lp["bv"]).reshape(n, p, heads, d)
        q = _rope_2d(q, cos, sin).astype(hn.dtype)
        k = _rope_2d(k, cos, sin).astype(hn.dtype)
        q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
        s = jnp.einsum("nhqd,nhkd->nhqk", q, k,
                       preferred_element_type=jnp.float32) * (d ** -0.5)
        probs = jax.nn.softmax(s, axis=-1).astype(q.dtype)
        attn = jnp.einsum("nhqk,nhkd->nhqd", probs, v)
        attn = attn.transpose(0, 2, 1, 3).reshape(n, p, heads * d)
        hid = hid + (attn @ lp["wo"] + lp["bo"])
        hn = layer_norm(hid, lp["ln2_w"], lp["ln2_b"], eps=eps)
        hid = hid + (jax.nn.gelu(hn @ lp["fc1"] + lp["b1"], approximate=False)
                     @ lp["fc2"] + lp["b2"])
        return hid, None

    x, _ = jax.lax.scan(body, x, vp["layers"])
    x = layer_norm(x, vp["post_w"], vp["post_b"], eps=eps)
    x = x[:, :-1]                                  # drop CLS
    x = _pixel_shuffle(x, shuffle_ratio)
    x = jax.nn.gelu(x @ vp["adapter_fc1"], approximate=False)
    x = jax.nn.gelu(x @ vp["adapter_fc2"], approximate=False)
    return x @ vp["proj"]                          # -> text hidden


class Llama4VisionInferenceConfig(ImageToTextInferenceConfig, Llama4InferenceConfig):
    REQUIRED_ATTRIBUTES = ("vision_config", "image_token_index")

    def add_derived_config(self) -> None:
        ImageToTextInferenceConfig.add_derived_config(self)
        Llama4InferenceConfig.add_derived_config(self)
        if not hasattr(self, "image_token_index"):
            self.image_token_index = getattr(self, "image_token_id", 200092)


class Llama4ForConditionalGeneration(TpuModelForImageToText, Llama4ForCausalLM):
    """≈ reference NeuronLlama4ForConditionalGeneration (vision tower + text MoE)."""

    @classmethod
    def get_config_cls(cls):
        return Llama4VisionInferenceConfig

    @classmethod
    def convert_hf_state_dict(cls, state_dict, config):
        # multimodal checkpoints nest the text model under language_model.*
        text = {k[len("language_model."):]: v for k, v in state_dict.items()
                if k.startswith("language_model.")}
        return Llama4ForCausalLM.convert_hf_state_dict(text or state_dict, config)

    def vision_encode_fn(self):
        vc = self.config.vision_config
        return functools.partial(
            llama4_vision_encode,
            patch_size=vc["patch_size"],
            heads=vc["num_attention_heads"],
            shuffle_ratio=float(vc.get("pixel_shuffle_ratio", 0.5)),
            eps=1e-5)

    @classmethod
    def convert_hf_vision_state_dict(cls, state_dict, config) -> Dict:
        vc = config.vision_config

        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return np.asarray(state_dict[name])

        def lin_t(name):
            return np.ascontiguousarray(get(name).T)

        layers = {k: [] for k in ("ln1_w", "ln1_b", "wq", "bq", "wk", "bk", "wv",
                                  "bv", "wo", "bo", "ln2_w", "ln2_b", "fc1", "b1",
                                  "fc2", "b2")}
        for i in range(vc["num_hidden_layers"]):
            p = f"vision_model.model.layers.{i}."
            layers["wq"].append(lin_t(p + "self_attn.q_proj.weight"))
            layers["bq"].append(get(p + "self_attn.q_proj.bias"))
            layers["wk"].append(lin_t(p + "self_attn.k_proj.weight"))
            layers["bk"].append(get(p + "self_attn.k_proj.bias"))
            layers["wv"].append(lin_t(p + "self_attn.v_proj.weight"))
            layers["bv"].append(get(p + "self_attn.v_proj.bias"))
            layers["wo"].append(lin_t(p + "self_attn.o_proj.weight"))
            layers["bo"].append(get(p + "self_attn.o_proj.bias"))
            layers["ln1_w"].append(get(p + "input_layernorm.weight"))
            layers["ln1_b"].append(get(p + "input_layernorm.bias"))
            layers["ln2_w"].append(get(p + "post_attention_layernorm.weight"))
            layers["ln2_b"].append(get(p + "post_attention_layernorm.bias"))
            layers["fc1"].append(lin_t(p + "mlp.fc1.weight"))
            layers["b1"].append(get(p + "mlp.fc1.bias"))
            layers["fc2"].append(lin_t(p + "mlp.fc2.weight"))
            layers["b2"].append(get(p + "mlp.fc2.bias"))

        angles = vision_rope_tables(vc["image_size"], vc["patch_size"],
                                    vc["hidden_size"], vc["num_attention_heads"],
                                    float(vc.get("rope_theta", 10000)))
        return {
            "patch_w": lin_t("vision_model.patch_embedding.linear.weight"),
            "class_embed": get("vision_model.class_embedding"),
            "pos_embed": get("vision_model.positional_embedding_vlm"),
            "pre_w": get("vision_model.layernorm_pre.weight"),
            "pre_b": get("vision_model.layernorm_pre.bias"),
            "post_w": get("vision_model.layernorm_post.weight"),
            "post_b": get("vision_model.layernorm_post.bias"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "adapter_fc1": lin_t("vision_model.vision_adapter.mlp.fc1.weight"),
            "adapter_fc2": lin_t("vision_model.vision_adapter.mlp.fc2.weight"),
            "proj": lin_t("multi_modal_projector.linear_1.weight"),
            "rope_cos": np.cos(angles),
            "rope_sin": np.sin(angles),
        }
