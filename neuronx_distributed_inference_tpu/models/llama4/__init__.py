from .modeling_llama4 import (Llama4ArchArgs, Llama4ForCausalLM,
                              Llama4InferenceConfig)

__all__ = ["Llama4ArchArgs", "Llama4ForCausalLM", "Llama4InferenceConfig"]
