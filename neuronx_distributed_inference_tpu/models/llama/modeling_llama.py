"""Llama model family (Llama 2 / 3 / 3.1 / 3.2).

≈ reference `models/llama/modeling_llama.py` (`NeuronLlamaForCausalLM`,
`convert_hf_to_neuron_state_dict` :1454-1524). TPU design: the compute graph is the
shared functional core in `models/base.py`; this module contributes (a) the architecture
args derived from the HF config (including Llama-3.1 scaled RoPE), and (b) the HF →
stacked-pytree weight conversion (with GQA kv-head replication when tp demands it).
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from ...config import InferenceConfig
from ...modules import gqa
from ...ops import rope as rope_ops
from ..base import ModelArchArgs
from ...runtime.application import TpuModelForCausalLM


class LlamaInferenceConfig(InferenceConfig):
    REQUIRED_ATTRIBUTES = (
        "hidden_size", "num_attention_heads", "num_hidden_layers",
        "num_key_value_heads", "vocab_size", "intermediate_size",
    )

    def add_derived_config(self) -> None:
        if not hasattr(self, "head_dim") or self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_attention_heads
        for attr, default in (("rms_norm_eps", 1e-5), ("rope_theta", 10000.0),
                              ("rope_scaling", None), ("tie_word_embeddings", False),
                              ("attention_bias", False), ("hidden_act", "silu")):
            if not hasattr(self, attr):
                setattr(self, attr, default)


class LlamaForCausalLM(TpuModelForCausalLM):
    """≈ NeuronLlamaForCausalLM."""

    @classmethod
    def get_config_cls(cls):
        return LlamaInferenceConfig

    @classmethod
    def arch_args_from_config(cls, config: LlamaInferenceConfig) -> ModelArchArgs:
        tp = config.tpu_config.tp_degree
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=gqa.effective_q_heads(tp, config.num_attention_heads,
                                            config.num_key_value_heads),
            num_kv_heads=gqa.effective_kv_heads(tp, config.num_key_value_heads),
            head_dim=config.head_dim,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.rms_norm_eps,
            activation=config.hidden_act,
            attention_bias=config.attention_bias,
            rope_attention_scaling=rope_ops.attention_scaling_from_hf_config(
                config.rope_scaling),
            tie_word_embeddings=config.tie_word_embeddings,
        )

    @classmethod
    def inv_freq_from_config(cls, config: LlamaInferenceConfig) -> np.ndarray:
        return rope_ops.inv_freq_from_hf_config(
            config.head_dim, config.rope_theta, config.rope_scaling)

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray],
                              config: LlamaInferenceConfig) -> Dict:
        """HF checkpoint names -> stacked functional pytree (numpy, host-side).

        ≈ `convert_hf_to_neuron_state_dict` (`modeling_llama.py:1454-1524`); weights are
        transposed to (in, out) and kv projections replicated per the GQA strategy.
        """
        args = cls.arch_args_from_config(config)
        L = config.num_hidden_layers
        n_q = config.num_attention_heads
        n_kv = config.num_key_value_heads
        d = config.head_dim
        tp = config.tpu_config.tp_degree
        factor = args.num_kv_heads // n_kv

        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return state_dict[name]

        def linear_t(name):
            return np.ascontiguousarray(get(name).T)

        layers = {"ln1": [], "wq": [], "wk": [], "wv": [], "wo": [],
                  "ln2": [], "wg": [], "wu": [], "wd": []}
        if args.attention_bias:
            layers.update({"bq": [], "bk": [], "bv": []})
        if args.qk_norm:
            layers.update({"q_norm": [], "k_norm": []})
        for i in range(L):
            p = f"model.layers.{i}."
            layers["ln1"].append(get(p + "input_layernorm.weight"))
            layers["wq"].append(gqa.expand_q_weight(
                linear_t(p + "self_attn.q_proj.weight"), n_q, n_kv, d, tp))
            layers["wk"].append(gqa.replicate_kv_weight(
                linear_t(p + "self_attn.k_proj.weight"), n_kv, d, factor))
            layers["wv"].append(gqa.replicate_kv_weight(
                linear_t(p + "self_attn.v_proj.weight"), n_kv, d, factor))
            layers["wo"].append(gqa.expand_o_weight(
                get(p + "self_attn.o_proj.weight").T, n_q, n_kv, d, tp))
            layers["ln2"].append(get(p + "post_attention_layernorm.weight"))
            layers["wg"].append(linear_t(p + "mlp.gate_proj.weight"))
            layers["wu"].append(linear_t(p + "mlp.up_proj.weight"))
            layers["wd"].append(linear_t(p + "mlp.down_proj.weight"))
            if args.attention_bias:
                layers["bq"].append(gqa.expand_q_bias(
                    get(p + "self_attn.q_proj.bias"), n_q, n_kv, d, tp))
                layers["bk"].append(gqa.replicate_kv_bias(
                    get(p + "self_attn.k_proj.bias"), n_kv, d, factor))
                layers["bv"].append(gqa.replicate_kv_bias(
                    get(p + "self_attn.v_proj.bias"), n_kv, d, factor))
            if args.qk_norm:
                layers["q_norm"].append(get(p + "self_attn.q_norm.weight"))
                layers["k_norm"].append(get(p + "self_attn.k_norm.weight"))

        params = {
            "embed": get("model.embed_tokens.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "final_norm": get("model.norm.weight"),
            "rope_inv_freq": cls.inv_freq_from_config(config),
        }
        if not args.tie_word_embeddings:
            params["lm_head"] = np.ascontiguousarray(get("lm_head.weight").T)
        return params
