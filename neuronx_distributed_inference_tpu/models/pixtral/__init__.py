from .modeling_pixtral import (PixtralForConditionalGeneration,
                               PixtralInferenceConfig)

__all__ = ["PixtralForConditionalGeneration", "PixtralInferenceConfig"]
