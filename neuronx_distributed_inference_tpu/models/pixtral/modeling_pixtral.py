"""Pixtral (Llava-architecture) image-to-text family.

≈ reference `models/pixtral/` (423 + 614 LoC: PixtralVisionModel port + conditional
generation). Components:

- **Vision tower** (HF `PixtralVisionModel`): patchify-conv (done as a patch matmul —
  MXU-friendly, identical math), RMS ln_pre, N attention layers with 2D rotary
  (per-patch (h, w) frequency table), bias-free projections, gated-silu MLP, full
  (non-causal) attention. Images are batched along the leading dim: HF concatenates
  all images into one sequence under a block-diagonal mask, which is exactly
  independent per-image attention.
- **Projector** (HF `LlavaMultiModalProjector`): linear → act → linear into the text
  hidden size.
- **Text model**: Mistral via the shared functional core; image features replace the
  token embeddings at image-token positions (runtime/image_to_text.py).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ...ops.norms import rms_norm
from ...runtime.image_to_text import (ImageToTextInferenceConfig,
                                      TpuModelForImageToText)
from ..mistral.modeling_mistral import MistralForCausalLM

_VISION_ACTS = {
    "gelu": jax.nn.gelu,
    "silu": jax.nn.silu,
    "gelu_pytorch_tanh": lambda x: jax.nn.gelu(x, approximate=True),
}


def pixtral_rope_table(head_dim: int, rope_theta: float, max_side: int) -> np.ndarray:
    """(max_side^2, head_dim) per-position frequency table (HF PixtralRotaryEmbedding):
    even head dims carry the row (h) frequencies, odd dims the column (w)."""
    freqs = 1.0 / (rope_theta ** (np.arange(0, head_dim, 2, dtype=np.float64)
                                  / head_dim))
    h = np.arange(max_side, dtype=np.float64)
    w = np.arange(max_side, dtype=np.float64)
    freqs_h = np.outer(h, freqs[0::2])
    freqs_w = np.outer(w, freqs[1::2])
    table = np.concatenate([
        np.repeat(freqs_h[:, None, :], max_side, axis=1),
        np.repeat(freqs_w[None, :, :], max_side, axis=0),
    ], axis=-1).reshape(max_side * max_side, head_dim // 2)
    return np.concatenate([table, table], axis=-1).astype(np.float32)


def _rotate_half(x):
    half = x.shape[-1] // 2
    return jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)


def vision_encode(vp: Dict[str, Any], pixel_values: jnp.ndarray,
                  *, patch_size: int, num_heads: int, eps: float = 1e-5,
                  act: str = "gelu", projector_act: str = "gelu") -> jnp.ndarray:
    """(N, C, H, W) -> (N, patches, H_text) image features.

    Pure function closed over static geometry; jitted by the application."""
    n, c, hh, ww = pixel_values.shape
    p = patch_size
    gh, gw = hh // p, ww // p
    # patchify matmul == stride-p conv: (N, C, gh, p, gw, p) -> (N, gh*gw, C*p*p)
    x = pixel_values.reshape(n, c, gh, p, gw, p).transpose(0, 2, 4, 1, 3, 5)
    x = x.reshape(n, gh * gw, c * p * p)
    h = x @ vp["patch_w"]                                   # (N, P, hidden)
    h = rms_norm(h, vp["ln_pre"], eps)

    # 2D rope: position id of patch (r, c) = r * max_side + c
    max_side = int(np.sqrt(vp["rope_table"].shape[0]))
    pos = (jnp.arange(gh)[:, None] * max_side + jnp.arange(gw)[None, :]).reshape(-1)
    freqs = jnp.take(vp["rope_table"], pos, axis=0)         # (P, D)
    cos, sin = jnp.cos(freqs), jnp.sin(freqs)

    d = h.shape[-1] // num_heads
    act_fn = _VISION_ACTS[act]

    def layer(carry, lp):
        hid = carry
        hn = rms_norm(hid, lp["ln1"], eps)
        q = (hn @ lp["wq"]).reshape(n, -1, num_heads, d).transpose(0, 2, 1, 3)
        k = (hn @ lp["wk"]).reshape(n, -1, num_heads, d).transpose(0, 2, 1, 3)
        v = (hn @ lp["wv"]).reshape(n, -1, num_heads, d).transpose(0, 2, 1, 3)
        q = q * cos + _rotate_half(q) * sin
        k = k * cos + _rotate_half(k) * sin
        scores = jnp.einsum("nhqd,nhkd->nhqk", q, k,
                            preferred_element_type=jnp.float32) * (d ** -0.5)
        probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
        attn = jnp.einsum("nhqk,nhkd->nhqd", probs, v)
        attn = attn.transpose(0, 2, 1, 3).reshape(n, -1, num_heads * d)
        hid = hid + attn @ lp["wo"]
        hn = rms_norm(hid, lp["ln2"], eps)
        hid = hid + (act_fn(hn @ lp["wg"]) * (hn @ lp["wu"])) @ lp["wd"]
        return hid, None

    h, _ = jax.lax.scan(layer, h, vp["layers"])

    # multimodal projector into the text hidden size
    proj_act = _VISION_ACTS[projector_act]
    feats = proj_act(h @ vp["proj_w1"] + vp["proj_b1"])
    return feats @ vp["proj_w2"] + vp["proj_b2"]


from ..mistral.modeling_mistral import MistralInferenceConfig


class PixtralInferenceConfig(ImageToTextInferenceConfig, MistralInferenceConfig):
    REQUIRED_ATTRIBUTES = ("vision_config", "image_token_index")

    def add_derived_config(self) -> None:
        # flatten text_config, then fill the llama/mistral text defaults from the
        # existing config classes (no duplicated default tables)
        ImageToTextInferenceConfig.add_derived_config(self)
        MistralInferenceConfig.add_derived_config(self)
        for attr, default in (("projector_hidden_act", "gelu"),
                              ("multimodal_projector_bias", True)):
            if not hasattr(self, attr):
                setattr(self, attr, default)
        tower = self.vision_config.get("model_type", "pixtral")
        if tower not in ("pixtral",):
            raise ValueError(
                f"only Pixtral vision towers are supported for the llava "
                f"architecture yet (got vision tower {tower!r})")


def _normalize_llava_keys(state_dict: Dict[str, Any]) -> Dict[str, Any]:
    """Map HF's on-disk legacy Llava layout (``language_model.model.*``, bare
    ``vision_tower.*``) onto the in-memory layout (``model.language_model.*`` etc.);
    in-memory keys pass through unchanged."""
    out = {}
    for k, v in state_dict.items():
        if k.startswith("language_model.model."):
            k = "model.language_model." + k[len("language_model.model."):]
        elif k == "language_model.lm_head.weight":
            k = "lm_head.weight"
        elif k.startswith("vision_tower.") or k.startswith("multi_modal_projector."):
            k = "model." + k
        out[k] = v
    return out


class PixtralForConditionalGeneration(TpuModelForImageToText, MistralForCausalLM):
    """≈ reference pixtral conditional generation (HF Llava + PixtralVisionModel)."""

    @classmethod
    def get_config_cls(cls):
        return PixtralInferenceConfig

    def vision_encode_fn(self):
        vc = self.config.vision_config
        import functools

        return functools.partial(
            vision_encode,
            patch_size=vc["patch_size"],
            num_heads=vc["num_attention_heads"],
            act=vc.get("hidden_act", "gelu"),
            projector_act=self.config.projector_hidden_act,
        )

    @classmethod
    def convert_hf_state_dict(cls, state_dict: Dict[str, np.ndarray], config) -> Dict:
        # text side: strip the Llava prefix and reuse the llama/mistral converter
        state_dict = _normalize_llava_keys(state_dict)
        text_sd = {}
        for k, v in state_dict.items():
            if k.startswith("model.language_model."):
                text_sd["model." + k[len("model.language_model."):]] = v
            elif k == "lm_head.weight":
                text_sd[k] = v
        return super().convert_hf_state_dict(text_sd, config)

    @classmethod
    def convert_hf_vision_state_dict(cls, state_dict: Dict[str, np.ndarray],
                                     config) -> Dict:
        state_dict = _normalize_llava_keys(state_dict)
        vc = config.vision_config
        L = vc["num_hidden_layers"]
        hidden = vc["hidden_size"]

        def get(name):
            if name not in state_dict:
                raise KeyError(f"missing weight {name}")
            return state_dict[name]

        def linear_t(name):
            return np.ascontiguousarray(get(name).T)

        layers = {k: [] for k in ("ln1", "wq", "wk", "wv", "wo", "ln2",
                                  "wg", "wu", "wd")}
        for i in range(L):
            p = f"model.vision_tower.transformer.layers.{i}."
            layers["ln1"].append(get(p + "attention_norm.weight"))
            layers["wq"].append(linear_t(p + "attention.q_proj.weight"))
            layers["wk"].append(linear_t(p + "attention.k_proj.weight"))
            layers["wv"].append(linear_t(p + "attention.v_proj.weight"))
            layers["wo"].append(linear_t(p + "attention.o_proj.weight"))
            layers["ln2"].append(get(p + "ffn_norm.weight"))
            layers["wg"].append(linear_t(p + "feed_forward.gate_proj.weight"))
            layers["wu"].append(linear_t(p + "feed_forward.up_proj.weight"))
            layers["wd"].append(linear_t(p + "feed_forward.down_proj.weight"))

        conv = get("model.vision_tower.patch_conv.weight")   # (hidden, C, p, p)
        return {
            "patch_w": np.ascontiguousarray(
                conv.reshape(hidden, -1).T),                 # (C*p*p, hidden)
            "ln_pre": get("model.vision_tower.ln_pre.weight"),
            "layers": {k: np.stack(v) for k, v in layers.items()},
            "rope_table": pixtral_rope_table(
                hidden // vc["num_attention_heads"],
                vc.get("rope_theta", 10000.0),
                vc["image_size"] // vc["patch_size"]),
            "proj_w1": linear_t("model.multi_modal_projector.linear_1.weight"),
            "proj_b1": get("model.multi_modal_projector.linear_1.bias"),
            "proj_w2": linear_t("model.multi_modal_projector.linear_2.weight"),
            "proj_b2": get("model.multi_modal_projector.linear_2.bias"),
        }
