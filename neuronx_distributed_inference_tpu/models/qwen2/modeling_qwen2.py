"""Qwen2 / Qwen2.5 model family.

≈ reference `models/qwen2/modeling_qwen2.py` (283 LoC: NeuronQwen2ForCausalLM). The
architecture is Llama with QKV projection biases (and no output-projection bias), so the
implementation subclasses the Llama family and flips ``attention_bias``.
"""

from __future__ import annotations

from ...modules import gqa
from ..base import ModelArchArgs
from ..llama.modeling_llama import LlamaForCausalLM, LlamaInferenceConfig


class Qwen2InferenceConfig(LlamaInferenceConfig):
    def add_derived_config(self) -> None:
        # HF Qwen2Config has no attention_bias attribute: q/k/v biases are always
        # present, o bias never is. Set before the Llama default (False) applies.
        if not hasattr(self, "attention_bias"):
            self.attention_bias = True
        super().add_derived_config()
        if not hasattr(self, "qkv_bias"):
            self.qkv_bias = self.attention_bias


class Qwen2ForCausalLM(LlamaForCausalLM):
    """≈ NeuronQwen2ForCausalLM."""

    @classmethod
    def get_config_cls(cls):
        return Qwen2InferenceConfig

    @classmethod
    def arch_args_from_config(cls, config: Qwen2InferenceConfig) -> ModelArchArgs:
        tp = config.tpu_config.tp_degree
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=gqa.effective_kv_heads(tp, config.num_key_value_heads),
            head_dim=config.head_dim,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.rms_norm_eps,
            activation=config.hidden_act,
            attention_bias=bool(config.qkv_bias),
            tie_word_embeddings=config.tie_word_embeddings,
        )
