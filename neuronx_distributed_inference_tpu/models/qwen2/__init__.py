from .modeling_qwen2 import Qwen2ForCausalLM, Qwen2InferenceConfig  # noqa: F401
