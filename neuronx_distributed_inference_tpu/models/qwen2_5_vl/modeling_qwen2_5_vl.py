"""Qwen2.5-VL family: window-attention ViT + M-RoPE text model.

≈ reference `models/qwen2_vl` / `models/qwen3_vl` (M-RoPE, deepstack vision —
`models/model_base.py:1235-1247`). Components (match HF Qwen2.5-VL):

- **Vision tower**: patchified Conv3d embedding, per-patch 2D rotary (h/w halves of
  head_dim/2), blocks with RMS norms + biased qkv and gated-silu MLP; *window
  attention* on most blocks (tokens reordered into spatial windows, block-diagonal
  masks) with `fullatt_block_indexes` attending per-image; a spatial-merge MLP head
  compresses each 2x2 patch group into one LLM token. Window reorder/index math runs
  host-side (numpy); the jitted encoder consumes precomputed masks + rope tables.
- **M-RoPE text model**: Qwen2 architecture whose rotary positions are 3D
  (temporal/height/width sections of the head dim). The prompt's 3D positions come
  from the HF `get_rope_index` algorithm (ported host-side); prefill passes the
  resulting multimodal cos/sin via the base model's ``rope_override``; decode
  collapses to 1D rope at (kv position + per-row delta), carried in the cache as
  ``rope_delta`` (see models/base.decode_forward).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...modules import gqa
from ...ops import rope as rope_ops
from ...ops.norms import rms_norm
from ...runtime.image_to_text import (ImageToTextInferenceConfig,
                                      TpuModelForImageToText)
from ..qwen2.modeling_qwen2 import Qwen2ForCausalLM, Qwen2InferenceConfig


# --- host-side geometry (numpy ports of the HF helpers) -------------------------------


def vision_rot_pos_emb(grid_thw: np.ndarray, head_dim: int,
                       spatial_merge_size: int, theta: float = 10000.0) -> np.ndarray:
    """Per-patch (h, w) rotary table (seq, head_dim//2), patches in merge-group order
    (HF `rot_pos_emb`)."""
    dim_quarter = head_dim // 4
    inv_freq = 1.0 / (theta ** (np.arange(0, dim_quarter * 2, 2, dtype=np.float64)
                                / (dim_quarter * 2)))
    out = []
    m = spatial_merge_size
    for t, h, w in grid_thw:
        hpos = np.broadcast_to(np.arange(h)[:, None], (h, w))
        wpos = np.broadcast_to(np.arange(w)[None, :], (h, w))

        def merge_order(x):
            return (x.reshape(h // m, m, w // m, m).transpose(0, 2, 1, 3)
                    .reshape(-1))

        hp, wp = merge_order(hpos), merge_order(wpos)
        freqs_h = hp[:, None] * inv_freq[None, :]
        freqs_w = wp[:, None] * inv_freq[None, :]
        table = np.concatenate([freqs_h, freqs_w], axis=-1)   # (h*w, head_dim//2)
        out.append(np.tile(table, (int(t), 1)))
    return np.concatenate(out, axis=0).astype(np.float32)


def get_window_index(grid_thw: np.ndarray, window_size: int,
                     spatial_merge_size: int, patch_size: int
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """(window_index (n_merged,), cu_window_seqlens) — HF `get_window_index`."""
    window_index: List[np.ndarray] = []
    cu: List[int] = [0]
    offset = 0
    m = spatial_merge_size
    unit = m * m
    vit_win = window_size // m // patch_size
    for t, h, w in grid_thw:
        lh, lw = h // m, w // m
        index = np.arange(t * lh * lw).reshape(t, lh, lw)
        pad_h = vit_win - lh % vit_win
        pad_w = vit_win - lw % vit_win
        nwh, nww = (lh + pad_h) // vit_win, (lw + pad_w) // vit_win
        padded = np.pad(index, ((0, 0), (0, pad_h), (0, pad_w)),
                        constant_values=-100)
        padded = padded.reshape(t, nwh, vit_win, nww, vit_win)
        padded = padded.transpose(0, 1, 3, 2, 4).reshape(t, nwh * nww, vit_win,
                                                         vit_win)
        seqlens = (padded != -100).sum(axis=(2, 3)).reshape(-1)
        flat = padded.reshape(-1)
        keep = flat[flat != -100]
        window_index.append(keep + offset)
        cu.extend((np.cumsum(seqlens) * unit + cu[-1]).tolist())
        offset += int(t * lh * lw)
    cu_arr = np.array(sorted(set(cu)), dtype=np.int64)
    return np.concatenate(window_index), cu_arr


def segment_mask(cu_seqlens: np.ndarray, seq_len: int) -> np.ndarray:
    """cu_seqlens boundaries -> (seq, seq) bool mask (attend within one segment)."""
    seg = np.searchsorted(cu_seqlens[1:], np.arange(seq_len), side="right")
    return seg[:, None] == seg[None, :]


def get_rope_index_images(input_ids: np.ndarray, attention_mask: Optional[np.ndarray],
                          image_grid_thw: Optional[np.ndarray],
                          spatial_merge_size: int, image_token_id: int,
                          vision_start_token_id: int
                          ) -> Tuple[np.ndarray, np.ndarray]:
    """3D rope positions + per-row deltas (HF `get_rope_index`, images only).

    Returns (position_ids (3, B, S) int32, deltas (B,) int32) where delta =
    (max position + 1) - num_real_tokens."""
    if image_grid_thw is not None and (np.asarray(image_grid_thw)[:, 0] > 1).any():
        # video grids (t > 1) need Qwen2.5-VL's second_per_grid_ts * tokens_per_second
        # temporal scaling; plain arange positions would be silently wrong M-RoPE
        raise NotImplementedError(
            "video inputs (grid t > 1) are not supported: temporal M-RoPE scaling "
            "(second_per_grid_ts * tokens_per_second) is not implemented")
    b, s = input_ids.shape
    positions = np.zeros((3, b, s), dtype=np.int64)
    deltas = np.zeros((b,), dtype=np.int64)
    if image_grid_thw is None or (input_ids == image_token_id).sum() == 0:
        for i in range(b):
            mask_row = (attention_mask[i].astype(bool) if attention_mask is not None
                        else np.ones((s,), dtype=bool))
            idx = np.cumsum(mask_row) - 1
            positions[:, i] = np.where(mask_row, idx, 1)
            deltas[i] = 0
        return positions.astype(np.int32), deltas.astype(np.int32)

    m = spatial_merge_size
    image_index = 0
    for i in range(b):
        row = input_ids[i]
        mask_row = (attention_mask[i].astype(bool) if attention_mask is not None
                    else np.ones((s,), dtype=bool))
        tokens = row[mask_row].tolist()
        parts: List[np.ndarray] = []
        st = 0
        n_images = sum(1 for j in np.where(np.asarray(tokens) ==
                                           vision_start_token_id)[0]
                       if j + 1 < len(tokens) and tokens[j + 1] == image_token_id)
        for _ in range(n_images):
            ed = tokens.index(image_token_id, st)
            t, h, w = image_grid_thw[image_index]
            image_index += 1
            lh, lw = int(h) // m, int(w) // m
            text_len = ed - st
            st_idx = (parts[-1].max() + 1) if parts else 0
            if text_len:
                parts.append(np.broadcast_to(
                    np.arange(text_len) + st_idx, (3, text_len)).copy())
                st_idx = parts[-1].max() + 1
            t_idx = np.repeat(np.arange(int(t)), lh * lw)
            h_idx = np.tile(np.repeat(np.arange(lh), lw), int(t))
            w_idx = np.tile(np.arange(lw), lh * int(t))
            parts.append(np.stack([t_idx, h_idx, w_idx]) + st_idx)
            st = ed + int(t) * lh * lw
        if st < len(tokens):
            st_idx = (parts[-1].max() + 1) if parts else 0
            text_len = len(tokens) - st
            parts.append(np.broadcast_to(
                np.arange(text_len) + st_idx, (3, text_len)).copy())
        pos_row = np.concatenate(parts, axis=1)       # (3, n_real)
        positions[:, i, mask_row] = pos_row
        deltas[i] = int(pos_row.max()) + 1 - len(tokens)
    return positions.astype(np.int32), deltas.astype(np.int32)


# --- vision encoder (jitted) ----------------------------------------------------------


def vision_encode(vp: Dict[str, Any], patches: jnp.ndarray, cos: jnp.ndarray,
                  sin: jnp.ndarray, full_mask: jnp.ndarray, win_mask: jnp.ndarray,
                  *, num_heads: int, is_full: Tuple[bool, ...],
                  spatial_merge_unit: int, eps: float = 1e-6) -> jnp.ndarray:
    """(seq, in_dim) window-ordered patches -> (seq // merge_unit, out_hidden).

    cos/sin (seq, head_dim): 2D rotary tables; full/win masks (seq, seq)."""
    h = patches @ vp["patch_w"]                       # (seq, hidden)
    seq, hidden = h.shape
    d = hidden // num_heads
    is_full_arr = jnp.asarray(is_full)

    def block(hid, xs):
        lp, full = xs
        hn = rms_norm(hid, lp["ln1"], eps)
        qkv = hn @ lp["wqkv"] + lp["bqkv"]            # (seq, 3*hidden)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(seq, num_heads, d)
        k = k.reshape(seq, num_heads, d)
        v = v.reshape(seq, num_heads, d)
        q = (q * cos[:, None, :] + _rotate_half(q) * sin[:, None, :]).astype(q.dtype)
        k = (k * cos[:, None, :] + _rotate_half(k) * sin[:, None, :]).astype(k.dtype)
        mask = jnp.where(full, full_mask, win_mask)
        scores = jnp.einsum("qhd,khd->hqk", q, k,
                            preferred_element_type=jnp.float32) * (d ** -0.5)
        scores = jnp.where(mask[None], scores, jnp.finfo(jnp.float32).min)
        probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
        attn = jnp.einsum("hqk,khd->qhd", probs, v).reshape(seq, hidden)
        hid = hid + (attn @ lp["wo"] + lp["bo"])
        hn = rms_norm(hid, lp["ln2"], eps)
        gate = jax.nn.silu(hn @ lp["wg"] + lp["bg"])
        hid = hid + ((gate * (hn @ lp["wu"] + lp["bu"])) @ lp["wd"] + lp["bd"])
        return hid, None

    h, _ = jax.lax.scan(block, h, (vp["blocks"], is_full_arr))

    # spatial merge head: RMS norm then 2x2-group MLP into the text hidden size
    h = rms_norm(h, vp["merge_ln"], eps)
    h = h.reshape(seq // spatial_merge_unit, spatial_merge_unit * hidden)
    h = jax.nn.gelu(h @ vp["merge_w1"] + vp["merge_b1"], approximate=False)
    return h @ vp["merge_w2"] + vp["merge_b2"]


def _rotate_half(x):
    half = x.shape[-1] // 2
    return jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)


# --- config / application -------------------------------------------------------------


class Qwen2_5_VLInferenceConfig(ImageToTextInferenceConfig, Qwen2InferenceConfig):
    REQUIRED_ATTRIBUTES = ("vision_config", "image_token_id")

    def add_derived_config(self) -> None:
        ImageToTextInferenceConfig.add_derived_config(self)
        Qwen2InferenceConfig.add_derived_config(self)
        for attr, default in (("vision_start_token_id", 151652),):
            if not hasattr(self, attr):
                setattr(self, attr, default)
        rs = getattr(self, "rope_scaling", None)
        sec = (rs or {}).get("mrope_section")
        if not sec:
            # fallback must partition head_dim//2 EXACTLY; remainder -> temporal
            third = (self.head_dim // 2) // 3
            sec = [self.head_dim // 2 - 2 * third, third, third]
        if sum(sec) != self.head_dim // 2:
            raise ValueError(f"mrope_section {sec} must sum to head_dim//2 "
                             f"({self.head_dim // 2})")
        self.mrope_section = sec


class Qwen2_5_VLForConditionalGeneration(TpuModelForImageToText, Qwen2ForCausalLM):
    """≈ reference qwen2_vl/qwen3_vl conditional generation."""

    @classmethod
    def get_config_cls(cls):
        return Qwen2_5_VLInferenceConfig

    @classmethod
    def inv_freq_from_config(cls, config) -> np.ndarray:
        # mrope keeps the base rotary frequencies; sections only select which of the
        # 3 position streams drives each channel
        return rope_ops.default_inv_freq(config.head_dim,
                                         getattr(config, "rope_theta", 1e6))

    @property
    def image_token_index(self) -> int:
        return self.config.image_token_id

    @classmethod
    def convert_hf_state_dict(cls, state_dict, config):
        # text side lives under model.language_model.* (or language_model.model.* on
        # disk); remap to the plain qwen2 layout and reuse its converter
        text_sd = {}
        for k, v in state_dict.items():
            if k.startswith("model.language_model."):
                text_sd["model." + k[len("model.language_model."):]] = v
            elif k.startswith("language_model.model."):
                text_sd["model." + k[len("language_model.model."):]] = v
            elif k == "language_model.lm_head.weight":
                text_sd["lm_head.weight"] = v
            elif k.startswith(("model.visual.", "visual.")):
                continue
            elif k.startswith("model.") or k == "lm_head.weight":
                text_sd[k] = v        # on-disk layout keeps the plain qwen2 keys
        return super().convert_hf_state_dict(text_sd, config)

    @classmethod
    def convert_hf_vision_state_dict(cls, state_dict, config):
        vc = config.vision_config
        hidden = vc["hidden_size"]

        def norm_key(k):
            if k.startswith("model.visual."):
                return "visual." + k[len("model.visual."):]
            return k

        sd = {norm_key(k): v for k, v in state_dict.items()}

        def get(name):
            if name not in sd:
                raise KeyError(f"missing weight {name}")
            return sd[name]

        def linear_t(name):
            return np.ascontiguousarray(get(name).T)

        blocks = {k: [] for k in ("ln1", "wqkv", "bqkv", "wo", "bo", "ln2",
                                  "wg", "bg", "wu", "bu", "wd", "bd")}
        for i in range(vc["depth"]):
            p = f"visual.blocks.{i}."
            blocks["ln1"].append(get(p + "norm1.weight"))
            blocks["wqkv"].append(linear_t(p + "attn.qkv.weight"))
            blocks["bqkv"].append(get(p + "attn.qkv.bias"))
            blocks["wo"].append(linear_t(p + "attn.proj.weight"))
            blocks["bo"].append(get(p + "attn.proj.bias"))
            blocks["ln2"].append(get(p + "norm2.weight"))
            blocks["wg"].append(linear_t(p + "mlp.gate_proj.weight"))
            blocks["bg"].append(get(p + "mlp.gate_proj.bias"))
            blocks["wu"].append(linear_t(p + "mlp.up_proj.weight"))
            blocks["bu"].append(get(p + "mlp.up_proj.bias"))
            blocks["wd"].append(linear_t(p + "mlp.down_proj.weight"))
            blocks["bd"].append(get(p + "mlp.down_proj.bias"))

        conv = get("visual.patch_embed.proj.weight")   # (hidden, C, tps, p, p)
        return {
            "patch_w": np.ascontiguousarray(conv.reshape(hidden, -1).T),
            "blocks": {k: np.stack(v) for k, v in blocks.items()},
            "merge_ln": get("visual.merger.ln_q.weight"),
            "merge_w1": linear_t("visual.merger.mlp.0.weight"),
            "merge_b1": get("visual.merger.mlp.0.bias"),
            "merge_w2": linear_t("visual.merger.mlp.2.weight"),
            "merge_b2": get("visual.merger.mlp.2.bias"),
        }

    def vision_encode_fn(self):
        # unused: this family drives its own encoder jit (variable image grids need
        # host-side reordering); keep the hook satisfied with identity
        return lambda vp, px: px

    def __init__(self, model_path, config, mesh=None):
        super().__init__(model_path, config, mesh=mesh)
        vc = config.vision_config
        self._vision_geo = {
            "patch_size": vc["patch_size"],
            "spatial_merge_size": vc["spatial_merge_size"],
            "window_size": vc["window_size"],
            "num_heads": vc["num_heads"],
            "depth": vc["depth"],
            "fullatt": tuple(vc["fullatt_block_indexes"]),
            "head_dim": vc["hidden_size"] // vc["num_heads"],
        }
        m = vc["spatial_merge_size"]
        # single persistent jit: XLA's trace cache keys on input shapes, so each
        # image geometry compiles once and is reused across requests
        self._vision_jit = jax.jit(functools.partial(
            vision_encode, num_heads=vc["num_heads"],
            is_full=tuple(i in self._vision_geo["fullatt"]
                          for i in range(vc["depth"])),
            spatial_merge_unit=m * m))

    # --- vision -----------------------------------------------------------------------
    def encode_vision(self, pixel_values: np.ndarray,
                      image_grid_thw: np.ndarray) -> np.ndarray:
        """(seq, C*tps*p*p) flattened patches + grids -> (n_llm_tokens, H_text)."""
        g = self._vision_geo
        grid = np.asarray(image_grid_thw)
        seq = int(np.prod(grid, axis=1).sum())
        m = g["spatial_merge_size"]
        unit = m * m
        rpe = vision_rot_pos_emb(grid, g["head_dim"], m)
        window_index, cu_win = get_window_index(grid, g["window_size"], m,
                                                g["patch_size"])
        # reorder patches + rope tables into window order (host)
        order = (window_index[:, None] * unit + np.arange(unit)[None, :]).reshape(-1)
        px = np.asarray(pixel_values, dtype=np.float32)[order]
        rpe = rpe[order]
        emb = np.concatenate([rpe, rpe], axis=-1)
        cos, sin = np.cos(emb), np.sin(emb)
        # masks: "full" blocks attend per FRAME (HF repeat_interleave(h*w, t)),
        # window blocks per spatial window
        frame_lens = np.repeat(grid[:, 1] * grid[:, 2], grid[:, 0])
        cu_full = np.concatenate([[0], np.cumsum(frame_lens)]).astype(np.int64)
        full_mask = segment_mask(cu_full, seq)
        win_mask = segment_mask(cu_win, seq)
        feats = np.asarray(self._vision_jit(self.vision_params, px, cos, sin,
                                            full_mask, win_mask))
        reverse = np.argsort(window_index)
        return feats[reverse]

    # --- mm prefill with M-RoPE -------------------------------------------------------
    def _build_mm_prefill(self):
        args, mesh, rules = self.arch_args, self.mesh, self.sharding_rules
        odsc = self.sampling_config
        prefill_core = self.prefill_fn()
        sections = tuple(self.config.mrope_section)
        from ...ops import sampling as sampling_ops

        precision, use_ring, use_flash = self._mm_strategy()

        def _prefill_mm(params, input_ids, position_ids, last_token_idx, cache,
                        sampling_params, key, mm_mask, mm_override, positions3,
                        adapter_ids=None):
            with jax.default_matmul_precision(precision):
                cos, sin = rope_ops.mrope_cos_sin(
                    params["rope_inv_freq"], positions3, sections,
                    args.rope_attention_scaling)
                logits, cache = prefill_core(
                    params, args, input_ids, position_ids, last_token_idx, cache,
                    mesh=mesh, rules=rules, adapter_ids=adapter_ids,
                    use_flash=use_flash, use_ring=use_ring,
                    merge_embeds=(mm_mask, mm_override),
                    rope_override=(cos, sin))
                tokens = sampling_ops.sample(logits, sampling_params, key, odsc)
            return tokens, logits, cache

        return jax.jit(_prefill_mm, donate_argnums=(4,))

    def reset_cache(self) -> None:
        super().reset_cache()
        b = self.tpu_config.max_batch_size
        self.kv_cache["rope_delta"] = jnp.zeros((b,), dtype=jnp.int32)

    def warmup(self) -> None:
        # text graphs only: the vision/mm graphs compile per image-grid geometry, so
        # there is no single shape to pre-compile (first image request per geometry
        # pays the compile, like the reference's per-bucket lazy compilation)
        from ...runtime.application import TpuModelForCausalLM

        TpuModelForCausalLM.warmup(self)

    # --- generation -------------------------------------------------------------------
    def generate(self, input_ids, pixel_values=None, image_grid_thw=None, **kwargs):
        if pixel_values is None:
            return Qwen2ForCausalLM.generate(self, input_ids, **kwargs)
        feats = self.encode_vision(pixel_values, image_grid_thw)
        mm = {"features": feats, "grid_thw": np.asarray(image_grid_thw)}
        return Qwen2ForCausalLM.generate(self, input_ids, _mm_embeds=mm, **kwargs)

    def _run_prefill(self, padded, sampling_params, key, adapter_ids, mm=None):
        if mm is None:
            return super(TpuModelForImageToText, self)._run_prefill(
                padded, sampling_params, key, adapter_ids)
        mask, override = self._scatter_features(padded, mm["features"])
        ids = np.asarray(padded.input_ids)
        # 3D rope positions over the padded (compacted) prompt; pad region gets
        # sequential continuation (unused — masked out by position validity)
        valid = np.arange(ids.shape[1])[None, :] <= np.asarray(
            padded.last_token_idx)[:, None]
        positions3, deltas = get_rope_index_images(
            ids, valid.astype(np.int64), mm["grid_thw"],
            self.config.vision_config["spatial_merge_size"],
            self.image_token_index, self.config.vision_start_token_id)
        self.kv_cache["rope_delta"] = jnp.asarray(deltas, dtype=jnp.int32)
        return self._mm_prefill_step(
            self.params, padded.input_ids, padded.position_ids,
            padded.last_token_idx, self.kv_cache, sampling_params, key,
            mask, override, positions3, adapter_ids)
