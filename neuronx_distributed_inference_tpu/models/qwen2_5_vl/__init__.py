from .modeling_qwen2_5_vl import (Qwen2_5_VLForConditionalGeneration,
                                  Qwen2_5_VLInferenceConfig)

__all__ = ["Qwen2_5_VLForConditionalGeneration", "Qwen2_5_VLInferenceConfig"]
