from .modeling_qwen3 import Qwen3ForCausalLM, Qwen3InferenceConfig  # noqa: F401
