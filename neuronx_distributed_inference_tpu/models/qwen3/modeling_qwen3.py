"""Qwen3 model family.

≈ reference `models/qwen3/modeling_qwen3.py` (241 LoC: NeuronQwen3ForCausalLM). Llama
architecture plus per-head RMSNorm on q/k before RoPE (``qk_norm``) and an explicit
``head_dim`` decoupled from hidden_size/num_heads.
"""

from __future__ import annotations

from ...modules import gqa
from ..base import ModelArchArgs
from ..llama.modeling_llama import LlamaForCausalLM, LlamaInferenceConfig


class Qwen3InferenceConfig(LlamaInferenceConfig):
    def add_derived_config(self) -> None:
        super().add_derived_config()
        self.attention_bias = getattr(self, "attention_bias", False)


class Qwen3ForCausalLM(LlamaForCausalLM):
    """≈ NeuronQwen3ForCausalLM."""

    @classmethod
    def get_config_cls(cls):
        return Qwen3InferenceConfig

    @classmethod
    def arch_args_from_config(cls, config: Qwen3InferenceConfig) -> ModelArchArgs:
        tp = config.tpu_config.tp_degree
        return ModelArchArgs(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_hidden_layers,
            num_heads=config.num_attention_heads,
            num_kv_heads=gqa.effective_kv_heads(tp, config.num_key_value_heads),
            head_dim=config.head_dim,
            intermediate_size=config.intermediate_size,
            rms_norm_eps=config.rms_norm_eps,
            activation=config.hidden_act,
            attention_bias=config.attention_bias,
            qk_norm=True,
            tie_word_embeddings=config.tie_word_embeddings,
        )
