"""EAGLE draft model: a shallow decoder conditioned on the target's hidden states.

≈ reference `modules/eagle/` + the EAGLE fc / hidden-state plumbing in
`models/model_base.py` (`_eagle_context_encoding_forward` :2075-2134, draft hidden
processing :1569-1635): the draft has no embedding or lm_head of its own — it reuses the
target's — and its layer-0 input is ``fc(concat(embed(token), cond_hidden))`` where
``cond_hidden`` is the target's final hidden state at the *previous* position (during
autoregressive drafting the draft substitutes its own output hidden, the standard
EAGLE-1 approximation). The reference's `HiddenStateRollingBuffer`
(`modules/eagle/hidden_state.py`) keys hidden states by (seq, pos) across host steps;
here the fused step carries the (B, H) conditioning hidden as explicit jit state, so no
buffer indexing is needed.

The draft shares `ModelArchArgs` geometry with the target for hidden size / head_dim
(vocab via the target's lm_head); layer count and head counts may differ.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..modules import kvcache
from ..ops import rope as rope_ops
from ..ops.attention import causal_mask
from ..ops.norms import rms_norm
from . import base as model_base
from .base import ModelArchArgs, Params


def init_eagle_params(args: ModelArchArgs, key: jax.Array, dtype=jnp.bfloat16,
                      inv_freq: Optional[np.ndarray] = None) -> Params:
    """Random draft params: fc (2H -> H) + the stacked decoder layers + final norm.

    ``args`` describes the *draft* stack (usually 1 layer, target's hidden size).
    """
    k_fc, k_layers = jax.random.split(key)
    full = model_base.init_params(args, k_layers, dtype=dtype, inv_freq=inv_freq)
    h = args.hidden_size
    return {
        "fc": (jax.random.normal(k_fc, (2 * h, h), jnp.float32) * 0.02).astype(dtype),
        "layers": full["layers"],
        "final_norm": full["final_norm"],
        "rope_inv_freq": full["rope_inv_freq"],
    }


def convert_eagle_state_dict(state_dict: Dict[str, np.ndarray],
                             args: ModelArchArgs,
                             inv_freq: np.ndarray) -> Params:
    """EAGLE checkpoint (llama-style ``layers.{i}.*`` + ``fc.weight``) -> draft pytree."""
    from ..modules import gqa

    def linear_t(name):
        return np.ascontiguousarray(state_dict[name].T)

    L, d = args.num_layers, args.head_dim
    # EAGLE checkpoints store raw kv head count; replicate as the args demand
    layers = {"ln1": [], "wq": [], "wk": [], "wv": [], "wo": [],
              "ln2": [], "wg": [], "wu": [], "wd": []}
    for i in range(L):
        p = f"layers.{i}."
        if p + "input_layernorm.weight" in state_dict:
            layers["ln1"].append(state_dict[p + "input_layernorm.weight"])
        else:  # EAGLE-1 drops layer-0's input norm (fc output feeds attention raw)
            layers["ln1"].append(np.ones_like(state_dict[p + "post_attention_layernorm.weight"]))
        wk = linear_t(p + "self_attn.k_proj.weight")
        wv = linear_t(p + "self_attn.v_proj.weight")
        n_kv_ckpt = wk.shape[1] // d
        factor = args.num_kv_heads // n_kv_ckpt
        layers["wq"].append(linear_t(p + "self_attn.q_proj.weight"))
        layers["wk"].append(gqa.replicate_kv_weight(wk, n_kv_ckpt, d, factor))
        layers["wv"].append(gqa.replicate_kv_weight(wv, n_kv_ckpt, d, factor))
        layers["wo"].append(linear_t(p + "self_attn.o_proj.weight"))
        layers["ln2"].append(state_dict[p + "post_attention_layernorm.weight"])
        layers["wg"].append(linear_t(p + "mlp.gate_proj.weight"))
        layers["wu"].append(linear_t(p + "mlp.up_proj.weight"))
        layers["wd"].append(linear_t(p + "mlp.down_proj.weight"))
    params = {
        "fc": linear_t("fc.weight"),
        "layers": {k: np.stack(v) for k, v in layers.items()},
        "rope_inv_freq": inv_freq,
    }
    params["final_norm"] = state_dict.get(
        "norm.weight", np.ones((args.hidden_size,), dtype=np.float32))
    return params


# --- EAGLE3 -----------------------------------------------------------------------
#
# ≈ reference EAGLE3 (`models/model_base.py:1429-1432` target-hidden capture at 3
# layers, `modules/eagle/`): the draft conditions on fc(concat(h_low, h_mid, h_high))
# of THREE captured target layers instead of the final hidden, the decoder layer's
# QKV projections read concat(norm(embed), norm(hidden)) (2H wide), and the draft
# lm_head predicts over a reduced auxiliary vocabulary mapped back to target ids via
# a d2t offset table.


def init_eagle3_params(args: ModelArchArgs, key: jax.Array, draft_vocab: int,
                       dtype=jnp.bfloat16,
                       inv_freq: Optional[np.ndarray] = None) -> Params:
    """Random EAGLE3 draft params (single midlayer; QKV input width 2H)."""
    ks = jax.random.split(key, 10)
    h = args.hidden_size

    def w(k, shape, scale=0.02):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype)

    L, I = 1, args.intermediate_size
    layers = {
        "wq": w(ks[0], (L, 2 * h, args.q_size)),
        "wk": w(ks[1], (L, 2 * h, args.kv_size)),
        "wv": w(ks[2], (L, 2 * h, args.kv_size)),
        "wo": w(ks[3], (L, args.q_size, h)),
        "ln2": jnp.ones((L, h), dtype=dtype),
        "wg": w(ks[4], (L, h, I)),
        "wu": w(ks[5], (L, h, I)),
        "wd": w(ks[6], (L, I, h)),
    }
    if inv_freq is None:
        inv_freq = rope_ops.default_inv_freq(args.head_dim)
    return {
        "fc": w(ks[7], (3 * h, h)),
        "in_norm": jnp.ones((h,), dtype=dtype),
        "hid_norm": jnp.ones((h,), dtype=dtype),
        "layers": layers,
        "final_norm": jnp.ones((h,), dtype=dtype),
        "lm_head_d": w(ks[8], (h, draft_vocab)),
        "d2t": jnp.zeros((draft_vocab,), jnp.int32),
        "rope_inv_freq": jnp.asarray(inv_freq, jnp.float32),
    }


def convert_eagle3_state_dict(state_dict: Dict[str, np.ndarray],
                              args: ModelArchArgs,
                              inv_freq: np.ndarray) -> Params:
    """EAGLE3 checkpoint (``midlayer.*`` single layer, ``fc``, draft lm_head + d2t
    table) -> draft pytree."""
    def linear_t(name):
        return np.ascontiguousarray(state_dict[name].T)

    p = "midlayer."
    layers = {
        "wq": linear_t(p + "self_attn.q_proj.weight")[None],
        "wk": linear_t(p + "self_attn.k_proj.weight")[None],
        "wv": linear_t(p + "self_attn.v_proj.weight")[None],
        "wo": linear_t(p + "self_attn.o_proj.weight")[None],
        "ln2": state_dict[p + "post_attention_layernorm.weight"][None],
        "wg": linear_t(p + "mlp.gate_proj.weight")[None],
        "wu": linear_t(p + "mlp.up_proj.weight")[None],
        "wd": linear_t(p + "mlp.down_proj.weight")[None],
    }
    return {
        "fc": linear_t("fc.weight"),
        "in_norm": state_dict[p + "input_layernorm.weight"],
        "hid_norm": state_dict[p + "hidden_norm.weight"],
        "layers": layers,
        "final_norm": state_dict["norm.weight"],
        "lm_head_d": linear_t("lm_head.weight"),
        "d2t": np.asarray(state_dict["d2t"], np.int32),
        "rope_inv_freq": np.asarray(inv_freq, np.float32),
    }


def eagle3_fuse_hiddens(d_params: Params, caps) -> jnp.ndarray:
    """fc(concat(3 captured target hiddens)) -> (..., H) conditioning."""
    x = jnp.concatenate([c.astype(d_params["fc"].dtype) for c in caps], axis=-1)
    return x @ d_params["fc"]


def eagle3_forward(
    d_params: Params,
    t_params: Params,           # target embed reused
    args: ModelArchArgs,        # draft geometry (heads/kv_heads/head_dim/inter)
    input_ids: jnp.ndarray,     # (B, T)
    cond_hidden: jnp.ndarray,   # (B, T, H): fused target hiddens / draft hiddens
    position_ids: jnp.ndarray,  # (B,) rope+slot position of token 0
    cache: kvcache.KVCache,
    decode_bucket: Optional[int],   # None -> prefill over the fresh T tokens
    slot_offset=0,              # tree slots: token i writes at positions+slot_offset+i
    depths=None,                # (T,) static rope-depth offsets (tree rounds)
    extra_mask=None,            # (B, 1, T, bucket) visibility override (tree)
    mesh=None,
    rules=None,
):
    """One EAGLE3 draft forward. Returns (draft logits (B, T, V_d), draft hiddens
    (B, T, H), cache). The residual stream is the conditioning hidden (midlayer
    semantics): h = cond + attn(concat(norm(embed), norm(cond))) then MLP."""
    b, t = input_ids.shape
    lp = jax.tree.map(lambda x: x[0], d_params["layers"])
    e = jnp.take(t_params["embed"], input_ids, axis=0)
    x = jnp.concatenate([
        rms_norm(e, d_params["in_norm"], args.rms_norm_eps),
        rms_norm(cond_hidden.astype(e.dtype), d_params["hid_norm"],
                 args.rms_norm_eps)], axis=-1)

    if depths is None:
        pos_grid = position_ids[:, None] + slot_offset + jnp.arange(t)[None, :]
    else:
        pos_grid = position_ids[:, None] + jnp.asarray(depths, jnp.int32)[None, :]
    cos, sin = rope_ops.compute_cos_sin(d_params["rope_inv_freq"], pos_grid,
                                        args.rope_attention_scaling)
    q = (x @ lp["wq"]).reshape(b, t, args.num_heads, args.head_dim).transpose(0, 2, 1, 3)
    k = (x @ lp["wk"]).reshape(b, t, args.num_kv_heads, args.head_dim).transpose(0, 2, 1, 3)
    v = (x @ lp["wv"]).reshape(b, t, args.num_kv_heads, args.head_dim).transpose(0, 2, 1, 3)
    q, k = rope_ops.apply_rotary(q, k, cos, sin)

    kc, vc = cache["k"][0], cache["v"][0]
    if decode_bucket is None:
        kc = kvcache.write_prefill(kc, k)
        vc = kvcache.write_prefill(vc, v)
        k_att, v_att = k, v
        mask = pos_grid[:, None, :, None] >= pos_grid[:, None, None, :]
    else:
        slots = position_ids + slot_offset
        kc = kvcache.write_decode(kc, k, slots)
        vc = kvcache.write_decode(vc, v, slots)
        k_att = kvcache.read_bucket(kc, decode_bucket)
        v_att = kvcache.read_bucket(vc, decode_bucket)
        if extra_mask is not None:
            mask = extra_mask
        else:
            kv_pos = jnp.arange(decode_bucket)[None, None, None, :]
            mask = kv_pos <= pos_grid[:, None, :, None]
    from ..ops.attention import attend

    attn = attend(q, k_att.astype(q.dtype), v_att.astype(q.dtype), mask=mask)
    attn = attn.transpose(0, 2, 1, 3).reshape(b, t, args.q_size)
    h = cond_hidden.astype(e.dtype) + attn @ lp["wo"]
    hn = rms_norm(h, lp["ln2"], args.rms_norm_eps)
    ffn = (jax.nn.silu(hn @ lp["wg"]) * (hn @ lp["wu"])) @ lp["wd"]
    h = h + ffn
    hn = rms_norm(h, d_params["final_norm"], args.rms_norm_eps)
    d_logits = (hn @ d_params["lm_head_d"]).astype(jnp.float32)
    cache = dict(cache, k=kc[None], v=vc[None])
    return d_logits, h, cache


def _fuse_input(d_params: Params, t_params: Params, args: ModelArchArgs,
                input_ids: jnp.ndarray, cond_hidden: jnp.ndarray) -> jnp.ndarray:
    e = jnp.take(t_params["embed"], input_ids, axis=0)       # (B, T, H)
    x = jnp.concatenate([e, cond_hidden.astype(e.dtype)], axis=-1)
    return x @ d_params["fc"]


def eagle_prefill_forward(
    d_params: Params,
    t_params: Params,          # target params: embed + lm_head reused
    args: ModelArchArgs,       # draft stack geometry (target vocab/hidden)
    input_ids: jnp.ndarray,    # (B, S) prompt tokens
    cond_hidden: jnp.ndarray,  # (B, S, H) target hiddens shifted right (row 0 = zeros)
    position_ids: jnp.ndarray,
    last_token_idx: jnp.ndarray,
    cache: kvcache.KVCache,
    mesh=None,
    rules=None,
    slot_mapping=None,         # (B, S) paged write slots (-1 = drop)
) -> kvcache.KVCache:
    """Draft context encoding: populates the draft KV cache and returns it.

    (Prefill emits no draft proposal — the first fused step drafts from the target's
    prefill hidden — so no lm_head runs here.) With ``slot_mapping`` the draft
    cache is PAGED (continuous-batching serving; blocks shared with the target's
    table, pools separate)."""
    del last_token_idx
    h = _fuse_input(d_params, t_params, args, input_ids, cond_hidden)
    cos, sin = rope_ops.compute_cos_sin(d_params["rope_inv_freq"], position_ids,
                                        args.rope_attention_scaling)
    s = input_ids.shape[1]
    mask = (position_ids[:, None, :, None] >= position_ids[:, None, None, :])
    mask = jnp.logical_and(mask, causal_mask(s, s)[None, None])
    paged = None
    if slot_mapping is not None:
        paged = (jnp.zeros((input_ids.shape[0], 1), dtype=jnp.int32),
                 slot_mapping)
    _, cache = model_base._run_stack(d_params, args, h, cos, sin, mask, cache,
                                     positions=None, decode_bucket=None,
                                     mesh=mesh, rules=rules, paged=paged)
    return cache


def eagle_decode_forward(
    d_params: Params,
    t_params: Params,
    args: ModelArchArgs,
    input_ids: jnp.ndarray,    # (B, T)
    cond_hidden: jnp.ndarray,  # (B, T, H)
    position_ids: jnp.ndarray, # (B,)
    cache: kvcache.KVCache,
    decode_bucket: Optional[int],
    mesh=None,
    rules=None,
    block_table=None,          # (B, MB) paged: per-seq block ids
    slot_mapping=None,         # (B, T) paged: flat write slots
    skip_logits: bool = False,  # static: KV-only step — skip the (target) lm_head
) -> Tuple[jnp.ndarray, jnp.ndarray, kvcache.KVCache]:
    """Draft token generation. Returns (logits (B, T, V), draft hiddens (B, T, H),
    cache). With ``block_table``/``slot_mapping`` the draft cache is paged
    (CB serving; reads gather through the table). ``skip_logits`` returns None
    logits — the k-th draft step of a fused iteration runs only for its KV
    write, and the EAGLE draft head is the TARGET's full lm_head (the single
    largest weight stream in the draft step)."""
    b, t = input_ids.shape
    h = _fuse_input(d_params, t_params, args, input_ids, cond_hidden)
    pos_grid = position_ids[:, None] + jnp.arange(t)[None, :]
    cos, sin = rope_ops.compute_cos_sin(d_params["rope_inv_freq"], pos_grid,
                                        args.rope_attention_scaling)
    paged = None
    if block_table is not None:
        paged = (block_table, slot_mapping)
        decode_bucket = block_table.shape[1] * cache["k"].shape[3]
    kv_pos = jnp.arange(decode_bucket)[None, None, None, :]
    mask = kv_pos <= pos_grid[:, None, :, None]
    h, cache = model_base._run_stack(d_params, args, h, cos, sin, mask, cache,
                                     positions=position_ids,
                                     decode_bucket=decode_bucket,
                                     mesh=mesh, rules=rules, paged=paged)
    hn = rms_norm(h, d_params["final_norm"], args.rms_norm_eps)
    if skip_logits:
        return None, hn, cache
    logits = model_base._lm_head(t_params, args, hn, mesh, rules)
    return logits, hn, cache
