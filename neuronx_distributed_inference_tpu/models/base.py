"""Functional decoder-only transformer core.

≈ reference `models/model_base.py` `NeuronBaseModel` (the single traced forward,
:696-1074 / `get_model_output` :1249-1496), redesigned functionally for JAX:

- One pure function per sub-model: `prefill_forward` (≈ context encoding) and
  `decode_forward` (≈ token generation); `jax.jit` + static bucket args replace the
  reference's per-bucket NEFF trace (`models/model_wrapper.py:34-39`).
- Layers are *stacked* (leading L dim on every layer param) and executed with
  `lax.scan`, which keeps compile time O(1) in depth; the KV cache (L, B, H, S, D) is
  scanned alongside and re-stacked updated layers are the scan ys.
- Sharding is expressed with logical-axis constraints (parallel/sharding.py); XLA GSPMD
  inserts the tp all-reduces the reference's Row/ColumnParallel layers issue explicitly.
- Last-token gather before lm_head (≈ `model_base.py:1004-1016`) so prefill pays vocab
  matmul for one position per sequence.

Weight layout: matmul weights are stored (in_features, out_features) so application is
``x @ w`` (transposed relative to torch Linear).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..modules import block_kvcache, kvcache
from ..modules.lora import LoraSpec, apply_lora
from ..ops import rope as rope_ops
from ..ops.attention import attend, causal_mask
from ..ops.moe import MoEArgs, moe_block
from ..ops.norms import layer_norm, rms_norm
from ..ops.quantization import qapply
from ..parallel import overlap as overlap_lib
from ..parallel.sharding import constrain

Params = Dict[str, Any]


@dataclass(frozen=True)
class ModelArchArgs:
    """Static architecture description — hashable, closed over by jitted functions.

    Derived from an InferenceConfig (HF attrs) by each model family's
    ``arch_args_from_config`` (≈ the per-arch config classes under `models/<arch>/`).
    """

    vocab_size: int
    hidden_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    intermediate_size: int
    rms_norm_eps: float = 1e-6
    activation: str = "silu"
    norm_type: str = "rms"                # "rms" | "layer" (DBRX uses bias-free LayerNorm)
    clip_qkv: Optional[float] = None      # DBRX clamps q/k/v to [-clip, clip]
    attention_bias: bool = False
    o_bias: bool = False                  # bias on the attention output projection
    attn_sinks: bool = False              # gpt-oss learned per-head attention sinks
    mlp_bias: bool = False
    qk_norm: bool = False                 # qwen3-style per-head RMSNorm on q/k
    qk_norm_scope: str = "head"           # "head" (per-head) | "full" (olmo2: over
    #                                       the whole flattened q/k projection)
    qk_norm_after_rope: bool = False      # hunyuan: per-head q/k norm applied
    #                                       AFTER rotary (default is before)
    qk_norm_type: str = "rms"             # "rms" | "layer" (persimmon: biased
    #                                       per-head LayerNorm, params q_norm_b/k_norm_b)
    pre_norms: bool = True                # False = no input norms; the branch
    #                                       output norms (sandwich) carry alone (olmo2)
    sliding_window: Optional[int] = None  # gemma/gpt-oss SWA (applied to all layers if set)
    # per-layer attention kind, e.g. ("sliding", "sliding", ..., "full") — gemma3's
    # alternating local/global pattern; None = every layer identical
    layer_pattern: Optional[Tuple[str, ...]] = None
    # separate RoPE theta for sliding layers under a layer_pattern (gemma3 local rope)
    local_rope_theta: Optional[float] = None
    sandwich_norms: bool = False          # gemma-style post-attn/post-mlp branch norms
    zero_centered_norms: bool = False     # gemma-style (1 + weight) RMSNorm scaling
    logits_soft_cap: Optional[float] = None
    attention_scale: Optional[float] = None   # None -> 1/sqrt(head_dim)
    embedding_multiplier: float = 1.0     # gemma scales embeddings by sqrt(hidden)
    tie_word_embeddings: bool = False
    rope_attention_scaling: float = 1.0   # HF rope_scaling attention_factor
    # cos/sin magnitude for sliding layers under a layer_pattern (gpt-oss shares the
    # yarn factor across both layer kinds; gemma3's local rope is unscaled)
    local_rope_attention_scaling: float = 1.0
    # --- contrib-arch primitives (gpt2/opt/pythia/phi/starcoder2/falcon) ---
    learned_pos: bool = False        # learned position embeddings (params.pos_embed);
    #                                  rope disabled via a zero inv_freq table
    pos_offset: int = 0              # OPT adds 2 to every position index
    norm_bias: bool = False          # LayerNorm with bias params (ln1_b/ln2_b/...)
    mlp_kind: str = "gated"          # "gated" (silu gate*up) | "plain" (fc -> act -> fc)
    parallel_residual: bool = False  # h = x + attn(ln1(x)) + mlp(ln2(x) or ln1(x))
    shared_ln: bool = False          # parallel residual reusing ONE norm (falcon-7b)
    rotary_dim: Optional[int] = None  # partial rotary (phi/gpt-neox rotary_pct)
    alibi: bool = False              # ALiBi additive attention bias (bloom/mpt);
    #                                  rope disabled via a zero inv_freq table
    embed_norm: bool = False         # LayerNorm on embeddings (bloom)
    # int8 dynamic per-token ACTIVATION quantization on the norm-adjacent
    # projections (qkv + mlp) — the TPU-native rmsnorm_quant analog (int8 MXU;
    # v5e has no fp8 matmul units). Requires int8 weight quantization.
    activation_quant: bool = False
    # --- contrib-arch primitives (round 3: granite/cohere/glm4/gemma2) ---
    residual_multiplier: float = 1.0  # granite scales each branch before the add
    logits_scale: float = 1.0         # cohere logit_scale / granite 1/logits_scaling
    final_logits_soft_cap: Optional[float] = None   # gemma2 final tanh cap
    rope_interleaved: bool = False    # glm4-style pairwise-interleaved rotary
    # MoE FFN (Mixtral/Qwen3-MoE/DBRX); None = dense MLP. See ops/moe.py.
    moe: Optional["MoEArgs"] = None
    # static multi-LoRA serving (see modules/lora.py); None = disabled
    lora: Optional["LoraSpec"] = None

    @property
    def q_size(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_size(self) -> int:
        return self.num_kv_heads * self.head_dim


# logical sharding axes for each stacked layer param (see parallel/sharding.py)
def param_logical_axes(args: ModelArchArgs) -> Params:
    layer = {
        "ln1": ("layers", None),
        "wq": ("layers", "embed", "heads"),
        "wk": ("layers", "embed", "kv_heads"),
        "wv": ("layers", "embed", "kv_heads"),
        "wo": ("layers", "heads", "embed"),
        "ln2": ("layers", None),
    }
    if args.norm_bias:
        layer.update({"ln1_b": ("layers", None), "ln2_b": ("layers", None)})
    if args.activation == "xielu":
        layer.update({"xielu_ap": ("layers", None), "xielu_an": ("layers", None)})
    if args.moe is not None:
        layer.update({
            "router": ("layers", "embed", None),
            "wg": ("layers", "experts", "embed", "expert_mlp"),
            "wu": ("layers", "experts", "embed", "expert_mlp"),
            "wd": ("layers", "experts", "expert_mlp", "embed"),
        })
        if args.moe.router_bias:
            layer["router_b"] = ("layers", None)
        if args.moe.score_correction_bias:
            layer["router_cb"] = ("layers", None)
        if args.moe.expert_bias:
            layer.update({
                "bg": ("layers", "experts", "expert_mlp"),
                "bu": ("layers", "experts", "expert_mlp"),
                "bd": ("layers", "experts", None),
            })
        if args.moe.shared_expert_intermediate_size:
            layer.update({
                "shared_wg": ("layers", "embed", "mlp"),
                "shared_wu": ("layers", "embed", "mlp"),
                "shared_wd": ("layers", "mlp", "embed"),
            })
            if args.moe.shared_expert_gated:
                layer["shared_gate"] = ("layers", "embed", None)
    elif args.mlp_kind == "plain":
        layer.update({
            "wg": ("layers", "embed", "mlp"),
            "wd": ("layers", "mlp", "embed"),
        })
        if args.mlp_bias:
            layer.update({"bg": ("layers", "mlp"), "bd": ("layers", None)})
    else:
        layer.update({
            "wg": ("layers", "embed", "mlp"),
            "wu": ("layers", "embed", "mlp"),
            "wd": ("layers", "mlp", "embed"),
        })
        if args.mlp_bias:
            layer.update({"bg": ("layers", "mlp"), "bu": ("layers", "mlp"),
                          "bd": ("layers", None)})
    if args.attention_bias:
        layer.update({
            "bq": ("layers", "heads"),
            "bk": ("layers", "kv_heads"),
            "bv": ("layers", "kv_heads"),
        })
    if args.o_bias:
        layer["bo"] = ("layers", None)
    if args.attn_sinks:
        layer["sinks"] = ("layers", "heads")
    if args.qk_norm:
        layer.update({"q_norm": ("layers", None), "k_norm": ("layers", None)})
        if args.qk_norm_type == "layer":
            layer.update({"q_norm_b": ("layers", None),
                          "k_norm_b": ("layers", None)})
    if args.sandwich_norms:
        layer.update({"ln1_post": ("layers", None), "ln2_post": ("layers", None)})
    if args.lora is not None:
        from ..modules.lora import lora_logical_axes

        layer.update(lora_logical_axes(args, args.lora))
    out = {
        "embed": ("vocab", "embed"),
        "layers": layer,
        "final_norm": (None,),
        "rope_inv_freq": (None,),
    }
    if args.norm_bias:
        out["final_norm_b"] = (None,)
    if args.learned_pos:
        out["pos_embed"] = (None, "embed")
    if args.alibi:
        out["alibi_slopes"] = ("heads",)
    if args.embed_norm:
        out.update({"embed_ln": (None,), "embed_ln_b": (None,)})
    if args.local_rope_theta is not None:
        out["rope_inv_freq_local"] = (None,)
    if not args.tie_word_embeddings:
        out["lm_head"] = ("embed", "vocab")
    return out


def init_params(args: ModelArchArgs, key: jax.Array, dtype=jnp.bfloat16,
                inv_freq: Optional[np.ndarray] = None) -> Params:
    """Random parameter pytree (tests / synthetic benchmarks; real weights come from
    utils/checkpoint + the per-arch converter)."""
    ks = jax.random.split(key, 14)
    L, H, I = args.num_layers, args.hidden_size, args.intermediate_size

    def w(k, shape, scale=0.02):
        return (jax.random.normal(k, shape, dtype=jnp.float32) * scale).astype(dtype)

    layers = {
        "ln1": jnp.ones((L, H), dtype=dtype),
        "wq": w(ks[0], (L, H, args.q_size)),
        "wk": w(ks[1], (L, H, args.kv_size)),
        "wv": w(ks[2], (L, H, args.kv_size)),
        "wo": w(ks[3], (L, args.q_size, H)),
        "ln2": jnp.ones((L, H), dtype=dtype),
    }
    if args.moe is not None:
        E = args.moe.num_experts
        layers.update({
            "router": w(ks[9], (L, H, E)),
            "wg": w(ks[4], (L, E, H, I)),
            "wu": w(ks[5], (L, E, H, I)),
            "wd": w(ks[6], (L, E, I, H)),
        })
        if args.moe.router_bias:
            layers["router_b"] = jnp.zeros((L, E), dtype=dtype)
        if args.moe.score_correction_bias:
            layers["router_cb"] = jnp.zeros((L, E), dtype=dtype)
        if args.moe.expert_bias:
            layers.update({
                "bg": jnp.zeros((L, E, I), dtype=dtype),
                "bu": jnp.zeros((L, E, I), dtype=dtype),
                "bd": jnp.zeros((L, E, H), dtype=dtype),
            })
        shared_i = args.moe.shared_expert_intermediate_size
        if shared_i:
            layers.update({
                "shared_wg": w(ks[10], (L, H, shared_i)),
                "shared_wu": w(ks[11], (L, H, shared_i)),
                "shared_wd": w(ks[12], (L, shared_i, H)),
            })
            if args.moe.shared_expert_gated:
                layers["shared_gate"] = w(ks[13], (L, H, 1))
    elif args.mlp_kind == "plain":
        layers.update({
            "wg": w(ks[4], (L, H, I)),
            "wd": w(ks[6], (L, I, H)),
        })
        if args.mlp_bias:
            layers.update({"bg": jnp.zeros((L, I), dtype=dtype),
                           "bd": jnp.zeros((L, H), dtype=dtype)})
    else:
        if args.mlp_bias:
            layers.update({"bg": jnp.zeros((L, I), dtype=dtype),
                           "bu": jnp.zeros((L, I), dtype=dtype),
                           "bd": jnp.zeros((L, H), dtype=dtype)})
        layers.update({
            "wg": w(ks[4], (L, H, I)),
            "wu": w(ks[5], (L, H, I)),
            "wd": w(ks[6], (L, I, H)),
        })
    if args.norm_bias:
        layers.update({"ln1_b": jnp.zeros((L, H), dtype=dtype),
                       "ln2_b": jnp.zeros((L, H), dtype=dtype)})
    if args.attention_bias:
        layers.update({
            "bq": jnp.zeros((L, args.q_size), dtype=dtype),
            "bk": jnp.zeros((L, args.kv_size), dtype=dtype),
            "bv": jnp.zeros((L, args.kv_size), dtype=dtype),
        })
    if args.o_bias:
        layers["bo"] = jnp.zeros((L, H), dtype=dtype)
    if args.attn_sinks:
        layers["sinks"] = jnp.zeros((L, args.num_heads), dtype=dtype)
    if args.lora is not None:
        from ..modules.lora import init_lora_params

        layers.update({k: jnp.asarray(v, dtype=dtype)
                       for k, v in init_lora_params(args, args.lora).items()})
    if args.activation == "xielu":
        import math as _math

        layers.update({
            "xielu_ap": jnp.full((L, 1), _math.log(_math.expm1(0.8)),
                                 dtype=jnp.float32),
            "xielu_an": jnp.full((L, 1), _math.log(_math.expm1(0.3)),
                                 dtype=jnp.float32),
        })
    norm_fill = 0.0 if args.zero_centered_norms else 1.0
    if args.qk_norm:
        qn = args.q_size if args.qk_norm_scope == "full" else args.head_dim
        kn = args.kv_size if args.qk_norm_scope == "full" else args.head_dim
        layers.update({
            "q_norm": jnp.full((L, qn), norm_fill, dtype=dtype),
            "k_norm": jnp.full((L, kn), norm_fill, dtype=dtype),
        })
        if args.qk_norm_type == "layer":
            layers.update({
                "q_norm_b": jnp.zeros((L, qn), dtype=dtype),
                "k_norm_b": jnp.zeros((L, kn), dtype=dtype),
            })
    if args.sandwich_norms:
        layers.update({
            "ln1_post": jnp.full((L, H), norm_fill, dtype=dtype),
            "ln2_post": jnp.full((L, H), norm_fill, dtype=dtype),
        })
    if args.zero_centered_norms:
        layers["ln1"] = jnp.zeros((L, H), dtype=dtype)
        layers["ln2"] = jnp.zeros((L, H), dtype=dtype)
    if inv_freq is None:
        if args.learned_pos:
            inv_freq = np.zeros((args.head_dim // 2,), np.float32)  # rope = identity
        else:
            inv_freq = rope_ops.default_inv_freq(args.rotary_dim or args.head_dim)
    params = {
        "embed": w(ks[7], (args.vocab_size, H)),
        "layers": layers,
        "final_norm": jnp.full((H,), norm_fill, dtype=dtype),
        "rope_inv_freq": jnp.asarray(inv_freq, dtype=jnp.float32),
    }
    if args.norm_bias:
        params["final_norm_b"] = jnp.zeros((H,), dtype=dtype)
    if args.learned_pos:
        params["pos_embed"] = w(ks[9], (4096 + args.pos_offset, H))
    if args.alibi:
        params["alibi_slopes"] = jnp.asarray(
            alibi_slopes(args.num_heads), dtype=jnp.float32)
    if args.embed_norm:
        params["embed_ln"] = jnp.ones((H,), dtype=dtype)
        params["embed_ln_b"] = jnp.zeros((H,), dtype=dtype)
    if args.local_rope_theta is not None:
        params["rope_inv_freq_local"] = jnp.asarray(
            rope_ops.default_inv_freq(args.head_dim, args.local_rope_theta),
            dtype=jnp.float32)
    if not args.tie_word_embeddings:
        params["lm_head"] = w(ks[8], (H, args.vocab_size))
    return params


_ACTIVATIONS = {
    "silu": jax.nn.silu,
    "gelu": jax.nn.gelu,
    "gelu_new": lambda x: jax.nn.gelu(x, approximate=True),
    "gelu_pytorch_tanh": lambda x: jax.nn.gelu(x, approximate=True),
    "relu": jax.nn.relu,
    "relu2": lambda x: jnp.square(jax.nn.relu(x)),   # nemotron squared ReLU
}


def _xielu(x, alpha_p, alpha_n, beta=0.5, eps=-1e-6):
    """xIELU activation with LEARNED per-layer alpha parameters (apertus;
    arXiv:2411.13010): quadratic-positive / shifted-expm1-negative branches."""
    x32 = x.astype(jnp.float32)
    ap = jax.nn.softplus(alpha_p.astype(jnp.float32))
    an = beta + jax.nn.softplus(alpha_n.astype(jnp.float32))
    out = jnp.where(x32 > 0, ap * x32 * x32 + beta * x32,
                    (jnp.expm1(jnp.minimum(x32, eps)) - x32) * an + beta * x32)
    return out.astype(x.dtype)


def _norm(x: jnp.ndarray, weight: jnp.ndarray, args: "ModelArchArgs",
          bias=None) -> jnp.ndarray:
    """Hidden-state norm: RMSNorm by default, LayerNorm (optionally biased) for
    DBRX/GPT-style archs."""
    if args.norm_type == "layer":
        w = weight + 1.0 if args.zero_centered_norms else weight   # nemotron LN1P
        return layer_norm(x, w,
                          bias if bias is not None else jnp.zeros_like(weight),
                          eps=args.rms_norm_eps)
    return rms_norm(x, weight, args.rms_norm_eps,
                    zero_centered=args.zero_centered_norms)


def _deinterleave_rope(x):
    """(..., D) pairwise-interleaved layout -> half-split layout: channel order
    (0, 2, 4, ..., 1, 3, 5, ...), the glm4/deepseek interleaved-rotary convention."""
    b, h, s, d = x.shape
    return x.reshape(b, h, s, d // 2, 2).transpose(0, 1, 2, 4, 3).reshape(
        b, h, s, d)


def _apply_rope(args: ModelArchArgs, q, k, cos, sin):
    """Rotary application with optional partial rotary dims (phi/gpt-neox
    rotary_pct) and optional interleaved-pair channel layout (glm4): only the
    first ``rotary_dim`` channels rotate."""
    rd = args.rotary_dim
    if rd is None or rd == args.head_dim:
        if args.rope_interleaved:
            q, k = _deinterleave_rope(q), _deinterleave_rope(k)
        return rope_ops.apply_rotary(q, k, cos, sin)
    qr, kr = q[..., :rd], k[..., :rd]
    if args.rope_interleaved:
        qr, kr = _deinterleave_rope(qr), _deinterleave_rope(kr)
    q1, k1 = rope_ops.apply_rotary(qr, kr, cos, sin)
    return (jnp.concatenate([q1, q[..., rd:]], axis=-1),
            jnp.concatenate([k1, k[..., rd:]], axis=-1))


def alibi_slopes(num_heads: int) -> np.ndarray:
    """Standard ALiBi head slopes (power-of-two geometric ladder; the non-power-of-2
    extension interleaves the next ladder, per the ALiBi paper / HF bloom)."""
    def pow2_slopes(n):
        start = 2.0 ** (-(2.0 ** -(np.log2(n) - 3)))
        return start * (start ** np.arange(n))

    n = 2 ** int(np.floor(np.log2(num_heads)))
    slopes = pow2_slopes(n)
    if n < num_heads:
        extra = pow2_slopes(2 * n)[0::2][: num_heads - n]
        slopes = np.concatenate([slopes, extra])
    return slopes.astype(np.float32)


def _alibi_bias(slopes: jnp.ndarray, q_pos: jnp.ndarray, kv_pos: jnp.ndarray
                ) -> jnp.ndarray:
    """(B?, 1, S_q, S_kv) position grids -> additive (B?, H, S_q, S_kv) bias:
    slope_h * -(q_pos - kv_pos) (masked positions die via the boolean mask)."""
    dist = (q_pos - kv_pos).astype(jnp.float32)          # (..., 1, S_q, S_kv)
    return -slopes[None, :, None, None] * dist


def _project_qkv(lp: Params, args: ModelArchArgs, hn: jnp.ndarray,
                 adapter_ids=None, mesh=None, rules=None, ov=None):
    """(B, S, H) -> q (B, nq, S, D), k/v (B, nkv, S, D).

    ``ov`` ("seq"/"hidden", see parallel/overlap.layer_phase) routes the three
    projections through ONE fused collective matmul: the all-gather half of
    the sharded-residual collective rotates activation shards in behind the
    MXU instead of blocking in front of it."""
    b, s, _ = hn.shape
    aq = args.activation_quant
    qkv = None
    if ov is not None:
        qkv = overlap_lib.column_projection(
            hn, [lp["wq"], lp["wk"], lp["wv"]], mesh, rules, ov,
            ("heads", "kv_heads", "kv_heads"))
    if qkv is not None:
        q, k, v = qkv
    else:
        q = qapply(hn, lp["wq"], act_quant=aq)
        k = qapply(hn, lp["wk"], act_quant=aq)
        v = qapply(hn, lp["wv"], act_quant=aq)
    if args.lora is not None:
        sc = args.lora.scaling
        q = apply_lora(lp, "wq", hn, q, adapter_ids, sc)
        k = apply_lora(lp, "wk", hn, k, adapter_ids, sc)
        v = apply_lora(lp, "wv", hn, v, adapter_ids, sc)
    if args.attention_bias:
        q = q + lp["bq"]
        k = k + lp["bk"]
        v = v + lp["bv"]
    if args.clip_qkv is not None:
        clip = jnp.asarray(args.clip_qkv, q.dtype)
        q = jnp.clip(q, -clip, clip)
        k = jnp.clip(k, -clip, clip)
        v = jnp.clip(v, -clip, clip)
    if args.qk_norm and args.qk_norm_scope == "full":
        # olmo2: RMSNorm over the whole flattened q/k projection output
        zc = args.zero_centered_norms
        q = rms_norm(q, lp["q_norm"], args.rms_norm_eps, zero_centered=zc)
        k = rms_norm(k, lp["k_norm"], args.rms_norm_eps, zero_centered=zc)
    q = q.reshape(b, s, args.num_heads, args.head_dim).transpose(0, 2, 1, 3)
    k = k.reshape(b, s, args.num_kv_heads, args.head_dim).transpose(0, 2, 1, 3)
    v = v.reshape(b, s, args.num_kv_heads, args.head_dim).transpose(0, 2, 1, 3)
    if args.qk_norm and args.qk_norm_scope == "head" \
            and not args.qk_norm_after_rope:
        q, k = _head_qk_norm(lp, args, q, k)
    return q, k, v


def _o_proj(lp: Params, args: ModelArchArgs, attn: jnp.ndarray, mesh, rules,
            ov, adapter_ids, resid_logical) -> jnp.ndarray:
    """Attention output projection, landing in the residual layout.

    ``ov`` routes through the matmul->reduce-scatter collective matmul
    (parallel/overlap.py): partial sums rotate-accumulate around the tp ring
    and the output arrives already sharded like the residual stream. The
    fallback is qapply + a GSPMD constraint (which turns the all-reduce into
    reduce-scatter when the residual rules are sharded)."""
    out = (overlap_lib.row_projection(attn, lp["wo"], mesh, rules, ov, "heads")
           if ov is not None else None)
    if out is None:
        out = qapply(attn, lp["wo"])
    if args.lora is not None:
        out = apply_lora(lp, "wo", attn, out, adapter_ids, args.lora.scaling)
    if args.o_bias:
        out = out + lp["bo"]
    return constrain(out, resid_logical, rules, mesh=mesh)


def _head_qk_norm(lp: Params, args: ModelArchArgs, q, k):
    if args.qk_norm_type == "layer":
        q = layer_norm(q, lp["q_norm"], lp["q_norm_b"], eps=args.rms_norm_eps)
        k = layer_norm(k, lp["k_norm"], lp["k_norm_b"], eps=args.rms_norm_eps)
    else:
        zc = args.zero_centered_norms
        q = rms_norm(q, lp["q_norm"], args.rms_norm_eps, zero_centered=zc)
        k = rms_norm(k, lp["k_norm"], args.rms_norm_eps, zero_centered=zc)
    return q, k


def _mlp(lp: Params, args: ModelArchArgs, hn: jnp.ndarray, mesh, rules,
         adapter_ids=None, ov=None) -> jnp.ndarray:
    act = (_ACTIVATIONS[args.activation] if args.activation != "xielu"
           else None)
    if args.mlp_kind == "plain":
        # fc -> act -> fc (GPT-style, optionally biased)
        cols = (overlap_lib.column_projection(hn, [lp["wg"]], mesh, rules, ov,
                                              ("mlp",))
                if ov is not None else None)
        inter = cols[0] if cols is not None else qapply(hn, lp["wg"])
        if args.mlp_bias:
            inter = inter + lp["bg"]
        if args.activation == "xielu":
            inter = _xielu(inter, lp["xielu_ap"][None], lp["xielu_an"][None])
        else:
            inter = act(inter)
        inter = constrain(inter, ("batch", None, "mlp"), rules, mesh=mesh)
        down = (overlap_lib.row_projection(inter, lp["wd"], mesh, rules, ov,
                                           "mlp")
                if ov is not None else None)
        if down is None:
            down = qapply(inter, lp["wd"])
        if args.mlp_bias:
            down = down + lp["bd"]
        return down
    aq = args.activation_quant
    cols = (overlap_lib.column_projection(hn, [lp["wg"], lp["wu"]], mesh,
                                          rules, ov, ("mlp", "mlp"))
            if ov is not None else None)
    if cols is not None:
        gate, up = cols
    else:
        gate = qapply(hn, lp["wg"], act_quant=aq)
        up = qapply(hn, lp["wu"], act_quant=aq)
    if args.lora is not None:
        sc = args.lora.scaling
        gate = apply_lora(lp, "wg", hn, gate, adapter_ids, sc)
        up = apply_lora(lp, "wu", hn, up, adapter_ids, sc)
    if args.mlp_bias:
        gate = gate + lp["bg"]
        up = up + lp["bu"]
    gate = act(gate)
    inter = constrain(gate * up, ("batch", None, "mlp"), rules, mesh=mesh)
    down = (overlap_lib.row_projection(inter, lp["wd"], mesh, rules, ov, "mlp")
            if ov is not None else None)
    if down is None:
        down = qapply(inter, lp["wd"], act_quant=aq)
    if args.lora is not None:
        down = apply_lora(lp, "wd", inter, down, adapter_ids, args.lora.scaling)
    if args.mlp_bias:
        down = down + lp["bd"]
    return down


def shard_map_compat(local_fn, *, mesh, in_specs, out_specs):
    """shard_map with the replication check off, across jax versions (current
    jax exposes `jax.shard_map(..., check_vma=)`; older releases have
    `jax.experimental.shard_map.shard_map(..., check_rep=)`)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(local_fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(local_fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def _shard_mapped(local_fn, mesh, rules, in_logical, out_logical):
    """shard_map a Pallas-kernel wrapper over the mesh with logical-axis operand
    specs.

    Pallas calls have no GSPMD partitioning rule, so each kernel runs per-shard on
    its local block (≈ the reference launching one NKI kernel per core,
    `attention_base.py:121-125`). ``in_logical`` is a sequence of logical-axis
    tuples (None = fully replicated); ``out_logical`` is one tuple for a single
    output or a list of tuples for multiple. With ``mesh=None`` the local fn runs
    unwrapped."""
    if mesh is None:
        return local_fn
    from jax.sharding import PartitionSpec as P

    from ..parallel.sharding import DEFAULT_RULES, logical_to_spec

    r = rules or DEFAULT_RULES

    def spec(lg):
        return P() if lg is None else logical_to_spec(lg, r)

    out_specs = (tuple(spec(lg) for lg in out_logical)
                 if isinstance(out_logical, list) else spec(out_logical))
    return shard_map_compat(local_fn, mesh=mesh,
                            in_specs=tuple(spec(lg) for lg in in_logical),
                            out_specs=out_specs)


_DECODE_NEW_KV = ("decode_batch", "decode_kv_heads", None, None)
_DECODE_Q = ("decode_batch", "decode_heads", None, None)


def _stacked_attend_min_bucket() -> int:
    """Smallest decode bucket that takes the Pallas length-aware stacked attend
    instead of dynamic-slice + jnp.

    MEASURED r5 (8B bs=64, bucket 512, 128-step decode): the slice+jnp path
    runs 17.7 ms/step (fp8) / 17.3 (int8) vs the stacked kernel's 20.9 / 20.2
    — even though the slice COPIES cost ~2.55 ms/step (3x cache traffic), the
    kernel's per-cell costs at short widths cost more. Length-aware reads only
    pay at >=1024-wide buckets, confirming the r4 tuning. Overridable for
    probes via TPUINF_STACKED_ATTEND_MIN_BUCKET — read at TRACE time, so it
    must be set before the first compile (a warm executable never re-reads it)."""
    import os

    return int(os.environ.get("TPUINF_STACKED_ATTEND_MIN_BUCKET", "1024"))



def _head_extras(sinks, alibi_slopes, logical_axis):
    """Per-q-head kernel extras (sinks / ALiBi slopes) -> (in_logical tail,
    operand tail, kw names) for the shard_map wrappers below."""
    in_logical, operands, kw_names = [], [], []
    for name, extra in (("sinks", sinks), ("alibi_slopes", alibi_slopes)):
        if extra is not None:
            in_logical.append((logical_axis,))
            operands.append(extra)
            kw_names.append(name)
    return in_logical, operands, kw_names


def _sharded_kv_write(k_cache, v_cache, new_k, new_v, positions, layer_idx, mesh,
                      rules):
    """Stacked-cache decode K+V write (one Pallas DMA-scatter kernel) under the mesh.

    ≈ the reference's batched KV write kernel (`modules/kvcache/utils.py:20-38`):
    overlapped strided DMAs instead of the serial per-row while loop XLA lowers a
    vmapped dynamic_update_slice to. The saturating cache-dtype cast lives HERE
    (not at call sites) so the kernel read-side assumption — fp8 payloads are
    finite — is guaranteed by the write site itself."""
    from ..modules.kvcache import CACHE_LOGICAL, to_cache_dtype
    from ..ops.flash_decode import write_decode_stacked_kv

    interpret = jax.default_backend() == "cpu"
    new_k = to_cache_dtype(new_k, k_cache.dtype)
    new_v = to_cache_dtype(new_v, v_cache.dtype)

    def _local(ck, cv, nk, nv, p, li):
        return write_decode_stacked_kv(ck, cv, nk, nv, p, li, interpret=interpret)

    fn = _shard_mapped(_local, mesh, rules,
                       [CACHE_LOGICAL, CACHE_LOGICAL, _DECODE_NEW_KV,
                        _DECODE_NEW_KV, ("decode_batch",), None],
                       [CACHE_LOGICAL, CACHE_LOGICAL])
    return fn(k_cache, v_cache, new_k, new_v, positions, layer_idx)


def _sharded_decode_attend(q, k_cache, v_cache, positions, layer_idx, bucket,
                           args: ModelArchArgs, mesh, rules, sinks=None,
                           alibi_slopes=None):
    """Stacked-cache decode attention (Pallas, length-aware) under the mesh.

    ≈ the reference TKG attention kernels (`attention_base.py:1483-1677`): reads only
    KV tiles at or below each row's position instead of the full bucket width.
    ``sinks``/``alibi_slopes`` are (Hq,) per-q-head extras, sharded with the heads."""
    from ..modules.kvcache import CACHE_LOGICAL
    from ..ops.flash_decode import flash_decode_attention_stacked

    interpret = jax.default_backend() == "cpu"
    xl, xo, kw_names = _head_extras(sinks, alibi_slopes, "decode_heads")
    in_logical = [_DECODE_Q, CACHE_LOGICAL, CACHE_LOGICAL,
                  ("decode_batch",), None] + xl
    operands = [q, k_cache, v_cache, positions, layer_idx] + xo

    def _local(q, kc, vc, p, li, *extras):
        kw = dict(zip(kw_names, extras))
        return flash_decode_attention_stacked(
            q, kc, vc, p, li, bucket=bucket, scale=args.attention_scale,
            window=args.sliding_window, soft_cap=args.logits_soft_cap,
            interpret=interpret, **kw)

    fn = _shard_mapped(_local, mesh, rules, in_logical, _DECODE_Q)
    return fn(*operands)


def _sharded_paged_kv_write(k_cache, v_cache, new_k, new_v, slot_mapping, layer_idx,
                            mesh, rules):
    """Stacked paged-cache decode K+V write (Pallas DMA RMW scatter) under the mesh.

    ≈ the reference's batched KV write kernel over the paged layout
    (`modules/kvcache/utils.py:20-38` + `block_kv_cache_manager.py:268-374`).
    The saturating cache-dtype cast lives HERE (see _sharded_kv_write)."""
    from ..modules.block_kvcache import PAGED_CACHE_LOGICAL
    from ..modules.kvcache import to_cache_dtype
    from ..ops.paged_decode import write_paged_stacked_kv

    interpret = jax.default_backend() == "cpu"
    new_k = to_cache_dtype(new_k, k_cache.dtype)
    new_v = to_cache_dtype(new_v, v_cache.dtype)

    def _local(ck, cv, nk, nv, sm, li):
        return write_paged_stacked_kv(ck, cv, nk, nv, sm, li, interpret=interpret)

    fn = _shard_mapped(_local, mesh, rules,
                       [PAGED_CACHE_LOGICAL, PAGED_CACHE_LOGICAL, _DECODE_NEW_KV,
                        _DECODE_NEW_KV, ("decode_batch", None), None],
                       [PAGED_CACHE_LOGICAL, PAGED_CACHE_LOGICAL])
    return fn(k_cache, v_cache, new_k, new_v, slot_mapping, layer_idx)


def _paged_fused_enabled() -> bool:
    """Static routing for the FUSED paged append+attend kernel (the decode hot
    path): one pallas call per layer writes the step's K/V and attends —
    eliminating the per-layer write dispatch and the read-after-write of the
    just-written block. Default ON; TPUINF_PAGED_FUSED=0 falls back to the
    separate write-then-attend kernels (read at TRACE time — set before the
    first compile)."""
    import os

    return os.environ.get("TPUINF_PAGED_FUSED", "1") != "0"


def _sharded_paged_fused(q, k_cache, v_cache, new_k, new_v, positions,
                         slot_mapping, layer_idx, block_table,
                         args: ModelArchArgs, mesh, rules, sinks=None,
                         alibi_slopes=None):
    """FUSED paged decode step (write + attend in ONE pallas call) under the
    mesh.

    ≈ the reference TKG hot path (`block_kv_cache_manager.py:268-374` +
    `attention_base.py:1483-1677`) collapsed to a single kernel per layer:
    the fresh tokens commit through the same RMW windows as
    `write_paged_stacked_kv` and attend from VMEM operands, while committed
    blocks stream through a prefetch-pipelined manual DMA loop (see
    ops/paged_decode.fused_paged_decode_stacked). The saturating cache-dtype
    cast lives HERE (see _sharded_kv_write). Returns (attn, k_cache, v_cache)."""
    from ..modules.block_kvcache import PAGED_CACHE_LOGICAL
    from ..modules.kvcache import to_cache_dtype
    from ..ops.paged_decode import fused_paged_decode_stacked

    interpret = jax.default_backend() == "cpu"
    new_k = to_cache_dtype(new_k, k_cache.dtype)
    new_v = to_cache_dtype(new_v, v_cache.dtype)
    xl, xo, kw_names = _head_extras(sinks, alibi_slopes, "decode_heads")
    in_logical = [_DECODE_Q, PAGED_CACHE_LOGICAL, PAGED_CACHE_LOGICAL,
                  _DECODE_NEW_KV, _DECODE_NEW_KV, ("decode_batch",),
                  ("decode_batch", None), None, ("decode_batch", None)] + xl
    operands = [q, k_cache, v_cache, new_k, new_v, positions, slot_mapping,
                layer_idx, block_table] + xo

    def _local(q, kc, vc, nk, nv, p, sm, li, bt, *extras):
        kw = dict(zip(kw_names, extras))
        return fused_paged_decode_stacked(
            q, nk, nv, kc, vc, p, sm, li, bt, scale=args.attention_scale,
            window=args.sliding_window, soft_cap=args.logits_soft_cap,
            interpret=interpret, **kw)

    fn = _shard_mapped(_local, mesh, rules, in_logical,
                       [_DECODE_Q, PAGED_CACHE_LOGICAL, PAGED_CACHE_LOGICAL])
    return fn(*operands)


def _sharded_paged_attend(q, k_cache, v_cache, positions, layer_idx, block_table,
                          args: ModelArchArgs, mesh, rules, sinks=None,
                          alibi_slopes=None, q_lens=None):
    """Ragged paged decode attention (Pallas, block-table-indexed, length-aware)
    under the mesh.

    ≈ the reference TKG attention kernels over the paged cache — the serving hot
    path SURVEY §7 calls "the performance cliff": HBM reads track each row's live
    length instead of the block-table width. With ``q_lens`` the MIXED-STEP
    kernel serves per-row variable q_len (decode rows q=1 alongside prefill
    chunks) in one call — see ops/paged_decode.paged_mixed_attention_stacked."""
    from ..modules.block_kvcache import PAGED_CACHE_LOGICAL
    from ..ops.paged_decode import (paged_decode_attention_stacked,
                                    paged_mixed_attention_stacked)

    interpret = jax.default_backend() == "cpu"
    xl, xo, kw_names = _head_extras(sinks, alibi_slopes, "decode_heads")
    in_logical = [_DECODE_Q, PAGED_CACHE_LOGICAL, PAGED_CACHE_LOGICAL,
                  ("decode_batch",), None, ("decode_batch", None)] + xl
    operands = [q, k_cache, v_cache, positions, layer_idx, block_table] + xo

    if q_lens is not None:
        in_logical = in_logical[:4] + [("decode_batch",)] + in_logical[4:]
        operands = operands[:4] + [q_lens] + operands[4:]

    def _local(q, kc, vc, p, *rest):
        extras = rest[3 if q_lens is not None else 2:]
        kw = dict(zip(kw_names, extras))
        kw.update(scale=args.attention_scale, window=args.sliding_window,
                  soft_cap=args.logits_soft_cap, interpret=interpret)
        if q_lens is not None:
            ql, li, bt = rest[:3]
            return paged_mixed_attention_stacked(q, kc, vc, p, ql, li, bt, **kw)
        li, bt = rest[:2]
        return paged_decode_attention_stacked(q, kc, vc, p, li, bt, **kw)

    fn = _shard_mapped(_local, mesh, rules, in_logical, _DECODE_Q)
    return fn(*operands)


def _flash_decoding_step(q, k_new, v_new, k_cache, v_cache, positions,
                         args: ModelArchArgs, mesh, rules):
    """KV-sequence-sharded decode step (flash decoding): write + attend in one
    shard_map.

    ≈ reference flash decoding (`modules/flashdecode/utils.py:11-58`,
    `attention_base.py:2171-2188`): the KV cache's sequence dim is sharded over the
    ``cp`` mesh axis; the shard owning each row's position writes the fresh K/V, and
    every shard computes attention over its local KV range — the partial softmaxes
    merge with a log-sum-exp reduction (pmax + psum over cp), so decode attention
    time and per-chip cache memory both scale 1/cp with context length.
    Returns (attn (B, n_q, T, D), k_cache, v_cache)."""
    from ..parallel.mesh import AXIS_CP
    from ..parallel.sharding import DEFAULT_RULES, logical_to_spec

    r = dict(rules or DEFAULT_RULES)
    d = q.shape[-1]
    t = q.shape[2]
    scale = args.attention_scale if args.attention_scale is not None else d ** -0.5

    def _local(q, kn, vn, kc, vc, pos):
        # all shapes here are PER-SHARD: kc/vc (B', n_kv', S/cp, D), q replicated
        # over cp with its heads sharded over tp
        b, n_q = q.shape[0], q.shape[1]
        n_kv = kc.shape[1]
        rep = n_q // n_kv
        local_s = kc.shape[2]
        base = jax.lax.axis_index(AXIS_CP) * local_s

        def _write(cache, new):
            # per-token scatter: a T-token span may straddle shard boundaries,
            # so each fresh row lands on whichever shard owns ITS position
            def one(row_c, row_n, p0):
                for j in range(t):
                    pj = p0 + j - base
                    ok = (pj >= 0) & (pj < local_s)
                    upd = jax.lax.dynamic_update_slice(
                        row_c, row_n[:, j:j + 1].astype(row_c.dtype),
                        (0, jnp.clip(pj, 0, local_s - 1), 0))
                    row_c = jnp.where(ok, upd, row_c)
                return row_c

            return jax.vmap(one)(cache, new, pos)

        kc = _write(kc, kn)
        vc = _write(vc, vn)

        kv_pos = base + jnp.arange(local_s)[None, None, None, :]
        q_pos = (pos[:, None] + jnp.arange(t)[None, :])[:, None, :, None]
        mask = kv_pos <= q_pos
        if args.sliding_window is not None:
            mask = jnp.logical_and(mask, kv_pos > q_pos - args.sliding_window)
        qg = q.reshape(b, n_kv, rep, t, d)
        s = jnp.einsum("bkrqd,bktd->bkrqt", qg, kc.astype(q.dtype),
                       preferred_element_type=jnp.float32) * scale
        s = jnp.where(mask[:, :, None], s, -jnp.inf)
        m = jnp.max(s, axis=-1, keepdims=True)              # local max
        gm = jax.lax.pmax(m, AXIS_CP)                       # global max
        gm_safe = jnp.where(jnp.isfinite(gm), gm, 0.0)
        p = jnp.where(jnp.isfinite(s), jnp.exp(s - gm_safe), 0.0)
        num = jnp.einsum("bkrqt,bktd->bkrqd", p.astype(q.dtype),
                         vc.astype(q.dtype)).astype(jnp.float32)
        den = jnp.sum(p, axis=-1, keepdims=True)
        num = jax.lax.psum(num, AXIS_CP)
        den = jax.lax.psum(den, AXIS_CP)
        out = (num / jnp.maximum(den, 1e-20)).astype(q.dtype)
        return out.reshape(b, n_q, t, d), kc, vc

    q_spec = logical_to_spec(("decode_batch", "decode_heads", None, None), r)
    new_spec = logical_to_spec(("decode_batch", "decode_kv_heads", None, None), r)
    kv_spec = logical_to_spec(("decode_batch", "decode_kv_heads", "kv_seq", None), r)
    pos_spec = logical_to_spec(("decode_batch",), r)
    fn = shard_map_compat(_local, mesh=mesh,
                          in_specs=(q_spec, new_spec, new_spec, kv_spec,
                                    kv_spec, pos_spec),
                          out_specs=(q_spec, kv_spec, kv_spec))
    return fn(q, k_new, v_new, k_cache, v_cache, positions)


def _sharded_flash_attention(q, k, v, args: ModelArchArgs, mesh, rules, sinks=None,
                             alibi_slopes=None):
    """Run the Pallas flash kernel with heads local per shard.

    Pallas calls have no GSPMD partitioning rule, so under a mesh the kernel is wrapped
    in `shard_map` over (batch->dp, heads->tp): each shard runs the kernel on its
    local heads — the same SPMD shape as the reference launching one NKI kernel per
    core (`attention_base.py:121-125`).
    """
    from ..ops.flash_attention import flash_attention

    interpret = jax.default_backend() == "cpu"   # CPU runs (tests) interpret the kernel
    xl, xo, kw_names = _head_extras(sinks, alibi_slopes, "heads")
    in_logical = [("batch", "heads", None, None),
                  ("batch", "kv_heads", None, None),
                  ("batch", "kv_heads", None, None)] + xl
    operands = [q, k, v] + xo

    def _local(q, k, v, *extras):
        kw = dict(zip(kw_names, extras))
        return flash_attention(q, k, v, causal=True, scale=args.attention_scale,
                               window=args.sliding_window,
                               soft_cap=args.logits_soft_cap,
                               interpret=interpret, **kw)

    fn = _shard_mapped(_local, mesh, rules, in_logical,
                       ("batch", "heads", None, None))
    return fn(*operands)


def _decoder_layer(
    lp: Params,
    args: ModelArchArgs,
    h: jnp.ndarray,              # (B, S, H)
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    mask: jnp.ndarray,           # (B, 1, S, S_kv) True=attend
    k_cache: jnp.ndarray,        # (B, H_kv, S_cache, D)
    v_cache: jnp.ndarray,
    positions: Optional[jnp.ndarray],  # (B,) decode write positions; None for prefill
    decode_bucket: Optional[int],      # static; None for prefill (attend over fresh k/v)
    mesh,
    rules=None,
    use_flash: bool = False,
    paged: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None,  # (block_table, slot_mapping)
    cache_batch_start=0,
    adapter_ids: Optional[jnp.ndarray] = None,   # (B,) multi-LoRA slots
    ring_positions: Optional[jnp.ndarray] = None,  # (B, S) positions -> ring attention
    window_row=None,   # traced scalar: dense windowed-prefill cache batch row
    # traced scalar: decode over the STACKED cache via the Pallas kernels
    # (k_cache/v_cache then carry the full (L, B, H, S, D) arrays)
    stacked_layer_idx=None,
    # with stacked_layer_idx: (block_table, slot_mapping) — the stacked cache is
    # PAGED (L, NB, H, BS, D) and the Pallas ragged paged kernels serve the step
    paged_stacked=None,
    # (B,) per-row live query counts: MIXED-STEP ragged serving (decode rows
    # q=1 + prefill-chunk rows q<=T in one dispatch); kernel path only
    q_lens: Optional[jnp.ndarray] = None,
    # (B,) true row lengths: prefill writes into a rolling window cache (the layer's
    # cache stack is W wide; see kvcache.write_prefill_rolling)
    rolling_lengths: Optional[jnp.ndarray] = None,
    # (B,) kernel-decode write slots when they differ from the attend positions —
    # rolling sliding stacks write at (p mod W) while attending length-aware at
    # min(p, W-1) (see _run_stack_pattern_decode_kernel)
    write_positions: Optional[jnp.ndarray] = None,
    flash_decoding: bool = False,   # KV-seq-sharded decode over the cp axis
    attn_bias: Optional[jnp.ndarray] = None,   # additive attention bias (ALiBi)
    alibi_slopes: Optional[jnp.ndarray] = None,  # (Hq,) — kernel paths compute the
                                                 # bias in-kernel from these
    # static fp8 KV scales for THIS layer: (σ_k (Hkv,), σ_v (Hkv,)) fp32. The cache
    # stores K/σ_k and V/σ_v; σ_k folds into q and σ_v into the attention output —
    # exact math, so every attend path (jnp / Pallas dense / paged / ring / flash)
    # serves scaled caches unchanged. ≈ reference static-scale fp8 KV.
    kv_scales: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None,
):
    rm = args.residual_multiplier          # granite branch scaling (1.0 = no-op)
    # sharded-residual layout (sequence parallelism): prefill residuals shard
    # over seq (act_seq: (cp, tp)); decode residuals (T≈1) shard over hidden
    # (act_embed: tp). Both rules default to None, making this the exact
    # replicated layout of before. ``ov`` additionally routes the dense
    # projections through the overlap-scheduled collective matmuls.
    resid_logical = (("batch", "act_seq", None) if positions is None
                     else ("batch", None, "act_embed"))
    ov = overlap_lib.layer_phase(args, mesh, rules,
                                 decode=positions is not None)
    resid = h
    hn = (_norm(h, lp["ln1"], args, lp.get("ln1_b")) if args.pre_norms else h)
    q, k, v = _project_qkv(lp, args, hn, adapter_ids, mesh=mesh, rules=rules,
                           ov=ov)
    if positions is None:
        # prefill activations shard along seq over cp (sequence/context parallelism,
        # ≈ SP reduce-scatter + CP seq shards, `model_base.py:1509-1560`); no-op at cp=1
        q = constrain(q, ("batch", "heads", "seq", None), rules, mesh=mesh)
        k = constrain(k, ("batch", "kv_heads", "seq", None), rules, mesh=mesh)
        v = constrain(v, ("batch", "kv_heads", "seq", None), rules, mesh=mesh)
    else:
        # decode attention layout: identical to prefill by default; under
        # attention-DP the decode_* rules remap batch over (dp, tp) with replicated
        # kv heads (GSPMD inserts the region-boundary all-to-alls)
        q = constrain(q, ("decode_batch", "decode_heads", None, None), rules,
                      mesh=mesh)
        k = constrain(k, ("decode_batch", "decode_kv_heads", None, None), rules,
                      mesh=mesh)
        v = constrain(v, ("decode_batch", "decode_kv_heads", None, None), rules,
                      mesh=mesh)
    q, k = _apply_rope(args, q, k, cos, sin)
    if args.qk_norm and args.qk_norm_scope == "head" and args.qk_norm_after_rope:
        q, k = _head_qk_norm(lp, args, q, k)   # hunyuan post-rope q/k norm

    if kv_scales is not None:
        # static fp8 scale fold: write K̂ = K/σ_k (the cast to the fp8 cache dtype
        # happens at the write sites below), attend with q̂ = q·σ_k — so
        # q̂·K̂ = q·K exactly; the matching σ_v un-fold multiplies the attention
        # output (just before each o-projection)
        sk, sv = kv_scales
        n_rep_s = q.shape[1] // k.shape[1]
        k = k / sk[None, :, None, None].astype(k.dtype)
        v = v / sv[None, :, None, None].astype(v.dtype)
        dt = jnp.dtype(k_cache.dtype)
        if dt.itemsize == 1 and dt.kind != "i":   # fp8 dtypes report kind 'V'
            # fp8 cache: saturate instead of overflowing to NaN — calibration sets
            # σ from sample absmax, and serving values can exceed it slightly
            import ml_dtypes

            fmax = float(ml_dtypes.finfo(dt).max)
            k = jnp.clip(k, -fmax, fmax)
            v = jnp.clip(v, -fmax, fmax)
        q = q * jnp.repeat(sk, n_rep_s)[None, :, None, None].astype(q.dtype)
        _sv_unfold = jnp.repeat(sv, n_rep_s)[None, :, None, None]
    else:
        _sv_unfold = None

    if stacked_layer_idx is not None:
        # kernel decode path: the stacked cache is carried whole (never sliced or
        # re-stacked by scan) — write the step's rows with a DMA scatter. Short
        # buckets then attend with jnp over one dynamic layer slice (profiling: the
        # slice read is ~0.1ms and the attend fuses well; the Pallas attend's
        # per-cell overhead only pays off once length-aware reads skip real
        # bandwidth, i.e. long buckets).
        sinks_arr = lp.get("sinks") if args.attn_sinks else None
        if paged_stacked is not None:
            # ragged paged serving: block-table-indexed write + length-aware
            # attend. Decode rows (uniform q_len <= 8) take the FUSED
            # append+attend kernel — ONE pallas call per layer instead of a
            # write dispatch plus an attend that re-reads the just-written
            # block; mixed steps (q_lens) keep the separate kernels (the
            # chunk-length write is the t > 8 one-RMW-per-window path)
            block_table, slot_mapping = paged_stacked
            if (q_lens is None and q.shape[2] <= 8 and _paged_fused_enabled()):
                attn, k_cache, v_cache = _sharded_paged_fused(
                    q, k_cache, v_cache, k, v, positions, slot_mapping,
                    stacked_layer_idx, block_table, args, mesh, rules,
                    sinks=sinks_arr, alibi_slopes=alibi_slopes)
            else:
                k_cache, v_cache = _sharded_paged_kv_write(
                    k_cache, v_cache, k, v, slot_mapping, stacked_layer_idx,
                    mesh, rules)
                attn = _sharded_paged_attend(q, k_cache, v_cache, positions,
                                             stacked_layer_idx, block_table,
                                             args, mesh, rules,
                                             sinks=sinks_arr,
                                             alibi_slopes=alibi_slopes,
                                             q_lens=q_lens)
        else:
            wp = positions if write_positions is None else write_positions
            k_cache, v_cache = _sharded_kv_write(
                k_cache, v_cache, k, v, wp, stacked_layer_idx, mesh, rules)
            if decode_bucket >= _stacked_attend_min_bucket():
                attn = _sharded_decode_attend(q, k_cache, v_cache, positions,
                                              stacked_layer_idx, decode_bucket,
                                              args, mesh, rules, sinks=sinks_arr,
                                              alibi_slopes=alibi_slopes)
            else:
                sizes = (1,) + k_cache.shape[1:3] + (decode_bucket,
                                                     k_cache.shape[4])
                start = (stacked_layer_idx, 0, 0, 0, 0)
                k_att = jax.lax.dynamic_slice(k_cache, start, sizes)[0]
                v_att = jax.lax.dynamic_slice(v_cache, start, sizes)[0]
                bias = None
                if alibi_slopes is not None:
                    t_q = q.shape[2]
                    q_pos = (positions[:, None] + jnp.arange(t_q)[None, :]
                             )[:, None, :, None]
                    kv_pos = jnp.arange(decode_bucket)[None, None, None, :]
                    bias = _alibi_bias(alibi_slopes, q_pos, kv_pos)
                attn = attend(q, k_att.astype(q.dtype), v_att.astype(q.dtype),
                              mask=mask, scale=args.attention_scale,
                              logits_soft_cap=args.logits_soft_cap,
                              sinks=sinks_arr, bias=bias)
        if _sv_unfold is not None:
            attn = attn * _sv_unfold.astype(attn.dtype)
        attn = attn.transpose(0, 2, 1, 3).reshape(h.shape[0], h.shape[1], args.q_size)
        attn_out = _o_proj(lp, args, attn, mesh, rules, ov, adapter_ids,
                           resid_logical)
        if args.sandwich_norms:
            attn_out = _norm(attn_out, lp["ln1_post"], args)
        if args.parallel_residual:
            mlp_in = (hn if args.shared_ln
                      else _norm(resid, lp["ln2"], args, lp.get("ln2_b")))
            ffn = _mlp(lp, args, mlp_in, mesh, rules, adapter_ids, ov=ov)
            h = resid + rm * attn_out + rm * constrain(ffn, resid_logical, rules,
                                             mesh=mesh)
            return h, k_cache, v_cache
        h = resid + rm * attn_out

        resid = h
        hn = (_norm(h, lp["ln2"], args, lp.get("ln2_b")) if args.pre_norms else h)
        if args.moe is not None:
            ffn = moe_block(lp, args, hn, mesh, rules,
                            _ACTIVATIONS[args.activation],
                            decode=positions is not None)
        else:
            ffn = _mlp(lp, args, hn, mesh, rules, adapter_ids, ov=ov)
        mlp_out = constrain(ffn, resid_logical, rules, mesh=mesh)
        if args.sandwich_norms:
            mlp_out = _norm(mlp_out, lp["ln2_post"], args)
        h = resid + rm * mlp_out
        return h, k_cache, v_cache

    if flash_decoding and positions is not None:
        attn, k_cache, v_cache = _flash_decoding_step(
            q, k, v, k_cache, v_cache, positions, args, mesh, rules)
        if _sv_unfold is not None:
            attn = attn * _sv_unfold.astype(attn.dtype)
        attn = attn.transpose(0, 2, 1, 3).reshape(h.shape[0], h.shape[1], args.q_size)
        attn_out = _o_proj(lp, args, attn, mesh, rules, ov, adapter_ids,
                           resid_logical)
        h = resid + rm * attn_out
        resid = h
        hn = (_norm(h, lp["ln2"], args, lp.get("ln2_b")) if args.pre_norms else h)
        if args.moe is not None:
            ffn = moe_block(lp, args, hn, mesh, rules,
                            _ACTIVATIONS[args.activation],
                            decode=positions is not None)
        else:
            ffn = _mlp(lp, args, hn, mesh, rules, adapter_ids, ov=ov)
        h = resid + rm * constrain(ffn, resid_logical, rules, mesh=mesh)
        return h, k_cache, v_cache

    if paged is not None:
        # paged cache: scatter at flat slots; reads gather through the block table
        block_table, slot_mapping = paged
        k_cache = block_kvcache.write_slots(k_cache, k, slot_mapping)
        v_cache = block_kvcache.write_slots(v_cache, v, slot_mapping)
        if positions is None:
            k_att, v_att = k, v     # prefill attends over the fresh tokens only
        else:
            k_att = block_kvcache.read_seq(k_cache, block_table)
            v_att = block_kvcache.read_seq(v_cache, block_table)
    elif positions is not None and window_row is not None:
        # dense windowed (chunked) prefill: the T input tokens are a *contiguous
        # prompt window* starting at positions[0], landing at cache batch rows
        # [window_row, window_row+B) — write the window as one contiguous block, then
        # attend over those rows' cache (prior windows + this one). ≈ reference
        # windowed context encoding (`models/model_base.py:918-973`).
        k_cache = kvcache.write_prefill(k_cache, k, start=positions[0],
                                        batch_start=window_row)
        v_cache = kvcache.write_prefill(v_cache, v, start=positions[0],
                                        batch_start=window_row)
        b_rows = k.shape[0]
        k_att = jax.lax.dynamic_slice_in_dim(
            kvcache.read_bucket(k_cache, decode_bucket), window_row, b_rows, axis=0)
        v_att = jax.lax.dynamic_slice_in_dim(
            kvcache.read_bucket(v_cache, decode_bucket), window_row, b_rows, axis=0)
    elif positions is None:
        # prefill: cache write at [0, S), attend over the fresh (unpadded-bucket) k/v.
        # The cache keeps its decode layout (≈ the reference's CP-prefill -> DP/TP-
        # decode KV handover, `kv_cache_manager.py:469-486` — GSPMD reshards at the
        # write instead of remapping kv-head indices by hand). Rolling (sliding-
        # window) layers keep only each row's last W tokens at modular slots.
        if rolling_lengths is not None:
            k_cache = kvcache.write_prefill_rolling(
                k_cache, k, rolling_lengths, batch_start=cache_batch_start)
            v_cache = kvcache.write_prefill_rolling(
                v_cache, v, rolling_lengths, batch_start=cache_batch_start)
        else:
            k_cache = kvcache.write_prefill(k_cache, k,
                                            batch_start=cache_batch_start)
            v_cache = kvcache.write_prefill(v_cache, v,
                                            batch_start=cache_batch_start)
        k_cache = constrain(k_cache, kvcache.CACHE_LOGICAL[1:], rules, mesh=mesh)
        v_cache = constrain(v_cache, kvcache.CACHE_LOGICAL[1:], rules, mesh=mesh)
        k_att, v_att = k, v
    else:
        k_cache = kvcache.write_decode(k_cache, k, positions)
        v_cache = kvcache.write_decode(v_cache, v, positions)
        k_cache = constrain(k_cache, kvcache.CACHE_LOGICAL[1:], rules, mesh=mesh)
        v_cache = constrain(v_cache, kvcache.CACHE_LOGICAL[1:], rules, mesh=mesh)
        k_att = kvcache.read_bucket(k_cache, decode_bucket)
        v_att = kvcache.read_bucket(v_cache, decode_bucket)

    if k_att.dtype != q.dtype:
        # fp8 KV cache (direct-cast mode): dequantize at read for the attention matmuls
        k_att = k_att.astype(q.dtype)
        v_att = v_att.astype(q.dtype)
    if ring_positions is not None and positions is None:
        from ..ops.ring_attention import ring_attention

        attn = ring_attention(q, k_att, v_att, ring_positions, ring_positions,
                              mesh, rules, scale=args.attention_scale,
                              window=args.sliding_window)
    elif use_flash and positions is None:
        attn = _sharded_flash_attention(
            q, k_att, v_att, args, mesh, rules,
            sinks=lp.get("sinks") if args.attn_sinks else None,
            alibi_slopes=alibi_slopes)
    else:
        attn = attend(q, k_att, v_att, mask=mask, scale=args.attention_scale,
                      logits_soft_cap=args.logits_soft_cap,
                      sinks=lp.get("sinks") if args.attn_sinks else None,
                      bias=attn_bias)
    if _sv_unfold is not None:
        attn = attn * _sv_unfold.astype(attn.dtype)
    attn = attn.transpose(0, 2, 1, 3).reshape(h.shape[0], h.shape[1], args.q_size)
    attn_out = _o_proj(lp, args, attn, mesh, rules, ov, adapter_ids,
                       resid_logical)
    if args.sandwich_norms:
        attn_out = _norm(attn_out, lp["ln1_post"], args)
    if args.parallel_residual:
        # GPT-NeoX / phi / falcon-style: attention and MLP both branch off the
        # residual; shared_ln reuses ln1's output as the MLP input
        mlp_in = (hn if args.shared_ln
                  else _norm(resid, lp["ln2"], args, lp.get("ln2_b")))
        ffn = _mlp(lp, args, mlp_in, mesh, rules, adapter_ids, ov=ov)
        h = resid + rm * attn_out + rm * constrain(ffn, resid_logical, rules,
                                         mesh=mesh)
        return h, k_cache, v_cache
    h = resid + rm * attn_out

    resid = h
    hn = (_norm(h, lp["ln2"], args, lp.get("ln2_b")) if args.pre_norms else h)
    if args.moe is not None:
        ffn = moe_block(lp, args, hn, mesh, rules,
                            _ACTIVATIONS[args.activation],
                            decode=positions is not None)
    else:
        ffn = _mlp(lp, args, hn, mesh, rules, adapter_ids, ov=ov)
    mlp_out = constrain(ffn, resid_logical, rules, mesh=mesh)
    if args.sandwich_norms:
        mlp_out = _norm(mlp_out, lp["ln2_post"], args)
    h = resid + rm * mlp_out
    return h, k_cache, v_cache


def _w4_kernel_ok(mesh) -> bool:
    """Static routing for int4 weights: the Pallas w4 matmul has no GSPMD
    partitioning rule, so it runs only on single-device meshes (the bench /
    serving configuration); sharded meshes take the XLA dequant path inside
    w4_apply (correct under GSPMD, slower — multi-chip int4 kernels via
    shard_map are future work)."""
    return mesh is None or mesh.devices.size == 1


def _split_w4_stacks(tree):
    """Pull int4-packed {"q4","s"} leaves OUT of the scan xs: their stacked
    payload must reach the Pallas kernel whole (an xs slice feeding a
    pallas_call materializes a per-layer copy — exactly the traffic int4
    exists to avoid; see ops/w4.py). Returns (stripped_tree, [(path, leaf)])."""
    from ..ops.w4 import is_w4

    found = []

    def walk(node, path):
        if isinstance(node, dict):
            if is_w4(node):
                found.append((path, node))
                return None
            return {k: walk(v, path + (k,)) for k, v in node.items()}
        return node

    return walk(tree, ()), found


def _merge_w4_stacks(lp, w4_stacks, li, use_kernel):
    """Re-attach the full stacked w4 leaves (plus the in-scan layer index and
    the static kernel-vs-dequant routing flag) into a sliced layer-param tree."""
    if not w4_stacks:
        return lp

    def insert(node, path, leaf):
        node = dict(node)
        if len(path) == 1:
            node[path[0]] = leaf
        else:
            node[path[0]] = insert(node[path[0]], path[1:], leaf)
        return node

    for path, leaf in w4_stacks:
        lp = insert(lp, path, {**leaf, "layer": li, "use_kernel": use_kernel})
    return lp


def _scan_layers(stack_params, k_stack, v_stack, h, step, *, cache_mode="xs",
                 kv_scale_stacks=None, layer_indices=None,
                 capture_layers: Optional[Tuple[int, ...]] = None,
                 deepstack: Optional[jnp.ndarray] = None,
                 allow_hidden_tap: bool = False, mesh=None):
    """THE layer-stack scan driver — every runner below is a thin strategy wrapper.

    ``step(h, lp, kc, vc, li, kv_scales) -> (new_h, kc, vc)`` is the per-layer
    attention/MLP strategy: it closes over rope tables / masks / mesh and calls
    `_decoder_layer` with its path-specific kwargs. The driver owns everything the
    six pre-consolidation runners duplicated: the `lax.scan` scaffolding, the
    cache plumbing per ``cache_mode``, the fp8 KV-scale gather, the EAGLE3
    capture buffers (selection happens inside the scan with one carried buffer
    per index, so no (L, B, S, H) stack materializes — ≈ reference target-hidden
    capture, `models/model_base.py:1429-1432`), the DeepStack adds, and the
    hidden-stack tensor-capture tap.

    cache_mode:
      "xs"          — k/v stacks slice per layer through scan xs and re-stack
                      through ys (generic prefill/decode path).
      "carry"       — k/v stacks ride the scan carry WHOLE; step receives the
                      full stacked arrays (the Pallas kernels index layer ``li``
                      in-kernel via aliased writes — no slice/re-stack copies).
      "carry_slice" — stacks ride the carry whole; the driver hands step a
                      per-layer dynamic slice and writes it back (paged gather:
                      the xs/ys path would stack a second full block-pool copy
                      for the ys output and OOM at serving scale).

    Returns ``(h, k_new, v_new, caps)`` with ``caps`` a list of captured hidden
    states (empty unless ``capture_layers``)."""
    stack_params, w4_stacks = _split_w4_stacks(stack_params)
    w4_kernel = _w4_kernel_ok(mesh)
    n = len(jax.tree.leaves(stack_params)[0])
    li_all = (jnp.arange(n, dtype=jnp.int32) if layer_indices is None
              else layer_indices)
    has_scales = kv_scale_stacks is not None
    caps0 = tuple(jnp.zeros_like(h) for _ in (capture_layers or ()))
    from ..utils import tensor_capture as _tc

    if allow_hidden_tap and cache_mode != "xs":
        raise ValueError("hidden_stack capture requires cache_mode='xs' (the "
                         "carry modes never stack per-layer hidden states)")
    want_hidden = (allow_hidden_tap and _tc._ACTIVE.get() is not None
                   and _tc._ACTIVE.get().wants("hidden_stack"))

    def _post(caps, li, new_h):
        # capture BEFORE deepstack: EAGLE3 conditions on the raw layer output
        if capture_layers:
            caps = tuple(jnp.where(li == idx, new_h, buf)
                         for idx, buf in zip(capture_layers, caps))
        if deepstack is not None:
            # DeepStack (qwen3-vl): intermediate vision features add into the
            # first K layers' outputs at image-token positions (pre-scattered)
            for k_i in range(deepstack.shape[0]):
                new_h = new_h + jnp.where(li == k_i, deepstack[k_i], 0.0)
        return caps, new_h

    # w4 stacks are indexed by RUN-LOCAL position (the stacks were sliced to
    # this scan's layers), while ``li`` may be a GLOBAL cache-layer index
    # (pattern runners) — carry a separate local arange for the merge
    w4_li = jnp.arange(n, dtype=jnp.int32)

    if cache_mode == "xs":
        xs = (stack_params, k_stack, v_stack, li_all, w4_li)
        if has_scales:
            xs = xs + tuple(kv_scale_stacks)

        def body(carry, layer_xs):
            carry_h, caps = carry
            if has_scales:
                lp, kc, vc, li, wli, sk, sv = layer_xs
                kvs = (sk, sv)
            else:
                lp, kc, vc, li, wli = layer_xs
                kvs = None
            lp = _merge_w4_stacks(lp, w4_stacks, wli, w4_kernel)
            new_h, kc, vc = step(carry_h, lp, kc, vc, li, kvs)
            caps, new_h = _post(caps, li, new_h)
            ys = (kc, vc) + ((new_h,) if want_hidden else ())
            return (new_h, caps), ys

        (h, caps), ys = jax.lax.scan(body, (h, caps0), xs)
        k_new, v_new = ys[0], ys[1]
        if want_hidden:
            from ..utils.tensor_capture import tap

            tap("hidden_stack", ys[2])  # (L, B, S, H) per-layer hidden states
        return h, k_new, v_new, list(caps)

    def body(carry, xs):
        carry_h, ck, cv, caps = carry
        lp, li, wli = xs
        lp = _merge_w4_stacks(lp, w4_stacks, wli, w4_kernel)
        kvs = ((jnp.take(kv_scale_stacks[0], li, axis=0),
                jnp.take(kv_scale_stacks[1], li, axis=0)) if has_scales else None)
        if cache_mode == "carry_slice":
            kc = jax.lax.dynamic_index_in_dim(ck, li, 0, keepdims=False)
            vc = jax.lax.dynamic_index_in_dim(cv, li, 0, keepdims=False)
            new_h, kc, vc = step(carry_h, lp, kc, vc, li, kvs)
            ck = jax.lax.dynamic_update_index_in_dim(ck, kc, li, 0)
            cv = jax.lax.dynamic_update_index_in_dim(cv, vc, li, 0)
        else:
            new_h, ck, cv = step(carry_h, lp, ck, cv, li, kvs)
        caps, new_h = _post(caps, li, new_h)
        return (new_h, ck, cv, caps), ()

    # measured on-chip (round 3): unrolling this scan (lax.scan unroll>1) is
    # ~8x SLOWER (128 ms/step at unroll=8 vs 16.5) — the per-layer Pallas write
    # kernel calls serialize badly when unrolled; keep the rolled loop
    (h, k_new, v_new, caps), _ = jax.lax.scan(
        body, (h, k_stack, v_stack, caps0), (stack_params, li_all, w4_li))
    return h, k_new, v_new, list(caps)


def _cache_scales(cache):
    return ((cache["k_scale"], cache["v_scale"]) if "k_scale" in cache else None)


def _run_stack(params: Params, args: ModelArchArgs, h, cos, sin, mask, cache,
               positions, decode_bucket, mesh, rules, use_flash=False,
               paged=None, cache_batch_start=0,
               adapter_ids=None, ring_positions=None, window_row=None,
               capture_layers: Optional[Tuple[int, ...]] = None,
               deepstack: Optional[jnp.ndarray] = None, flash_decoding=False,
               attn_bias=None, alibi_slopes=None):
    """Generic layer scan (xs/ys cache plumbing) — see `_scan_layers`."""
    def step(carry_h, lp, kc, vc, li, kvs):
        return _decoder_layer(lp, args, carry_h, cos, sin, mask, kc, vc,
                              positions, decode_bucket, mesh, rules,
                              use_flash=use_flash, paged=paged,
                              cache_batch_start=cache_batch_start,
                              adapter_ids=adapter_ids,
                              ring_positions=ring_positions,
                              window_row=window_row,
                              flash_decoding=flash_decoding,
                              attn_bias=attn_bias, alibi_slopes=alibi_slopes,
                              kv_scales=kvs)

    with jax.named_scope("layer_stack"):   # dispatch annotation (device traces)
        h, k_new, v_new, caps = _scan_layers(
            params["layers"], cache["k"], cache["v"], h, step, cache_mode="xs",
            kv_scale_stacks=_cache_scales(cache), capture_layers=capture_layers,
            deepstack=deepstack, allow_hidden_tap=True, mesh=mesh)
    # preserve auxiliary cache entries (e.g. M-RoPE rope_delta) alongside k/v
    out_cache = {**cache, "k": k_new, "v": v_new}
    if capture_layers:
        return h, out_cache, caps
    return h, out_cache


def _segment_runs(flags: Tuple[bool, ...]):
    """Contiguous runs of equal flag: [(flag, global_start, run_len, kind_local_start)]
    — the scan grouping for per-layer attention patterns (same shape as the llama4
    dense/MoE interleave)."""
    runs = []
    counts = {True: 0, False: 0}
    i = 0
    while i < len(flags):
        j = i
        while j < len(flags) and flags[j] == flags[i]:
            j += 1
        runs.append((flags[i], i, j - i, counts[flags[i]]))
        counts[flags[i]] += j - i
        i = j
    return runs


def _run_stack_pattern(params: Params, args: ModelArchArgs, h, ctx_full, ctx_slide,
                       cache, positions, decode_bucket, mesh, rules,
                       use_flash=False, cache_batch_start=0, adapter_ids=None,
                       true_lengths=None):
    """Layer scan for per-layer attention patterns (gemma3/gpt-oss sliding/full
    interleave): contiguous same-kind runs are scanned together, each against its own
    cache stack — full layers over the (L_full, B, H, S_max, D) stack, sliding layers
    over the **rolling** (L_slide, B, H, W, D) stack with modular positions. Each
    run's RoPE tables / mask / window are static, ≈ the reference's per-layer cache
    sizes + SWA masks (`kv_cache_manager.py:199-237`, `model_base.py:287-363`).

    ctx_full / ctx_slide: (cos, sin, mask) for each kind. ``true_lengths`` drives the
    rolling prefill write (which keeps only each row's last W tokens)."""
    import dataclasses as _dc

    flags = tuple(kind == "sliding" for kind in args.layer_pattern)
    runs = _segment_runs(flags)
    w_alloc = cache["k_sliding"].shape[3]
    args_full = _dc.replace(args, sliding_window=None, layer_pattern=None)
    args_slide = _dc.replace(args, layer_pattern=None)
    parts = {True: [], False: []}      # per-kind (k_run, v_run) in kind-local order

    for is_slide, g0, n, l0 in runs:
        stack = jax.tree.map(lambda x: x[g0 : g0 + n], params["layers"])
        if is_slide:
            a_run = args_slide
            cos_i, sin_i, mask_i = ctx_slide
            kc_stack = cache["k_sliding"][l0 : l0 + n]
            vc_stack = cache["v_sliding"][l0 : l0 + n]
            pos_run = positions % w_alloc if positions is not None else None
            bucket_run = w_alloc if positions is not None else None
            rl = true_lengths if positions is None else None
        else:
            a_run = args_full
            cos_i, sin_i, mask_i = ctx_full
            kc_stack = cache["k"][l0 : l0 + n]
            vc_stack = cache["v"][l0 : l0 + n]
            pos_run = positions
            bucket_run = decode_bucket
            rl = None

        def step(carry_h, lp, kc, vc, li, kvs, _a=a_run, _cos=cos_i, _sin=sin_i,
                 _mask=mask_i, _pos=pos_run, _bucket=bucket_run, _rl=rl):
            return _decoder_layer(lp, _a, carry_h, _cos, _sin, _mask, kc, vc,
                                  _pos, _bucket, mesh, rules,
                                  use_flash=use_flash,
                                  cache_batch_start=cache_batch_start,
                                  adapter_ids=adapter_ids,
                                  rolling_lengths=_rl)

        h, ks, vs, _ = _scan_layers(stack, kc_stack, vc_stack, h, step,
                                    cache_mode="xs", mesh=mesh)
        parts[is_slide].append((ks, vs))

    out = dict(cache)
    if parts[False]:
        out["k"] = jnp.concatenate([p[0] for p in parts[False]], axis=0)
        out["v"] = jnp.concatenate([p[1] for p in parts[False]], axis=0)
    if parts[True]:
        out["k_sliding"] = jnp.concatenate([p[0] for p in parts[True]], axis=0)
        out["v_sliding"] = jnp.concatenate([p[1] for p in parts[True]], axis=0)
    return h, out


def _run_stack_paged_gather(params: Params, args: ModelArchArgs, h, cos, sin,
                            mask, cache, positions, decode_bucket, block_table,
                            slot_mapping, mesh, rules, adapter_ids=None,
                            attn_bias=None):
    """Paged gather-path layer scan with the block pool as a scan CARRY.

    The generic `_run_stack` feeds the pool through scan xs/ys, which stacks a
    full second copy of the (L, NB, H, BS, D) pool for the ys output — at
    bs=64 x 32 layers that is +4.4 GB and OOMs the chip (measured: the paged
    insert graph hit 16.23/15.75 GB HBM). Carrying the pool and updating one
    layer per step via dynamic_update_index keeps the peak at pool + one
    transient layer slice. Used by the paged INSERT (wide prefix-prefill
    queries) and any paged decode the Pallas kernel declines."""
    def step(carry_h, lp, kc, vc, li, kvs):
        return _decoder_layer(lp, args, carry_h, cos, sin, mask, kc, vc,
                              positions, decode_bucket, mesh, rules,
                              paged=(block_table, slot_mapping),
                              adapter_ids=adapter_ids,
                              attn_bias=attn_bias, kv_scales=kvs)

    h, k_new, v_new, _ = _scan_layers(
        params["layers"], cache["k"], cache["v"], h, step,
        cache_mode="carry_slice", kv_scale_stacks=_cache_scales(cache),
        mesh=mesh)
    return h, {**cache, "k": k_new, "v": v_new}


def _run_stack_pattern_decode_kernel(params: Params, args: ModelArchArgs, h,
                                     ctx_full, ctx_slide, cache, positions,
                                     decode_bucket, mesh, rules,
                                     adapter_ids=None):
    """Kernel decode for per-layer attention patterns (gemma3/gpt-oss-class
    sliding/full interleaves) — VERDICT r3 #7.

    Both cache stacks ride their runs' scans as CARRIES (no per-layer slice /
    re-stack copies). Full runs take the standard stacked path. Sliding runs use
    ROLLING semantics: the W-slot stack writes at ``p mod W`` and attends
    length-aware over ``min(p+1, W)`` slots with NO window mask — a rolled
    window holds exactly the last ``min(p+1, W)`` positions (w_alloc =
    min(seq_len, window), kvcache.rolling_width) and attention is
    permutation-invariant over key slots, so slot order never matters.
    ≈ the reference's sliding-window TKG kernel strategy
    (`modules/sliding_window/attention.py`, `attention_base.py:1483-1677`)."""
    import dataclasses as _dc

    flags = tuple(kind == "sliding" for kind in args.layer_pattern)
    runs = _segment_runs(flags)
    w_alloc = cache["k_sliding"].shape[3]
    args_plain = _dc.replace(args, sliding_window=None, layer_pattern=None)
    ck, cv = cache["k"], cache["v"]
    cks, cvs = cache["k_sliding"], cache["v_sliding"]

    for is_slide, g0, n, l0 in runs:
        stack = jax.tree.map(lambda x: x[g0 : g0 + n], params["layers"])
        li = l0 + jnp.arange(n, dtype=jnp.int32)
        if is_slide:
            cos_i, sin_i, mask_i = ctx_slide
            pos_attend = jnp.minimum(positions, w_alloc - 1)
            pos_write = positions % w_alloc
            bucket_run = w_alloc
            carry_k, carry_v = cks, cvs
        else:
            cos_i, sin_i, mask_i = ctx_full
            pos_attend, pos_write = positions, None
            bucket_run = decode_bucket
            carry_k, carry_v = ck, cv

        def step(carry_h, lp, kk, vv, li_j, kvs, _cos=cos_i, _sin=sin_i,
                 _mask=mask_i, _pa=pos_attend, _pw=pos_write, _bucket=bucket_run):
            return _decoder_layer(lp, args_plain, carry_h, _cos, _sin,
                                  _mask, kk, vv, _pa, _bucket, mesh, rules,
                                  adapter_ids=adapter_ids,
                                  stacked_layer_idx=li_j,
                                  write_positions=_pw)

        h, carry_k, carry_v, _ = _scan_layers(stack, carry_k, carry_v, h, step,
                                              cache_mode="carry",
                                              layer_indices=li, mesh=mesh)
        if is_slide:
            cks, cvs = carry_k, carry_v
        else:
            ck, cv = carry_k, carry_v

    return h, {**cache, "k": ck, "v": cv, "k_sliding": cks, "v_sliding": cvs}


def _run_stack_decode_kernel(params: Params, args: ModelArchArgs, h, cos, sin, mask,
                             cache, positions, decode_bucket, mesh, rules,
                             adapter_ids=None, alibi_slopes=None):
    """Decode layer scan for the Pallas stacked-cache path.

    The cache rides the scan as a CARRY (full stacked arrays, updated in place by the
    aliased write kernel); only the layer params are scan xs. This removes the
    per-layer cache slice (xs) and re-stack (ys) copies the generic _run_stack pays."""
    def step(carry_h, lp, ck, cv, li, kvs):
        return _decoder_layer(lp, args, carry_h, cos, sin, mask, ck, cv,
                              positions, decode_bucket, mesh, rules,
                              adapter_ids=adapter_ids, stacked_layer_idx=li,
                              alibi_slopes=alibi_slopes, kv_scales=kvs)

    h, k_new, v_new, _ = _scan_layers(
        params["layers"], cache["k"], cache["v"], h, step, cache_mode="carry",
        kv_scale_stacks=_cache_scales(cache), mesh=mesh)
    return h, {**cache, "k": k_new, "v": v_new}


def _run_stack_paged_kernel(params: Params, args: ModelArchArgs, h, cos, sin,
                            cache, positions, block_table, slot_mapping, mesh,
                            rules, adapter_ids=None, alibi_slopes=None,
                            q_lens=None):
    """Decode layer scan for the Pallas ragged paged path (continuous batching).

    The paged cache (L, NB, H, BS, D) rides the scan as a CARRY — the block pool is
    never sliced per layer (the gather path's per-layer xs/ys copies scale with the
    whole pool, not the live tokens). Per layer: block-table RMW write + ragged
    length-aware attend (with ``q_lens``: the mixed-step variable-q_len attend).
    ≈ the reference's paged TKG hot path
    (`block_kv_cache_manager.py:268-374` + `attention_base.py:1483-1677`)."""
    def step(carry_h, lp, ck, cv, li, kvs):
        return _decoder_layer(
            lp, args, carry_h, cos, sin, None, ck, cv, positions, None, mesh,
            rules, adapter_ids=adapter_ids, stacked_layer_idx=li,
            paged_stacked=(block_table, slot_mapping), alibi_slopes=alibi_slopes,
            kv_scales=kvs, q_lens=q_lens)

    h, k_new, v_new, _ = _scan_layers(
        params["layers"], cache["k"], cache["v"], h, step, cache_mode="carry",
        kv_scale_stacks=_cache_scales(cache), mesh=mesh)
    return h, {**cache, "k": k_new, "v": v_new}


def _embed(params: Params, args: ModelArchArgs, input_ids, mesh, rules):
    # named_scope: dispatch annotation — the phase shows up named in
    # jax.profiler device traces / HLO metadata (utils/profiling.py), so the
    # serving loop's host spans (utils/metrics.ServingTelemetry.annotate)
    # line up against on-device embed/layers/lm_head time
    with jax.named_scope("embed"):
        h = jnp.take(params["embed"], input_ids, axis=0)
        if args.embedding_multiplier != 1.0:
            h = h * jnp.asarray(args.embedding_multiplier, h.dtype)
        return constrain(h, ("batch", None, None), rules, mesh=mesh)


def _lm_head(params: Params, args: ModelArchArgs, h, mesh, rules) -> jnp.ndarray:
    with jax.named_scope("lm_head"):
        if args.tie_word_embeddings:
            logits = (h @ params["embed"].T).astype(jnp.float32)
        else:
            from ..ops.w4 import is_w4

            head = params["lm_head"]
            if is_w4(head):
                # opt-in int4 lm_head (flat 2D leaf, not under the layer scan):
                # attach the same static kernel-vs-dequant routing the scan
                # applies
                head = {**head, "use_kernel": _w4_kernel_ok(mesh)}
            logits = qapply(h, head).astype(jnp.float32)
        if "lm_head_b" in params:           # phi-style biased output head
            logits = logits + params["lm_head_b"].astype(jnp.float32)
        if args.logits_scale != 1.0:    # cohere logit_scale / granite 1/scaling
            logits = logits * args.logits_scale
        if args.final_logits_soft_cap is not None:   # gemma2 final tanh cap
            cap = args.final_logits_soft_cap
            logits = cap * jnp.tanh(logits / cap)
        logical = (("batch", "vocab") if logits.ndim == 2
                   else ("batch", None, "vocab"))
        return constrain(logits, logical, rules, mesh=mesh)


def _finalize_logits(params, args: ModelArchArgs, h, cache, mesh, rules,
                     return_hidden=False, caps=None, skip_logits=False,
                     logit_idx=None):
    """Shared decode epilogue: final norm + lm_head, assembling the
    (logits, cache[, hidden][, captures]) return tuple every decode path shares.

    ``skip_logits`` (static) drops the final norm + lm_head entirely and
    returns ``(None, cache, ...)`` — for KV-only forwards whose logits are
    never read (the last draft step of a speculative iteration runs only so
    its KV lands before a possible full accept; streaming the lm_head and
    materializing a (B, V) logits tensor for it is pure waste).

    ``logit_idx`` ((B,) traced) gathers ONE hidden row per sequence before the
    final norm + lm_head, so only that token pays the vocab projection —
    logits return (B, 1, V). The chunked-insert / mixed-step sampling shape:
    a T-token prefill chunk needs logits only at its last live token
    (materializing (B, T, V) for a 128k vocab is ~131 MB per insert window)."""
    if skip_logits:
        if return_hidden:
            # every other path returns the POST-final-norm hidden; handing a
            # pre-norm hidden out here would silently corrupt e.g. EAGLE
            # conditioning built on it
            raise ValueError("skip_logits does not compose with return_hidden "
                             "(the final norm is skipped along with the "
                             "lm_head, so the hidden would be pre-norm)")
        res = (None, cache)
        if caps is not None:
            res = res + (caps,)
        return res
    if logit_idx is not None:
        if return_hidden:
            raise ValueError("logit_idx does not compose with return_hidden "
                             "(the hidden would be a single gathered row)")
        h = jnp.take_along_axis(h, logit_idx[:, None, None], axis=1)  # (B,1,H)
    h = _norm(h, params["final_norm"], args, params.get("final_norm_b"))
    logits = _lm_head(params, args, h, mesh, rules)
    res = (logits, cache)
    if return_hidden:
        res = res + (h,)
    if caps is not None:
        res = res + (caps,)
    return res


def prefill_forward(
    params: Params,
    args: ModelArchArgs,
    input_ids: jnp.ndarray,       # (B, S) int32, right-padded to the bucket
    position_ids: jnp.ndarray,    # (B, S) int32
    last_token_idx: jnp.ndarray,  # (B,) index of last real token per sequence
    cache: kvcache.KVCache,       # donated
    mesh=None,
    rules=None,
    use_flash: bool = False,
    slot_mapping: Optional[jnp.ndarray] = None,  # (B, S) paged write slots (-1 = drop)
    cache_batch_start=0,          # dense continuous batching: batch row to insert at
    adapter_ids: Optional[jnp.ndarray] = None,   # (B,) multi-LoRA slots
    use_ring: bool = False,       # context-parallel prefill via ring attention
    return_hidden: bool = False,  # also return the full normed hidden states (B, S, H)
    # static layer indices whose output hiddens are captured (EAGLE3 conditioning,
    # ≈ `model_base.py:1429-1432`); appends a list of (B, S, H) to the return
    capture_layers: Optional[Tuple[int, ...]] = None,
    # (K, B, S, H) per-early-layer additive visual features at image positions
    # (DeepStack, qwen3-vl; zeros elsewhere)
    deepstack: Optional[jnp.ndarray] = None,
    # multimodal embed merge: (mask (B, S, 1) bool, override (B, S, H)) — positions
    # where mask is True take the override row (image embeds scattered at image-token
    # positions, ≈ reference image-to-text pipelined vision→CTE merge,
    # `models/image_to_text_model_base.py`)
    merge_embeds: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None,
    # M-RoPE (qwen-vl): replace the 1D-position cos/sin with externally computed
    # multimodal rotary tables (B, S, D); masks/cache writes still use position_ids
    rope_override: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None,
) -> Tuple[jnp.ndarray, kvcache.KVCache]:
    """Context encoding: returns (last-token logits (B, V) fp32, updated cache).

    With ``slot_mapping`` the cache is a paged pytree (see modules/block_kvcache) and
    writes scatter to flat slots; with ``cache_batch_start`` the dense write lands at a
    specific batch row (continuous-batching insert)."""
    from ..utils.tensor_capture import tap

    h = _embed(params, args, input_ids, mesh, rules)
    if args.learned_pos:
        h = h + jnp.take(params["pos_embed"], position_ids + args.pos_offset,
                         axis=0).astype(h.dtype)
    if args.embed_norm:
        h = layer_norm(h, params["embed_ln"], params["embed_ln_b"],
                       eps=args.rms_norm_eps)
    if merge_embeds is not None:
        mm_mask, mm_override = merge_embeds
        h = jnp.where(mm_mask, mm_override.astype(h.dtype), h)
    h = tap("embed", h)
    if rope_override is not None:
        cos, sin = rope_override
    else:
        cos, sin = rope_ops.compute_cos_sin(params["rope_inv_freq"], position_ids,
                                            args.rope_attention_scaling)
    s = input_ids.shape[1]
    mask = (position_ids[:, None, :, None] >= position_ids[:, None, None, :])
    mask = jnp.logical_and(mask, causal_mask(s, s)[None, None])
    kv_pos = position_ids[:, None, None, :]
    q_pos = position_ids[:, None, :, None]
    sliding = (jnp.logical_and(mask, kv_pos > q_pos - args.sliding_window)
               if args.sliding_window is not None else None)
    if args.layer_pattern is not None:
        if slot_mapping is not None or use_ring:
            raise ValueError("paged/ring prefill is not supported for per-layer "
                             "attention patterns (rolling sliding caches)")
        inv_local = params.get("rope_inv_freq_local", params["rope_inv_freq"])
        cos_l, sin_l = rope_ops.compute_cos_sin(inv_local, position_ids,
                                                args.local_rope_attention_scaling)
        h, cache = _run_stack_pattern(
            params, args, h, (cos, sin, mask),
            (cos_l, sin_l, sliding if sliding is not None else mask), cache,
            positions=None, decode_bucket=None, mesh=mesh, rules=rules,
            use_flash=use_flash, cache_batch_start=cache_batch_start,
            adapter_ids=adapter_ids, true_lengths=last_token_idx + 1)
        h = tap("final_hidden", _norm(h, params["final_norm"], args, params.get("final_norm_b")))
        h_last = jnp.take_along_axis(h, last_token_idx[:, None, None], axis=1)[:, 0]
        logits = tap("logits", _lm_head(params, args, h_last, mesh, rules))
        if return_hidden:
            return logits, cache, h
        return logits, cache
    if sliding is not None:
        mask = sliding
    attn_bias = (_alibi_bias(params["alibi_slopes"], q_pos, kv_pos)
                 if args.alibi else None)

    paged = None
    if slot_mapping is not None:
        paged = (jnp.zeros((input_ids.shape[0], 1), dtype=jnp.int32), slot_mapping)
    if use_ring:
        h = constrain(h, ("batch", "seq", None), rules, mesh=mesh)
    out = _run_stack(params, args, h, cos, sin, mask, cache,
                     positions=None, decode_bucket=None, mesh=mesh, rules=rules,
                     use_flash=use_flash,
                     paged=paged, cache_batch_start=cache_batch_start,
                     adapter_ids=adapter_ids,
                     ring_positions=position_ids if use_ring else None,
                     capture_layers=capture_layers, deepstack=deepstack,
                     attn_bias=attn_bias,
                     alibi_slopes=params.get("alibi_slopes") if args.alibi
                     else None)
    h, cache = out[0], out[1]
    h = tap("final_hidden", _norm(h, params["final_norm"], args, params.get("final_norm_b")))
    h_last = jnp.take_along_axis(h, last_token_idx[:, None, None], axis=1)[:, 0]
    logits = tap("logits", _lm_head(params, args, h_last, mesh, rules))
    res = (logits, cache)
    if return_hidden:
        res = res + (h,)
    if capture_layers:
        res = res + (out[2],)
    return res


def decode_forward(
    params: Params,
    args: ModelArchArgs,
    input_ids: jnp.ndarray,      # (B, T) int32 (T = 1, or speculation width)
    position_ids: jnp.ndarray,   # (B,) int32 position of input_ids[:, 0]
    cache: kvcache.KVCache,      # donated
    decode_bucket: Optional[int],  # static: cache slice width (None for paged mode)
    mesh=None,
    rules=None,
    block_table: Optional[jnp.ndarray] = None,   # (B, MB) paged: per-seq block ids
    slot_mapping: Optional[jnp.ndarray] = None,  # (B, T) paged: flat write slots
    adapter_ids: Optional[jnp.ndarray] = None,   # (B,) multi-LoRA slots
    tree: Optional[Tuple[np.ndarray, np.ndarray]] = None,  # (depths (T,), ancestor (T,T))
    return_hidden: bool = False,  # also return the final normed hidden states (B, T, H)
    window_row=None,  # traced scalar: dense windowed prefill at this cache batch row
    use_kernel: bool = False,  # static: Pallas stacked-cache decode (hot path)
    # static: KV-seq-sharded decode over the cp axis (flash decoding); multi-token chains OK, tree/paged unsupported
    flash_decoding: bool = False,
    # static layer indices whose output hiddens are captured (EAGLE3 conditioning)
    capture_layers: Optional[Tuple[int, ...]] = None,
    # static: KV-only forward — skip final norm + lm_head, logits return None
    # (the k-th draft step of a fused speculative iteration)
    skip_logits: bool = False,
    # (B,) per-row live query counts — MIXED-STEP ragged serving (paged only):
    # decode rows carry q_len 1 and prefill-chunk rows up to T in ONE dispatch;
    # tokens at or beyond q_lens[b] are padding (masked attention, slot -1
    # writes expected in slot_mapping)
    q_lens: Optional[jnp.ndarray] = None,
    # (B,) traced: compute logits ONLY at this token index per row (see
    # _finalize_logits); returns (B, 1, V)
    logit_idx: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, kvcache.KVCache]:
    """Token generation: returns (logits (B, T, V) fp32, updated cache).

    Dense mode slices the cache at the static ``decode_bucket``; paged mode
    (``block_table``/``slot_mapping`` given) gathers each row's blocks instead, with the
    attention width set by the table (MB * block_size).

    ``window_row`` switches the call to *dense windowed (chunked) prefill*: the T
    input tokens are a contiguous prompt window at positions [position_ids[0],
    position_ids[0]+T) landing at cache batch rows [window_row, window_row+B) — the
    dense-mode analog of the paged windowed prefill (≈ reference windowed CTE,
    `models/model_base.py:918-973`). All rows share position_ids[0].

    ``tree`` switches the T input tokens from a left-to-right chain to a static token
    tree (Medusa / EAGLE tree verify, ≈ reference tree decoding
    `models/model_base.py:2136-2558`): token i's KV still lands at cache slot
    ``position_ids + i`` (sequential slots), but its RoPE position is
    ``position_ids + depths[i]`` and intra-window attention follows the ancestor mask
    instead of the causal triangle. Cache slots below ``position_ids`` (committed
    context) stay visible to every node."""
    paged = None
    if block_table is not None:
        paged = (block_table, slot_mapping)
        block_size = cache["k"].shape[3]
        decode_bucket = block_table.shape[1] * block_size
    if q_lens is not None and (block_table is None or tree is not None
                               or window_row is not None or flash_decoding):
        raise ValueError("q_lens (mixed-step ragged serving) requires paged "
                         "chain decode (block_table given; no tree/window/"
                         "flash-decoding)")
    b, t = input_ids.shape
    h = _embed(params, args, input_ids, mesh, rules)
    if tree is None:
        pos_grid = position_ids[:, None] + jnp.arange(t)[None, :]  # (B, T)
    else:
        depths, ancestor = tree
        pos_grid = position_ids[:, None] + jnp.asarray(depths, jnp.int32)[None, :]
    if args.learned_pos:
        h = h + jnp.take(params["pos_embed"], pos_grid + args.pos_offset,
                         axis=0).astype(h.dtype)
    if args.embed_norm:
        h = layer_norm(h, params["embed_ln"], params["embed_ln_b"],
                       eps=args.rms_norm_eps)
    rope_pos = pos_grid
    if "rope_delta" in cache:
        # M-RoPE decode: all three position dims advance together past the prompt,
        # collapsing to 1D rope at (kv position + per-row delta)
        rope_pos = pos_grid + cache["rope_delta"][:, None]
    cos, sin = rope_ops.compute_cos_sin(params["rope_inv_freq"], rope_pos,
                                        args.rope_attention_scaling)
    if use_kernel:
        if tree is not None or window_row is not None:
            raise ValueError("use_kernel supports plain chain decode only")
        if args.layer_pattern is not None:
            if paged is not None:
                raise ValueError("paged decode is not supported for per-layer "
                                 "attention patterns (rolling sliding caches)")
            w_alloc = cache["k_sliding"].shape[3]
            if t > 1 and w_alloc < cache["k"].shape[3]:
                raise ValueError(
                    "multi-token decode over a rolling sliding cache is not "
                    "supported (slots written this step would alias older "
                    "positions)")
            inv_local = params.get("rope_inv_freq_local", params["rope_inv_freq"])
            cos_l, sin_l = rope_ops.compute_cos_sin(
                inv_local, pos_grid, args.local_rope_attention_scaling)
            kv_pos_k = jnp.arange(decode_bucket)[None, None, None, :]
            mask_full = kv_pos_k <= pos_grid[:, None, :, None]
            window = (args.sliding_window if args.sliding_window is not None
                      else w_alloc)
            mask_slide = kvcache.rolling_mask(position_ids, t, w_alloc, window)
            h, cache = _run_stack_pattern_decode_kernel(
                params, args, h, (cos, sin, mask_full), (cos_l, sin_l, mask_slide),
                cache, position_ids, decode_bucket, mesh, rules,
                adapter_ids=adapter_ids)
            return _finalize_logits(params, args, h, cache, mesh, rules,
                                    return_hidden, skip_logits=skip_logits,
                                    logit_idx=logit_idx)
        slopes = params.get("alibi_slopes") if args.alibi else None
        if paged is not None:
            # ragged paged serving hot path: Pallas block-table kernels, cache
            # as scan carry (never gathered to the table width)
            h, cache = _run_stack_paged_kernel(
                params, args, h, cos, sin, cache, position_ids, block_table,
                slot_mapping, mesh, rules, adapter_ids=adapter_ids,
                alibi_slopes=slopes, q_lens=q_lens)
            return _finalize_logits(params, args, h, cache, mesh, rules,
                                    return_hidden, skip_logits=skip_logits,
                                    logit_idx=logit_idx)
        kv_pos_k = jnp.arange(decode_bucket)[None, None, None, :]
        mask_k = kv_pos_k <= pos_grid[:, None, :, None]
        if args.sliding_window is not None:
            mask_k = jnp.logical_and(
                mask_k, kv_pos_k > pos_grid[:, None, :, None] - args.sliding_window)
        h, cache = _run_stack_decode_kernel(
            params, args, h, cos, sin, mask_k, cache, positions=position_ids,
            decode_bucket=decode_bucket, mesh=mesh, rules=rules,
            adapter_ids=adapter_ids, alibi_slopes=slopes)
        return _finalize_logits(params, args, h, cache, mesh, rules,
                                return_hidden, skip_logits=skip_logits,
                                logit_idx=logit_idx)
    kv_pos = jnp.arange(decode_bucket)[None, None, None, :]
    q_pos = pos_grid[:, None, :, None]
    if tree is None:
        mask = kv_pos <= q_pos                                     # (B, 1, T, bucket)
        if q_lens is not None:
            # mixed-step ragged rows: tokens at or beyond a row's q_len are
            # padding — fully masked (attend's finite NEG_INF keeps their
            # softmax NaN-free; their outputs are discarded and their KV
            # writes carry slot -1)
            mask = jnp.logical_and(
                mask,
                (jnp.arange(t)[None, :] < q_lens[:, None])[:, None, :, None])
    else:
        # committed-context slots are visible to all nodes; tree slots follow ancestry
        write_start = position_ids[:, None, None, None]            # (B, 1, 1, 1)
        committed = kv_pos < write_start
        rel = kv_pos - write_start                                 # slot idx within tree
        anc = jnp.asarray(ancestor, bool)         # (T, T) static or (B, T, T) traced
        in_tree = jnp.logical_and(rel >= 0, rel < t)
        rel_c = jnp.broadcast_to(jnp.clip(rel, 0, t - 1),
                                 (b, 1, t, rel.shape[-1]))
        anc_b = anc[None, None] if anc.ndim == 2 else anc[:, None]
        tree_vis = jnp.take_along_axis(
            jnp.broadcast_to(anc_b, (b, 1, t, t)), rel_c, axis=3)
        mask = committed | (in_tree & tree_vis)
    sliding = (jnp.logical_and(mask, kv_pos > q_pos - args.sliding_window)
               if args.sliding_window is not None else None)
    if args.layer_pattern is not None:
        if tree is not None or paged is not None or window_row is not None:
            raise ValueError("tree/paged/windowed decode is not supported for "
                             "per-layer attention patterns (rolling sliding caches)")
        w_alloc = cache["k_sliding"].shape[3]
        if t > 1 and w_alloc < cache["k"].shape[3]:
            raise ValueError("multi-token decode over a rolling sliding cache is "
                             "not supported (slots written this step would alias "
                             "older positions in the mask)")
        inv_local = params.get("rope_inv_freq_local", params["rope_inv_freq"])
        cos_l, sin_l = rope_ops.compute_cos_sin(inv_local, pos_grid,
                                                args.local_rope_attention_scaling)
        window = args.sliding_window if args.sliding_window is not None else w_alloc
        mask_slide = kvcache.rolling_mask(position_ids, t, w_alloc, window)
        h, cache = _run_stack_pattern(
            params, args, h, (cos, sin, mask), (cos_l, sin_l, mask_slide), cache,
            positions=position_ids, decode_bucket=decode_bucket, mesh=mesh,
            rules=rules, adapter_ids=adapter_ids)
        return _finalize_logits(params, args, h, cache, mesh, rules,
                                return_hidden, skip_logits=skip_logits,
                                logit_idx=logit_idx)
    if sliding is not None:
        mask = sliding

    if flash_decoding and (tree is not None or paged is not None):
        raise ValueError("flash decoding supports chain decode only (no "
                         "tree/paged); multi-token chains (speculative wide "
                         "verify) are supported")
    attn_bias = (_alibi_bias(params["alibi_slopes"], q_pos, kv_pos)
                 if args.alibi else None)
    if paged is not None and not capture_layers:
        # pool rides as a scan carry — the generic xs/ys path would stack a
        # second full pool copy (OOM at serving scale; see _run_stack_paged_gather)
        h, cache = _run_stack_paged_gather(
            params, args, h, cos, sin, mask, cache, position_ids, decode_bucket,
            block_table, slot_mapping, mesh, rules, adapter_ids=adapter_ids,
            attn_bias=attn_bias)
        return _finalize_logits(params, args, h, cache, mesh, rules,
                                return_hidden, skip_logits=skip_logits,
                                logit_idx=logit_idx)
    out = _run_stack(params, args, h, cos, sin, mask, cache,
                     positions=position_ids, decode_bucket=decode_bucket,
                     mesh=mesh, rules=rules,
                     paged=paged, adapter_ids=adapter_ids,
                     window_row=window_row, capture_layers=capture_layers,
                     flash_decoding=flash_decoding, attn_bias=attn_bias)
    return _finalize_logits(params, args, out[0], out[1], mesh, rules,
                            return_hidden, skip_logits=skip_logits,
                            logit_idx=logit_idx,
                            caps=out[2] if capture_layers else None)
