"""Online knob controller with a decision audit trail (ISSUE-18 tentpole b).

:class:`ServingTuner` closes the observability→control loop: the stack
already MEASURES everything (roofline efficiency, SLO health, dispatch
gaps, queue depth), and this controller acts on those signals by walking
the schedule-only knobs the :mod:`serving.knobs` registry enumerates —
``megastep_k``, ``async_depth``, ``prefill_token_budget``, ``spec_chunk``,
brown-out thresholds, autoscaler bounds. Every knob is schedule-only, so
bit-exactness of every emitted stream is preserved BY CONSTRUCTION however
the controller walks them (the runner applies changes at pipeline-drain
safe points; tests/test_tuner.py pins tokens bit-identical under arbitrary
knob trajectories).

Control discipline (the autoscaler's, generalized):

- **Workload-phase classification** per tick: ``interactive`` (short
  prompts, shallow queue), ``bulk`` (deep queue / high occupancy), or
  ``long_context`` (mean recent prompt length past a threshold). Rules are
  phase-conditioned — the megastep walk-up that wins a decode-heavy bulk
  window is exactly what an interactive burst under SLO pressure walks
  back down.
- **Hysteresis**: a rule must hold for ``up_after``/``down_after``
  consecutive ticks before acting; a ``cooldown_s`` quiet period separates
  actions; at most ONE knob change per tick. ``clock`` is injectable.
- **Never-worse guard**: each change records the measured objective rate
  (tokens/s by default) as its baseline; after ``eval_ticks`` ticks the
  candidate's rate is compared, and a regression past
  ``rollback_tolerance`` rolls the knob back (counted
  ``tuner_rollbacks_total``) and freezes that direction for
  ``freeze_ticks``. While a candidate is under evaluation no new change
  starts — evaluation is serial so the attribution is unambiguous.

Decision audit trail — every decision (and rollback) is stamped exactly
like a brown-out transition:

- ``tuner_decisions_total{knob=,direction=}`` counter +
  ``serving_knob{knob=}`` gauges (via the registry set);
- ONE structured ``tuner_decision {json}`` log line;
- a ``tuner_decision`` router-journal event (fleet traces show it);
- the runner ``_fall_through`` plumbing stamps ``tuner:<knob>_<dir>`` onto
  every healthy replica's next step-timeline record, so
  ``explain_request`` span trees show the decision inside the requests it
  affected.
"""

from __future__ import annotations

import json
import logging
import time
from typing import Callable, Dict, List, Optional

from .knobs import FleetKnobs

logger = logging.getLogger("tpu-inference")

__all__ = ["ServingTuner", "TunerRule", "default_rules", "PHASES"]

PHASES = ("interactive", "bulk", "long_context")


class TunerRule:
    """One phase-conditioned walk rule: when ``when(signals)`` holds for
    the hysteresis window, walk ``knob`` one step in ``direction``."""

    __slots__ = ("knob", "direction", "when", "reason")

    def __init__(self, knob: str, direction: str,
                 when: Callable[[Dict[str, object]], bool], reason: str):
        if direction not in ("up", "down"):
            raise ValueError(f"direction must be up/down, got {direction!r}")
        self.knob = knob
        self.direction = direction
        self.when = when
        self.reason = reason

    @property
    def key(self) -> tuple:
        return (self.knob, self.direction)


def default_rules() -> List[TunerRule]:
    """The built-in policy, in priority order (first matured rule wins the
    tick). Latency protection (walk-downs under SLO pressure) outranks
    throughput (walk-ups in healthy decode-heavy windows); rules for knobs
    a deployment didn't enable are skipped at evaluation."""
    return [
        # SLO pressure on interactive traffic: shrink the schedule quanta
        # first — long device-resident loops and deep pipelines are where
        # TTFT hides
        TunerRule("megastep_k", "down",
                  lambda s: not s["slo_healthy"]
                  and s["phase"] == "interactive",
                  "SLO unhealthy on interactive traffic: shorter megasteps "
                  "bound insert service latency"),
        TunerRule("async_depth", "down",
                  lambda s: not s["slo_healthy"]
                  and s["phase"] == "interactive",
                  "SLO unhealthy on interactive traffic: shallower pipeline "
                  "commits tokens sooner"),
        TunerRule("prefill_token_budget", "down",
                  lambda s: not s["slo_healthy"]
                  and s["phase"] == "interactive",
                  "SLO unhealthy on interactive traffic: smaller prefill "
                  "bites bound decode interference"),
        # healthy decode-heavy windows: amortize the host round trip harder
        TunerRule("megastep_k", "up",
                  lambda s: s["slo_healthy"] and s["decode_heavy"],
                  "decode-heavy window: amortize the dispatch floor over "
                  "more device-resident inner steps"),
        TunerRule("async_depth", "up",
                  lambda s: s["slo_healthy"] and s["decode_heavy"]
                  and (s["dispatch_gap_frac"] or 0.0) > 0.2,
                  "measured dispatch gap: deepen the dispatch-ahead "
                  "pipeline to overlap host commit work"),
        TunerRule("spec_chunk", "up",
                  lambda s: s["slo_healthy"] and s["decode_heavy"],
                  "decode-heavy window: longer fused speculative scans per "
                  "round trip"),
        # long-context intake with a backlog: feed prompts in bigger bites
        TunerRule("prefill_token_budget", "up",
                  lambda s: s["slo_healthy"] and s["phase"] == "long_context"
                  and s["queue_depth"] > 0,
                  "long-context backlog: raise the mixed-step prompt-token "
                  "budget"),
    ]


class ServingTuner:
    """Drive the fleet's knob registries from measured serving signals.

    Targets either a ``router=`` fleet (knob sets fan out across healthy
    replicas, decisions land in the router journal) or a single
    ``runner=``. Tests inject ``clock`` / ``signals`` / ``objective``; in
    production the defaults read the live fleet.

    ``objective``: callable returning a MONOTONE cumulative count (default:
    the router's emitted-token counter); the never-worse guard compares
    rates of this. ``signals``: callable returning a partial signal dict
    that overrides gathered values (tests drive phases deterministically).
    ``knob_whitelist``: restrict tuning to these knobs (e.g. only the
    retrace-free ones for a measurement window)."""

    def __init__(self, *, router=None, runner=None, autoscaler=None,
                 knobs: Optional[FleetKnobs] = None,
                 slo_signal: Optional[Callable[[], bool]] = None,
                 objective: Optional[Callable[[], float]] = None,
                 signals: Optional[Callable[[], Dict[str, object]]] = None,
                 rules: Optional[List[TunerRule]] = None,
                 knob_whitelist: Optional[List[str]] = None,
                 up_after: int = 2, down_after: int = 2,
                 cooldown_s: float = 0.0, eval_ticks: int = 4,
                 rollback_tolerance: float = 0.1, freeze_ticks: int = 8,
                 long_prompt_threshold: int = 512, bulk_queue_depth: int = 4,
                 bulk_occupancy: float = 0.75, gap_window: int = 32,
                 clock: Callable[[], float] = time.monotonic,
                 max_decisions: int = 1000):
        if router is None and runner is None and knobs is None:
            raise ValueError("ServingTuner needs a router, a runner, or an "
                             "explicit FleetKnobs")
        if up_after < 1 or down_after < 1 or eval_ticks < 1:
            raise ValueError("up_after/down_after/eval_ticks must be >= 1")
        self.router = router
        self.runner = runner
        self.autoscaler = autoscaler
        self.knobs = knobs if knobs is not None else FleetKnobs(
            router=router, autoscaler=autoscaler,
            runners=[runner] if runner is not None else None)
        self.slo_signal = slo_signal
        self._signals_fn = signals
        self.rules = rules if rules is not None else default_rules()
        self.knob_whitelist = (set(knob_whitelist)
                               if knob_whitelist is not None else None)
        self.up_after = int(up_after)
        self.down_after = int(down_after)
        self.cooldown_s = float(cooldown_s)
        self.eval_ticks = int(eval_ticks)
        self.rollback_tolerance = float(rollback_tolerance)
        self.freeze_ticks = int(freeze_ticks)
        self.long_prompt_threshold = int(long_prompt_threshold)
        self.bulk_queue_depth = int(bulk_queue_depth)
        self.bulk_occupancy = float(bulk_occupancy)
        self.gap_window = int(gap_window)
        self.clock = clock
        self.max_decisions = int(max_decisions)
        if objective is not None:
            self._objective = objective
        elif router is not None:
            self._objective = lambda: float(router._c_tokens.value)
        else:
            raise ValueError("runner-only tuning needs an explicit "
                             "objective= (cumulative token count)")
        reg = (router.registry if router is not None
               else runner.telemetry.registry)
        self.registry = reg
        self._c_ticks = reg.counter(
            "tuner_ticks_total", "tuner control-loop evaluations")
        self._c_rollbacks = reg.counter(
            "tuner_rollbacks_total",
            "knob changes rolled back by the never-worse guard")
        self._c_decisions: Dict[tuple, object] = {}
        self._g_phase = {
            p: reg.gauge("serving_tuner_phase",
                         "1 while the tuner classifies the workload as this "
                         "phase", labels={"phase": p})
            for p in PHASES}
        self._streaks: Dict[tuple, int] = {}
        self._frozen_until: Dict[tuple, int] = {}
        self._ticks = 0
        self._last_action_t: Optional[float] = None
        self._pending_eval: Optional[dict] = None
        self._history: List[tuple] = []        # (t, cumulative objective)
        self._prompt_len_ewma: Optional[float] = None
        self._rid_mark = (router._next_id if router is not None else 0)
        self._seen_replicas = (set(router.replicas)
                               if router is not None else set())
        self.decisions: List[dict] = []
        self.phase = "interactive"

    # -------------------------------------------------------------- signals
    def _healthy_runners(self) -> List[object]:
        if self.router is not None:
            return [rep.runner for rid, rep in self.router.replicas.items()
                    if self.router.replica_state(rid) == "healthy"]
        return [self.runner] if self.runner is not None else []

    def _dispatch_gap_frac(self, runners) -> Optional[float]:
        """Measured host-gap fraction over the freshest step records: the
        wall-time span of the last ``gap_window`` records minus the time
        covered by their host spans. None without telemetry (no records)."""
        gaps = []
        for r in runners:
            steps = r.telemetry.steps
            if len(steps) < 4:
                continue
            win = steps[-self.gap_window:]
            span = (win[-1]["ts"] + win[-1].get("dur_s", 0.0)) - win[0]["ts"]
            if span <= 0:
                continue
            busy = sum(s.get("dur_s", 0.0) for s in win)
            gaps.append(max(0.0, 1.0 - min(busy / span, 1.0)))
        return (sum(gaps) / len(gaps)) if gaps else None

    def _roofline_eff(self, runners) -> Optional[float]:
        """Min decode-family roofline efficiency, when the PR 13 join ran
        (attribute_device_time attaches it to the telemetry)."""
        effs = []
        for r in runners:
            rl = getattr(r.telemetry, "roofline", None)
            if not isinstance(rl, dict):
                continue
            for kind, row in rl.items():
                if isinstance(row, dict) and row.get("efficiency") is not None:
                    effs.append(float(row["efficiency"]))
        return min(effs) if effs else None

    def _note_recent_prompts(self) -> None:
        """Fold prompt lengths of arrivals since the last tick into the
        EWMA (the phase classifier's long-context signal)."""
        lens: List[int] = []
        if self.router is not None:
            for rid in range(self._rid_mark, self.router._next_id):
                req = self.router.requests.get(rid)
                if req is not None:
                    lens.append(len(req.prompt))
            self._rid_mark = self.router._next_id
        elif self.runner is not None:
            lens = [len(r.prompt) for r in self.runner.queue]
        for n in lens:
            self._prompt_len_ewma = (
                float(n) if self._prompt_len_ewma is None
                else 0.7 * self._prompt_len_ewma + 0.3 * float(n))

    def gather_signals(self) -> Dict[str, object]:
        """One tick's signal snapshot (``signals=`` overrides win)."""
        runners = self._healthy_runners()
        queue = (len(self.router.queue) if self.router is not None
                 else (len(self.runner.queue) if self.runner is not None
                       else 0))
        active = slots = 0
        inserting = False
        for r in runners:
            slots += r.num_slots
            for req in r.active:
                if req is not None and not req.done:
                    active += 1
                    inserting = inserting or req.inserting
            queue += len(r.queue) if self.router is not None else 0
        self._note_recent_prompts()
        sig: Dict[str, object] = {
            "queue_depth": queue,
            "occupancy": active / slots if slots else 0.0,
            "active": active,
            "inserting": inserting,
            "mean_prompt_len": self._prompt_len_ewma or 0.0,
            "slo_healthy": (bool(self.slo_signal())
                            if self.slo_signal is not None else True),
            "dispatch_gap_frac": self._dispatch_gap_frac(runners),
            "roofline_eff_min": self._roofline_eff(runners),
        }
        sig["decode_heavy"] = (queue == 0 and active > 0 and not inserting)
        if self._signals_fn is not None:
            sig.update(self._signals_fn())
        sig["phase"] = self.classify_phase(sig)
        return sig

    def classify_phase(self, sig: Dict[str, object]) -> str:
        if sig.get("mean_prompt_len", 0.0) >= self.long_prompt_threshold:
            return "long_context"
        if (sig.get("queue_depth", 0) >= self.bulk_queue_depth
                or sig.get("occupancy", 0.0) >= self.bulk_occupancy):
            return "bulk"
        return "interactive"

    # ------------------------------------------------------------ objective
    def _rate_since(self, t0: float, tok0: float,
                    t1: float, tok1: float) -> Optional[float]:
        dt = t1 - t0
        return (tok1 - tok0) / dt if dt > 0 else None

    def _baseline_rate(self) -> Optional[float]:
        """Objective rate over (up to) the last ``eval_ticks`` ticks."""
        if len(self._history) < 2:
            return None
        t1, k1 = self._history[-1]
        t0, k0 = self._history[max(0, len(self._history) - 1
                                   - self.eval_ticks)]
        return self._rate_since(t0, k0, t1, k1)

    # ----------------------------------------------------------------- tick
    def tick(self) -> List[dict]:
        """One control-loop evaluation; returns the decisions made (0 or 1
        change, or a rollback). Call it from the serving loop — every
        router step or on a timer."""
        now = self.clock()
        self._ticks += 1
        self._c_ticks.inc()
        # a replica grown since the last tick (autoscaler) missed earlier
        # fan-out sets: sync it to the fleet's current runner-scope values
        if self.router is not None:
            for rid, rep in self.router.replicas.items():
                if rid not in self._seen_replicas:
                    self._seen_replicas.add(rid)
                    self.knobs.sync_replica(rep.runner)
        sig = self.gather_signals()
        self.phase = sig["phase"]
        for p, g in self._g_phase.items():
            g.set(1.0 if p == self.phase else 0.0)
        tok = float(self._objective())
        self._history.append((now, tok))
        if len(self._history) > 4 * self.eval_ticks + 8:
            del self._history[: 2 * self.eval_ticks]

        out: List[dict] = []
        # never-worse guard: evaluate the in-flight candidate first
        pe = self._pending_eval
        if pe is not None and self._ticks - pe["tick"] >= self.eval_ticks:
            self._pending_eval = None
            rate = self._rate_since(pe["t"], pe["tok"], now, tok)
            base = pe["baseline_rate"]
            if (rate is not None and base is not None
                    and rate < base * (1.0 - self.rollback_tolerance)):
                out.append(self._rollback(pe, rate, sig))
            else:
                pe["kept_rate"] = rate
        # update every rule's hysteresis streak on every tick (matching the
        # brown-out ladder: a condition that lapses resets its streak)
        matured: Optional[TunerRule] = None
        for rule in self.rules:
            k = rule.key
            if rule.when(sig):
                self._streaks[k] = self._streaks.get(k, 0) + 1
            else:
                self._streaks[k] = 0
                continue
            need = self.up_after if rule.direction == "up" \
                else self.down_after
            if (matured is None and self._streaks[k] >= need
                    and self._ticks >= self._frozen_until.get(k, 0)
                    and self._tunable(rule.knob)):
                matured = rule
        if (matured is not None and self._pending_eval is None
                and not self._cooling(now)):
            dec = self._act(matured, sig, now, tok)
            if dec is not None:
                out.append(dec)
        return out

    def _cooling(self, now: float) -> bool:
        return (self._last_action_t is not None
                and now - self._last_action_t < self.cooldown_s)

    def _tunable(self, name: str) -> bool:
        if self.knob_whitelist is not None \
                and name not in self.knob_whitelist:
            return False
        try:
            return self.knobs.knob(name).tunable
        # lint: ok(silent-except): a rule naming a knob this fleet doesn't register (spec_chunk on a non-spec runner) is simply not tunable here — the ladder skips it by design
        except KeyError:
            return False

    # -------------------------------------------------------------- actions
    def _act(self, rule: TunerRule, sig: Dict[str, object], now: float,
             tok: float) -> Optional[dict]:
        knob = self.knobs.knob(rule.knob)
        cur = self.knobs.value(rule.knob)
        nxt = (knob.next_up(cur) if rule.direction == "up"
               else knob.next_down(cur))
        if nxt is None:
            return None                       # already at the bound
        old, new = self.knobs.set(rule.knob, nxt)
        self._streaks[rule.key] = 0
        self._last_action_t = now
        baseline = self._baseline_rate()
        rec = {"knob": rule.knob, "from": old, "to": new,
               "direction": rule.direction, "phase": self.phase,
               "reason": rule.reason, "tick": self._ticks,
               "baseline_rate": baseline}
        self._pending_eval = {"tick": self._ticks, "t": now, "tok": tok,
                              "knob": rule.knob, "old": old, "new": new,
                              "direction": rule.direction,
                              "baseline_rate": baseline}
        self._stamp(rec)
        return rec

    def _rollback(self, pe: dict, rate: Optional[float],
                  sig: Dict[str, object]) -> dict:
        self.knobs.set(pe["knob"], pe["old"])
        self._c_rollbacks.inc()
        # freeze the regressing direction so the same walk cannot restart
        # before the workload has a chance to change shape
        self._frozen_until[(pe["knob"], pe["direction"])] = (
            self._ticks + self.freeze_ticks)
        rec = {"knob": pe["knob"], "from": pe["new"], "to": pe["old"],
               "direction": "rollback", "phase": self.phase,
               "reason": (f"never-worse guard: candidate rate {rate!r} "
                          f"regressed baseline {pe['baseline_rate']!r}"),
               "tick": self._ticks, "baseline_rate": pe["baseline_rate"],
               "candidate_rate": rate}
        self._stamp(rec)
        return rec

    def _stamp(self, rec: dict) -> None:
        """The decision audit trail: counter + structured log + router
        journal + step-timeline stamp on every healthy replica — exactly
        the brown-out transition's four surfaces."""
        key = (rec["knob"], rec["direction"])
        c = self._c_decisions.get(key)
        if c is None:
            c = self.registry.counter(
                "tuner_decisions_total",
                "online tuner knob decisions (rollback = never-worse guard)",
                labels={"knob": rec["knob"], "direction": rec["direction"]})
            self._c_decisions[key] = c
        c.inc()
        logger.warning("tuner_decision %s", json.dumps(rec, sort_keys=True,
                                                       default=str))
        detail = f"{rec['from']}->{rec['to']}"
        if self.router is not None:
            self.router._trace_event("tuner_decision", **rec)
            self.router.stamp_fleet(
                "tuner", f"{rec['knob']}_{rec['direction']}", detail=detail)
        elif self.runner is not None:
            try:
                self.runner._note_fall_through(
                    "tuner", f"{rec['knob']}_{rec['direction']}",
                    detail=detail)
            # lint: ok(silent-except): best-effort timeline stamp; the decision is already counted+logged
            except Exception:
                pass
        self.decisions.append(rec)
        if len(self.decisions) > self.max_decisions:
            del self.decisions[: self.max_decisions // 4]

    # -------------------------------------------------------------- export
    def stats(self) -> Dict[str, object]:
        return {
            "ticks": self._ticks,
            "phase": self.phase,
            "decisions": int(sum(c.value
                                 for c in self._c_decisions.values())),
            "rollbacks": int(self._c_rollbacks.value),
            "recent_decisions": self.decisions[-20:],
            "pending_eval": (None if self._pending_eval is None else {
                k: self._pending_eval[k]
                for k in ("knob", "old", "new", "direction", "tick")}),
            "streaks": {f"{k}:{d}": n
                        for (k, d), n in sorted(self._streaks.items()) if n},
            "frozen": {f"{k}:{d}": until for (k, d), until
                       in sorted(self._frozen_until.items())
                       if until > self._ticks},
            "knobs": self.knobs.snapshot(),
        }
