"""Declarative registry of every live-tunable serving knob (ISSUE-18).

The observability plane measures everything (roofline efficiency, SLO
health, dispatch gaps, fragmentation) but until now every schedule
parameter was static constructor config scattered across three owners —
the runner, the router, and the autoscaler. This module is the single
enumerable table the control plane (serving/tuner.py) and the audit trail
need:

- :class:`Knob` — one tunable: name, scope (``runner`` / ``router`` /
  ``autoscaler``), bounds, step rule, and getter/setter closures into the
  owner's live state. Every knob here is SCHEDULE-ONLY: changing it can
  re-batch, re-order, or re-chunk work but can never change any emitted
  token stream (the bit-exactness invariant the whole serving stack is
  built on — tests/test_tuner.py pins it across mid-flight changes).
- :class:`KnobRegistry` — the per-owner table. Registration exports the
  live value as a ``serving_knob{knob=}`` gauge on the owner's metrics
  registry and every :meth:`set` re-exports it, so the CURRENT setting of
  every knob is always one scrape away; ``snapshot()`` is the
  ``stats()["knobs"]`` surface.
- :class:`FleetKnobs` — the merged fleet-level view the tuner drives:
  router- and autoscaler-scope knobs pass through, runner-scope knobs fan
  out to EVERY healthy replica (schedule policy is fleet-uniform; a
  replica added later inherits the fleet's values through
  ``sync_replica``).

Setters do NOT need to apply instantly: the runner's setters queue the
change and apply it at the next pipeline-drain safe point (see
``ContinuousBatchingRunner._apply_pending_knobs``), which is what makes a
mid-flight change exact by construction. ``value()`` always reads the
owner's live (applied) state.
"""

from __future__ import annotations

import logging
from typing import Callable, Dict, List, Optional, Sequence

logger = logging.getLogger("tpu-inference")

__all__ = ["Knob", "KnobRegistry", "FleetKnobs", "build_runner_knobs",
           "build_router_knobs", "build_autoscaler_knobs"]

#: valid knob scopes (the owner layer the setter mutates)
SCOPES = ("runner", "router", "autoscaler")


class Knob:
    """One live-tunable parameter: bounds + closures into the owner."""

    __slots__ = ("name", "scope", "kind", "lo", "hi", "step", "doc",
                 "get", "set", "tunable")

    def __init__(self, name: str, *, scope: str, get: Callable[[], object],
                 set: Callable[[object], None], kind: type = int,
                 lo: Optional[float] = None, hi: Optional[float] = None,
                 step: object = "x2", doc: str = "", tunable: bool = True):
        if scope not in SCOPES:
            raise ValueError(f"knob scope must be one of {SCOPES}, "
                             f"got {scope!r}")
        if kind not in (int, float, bool):
            raise ValueError(f"knob kind must be int/float/bool, got {kind}")
        self.name = name
        self.scope = scope
        self.kind = kind
        self.lo = lo
        self.hi = hi
        # step rule for the tuner's walk: "x2" doubles/halves (integer
        # knobs — the geometric walk covers a [1, ring] range in log steps),
        # a number is an additive increment
        self.step = step
        self.doc = doc
        self.get = get
        self.set = set
        # tunable=False: enumerated + audited + gauge-exported, but the
        # online tuner must not touch it (e.g. values whose change forces a
        # recompile mid-measurement)
        self.tunable = tunable

    def coerce(self, value: object):
        """Validate + coerce a candidate value against kind and bounds."""
        if self.kind is bool:
            if not isinstance(value, (bool, int)) or value not in (0, 1,
                                                                   True,
                                                                   False):
                raise ValueError(f"knob {self.name}: {value!r} is not a bool")
            return bool(value)
        try:
            v = self.kind(value)
        except (TypeError, ValueError):
            raise ValueError(f"knob {self.name}: {value!r} is not "
                             f"{self.kind.__name__}")
        if self.kind is int and float(value) != float(v):
            raise ValueError(f"knob {self.name}: {value!r} is not integral")
        if self.lo is not None and v < self.lo:
            raise ValueError(f"knob {self.name}: {v} below bound {self.lo}")
        if self.hi is not None and v > self.hi:
            raise ValueError(f"knob {self.name}: {v} above bound {self.hi}")
        return v

    def next_up(self, value) -> Optional[object]:
        """The next candidate above ``value`` (None at the upper bound)."""
        if self.kind is bool:
            return True if not value else None
        nxt = value * 2 if self.step == "x2" else value + self.step
        if self.hi is not None:
            nxt = min(nxt, self.hi)
        nxt = self.kind(nxt)
        return nxt if nxt != value else None

    def next_down(self, value) -> Optional[object]:
        """The next candidate below ``value`` (None at the lower bound)."""
        if self.kind is bool:
            return False if value else None
        nxt = value // 2 if self.step == "x2" and self.kind is int \
            else (value / 2 if self.step == "x2" else value - self.step)
        if self.lo is not None:
            nxt = max(nxt, self.lo)
        nxt = self.kind(nxt)
        return nxt if nxt != value else None


class KnobRegistry:
    """The declarative knob table of ONE owner (runner/router/autoscaler).

    ``metrics_registry``: the owner's MetricsRegistry — registration and
    every set() export the live value as ``serving_knob{knob=<name>}``."""

    def __init__(self, metrics_registry=None, scope: str = "runner"):
        if scope not in SCOPES:
            raise ValueError(f"scope must be one of {SCOPES}, got {scope!r}")
        self.scope = scope
        self._metrics = metrics_registry
        self._knobs: Dict[str, Knob] = {}
        self._gauges: Dict[str, object] = {}

    def register(self, name: str, *, get, set, kind: type = int,
                 lo: Optional[float] = None, hi: Optional[float] = None,
                 step: object = "x2", doc: str = "",
                 tunable: bool = True) -> Knob:
        if name in self._knobs:
            raise ValueError(f"knob {name!r} already registered")
        k = Knob(name, scope=self.scope, get=get, set=set, kind=kind,
                 lo=lo, hi=hi, step=step, doc=doc, tunable=tunable)
        self._knobs[name] = k
        if self._metrics is not None:
            g = self._metrics.gauge(
                "serving_knob", "live value of a serving schedule knob "
                "(serving/knobs.py)", labels={"knob": name})
            self._gauges[name] = g
        self.refresh(name)
        return k

    # ------------------------------------------------------------- queries
    def __contains__(self, name: str) -> bool:
        return name in self._knobs

    def __len__(self) -> int:
        return len(self._knobs)

    def names(self) -> List[str]:
        return sorted(self._knobs)

    def knob(self, name: str) -> Knob:
        if name not in self._knobs:
            raise KeyError(f"unknown knob {name!r} (have {self.names()})")
        return self._knobs[name]

    def value(self, name: str):
        return self.knob(name).get()

    # ------------------------------------------------------------- mutation
    def set(self, name: str, value) -> tuple:
        """Validate, hand to the owner's setter, re-export the gauge.
        Returns ``(old, new)`` — old is the live value BEFORE the set (the
        owner may defer application to its next safe point; the gauge
        tracks the requested target, refreshed to live state on apply)."""
        k = self.knob(name)
        v = k.coerce(value)
        old = k.get()
        k.set(v)
        g = self._gauges.get(name)
        if g is not None:
            g.set(float(v))
        return old, v

    def refresh(self, name: Optional[str] = None) -> None:
        """Re-export gauge(s) from the owner's LIVE state (called by the
        runner after deferred knob application)."""
        for n in ([name] if name is not None else list(self._knobs)):
            g = self._gauges.get(n)
            if g is not None:
                g.set(float(self._knobs[n].get()))

    # -------------------------------------------------------------- export
    def snapshot(self) -> Dict[str, dict]:
        """The ``stats()["knobs"]`` surface: every knob's live value,
        bounds, scope, and tunability."""
        out = {}
        for name, k in sorted(self._knobs.items()):
            out[name] = {"value": k.get(), "scope": k.scope,
                         "lo": k.lo, "hi": k.hi,
                         "kind": k.kind.__name__,
                         "tunable": k.tunable, "doc": k.doc}
        return out


class FleetKnobs:
    """The tuner's merged view over a fleet: one namespace spanning the
    router's knobs, the autoscaler's, and the (fleet-uniform) runner knobs
    of every healthy replica.

    Runner-scope reads come from the first healthy replica; runner-scope
    sets fan out to EVERY healthy replica (schedule policy is uniform —
    two replicas running different megastep depths would make placement
    latency depend on which replica a request landed on)."""

    def __init__(self, router=None, autoscaler=None,
                 runners: Optional[Sequence[object]] = None):
        if router is None and autoscaler is None and not runners:
            raise ValueError("FleetKnobs needs a router, an autoscaler, or "
                             "runners")
        self.router = router
        self.autoscaler = autoscaler
        self._runners = list(runners or [])

    # ------------------------------------------------------------- helpers
    def _runner_registries(self) -> List[KnobRegistry]:
        regs = []
        if self.router is not None:
            for rid, rep in self.router.replicas.items():
                if self.router.replica_state(rid) != "healthy":
                    continue
                kr = getattr(rep.runner, "knobs", None)
                if kr is not None:
                    regs.append(kr)
        for r in self._runners:
            kr = getattr(r, "knobs", None)
            if kr is not None:
                regs.append(kr)
        return regs

    def _owner_registries(self) -> List[KnobRegistry]:
        out = []
        if self.router is not None:
            kr = getattr(self.router, "knobs", None)
            if kr is not None:
                out.append(kr)
        if self.autoscaler is not None:
            kr = getattr(self.autoscaler, "knobs", None)
            if kr is not None:
                out.append(kr)
        return out

    def _find(self, name: str):
        """(registry, fan_out_registries) owning ``name``."""
        for reg in self._owner_registries():
            if name in reg:
                return reg, [reg]
        runner_regs = [r for r in self._runner_registries() if name in r]
        if runner_regs:
            return runner_regs[0], runner_regs
        raise KeyError(f"unknown knob {name!r} (have {self.names()})")

    # ------------------------------------------------------------- surface
    def names(self) -> List[str]:
        names = set()
        for reg in self._owner_registries():
            names.update(reg.names())
        regs = self._runner_registries()
        if regs:
            names.update(regs[0].names())
        return sorted(names)

    def __contains__(self, name: str) -> bool:
        try:
            self._find(name)
            return True
        # lint: ok(silent-except): membership probe — False IS the answer ("spec_chunk" in knobs on a non-spec fleet); callers needing the failure use knob()/set(), which raise
        except KeyError:
            return False

    def knob(self, name: str) -> Knob:
        reg, _ = self._find(name)
        return reg.knob(name)

    def value(self, name: str):
        reg, _ = self._find(name)
        return reg.value(name)

    def set(self, name: str, value) -> tuple:
        """Set on the owner (fan-out across replicas for runner scope).
        Returns ``(old, new)`` from the first registry."""
        _, regs = self._find(name)
        old = new = None
        for i, reg in enumerate(regs):
            o, n = reg.set(name, value)
            if i == 0:
                old, new = o, n
        return old, new

    def sync_replica(self, runner) -> int:
        """Push the fleet's current runner-scope values onto a replica that
        joined later (autoscaler grow): a grown replica must not serve
        under stale constructor defaults while the rest of the fleet runs
        tuned values. Returns the number of knobs synced."""
        regs = self._runner_registries()
        target = getattr(runner, "knobs", None)
        if target is None or not regs:
            return 0
        src = regs[0]
        if src is target:
            return 0
        n = 0
        for name in src.names():
            if name in target:
                cur = src.value(name)
                if target.value(name) != cur:
                    target.set(name, cur)
                    n += 1
        return n

    def snapshot(self) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        regs = self._runner_registries()
        if regs:
            out.update(regs[0].snapshot())
        for reg in self._owner_registries():
            out.update(reg.snapshot())
        return out


# ---------------------------------------------------------------- builders
def build_runner_knobs(runner) -> KnobRegistry:
    """The runner's schedule-only knob table. Setters QUEUE the change
    (``runner.set_knob``) and the runner applies it at the next
    pipeline-drain safe point, so every knob here is exact by construction
    however mid-flight the change lands. Knobs whose feature is off for
    this runner (no megastep / no mixed scheduler / no speculation) are
    simply absent — the tuner cannot tune what the deployment didn't
    enable."""
    reg = KnobRegistry(runner.telemetry.registry, scope="runner")
    mk = runner.set_knob
    reg.register(
        "async_depth", get=lambda: runner.async_depth,
        set=lambda v: mk("async_depth", v), lo=1, hi=32,
        doc="dispatch-ahead pipeline depth (chunks in flight)")
    reg.register(
        "decode_chunk", get=lambda: runner.decode_chunk,
        set=lambda v: mk("decode_chunk", v), lo=1,
        hi=max(1, runner.cfg.seq_len - 1), tunable=False,
        doc="decode scan length per plain dispatch (retrace per value — "
            "enumerated, not online-tuned)")
    if runner.megastep_k is not None:
        reg.register(
            "megastep_k", get=lambda: runner.megastep_k,
            set=lambda v: mk("megastep_k", v), lo=1, hi=runner.megastep_ring,
            doc="device-resident inner steps per megastep dispatch (K is a "
                "dynamic operand of one executable; ring bounds it)")
    if runner.mixed:
        reg.register(
            "prefill_token_budget", get=lambda: runner.prefill_budget,
            set=lambda v: mk("prefill_token_budget", v),
            lo=runner.prefill_chunk, hi=runner.cfg.seq_len,
            step=runner.prefill_chunk,
            doc="prompt tokens packed per mixed step (chunk-row count "
                "follows; row-count changes retrace once per value)")
        reg.register(
            "mixed_decode_steps", get=lambda: runner.mixed_decode_steps,
            set=lambda v: mk("mixed_decode_steps", v), lo=1, hi=64,
            doc="decode iterations chained inside each mixed dispatch")
    if runner.k:
        reg.register(
            "spec_chunk", get=lambda: runner.spec_chunk,
            set=lambda v: mk("spec_chunk", v), lo=1, hi=64,
            doc="fused speculative iterations scanned per dispatch")
        reg.register(
            "spec_adaptive", get=lambda: runner.spec_adaptive,
            set=lambda v: mk("spec_adaptive", v), kind=bool,
            doc="acceptance-floor adaptive fallback to plain decode")
    if runner.paged:
        reg.register(
            "prefetch_depth", get=lambda: runner.prefetch_depth,
            set=lambda v: mk("prefetch_depth", v), lo=0, hi=16, step=2,
            doc="fused paged-decode DMA pipeline depth; 0 = per-dtype "
                "VMEM-budget auto (applies to dispatches traced after the "
                "change — retrace per value, never a stream change)")
    return reg


def build_router_knobs(router) -> KnobRegistry:
    """Router-scope knobs: overload-plane thresholds read fresh each step,
    so plain attribute sets are live by nature."""
    reg = KnobRegistry(router.registry, scope="router")

    def attr(name, lo, hi, doc, kind=int, step="x2"):
        reg.register(name,
                     get=lambda: getattr(router, name),
                     set=lambda v: setattr(router, name, v),
                     kind=kind, lo=lo, hi=hi, step=step, doc=doc)

    attr("brownout_up_after", 1, 64,
         "consecutive unhealthy SLO readings before the ladder rises")
    attr("brownout_down_after", 1, 64,
         "consecutive healthy SLO readings before the ladder falls")
    attr("brownout_decode_cap", 1, 256,
         "max concurrent placements of a capped class (fleet-wide)")
    if router.shed_queue_depth is not None:
        attr("shed_queue_depth", 1, 100_000,
             "frontend queue depth past which arrivals shed")
    return reg


def build_autoscaler_knobs(autoscaler) -> KnobRegistry:
    """Autoscaler-scope knobs: fleet bounds + pressure thresholds (pure
    host state, read per tick)."""
    reg = KnobRegistry(autoscaler.router.registry, scope="autoscaler")

    def attr(name, lo, hi, doc, kind=int, step=1):
        def _set(v, _n=name):
            old = getattr(autoscaler, _n)
            setattr(autoscaler, _n, v)
            if autoscaler.max_replicas < autoscaler.min_replicas:
                setattr(autoscaler, _n, old)
                raise ValueError("min_replicas must stay <= max_replicas")
        reg.register(name, get=lambda _n=name: getattr(autoscaler, _n),
                     set=_set, kind=kind, lo=lo, hi=hi, step=step, doc=doc)

    attr("min_replicas", 1, 1024, "fleet size floor")
    attr("max_replicas", 1, 1024, "fleet size ceiling")
    attr("scale_up_queue_depth", 0, 100_000,
         "router queue depth that counts as grow pressure")
    attr("scale_down_queue_depth", 0, 100_000,
         "router queue depth at or below which the fleet may shrink")
    attr("up_after", 1, 64, "grow-pressure ticks before growing")
    attr("down_after", 1, 64, "idle ticks before draining")
    attr("cooldown_s", 0.0, 3600.0, "quiet period between actions",
         kind=float, step="x2")
    return reg
