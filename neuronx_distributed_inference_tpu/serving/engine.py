"""Engine core of the scale-out split: one replica = runner + telemetry/SLO.

:class:`EngineReplica` wraps a ContinuousBatchingRunner as a self-contained
serving replica with a stable id. It adds exactly what the frontend
(serving/router.py) needs and nothing the runner already does:

- **Admission interface** — ``can_admit`` / ``admission()``: KV-block
  headroom, queue depth, and in-flight chunk count, all computed from state
  the runner already tracks (``stats()`` + the metrics registry). The router
  load-balances and spills on these signals; the SLO monitor reads the same
  registry.
- **Per-replica labelled metrics** — the replica builds its runner's
  telemetry on a ``MetricsRegistry(default_labels={"replica": id})``, so
  every instrument the runner (or SLO monitor) registers carries the replica
  label with zero per-call-site threading, and N replicas' expositions
  concatenate into one scrape.
- **Prefix-affinity probe** — ``resident_prefix_blocks(hashes)``: how many
  leading chained block hashes of a prompt are resident on this replica
  (device prefix cache, idle pool, or its host-RAM tier). The router's
  placement score.
- **Drain** — ``drain()``: evict every unfinished request through the
  runner's existing mid-prompt preemption/resume path and hand the payloads
  back for re-placement; with a KV tier attached the committed prefixes are
  spilled to host RAM on the way out.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional

import numpy as np

from ..modules.block_kvcache import BlockAllocator
from ..runtime.continuous_batching import ContinuousBatchingRunner
from ..utils import metrics as metrics_lib

__all__ = ["EngineReplica", "prompt_block_hashes"]


def prompt_block_hashes(prompt: np.ndarray, block_size: int,
                        adapter_id: int = 0) -> List[bytes]:
    """Chained content hashes of the prompt's leading FULL blocks — the same
    chain (and the same adapter salt) the runner's prefix cache keys blocks
    by (``_begin_insert`` / BlockAllocator), so a router-side hash walk and a
    replica-side residency probe speak one language."""
    prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
    if adapter_id != 0 and prompt.size:
        prompt = prompt.copy()
        prompt[0] ^= np.int32(adapter_id << 20)
    out: List[bytes] = []
    prev = b""
    for i in range(len(prompt) // block_size):
        prev = BlockAllocator._chain_hash(
            prev, prompt[i * block_size : (i + 1) * block_size])
        out.append(prev)
    return out


class EngineReplica:
    """A ContinuousBatchingRunner packaged as one serving replica.

    ``runner_factory``: callable ``(telemetry) -> ContinuousBatchingRunner``
    — the replica owns telemetry construction so the registry carries its
    ``replica=<id>`` default label. Pass an existing runner via ``runner=``
    instead when the caller already built one (its metrics then keep their
    unlabelled names).
    """

    # serving-mode ledger-audit cadence (waves): a leak surfaces within this
    # many steps even if nothing drains, evicts, or scrapes in between
    LEDGER_AUDIT_EVERY = 64

    #: valid ``pool_role`` values (serving/pools.py re-exports these)
    POOL_ROLES = ("prefill", "decode", "unified")

    def __init__(self, replica_id: str, runner_factory=None, *,
                 runner: Optional[ContinuousBatchingRunner] = None,
                 telemetry_enabled: bool = False,
                 jsonl_path: Optional[str] = None,
                 max_queue_depth: Optional[int] = None,
                 pool_role: str = "unified"):
        if (runner is None) == (runner_factory is None):
            raise ValueError("pass exactly one of runner_factory / runner")
        if pool_role not in self.POOL_ROLES:
            raise ValueError(f"pool_role must be one of {self.POOL_ROLES}, "
                             f"got {pool_role!r}")
        self.replica_id = str(replica_id)
        # disaggregated-pool membership (serving/pools.py): "prefill" replicas
        # take fresh arrivals, "decode" replicas take handed-off requests,
        # "unified" replicas take both (the pre-pools default, and what every
        # placement policy other than remote_prefill treats all roles as)
        self.pool_role = pool_role
        if runner is None:
            registry = metrics_lib.MetricsRegistry(
                default_labels={"replica": self.replica_id})
            telemetry = metrics_lib.ServingTelemetry(
                enabled=telemetry_enabled, registry=registry,
                jsonl_path=jsonl_path)
            runner = runner_factory(telemetry)
            if runner.telemetry is not telemetry:
                raise ValueError("runner_factory must build the runner on the "
                                 "telemetry it is given (pass telemetry= "
                                 "through to ContinuousBatchingRunner)")
        self.runner = runner
        self.registry = runner.telemetry.registry
        # replica-lifecycle gauges (labelled like everything else here)
        self._g_accepting = self.registry.gauge(
            "serving_replica_accepting",
            "1 while this replica is in the router's placement set")
        self._g_accepting.set(1)
        # queue-admission ceiling: a replica whose backlog already covers
        # 2x its slots gains nothing from more queue — the router should
        # spill to a less loaded replica instead
        self.max_queue_depth = (max_queue_depth if max_queue_depth is not None
                                else 2 * runner.num_slots)
        self.draining = False
        self._wave = 0      # step counter for the periodic ledger audit
        if runner.paged and runner.kv_tier is not None:
            # per-replica VIEWS of the (possibly shared) tier's state — a
            # shared tier repeats the same value under every replica label,
            # so these are gauges, deliberately NOT _total-named counters
            # (sum() over replicas of a shared tier would double-count; the
            # authoritative counter is tier.stats()["integrity_failures"],
            # which bench publishes as kv_tier_integrity_failures_total)
            self._tier_gauges = {
                k: self.registry.gauge(
                    f"serving_kv_tier_{k}",
                    "host-RAM KV tier state (serving/kv_tiering.py)")
                for k in ("host_blocks", "evictions", "host_evictions",
                          "readmit_blocks", "integrity_failures")}
        else:
            self._tier_gauges = None

    # ------------------------------------------------------------- admission
    def admission(self) -> Dict[str, object]:
        """The router's placement signals, point-in-time: queue depth,
        in-flight chunk count, live occupancy, and (paged) KV-block headroom
        — the tiered allocator counts idle blocks as headroom, which is the
        wiring that makes host-tier eviction admission-driven."""
        r = self.runner
        out = {
            "replica": self.replica_id,
            "accepting": not self.draining,
            "pool_role": self.pool_role,
            "queue_depth": len(r.queue),
            "inflight_chunks": len(r._inflight),
            "active_requests": sum(
                q is not None and not q.done for q in r.active),
            "num_slots": r.num_slots,
        }
        if r.paged:
            out["kv_blocks_free"] = r.allocator.num_free
            out["kv_blocks_total"] = r.allocator.num_blocks
            out["kv_headroom_frac"] = (r.allocator.num_free
                                       / max(1, r.allocator.num_blocks))
        return out

    def blocks_needed(self, prompt_len: int) -> int:
        """Blocks a fresh placement of this prompt requires — the same
        prompt + one-decode-chunk bound ``_place_queued`` admits by."""
        r = self.runner
        if not r.paged:
            return 0
        chunk_tokens = r.spec_chunk * r.k if r.k else r.decode_chunk
        return -(-(prompt_len + 1 + chunk_tokens) // r.block_size)

    def can_admit(self, prompt_len: int) -> bool:
        """Would this replica make progress on the request rather than just
        queue it? False while draining, past the queue ceiling, or (paged)
        when even after the queue drains the pool cannot hold the prompt."""
        if self.draining:
            return False
        r = self.runner
        if len(r.queue) >= self.max_queue_depth:
            return False
        if r.paged and self.blocks_needed(prompt_len) > r.allocator.num_blocks:
            return False
        return True

    def has_headroom(self, prompt_len: int) -> bool:
        """Immediate placement headroom (no wait): free blocks cover the
        prompt and a free slot exists. The router prefers these replicas and
        records a graceful SPILL when the affinity target lacks them."""
        r = self.runner
        if self.draining:
            return False
        # the backlog ahead of us must fit in the free slots for placement
        # to be immediate (queue == free slots means we'd wait a generation)
        free_slots = sum(q is None for q in r.active)
        if len(r.queue) >= free_slots:
            return False
        if r.paged and self.blocks_needed(prompt_len) > r.allocator.num_free:
            return False
        return True

    # ------------------------------------------------------------- affinity
    def prefix_residency(self, hashes: List[bytes]) -> tuple:
        """Leading-block residency BREAKDOWN ``(device, host, cluster)`` down
        the lookup ladder: device prefix cache (live or idle), this
        replica's host tier (a hit re-admits), then the fleet's cluster
        store (serving/cluster_kv.py — a hit pulls, which still beats
        recompute). The cluster rung is what lets a COLD replica score
        nonzero affinity for a fleet-warm prompt, so placement load-balances
        it instead of re-prefilling."""
        r = self.runner
        if not r.paged:
            return (0, 0, 0)
        alloc = r.allocator
        tier = r.kv_tier
        dev = host = cluster = 0
        for h in hashes:
            if h in getattr(alloc, "hash_to_block", {}):
                dev += 1
            elif tier is not None and h in tier:
                host += 1
            elif tier is not None and getattr(tier, "cluster_has",
                                              lambda _h: False)(h):
                cluster += 1
            else:
                break
        return (dev, host, cluster)

    def resident_prefix_blocks(self, hashes: List[bytes]) -> int:
        """Leading blocks of the hash chain this replica can serve without
        re-prefill (device + host tier + cluster store) — the router's
        placement score."""
        return sum(self.prefix_residency(hashes))

    # ------------------------------------------------------------- serving
    def submit(self, prompt, **kw) -> int:
        return self.runner.submit(prompt, **kw)

    def step(self, key=None) -> Dict[int, List[int]]:
        if self._tier_gauges is not None:
            ts = self.runner.kv_tier.stats()
            for k, g in self._tier_gauges.items():
                g.set(ts[k])
        led = getattr(self.runner, "ledger", None)
        if led is not None:
            # periodic (NOT per-wave — both are O(num_blocks) host work the
            # hot loop must not pay every step): refresh the replica-labelled
            # owner-state gauges and run the conservation audit, so a leaked
            # block surfaces within bounded waves even if nothing drains or
            # scrapes; every Prometheus scrape does both too
            self._wave += 1
            if self._wave % self.LEDGER_AUDIT_EVERY == 0:
                led.export_gauges(
                    fragmentation=self.runner._kv_fragmentation())
                self.runner.audit_ledger()
        return self.runner.step(key)

    @property
    def has_work(self) -> bool:
        return self.runner.has_work

    def stats(self) -> Dict[str, object]:
        s = self.runner.stats()
        s["replica"] = self.replica_id
        s["admission"] = self.admission()
        return s

    def drain(self):
        """Leave the placement set: evict every unfinished request through
        the runner's preemption/resume path and return (emitted, requests)
        for the router to re-place. The replica stays steppable (it may be
        re-added later)."""
        self.draining = True
        self._g_accepting.set(0)
        return self.runner.drain_requests()

    def evict_request(self, local_id: int):
        """Single-request drain (router SLA preemption, serving/router.py):
        evict ONE request through the runner's preempt path and hand it
        back — the replica stays in the placement set. Returns
        ``(emitted, request-or-None)``."""
        return self.runner.evict_request(local_id)

    def reactivate(self) -> None:
        self.draining = False
        self._g_accepting.set(1)

    def prometheus_text(self, exemplars: bool = False) -> str:
        # scrape-time conservation audit + gauge refresh: a leaked block is
        # visible in THIS exposition (memledger_violations_total /
        # serving_kv_leaked_blocks_total), not only after the next drain
        if getattr(self.runner, "ledger", None) is not None:
            try:
                self.runner.audit_ledger()
                self.runner.ledger.export_gauges(
                    fragmentation=self.runner._kv_fragmentation())
            except Exception as e:   # lint: ok(silent-except): a broken ledger must not break the scrape itself (logged)
                logging.getLogger("tpu-inference").warning(
                    "scrape-time ledger audit failed on replica %s: %s",
                    self.replica_id, e)
        return self.registry.prometheus_text(exemplars=exemplars)

    def trace_source(self) -> Dict[str, object]:
        """This replica's telemetry stream as a tracing source
        (serving/tracing.py): the ``replica<id>``-named events/steps/epoch
        triple the fleet-merge and span-tree builders consume."""
        from . import tracing

        return tracing.source_from_telemetry(f"replica{self.replica_id}",
                                             self.runner.telemetry)
