"""Deterministic what-if replayer over committed serving traces (ISSUE-18
tentpole a).

Tuning policies were untestable before this module: the only way to ask
"would ``megastep_k=8`` have beaten ``=2`` on yesterday's traffic?" was to
run yesterday's traffic again, and wall-clock arrival replays are not
reproducible. This module closes that gap in three moves:

- :class:`ArrivalTrace` — the portable arrival schedule: prompts, virtual
  arrival timestamps, SLA classes, per-request serving params, and
  ``trace_id`` join keys. Committable as JSONL (``save``/``load``), small
  enough to live in ``tests/data/``.
- :func:`reconstruct_trace` — rebuild an :class:`ArrivalTrace` from a
  committed router-journal spool (``PrefixAffinityRouter.
  write_trace_events``). Requires the router ran with
  ``journal_prompts=True`` — prompts are payload, not telemetry, so the
  default journal deliberately omits them and reconstruction fails with an
  actionable error instead of fabricating tokens.
- :func:`replay` — re-run the trace on a REAL fleet under candidate knob
  settings, on **virtual time**: replay step ``n`` releases every arrival
  with ``ts <= n * step_quantum_s``, then steps the router once. The
  release schedule is a pure function of the trace, never of the host
  clock, so the same trace + the same knobs produce the same submission
  order, the same placement decisions, and therefore bit-identical token
  streams (pinned by tests/test_tuner.py). Each replay is scored with the
  EXISTING telemetry pipeline — per-replica
  :func:`~.tracing.validate_coverage` (the PR 11 ≤5% reconciliation
  contract) plus per-request waterfalls — so a candidate's report is held
  to the same honesty bar as a live bench run.

What-if comparison is then just two calls::

    static = replay(trace, fleet_factory, knobs={"megastep_k": 2})
    tuned  = replay(trace, fleet_factory, knobs={"megastep_k": 2},
                    tuner_factory=lambda r: ServingTuner(router=r, ...))
    ratio  = tuned.tokens_per_s / static.tokens_per_s

and because both legs emit bit-identical streams (schedule-only knobs),
the ratio is a pure scheduling comparison — never a quality trade.
"""

from __future__ import annotations

import json
import logging
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np

from .knobs import FleetKnobs
from . import tracing

logger = logging.getLogger("tpu-inference")

__all__ = ["Arrival", "ArrivalTrace", "ReplayResult", "reconstruct_trace",
           "replay"]

#: format tag of the committed ArrivalTrace JSONL header line
TRACE_FORMAT = "arrival_trace_v1"


@dataclass
class Arrival:
    """One request of the schedule: virtual arrival time + everything
    ``router.submit`` needs to reproduce the original submission."""

    ts: float                         # virtual seconds from trace start
    prompt: List[int]
    max_new_tokens: int = 32
    eos_token_id: Optional[int] = None
    sla_class: Optional[str] = None
    adapter_id: int = 0
    trace_id: Optional[str] = None    # join key back to the original run

    def to_json(self) -> dict:
        d = {"ts": self.ts, "prompt": list(self.prompt),
             "max_new_tokens": self.max_new_tokens}
        if self.eos_token_id is not None:
            d["eos_token_id"] = self.eos_token_id
        if self.sla_class is not None:
            d["sla_class"] = self.sla_class
        if self.adapter_id:
            d["adapter_id"] = self.adapter_id
        if self.trace_id is not None:
            d["trace_id"] = self.trace_id
        return d

    @classmethod
    def from_json(cls, d: dict) -> "Arrival":
        return cls(ts=float(d["ts"]), prompt=[int(t) for t in d["prompt"]],
                   max_new_tokens=int(d.get("max_new_tokens", 32)),
                   eos_token_id=d.get("eos_token_id"),
                   sla_class=d.get("sla_class"),
                   adapter_id=int(d.get("adapter_id", 0)),
                   trace_id=d.get("trace_id"))


class ArrivalTrace:
    """An ordered arrival schedule + the virtual-time quantum that maps it
    onto router steps. ``step_quantum_s`` is PART of the trace: two replays
    of one trace always agree on which step releases which arrival."""

    def __init__(self, arrivals: List[Arrival], step_quantum_s: float,
                 meta: Optional[dict] = None):
        if step_quantum_s <= 0:
            raise ValueError("step_quantum_s must be > 0")
        self.arrivals = sorted(arrivals, key=lambda a: (a.ts,
                                                        a.trace_id or ""))
        self.step_quantum_s = float(step_quantum_s)
        self.meta = dict(meta or {})

    def __len__(self) -> int:
        return len(self.arrivals)

    def release_step(self, arrival: Arrival) -> int:
        """The replay step index that releases this arrival (pure function
        of the trace — the determinism anchor)."""
        import math
        return int(math.ceil(arrival.ts / self.step_quantum_s))

    # ------------------------------------------------------------ save/load
    def save(self, path: str) -> str:
        """Commit as JSONL: one header line (format tag + quantum + meta),
        one line per arrival."""
        with open(path, "w") as fh:
            fh.write(json.dumps({"format": TRACE_FORMAT,
                                 "step_quantum_s": self.step_quantum_s,
                                 "arrivals": len(self.arrivals),
                                 "meta": self.meta}) + "\n")
            for a in self.arrivals:
                fh.write(json.dumps(a.to_json()) + "\n")
        return path

    @classmethod
    def load(cls, path: str) -> "ArrivalTrace":
        with open(path) as fh:
            header = json.loads(fh.readline())
            if header.get("format") != TRACE_FORMAT:
                raise ValueError(
                    f"{path}: not an ArrivalTrace spool (header format "
                    f"{header.get('format')!r}, want {TRACE_FORMAT!r})")
            arrivals = [Arrival.from_json(json.loads(line))
                        for line in fh if line.strip()]
        return cls(arrivals, header["step_quantum_s"],
                   meta=header.get("meta"))


def reconstruct_trace(journal_path: str, *,
                      step_quantum_s: Optional[float] = None
                      ) -> ArrivalTrace:
    """Rebuild the arrival schedule from a committed router-journal spool.

    Epoch semantics match :func:`~.tracing.load_jsonl_source`: a later
    ``telemetry_epoch`` header marks a ``reset()`` and drops everything
    before it. Arrival timestamps are re-zeroed to the first submit.

    ``step_quantum_s`` defaults to the journal's own arrival cadence
    (median inter-arrival gap, floored at 1 ms) — dense enough that the
    replay preserves the trace's burst structure, coarse enough that idle
    stretches don't spin empty router steps."""
    submits: List[dict] = []
    epoch = 0.0
    with open(journal_path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if rec.get("event") == "telemetry_epoch":
                if rec.get("epoch", 0.0) > epoch:
                    epoch = rec["epoch"]
                    submits = []          # a reset(): earlier window discarded
                continue
            if rec.get("event") == "submit":
                submits.append(rec)
    if not submits:
        raise ValueError(f"{journal_path}: no submit events in the journal")
    missing = [r for r in submits if "prompt" not in r]
    if missing:
        raise ValueError(
            f"{journal_path}: {len(missing)}/{len(submits)} submit events "
            f"have no prompt tokens — the router must run with "
            f"journal_prompts=True for its journal to be replayable "
            f"(prompts are payload, so the default journal omits them)")
    t0 = min(r["ts"] for r in submits)
    arrivals = [Arrival(ts=r["ts"] - t0, prompt=r["prompt"],
                        max_new_tokens=int(r.get("max_new_tokens", 32)),
                        eos_token_id=r.get("eos_token_id"),
                        sla_class=r.get("sla_class"),
                        adapter_id=int(r.get("adapter_id", 0)),
                        trace_id=r.get("trace_id"))
                for r in submits]
    if step_quantum_s is None:
        ts = sorted(a.ts for a in arrivals)
        gaps = sorted(b - a for a, b in zip(ts, ts[1:]) if b > a)
        step_quantum_s = max(gaps[len(gaps) // 2], 1e-3) if gaps else 1e-3
    return ArrivalTrace(arrivals, step_quantum_s,
                        meta={"journal": journal_path,
                              "reconstructed": True})


@dataclass
class ReplayResult:
    """One replay leg's full report: streams, scores, and the audit."""

    tokens: Dict[str, List[int]]            # trace_id -> emitted stream
    steps: int                              # router steps the replay took
    wall_s: float                           # host wall time of the loop
    tokens_total: int
    tokens_per_s: float
    knobs: Dict[str, object]                # candidate settings applied
    coverage: Dict[str, dict] = field(default_factory=dict)   # per replica
    waterfalls: Dict[str, dict] = field(default_factory=dict) # per trace_id
    shed: List[str] = field(default_factory=list)             # trace_ids
    tuner_decisions: List[dict] = field(default_factory=list)
    router_stats: Optional[dict] = None

    @property
    def coverage_ok(self) -> bool:
        """The PR 11 honesty verdict over every replica that traced."""
        return bool(self.coverage) and all(c["ok"]
                                           for c in self.coverage.values())

    def summary(self) -> dict:
        wf = [w for w in self.waterfalls.values()
              if w.get("ttft_ms") is not None]
        mean = lambda xs: (sum(xs) / len(xs)) if xs else None  # noqa: E731
        return {
            "requests": len(self.tokens), "shed": len(self.shed),
            "steps": self.steps, "tokens_total": self.tokens_total,
            "tokens_per_s": round(self.tokens_per_s, 2),
            "coverage_ok": self.coverage_ok,
            "mean_ttft_ms": mean([w["ttft_ms"] for w in wf]),
            "mean_e2e_ms": mean([w["e2e_ms"] for w in wf
                                 if w.get("e2e_ms") is not None]),
            "tuner_decisions": len(self.tuner_decisions),
            "knobs": dict(self.knobs),
        }


def replay(trace: ArrivalTrace, fleet_factory: Callable[[], object], *,
           knobs: Optional[Dict[str, object]] = None,
           tuner_factory: Optional[Callable[[object], object]] = None,
           tick_every: int = 1, tolerance: float = 0.05,
           max_steps: int = 200_000) -> ReplayResult:
    """Re-run ``trace`` on a fresh fleet under candidate ``knobs``.

    ``fleet_factory`` builds a NEW router (replicas attached, telemetry
    enabled for scoring) per call — legs must not share mutable state.
    ``knobs`` are applied through :class:`FleetKnobs` BEFORE any arrival is
    submitted (a candidate is a starting configuration). ``tuner_factory``
    (router → controller with a ``tick()``) makes the leg self-tuning:
    the controller runs every ``tick_every`` replay steps and its decisions
    land in the result's audit trail.

    Determinism: arrival release is indexed by replay step (virtual time),
    not the host clock — see :meth:`ArrivalTrace.release_step`."""
    import time

    router = fleet_factory()
    fleet = FleetKnobs(router=router)
    applied: Dict[str, object] = {}
    for name in sorted(knobs or {}):
        fleet.set(name, knobs[name])
        applied[name] = knobs[name]
    tuner = tuner_factory(router) if tuner_factory is not None else None

    arrivals = trace.arrivals
    rid_to_tid: Dict[int, str] = {}
    shed: List[str] = []
    released = 0
    n = 0
    t_start = time.perf_counter()
    while released < len(arrivals) or router.has_work:
        if n >= max_steps:
            raise RuntimeError(
                f"replay exceeded max_steps={max_steps} with "
                f"{len(arrivals) - released} arrivals unreleased — wedged "
                f"fleet or a quantum far below the service rate")
        vt = n * trace.step_quantum_s
        while released < len(arrivals) and arrivals[released].ts <= vt:
            a = arrivals[released]
            tid = a.trace_id or f"arrival{released}"
            try:
                rid = router.submit(
                    np.asarray(a.prompt, dtype=np.int32),
                    max_new_tokens=a.max_new_tokens,
                    eos_token_id=a.eos_token_id,
                    adapter_id=a.adapter_id, sla_class=a.sla_class)
                rid_to_tid[rid] = tid
            # brown-out shed is a legitimate replay outcome (the candidate
            # thresholds may shed what the original run admitted): recorded,
            # not raised — a what-if must report load shedding, not die on it
            except Exception as e:
                if type(e).__name__ != "RouterOverloaded":
                    raise
                shed.append(tid)
            released += 1
        if router.has_work:
            router.step()
        if tuner is not None and n % max(1, tick_every) == 0:
            tuner.tick()
        if not router.has_work and released < len(arrivals):
            # idle skip-ahead: jump virtual time straight to the next
            # arrival's release step. Deterministic (a pure function of the
            # trace and the drained fleet state) — it only skips steps that
            # would have done nothing, so a journal recorded with long wall
            # gaps (compile pauses, quiet traffic) replays in bounded steps.
            n = max(n + 1, trace.release_step(arrivals[released]))
        else:
            n += 1
    wall = time.perf_counter() - t_start

    tokens = {rid_to_tid[rid]: list(req.generated)
              for rid, req in router.requests.items() if rid in rid_to_tid}
    total = sum(len(v) for v in tokens.values())
    coverage: Dict[str, dict] = {}
    waterfalls: Dict[str, dict] = {}
    for repl_id, rep in sorted(router.replicas.items()):
        tel = rep.runner.telemetry
        if not tel.enabled:
            continue
        coverage[repl_id] = tracing.validate_coverage(
            tel, tolerance=tolerance, source_name=f"replica{repl_id}")
        ts = tracing.build_trace_set(
            tracing.source_from_telemetry(f"replica{repl_id}", tel))
        for _rid, tr in sorted(ts["traces"].items()):
            if tr.get("trace_id") and tr["complete"]:
                waterfalls[tr["trace_id"]] = tracing.waterfall(
                    tr, ts["steps"], tolerance=tolerance)
    return ReplayResult(
        tokens=tokens, steps=n, wall_s=wall, tokens_total=total,
        tokens_per_s=(total / wall if wall > 0 else 0.0), knobs=applied,
        coverage=coverage, waterfalls=waterfalls, shed=shed,
        tuner_decisions=(list(tuner.decisions) if tuner is not None else []),
        router_stats=router.stats())
