"""Fleet-scope request tracing: causal span trees over the serving telemetry.

The observability stack already records everything that happens to a request
— lifecycle events (utils/metrics.ServingTelemetry), the per-dispatch step
timeline, the router's placement decisions — but scattered across N replica
event logs and the router journal. This module turns those streams into ONE
causal span tree per request:

- **Trace context**: a ``trace_id`` minted at ``router.submit()`` (or by a
  standalone runner's telemetry) and threaded through placement →
  ``EngineReplica.submit`` → ``ContinuousBatchingRunner.submit`` →
  ``request_arrival``, so every event a request generates — on any replica it
  ever runs on — carries one joinable key.
- **Span trees**: per request, a root ``request`` span with ``queue_wait``,
  ``placement``, per-window ``prefill_chunk`` spans *linked to the dispatch
  step-timeline record that carried them* (so the PR 7 device-time
  attribution splits them into host/gap/device), ``tier_readmit``,
  ``preempt``/``resume``, a ``decode`` span with per-commit children, and a
  ``finish`` reason.
- **Continuity edges**: a request that migrates off a drained replica
  resumes as a new SEGMENT with a ``migrated_from`` link; a request whose
  replica DIED gets a synthesized ``recovered`` span built from the router's
  own journal (the dead replica's log ends mid-stream; the trace doesn't).
- **Clock model**: every telemetry stream timestamps against one process
  clock (``time.perf_counter``) with a per-stream epoch (its ``_t0``).
  Sources normalize onto the SHARED epoch by adding their epoch back —
  that's the whole clock model, and it is what makes the fleet-merged
  Perfetto export honest (JSONL spools carry a ``telemetry_epoch`` header
  line so offline files merge the same way).
- **Waterfall + reconciliation**: :func:`waterfall` decomposes a request's
  TTFT/E2E into queue-wait / own-prefill / readmit / decode / interference /
  dispatch-gap components measured independently from the step timeline; the
  components must SUM to the recorded TTFT/E2E (a double-counted or
  overlapping step record breaks the sum — reconciliation is the integrity
  test, not a pretty-printer). ``scripts/explain_request.py`` is the CLI.

Everything here is host-side post-processing over already-recorded events:
the serving loop gains NO new work (and no new host syncs) from tracing —
the only live-path additions are the trace-id string on arrival and the
last-exemplar store on histogram observes, both gated on telemetry being
enabled (tests/test_perf_regression.py pins the off path).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["source_from_telemetry", "source_from_router", "load_jsonl_source",
           "build_trace_set", "build_fleet_traces", "validate_trace",
           "validate_coverage", "waterfall", "inflight_span_trees",
           "inflight_span_trees_safe", "merged_chrome_trace",
           "write_merged_chrome_trace", "PREFILL_KINDS", "DECODE_KINDS"]

# step-timeline kinds by role (the event→span classification key)
PREFILL_KINDS = ("insert", "insert_window")
DECODE_KINDS = ("decode", "spec_chunk", "megastep")

# fall-through note origins that are CONTROL-PLANE DECISIONS (brown-out
# transitions, autoscaler actions, tuner knob walks, knob applications) —
# surfaced as zero-duration ``decision`` spans inside every request tree
# whose lifetime covers them, so ``explain_request`` shows WHY the fleet
# changed shape mid-request (ISSUE-18 audit trail)
DECISION_ORIGINS = ("brownout", "autoscaler", "tuner", "knob")

# router-journal events that are fleet-level decisions (no trace_id of
# their own; joined to requests by time overlap in build_fleet_traces)
DECISION_EVENTS = ("brownout", "autoscale", "tuner_decision")
MIXED_KINDS = ("mixed",)


# ---------------------------------------------------------------- sources
def source_from_telemetry(name: str, telemetry) -> dict:
    """Wrap a live ServingTelemetry as a trace source (shares the lists —
    build immediately, don't hold across a reset())."""
    return {"name": name, "events": telemetry.events,
            "steps": telemetry.steps, "epoch": telemetry.epoch}


def source_from_router(router) -> dict:
    """The router journal as a trace source (its placement/migration/recovery
    events; it has no step timeline — the replicas dispatch)."""
    return {"name": "router", "events": router.trace_events, "steps": [],
            "epoch": router.trace_epoch}


def load_jsonl_source(path: str, name: Optional[str] = None) -> dict:
    """Read a ServingTelemetry JSONL spool (or a router journal dump) back
    into a trace source. ``telemetry_epoch`` header lines set the clock
    origin; a LATER epoch line marks a reset() — everything before it
    belongs to a discarded measurement window and is dropped."""
    events: List[dict] = []
    steps: List[dict] = []
    epoch = 0.0
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            ev = rec.get("event")
            if ev == "telemetry_epoch":
                events.clear()
                steps.clear()
                epoch = float(rec["epoch"])
            elif ev == "step":
                steps.append({k: v for k, v in rec.items() if k != "event"})
            elif ev == "device_counters":
                continue
            else:
                events.append(rec)
    return {"name": name or path, "events": events, "steps": steps,
            "epoch": epoch}


# ---------------------------------------------------------------- span trees
def _abs_steps(source: dict) -> List[dict]:
    """Step records with absolute (shared-epoch) t0/t1, sorted by start."""
    epoch = source.get("epoch", 0.0)
    out = []
    for i, s in enumerate(source.get("steps") or []):
        t0 = s["ts"] + epoch
        out.append({"index": i, "t0": t0, "t1": t0 + s.get("dur_s", 0.0),
                    "kind": s.get("kind"), "request_id": s.get("request_id"),
                    "tokens": s.get("tokens", 0),
                    "prefill_tokens": s.get("prefill_tokens", 0)})
    out.sort(key=lambda s: s["t0"])
    return out


def _carrying_step(steps_abs: List[dict], ts: float) -> Optional[dict]:
    """The dispatch record that carried an event: the newest step whose host
    span STARTED at or before the event (lifecycle events are emitted during
    or immediately after the host span of the dispatch that produced them —
    both orders occur in the runner, so matching on start is the invariant)."""
    lo, hi = 0, len(steps_abs)
    while lo < hi:
        mid = (lo + hi) // 2
        if steps_abs[mid]["t0"] <= ts:
            lo = mid + 1
        else:
            hi = mid
    return steps_abs[lo - 1] if lo else None


def _device_split(kind: Optional[str], dur_ms: float,
                  timing: Optional[Dict[str, dict]]) -> Optional[dict]:
    """Split a span's host duration into device/gap components using the
    PR 7 per-kind attribution ratios (None when no timing was profiled or
    the backend reported no device events)."""
    if not timing or kind is None:
        return None
    row = timing.get(kind)
    if row is None and kind in PREFILL_KINDS:
        # insert-family kinds are attributed under one merged "insert" row
        # (runner._attr_family — per-kind rows would double-count shared
        # insert events)
        row = timing.get("insert")
    if not row or not row.get("host_ms") or row.get("device_ms") is None:
        return None
    frac = min(1.0, row["device_ms"] / row["host_ms"])
    return {"device_ms": round(dur_ms * frac, 3),
            "host_gap_ms": round(dur_ms * (1.0 - frac), 3)}


class _TreeBuilder:
    def __init__(self, source_name: str):
        self.spans: List[dict] = []
        self.source = source_name

    def add(self, name: str, kind: str, t0: float, t1: Optional[float],
            parent: Optional[int], **attrs) -> int:
        sid = len(self.spans)
        self.spans.append({"id": sid, "parent": parent, "name": name,
                           "kind": kind, "t0": t0, "t1": t1,
                           "source": self.source,
                           "attrs": {k: v for k, v in attrs.items()
                                     if v is not None}})
        return sid


def build_trace_set(source: dict,
                    timing: Optional[Dict[str, dict]] = None) -> dict:
    """One telemetry stream → ``{"name", "steps": abs-steps,
    "traces": {request_id: trace}}``.

    A trace is ``{"trace_id", "request_id", "source", "complete", "spans",
    "arrival_ts"/"placed_ts"/"first_token_ts"/"finish_ts"}`` with every span
    parented under span 0 (the ``request`` root). ``complete`` means the
    request finished — an in-flight request's open spans have ``t1: None``
    (the span-leak check keys on this)."""
    epoch = source.get("epoch", 0.0)
    steps_abs = _abs_steps(source)
    # control-plane decisions stamped onto the step timeline (the runner's
    # _note_fall_through plumbing): read from the RAW records — _abs_steps
    # deliberately strips extras
    decisions: List[Tuple[int, float, str]] = []
    for i, s in enumerate(source.get("steps") or []):
        ft = s.get("fall_through")
        if not ft:
            continue
        for note in str(ft).split(","):
            if note.split(":", 1)[0] in DECISION_ORIGINS:
                decisions.append((i, s["ts"] + epoch, note))
    by_rid: Dict[int, List[dict]] = {}
    for e in source.get("events") or []:
        rid = e.get("request_id")
        if rid is None:
            continue
        by_rid.setdefault(rid, []).append(e)

    traces: Dict[int, dict] = {}
    for rid, evs in by_rid.items():
        evs = sorted(evs, key=lambda e: e["ts"])
        arrival = next((e for e in evs if e["event"] == "arrival"), None)
        if arrival is None:
            continue          # trimmed log: no tree without a birth record
        t_arr = arrival["ts"] + epoch
        finish = next((e for e in evs if e["event"] == "finish"), None)
        t_fin = finish["ts"] + epoch if finish is not None else None
        tb = _TreeBuilder(source["name"])
        root = tb.add("request", "request", t_arr, t_fin, None,
                      trace_id=arrival.get("trace_id"),
                      prompt_len=arrival.get("prompt_len"),
                      max_new_tokens=arrival.get("max_new_tokens"),
                      finish_reason=(finish.get("reason")
                                     if finish is not None else None),
                      tokens=(finish.get("tokens")
                              if finish is not None else None))
        placed = [e for e in evs if e["event"] == "placed"]
        t_placed = placed[0]["ts"] + epoch if placed else None
        tb.add("queue_wait", "queue_wait", t_arr, t_placed, root)
        for e in placed:
            t = e["ts"] + epoch
            tb.add("resume" if e.get("resumed") else "placement",
                   "placement", t, t, root, slot=e.get("slot"),
                   resumed=e.get("resumed"))
        for e in evs:
            t = e["ts"] + epoch
            if e["event"] == "preempted":
                tb.add("preempt", "preempt", t, t, root,
                       blocks_held=e.get("blocks_held"))
            elif e["event"] == "prefix_hit":
                tb.add("prefix_hit", "prefix_hit", t, t, root,
                       tokens=e.get("tokens"))
            elif e["event"] == "prefill_chunk":
                step = _carrying_step(steps_abs, t)
                if step is not None and (step["kind"] in PREFILL_KINDS
                                         or step["kind"] in MIXED_KINDS):
                    dur_ms = (step["t1"] - step["t0"]) * 1e3
                    tb.add("prefill_chunk", "prefill", step["t0"], step["t1"],
                           root, tokens=e.get("tokens"), pos=e.get("pos"),
                           step_kind=step["kind"], step_index=step["index"],
                           device=_device_split(step["kind"], dur_ms, timing))
                else:
                    tb.add("prefill_chunk", "prefill", t, t, root,
                           tokens=e.get("tokens"), pos=e.get("pos"))
        # this request's own tier re-admissions (stamped by the runner)
        for step in steps_abs:
            if step["kind"] == "tier_readmit" and step["request_id"] == rid:
                tb.add("tier_readmit", "tier_readmit", step["t0"], step["t1"],
                       root, step_index=step["index"],
                       tokens=step["prefill_tokens"])
        first_tok = next((e for e in evs if e["event"] == "first_token"), None)
        if first_tok is not None:
            t_ft = first_tok["ts"] + epoch
            commits = [e for e in evs if e["event"] == "commit"]
            t_last = (commits[-1]["ts"] + epoch) if commits else t_ft
            dec = tb.add("decode", "decode", t_ft,
                         t_last if finish is not None else None, root,
                         tokens=sum(e.get("tokens", 0) for e in commits))
            for e in commits:
                t = e["ts"] + epoch
                step = _carrying_step(steps_abs, t)
                tb.add("decode_commit", "decode_commit", t, t, dec,
                       tokens=e.get("tokens"),
                       step_kind=step["kind"] if step else None,
                       step_index=step["index"] if step else None)
        # zero-duration decision spans: every control-plane decision this
        # request lived through (zero width — waterfall reconciliation and
        # the span-leak check are unaffected by construction)
        for i, t, note in decisions:
            if t >= t_arr and (t_fin is None or t <= t_fin):
                tb.add(f"decision:{note.split('=', 1)[0]}", "decision",
                       t, t, root, note=note, step_index=i)
        traces[rid] = {
            "trace_id": arrival.get("trace_id"), "request_id": rid,
            "source": source["name"], "complete": finish is not None,
            "arrival_ts": t_arr, "placed_ts": t_placed,
            "first_token_ts": (first_tok["ts"] + epoch
                               if first_tok is not None else None),
            "finish_ts": t_fin, "spans": tb.spans,
        }
    return {"name": source["name"], "steps": steps_abs, "traces": traces}


# ---------------------------------------------------------------- validation
def validate_trace(trace: dict) -> List[str]:
    """Structural problems of one span tree: unparented (orphan) spans,
    multiple roots, and — for COMPLETE traces — spans left open (the span
    leak the finish/shed paths must not allow)."""
    problems = []
    ids = {s["id"] for s in trace["spans"]}
    roots = [s for s in trace["spans"] if s["parent"] is None]
    if len(roots) != 1:
        problems.append(f"expected exactly 1 root span, got {len(roots)}")
    for s in trace["spans"]:
        if s["parent"] is not None and s["parent"] not in ids:
            problems.append(f"orphan span {s['id']} ({s['name']}): parent "
                            f"{s['parent']} missing")
        if trace.get("complete") and s["t1"] is None:
            problems.append(f"span {s['id']} ({s['name']}) open after finish")
        if s["t1"] is not None and s["t1"] < s["t0"] - 1e-9:
            problems.append(f"span {s['id']} ({s['name']}) ends before it "
                            f"starts")
    return problems


# ---------------------------------------------------------------- waterfall
def _clip(t0: float, t1: float, lo: float, hi: float) -> float:
    return max(0.0, min(t1, hi) - max(t0, lo))


def _union_len(intervals: List[Tuple[float, float]]) -> float:
    total, end = 0.0, None
    for a, b in sorted(intervals):
        if end is None or a > end:
            total += b - a
            end = b
        elif b > end:
            total += b - end
            end = b
    return total


def waterfall(trace: dict, steps_abs: List[dict],
              timing: Optional[Dict[str, dict]] = None,
              tolerance: float = 0.05) -> dict:
    """Latency decomposition of one request from the step timeline.

    Components (ms): ``queue_wait`` (arrival→placed), then — over
    [placed, first_token] for TTFT and [placed, finish] for E2E — the
    clipped host spans of every overlapping dispatch record, classified:

    - ``prefill``: dispatches that carried THIS request's prefill windows
      (linked via the span tree), plus its own ``tier_readmit`` restores
      (reported separately as ``tier_readmit``);
    - ``decode``: decode-family dispatches after this request's first token
      (continuous batching advances every live row, ours included);
    - ``decode_interference``: decode-family dispatches BEFORE our first
      token (residents decoding while our prefill waits);
    - ``prefill_interference``: insert-family dispatches carrying OTHER
      requests' windows;
    - ``dispatch_gap``: wall time covered by NO dispatch record (host
      scheduling / commit / dispatch-floor time).

    RECONCILIATION: ``dispatch_gap`` is measured independently (window minus
    the UNION of dispatch intervals), so the component sum equals the
    recorded TTFT/E2E only if the step records partition the timeline —
    overlapping or double-counted records break the sum. ``reconciled`` is
    the |sum − recorded| ≤ tolerance × recorded verdict for both windows."""
    t_arr, t_placed = trace["arrival_ts"], trace["placed_ts"]
    t_ft, t_fin = trace["first_token_ts"], trace["finish_ts"]
    out = {"request_id": trace["request_id"], "trace_id": trace["trace_id"],
           "complete": trace["complete"], "reconciled": False,
           "ttft_ms": None, "e2e_ms": None}
    if t_placed is None or t_ft is None:
        out["error"] = "incomplete trace: no placement / first token"
        return out
    own_prefill_steps = {s["attrs"]["step_index"] for s in trace["spans"]
                         if s["kind"] == "prefill"
                         and "step_index" in s["attrs"]}
    own_readmit_steps = {s["attrs"]["step_index"] for s in trace["spans"]
                        if s["kind"] == "tier_readmit"
                        and "step_index" in s["attrs"]}

    def decompose(lo: float, hi: float) -> Dict[str, float]:
        comp = {"queue_wait": (t_placed - t_arr) * 1e3, "prefill": 0.0,
                "tier_readmit": 0.0, "decode": 0.0,
                "decode_interference": 0.0, "prefill_interference": 0.0,
                "dispatch_gap": 0.0}
        by_kind: Dict[str, float] = {}
        covered: List[Tuple[float, float]] = []
        for s in steps_abs:
            dur = _clip(s["t0"], s["t1"], lo, hi)
            if dur <= 0.0:
                continue
            covered.append((max(s["t0"], lo), min(s["t1"], hi)))
            kind = s["kind"]
            if s["index"] in own_prefill_steps:
                cat = "prefill"
            elif s["index"] in own_readmit_steps:
                cat = "tier_readmit"
            elif kind in PREFILL_KINDS or kind == "tier_readmit":
                cat = "prefill_interference"
            elif max(s["t0"], lo) >= t_ft:
                cat = "decode"
            else:
                cat = "decode_interference"
            comp[cat] += dur * 1e3
            by_kind[kind] = by_kind.get(kind, 0.0) + dur * 1e3
        comp["dispatch_gap"] = ((hi - lo) - _union_len(covered)) * 1e3
        comp["_by_kind"] = by_kind
        return comp

    ttft_ms = (t_ft - t_arr) * 1e3
    out["ttft_ms"] = round(ttft_ms, 3)
    ttft_comp = decompose(t_placed, t_ft)
    by_kind_ttft = ttft_comp.pop("_by_kind")
    ttft_sum = sum(ttft_comp.values())
    out["ttft_components_ms"] = {k: round(v, 3)
                                 for k, v in ttft_comp.items()}
    out["ttft_residual_frac"] = (abs(ttft_sum - ttft_ms)
                                 / max(ttft_ms, 1e-9))
    ok = out["ttft_residual_frac"] <= tolerance
    if trace["complete"] and t_fin is not None:
        e2e_ms = (t_fin - t_arr) * 1e3
        out["e2e_ms"] = round(e2e_ms, 3)
        e2e_comp = decompose(t_placed, t_fin)
        e2e_comp.pop("_by_kind")
        e2e_sum = sum(e2e_comp.values())
        out["e2e_components_ms"] = {k: round(v, 3)
                                    for k, v in e2e_comp.items()}
        out["e2e_residual_frac"] = (abs(e2e_sum - e2e_ms)
                                    / max(e2e_ms, 1e-9))
        ok = ok and out["e2e_residual_frac"] <= tolerance
    if timing:
        split = {}
        for kind, ms in by_kind_ttft.items():
            d = _device_split(kind, ms, timing)
            if d is not None:
                split[kind] = d
        if split:
            out["ttft_device_split_ms"] = split
    out["reconciled"] = ok
    return out


def validate_coverage(telemetry, tolerance: float = 0.05,
                      timing: Optional[Dict[str, dict]] = None,
                      source_name: str = "runner") -> dict:
    """The bench honesty guard: EVERY request in the telemetry's event log
    must yield a complete, structurally valid span tree whose waterfall
    reconciles within ``tolerance`` — otherwise the caller refuses to
    publish (``trace_coverage_invalid``, the r5 pattern)."""
    ts = build_trace_set(source_from_telemetry(source_name, telemetry),
                         timing=timing)
    incomplete, orphans, unreconciled = [], [], []
    max_resid = 0.0
    for rid, trace in sorted(ts["traces"].items()):
        if not trace["complete"]:
            incomplete.append(rid)
            continue
        if validate_trace(trace):
            orphans.append(rid)
            continue
        wf = waterfall(trace, ts["steps"], timing=timing,
                       tolerance=tolerance)
        for key in ("ttft_residual_frac", "e2e_residual_frac"):
            if wf.get(key) is not None:
                max_resid = max(max_resid, wf[key])
        if not wf["reconciled"]:
            unreconciled.append(rid)
    n = len(ts["traces"])
    ok = n > 0 and not (incomplete or orphans or unreconciled)
    reason = None
    if n == 0:
        reason = "no traced requests in the event log"
    elif incomplete:
        reason = f"incomplete span trees for requests {incomplete[:8]}"
    elif orphans:
        reason = f"structurally invalid trees for requests {orphans[:8]}"
    elif unreconciled:
        reason = (f"waterfall components do not reconcile within "
                  f"{tolerance:.0%} for requests {unreconciled[:8]}")
    return {"ok": ok, "requests": n, "incomplete": incomplete,
            "orphans": orphans, "unreconciled": unreconciled,
            "max_residual_frac": round(max_resid, 5), "reason": reason}


def inflight_span_trees(telemetry) -> List[dict]:
    """Span trees of every request still in flight — what the flight
    recorder embeds in a debug bundle so a post-mortem shows exactly where
    each live request was when the dump fired."""
    ts = build_trace_set(source_from_telemetry("runner", telemetry))
    return [t for _rid, t in sorted(ts["traces"].items())
            if not t["complete"]]


def inflight_span_trees_safe(telemetry) -> Optional[List[dict]]:
    """The crash-path variant: every debug-bundle dump site enriches with
    span trees THROUGH this guard, so a tracing failure can never mask the
    fault being dumped (None = enrichment unavailable, bundle still lands)."""
    try:
        return inflight_span_trees(telemetry)
    # lint: ok(silent-except): best-effort bundle enrichment on the crash path; the dump itself must never be masked by it
    except Exception:
        return None


# ---------------------------------------------------------------- fleet merge
def build_fleet_traces(replica_sources: Sequence[dict],
                       router_source: Optional[dict] = None,
                       timing: Optional[Dict[str, Dict[str, dict]]] = None
                       ) -> Dict[str, dict]:
    """Merge N replicas' span trees (plus the router journal) into one
    fleet-level trace per ``trace_id``.

    Each fleet trace has ONE root ``request`` span; each replica visit is a
    ``segment:<replica>`` child (the replica-local tree re-parented under
    it). Continuity edges: segment k>0 carries ``migrated_from``
    (drain/migration) or ``recovered_from`` (the replica DIED — a
    ``recovered`` span synthesized from the router journal covers the
    failure-to-resubmit window, and the dead segment's open spans are closed
    at the recovery boundary so the merged tree leaks nothing). Router
    placement/queue spans ride under the root when a journal is given; a
    pool KV handoff (serving/pools.py) adds a ``handoff`` span bridging the
    prefill-pool and decode-pool segments (start→commit/abort window)."""
    sets = {src["name"]: build_trace_set(
        src, timing=(timing or {}).get(src["name"]))
        for src in replica_sources}
    by_tid: Dict[str, List[dict]] = {}
    for name, ts in sets.items():
        for trace in ts["traces"].values():
            tid = trace.get("trace_id")
            if tid is not None:
                by_tid.setdefault(tid, []).append(trace)
    router_by_tid: Dict[str, List[dict]] = {}
    r_decisions: List[dict] = []
    r_epoch = router_source.get("epoch", 0.0) if router_source else 0.0
    if router_source:
        for e in router_source.get("events") or []:
            tid = e.get("trace_id")
            if tid is not None:
                router_by_tid.setdefault(tid, []).append(e)
            elif e.get("event") in DECISION_EVENTS:
                # fleet-level decisions carry no trace_id: joined to every
                # request whose lifetime covers them (below)
                r_decisions.append(e)
    out: Dict[str, dict] = {}
    for tid in set(by_tid) | set(router_by_tid):
        segments = sorted(by_tid.get(tid, ()),
                          key=lambda t: t["arrival_ts"])
        r_evs = sorted(router_by_tid.get(tid, ()), key=lambda e: e["ts"])
        submit = next((e for e in r_evs if e["event"] == "submit"), None)
        r_finish = next((e for e in r_evs if e["event"] == "finish"), None)
        t0 = (submit["ts"] + r_epoch if submit is not None
              else segments[0]["arrival_ts"] if segments else 0.0)
        fins = [s["finish_ts"] for s in segments if s["finish_ts"] is not None]
        t1 = (r_finish["ts"] + r_epoch if r_finish is not None
              else max(fins) if fins and segments[-1]["complete"] else None)
        tb = _TreeBuilder("fleet")
        root = tb.add("request", "request", t0, t1, None, trace_id=tid,
                      segments=len(segments),
                      frontend_request_id=(submit.get("request_id")
                                           if submit else None),
                      # SLA class (serving/sla.py): the tier this request
                      # served under — journaled at submit, so a waterfall
                      # can be sliced by tenant class
                      sla_class=(submit.get("sla_class")
                                 if submit else None))
        # router-altitude spans: frontend queue wait + every placement
        places = [e for e in r_evs if e["event"] == "place"]
        if submit is not None:
            tb.add("queue_wait", "queue_wait", t0,
                   places[0]["ts"] + r_epoch if places else None, root,
                   altitude="router")
        for e in places:
            t = e["ts"] + r_epoch
            tb.add("placement", "placement", t, t, root, altitude="router",
                   replica=e.get("replica"), local_id=e.get("local_id"),
                   affinity_blocks=e.get("affinity_blocks"),
                   spilled_from_blocks=e.get("spilled_from"),
                   migration=e.get("migrations", 0) > 0)
        h_start = None       # at most one live handoff per request at a time
        for e in r_evs:
            t = e["ts"] + r_epoch
            if e["event"] == "migrate_out":
                tb.add("migration", "migration", t, t, root,
                       altitude="router", from_replica=e.get("from_replica"))
            elif e["event"] == "handoff_start":
                h_start = e
            elif e["event"] in ("handoff_done", "handoff_abort"):
                # the pool-to-pool KV handoff span (serving/pools.py): spans
                # from the transfer opening to commit/abort, JOINING the
                # prefill-pool and decode-pool segments of this trace — the
                # overlap window where blocks moved while prefill still ran
                t0h = (h_start["ts"] + r_epoch) if h_start is not None else t
                tb.add("handoff", "handoff", t0h, t, root, altitude="router",
                       from_replica=e.get("from_replica"),
                       to_replica=e.get("to_replica"),
                       channel=(e.get("channel")
                                or (h_start or {}).get("channel")),
                       blocks=e.get("blocks", e.get("staged_blocks")),
                       overlap_blocks=e.get("overlap_blocks"),
                       latency_ms=e.get("latency_ms"),
                       aborted=e["event"] == "handoff_abort",
                       abort_reason=e.get("reason"))
                h_start = None
            elif e["event"] == "recover":
                nxt = next((p["ts"] + r_epoch for p in places
                            if p["ts"] >= e["ts"]), None)
                # the synthesized span: the dead replica cannot report this
                # window; the router journal is the only witness
                tb.add("recovered", "recovered", t, nxt if nxt else t, root,
                       altitude="router", from_replica=e.get("from_replica"),
                       resumed_tokens=e.get("resumed_tokens"))
        # router-altitude decision spans (zero duration): the brown-out /
        # autoscale / tuner decisions this request lived through
        for e in r_decisions:
            t = e["ts"] + r_epoch
            if t >= t0 and (t1 is None or t <= t1):
                attrs = {k: v for k, v in e.items()
                         if k not in ("ts", "event")}
                tb.add(f"decision:{e['event']}", "decision", t, t, root,
                       altitude="router", **attrs)
        recovers = [e for e in r_evs if e["event"] == "recover"]
        for i, seg in enumerate(segments):
            edge = {}
            if i > 0:
                prev = segments[i - 1]
                recovered = any(prev["arrival_ts"] <= e["ts"] + r_epoch
                                <= seg["arrival_ts"] for e in recovers)
                edge = ({"recovered_from": prev["source"]} if recovered
                        else {"migrated_from": prev["source"]})
            seg_root = tb.add(f"segment:{seg['source']}", "segment",
                              seg["arrival_ts"],
                              seg["finish_ts"], root,
                              replica=seg["source"],
                              local_request_id=seg["request_id"], **edge)
            # boundary to close a dead/abandoned segment's open spans at:
            # the next segment's arrival (the stream provably moved on)
            boundary = (segments[i + 1]["arrival_ts"]
                        if i + 1 < len(segments) else None)
            id_map = {}
            for s in seg["spans"]:
                t1s = s["t1"]
                closed_by = None
                if t1s is None and boundary is not None:
                    t1s, closed_by = boundary, edge or "handoff"
                parent = (seg_root if s["parent"] is None
                          else id_map[s["parent"]])
                attrs = dict(s["attrs"])
                if closed_by:
                    attrs["closed_at_handoff"] = True
                sid = tb.add(s["name"], s["kind"], s["t0"], t1s, parent,
                             **attrs)
                id_map[s["id"]] = sid
            if boundary is not None and seg["spans"] and seg_root is not None:
                # the abandoned segment itself closes at the hand-off
                if tb.spans[seg_root]["t1"] is None:
                    tb.spans[seg_root]["t1"] = boundary
        complete = (segments[-1]["complete"] if segments else False) and (
            r_finish is not None or router_source is None or not r_evs)
        out[tid] = {"trace_id": tid, "complete": complete,
                    "segments": [s["source"] for s in segments],
                    "frontend_request_id": (submit.get("request_id")
                                            if submit else None),
                    "arrival_ts": t0, "finish_ts": t1, "spans": tb.spans,
                    # waterfall over a fleet trace uses the LAST segment's
                    # replica-local view (its steps carried the finish)
                    "last_segment": segments[-1] if segments else None}
    return out


# ---------------------------------------------------------------- perfetto
def _chrome_events_for_source(pid: int, source: dict, epoch0: float,
                              trace_ids: Dict[int, str]) -> List[dict]:
    evs: List[dict] = [
        {"name": "process_name", "ph": "M", "pid": pid,
         "args": {"name": source["name"]}},
        {"name": "thread_name", "ph": "M", "pid": pid, "tid": 0,
         "args": {"name": f"{source['name']}:steps"}},
        {"name": "thread_name", "ph": "M", "pid": pid, "tid": 1,
         "args": {"name": f"{source['name']}:requests"}},
    ]
    shift = source.get("epoch", 0.0) - epoch0
    for s in source.get("steps") or []:
        args = {k: v for k, v in s.items() if k not in ("ts", "dur_s")}
        evs.append({"name": f"step:{s['kind']}", "ph": "X", "cat": "step",
                    "ts": (s["ts"] + shift) * 1e6,
                    "dur": s.get("dur_s", 0.0) * 1e6,
                    "pid": pid, "tid": 0, "args": args})
    # per-request bookkeeping so every async begin this source opens is
    # CLOSED by this source: a segment abandoned mid-stream (migration /
    # replica death) otherwise dangles to end-of-trace in Perfetto
    open_at: Dict[object, float] = {}      # tid_str -> last event ts
    closed = set()
    for e in source.get("events") or []:
        args = {k: v for k, v in e.items() if k not in ("ts", "event")}
        rid = e.get("request_id")
        tid_str = e.get("trace_id") or trace_ids.get(rid)
        if tid_str is not None:
            args["trace_id"] = tid_str
        evs.append({"name": e["event"], "ph": "i", "s": "t", "cat": "request",
                    "ts": (e["ts"] + shift) * 1e6, "pid": pid, "tid": 1,
                    "args": args})
        # async begin/end per request: same (cat, id) across processes, so
        # a migrated request's segments join on one async track chain
        # (replica streams open at `arrival`, the router's at `submit`)
        if tid_str is None:
            continue
        if e["event"] in ("arrival", "submit"):
            evs.append({"name": f"request:{tid_str}", "ph": "b",
                        "cat": "request_span", "id": tid_str,
                        "ts": (e["ts"] + shift) * 1e6, "pid": pid, "tid": 1,
                        "args": {"trace_id": tid_str}})
            open_at[tid_str] = e["ts"]
        elif tid_str in open_at:
            open_at[tid_str] = e["ts"]
            if e["event"] == "finish":
                evs.append({"name": f"request:{tid_str}", "ph": "e",
                            "cat": "request_span", "id": tid_str,
                            "ts": (e["ts"] + shift) * 1e6, "pid": pid,
                            "tid": 1, "args": {"trace_id": tid_str}})
                closed.add(tid_str)
    for tid_str, last_ts in open_at.items():
        if tid_str in closed:
            continue
        # abandoned (migrated/recovered-away) or still-in-flight segment:
        # close at this source's last sighting, visibly marked
        evs.append({"name": f"request:{tid_str}", "ph": "e",
                    "cat": "request_span", "id": tid_str,
                    "ts": (last_ts + shift) * 1e6, "pid": pid, "tid": 1,
                    "args": {"trace_id": tid_str,
                             "closed": "end_of_stream"}})
    return evs


def merged_chrome_trace(replica_sources: Sequence[dict],
                        router_source: Optional[dict] = None) -> dict:
    """ONE Chrome/Perfetto trace for the whole fleet: router + N replicas as
    separate processes with replica-prefixed tracks, every timestamp
    normalized onto the shared epoch (the earliest source epoch — all
    sources share one ``time.perf_counter`` clock in-process, and JSONL
    epoch headers restore the same relation offline). Replaces the
    per-replica-only exports the scale-out split shipped with (same-name
    device programs still cannot share one xplane trace — DEVICE attribution
    stays per-solo-window; this merge is the host-side timeline)."""
    sources = list(replica_sources)
    all_sources = sources + ([router_source] if router_source else [])
    if not all_sources:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    epoch0 = min(s.get("epoch", 0.0) for s in all_sources)
    evs: List[dict] = []
    if router_source is not None:
        evs += _chrome_events_for_source(0, router_source, epoch0, {})
    for i, src in enumerate(sources):
        trace_ids = {e.get("request_id"): e.get("trace_id")
                     for e in (src.get("events") or [])
                     if e.get("event") == "arrival" and e.get("trace_id")}
        evs += _chrome_events_for_source(i + 1, src, epoch0, trace_ids)
    return {"traceEvents": evs, "displayTimeUnit": "ms"}


def write_merged_chrome_trace(path: str,
                              replica_sources: Sequence[dict],
                              router_source: Optional[dict] = None) -> str:
    with open(path, "w") as fh:
        json.dump(merged_chrome_trace(replica_sources, router_source), fh)
    return path
