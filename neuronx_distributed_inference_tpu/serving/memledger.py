"""Accountable KV memory: a per-replica block ledger with per-request
attribution, conservation auditing, and OOM forensics.

KV-block capacity is the admission signal, the autoscaler input, and the
migration currency of the whole serving stack — yet the paged allocator's
refcounts, idle pool, host tier, and in-flight readmit reservations are
trusted bookkeeping that nothing audits. A leaked block silently shrinks
capacity forever, and ``KVBlocksExhausted`` fires with no record of who
holds what. This module is the memory analog of the PR 7 time attribution:
every physical block is attributed to an OWNER STATE, and a conservation
auditor proves — bit for bit, at every hand-off — that the bookkeeping
balances. All host-side: zero new dispatches, zero new host syncs.

Owner-state machine (a disjoint partition of the device pool)::

    free ──alloc──▶ live(request_id) ──release──▶ free
                      │  ▲                   └──▶ idle(hash)     (tiered:
                      │  │ reactivate / alloc-reclaim(spill)      hashed
                      │  │                                        blocks park)
                      │  idle(hash)
                      │
      tier hit: alloc + tier.reserve(hash)
                      ▼
           host_reserved(hash)  ── take_pending_readmits ──▶ readmit_inflight
                                   ── readmit dispatch commits ──▶ live

``free``            on the allocator free list.
``live``            refcounted; holders attributed per request (shared
                    prefix blocks carry one holder entry per sharer, and
                    the per-block holder sum must equal the refcount).
``idle``            the tiered allocator's idle pool (refcount 0,
                    device-resident, hash registered — allocatable headroom).
``host_reserved``   allocated for a host-tier prefix hit; the reserved host
                    bytes sit in the allocator's pending-readmit queue.
``readmit_inflight`` taken by the runner for the readmit scatter but not yet
                    committed — a block stuck here is an orphaned readmit.
``handoff_inflight`` allocated on a DECODE-pool replica as the destination of
                    a live prefill→decode KV handoff (serving/pools.py):
                    bytes staged by the ``cb.paged.kv_handoff`` scatter but
                    the hash not yet published. Held by a negative-id handoff
                    session (the runner's roster includes open sessions), so
                    an abandoned session shows up as an attributed leak.
                    Unlike ``readmit_inflight`` the state legitimately spans
                    many steps — the transfer overlaps the source replica's
                    remaining prefill chunks.

The ledger maintains this machine by wrapping the EXISTING seams
(``BlockAllocator._alloc_one``/``_release_one``, the tiered allocator's
reactivate/spill/pending-readmit flow) at instance level — the same idiom
the fault injector uses — with the runner supplying attribution context
(request id, seam name, SLA class) around its allocator calls.

``audit()`` is the conservation check: free + live + idle + host_reserved +
readmit_inflight + handoff_inflight == num_blocks, the ledger's view matches the allocator's
actual structures (free list, refcounts, idle pool, hash bijection, pending
queue), per-block holder sums match refcounts, and — given the runner's
expected-holder roster — every held block belongs to a live request. A
dropped release (the ``leak`` fault kind, serving/faults.py) shows up as a
block held by a request that no longer exists, attributed to the exact
request id and the seam that last touched it. Violations raise in tests
(the autouse conftest fixture) and emit ONE structured
``memledger_violation {json}`` line + a counter in serving.

On top of the ledger: fragmentation / idle-age / host-tier telemetry
(``serving_kv_blocks{state=}``, ``serving_kv_idle_age_seconds{quantile=}``,
``serving_kv_bytes{sla_class=}``), per-request byte attribution in
``stats()["memory"]``, and OOM forensics — ``KVBlocksExhausted`` carries a
``ledger_snapshot`` naming the top holders, so "out of KV blocks" is
answerable (scripts/explain_memory.py renders it).
"""

from __future__ import annotations

import contextlib
import json
import logging
import time
import weakref
from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as np

from ..modules.block_kvcache import KVBlocksExhausted

logger = logging.getLogger("tpu-inference")

__all__ = ["BlockLedger", "MemLedgerViolation", "STATES",
           "FREE", "LIVE", "IDLE", "HOST_RESERVED", "READMIT_INFLIGHT",
           "HANDOFF_INFLIGHT",
           "note_runner", "live_runners", "snapshot_safe", "timeline_safe"]

FREE = "free"
LIVE = "live"
IDLE = "idle"
HOST_RESERVED = "host_reserved"
READMIT_INFLIGHT = "readmit_inflight"
HANDOFF_INFLIGHT = "handoff_inflight"
STATES = (FREE, LIVE, IDLE, HOST_RESERVED, READMIT_INFLIGHT,
          HANDOFF_INFLIGHT)

# bounded per-request holdings timeline (events per request / requests kept)
TIMELINE_EVENTS_PER_REQUEST = 64
TIMELINE_REQUESTS = 1024


class MemLedgerViolation(RuntimeError):
    """The conservation audit found the bookkeeping out of balance. Carries
    the full audit report in ``.report``."""

    def __init__(self, report: dict):
        self.report = report
        head = report["violations"][0] if report["violations"] else {}
        super().__init__(
            f"KV block ledger audit failed: {len(report['violations'])} "
            f"violation(s); first: {head}")


class _Ctx:
    """Attribution context for one runner seam (who is allocating/releasing,
    from where). ``credits`` collects the holder credits the inner wrapped
    calls recorded during one ``allocate_for_prompt``, so the post-call
    reconcile can credit refcount-share prefix hits the internals never
    surface."""

    __slots__ = ("request_id", "seam", "sla_class", "credits",
                 "expect_exhaustion")

    def __init__(self, request_id, seam, sla_class,
                 expect_exhaustion=False):
        self.request_id = request_id
        self.seam = seam
        self.sla_class = sla_class
        self.expect_exhaustion = expect_exhaustion
        self.credits: Dict[int, int] = {}


class _Rec:
    """One non-free block's ledger record."""

    __slots__ = ("state", "hash", "since", "holders", "seam")

    def __init__(self, state, hash_, since, holders, seam):
        self.state = state
        self.hash = hash_          # bytes or None (live blocks may be hashed)
        self.since = since         # last state-transition timestamp
        self.holders = holders     # {request_id_or_None: count}
        self.seam = seam           # seam of the last transition


class BlockLedger:
    """Per-replica KV block ledger over one (Python) block allocator.

    ``allocator`` must expose the Python seams (``_alloc_one`` /
    ``_release_one``); the native C++ allocator is opaque and cannot be
    ledgered (``ContinuousBatchingRunner(memledger=True)`` selects the
    Python allocator when a ledger is required). ``attach()`` wraps the
    seams at instance level and is called by the constructor."""

    def __init__(self, allocator, tier=None, registry=None,
                 replica: Optional[str] = None):
        self.allocator = allocator
        self.tier = tier
        self.num_blocks = int(allocator.num_blocks)
        self.replica = replica
        self.records: Dict[int, _Rec] = {}     # absent = free
        self.request_class: Dict[int, Optional[str]] = {}
        self.request_log: "OrderedDict[int, List[dict]]" = OrderedDict()
        self.bytes_per_block = 0               # set by the owning runner
        self.last_oom: Optional[dict] = None
        self._last_oom_t = 0.0          # snapshot-rebuild rate limiter
        self._known_leaked: set = set()
        self._seen_violation_sigs: set = set()
        self._ctx: Optional[_Ctx] = None
        self._t0 = time.monotonic()
        self._registry = registry
        if registry is not None:
            self._c_violations = registry.counter(
                "memledger_violations_total",
                "KV block ledger conservation-audit violations")
            self._c_leaked = registry.counter(
                "serving_kv_leaked_blocks_total",
                "KV blocks found held by no live request (leaked)")
            self._c_oom = registry.counter(
                "serving_kv_oom_events_total",
                "KVBlocksExhausted raises captured with a ledger snapshot")
        else:
            self._c_violations = self._c_leaked = self._c_oom = None
        self.attach()

    # ------------------------------------------------------------------ context
    @contextlib.contextmanager
    def context(self, request_id=None, seam: str = "",
                sla_class: Optional[str] = None,
                expect_exhaustion: bool = False):
        """Attribution scope for one runner seam: allocations/releases inside
        credit/debit ``request_id`` and stamp ``seam`` on the transitions.
        ``expect_exhaustion``: this seam PROBES for headroom and handles
        ``KVBlocksExhausted`` as designed degradation (megastep partial
        reservation, the preempting grower) — the OOM forensics capture is
        suppressed so normal tight-pool operation does not read as a stream
        of phantom OOM events."""
        prev = self._ctx
        self._ctx = _Ctx(request_id, seam, sla_class,
                         expect_exhaustion=expect_exhaustion)
        if request_id is not None:
            self.request_class[request_id] = sla_class
        try:
            yield
        finally:
            self._ctx = prev

    def _now(self) -> float:
        return time.monotonic()

    def _log(self, rid, event: str, **fields) -> None:
        if rid is None:
            return
        log = self.request_log.get(rid)
        if log is None:
            log = self.request_log[rid] = []
            while len(self.request_log) > TIMELINE_REQUESTS:
                self.request_log.popitem(last=False)
        log.append({"t": round(self._now() - self._t0, 6), "event": event,
                    **fields})
        del log[:-TIMELINE_EVENTS_PER_REQUEST]

    # ------------------------------------------------------------------ attach
    def attach(self) -> None:
        """Wrap the allocator's seams at instance level (the fault-injector
        idiom: later wrappers — e.g. an injected ``leak`` — compose on top)."""
        alloc = self.allocator
        real_alloc = alloc._alloc_one
        real_release = alloc._release_one
        real_prompt = alloc.allocate_for_prompt

        def _alloc_one():
            try:
                blk = real_alloc()
            except KVBlocksExhausted as e:
                # designed headroom probes (megastep partial reservation,
                # the preempting grower) handle this raise as steady-state
                # degradation — no forensics capture for those
                if self._ctx is None or not self._ctx.expect_exhaustion:
                    self.note_exhaustion(
                        self._ctx.seam if self._ctx else "unknown", exc=e)
                raise
            self._on_alloc(blk)
            return blk

        def _release_one(blk):
            real_release(blk)
            self._on_release(blk)

        def allocate_for_prompt(tokens):
            ctx = self._ctx
            if ctx is None:
                ctx = self._ctx = _Ctx(None, "unattributed", None)
                anon = True
            else:
                anon = False
            ctx.credits = {}
            pend = getattr(alloc, "_pending_readmits", None)
            n_pend0 = len(pend) if pend is not None else 0
            try:
                blocks, cached = real_prompt(tokens)
            finally:
                if anon:
                    self._ctx = None
            # refcount-share prefix hits increment refcounts without touching
            # _alloc_one/_reactivate — reconcile the holder credits here
            need: Dict[int, int] = {}
            for blk in blocks:
                need[blk] = need.get(blk, 0) + 1
            for blk, n in need.items():
                extra = n - ctx.credits.get(blk, 0)
                if extra > 0:
                    rec = self.records.get(blk)
                    if rec is not None:
                        rec.holders[ctx.request_id] = (
                            rec.holders.get(ctx.request_id, 0) + extra)
            # host-tier hits queued a readmit: those blocks are allocated but
            # their KV bytes are still host-side reservations
            if pend is not None:
                now = self._now()
                for blk, h, _hb in pend[n_pend0:]:
                    rec = self.records.get(blk)
                    if rec is not None:
                        rec.state = HOST_RESERVED
                        rec.hash = h
                        rec.since = now
            self._log(ctx.request_id, "allocate", seam=ctx.seam,
                      blocks=len(blocks), cached_tokens=int(cached),
                      readmits=(len(pend) - n_pend0 if pend is not None
                                else 0))
            return blocks, cached

        alloc._alloc_one = _alloc_one
        alloc._release_one = _release_one
        alloc.allocate_for_prompt = allocate_for_prompt

        if hasattr(alloc, "_reactivate"):
            real_reactivate = alloc._reactivate

            def _reactivate(blk):
                real_reactivate(blk)
                self._on_reactivate(blk)

            alloc._reactivate = _reactivate
        if hasattr(alloc, "spill_idle"):
            real_spill_idle = alloc.spill_idle

            def spill_idle(keep=0):
                n = real_spill_idle(keep)
                # spilled idle blocks returned to the free list
                idle_now = alloc.idle
                for blk in [b for b, r in self.records.items()
                            if r.state == IDLE and b not in idle_now]:
                    del self.records[blk]
                return n

            alloc.spill_idle = spill_idle
        if hasattr(alloc, "take_pending_readmits"):
            real_take = alloc.take_pending_readmits

            def take_pending_readmits():
                out = real_take()
                now = self._now()
                for blk, _h, _hb in out:
                    rec = self.records.get(blk)
                    if rec is not None and rec.state == HOST_RESERVED:
                        rec.state = READMIT_INFLIGHT
                        rec.since = now
                return out

            alloc.take_pending_readmits = take_pending_readmits

    # -------------------------------------------------------------- transitions
    def _on_alloc(self, blk: int) -> None:
        ctx = self._ctx
        rid = ctx.request_id if ctx else None
        seam = ctx.seam if ctx else "unattributed"
        self.records[blk] = _Rec(LIVE, None, self._now(), {rid: 1}, seam)
        if ctx is not None:
            ctx.credits[blk] = ctx.credits.get(blk, 0) + 1

    def _on_reactivate(self, blk: int) -> None:
        ctx = self._ctx
        rid = ctx.request_id if ctx else None
        rec = self.records.get(blk)
        h = getattr(self.allocator, "block_to_hash", {}).get(blk)
        if rec is None:
            rec = self.records[blk] = _Rec(LIVE, h, self._now(), {}, "")
        rec.state = LIVE
        rec.hash = h
        rec.since = self._now()
        rec.seam = ctx.seam if ctx else "unattributed"
        rec.holders = {rid: 1}
        if ctx is not None:
            ctx.credits[blk] = ctx.credits.get(blk, 0) + 1

    def _on_release(self, blk: int) -> None:
        alloc = self.allocator
        ctx = self._ctx
        rid = ctx.request_id if ctx else None
        seam = ctx.seam if ctx else "unattributed"
        rec = self.records.get(blk)
        if blk in alloc.refcount:
            # still live under other holders: debit the releasing request's
            # credit. A release with NO credit for this request debits
            # nothing — that happens legitimately only when an exhaustion
            # rollback returns a share-hit the post-call reconcile never got
            # to credit (the refcounts balance again once the rollback
            # completes); debiting another holder would paper over a real
            # mis-attributed release, which the audit must surface instead.
            if rec is not None:
                if rec.holders.get(rid, 0) > 0:
                    rec.holders[rid] -= 1
                    if rec.holders[rid] == 0:
                        del rec.holders[rid]
                rec.seam = seam
            self._log(rid, "release_shared", seam=seam, block=blk)
            return
        idle = getattr(alloc, "idle", None)
        if idle is not None and blk in idle:
            h = getattr(alloc, "block_to_hash", {}).get(blk)
            self.records[blk] = _Rec(IDLE, h, self._now(), {}, seam)
        else:
            self.records.pop(blk, None)
        self._log(rid, "release", seam=seam, block=blk)

    def readmit_committed(self, block_ids) -> None:
        """The readmit scatter landed: the named blocks' KV is device-resident
        again (runner._dispatch_readmits calls this per committed chunk)."""
        now = self._now()
        for blk in block_ids:
            rec = self.records.get(int(blk))
            if rec is not None and rec.state == READMIT_INFLIGHT:
                rec.state = LIVE
                rec.since = now

    def readmit_written_off(self, blk: int) -> None:
        """Crash recovery reconciled a dead replica's pending readmit back to
        the host store (serving/router.recover_replica): the device block
        stays allocated to its (ghost) holder but is plain live content-wise
        — without this the dead runner's ledger would report a stuck
        in-flight readmit that recovery already accounted for."""
        rec = self.records.get(int(blk))
        if rec is not None and rec.state in (READMIT_INFLIGHT, HOST_RESERVED):
            rec.state = LIVE
            rec.since = self._now()

    def handoff_begin(self, block_ids) -> None:
        """Destination-side KV handoff staging (serving/pools.py): the named
        freshly-allocated blocks become transfer targets — live -> handoff
        in flight. Holders (the negative-id handoff session) carry over."""
        now = self._now()
        for blk in block_ids:
            rec = self.records.get(int(blk))
            if rec is not None and rec.state == LIVE:
                rec.state = HANDOFF_INFLIGHT
                rec.since = now

    def handoff_committed(self, block_ids) -> None:
        """The handoff session finalized: the staged blocks' bytes are
        authoritative and their hashes publish to the prefix cache —
        handoff_inflight -> live (the session then releases them, parking
        the hashed blocks idle for the migrated request's prefix walk)."""
        now = self._now()
        for blk in block_ids:
            rec = self.records.get(int(blk))
            if rec is not None and rec.state == HANDOFF_INFLIGHT:
                rec.state = LIVE
                rec.since = now

    def handoff_aborted(self, block_ids) -> None:
        """The handoff died mid-transfer (source replica death, admission
        fallback): staged blocks revert to plain live so the session's
        release path can return them to the free list — nothing half-staged
        survives as a prefix-cache entry."""
        now = self._now()
        for blk in block_ids:
            rec = self.records.get(int(blk))
            if rec is not None and rec.state == HANDOFF_INFLIGHT:
                rec.state = LIVE
                rec.since = now

    def note_event(self, request_id: int, event: str, **fields) -> None:
        """Runner hand-off marker (preempt/migrate/resume) for the holdings
        timeline, with the blocks held at the hand-off point."""
        held = sum(rec.holders.get(request_id, 0)
                   for rec in self.records.values())
        self._log(request_id, event, held_blocks=held, **fields)

    # ------------------------------------------------------------------ audit
    def audit(self, expected_holders: Optional[Dict[int, Dict[int, int]]]
              = None, raise_on_violation: bool = False,
              check_inflight: bool = True) -> dict:
        """Conservation + attribution audit.

        ``expected_holders``: the owner's roster — ``{request_id: {block:
        count}}`` for every request that legitimately holds blocks (the
        runner builds it from its active slots). With it, a block held by a
        request outside the roster is a LEAK, attributed to the request and
        the seam of its last transition. ``check_inflight=False`` tolerates
        ``readmit_inflight`` blocks (mid-dispatch callers only; every
        quiescent audit point must see none).

        Returns the report dict; ``raise_on_violation=True`` raises
        :class:`MemLedgerViolation` instead of logging. In serving (the
        non-raising mode) each failed audit emits ONE structured
        ``memledger_violation {json}`` log line and bumps
        ``memledger_violations_total``."""
        alloc = self.allocator
        v: List[dict] = []
        by_state: Dict[str, set] = {s: set() for s in STATES}
        for blk, rec in self.records.items():
            by_state[rec.state].add(blk)
        by_state[FREE] = set(range(self.num_blocks)) - set(self.records)

        # conservation: the owner states partition the pool
        total = sum(len(s) for s in by_state.values())
        if total != self.num_blocks:
            v.append({"kind": "conservation", "detail":
                      f"state partition sums to {total} != "
                      f"{self.num_blocks} blocks"})

        # free list: same set, no duplicates (a duplicate is a double free)
        free_list = list(alloc.free)
        if len(set(free_list)) != len(free_list):
            v.append({"kind": "double_free", "detail":
                      "allocator free list contains duplicate block ids"})
        if set(free_list) != by_state[FREE]:
            extra = sorted(set(free_list) - by_state[FREE])[:8]
            missing = sorted(by_state[FREE] - set(free_list))[:8]
            v.append({"kind": "free_list_mismatch", "detail":
                      f"allocator free list disagrees with ledger: "
                      f"allocator-only={extra} ledger-only={missing}"})

        # idle pool (tiered only)
        idle = getattr(alloc, "idle", None)
        if idle is not None and set(idle) != by_state[IDLE]:
            v.append({"kind": "idle_mismatch", "detail":
                      f"idle pool {sorted(idle)[:8]} != ledger idle "
                      f"{sorted(by_state[IDLE])[:8]}"})

        # refcounted set == live + host_reserved + inflight; per-block holder
        # sums match the refcounts (the per-request attribution invariant)
        refcounted = (by_state[LIVE] | by_state[HOST_RESERVED]
                      | by_state[READMIT_INFLIGHT]
                      | by_state[HANDOFF_INFLIGHT])
        if set(alloc.refcount) != refcounted:
            v.append({"kind": "refcount_set_mismatch", "detail":
                      f"refcounted blocks "
                      f"{sorted(set(alloc.refcount) - refcounted)[:8]} "
                      f"missing from the ledger; ledger-only "
                      f"{sorted(refcounted - set(alloc.refcount))[:8]}"})
        for blk in sorted(refcounted & set(alloc.refcount)):
            rec = self.records[blk]
            held = sum(rec.holders.values())
            if held != alloc.refcount[blk]:
                v.append({"kind": "refcount_mismatch", "block": blk,
                          "seam": rec.seam, "detail":
                          f"block {blk}: refcount {alloc.refcount[blk]} != "
                          f"attributed holder sum {held} "
                          f"(holders {dict(rec.holders)})"})

        # hash bijection + no orphaned hashes
        h2b = getattr(alloc, "hash_to_block", {})
        b2h = getattr(alloc, "block_to_hash", {})
        for h, blk in h2b.items():
            if b2h.get(blk) != h:
                v.append({"kind": "hash_bijection", "block": blk, "detail":
                          f"hash_to_block[{h.hex()[:12]}]={blk} but "
                          f"block_to_hash disagrees"})
            if blk not in self.records:
                v.append({"kind": "orphaned_hash", "block": blk, "detail":
                          f"hash {h.hex()[:12]} registered on FREE block "
                          f"{blk}"})
        # (deliberately NO device-vs-host-store hash disjointness check: the
        # content-addressed tier may be SHARED by several replicas, so a hash
        # another replica spilled can legitimately coexist with this
        # allocator's device-resident copy)

        # pending readmits == host_reserved; quiescent audits see no inflight
        pend = getattr(alloc, "_pending_readmits", None)
        if pend is not None:
            pend_blocks = {blk for blk, _h, _hb in pend}
            if pend_blocks != by_state[HOST_RESERVED]:
                v.append({"kind": "pending_mismatch", "detail":
                          f"pending readmit queue {sorted(pend_blocks)[:8]} "
                          f"!= ledger host_reserved "
                          f"{sorted(by_state[HOST_RESERVED])[:8]}"})
        if check_inflight and by_state[READMIT_INFLIGHT]:
            v.append({"kind": "inflight_stuck", "detail":
                      f"{len(by_state[READMIT_INFLIGHT])} readmit(s) taken "
                      f"but never committed: "
                      f"{sorted(by_state[READMIT_INFLIGHT])[:8]}"})

        # cluster-store ownership (serving/cluster_kv.py): this replica's
        # refs/pins must reconcile with the store — its violations merge
        # into the same report/raise/dedup machinery
        cluster = (getattr(self.tier, "cluster", None)
                   if self.tier is not None else None)
        if cluster is not None:
            v.extend(cluster.audit(owner=getattr(self.tier, "owner", None),
                                   check_inflight=check_inflight))

        # per-request attribution vs the owner's roster
        leaked: List[int] = []
        if expected_holders is not None:
            ledger_by_rid: Dict[int, Dict[int, int]] = {}
            for blk, rec in self.records.items():
                for rid, cnt in rec.holders.items():
                    if cnt:
                        ledger_by_rid.setdefault(rid, {})[blk] = cnt
            for rid, held in sorted(
                    ledger_by_rid.items(),
                    key=lambda kv: (kv[0] is None, kv[0])):
                exp = expected_holders.get(rid)
                if exp is None:
                    blocks = sorted(held)
                    leaked.extend(blocks)
                    seams = sorted({self.records[b].seam for b in blocks})
                    age = max(self._now() - self.records[b].since
                              for b in blocks)
                    v.append({"kind": "leak", "request_id": rid,
                              "blocks": blocks[:16], "seam": ",".join(seams),
                              "detail":
                              f"{sum(held.values())} block(s) held by "
                              f"request {rid} which no longer exists "
                              f"(last seam(s): {seams}, oldest "
                              f"{age:.3f}s)"})
                elif held != exp:
                    v.append({"kind": "holder_mismatch", "request_id": rid,
                              "detail":
                              f"request {rid} ledger holdings "
                              f"{sorted(held)[:8]}... != roster "
                              f"{sorted(exp)[:8]}..."})
            for rid, exp in expected_holders.items():
                if exp and rid not in ledger_by_rid:
                    v.append({"kind": "holder_mismatch", "request_id": rid,
                              "detail": f"request {rid} holds "
                              f"{len(exp)} block(s) per the roster but none "
                              f"per the ledger"})

        fresh_leaks = [b for b in leaked if b not in self._known_leaked]
        self._known_leaked.update(leaked)
        report = {
            "ok": not v,
            "violations": v,
            "counts": {s: len(by_state[s]) for s in STATES},
            "num_blocks": self.num_blocks,
            "leaked_blocks": len(leaked),
        }
        if v:
            # count + log each DISTINCT violation once, not once per audit:
            # a single unfixed leak would otherwise inflate the counter and
            # repeat the same ERROR line at every scrape/stats/drain audit
            # (the signature uses the stable fields — ages in the detail
            # text change every audit)
            fresh = [x for x in v if self._violation_sig(x)
                     not in self._seen_violation_sigs]
            self._seen_violation_sigs.update(
                self._violation_sig(x) for x in fresh)
            if fresh and self._c_violations is not None:
                self._c_violations.inc(len(fresh))
            if fresh_leaks and self._c_leaked is not None:
                self._c_leaked.inc(len(fresh_leaks))
            if raise_on_violation:
                raise MemLedgerViolation(report)
            if fresh:
                logger.error("memledger_violation %s", json.dumps(
                    {"replica": self.replica, "violations": fresh[:8],
                     "counts": report["counts"],
                     "leaked_blocks": report["leaked_blocks"]}, default=str))
        else:
            # a clean audit re-arms the dedup: a violation that recurs
            # after being fixed logs again
            self._seen_violation_sigs.clear()
        return report

    @staticmethod
    def _violation_sig(v: dict) -> tuple:
        """Stable identity of one violation across repeated audits (the
        ``detail`` text carries ages/counts that change every audit)."""
        return (v.get("kind"), v.get("request_id"), v.get("block"),
                tuple(v.get("blocks", ())), v.get("seam"))

    # ------------------------------------------------------------------ views
    def holders_by_request(self) -> Dict[int, int]:
        """{request_id: blocks held} over every refcounted block (shared
        blocks count once per holder — attribution, not conservation)."""
        out: Dict[int, int] = {}
        for rec in self.records.values():
            for rid, cnt in rec.holders.items():
                if cnt and rid is not None:
                    out[rid] = out.get(rid, 0) + cnt
        return out

    def idle_ages(self) -> np.ndarray:
        now = self._now()
        return np.asarray(sorted(
            now - rec.since for rec in self.records.values()
            if rec.state == IDLE), dtype=np.float64)

    def snapshot(self, top: int = 8) -> dict:
        """Point-in-time forensics view: owner-state counts, the top holders
        (request id, blocks, bytes, age, class, last seam), idle-age
        quantiles, host-tier occupancy. What the OOM path and the debug
        bundles capture."""
        now = self._now()
        counts = {s: 0 for s in STATES}
        per_rid: Dict[int, dict] = {}
        for blk, rec in self.records.items():
            counts[rec.state] += 1
            for rid, cnt in rec.holders.items():
                if not cnt or rid is None:
                    continue
                e = per_rid.setdefault(rid, {"blocks": 0, "age_s": 0.0,
                                             "seam": rec.seam,
                                             "_seam_t": rec.since})
                e["blocks"] += cnt
                e["age_s"] = max(e["age_s"], now - rec.since)
                if rec.since >= e["_seam_t"]:
                    # last_seam = the holder's LATEST transition, not
                    # whichever block happens to iterate last
                    e["_seam_t"] = rec.since
                    e["seam"] = rec.seam
        counts[FREE] = self.num_blocks - len(self.records)
        holders = [
            {"request_id": rid, "blocks": e["blocks"],
             "bytes": e["blocks"] * self.bytes_per_block,
             "age_s": round(e["age_s"], 3),
             "sla_class": self.request_class.get(rid),
             "last_seam": e["seam"]}
            for rid, e in sorted(per_rid.items(),
                                 key=lambda kv: -kv[1]["blocks"])]
        ages = self.idle_ages()
        out = {
            "states": counts,
            "num_blocks": self.num_blocks,
            "bytes_per_block": self.bytes_per_block,
            "top_holders": holders[:top],
            "holder_count": len(holders),
            "idle_age_s": {
                "count": int(ages.size),
                "p50": round(float(np.percentile(ages, 50)), 3)
                if ages.size else None,
                "p90": round(float(np.percentile(ages, 90)), 3)
                if ages.size else None,
                "max": round(float(ages[-1]), 3) if ages.size else None,
            },
        }
        if self.tier is not None:
            ts = self.tier.stats()
            out["host_tier"] = ts
        by_class: Dict[str, int] = {}
        for rid, e in per_rid.items():
            cls = self.request_class.get(rid)
            if cls:
                by_class[cls] = by_class.get(cls, 0) + e["blocks"]
        if by_class:
            out["by_class"] = {
                cls: {"blocks": n, "bytes": n * self.bytes_per_block}
                for cls, n in sorted(by_class.items())}
        return out

    def timeline(self, request_id: int) -> List[dict]:
        """The request's bounded holdings timeline (allocate / grow /
        release / preempt / resume hand-off events)."""
        return list(self.request_log.get(request_id, ()))

    # ------------------------------------------------------------------ OOM
    def note_exhaustion(self, seam: str, exc=None) -> None:
        """Capture the forensics snapshot at a ``KVBlocksExhausted`` raise:
        stashed as ``last_oom`` (stats / debug bundles read it), attached to
        the exception (``exc.ledger_snapshot``), counted, and emitted as one
        structured ``memledger_oom {json}`` line naming the top holders.

        Rate-limited: a storm of exhaustion raises (sustained pressure)
        counts every event but rebuilds the O(num_blocks) snapshot — and
        logs — at most once per second; in-between raises reuse the last
        snapshot (its holders are still what the pool looked like when the
        storm began)."""
        if self._c_oom is not None:
            self._c_oom.inc()
        now = self._now()
        if self.last_oom is not None and now - self._last_oom_t < 1.0:
            if exc is not None:
                exc.ledger_snapshot = self.last_oom
            return
        self._last_oom_t = now
        snap = self.snapshot()
        snap["seam"] = seam
        snap["ts_unix"] = time.time()
        self.last_oom = snap
        if exc is not None:
            exc.ledger_snapshot = snap
        logger.warning("memledger_oom %s", json.dumps(
            {"replica": self.replica, "seam": seam,
             "states": snap["states"],
             "top_holders": snap["top_holders"][:4]}, default=str))

    # ------------------------------------------------------------------ export
    def export_gauges(self, fragmentation: Optional[float] = None) -> None:
        """Refresh the ledger's gauges on the owning registry:
        ``serving_kv_blocks{state=}``, idle-age quantiles, host-tier
        occupancy/watermark, per-class byte attribution."""
        reg = self._registry
        if reg is None:
            return
        snap_states = {s: 0 for s in STATES}
        for rec in self.records.values():
            snap_states[rec.state] += 1
        snap_states[FREE] = self.num_blocks - len(self.records)
        for state, n in snap_states.items():
            reg.gauge("serving_kv_blocks",
                      "physical KV blocks by ledger owner state",
                      labels={"state": state}).set(n)
        ages = self.idle_ages()
        for q, label in ((50, "0.5"), (90, "0.9"), (100, "1.0")):
            val = float(np.percentile(ages, q)) if ages.size else 0.0
            reg.gauge("serving_kv_idle_age_seconds",
                      "age distribution of idle-pool blocks "
                      "(summary quantiles)",
                      labels={"quantile": label}).set(val)
        if fragmentation is not None:
            reg.gauge("serving_kv_fragmentation_ratio",
                      "allocated-but-unwritten slot fraction over live "
                      "blocks (internal fragmentation)").set(fragmentation)
        if self.tier is not None:
            ts = self.tier.stats()
            reg.gauge("serving_kv_host_tier_blocks",
                      "host-RAM KV tier occupancy").set(ts["host_blocks"])
            reg.gauge("serving_kv_host_tier_capacity",
                      "host-RAM KV tier capacity").set(ts["capacity_blocks"])
            reg.gauge("serving_kv_host_tier_watermark",
                      "peak host-RAM KV tier occupancy"
                      ).set(ts.get("watermark", 0))
        by_class: Dict[str, int] = {}
        for rec in self.records.values():
            for rid, cnt in rec.holders.items():
                cls = self.request_class.get(rid)
                if cls and cnt:
                    by_class[cls] = by_class.get(cls, 0) + cnt
        for cls, n in by_class.items():
            reg.gauge("serving_kv_bytes",
                      "KV bytes attributed to live requests by SLA class",
                      labels={"sla_class": cls}
                      ).set(n * self.bytes_per_block)


# ---------------------------------------------------------------------------
# runner registry (the autouse conservation fixture walks this) + guarded
# embed helpers (a ledger failure must never mask the fault being dumped)
# ---------------------------------------------------------------------------

_LIVE_RUNNERS: "weakref.WeakSet" = weakref.WeakSet()


def note_runner(runner) -> None:
    """Register a ledgered runner for the test suite's autouse conservation
    fixture (weak — the registry never extends a runner's lifetime)."""
    _LIVE_RUNNERS.add(runner)


def live_runners() -> list:
    return [r for r in _LIVE_RUNNERS if getattr(r, "ledger", None) is not None]


def snapshot_safe(runner) -> Optional[dict]:
    """Guarded ledger snapshot for bundle enrichment: None when the runner
    has no ledger; an ``{"error": ...}`` entry — never a raise — when the
    snapshot itself fails (the fault being dumped stays the headline)."""
    try:
        led = getattr(runner, "ledger", None)
        if led is None:
            return None
        snap = led.snapshot()
        if led.last_oom is not None:
            snap["last_oom"] = led.last_oom
        # the top holders' bounded holdings timelines (allocate / grow /
        # preempt / resume hand-offs) — the per-request forensics view
        snap["timelines"] = {
            h["request_id"]: led.timeline(h["request_id"])
            for h in snap.get("top_holders", ())}
        return snap
    # lint: ok(silent-except): guarded bundle enrichment — the error STRING is the visible degradation; a raise here would mask the fault being dumped
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


def timeline_safe(runner, request_id: int) -> Optional[list]:
    """Guarded per-request holdings timeline (same contract as
    :func:`snapshot_safe`)."""
    try:
        led = getattr(runner, "ledger", None)
        if led is None:
            return None
        return led.timeline(request_id)
    # lint: ok(silent-except): guarded bundle enrichment — the error record is the visible degradation; a raise here would mask the fault being dumped
    except Exception as e:
        return [{"error": f"{type(e).__name__}: {e}"}]
