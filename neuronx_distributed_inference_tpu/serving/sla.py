"""SLA classes for multi-tenant serving (the overload control plane's type).

An :class:`SLAClass` names one tenant tier and carries everything the
control plane needs to treat its traffic differently:

- ``priority`` — placement/preemption order (0 = most important). The router
  places high-priority arrivals first, and a high-priority request that
  cannot place may preempt the NEWEST request of a strictly lower class
  (serving/router.py).
- ``weight`` — the class's share of the mixed-step prefill token budget
  (runtime/continuous_batching._step_mixed splits ``prefill_token_budget``
  across the classes present by weight, work-conserving), so one tenant's
  100k-token prompts can never starve interactive prefill.
- ``ttft_target_ms`` / ``tpot_target_ms`` — optional per-class latency
  targets; :meth:`SLAClassSet.slo_class_targets` exports them in the shape
  ``utils/slo.SLOConfig.class_targets`` consumes.
- ``sheddable`` — may the brown-out ladder shed this class's ARRIVALS under
  sustained SLO degradation? The most-important class is never shed by the
  ladder regardless of the flag (only the global queue bound touches it).

An :class:`SLAClassSet` is the ordered registry one router + its replicas
share. Config strings (CLI ``--sla-classes``, bench) use the grammar::

    spec  := class (";" class)*
    class := name ":" key "=" value ("," key "=" value)*
    keys  := priority | weight | ttft_target_ms | tpot_target_ms
             | sheddable | default

    --sla-classes "interactive:priority=0,weight=4,ttft_target_ms=250;\
standard:priority=1,weight=2,default=1;batch:priority=2,weight=1"

Unlabelled submits map to the ``default`` class (exactly one per set;
defaults to the LOWEST-priority-number class when none is flagged).
``DEFAULT_CLASSES`` is the stock interactive/standard/batch three-tier set.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

__all__ = ["SLAClass", "SLAClassSet", "DEFAULT_CLASSES", "default_class_set"]


@dataclasses.dataclass(frozen=True)
class SLAClass:
    """One tenant tier. ``priority``: 0 = most important (placement and
    preemption order); ``weight``: weighted-fair share of the mixed-step
    prefill budget; ``sheddable``: brown-out may shed this class's arrivals
    (the top class is protected regardless)."""

    name: str
    priority: int
    weight: float = 1.0
    ttft_target_ms: Optional[float] = None
    tpot_target_ms: Optional[float] = None
    sheddable: bool = True

    def __post_init__(self):
        if not self.name or any(c in self.name for c in ";:,= \t\n{}\""):
            raise ValueError(f"invalid SLA class name {self.name!r}")
        if self.priority < 0:
            raise ValueError("priority must be >= 0 (0 = most important)")
        if not self.weight > 0:
            raise ValueError("weight must be > 0")


class SLAClassSet:
    """Ordered, validated registry of SLA classes.

    ``default``: the class unlabelled submits map to (name); when omitted,
    the most-important (lowest priority number) class.
    """

    def __init__(self, classes: Sequence[SLAClass],
                 default: Optional[str] = None):
        classes = list(classes)
        if not classes:
            raise ValueError("need at least one SLA class")
        names = [c.name for c in classes]
        if len(set(names)) != len(names):
            raise ValueError(f"SLA class names must be unique, got {names}")
        prios = [c.priority for c in classes]
        if len(set(prios)) != len(prios):
            # strict order keeps victim selection / shed order deterministic
            raise ValueError(
                f"SLA class priorities must be unique, got {prios}")
        self._by_name: Dict[str, SLAClass] = {c.name: c for c in classes}
        # most-important first, everywhere
        self._ordered = sorted(classes, key=lambda c: c.priority)
        if default is None:
            default = self._ordered[0].name
        if default not in self._by_name:
            raise ValueError(f"default class {default!r} not in {names}")
        self.default = default

    # ------------------------------------------------------------- lookups
    def __contains__(self, name: str) -> bool:
        return name in self._by_name

    def __len__(self) -> int:
        return len(self._by_name)

    def __iter__(self):
        return iter(self._ordered)

    def names(self) -> List[str]:
        """Class names, most-important first."""
        return [c.name for c in self._ordered]

    def get(self, name: str) -> SLAClass:
        try:
            return self._by_name[name]
        except KeyError:
            raise ValueError(f"unknown SLA class {name!r} "
                             f"(known: {self.names()})") from None

    def resolve(self, name: Optional[str]) -> str:
        """The class an (optionally unlabelled) submit lands in."""
        if name is None:
            return self.default
        return self.get(name).name

    def priority(self, name: str) -> int:
        return self.get(name).priority

    def weight(self, name: str) -> float:
        return self.get(name).weight

    def top(self) -> SLAClass:
        """The most-important class (never shed/capped by brown-out)."""
        return self._ordered[0]

    def shed_order(self) -> List[str]:
        """Brown-out shed order: LEAST-important sheddable classes first;
        the top class is excluded regardless of its flag."""
        return [c.name for c in reversed(self._ordered[1:]) if c.sheddable]

    def slo_class_targets(self) -> Dict[str, Dict[str, float]]:
        """Per-class latency targets in the ``SLOConfig.class_targets``
        shape (classes without targets are absent)."""
        out: Dict[str, Dict[str, float]] = {}
        for c in self._ordered:
            t: Dict[str, float] = {}
            if c.ttft_target_ms is not None:
                t["ttft_p99_ms"] = c.ttft_target_ms
            if c.tpot_target_ms is not None:
                t["tpot_p99_ms"] = c.tpot_target_ms
            if t:
                out[c.name] = t
        return out

    # ------------------------------------------------------------- parsing
    @classmethod
    def parse(cls, spec: str) -> "SLAClassSet":
        """Parse the CLI grammar (module docstring); unknown keys raise —
        a typo'd class config must not silently serve everyone equal."""
        classes: List[SLAClass] = []
        default = None
        for entry in spec.split(";"):
            entry = entry.strip()
            if not entry:
                continue
            name, _, args = entry.partition(":")
            name = name.strip()
            kw: Dict[str, object] = {"name": name}
            is_default = False
            for part in args.split(","):
                part = part.strip()
                if not part:
                    continue
                if "=" not in part:
                    raise ValueError(f"SLA class entry {part!r} is not "
                                     f"key=value (in {entry!r})")
                k, v = (s.strip() for s in part.split("=", 1))
                if k == "priority":
                    kw[k] = int(v)
                elif k in ("weight", "ttft_target_ms", "tpot_target_ms"):
                    kw[k] = float(v)
                elif k == "sheddable":
                    kw[k] = v.lower() in ("1", "true", "yes")
                elif k == "default":
                    is_default = v.lower() in ("1", "true", "yes")
                else:
                    raise ValueError(
                        f"unknown SLA class key {k!r} (known: priority, "
                        f"weight, ttft_target_ms, tpot_target_ms, "
                        f"sheddable, default)")
            if "priority" not in kw:
                # declaration order is the priority when unstated
                kw["priority"] = len(classes)
            classes.append(SLAClass(**kw))
            if is_default:
                if default is not None:
                    raise ValueError("more than one SLA class flagged "
                                     "default=1")
                default = name
        return cls(classes, default=default)

    def __repr__(self) -> str:
        inner = "; ".join(
            f"{c.name}(p{c.priority}, w{c.weight:g}"
            + ("" if c.sheddable else ", unsheddable") + ")"
            for c in self._ordered)
        return f"SLAClassSet[{inner}; default={self.default}]"


# the stock three-tier set: latency-sensitive interactive traffic, the
# default standard tier, and sheddable bulk/batch work
DEFAULT_CLASSES = (
    SLAClass("interactive", priority=0, weight=4.0, sheddable=False),
    SLAClass("standard", priority=1, weight=2.0),
    SLAClass("batch", priority=2, weight=1.0),
)


def default_class_set() -> SLAClassSet:
    """The stock interactive/standard/batch set with ``standard`` default."""
    return SLAClassSet(DEFAULT_CLASSES, default="standard")
