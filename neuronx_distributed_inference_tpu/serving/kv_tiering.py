"""Host-RAM KV tier for cold paged blocks: spill on reclaim, re-admit on hit.

The paged block layout is already transfer-friendly (each block is a
contiguous ``(L, H, BS, D)`` tile run — the property "Ragged Paged Attention"
builds its streaming on), so KV capacity does not have to end at HBM:

- **Idle pool** (:class:`TieredBlockAllocator`): a committed full block whose
  refcount drops to zero keeps its device residency AND its prefix-cache hash
  instead of returning to the free list — re-referencing it is free. Idle
  blocks still count as allocatable headroom (``num_free``), which is exactly
  the admission signal the router reads: headroom pressure is what drives
  eviction.
- **Spill** (headroom-driven eviction): when an allocation finds the free
  list empty, the least-recently-attended idle block is reclaimed — its
  content is first copied device→host (one batched gather +
  ``copy_to_host_async``, the host-side analog of the PR 4 prefetch-pipeline
  transfer shape: start the copy early, materialize at the last moment) and
  parked in the host store keyed by the same chained content hash.
- **Re-admit**: ``BlockAllocator.allocate_for_prompt``'s prefix walk consults
  the host store after a device miss; a hit allocates a fresh device block,
  counts the tokens cached, and queues the block for re-admission. The runner
  dispatches ONE ``cb.paged.tier_readmit`` scatter (an ``audited_jit`` site —
  cache donated/aliased, telemetry carry threaded) BEFORE the request's first
  insert window, so the windows' queries see the restored KV through the
  block table exactly as if it had never left the device.
- **Cluster rung** (serving/cluster_kv.py): with a
  :class:`~.cluster_kv.ClusterKVStore` attached (``HostKVTier(cluster=)``)
  the tier PUBLISHES every spilled block fleet-wide (dedup by content hash)
  and the prefix walk gains a third rung after the local-store miss —
  device → host tier → cluster. A cluster hit reserves a checksum-verified
  PULL that rides the very same pending-readmit queue and
  ``cb.paged.tier_readmit`` scatter, so a cold replica restores a
  fleet-warm prefix without re-prefill and without any new graph kind.

Exactness guarantee: spill reads the committed bytes and re-admit writes them
back verbatim — the round trip is BIT-identical in the cache dtype (int8/fp8
KV included; pinned by tests/test_kv_tiering.py), so a re-admitted prefix can
never perturb a token stream.
"""

from __future__ import annotations

import logging
import zlib
from typing import Callable, Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..analysis.registry import audited_jit
from ..modules.block_kvcache import BlockAllocator, KVBlocksExhausted
from ..utils import device_telemetry as dtel

logger = logging.getLogger("tpu-inference")

__all__ = ["HostKVTier", "TieredBlockAllocator", "KVBlocksExhausted",
           "READMIT_BUCKET_CAP", "build_handoff_step", "build_readmit_step",
           "readmit_bucket"]

# largest blocks-per-readmit-dispatch bucket; bigger batches dispatch in
# cap-sized chunks (ContinuousBatchingRunner._dispatch_readmits)
READMIT_BUCKET_CAP = 64

# process-unique default owner names for tiers attached to a cluster store
_TIER_SEQ = 0


def readmit_bucket(n: int, cap: int = READMIT_BUCKET_CAP) -> int:
    """Blocks-per-readmit-dispatch bucket: next power of two (capped) so the
    scatter executable count stays logarithmic in batch size."""
    b = 1
    while b < n and b < cap:
        b *= 2
    return b


def build_readmit_step(kind: str = "cb.paged.tier_readmit"):
    """The tier's ONE device dispatch: scatter N host-restored blocks back
    into the paged pool. ``block_ids`` rows of -1 are padding (remapped past
    the block axis and dropped), so a handful of power-of-two bucket shapes
    cover every re-admission batch."""

    def _tier_readmit(cache, telem, k_new, v_new, block_ids, block_size):
        nb = cache["k"].shape[1]
        blk = jnp.where(block_ids < 0, nb, block_ids)       # OOB -> dropped
        cache = dict(cache)
        cache["k"] = cache["k"].at[:, blk].set(
            k_new.astype(cache["k"].dtype), mode="drop")
        cache["v"] = cache["v"].at[:, blk].set(
            v_new.astype(cache["v"].dtype), mode="drop")
        n_live = jnp.sum(block_ids >= 0)
        telem = telem.at[dtel.IDX_KV_WRITES].add(n_live * block_size)
        telem = telem.at[dtel.IDX_KV_BLOCKS].add(n_live)
        telem = dtel.bump_kind(telem, dtel.KIND_TIER_READMIT)
        return cache, telem

    return audited_jit(_tier_readmit, kind=kind, cache_args=("cache",),
                       carry_args=("telem",),
                       static_argnames=("block_size",))


def build_handoff_step(kind: str = "cb.paged.kv_handoff"):
    """The pool-to-pool KV handoff's device dispatch (serving/pools.py):
    scatter N blocks gathered from a PREFILL-pool replica's cache into a
    DECODE-pool replica's paged pool. Same bucketed shape discipline as the
    readmit scatter (``block_ids`` rows of -1 are padding, remapped past the
    block axis and dropped), but counted under its own step kind so the
    telemetry carry and roofline attribute handoff traffic separately from
    host-tier re-admission."""

    def _kv_handoff(cache, telem, k_new, v_new, block_ids, block_size):
        nb = cache["k"].shape[1]
        blk = jnp.where(block_ids < 0, nb, block_ids)       # OOB -> dropped
        cache = dict(cache)
        cache["k"] = cache["k"].at[:, blk].set(
            k_new.astype(cache["k"].dtype), mode="drop")
        cache["v"] = cache["v"].at[:, blk].set(
            v_new.astype(cache["v"].dtype), mode="drop")
        n_live = jnp.sum(block_ids >= 0)
        telem = telem.at[dtel.IDX_KV_WRITES].add(n_live * block_size)
        telem = telem.at[dtel.IDX_KV_BLOCKS].add(n_live)
        telem = dtel.bump_kind(telem, dtel.KIND_KV_HANDOFF)
        return cache, telem

    return audited_jit(_kv_handoff, kind=kind, cache_args=("cache",),
                       carry_args=("telem",),
                       static_argnames=("block_size",))


class _HostBlock:
    """One spilled block: the device gather result until materialized, then
    plain numpy bytes. ``copy_to_host_async`` is scheduled at spill time so
    the D2H transfer overlaps whatever the serving loop dispatches next.

    Materialization also stamps a CONTENT CHECKSUM over the host bytes (shape
    descriptor + crc32): a host-RAM entry can rot between spill and re-admit
    (bit flips, a truncating copy, a fault injector), and a re-admitted
    garbage block would silently perturb every later token of any stream
    sharing the prefix. ``verify()`` recomputes the checksum — the readmit
    path refuses (drops the entry, falls back to re-prefill) on mismatch."""

    __slots__ = ("k", "v", "stamp", "_np", "checksum")

    def __init__(self, k, v, stamp: int):
        self.k, self.v, self.stamp = k, v, stamp
        self._np: Optional[Tuple[np.ndarray, np.ndarray]] = None
        self.checksum: Optional[int] = None

    @staticmethod
    def _digest(k: np.ndarray, v: np.ndarray) -> int:
        crc = zlib.crc32(repr((k.shape, str(k.dtype),
                               v.shape, str(v.dtype))).encode())
        crc = zlib.crc32(np.ascontiguousarray(k).tobytes(), crc)
        return zlib.crc32(np.ascontiguousarray(v).tobytes(), crc)

    def materialize(self) -> Tuple[np.ndarray, np.ndarray]:
        if self._np is None:
            self._np = (np.asarray(self.k), np.asarray(self.v))
            self.k = self.v = None          # drop the device handles
            self.checksum = self._digest(*self._np)
        return self._np

    def verify(self) -> bool:
        """True iff the host bytes still match the spill-time checksum."""
        k, v = self.materialize()
        return self.checksum == self._digest(k, v)

    def nbytes(self) -> int:
        if self._np is not None:
            return self._np[0].nbytes + self._np[1].nbytes
        return int(np.prod(self.k.shape) * self.k.dtype.itemsize * 2)


class HostKVTier:
    """Host-RAM store of spilled paged KV blocks, keyed by the allocator's
    chained content hash; LRU-by-last-attended eviction past
    ``capacity_blocks``.

    The tier is wired to a runner by ``ContinuousBatchingRunner(kv_tier=)``:
    the runner installs ``read_blocks`` (a batched gather over its live cache)
    and drives spills/readmits; the router reads ``stats()`` alongside the
    replica's admission signals.

    ``cluster``: an optional :class:`~.cluster_kv.ClusterKVStore` this tier
    publishes spills into and consults after a local miss (the fleet rung of
    the lookup ladder). ``owner`` names this tier in the store's ownership
    roster (publish refs, in-flight pull pins, leak attribution); it
    defaults to a process-unique ``tier<N>``.
    """

    def __init__(self, capacity_blocks: int = 1024, cluster=None,
                 owner: Optional[str] = None):
        if capacity_blocks < 0:
            raise ValueError("capacity_blocks must be >= 0")
        self.capacity_blocks = capacity_blocks
        self.cluster = cluster
        if owner is None:
            global _TIER_SEQ
            owner = f"tier{_TIER_SEQ}"
            _TIER_SEQ += 1
        self.owner = owner
        self.store: Dict[bytes, _HostBlock] = {}
        self._clock = 0
        # counters (always-on ints; the owning replica's registry exports
        # them with the replica label via EngineReplica)
        self.evictions = 0           # device blocks spilled to host
        self.host_evictions = 0      # host entries dropped past capacity
        self.discards = 0            # spill candidates dropped (capacity 0)
        self.readmit_blocks = 0      # host blocks restored to device
        self.readmit_requests = 0    # requests that hit the host tier
        self.cluster_hits = 0        # requests that pulled >=1 cluster block
        self.integrity_failures = 0  # entries dropped on checksum mismatch
        self.watermark = 0           # peak store occupancy (blocks) ever seen

    # ------------------------------------------------------------ bookkeeping
    def tick(self) -> int:
        self._clock += 1
        return self._clock

    def __contains__(self, h: bytes) -> bool:
        return h in self.store

    def host_blocks(self) -> int:
        return len(self.store)

    def host_bytes(self) -> int:
        return sum(b.nbytes() for b in self.store.values())

    def stats(self) -> Dict[str, int]:
        out = {
            "capacity_blocks": self.capacity_blocks,
            "host_blocks": self.host_blocks(),
            "watermark": self.watermark,
            "evictions": self.evictions,
            "host_evictions": self.host_evictions,
            "discards": self.discards,
            "readmit_blocks": self.readmit_blocks,
            "readmit_requests": self.readmit_requests,
            "integrity_failures": self.integrity_failures,
        }
        if self.cluster is not None:
            out["cluster_hits"] = self.cluster_hits
            out["cluster"] = self.cluster.stats()
        return out

    # ------------------------------------------------------------ spill side
    def spill(self, block_ids: List[int], hashes: List[bytes],
              read_blocks: Callable) -> None:
        """Copy the named device blocks into the host store (one batched
        gather, async D2H). Called by the allocator's reclaim path just
        before the blocks are handed out for reuse — the gather is enqueued
        ahead of any overwrite, so it reads the committed bytes.
        ``read_blocks`` is the OWNING replica's cache gather: a tier may be
        shared by several replicas (the store is content-addressed, and KV
        bytes for the same prefix are replica-invariant under shared weights
        and config), so each spill names its source."""
        todo = [(b, h) for b, h in zip(block_ids, hashes)
                if h not in self.store]
        if not todo:
            return
        if self.capacity_blocks == 0:
            self.discards += len(todo)
            return
        ids = np.asarray([b for b, _ in todo], dtype=np.int32)
        k, v = read_blocks(ids)                 # (L, N, H, BS, D) device
        try:
            k.copy_to_host_async()
            v.copy_to_host_async()
        # lint: ok(silent-except): non-array backends have no async D2H; materialize() below copies synchronously either way
        except AttributeError:
            pass
        stamp = self.tick()
        fresh = []
        for i, (_, h) in enumerate(todo):
            hb = _HostBlock(k[:, i], v[:, i], stamp)
            self.store[h] = hb
            fresh.append(hb)
            self.evictions += 1
        self.watermark = max(self.watermark, len(self.store))
        # materialize NOW (both D2H copies are already in flight, so the
        # waits overlap): a lazily-held device slice would pin the gather's
        # HBM buffer for the store entry's whole lifetime — the tier would
        # quietly be device-resident, growing HBM instead of relieving it
        for hb in fresh:
            hb.materialize()
        if self.cluster is not None:
            # fleet publication: dedup by content hash at the store (same
            # hash from N replicas stores once, refcounted per owner), so
            # cluster bytes scale with unique content, not with traffic
            for (_, h), hb in zip(todo, fresh):
                self.cluster.publish(h, hb, owner=self.owner)
        self._enforce_capacity()

    def _enforce_capacity(self) -> None:
        while len(self.store) > self.capacity_blocks:
            h = min(self.store, key=lambda x: self.store[x].stamp)
            del self.store[h]
            self.host_evictions += 1

    # ------------------------------------------------------------ readmit side
    def reserve(self, h: bytes) -> Optional[_HostBlock]:
        """REMOVE one host block for a queued re-admission, verifying its
        content checksum first. Removal at reservation time (not at dispatch)
        matters: a reclaim later in the same allocation could otherwise
        LRU-evict the entry between the prefix walk and the readmit dispatch,
        and the prompt would skip prefill over a block that never got its
        bytes back.

        Returns ``None`` when the entry failed verification — it is DROPPED
        (never restored, never dispatched) and counted in
        ``integrity_failures``; the caller treats the hash as a miss and the
        tokens re-prefill instead of reading garbage KV."""
        blk = self.store.pop(h)
        if not blk.verify():
            self.integrity_failures += 1
            logger.warning(
                "host KV tier entry %s failed its content checksum — "
                "dropped; the prefix re-prefills instead of re-admitting "
                "corrupt bytes", h.hex()[:16])
            return None
        return blk

    def restore(self, h: bytes, blk) -> None:
        """Put a reserved block back (allocation rollback / dead-replica
        reconciliation). Polymorphic over the reservation's source: a host
        reservation re-enters the local store; a CLUSTER pull (it carries
        ``abort``) has nothing host-local to put back — the abort releases
        its pin at the shared store instead."""
        if hasattr(blk, "abort"):
            blk.abort()
            return
        self.store[h] = blk
        self.watermark = max(self.watermark, len(self.store))
        self._enforce_capacity()

    def note_readmitted(self, n_blocks: int) -> None:
        self.readmit_blocks += n_blocks

    # ------------------------------------------------------------ cluster rung
    def cluster_has(self, h: bytes) -> bool:
        """Fleet-rung membership probe (False with no cluster attached)."""
        return self.cluster is not None and h in self.cluster

    def cluster_reserve(self, h: bytes):
        """Reserve a cluster pull under this tier's owner id: checksum
        verified at reservation, entry pinned until commit/abort. ``None``
        on miss or integrity failure (the store dropped + counted the entry;
        the caller re-prefills)."""
        if self.cluster is None:
            return None
        return self.cluster.reserve(h, owner=self.owner)


class TieredBlockAllocator(BlockAllocator):
    """BlockAllocator + an idle pool and a host tier behind the free list.

    Invariants on top of the base allocator:
    - a hashed block at refcount 0 parks in ``idle`` (device-resident,
      hash registered, reusable for free) instead of the free list;
    - ``_alloc_one`` prefers the free list, then reclaims the
      least-recently-attended idle block — spilling its bytes to the host
      tier first — and only then raises;
    - ``allocate_for_prompt``'s prefix walk sees the full ladder: live
      blocks (refcounted share), idle blocks (reactivate), host store
      (allocate + queue a re-admission), and — when the tier carries a
      cluster store — the fleet rung (allocate + queue a checksum-verified
      cluster pull on the same queue; ``take_pending_readmits`` hands both
      to the runner's readmit dispatch).
    ``num_free`` counts free + idle: idle blocks ARE allocatable headroom,
    and the admission signals the router reads must say so.
    """

    def __init__(self, num_blocks: int, block_size: int, tier: HostKVTier):
        super().__init__(num_blocks, block_size, enable_prefix_caching=True)
        self.tier = tier
        self.idle: Dict[int, int] = {}           # block -> last-attended stamp
        self._pending_readmits: List[Tuple[int, bytes]] = []
        # installed by the owning runner: block_ids -> (k, v) device gathers
        # of ITS cache (a shared tier needs to know which replica is spilling)
        self.read_blocks: Optional[Callable] = None

    @property
    def num_free(self) -> int:
        return len(self.free) + len(self.idle)

    @property
    def num_free_device(self) -> int:
        """Free-list-only headroom (no reclaim needed to use it)."""
        return len(self.free)

    # ---------------------------------------------------------------- internals
    def _release_one(self, blk: int) -> None:
        self.refcount[blk] -= 1
        if self.refcount[blk] > 0:
            return
        del self.refcount[blk]
        if blk in self.block_to_hash:
            # committed (hashed) block: park idle, keep the hash registered
            self.idle[blk] = self.tier.tick()
            return
        self.free.append(blk)

    def _alloc_one(self) -> int:
        if self.free:
            blk = self.free.pop()
            self.refcount[blk] = 1
            return blk
        if self.idle:
            blk = min(self.idle, key=self.idle.get)   # least recently attended
            self._reclaim(blk)
            self.refcount[blk] = 1
            return blk
        # typed exhaustion, NOT a hard crash: placement catches this and
        # preempts-or-sheds (runtime/continuous_batching._place_queued), the
        # growth/reservation paths already preempt or take partial coverage,
        # and the router sheds by SLO signal — serving degrades, never dies
        raise KVBlocksExhausted("out of KV blocks")

    def _reclaim(self, blk: int) -> None:
        """Spill one idle block to the host tier and unregister its hash."""
        del self.idle[blk]
        h = self.block_to_hash.pop(blk)
        self.hash_to_block.pop(h, None)
        if self.read_blocks is None:
            raise RuntimeError("TieredBlockAllocator.read_blocks is not "
                               "installed — attach the tier via "
                               "ContinuousBatchingRunner(kv_tier=...)")
        self.tier.spill([blk], [h], self.read_blocks)

    def _reactivate(self, blk: int) -> None:
        del self.idle[blk]
        self.refcount[blk] = 1

    def spill_idle(self, keep: int = 0) -> int:
        """Maintenance/drain hook: spill all but ``keep`` newest idle blocks
        to the host tier (ONE batched gather, not per-block dispatches) and
        return them to the free list. Used by replica drain (a removed
        replica's committed prefixes survive as host bytes) and by
        tests/harness to force the evict→readmit path."""
        pairs: List[Tuple[int, bytes]] = []
        while len(self.idle) > keep:
            blk = min(self.idle, key=self.idle.get)
            del self.idle[blk]
            h = self.block_to_hash.pop(blk)
            self.hash_to_block.pop(h, None)
            pairs.append((blk, h))
            self.free.append(blk)
        if pairs:
            if self.read_blocks is None:
                raise RuntimeError("TieredBlockAllocator.read_blocks is not "
                                   "installed — attach the tier via "
                                   "ContinuousBatchingRunner(kv_tier=...)")
            self.tier.spill([b for b, _ in pairs], [h for _, h in pairs],
                            self.read_blocks)
        return len(pairs)

    def free_sequence(self, blocks, no_park=()) -> None:
        """Release a sequence's blocks. ``no_park``: block ids that must NOT
        survive as idle prefix-cache entries — a mid-prompt preemption leaves
        the tail blocks registered but (possibly) unwritten, and an idle pool
        would otherwise serve their garbage to the next same-prefix request
        (the base allocator is immune: it drops hashes at release)."""
        for blk in blocks:
            if blk in no_park:
                h = self.block_to_hash.pop(blk, None)
                if h is not None:
                    self.hash_to_block.pop(h, None)
            self._release_one(blk)

    # ---------------------------------------------------------------- prompts
    def allocate_for_prompt(self, tokens) -> Tuple[List[int], int]:
        tokens = np.asarray(tokens, dtype=np.int32)
        n = len(tokens)
        bs = self.block_size
        n_full = n // bs
        blocks: List[int] = []
        registered: List[int] = []      # blocks whose hash THIS call created
        pending: List[Tuple[int, bytes]] = []
        num_cached = 0
        prev = b""
        reusing = True
        hit_tier = False
        hit_cluster = False
        try:
            for i in range(n_full):
                chunk = tokens[i * bs : (i + 1) * bs]
                h = self._chain_hash(prev, chunk)
                prev = h
                if reusing and h in self.hash_to_block:
                    blk = self.hash_to_block[h]
                    if blk in self.idle:
                        self._reactivate(blk)
                    else:
                        self.refcount[blk] += 1
                    blocks.append(blk)
                    num_cached += bs
                    continue
                if reusing and h in self.tier:
                    # allocate + register FIRST (exactly what the fresh-miss
                    # path below would do), so an exhaustion raise here rolls
                    # back cleanly with the tier entry untouched
                    blk = self._alloc_one()
                    self.hash_to_block[h] = blk
                    self.block_to_hash[blk] = h
                    registered.append(blk)
                    blocks.append(blk)
                    # reserve the host bytes NOW: a reclaim later in this
                    # very walk must not LRU-evict them before the dispatch.
                    # reserve() verifies the content checksum — a corrupt/
                    # truncated entry comes back None (dropped + counted), the
                    # block stays allocated as a plain miss, and the tokens
                    # RE-PREFILL instead of reading garbage KV.
                    host_blk = self.tier.reserve(h)
                    if host_blk is None:
                        reusing = False
                        continue
                    pending.append((blk, h, host_blk))
                    num_cached += bs
                    hit_tier = True
                    continue
                if reusing and self.tier.cluster_has(h):
                    # third rung: the fleet store. Same allocate+register-
                    # first discipline; the reservation verifies the content
                    # checksum and PINS the entry at the store, and the pull
                    # rides the same pending-readmit queue (and the same
                    # cb.paged.tier_readmit scatter) as a host-tier hit —
                    # rollback aborts it through tier.restore()
                    blk = self._alloc_one()
                    self.hash_to_block[h] = blk
                    self.block_to_hash[blk] = h
                    registered.append(blk)
                    blocks.append(blk)
                    pull = self.tier.cluster_reserve(h)
                    if pull is None:
                        reusing = False
                        continue
                    pending.append((blk, h, pull))
                    num_cached += bs
                    hit_cluster = True
                    continue
                reusing = False
                blk = self._alloc_one()
                self.hash_to_block[h] = blk
                self.block_to_hash[blk] = h
                registered.append(blk)
                blocks.append(blk)
            remaining = n - n_full * bs
            if remaining > 0 or n_full == len(blocks):
                blocks.append(self._alloc_one())
        except RuntimeError:
            # clean rollback: hashes registered here must not survive (an
            # idle-parked never-written block would serve garbage later),
            # reserved host bytes go back to the store, and queued
            # re-admissions never reach the runner
            for _, h, hb in pending:
                self.tier.restore(h, hb)
            for blk in registered:
                h = self.block_to_hash.pop(blk, None)
                if h is not None:
                    self.hash_to_block.pop(h, None)
            for blk in blocks:
                self._release_one(blk)
            raise
        if pending:
            self._pending_readmits.extend(pending)
        if hit_tier:
            self.tier.readmit_requests += 1
        if hit_cluster:
            self.tier.cluster_hits += 1
        return blocks, num_cached

    def take_pending_readmits(self) -> List[Tuple[int, bytes]]:
        out, self._pending_readmits = self._pending_readmits, []
        return out
