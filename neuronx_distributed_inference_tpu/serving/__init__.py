"""Scale-out serving: engine/frontend split over the continuous-batching core.

The stack factors into three layers (ROADMAP open item 4 — "one runner on one
mesh cannot be millions of users"):

- ``engine``: :class:`EngineReplica` — a ContinuousBatchingRunner plus its
  telemetry/SLO state as a self-contained replica with a stable id, an
  admission interface (KV-block headroom, queue depth, in-flight chunks), and
  per-replica labelled metric export.
- ``router``: :class:`PrefixAffinityRouter` — the frontend. Owns the arrival
  queue and places each request on a replica by prefix-cache affinity (the
  same chained block-content hashes the BlockAllocator keys its prefix cache
  by), load-balancing on KV headroom + queue depth, with graceful spill and
  drain/migration through the runner's preemption/resume path.
- ``kv_tiering``: :class:`HostKVTier` — a host-RAM tier for cold paged KV
  blocks (evict least-recently-attended committed blocks to host buffers,
  re-admit bit-identically on prefix hit), extending KV capacity past HBM.
- ``cluster_kv``: :class:`ClusterKVStore` — the fleet rung under the host
  tier: a content-hash-keyed, refcounted, dedup'd cluster block store with
  a transport seam (in-process / multi-host DCN), so a prefix computed on
  one replica serves fleet-wide without re-prefill and fleet KV bytes
  scale with unique content instead of traffic.
- ``faults``: :class:`FaultInjector` — deterministic, seeded fault
  injection over the seams above (dispatch exceptions, wedged dispatches,
  hard replica death, allocation failure, host-tier corruption), so the
  router's supervision/recovery paths are exercised, not hoped for.
- ``tracing``: fleet-scope request tracing — trace ids minted at
  ``router.submit()`` and threaded through placement, causal span trees
  rebuilt from the telemetry event streams + router journal (continuity
  across drain/migration AND ``recover_replica``), a fleet-merged Perfetto
  export on one shared epoch clock, and the latency-waterfall explainer
  behind ``scripts/explain_request.py``.
- ``sla``: :class:`SLAClass`/:class:`SLAClassSet` — tenant tiers for the
  overload control plane: priority placement + preemption order,
  weighted-fair mixed-step prefill budgets, per-class latency targets, and
  the brown-out shed order.
- ``autoscaler``: :class:`ReplicaAutoscaler` — grows replicas from a
  registered factory under sustained queue/KV/SLO pressure and
  drains+retires them when the fleet idles (two-phase, bit-exact
  migration), with hysteresis and min/max bounds.
- ``pools``: :class:`PoolManager` — disaggregated prefill/decode pools:
  replicas carry a pool role, the router's ``remote_prefill`` policy places
  arrivals on the prefill pool, and on prompt completion each request's
  committed KV blocks hand off LIVE to a decode-pool replica (device
  gather/scatter sessions or the checksummed host tier), the transfer
  overlapped against the remaining prefill chunks.
- ``knobs``: :class:`KnobRegistry`/:class:`FleetKnobs` — the declarative
  table of every live-tunable schedule knob (bounds, owners, gauge export),
  the seam the control plane drives.
- ``tuner``: :class:`ServingTuner` — the online controller: walks
  schedule-only knobs from roofline/SLO/dispatch-gap signals with
  per-phase rules, hysteresis, and a never-worse rollback guard; every
  decision stamped into the step timeline, the router journal, and the
  metrics registry (the decision audit trail).
- ``replay``: :class:`ArrivalTrace`/:func:`replay` — the deterministic
  what-if replayer: reconstruct an arrival schedule from a committed
  router journal and re-run it on a real fleet under candidate knobs in
  virtual time, scored by the existing waterfall/coverage pipeline.
- ``memledger``: :class:`BlockLedger` — the accountable-KV-memory layer:
  every physical block attributed to an owner state ({free, live(request),
  idle(hash), host-reserved(hash), readmit-in-flight}), a conservation
  auditor over the allocator's real structures, fragmentation/idle-age
  telemetry, per-request/per-class byte attribution, and OOM forensics
  (``KVBlocksExhausted.ledger_snapshot`` naming the top holders).

Replicas are plain Python objects over independent runners, so "N replicas"
can mean N sub-meshes on one host (the dryrun harness fakes 8 devices) or,
later, N hosts behind the gloo launcher — the router only speaks the
admission interface.
"""

from . import memledger, tracing
from .autoscaler import ReplicaAutoscaler
from .engine import EngineReplica
from .memledger import BlockLedger, MemLedgerViolation
from .faults import (FaultInjector, FaultSpec, InjectedFault,
                     InjectedReplicaDeath)
from .cluster_kv import (ClusterKVStore, ClusterTransport,
                         DistributedKVTransport, InProcessTransport)
from .knobs import FleetKnobs, Knob, KnobRegistry
from .kv_tiering import HostKVTier
from .pools import POOL_DECODE, POOL_PREFILL, POOL_UNIFIED, PoolManager
from .replay import Arrival, ArrivalTrace, ReplayResult, reconstruct_trace, \
    replay
from .router import (PrefixAffinityRouter, RouterOverloaded, RouterRequest,
                     REPLICA_DEGRADED, REPLICA_FAILED, REPLICA_HEALTHY,
                     REPLICA_RETIRED)
from .sla import SLAClass, SLAClassSet, default_class_set
from .tuner import ServingTuner, TunerRule, default_rules

__all__ = ["EngineReplica", "HostKVTier", "PrefixAffinityRouter",
           "RouterRequest", "RouterOverloaded", "FaultInjector", "FaultSpec",
           "InjectedFault", "InjectedReplicaDeath", "REPLICA_HEALTHY",
           "REPLICA_DEGRADED", "REPLICA_FAILED", "REPLICA_RETIRED",
           "SLAClass", "SLAClassSet", "ReplicaAutoscaler",
           "default_class_set", "tracing", "memledger", "BlockLedger",
           "MemLedgerViolation", "PoolManager", "POOL_PREFILL", "POOL_DECODE",
           "POOL_UNIFIED", "Knob", "KnobRegistry", "FleetKnobs",
           "ServingTuner", "TunerRule", "default_rules", "Arrival",
           "ArrivalTrace", "ReplayResult", "reconstruct_trace", "replay",
           "ClusterKVStore", "ClusterTransport", "InProcessTransport",
           "DistributedKVTransport"]
