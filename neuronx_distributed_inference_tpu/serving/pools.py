"""Disaggregated prefill/decode pools with live KV-block handoff.

The roofline model (analysis/perf_model.py) classifies prefill compute-bound
and decode memory-bound, yet a unified fleet serves both phases on every
replica — a big prefill wave burns seconds of device time against ~15 ms
decode steps (the ``prefill_interference_ratio`` bench measures exactly that
collision). This module composes the machinery the serving stack already has
— ``drain``/``submit(resume_tokens=)`` migration, the checksummed
content-addressed host KV tier, per-signal autoscaling, memledger
attribution — into the TPLA-style disaggregated topology:

- **Pool roles** (:data:`POOL_PREFILL` / :data:`POOL_DECODE` /
  :data:`POOL_UNIFIED`, carried by ``EngineReplica.pool_role``): under the
  router's ``remote_prefill`` placement policy fresh arrivals place on the
  PREFILL pool and decoding (resumed/handed-off) requests place on the
  DECODE pool; UNIFIED replicas take both (and every other policy treats
  all roles as unified).
- **Live handoff** (:class:`PoolManager`): while a request's prompt is still
  inserting on its prefill-pool replica, the blocks its insert windows have
  already committed stream to a decode-pool replica CHUNK BY CHUNK — the
  transfer overlaps the remaining prefill compute, so by prompt completion
  most bytes have already moved and the migration costs one eviction +
  re-placement. Admission is gated by decode-pool KV headroom
  (``can_admit`` + ``handoff_headroom``): a pressured decode pool defers
  the handoff (the request keeps decoding where it is) rather than OOMing
  the destination.
- **Two channels**: ``channel="device"`` uses the destination runner's
  handoff sessions — a bucketed gather/scatter pair
  (``cb.paged.kv_handoff``, built beside ``cb.paged.tier_readmit`` and
  registered through ``audited_jit`` with the telemetry carry threaded)
  whose staged blocks the memledger tracks as ``handoff_inflight`` until
  commit. ``channel="tier"`` routes the bytes through the destination's
  content-addressed host tier (``tier.spill`` reading the SOURCE replica's
  cache), whose checksum verification turns a corrupted handoff block into
  a counted re-prefill instead of a poisoned stream.
- **Exactness**: the migrated request re-places via the router's normal
  ``submit(resume_tokens=)`` path pinned to the destination; its prefix
  walk hits the handed-off hashes (device-resident idle blocks, or host
  bytes that re-admit) and skips re-prefill — and because the blocks'
  BYTES moved verbatim, the continued stream is bit-identical to a
  never-migrated reference. Faults compose: a source replica dying
  mid-handoff aborts the session (nothing half-staged survives) and the
  journal rebuilds the stream; tests/test_pools.py pins both.

Pools are simulated as sub-fleets of replicas on one host (the dryrun
harness fakes the devices) — the structural prerequisite for multi-host
pools and the fleet KV store (ROADMAP item 4).
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional

import numpy as np

logger = logging.getLogger("tpu-inference")

__all__ = ["POOL_PREFILL", "POOL_DECODE", "POOL_UNIFIED", "PoolManager"]

POOL_PREFILL = "prefill"
POOL_DECODE = "decode"
POOL_UNIFIED = "unified"

#: handoff channels: "device" = gather/scatter sessions on the destination
#: runner (cb.paged.kv_handoff); "tier" = through the destination's
#: content-addressed host tier (checksummed; a corrupt block re-prefills)
CHANNELS = ("device", "tier")


class PoolManager:
    """Drive live prefill→decode KV handoffs over a router's sub-fleets.

    Constructed by :class:`~.router.PrefixAffinityRouter` when
    ``policy="remote_prefill"``; ``tick()`` runs once per router step, after
    the replica sweep (freshest insert progress), and per tracked request:

    1. **open** — pick the healthiest decode-pool destination whose KV
       headroom admits the request's WHOLE stream; defer (retry next tick)
       when none does;
    2. **stage** — gather the prompt blocks the source's insert windows have
       committed since the last tick and scatter them into the destination
       (device sessions hold them ``handoff_inflight``; tier spills park
       them as host bytes). Chunks staged while the source is still
       inserting count as OVERLAPPED — handoff latency hiding behind
       prefill compute;
    3. **finalize** — at prompt completion (the request started decoding)
       commit the session (hashes publish, blocks park idle), evict the
       request from the source, and re-queue it at the front PINNED to the
       destination — its prefix walk there reuses the handed-off blocks;
    4. **abort** — a source or destination leaving HEALTHY mid-transfer
       tears the session down; the journal/recovery path owns the stream.
    """

    def __init__(self, router, channel: str = "device"):
        if channel not in CHANNELS:
            raise ValueError(f"channel must be one of {CHANNELS}, "
                             f"got {channel!r}")
        if not router.paged:
            raise ValueError("disaggregated pools require paged attention "
                             "(KV handoff moves paged blocks)")
        roles = {rep.pool_role for rep in router.replicas.values()}
        if POOL_PREFILL not in roles or POOL_DECODE not in roles:
            raise ValueError(
                "remote_prefill needs at least one prefill-pool and one "
                f"decode-pool replica (got roles {sorted(roles)}); build "
                "replicas with EngineReplica(pool_role=...)")
        if channel == "tier":
            missing = [rid for rid, rep in router.replicas.items()
                       if rep.pool_role == POOL_DECODE
                       and rep.runner.kv_tier is None]
            if missing:
                raise ValueError(
                    f"channel='tier' needs a host KV tier on every "
                    f"decode-pool replica (missing on {missing})")
        else:
            # device sessions stage through the Python allocator's
            # alloc/release/hash seams; the native C++ allocator has none
            native = [rid for rid, rep in router.replicas.items()
                      if rep.pool_role == POOL_DECODE
                      and not hasattr(rep.runner.allocator, "_alloc_one")]
            if native:
                raise ValueError(
                    f"channel='device' needs the Python block allocator on "
                    f"every decode-pool replica (native C++ allocator on "
                    f"{native}; enable a host KV tier or memledger=True)")
        self.router = router
        self.channel = channel
        # per-request transfer state, keyed by frontend request id
        self._transfers: Dict[int, dict] = {}
        self.latencies_ms: List[float] = []
        self.blocks_total = 0
        self.overlap_blocks = 0
        self.bytes_total = 0
        self.overlapped_bytes = 0
        self.aborted: Dict[str, int] = {}
        reg = router.registry
        self._c_started = reg.counter(
            "pool_handoffs_started_total",
            "prefill→decode KV handoffs opened")
        self._c_completed = reg.counter(
            "pool_handoffs_completed_total",
            "handoffs committed + migrated to the decode pool")
        self._c_deferred = reg.counter(
            "pool_handoffs_deferred_total",
            "handoff attempts deferred by decode-pool KV headroom")
        self._c_aborted = reg.counter(
            "pool_handoffs_aborted_total",
            "handoffs torn down mid-transfer (source/destination left "
            "HEALTHY, or the stream finished at the source)")
        self._c_bytes = reg.counter(
            "pool_handoff_bytes_total", "KV bytes moved by handoffs")
        self._c_overlap_bytes = reg.counter(
            "pool_handoff_overlapped_bytes_total",
            "handoff KV bytes moved while the source was still prefilling")
        self._c_empty = reg.counter(
            "pool_migrations_without_blocks_total",
            "prompt-complete migrations carrying no full block (prompt "
            "shorter than one block — nothing to hand off)")

    # -------------------------------------------------------------- helpers
    @staticmethod
    def _local_row(runner, local_id: int):
        for r in runner.active:
            if r is not None and r.request_id == local_id and not r.done:
                return r
        return None

    def _healthy(self, rid: str) -> bool:
        from .router import REPLICA_HEALTHY

        return self.router._health.get(rid) == REPLICA_HEALTHY

    def _choose_dest(self, req):
        """Decode-pool replica with the most KV headroom whose headroom
        admits the request's WHOLE stream (the pool admission gate) — None
        defers the handoff."""
        n = len(req.prompt) + len(req.generated)
        best, best_room = None, -1
        for rid, rep in self.router.replicas.items():
            if rep.pool_role != POOL_DECODE or rep.draining:
                continue
            if not self._healthy(rid) or not rep.can_admit(n):
                continue
            room = rep.runner.handoff_headroom()
            if room < rep.blocks_needed(n):
                continue
            if room > best_room:
                best, best_room = rep, room
        return best

    # ----------------------------------------------------------------- tick
    def tick(self) -> None:
        router = self.router
        self._sweep_dead()
        for (rid, local_id), gid in list(router._local.items()):
            rep = router.replicas.get(rid)
            if rep is None or rep.pool_role != POOL_PREFILL:
                continue
            if not self._healthy(rid):
                continue
            req = router.requests[gid]
            if req.done:
                continue
            lr = self._local_row(rep.runner, local_id)
            if lr is None:
                continue              # still in the runner queue (no blocks)
            rec = self._transfers.get(gid)
            if rec is None:
                rec = self._open(req, rid)
                if rec is None:
                    continue          # deferred: no destination admits yet
            if not self._stage(req, rec, rep, lr):
                continue              # chunk deferred by destination pressure
            if not lr.inserting:
                self._finalize(req, rec, rep, lr)

    def _sweep_dead(self) -> None:
        """Abort transfers whose endpoints left HEALTHY or whose stream
        finished/migrated at the source before the handoff could."""
        router = self.router
        for gid, rec in list(self._transfers.items()):
            req = router.requests[gid]
            reason = None
            if not self._healthy(rec["src"]) or req.replica != rec["src"]:
                reason = "src_failed"
            elif not self._healthy(rec["dest"]):
                reason = "dest_failed"
            elif req.done:
                reason = "finished_at_source"
            if reason is None:
                continue
            self._abort(gid, rec, reason)

    def _abort(self, gid: int, rec: dict, reason: str) -> None:
        router = self.router
        if rec["sid"] is not None:
            dest = router.replicas.get(rec["dest"])
            try:
                if dest is not None:
                    dest.runner.handoff_abort(rec["sid"])
            # lint: ok(silent-except): a dead destination cannot release its own pool — recovery replaces the whole runner; the abort is counted either way
            except Exception:
                pass
        self.aborted[reason] = self.aborted.get(reason, 0) + 1
        self._c_aborted.inc()
        req = router.requests[gid]
        router._trace_event("handoff_abort", req, from_replica=rec["src"],
                            to_replica=rec["dest"], reason=reason,
                            staged_blocks=rec["staged"])
        del self._transfers[gid]
        logger.info("handoff of request %d aborted (%s): %d staged block(s) "
                    "discarded", gid, reason, rec["staged"])

    def _open(self, req, src_rid: str) -> Optional[dict]:
        dest = self._choose_dest(req)
        if dest is None:
            self._c_deferred.inc()
            return None
        sid = (dest.runner.handoff_open() if self.channel == "device"
               else None)
        rec = {"src": src_rid, "dest": dest.replica_id, "sid": sid,
               "staged": 0, "overlap": 0, "t0": time.perf_counter()}
        self._transfers[req.request_id] = rec
        self._c_started.inc()
        self.router._trace_event("handoff_start", req, from_replica=src_rid,
                                 to_replica=dest.replica_id,
                                 channel=self.channel,
                                 blocks_expected=len(req.hashes))
        return rec

    def _stage(self, req, rec: dict, src_rep, lr) -> bool:
        """Move the blocks committed since the last tick. Returns False when
        the destination could not take the chunk (retry next tick)."""
        bs = self.router.block_size
        n_full = len(req.hashes)
        ready = (min(lr.insert_pos // bs, n_full) if lr.inserting else n_full)
        new = ready - rec["staged"]
        if new <= 0:
            return True
        ids = lr.blocks[rec["staged"]:ready]
        hashes = req.hashes[rec["staged"]:ready]
        dest = self.router.replicas[rec["dest"]]
        overlapping = bool(lr.inserting)
        if self.channel == "device":
            k, v = src_rep.runner._read_tier_blocks(
                np.asarray(ids, dtype=np.int32))
            got = dest.runner.handoff_receive(rec["sid"], k, v, hashes,
                                              request_id=req.request_id)
            if got is None:
                self._c_deferred.inc()
                return False
        else:
            dest.runner.kv_tier.spill(ids, hashes,
                                      src_rep.runner._read_tier_blocks)
        rec["staged"] = ready
        nbytes = new * src_rep.runner._bytes_per_block()
        self.blocks_total += new
        self.bytes_total += nbytes
        self._c_bytes.inc(nbytes)
        if overlapping:
            rec["overlap"] += new
            self.overlap_blocks += new
            self.overlapped_bytes += nbytes
            self._c_overlap_bytes.inc(nbytes)
        return True

    def _finalize(self, req, rec: dict, src_rep, lr) -> None:
        """Prompt complete, every full block staged: commit and migrate."""
        router = self.router
        if rec["sid"] is not None:
            dest = router.replicas[rec["dest"]]
            dest.runner.handoff_commit(rec["sid"])
        if rec["staged"] == 0:
            self._c_empty.inc()
        # evict through the runner's preempt path; the pipeline flush may
        # still commit tokens — they belong to their streams and merge into
        # the next step()'s emissions (the SLA-preemption convention)
        emitted, _evicted = src_rep.evict_request(lr.request_id)
        for lid, toks in emitted.items():
            router._fold(rec["src"], lid, toks, router._pending_emitted)
        router._local.pop((rec["src"], lr.request_id), None)
        latency_ms = 1e3 * (time.perf_counter() - rec["t0"])
        del self._transfers[req.request_id]
        if req.done:
            # the flush finished the stream at the source — the staged
            # blocks stay as destination prefix-cache entries, but there is
            # no migration to count
            self.aborted["finished_at_source"] = (
                self.aborted.get("finished_at_source", 0) + 1)
            self._c_aborted.inc()
            return
        req.replica = None
        req.local_id = None
        req.migrations += 1
        req.pin_replica = rec["dest"]
        router.queue.insert(0, req)
        router._g_queue.set(len(router.queue))
        router._c_migrations.inc()
        self._c_completed.inc()
        self.latencies_ms.append(latency_ms)
        router._trace_event("migrate_out", req, from_replica=rec["src"],
                            tokens_so_far=len(req.generated))
        router._trace_event("handoff_done", req, from_replica=rec["src"],
                            to_replica=rec["dest"], channel=self.channel,
                            blocks=rec["staged"],
                            overlap_blocks=rec["overlap"],
                            latency_ms=round(latency_ms, 3))

    # ---------------------------------------------------------------- export
    def stats(self) -> Dict[str, object]:
        lat = np.asarray(self.latencies_ms, dtype=np.float64)
        return {
            "channel": self.channel,
            "roles": {rid: rep.pool_role
                      for rid, rep in self.router.replicas.items()},
            "started": int(self._c_started.value),
            "completed": int(self._c_completed.value),
            "deferred": int(self._c_deferred.value),
            "aborted": dict(self.aborted),
            "in_flight": len(self._transfers),
            "blocks_total": self.blocks_total,
            "bytes_total": self.bytes_total,
            "overlap_blocks": self.overlap_blocks,
            "overlapped_bytes": self.overlapped_bytes,
            "overlap_ratio": (self.overlapped_bytes / self.bytes_total
                              if self.bytes_total else 0.0),
            "migrations_without_blocks": int(self._c_empty.value),
            "latency_ms_p50": (round(float(np.percentile(lat, 50)), 3)
                               if lat.size else None),
            "latency_ms_p99": (round(float(np.percentile(lat, 99)), 3)
                               if lat.size else None),
        }
