"""Fleet-wide content-addressed KV block store (ROADMAP open item 4).

Millions of users mostly share prompts — system prompts, templates, few-shot
prefixes, repeated attachments — but a committed prefix is only warm on the
replica (or that replica's :class:`~.kv_tiering.HostKVTier`) that computed
it, so fleet KV capacity scales with *traffic* instead of with *unique
content*. This module promotes the host tier to a cluster service:

- :class:`ClusterKVStore` — a DCN-addressable block store keyed by the SAME
  chained content hashes the allocator's prefix cache uses, with the SAME
  shape+crc32 checksum contract :class:`~.kv_tiering._HostBlock` stamps at
  spill time. Replicas PUBLISH spilled blocks into it (``HostKVTier.spill``
  does so automatically when a cluster is attached); publication dedups by
  content hash — the same hash published twice stores ONCE, with per-owner
  refcounts — so fleet KV bytes scale with unique content.
- **Lookup ladder** — the prefix walk
  (:meth:`~.kv_tiering.TieredBlockAllocator.allocate_for_prompt`) and the
  router's affinity probe (:meth:`~.engine.EngineReplica.prefix_residency`)
  both see three rungs: device prefix cache (live/idle) → local host tier →
  cluster store. A COLD replica can serve a fleet-warm prompt without
  re-prefilling the shared blocks.
- **Pulls** — :meth:`ClusterKVStore.reserve` verifies the content checksum
  AT RESERVATION (the PR 10 degradation contract: a corrupt entry is
  dropped + counted and the tokens re-prefill, never read garbage KV),
  PINS the entry against LRU eviction for the pull's lifetime, and returns
  a :class:`_ClusterPull` handle that rides the existing audited
  ``cb.paged.tier_readmit`` scatter — no new graph kinds, the same bucketed
  dispatch, issued before the requesting prompt's first insert window so
  the restore overlaps earlier requests' insert windows exactly like the
  pool handoff staging (serving/pools.py).
- **Ownership / leak model** — every entry records WHO published it
  (per-owner refcounts) and every in-flight pull is tracked against its
  puller. :meth:`ClusterKVStore.audit` verifies pins == outstanding pulls,
  owner refcounts, and unpinned occupancy within capacity; the memledger's
  conservation audit (serving/memledger.py) merges these violations, and a
  pull still outstanding at a quiescent audit point is a LEAKED PIN
  attributed to its owner. ``on_owner_death`` reconciles a dead replica:
  its publish refs drop and its outstanding pulls abort (the pinned bytes
  unpin; nothing leaks, nothing is lost — entries it published remain
  valid, because content-addressed bytes don't die with their publisher).
- **Transport seam** — byte storage hides behind :class:`ClusterTransport`:
  :class:`InProcessTransport` (default) keeps arrays in-process for
  single-host fleets and tests; :class:`DistributedKVTransport` moves the
  bytes over the multi-host launcher's gloo/DCN coordinator channel
  (runtime/launcher.py — ``jax.distributed`` key-value store), making the
  store addressable across hosts without changing any caller.
"""

from __future__ import annotations

import itertools
import logging
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from .kv_tiering import _HostBlock

logger = logging.getLogger("tpu-inference")

__all__ = ["ClusterKVStore", "ClusterTransport", "InProcessTransport",
           "DistributedKVTransport"]


# ------------------------------------------------------------------ transport
class ClusterTransport:
    """Byte-storage seam of the cluster store: the DIRECTORY (hashes,
    checksums, refcounts, pins, LRU) always lives in :class:`ClusterKVStore`;
    the BYTES live behind this interface. ``put``/``get``/``delete``/
    ``contains`` speak ``(key: bytes, k: np.ndarray, v: np.ndarray)``."""

    def put(self, key: bytes, k: np.ndarray, v: np.ndarray) -> None:
        raise NotImplementedError

    def get(self, key: bytes) -> Tuple[np.ndarray, np.ndarray]:
        raise NotImplementedError

    def delete(self, key: bytes) -> None:
        raise NotImplementedError

    def contains(self, key: bytes) -> bool:
        raise NotImplementedError


class InProcessTransport(ClusterTransport):
    """Single-host transport: arrays held in-process. ``put`` COPIES — the
    store's bytes must not alias a publisher's host-tier entry (the fault
    injector mutates tier entries in place; a shared buffer would corrupt
    both stores through one write)."""

    def __init__(self):
        self._data: Dict[bytes, Tuple[np.ndarray, np.ndarray]] = {}

    def put(self, key, k, v):
        self._data[key] = (np.ascontiguousarray(k).copy(),
                           np.ascontiguousarray(v).copy())

    def get(self, key):
        return self._data[key]

    def delete(self, key):
        self._data.pop(key, None)

    def contains(self, key):
        return key in self._data


class DistributedKVTransport(ClusterTransport):
    """Multi-host transport over the launcher's coordinator channel
    (runtime/launcher.py ``initialize_multihost`` → ``jax.distributed``):
    blocks serialize into the coordinator's key-value store, so every
    process in the fleet resolves the same key-space over DCN. Requires an
    initialized ``jax.distributed`` client — constructing one without it
    raises, pointing at the launcher (single-host callers simply keep the
    in-process default)."""

    def __init__(self, prefix: str = "ckv"):
        from jax._src import distributed

        client = distributed.global_state.client
        if client is None:
            raise RuntimeError(
                "DistributedKVTransport needs an initialized jax.distributed "
                "client — launch through runtime/launcher.py "
                "(initialize_multihost / init_from_env) first, or use the "
                "default in-process transport on a single host")
        self._client = client
        self._prefix = prefix
        # key presence tracked locally: the coordinator KV store has no
        # cheap existence probe, and the directory (ClusterKVStore) is the
        # authority on membership anyway
        self._known: set = set()

    def _key(self, key: bytes) -> str:
        return f"{self._prefix}/{key.hex()}"

    @staticmethod
    def _pack(k: np.ndarray, v: np.ndarray) -> str:
        import base64
        import io

        buf = io.BytesIO()
        np.savez(buf, k=np.ascontiguousarray(k), v=np.ascontiguousarray(v))
        return base64.b64encode(buf.getvalue()).decode("ascii")

    @staticmethod
    def _unpack(payload: str) -> Tuple[np.ndarray, np.ndarray]:
        import base64
        import io

        with np.load(io.BytesIO(base64.b64decode(payload))) as z:
            return z["k"], z["v"]

    def put(self, key, k, v):
        self._client.key_value_set(self._key(key), self._pack(k, v))
        self._known.add(key)

    def get(self, key):
        payload = self._client.blocking_key_value_get(self._key(key),
                                                      60_000)
        return self._unpack(payload)

    def delete(self, key):
        # the coordinator store has no delete; the directory drop is what
        # makes the entry unreachable (the orphaned payload ages out with
        # the coordinator)
        self._known.discard(key)

    def contains(self, key):
        return key in self._known


# ------------------------------------------------------------------ entries
class _ClusterEntry:
    """Directory record of one published block: checksum + shape contract,
    LRU stamp, per-owner publish refcounts, and the pin count that holds it
    against eviction while pulls are in flight. The BYTES live behind the
    transport."""

    __slots__ = ("checksum", "stamp", "owners", "pins", "nbytes")

    def __init__(self, checksum: int, stamp: int, owner: str, nbytes: int):
        self.checksum = checksum
        self.stamp = stamp
        self.owners: Dict[str, int] = {owner: 1}
        self.pins = 0
        self.nbytes = nbytes


class _ClusterPull:
    """One in-flight cluster→device pull: the bytes (fetched + checksum-
    verified at reservation), pinned at the store until ``commit`` (the
    readmit scatter landed) or ``abort`` (allocation rollback / dead-replica
    reconciliation). API-compatible with the slice of ``_HostBlock`` the
    readmit dispatch uses (``materialize``), plus ``abort`` — which is how
    ``HostKVTier.restore`` tells a cluster pull from a host reservation."""

    __slots__ = ("_store", "pull_id", "hash", "_np", "_done")

    def __init__(self, store: "ClusterKVStore", pull_id: int, h: bytes,
                 k: np.ndarray, v: np.ndarray):
        self._store = store
        self.pull_id = pull_id
        self.hash = h
        self._np = (k, v)
        self._done = False

    def materialize(self) -> Tuple[np.ndarray, np.ndarray]:
        return self._np

    def nbytes(self) -> int:
        return self._np[0].nbytes + self._np[1].nbytes

    def commit(self) -> None:
        """The readmit scatter is enqueued: unpin, count the restored
        blocks/bytes."""
        if not self._done:
            self._done = True
            self._store._finish_pull(self.pull_id, committed=True)

    def abort(self) -> None:
        """Allocation rollback or recovery: unpin without counting a
        restore (idempotent — recovery may race a rollback)."""
        if not self._done:
            self._done = True
            self._store._finish_pull(self.pull_id, committed=False)


# -------------------------------------------------------------------- store
class ClusterKVStore:
    """The fleet's content-addressed KV block store: dedup by hash,
    capacity-bounded LRU with pin-for-in-flight-pull, per-owner ownership
    accounting, and a transport seam for the bytes.

    One store instance is SHARED by every replica of the fleet (in-process)
    or mirrored per-process over :class:`DistributedKVTransport`. Replicas
    attach through ``HostKVTier(cluster=...)`` — the tier publishes on
    spill and reserves pulls during the allocator's prefix walk; nothing
    else in the serving stack talks to the store directly."""

    def __init__(self, capacity_blocks: int = 4096,
                 transport: Optional[ClusterTransport] = None,
                 name: str = "cluster0"):
        if capacity_blocks < 0:
            raise ValueError("capacity_blocks must be >= 0")
        self.capacity_blocks = capacity_blocks
        self.name = name
        self.transport = transport if transport is not None \
            else InProcessTransport()
        self.entries: Dict[bytes, _ClusterEntry] = {}
        # replicas publish/pull concurrently (each serving loop is its own
        # thread in a threaded frontend): directory mutations serialize here
        self._lock = threading.RLock()
        self._clock = 0
        self._pull_seq = itertools.count()
        # in-flight pulls: pull_id -> (hash, owner) — the leak roster
        self._outstanding: Dict[int, Tuple[bytes, str]] = {}
        # counters (plain ints; bench / router stats surface them)
        self.published_total = 0       # publish() calls (all, dup included)
        self.published_unique = 0      # entries actually stored (first copy)
        self.dedup_hits = 0            # publishes deduped against a stored copy
        self.pulls_total = 0           # reservations granted
        self.cross_replica_pulls = 0   # pulls by a non-publisher owner
        self.pull_blocks_committed = 0  # pulls whose readmit scatter landed
        self.pull_aborts = 0           # pulls rolled back / written off
        self.bytes_pulled = 0          # committed pull bytes
        self.evictions = 0             # LRU drops past capacity
        self.integrity_failures = 0    # entries dropped on checksum mismatch
        self.watermark = 0             # peak directory occupancy (blocks)

    # ------------------------------------------------------------- directory
    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def __contains__(self, h: bytes) -> bool:
        return h in self.entries

    def blocks(self) -> int:
        return len(self.entries)

    def bytes_stored(self) -> int:
        return sum(e.nbytes for e in self.entries.values())

    def dedup_ratio(self) -> Optional[float]:
        """unique / total published blocks — < 1.0 is the fleet-dedup win
        (None until anything was published)."""
        if self.published_total == 0:
            return None
        return self.published_unique / self.published_total

    # ------------------------------------------------------------ publish side
    def publish(self, h: bytes, host_blk: _HostBlock, owner: str) -> bool:
        """Publish one spilled block under its content hash. Dedup: a hash
        already stored takes a refcount for ``owner`` and stores NOTHING
        (the fleet-dedup win the bench's ``cluster_dedup_ratio`` measures).
        Returns True when this call stored the first copy."""
        with self._lock:
            return self._publish_locked(h, host_blk, owner)

    def _publish_locked(self, h: bytes, host_blk: _HostBlock,
                        owner: str) -> bool:
        self.published_total += 1
        ent = self.entries.get(h)
        if ent is not None:
            self.dedup_hits += 1
            ent.owners[owner] = ent.owners.get(owner, 0) + 1
            ent.stamp = self._tick()
            return False
        if self.capacity_blocks == 0:
            return False
        k, v = host_blk.materialize()
        checksum = host_blk.checksum
        if checksum is None:                       # defensive: stamp now
            checksum = _HostBlock._digest(k, v)
        self.transport.put(h, k, v)
        self.entries[h] = _ClusterEntry(checksum, self._tick(), owner,
                                        k.nbytes + v.nbytes)
        self.published_unique += 1
        self.watermark = max(self.watermark, len(self.entries))
        self._enforce_capacity()
        return True

    def _enforce_capacity(self) -> None:
        """LRU past capacity — PINNED entries (in-flight pulls) never evict;
        a fully-pinned over-capacity store carries the overage until the
        pulls finish."""
        while len(self.entries) > self.capacity_blocks:
            unpinned = [h for h, e in self.entries.items() if e.pins == 0]
            if not unpinned:
                return
            h = min(unpinned, key=lambda x: self.entries[x].stamp)
            del self.entries[h]
            self.transport.delete(h)
            self.evictions += 1

    # -------------------------------------------------------------- pull side
    def reserve(self, h: bytes, owner: str) -> Optional[_ClusterPull]:
        """Reserve one block for a cluster→device pull: fetch through the
        transport, VERIFY the content checksum (the reservation-time
        integrity gate — same contract as ``HostKVTier.reserve``), pin the
        entry against LRU eviction, and hand back the pull. ``None`` on a
        miss or on verification failure — the corrupt entry is DROPPED and
        counted, and the caller treats the hash as a miss (the tokens
        re-prefill; garbage KV is never readmitted)."""
        with self._lock:
            return self._reserve_locked(h, owner)

    def _reserve_locked(self, h: bytes, owner: str) -> Optional["_ClusterPull"]:
        ent = self.entries.get(h)
        if ent is None:
            return None
        try:
            k, v = self.transport.get(h)
            ok = _HostBlock._digest(k, v) == ent.checksum
        # lint: ok(silent-except): a torn/truncated payload can make the digest itself throw (shape gone) — that IS a failed verification
        except Exception:
            ok = False
        if not ok:
            self.integrity_failures += 1
            del self.entries[h]
            self.transport.delete(h)
            logger.warning(
                "cluster KV entry %s failed its content checksum — dropped; "
                "the prefix re-prefills instead of pulling corrupt bytes",
                h.hex()[:16])
            return None
        ent.pins += 1
        ent.stamp = self._tick()
        pull_id = next(self._pull_seq)
        self._outstanding[pull_id] = (h, owner)
        self.pulls_total += 1
        if owner not in ent.owners:
            # the content was computed (and published) elsewhere: this is
            # the cross-replica hit the whole store exists for
            self.cross_replica_pulls += 1
        return _ClusterPull(self, pull_id, h, k, v)

    def _finish_pull(self, pull_id: int, committed: bool) -> None:
        with self._lock:
            h, _owner = self._outstanding.pop(pull_id)
            ent = self.entries.get(h)
            if ent is not None and ent.pins > 0:
                ent.pins -= 1
            if committed:
                self.pull_blocks_committed += 1
                if ent is not None:
                    self.bytes_pulled += ent.nbytes
            else:
                self.pull_aborts += 1
            self._enforce_capacity()

    def outstanding_pulls(self, owner: Optional[str] = None) -> int:
        if owner is None:
            return len(self._outstanding)
        return sum(1 for _h, o in self._outstanding.values() if o == owner)

    # ------------------------------------------------------------- ownership
    def on_owner_death(self, owner: str) -> Dict[str, int]:
        """Reconcile a dead replica (serving/router.recover_replica): its
        publish refs drop (entries it alone published REMAIN — content-
        addressed bytes are replica-invariant and stay servable; they just
        become unowned LRU candidates) and its outstanding pulls abort so
        their pins release. Returns ``{"refs_dropped": n, "pulls_aborted":
        m}`` for the recovery log."""
        with self._lock:
            return self._on_owner_death_locked(owner)

    def _on_owner_death_locked(self, owner: str) -> Dict[str, int]:
        refs = 0
        for ent in self.entries.values():
            refs += ent.owners.pop(owner, 0)
        aborted = 0
        for pid in [p for p, (_h, o) in self._outstanding.items()
                    if o == owner]:
            self._finish_pull(pid, committed=False)
            aborted += 1
        if refs or aborted:
            logger.warning(
                "cluster store %s reconciled dead owner %s: %d publish "
                "ref(s) dropped, %d in-flight pull(s) aborted (published "
                "entries remain servable)", self.name, owner, refs, aborted)
        return {"refs_dropped": refs, "pulls_aborted": aborted}

    # ----------------------------------------------------------------- audit
    def audit(self, owner: Optional[str] = None,
              check_inflight: bool = True) -> List[dict]:
        """Ownership/conservation invariants, as memledger-shaped violation
        dicts (the BlockLedger audit merges them):

        - every entry's pin count equals the outstanding pulls naming it
          (a mismatch is a lost ``commit``/``abort`` — a pin leak);
        - owner refcounts are positive;
        - unpinned occupancy is within capacity (pinned overage is legal);
        - every directory entry's bytes are reachable through the transport;
        - with ``check_inflight``, no pull is outstanding for ``owner``
          (or for anyone, when ``owner`` is None) — a quiescent audit point
          seeing one means somebody took bytes and never finished."""
        v: List[dict] = []
        pins_by_hash: Dict[bytes, int] = {}
        for h, _o in self._outstanding.values():
            pins_by_hash[h] = pins_by_hash.get(h, 0) + 1
        for h, ent in self.entries.items():
            if ent.pins != pins_by_hash.get(h, 0):
                v.append({"kind": "cluster_pin_mismatch", "detail":
                          f"entry {h.hex()[:12]}: pins {ent.pins} != "
                          f"{pins_by_hash.get(h, 0)} outstanding pull(s) — "
                          f"a commit/abort was dropped"})
            for o, n in ent.owners.items():
                if n <= 0:
                    v.append({"kind": "cluster_owner_refs", "detail":
                              f"entry {h.hex()[:12]}: owner {o} holds "
                              f"non-positive refcount {n}"})
            if not self.transport.contains(h):
                v.append({"kind": "cluster_bytes_missing", "detail":
                          f"entry {h.hex()[:12]} has no bytes behind the "
                          f"transport"})
        unpinned = sum(1 for e in self.entries.values() if e.pins == 0)
        pinned = len(self.entries) - unpinned
        if len(self.entries) > self.capacity_blocks and unpinned > max(
                0, self.capacity_blocks - pinned):
            v.append({"kind": "cluster_over_capacity", "detail":
                      f"{len(self.entries)} entries ({pinned} pinned) over "
                      f"capacity {self.capacity_blocks} with evictable "
                      f"candidates — LRU enforcement was skipped"})
        if check_inflight:
            stuck = [(p, h, o) for p, (h, o) in self._outstanding.items()
                     if owner is None or o == owner]
            for pid, h, o in stuck[:8]:
                v.append({"kind": "cluster_pull_stuck", "seam": o, "detail":
                          f"pull {pid} of {h.hex()[:12]} by owner {o} "
                          f"outstanding at a quiescent audit point — a "
                          f"leaked pin"})
        return v

    # ----------------------------------------------------------------- stats
    def stats(self) -> Dict[str, object]:
        return {
            "name": self.name,
            "capacity_blocks": self.capacity_blocks,
            "blocks": len(self.entries),
            "bytes": self.bytes_stored(),
            "watermark": self.watermark,
            "published_total": self.published_total,
            "published_unique": self.published_unique,
            "dedup_hits": self.dedup_hits,
            "dedup_ratio": self.dedup_ratio(),
            "pulls_total": self.pulls_total,
            "cross_replica_pulls": self.cross_replica_pulls,
            "pull_blocks_committed": self.pull_blocks_committed,
            "pull_aborts": self.pull_aborts,
            "bytes_pulled": self.bytes_pulled,
            "outstanding_pulls": len(self._outstanding),
            "evictions": self.evictions,
            "integrity_failures": self.integrity_failures,
            "transport": type(self.transport).__name__,
        }
