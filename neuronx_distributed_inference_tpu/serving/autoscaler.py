"""SLO-driven replica autoscaling over the prefix-affinity router.

:class:`ReplicaAutoscaler` closes the loop ROADMAP open item 3 left open:
the router balances load and the SLO monitor judges health, and this module
CHANGES THE FLEET in response — growing replicas from a registered factory
under sustained pressure and draining/retiring them when the fleet idles.
Everything it does rides machinery that is already bit-exact:

- **Grow**: ``replica_factory(replica_id) -> EngineReplica`` builds a fresh
  replica (same weights object, own runner/pool) and
  ``router.add_replica()`` puts it in the placement set. New arrivals place
  onto it from the next wave.
- **Shrink**: ``router.drain_replica(id)`` migrates the victim's live
  streams through the mid-prompt preempt/resume path (bit-exact — the PR 8
  guarantee), then once the replica is empty ``router.remove_replica(id)``
  retires it. Shrink is therefore a two-phase ``drain → retire`` and the
  autoscaler never drops a token.

Signals (evaluated per :meth:`tick`):

- router arrival-queue depth (``scale_up_queue_depth`` — sustained backlog
  means the fleet cannot place what arrives);
- mean KV-block headroom fraction over HEALTHY replicas
  (``scale_up_kv_headroom`` floor — the admission signal the router
  load-balances on, aggregated);
- the SLO state (``slo_signal`` unhealthy counts as pressure — the same
  callable the router's brown-out ladder reads, so the autoscaler GROWS
  while the ladder sheds and the two meet in the middle).

Hysteresis: a signal must persist for ``up_after``/``down_after``
consecutive ticks and a ``cooldown_s`` quiet period separates actions, so
a bursty trace cannot thrash the fleet. ``clock`` is injectable (tests
drive a fake clock; production uses ``time.monotonic``).
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Dict, List, Optional

logger = logging.getLogger("tpu-inference")

__all__ = ["ReplicaAutoscaler"]


class ReplicaAutoscaler:
    """Grow/drain/retire replicas from router pressure signals.

    ``tick()`` evaluates the signals once and performs AT MOST one action;
    call it from the serving loop (every step or on a timer). Returns the
    action taken (``"grow:<id>"``, ``"drain:<id>"``, ``"retire:<id>"``) or
    None."""

    def __init__(self, router, replica_factory: Callable[[str], object], *,
                 min_replicas: int = 1, max_replicas: int = 4,
                 scale_up_queue_depth: int = 4,
                 scale_up_kv_headroom: float = 0.1,
                 scale_down_queue_depth: int = 0,
                 scale_down_kv_headroom: float = 0.5,
                 up_after: int = 2, down_after: int = 4,
                 cooldown_s: float = 10.0,
                 slo_signal: Optional[Callable[[], bool]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 pool: Optional[str] = None):
        if min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if max_replicas < min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if up_after < 1 or down_after < 1:
            raise ValueError("up_after/down_after must be >= 1")
        self.router = router
        self.replica_factory = replica_factory
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.scale_up_queue_depth = int(scale_up_queue_depth)
        self.scale_up_kv_headroom = float(scale_up_kv_headroom)
        self.scale_down_queue_depth = int(scale_down_queue_depth)
        self.scale_down_kv_headroom = float(scale_down_kv_headroom)
        self.up_after = int(up_after)
        self.down_after = int(down_after)
        self.cooldown_s = float(cooldown_s)
        self.slo_signal = slo_signal
        self.clock = clock
        # disaggregated pools (serving/pools.py): a non-None ``pool``
        # restricts EVERYTHING — fleet size, headroom aggregation, drain
        # victims, min/max bounds — to replicas of that role, so each pool
        # runs its own autoscaler on its own SLO signal (prefill-pool TTFT
        # vs decode-pool TPOT) without the two fighting over one fleet.
        # The replica_factory must build replicas carrying this pool_role.
        self.pool = pool
        self._up_streak = 0
        self._down_streak = 0
        self._last_action_t: Optional[float] = None
        self._next_id = 0
        self._draining: List[str] = []       # drain issued, retire pending
        reg = router.registry
        # per-pool autoscalers share one router registry: the pool label
        # keeps their instruments distinct (two unlabelled gauges of one
        # name would silently overwrite each other)
        labels = {"pool": pool} if pool is not None else None
        self._c_up = reg.counter(
            "autoscaler_scale_ups_total",
            "replicas grown from the factory", labels=labels)
        self._c_down = reg.counter(
            "autoscaler_scale_downs_total",
            "replicas drained + retired (two-phase; counted at retire)",
            labels=labels)
        self._g_replicas = reg.gauge(
            "autoscaler_replicas", "replicas currently in the placement set",
            labels=labels)
        self._g_replicas.set(self._fleet_size())
        # live knob table (serving/knobs.py, ISSUE-18): fleet bounds +
        # pressure thresholds, enumerated for the tuner and gauge-exported
        from .knobs import build_autoscaler_knobs

        self.knobs = build_autoscaler_knobs(self)

    def _stamp_decision(self, action: str, rid: str,
                        pressure: Dict[str, object]) -> None:
        """Decision audit trail (ISSUE-18 satellite): every grow/drain/
        retire lands in the router journal AND on every healthy replica's
        next step-timeline record — the same plumbing brown-out transitions
        use — so ``explain_request`` shows WHY a replica appeared or
        drained mid-request instead of just that it did."""
        self.router._trace_event(
            "autoscale", action=action, replica=rid, pool=self.pool,
            fleet_size=self._fleet_size(),
            queue_depth=pressure.get("queue_depth"),
            kv_headroom=pressure.get("kv_headroom"),
            slo_unhealthy=pressure.get("slo_unhealthy"))
        self.router.stamp_fleet("autoscaler", action, detail=rid)

    # -------------------------------------------------------------- signals
    def _in_scope(self, rep) -> bool:
        return self.pool is None or getattr(rep, "pool_role",
                                            "unified") == self.pool

    def _fleet_size(self) -> int:
        """Replicas that can take or hold work (FAILED ones don't count —
        recovery owns them; they are capacity only after reactivation).
        Pool-scoped when ``pool`` is set."""
        return sum(1 for rid, rep in self.router.replicas.items()
                   if self.router.replica_state(rid) != "failed"
                   and self._in_scope(rep))

    def _healthy_admissions(self) -> List[Dict[str, object]]:
        out = []
        for rid, rep in self.router.replicas.items():
            if self.router.replica_state(rid) != "healthy" or rep.draining:
                continue
            if not self._in_scope(rep):
                continue
            try:
                out.append(rep.admission())
            # lint: ok(silent-except): admission probe of a replica mid-failure; the supervisor owns its lifecycle
            except Exception:
                continue
        return out

    def _mean_kv_headroom(self) -> Optional[float]:
        fr = [a["kv_headroom_frac"] for a in self._healthy_admissions()
              if "kv_headroom_frac" in a]
        return (sum(fr) / len(fr)) if fr else None

    def pressure(self) -> Dict[str, object]:
        """The signal snapshot one tick evaluates (also the stats surface)."""
        queue = len(self.router.queue)
        headroom = self._mean_kv_headroom()
        slo_unhealthy = (self.slo_signal is not None
                         and not bool(self.slo_signal()))
        up = (queue > self.scale_up_queue_depth
              or (headroom is not None
                  and headroom < self.scale_up_kv_headroom)
              or slo_unhealthy)
        down = (queue <= self.scale_down_queue_depth
                and not slo_unhealthy
                and (headroom is None
                     or headroom > self.scale_down_kv_headroom))
        return {"queue_depth": queue, "kv_headroom": headroom,
                "slo_unhealthy": slo_unhealthy, "up": up, "down": down}

    def _cooling(self, now: float) -> bool:
        return (self._last_action_t is not None
                and now - self._last_action_t < self.cooldown_s)

    # ----------------------------------------------------------------- tick
    def tick(self) -> Optional[str]:
        now = self.clock()
        # phase 2 of a shrink first: retire any drained-out replica (no
        # cooldown gate — the capacity already left at drain time)
        for rid in list(self._draining):
            rep = self.router.replicas.get(rid)
            if rep is None:
                self._draining.remove(rid)
                continue
            if not rep.has_work:
                self.router.remove_replica(rid)
                self._draining.remove(rid)
                self._c_down.inc()
                self._g_replicas.set(self._fleet_size())
                logger.info("autoscaler: retired drained replica %s", rid)
                self._stamp_decision("retire", rid, self.pressure())
                return f"retire:{rid}"
        p = self.pressure()
        if p["up"]:
            self._up_streak += 1
            self._down_streak = 0
        elif p["down"]:
            self._down_streak += 1
            self._up_streak = 0
        else:
            self._up_streak = self._down_streak = 0
        size = self._fleet_size()
        if (self._up_streak >= self.up_after and size < self.max_replicas
                and not self._cooling(now)):
            return self._grow(now, p)
        if (self._down_streak >= self.down_after
                and size - len(self._draining) > self.min_replicas
                and not self._cooling(now)):
            return self._drain_one(now, p)
        return None

    def _grow(self, now: float, pressure: Dict[str, object]) -> str:
        # fresh ids: autoscaled replicas are "as<N>" and never collide with
        # the seed fleet's ids (add_replica rejects collisions anyway)
        while f"as{self._next_id}" in self.router.replicas:
            self._next_id += 1
        rid = f"as{self._next_id}"
        self._next_id += 1
        replica = self.replica_factory(rid)
        if replica.replica_id != rid:
            raise ValueError(f"replica_factory must honor the id it is "
                             f"given (got {replica.replica_id!r}, want "
                             f"{rid!r})")
        self.router.add_replica(replica)
        self._c_up.inc()
        self._g_replicas.set(self._fleet_size())
        self._last_action_t = now
        self._up_streak = 0
        logger.warning("autoscaler: GREW replica %s (%s)", rid, pressure)
        self._stamp_decision("grow", rid, pressure)
        return f"grow:{rid}"

    def _drain_one(self, now: float, pressure: Dict[str, object]) -> Optional[str]:
        # victim: the least-loaded healthy replica (its streams migrate the
        # cheapest); never one already draining
        best, best_key = None, None
        for rid, rep in self.router.replicas.items():
            if (self.router.replica_state(rid) != "healthy" or rep.draining
                    or rid in self._draining):
                continue
            if not self._in_scope(rep):
                continue
            try:
                a = rep.admission()
            # lint: ok(silent-except): admission probe mid-failure; the supervisor owns the lifecycle
            except Exception:
                continue
            key = (a["queue_depth"] + a["active_requests"], rid)
            if best_key is None or key < best_key:
                best, best_key = rid, key
        if best is None:
            return None
        migrated = self.router.drain_replica(best)
        self._draining.append(best)
        self._last_action_t = now
        self._down_streak = 0
        logger.warning("autoscaler: DRAINING replica %s (%d streams "
                       "migrating; %s)", best, migrated, pressure)
        self._stamp_decision("drain", best, pressure)
        return f"drain:{best}"

    # ---------------------------------------------------------------- export
    def stats(self) -> Dict[str, object]:
        return {
            "replicas": self._fleet_size(),
            "pool": self.pool,
            "knobs": self.knobs.snapshot(),
            "min": self.min_replicas, "max": self.max_replicas,
            "draining": list(self._draining),
            "scale_ups": int(self._c_up.value),
            "scale_downs": int(self._c_down.value),
            "up_streak": self._up_streak, "down_streak": self._down_streak,
            "cooldown_s": self.cooldown_s,
            "cooling": self._cooling(self.clock()),
            "pressure": self.pressure(),
        }
