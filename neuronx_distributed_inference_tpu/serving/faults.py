"""Deterministic fault injection for the scale-out serving stack.

Production TPU serving treats partial failure as the steady state (the
Gemma-on-TPU serving comparison, PAPERS.md): replicas wedge, dispatches
throw, host-RAM KV bytes rot, whole engines die without a goodbye. The
recovery machinery in serving/router.py (supervision, backoff, watchdog,
``recover_replica``) is only trustworthy if every one of those paths is
EXERCISED — by tests and by bench — not hoped for. This module is the
harness: a seeded, declarative :class:`FaultInjector` that wraps the
existing seams and fires faults on a deterministic schedule.

Fault kinds (``FAULT_KINDS``):

``exception``   a transient dispatch exception raised from the replica's
                ``step()`` seam (:class:`InjectedFault`) — the retry/backoff
                path's food.
``stall``       a wedged dispatch: ``step()`` blocks for ``stall_ms`` before
                proceeding — the watchdog's food.
``death``       hard replica death: the replica raises
                :class:`InjectedReplicaDeath` on every ``step``/``submit``/
                ``drain`` call from the fire point on (until ``revive``) —
                ``recover_replica``'s food.
``alloc``       one :class:`~..modules.block_kvcache.KVBlocksExhausted`
                raised from the replica allocator's next ``_alloc_one`` —
                the preempt-or-shed path's food.
``leak``        DROP the allocator's next ``_release_one`` (the refcount is
                never decremented, so the block stays held by a request
                that no longer exists) — the KV block ledger's food: the
                conservation auditor (serving/memledger.py) must detect the
                leak and attribute it to the exact request and seam.
``corrupt``     flip bytes in one host-KV-tier entry (checksum intact from
                spill time, bytes now wrong) — the readmit integrity check's
                food.
``truncate``    shrink one host-tier entry's arrays (a torn/partial copy) —
                same check, different failure shape.
``overload``    a seeded tenant BURST: ``burst`` synthetic arrivals of
                ``burst_prompt``-token prompts (class ``burst_class``, or
                the router's least-important class) submitted at the
                frontend — THROUGH admission, so the shed path is what
                absorbs them — followed by a ``stall_ms`` slow-drain stall
                on the stepping replica. The brown-out / shed / preemption
                paths' food (ISSUE-13).

Fault-spec grammar (CLI ``--inject-faults``, one string; documented in
docs/SERVING.md):

    spec     := entry (";" entry)*
    entry    := kind ["@" replica] [":" key "=" value ("," key "=" value)*]
    keys     := at_step | every_n | once | stall_ms
                | burst | burst_prompt | burst_new | burst_class

``at_step=N`` fires when the REPLICA's step counter reaches N (``once=1``
by default); ``every_n=N`` fires on every N-th step (``once=0`` by
default); no schedule key means ``at_step=1``. For ``corrupt``/``truncate``
the schedule means "at or AFTER": a mutation scheduled before the host
tier holds any bytes stays armed and fires at the first step with
something to corrupt. ``replica`` scopes the entry to one replica id;
omitted = every replica. Example::

    --inject-faults "death@0:at_step=4;exception:every_n=7;corrupt@1:at_step=2"

Determinism: the schedule is step-counted (no wall clock), and the only
randomness — which host-tier entry a ``corrupt``/``truncate`` picks — comes
from the injector's own seeded generator, so a fault run is replayable.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..modules.block_kvcache import KVBlocksExhausted

logger = logging.getLogger("tpu-inference")

__all__ = ["FAULT_KINDS", "FaultSpec", "FaultInjector", "InjectedFault",
           "InjectedReplicaDeath", "parse_fault_specs"]

FAULT_KINDS = ("exception", "stall", "death", "alloc", "leak", "corrupt",
               "truncate", "overload")


class InjectedFault(RuntimeError):
    """A transient injected dispatch failure (retryable)."""


class InjectedReplicaDeath(InjectedFault):
    """Hard replica death: every call after the fire point raises this —
    the replica cannot cooperate with its own recovery."""


@dataclass
class FaultSpec:
    """One declarative fault: what, where, when.

    Exactly one of ``at_step``/``every_n`` schedules it (neither defaults
    to ``at_step=1``); ``once`` bounds repeat fires per replica (defaults
    True for ``at_step``, False for ``every_n``)."""

    kind: str
    replica: Optional[str] = None        # None = every replica
    at_step: Optional[int] = None
    every_n: Optional[int] = None
    once: Optional[bool] = None
    stall_ms: float = 100.0
    # ``overload`` knobs: burst size / prompt length / max-new of the
    # injected tenant burst, and the SLA class it arrives under (None = the
    # router's least-important class, or classless on a classless router)
    burst: int = 8
    burst_prompt: int = 64
    burst_new: int = 16
    burst_class: Optional[str] = None
    # ``corrupt``/``truncate`` target: the replica's host tier ("tier",
    # default) or the fleet's cluster KV store ("cluster") — the latter
    # exercises the pull-side verify in serving/cluster_kv.py
    store: str = "tier"

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"(known: {FAULT_KINDS})")
        if self.store not in ("tier", "cluster"):
            raise ValueError(f"unknown fault store {self.store!r} "
                             f"(known: tier, cluster)")
        if self.burst < 1 or self.burst_prompt < 1 or self.burst_new < 1:
            raise ValueError("burst/burst_prompt/burst_new must be >= 1")
        if self.at_step is not None and self.every_n is not None:
            raise ValueError("at_step and every_n are mutually exclusive")
        if self.at_step is None and self.every_n is None:
            self.at_step = 1
        if self.at_step is not None and self.at_step < 1:
            raise ValueError("at_step must be >= 1")
        if self.every_n is not None and self.every_n < 1:
            raise ValueError("every_n must be >= 1")
        if self.once is None:
            self.once = self.every_n is None
        if self.stall_ms < 0:
            raise ValueError("stall_ms must be >= 0")

    @classmethod
    def parse(cls, entry: str) -> "FaultSpec":
        entry = entry.strip()
        head, _, args = entry.partition(":")
        kind, _, replica = head.strip().partition("@")
        kw: Dict[str, object] = {"kind": kind.strip(),
                                 "replica": replica.strip() or None}
        for part in args.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(f"fault spec entry {part!r} is not "
                                 f"key=value (in {entry!r})")
            k, v = (s.strip() for s in part.split("=", 1))
            if k in ("at_step", "every_n", "burst", "burst_prompt",
                     "burst_new"):
                kw[k] = int(v)
            elif k == "once":
                kw[k] = v.lower() in ("1", "true", "yes")
            elif k == "stall_ms":
                kw[k] = float(v)
            elif k in ("burst_class", "store"):
                kw[k] = v
            else:
                raise ValueError(f"unknown fault spec key {k!r} "
                                 f"(known: at_step, every_n, once, stall_ms, "
                                 f"burst, burst_prompt, burst_new, "
                                 f"burst_class, store)")
        return cls(**kw)


def parse_fault_specs(text: str) -> List[FaultSpec]:
    """Parse the CLI's semicolon-separated fault-spec string."""
    return [FaultSpec.parse(e) for e in text.split(";") if e.strip()]


class FaultInjector:
    """Fires :class:`FaultSpec` schedules against a router's replicas.

    Construction takes specs (objects or the grammar string) plus a seed;
    ``PrefixAffinityRouter(fault_injector=...)`` calls :meth:`attach`, which
    wraps each replica's seams:

    - ``EngineReplica.step`` — the schedule is evaluated here (one tick per
      step call); ``exception``/``death`` raise, ``stall`` sleeps, and
      ``corrupt``/``truncate``/``alloc`` arm their targets before the real
      step runs.
    - ``EngineReplica.submit`` / ``drain`` — poisoned by ``death`` (a dead
      replica cannot cooperate with anything, drain included).
    - ``allocator._alloc_one`` — raises one injected
      :class:`KVBlocksExhausted` per armed ``alloc`` fault.
    - the replica's host KV tier — ``corrupt``/``truncate`` mutate one
      seeded-random entry's bytes in place.

    Every fire is counted: ``fired`` (plain dict, always) and the
    ``faults_injected_total{kind=,replica=}`` counter on the router registry
    (when attached). ``fired_total == 0`` after a run means no fault
    actually hit — bench refuses to publish fault metrics on that
    (``faults_invalid``), the r5 honesty pattern.
    """

    def __init__(self, specs: Union[str, Sequence[FaultSpec]] = (),
                 seed: int = 0):
        if isinstance(specs, str):
            specs = parse_fault_specs(specs)
        self.specs: List[FaultSpec] = list(specs)
        self._rng = np.random.default_rng(seed)
        self._steps: Dict[str, int] = {}            # replica -> step count
        self._spec_fired: Dict[int, set] = {}       # spec idx -> replica ids
        self._dead: set = set()
        self._alloc_pending: Dict[str, int] = {}
        self._leak_pending: Dict[str, int] = {}
        self.fired: Dict[Tuple[str, str], int] = {} # (kind, replica) -> count
        self.fired_total = 0
        self._registry = None
        self._router = None                         # overload bursts submit here
        self._counters: Dict[Tuple[str, str], object] = {}
        # overload-burst visibility: arrivals the injector actually pushed
        # through admission vs arrivals admission shed back at it
        self.burst_submitted = 0
        self.burst_shed = 0

    # ------------------------------------------------------------------ attach
    def attach(self, router) -> None:
        """Wrap every replica of ``router`` (called by the router ctor)."""
        self._registry = router.registry
        self._router = router
        for rep in router.replicas.values():
            self.attach_replica(rep)

    def attach_replica(self, rep) -> None:
        """Wrap one replica's seams (also used when a FAILED replica is
        swapped for a fresh one at reactivation)."""
        rid = rep.replica_id
        self._steps.setdefault(rid, 0)

        real_step = rep.step

        def _step(key=None):
            self._on_step(rid, rep)
            return real_step(key)

        rep.step = _step
        for name in ("submit", "drain"):
            real = getattr(rep, name)

            def _guarded(*a, _real=real, **kw):
                self._check_dead(rid)
                return _real(*a, **kw)

            setattr(rep, name, _guarded)
        # the native C++ allocator has no Python alloc seam — alloc faults
        # need the Python/tiered allocator (the KVBlocksExhausted path)
        alloc = getattr(rep.runner, "allocator", None)
        if alloc is not None and hasattr(alloc, "_alloc_one"):
            real_alloc = alloc._alloc_one

            def _alloc_one():
                if self._alloc_pending.get(rid, 0) > 0:
                    self._alloc_pending[rid] -= 1
                    self._count("alloc", rid)
                    raise KVBlocksExhausted("out of KV blocks (injected)")
                return real_alloc()

            alloc._alloc_one = _alloc_one
        if alloc is not None and hasattr(alloc, "_release_one"):
            # the `leak` kind: swallow ONE release — wrapping the CURRENT
            # instance attribute means the block ledger's own seam wrapper
            # (attached at runner construction, below us) never sees the
            # release either, exactly like a real dropped-release bug
            real_release = alloc._release_one

            def _release_one(blk):
                if self._leak_pending.get(rid, 0) > 0:
                    self._leak_pending[rid] -= 1
                    self._count("leak", rid)
                    logger.warning(
                        "injected KV block leak on replica %s: release of "
                        "block %d dropped (refcount never decremented)",
                        rid, blk)
                    return
                return real_release(blk)

            alloc._release_one = _release_one

    def revive(self, replica_id: str) -> None:
        """Forget a death: the (fresh) replica under this id serves again.
        Called by ``router.reactivate_replica`` so a recovered fleet does
        not stay poisoned by a one-shot death spec."""
        self._dead.discard(replica_id)

    # ------------------------------------------------------------------ firing
    def _check_dead(self, rid: str) -> None:
        if rid in self._dead:
            raise InjectedReplicaDeath(
                f"replica {rid} is dead (injected hard death)")

    def _on_step(self, rid: str, rep) -> None:
        self._check_dead(rid)
        self._steps[rid] += 1
        step = self._steps[rid]
        for i, spec in enumerate(self.specs):
            if spec.replica is not None and spec.replica != rid:
                continue
            if not self._due(i, spec, rid, step):
                continue
            self._fire(i, spec, rid, rep, step)

    def _due(self, i: int, spec: FaultSpec, rid: str, step: int) -> bool:
        if spec.once and rid in self._spec_fired.get(i, ()):
            return False
        if spec.at_step is not None:
            if spec.kind in ("corrupt", "truncate"):
                # "at or after": a corruption scheduled before the tier
                # holds any bytes stays armed (the fire un-consumes itself
                # on an empty store) instead of silently never firing
                return step >= spec.at_step
            return step == spec.at_step
        return step % spec.every_n == 0

    def _fire(self, i: int, spec: FaultSpec, rid: str, rep,
              step: int) -> None:
        self._spec_fired.setdefault(i, set()).add(rid)
        kind = spec.kind
        if kind in ("corrupt", "truncate"):
            n = self._corrupt_tier(rep, truncate=(kind == "truncate"),
                                   store=spec.store)
            if n:
                self._count(kind, rid, n)
            else:
                # nothing to corrupt yet (empty store): a `once` schedule is
                # NOT consumed — it fires as soon as the tier holds bytes,
                # so `every_n=1,once=1` means "corrupt the first entry that
                # ever exists" deterministically
                self._spec_fired[i].discard(rid)
            return
        if kind == "alloc":
            # armed here, counted when the wrapped _alloc_one actually raises
            self._alloc_pending[rid] = self._alloc_pending.get(rid, 0) + 1
            return
        if kind == "leak":
            # armed here, counted when the wrapped _release_one drops one
            self._leak_pending[rid] = self._leak_pending.get(rid, 0) + 1
            return
        if kind == "overload":
            n = self._overload_burst(spec)
            if n:
                self._count(kind, rid)
            else:
                # no router / nothing submitted: not fired — bench's
                # honesty marker must see a mis-aimed overload schedule
                self._spec_fired[i].discard(rid)
            if spec.stall_ms:
                # the slow-drain half: the stepping replica wedges for
                # stall_ms while the burst sits in the frontend queue
                time.sleep(spec.stall_ms / 1e3)
            return
        if kind == "stall":
            self._count(kind, rid)
            logger.warning("injected %.0f ms dispatch stall on replica %s "
                           "(step %d)", spec.stall_ms, rid, step)
            time.sleep(spec.stall_ms / 1e3)
            return
        if kind == "death":
            self._dead.add(rid)
            self._count(kind, rid)
            raise InjectedReplicaDeath(
                f"replica {rid} died (injected at step {step})")
        self._count("exception", rid)
        raise InjectedFault(
            f"injected dispatch exception on replica {rid} (step {step})")

    def _overload_burst(self, spec: FaultSpec) -> int:
        """Fire one seeded tenant burst at the FRONTEND: ``burst`` synthetic
        prompts of ``burst_prompt`` tokens submitted through the router's
        normal admission (class ``burst_class``, defaulting to the router's
        least-important sheddable class) — so brown-out shed, queue-bound
        shed, priority placement and preemption all see exactly what a
        misbehaving tenant would send. Returns arrivals ATTEMPTED (0 when no
        router is attached — the schedule was mis-aimed and the fire is
        un-consumed)."""
        router = self._router
        if router is None:
            logger.warning("overload fault has no attached router — "
                           "nothing injected")
            return 0
        from .router import RouterOverloaded

        cls = spec.burst_class
        sla = getattr(router, "sla", None)
        if cls is None and sla is not None:
            order = sla.shed_order()
            cls = order[0] if order else sla.names()[-1]
        rep = next(iter(router.replicas.values()))
        vocab = int(rep.runner.app.arch_args.vocab_size)
        seq_len = int(rep.runner.cfg.seq_len)
        plen = max(1, min(spec.burst_prompt, seq_len - spec.burst_new - 1))
        submitted = shed = 0
        for _ in range(spec.burst):
            p = self._rng.integers(1, vocab,
                                   size=(plen,)).astype(np.int32)
            try:
                router.submit(p, max_new_tokens=spec.burst_new,
                              sla_class=cls)
                submitted += 1
            # lint: ok(silent-except): the shed IS the system working — the router counted+logged it (router_class_shed_total) and the burst summary below reports the tally
            except RouterOverloaded:
                shed += 1
            except ValueError as e:
                # a mis-configured burst_class (unknown class / classless
                # router) is a DRIVER error, not a replica failure — it must
                # not leak into the supervisor and fail the replica. Counted
                # as not-fired (the schedule stays armed; bench's honesty
                # marker sees the misaim).
                logger.warning("overload burst mis-configured "
                               "(burst_class=%r): %s — nothing injected",
                               cls, e)
                return 0
        self.burst_submitted += submitted
        self.burst_shed += shed
        logger.warning("injected overload burst: %d arrivals (class=%s, "
                       "prompt=%d tokens), %d shed by admission",
                       submitted + shed, cls, plen, shed)
        return submitted + shed

    def _corrupt_tier(self, rep, truncate: bool, store: str = "tier") -> int:
        """Mutate one seeded-random host-tier entry's bytes in place (the
        checksum stays what spill stamped, so the readmit verify MUST trip).
        ``store="cluster"`` targets the fleet store behind the replica's
        tier instead — bytes rewrite through the transport so the
        PULL-side verify (``ClusterKVStore.reserve``) trips. Returns
        entries mutated (0 when the replica has no tier entries — the
        schedule was mis-aimed; counted as not-fired so bench's
        ``faults_invalid`` honesty marker can see it)."""
        if store == "cluster":
            return self._corrupt_cluster(rep, truncate)
        tier = getattr(rep.runner, "kv_tier", None)
        if tier is None or not tier.store:
            logger.warning("corrupt/truncate fault found no host-tier "
                           "entries on replica %s — nothing mutated",
                           rep.replica_id)
            return 0
        keys = sorted(tier.store)
        h = keys[int(self._rng.integers(len(keys)))]
        blk = tier.store[h]
        k, v = blk.materialize()
        if truncate:
            # a torn copy: half the K bytes survive, shape collapses
            flat = np.ascontiguousarray(k).reshape(-1)
            blk._np = (flat[: max(1, flat.size // 2)].copy(), v)
        else:
            kk = np.ascontiguousarray(k).copy()
            kk.view(np.uint8).reshape(-1)[0] ^= 0xFF
            blk._np = (kk, v)
        return 1

    def _corrupt_cluster(self, rep, truncate: bool) -> int:
        """Mutate one seeded-random CLUSTER entry's bytes through the
        transport (the directory checksum stays what publish stamped, so
        ``reserve``'s verify trips → drop + re-prefill)."""
        tier = getattr(rep.runner, "kv_tier", None)
        cl = getattr(tier, "cluster", None) if tier is not None else None
        if cl is None or not cl.entries:
            logger.warning("corrupt/truncate fault (store=cluster) found no "
                           "cluster entries behind replica %s — nothing "
                           "mutated", rep.replica_id)
            return 0
        h = sorted(cl.entries)[int(self._rng.integers(len(cl.entries)))]
        k, v = cl.transport.get(h)
        if truncate:
            flat = np.ascontiguousarray(k).reshape(-1)
            cl.transport.put(h, flat[: max(1, flat.size // 2)].copy(), v)
        else:
            kk = np.ascontiguousarray(k).copy()
            kk.view(np.uint8).reshape(-1)[0] ^= 0xFF
            cl.transport.put(h, kk, v)
        return 1

    def _count(self, kind: str, rid: str, n: int = 1) -> None:
        key = (kind, rid)
        self.fired[key] = self.fired.get(key, 0) + n
        self.fired_total += n
        if self._registry is not None:
            c = self._counters.get(key)
            if c is None:
                c = self._registry.counter(
                    "faults_injected_total",
                    "faults fired by the serving fault injector",
                    labels={"kind": kind, "replica": rid})
                self._counters[key] = c
            c.inc(n)

    def stats(self) -> Dict[str, object]:
        return {
            "specs": len(self.specs),
            "fired_total": self.fired_total,
            "fired": {f"{k}@{r}": n for (k, r), n in sorted(self.fired.items())},
            "dead": sorted(self._dead),
            "steps": dict(self._steps),
            "burst_submitted": self.burst_submitted,
            "burst_shed": self.burst_shed,
        }
